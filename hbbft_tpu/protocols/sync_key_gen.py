"""SyncKeyGen: dealerless distributed key generation (DKG).

Reference: upstream ``src/sync_key_gen.rs`` (SURVEY.md §2 #12) — fork
checkout empty at survey time, reconstructed from the upstream public
crate's documented scheme.

Scheme (Pedersen-style DKG over symmetric bivariate polynomials):

* Each proposer ``d`` deals a random *symmetric* bivariate polynomial
  ``p_d(x, y)`` of degree ``t`` in each variable and publishes a ``Part``:
  the :class:`~hbbft_tpu.crypto.poly.BivarCommitment` plus, for each node
  ``m``, the row polynomial ``p_d(m+1, ·)`` encrypted to ``m``'s public
  key.
* A node ``m`` that receives a valid ``Part`` (its row matches the
  commitment) answers with an ``Ack`` carrying, for each node ``j``, the
  value ``p_d(m+1, j+1)`` encrypted to ``j``.  By symmetry this equals
  ``p_d(j+1, m+1)``, i.e. one evaluation point of ``j``'s row — so ``j``
  can reconstruct its secret even if the dealer equivocates or crashes
  after sending only some rows.
* A proposal is *complete* once ``2t+1`` nodes have acked it; key
  generation is *ready* once ``t+1`` proposals are complete.
* ``generate()``: the joint public-key commitment is the sum over
  complete proposals of the committed master row ``p_d(0, ·)``; node
  ``j``'s secret share is ``sum_d p_d(0, j+1)``, each term interpolated
  at ``x = 0`` from the ``t+1``-plus received evaluations
  ``p_d(m+1, j+1)``.

The synchronous-rounds assumption is satisfied by running the Part/Ack
exchange *through* consensus (DynamicHoneyBadger threads them through
committed batches, SURVEY.md §3.3), so every node processes the same
messages in the same order.  SyncKeyGen itself is a plain
message-in/outcome-out state machine with no Step/Target plumbing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from hbbft_tpu.crypto.keys import Ciphertext, PublicKey, SecretKey, SecretKeyShare
from hbbft_tpu.crypto.poly import BivarCommitment, BivarPoly, Commitment, Poly, interpolate
from hbbft_tpu.crypto.suite import Suite

FAULT_MULTIPLE_PARTS = "sync_key_gen:multiple-parts"
FAULT_BAD_PART = "sync_key_gen:invalid-part"
FAULT_BAD_ACK = "sync_key_gen:invalid-ack"
FAULT_UNKNOWN_SENDER = "sync_key_gen:unknown-sender"
FAULT_ACK_BEFORE_PART = "sync_key_gen:ack-without-part"

_SCALAR_BYTES = 32  # BLS12-381 r fits in 255 bits


class _NativeDkg:
    """Scalar-suite fast path for the DKG's N^3 private checks.

    The committed-ack value check (KEM decrypt + commitment row eval +
    compare) and the ack-row construction (poly evals + N encrypts) are
    the measured Python tail of an era change (BASELINE.md round-4/5).
    native/engine.cpp exposes them as single C calls over a registered
    commitment matrix; semantics are byte-identical to the pure path
    (same KEM, same Horner, same fault outcomes — the native engine
    equivalence suites pin this end to end), and ANY mismatch in shape,
    suite, or registry routing falls back to the pure-Python path.
    """

    def __init__(self, lib: Any, suite: Suite) -> None:
        import ctypes

        self._ctypes = ctypes
        self._lib = lib
        self._suite = suite
        self._g = suite.g1_generator().to_bytes()
        self._r = suite.scalar_modulus.to_bytes(_SCALAR_BYTES, "big")
        from hbbft_tpu.crypto.keys import _scalar_kem

        self.kem = _scalar_kem(suite)

    def commit_id(self, commitment: Any) -> int:
        """Register (once, memoized on the shared decoded object)."""
        cached = commitment.__dict__.get("_native_cid")
        if cached is not None:
            return cached
        try:
            flat = b"".join(
                e.value.to_bytes(_SCALAR_BYTES, "big")
                for row in commitment.elems
                for e in row
            )
            cid = int(
                self._lib.hbe_dkg_register(
                    flat, len(commitment.elems), self._g, self._r
                )
            )
        except Exception:
            cid = -1
        object.__setattr__(commitment, "_native_cid", cid)
        return cid

    def ack_check(
        self, cid: int, sender_pos: int, our_pos: int, ct: Any, sk_x: int
    ) -> Tuple[int, int]:
        """(rc, value): rc 1 ok, 2 bad value, 0 bad ciphertext, -1 fall
        back."""
        out = (self._ctypes.c_uint8 * _SCALAR_BYTES)()
        rc = int(
            self._lib.hbe_dkg_ack_check(
                cid, sender_pos, our_pos,
                ct.u.value.to_bytes(_SCALAR_BYTES, "big"), ct.v,
                ct.w.value.to_bytes(_SCALAR_BYTES, "big"),
                sk_x.to_bytes(_SCALAR_BYTES, "big"), out,
            )
        )
        return rc, int.from_bytes(bytes(out), "big")

    def row_check(self, cid: int, our_pos: int, plain: bytes, n1: int) -> int:
        return int(self._lib.hbe_dkg_row_check(cid, our_pos, plain, n1))

    def ack_values(
        self, row: "Poly", pub_keys_g1: list, rng: Any
    ) -> Tuple["Ciphertext", ...]:
        """The ack's encrypted row evaluations, batched: one C call for
        the N poly evals and one for the N KEM encrypts.  The rng draws
        happen HERE in the exact per-encrypt order of the pure path
        (PublicKey.encrypt draws randrange(1, r) once per call), so the
        consumption stream — and every equivalence test — is unchanged.
        """
        ctypes = self._ctypes
        n = len(pub_keys_g1)
        mod = self._suite.scalar_modulus
        coeffs = b"".join(
            c.to_bytes(_SCALAR_BYTES, "big") for c in row.coeffs
        )
        evals = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        self._lib.hbe_dkg_row_evals(coeffs, len(row.coeffs), n, evals)
        rs = b"".join(
            rng.randrange(1, mod).to_bytes(_SCALAR_BYTES, "big")
            for _ in range(n)
        )
        pks = b"".join(
            g.value.to_bytes(_SCALAR_BYTES, "big") for g in pub_keys_g1
        )
        out_u = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        out_v = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        out_w = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        self._lib.hbe_kem_encrypt_batch(
            pks, bytes(evals), n, rs, out_u, out_v, out_w
        )
        from hbbft_tpu.crypto.keys import scalar_ct_serde

        g_type = type(self._suite.g1_generator())
        u_b, v_b, w_b = bytes(out_u), bytes(out_v), bytes(out_w)
        cts = []
        for j in range(n):
            s = slice(_SCALAR_BYTES * j, _SCALAR_BYTES * (j + 1))
            ct = Ciphertext(
                g_type(int.from_bytes(u_b[s], "big"), mod),
                v_b[s],
                g_type(int.from_bytes(w_b[s], "big"), mod),
                self._suite,
            )
            object.__setattr__(ct, "_verify_ok", True)
            object.__setattr__(
                ct, "_serde_cache", scalar_ct_serde(u_b[s], v_b[s], w_b[s])
            )
            cts.append(ct)
        return tuple(cts)


_NATIVE_DKG: dict = {}


def _native_dkg(suite: Suite) -> Optional[_NativeDkg]:
    if suite.name != "scalar-insecure":
        return None
    nd = _NATIVE_DKG.get(suite.name, False)
    if nd is not False:
        return nd
    try:
        from hbbft_tpu import native_engine

        lib = native_engine.get_lib()
        nd = _NativeDkg(lib, suite) if lib is not None else None
        if nd is not None and nd.kem is None:
            nd = None
    except Exception:
        nd = None
    _NATIVE_DKG[suite.name] = nd
    return nd


def _encode_scalars(vals: Tuple[int, ...]) -> bytes:
    """Fixed-width canonical encoding — the decrypted plaintext is
    attacker-chosen, so no pickle here (arbitrary-object deserialization
    of Byzantine bytes would be code execution)."""
    return b"".join(v.to_bytes(_SCALAR_BYTES, "big") for v in vals)


def _decode_scalars(data: Any, count: int, modulus: int) -> Optional[Tuple[int, ...]]:
    if not isinstance(data, bytes) or len(data) != count * _SCALAR_BYTES:
        return None
    vals = tuple(
        int.from_bytes(data[i * _SCALAR_BYTES : (i + 1) * _SCALAR_BYTES], "big")
        for i in range(count)
    )
    if any(v >= modulus for v in vals):
        return None
    return vals


@dataclass(frozen=True)
class Part:
    """A dealer's contribution: commitment + per-node encrypted rows."""

    commitment: BivarCommitment
    rows: Tuple[Ciphertext, ...]  # rows[m] encrypts serde(row poly of node m)

    def __repr__(self) -> str:
        return f"Part(degree={self.commitment.degree}, rows={len(self.rows)})"


@dataclass(frozen=True)
class Ack:
    """Node's confirmation of a dealer's Part: per-node encrypted values."""

    proposer: Any
    values: Tuple[Ciphertext, ...]  # values[j] encrypts int p_d(our+1, j+1)

    def __repr__(self) -> str:
        return f"Ack(proposer={self.proposer!r}, values={len(self.values)})"


@dataclass(frozen=True)
class PartOutcome:
    """Result of handling a Part: an Ack to broadcast, or a fault."""

    ack: Optional[Ack] = None
    fault: Optional[str] = None

    @property
    def is_valid(self) -> bool:
        return self.fault is None


@dataclass(frozen=True)
class AckOutcome:
    fault: Optional[str] = None

    @property
    def is_valid(self) -> bool:
        return self.fault is None


class _ProposalState:
    """Per-dealer accumulation (upstream ``ProposalState``)."""

    def __init__(self, commitment: BivarCommitment) -> None:
        self.commitment = commitment
        # Evaluation point (m+1) -> value p_d(m+1, our+1) == p_d(our+1, m+1).
        self.values: Dict[int, int] = {}
        self.acks: Set[int] = set()  # node indices that acked

    def is_complete(self, threshold: int) -> bool:
        return len(self.acks) > 2 * threshold


class SyncKeyGen:
    """One node's view of a DKG among ``pub_keys``' owners.

    Construct via :meth:`new`, which also returns our ``Part`` to be
    disseminated (``None`` for observers).
    """

    def __init__(
        self,
        our_id: Any,
        secret_key: SecretKey,
        pub_keys: Dict[Any, PublicKey],
        threshold: int,
        suite: Suite,
    ) -> None:
        self.our_id = our_id
        self.secret_key = secret_key
        self.pub_keys = dict(pub_keys)
        self.threshold = threshold
        self.suite = suite
        self._ids: List[Any] = sorted(pub_keys)
        self._index = {n: i for i, n in enumerate(self._ids)}
        self.proposals: Dict[Any, _ProposalState] = {}

    # -- construction --------------------------------------------------
    @staticmethod
    def new(
        our_id: Any,
        secret_key: SecretKey,
        pub_keys: Dict[Any, PublicKey],
        threshold: int,
        rng: Any,
        suite: Suite,
    ) -> Tuple["SyncKeyGen", Optional[Part]]:
        skg = SyncKeyGen(our_id, secret_key, pub_keys, threshold, suite)
        if our_id not in skg._index:
            return skg, None  # observer: no contribution
        poly = BivarPoly.random(threshold, rng, suite.scalar_modulus)
        commitment = poly.commitment(suite)
        rows = tuple(
            pub_keys[n].encrypt(_encode_scalars(poly.row(m + 1).coeffs), rng)
            for m, n in enumerate(skg._ids)
        )
        return skg, Part(commitment, rows)

    # -- introspection -------------------------------------------------
    @property
    def our_index(self) -> Optional[int]:
        return self._index.get(self.our_id)

    def is_node_ready(self, proposer: Any) -> bool:
        state = self.proposals.get(proposer)
        return state is not None and state.is_complete(self.threshold)

    def count_complete(self) -> int:
        return sum(
            1 for s in self.proposals.values() if s.is_complete(self.threshold)
        )

    def is_ready(self) -> bool:
        """Enough complete proposals to generate the joint key."""
        return self.count_complete() > self.threshold

    # -- message handling ----------------------------------------------
    #
    # CRITICAL invariant: whether a Part is *accepted* and whether an Ack
    # is *counted* must depend only on PUBLICLY visible data (the message
    # bytes every node sees in the same consensus order) — never on data
    # only we can decrypt.  Otherwise a Byzantine dealer/acker could
    # corrupt one node's encrypted slot and make the proposal/ack sets —
    # and hence the generated keys — diverge across nodes.  Failures of
    # the *private* checks are reported as faults but do not affect
    # acceptance/counting.

    def handle_part(self, sender: Any, part: Part, rng: Any) -> PartOutcome:
        if sender not in self._index:
            return PartOutcome(fault=FAULT_UNKNOWN_SENDER)
        if not self._part_shape_ok(part):  # public check
            return PartOutcome(fault=FAULT_BAD_PART)
        existing = self.proposals.get(sender)
        if existing is not None:
            if existing.commitment == part.commitment:
                return PartOutcome()  # duplicate: ignore
            return PartOutcome(fault=FAULT_MULTIPLE_PARTS)
        self.proposals[sender] = _ProposalState(part.commitment)

        our_idx = self.our_index
        if our_idx is None:
            return PartOutcome()  # observer: track commitment only

        # Private check: our encrypted row.  On failure the proposal stays
        # tracked (others' acks can still complete it and recover our
        # share); we just cannot ack it ourselves.
        row = self._decrypt_row(part, our_idx)
        if row is None:
            return PartOutcome(fault=FAULT_BAD_PART)
        # Our ack: hand every node j one evaluation of its row.
        nd = _native_dkg(self.suite)
        if nd is not None:
            mod = self.suite.scalar_modulus
            pks_g1 = [getattr(self.pub_keys[n], "g1", None) for n in self._ids]
            if all(
                isinstance(getattr(g, "value", None), int)
                and 0 <= g.value < mod
                for g in pks_g1
            ):
                return PartOutcome(
                    ack=Ack(sender, nd.ack_values(row, pks_g1, rng))
                )
        values = tuple(
            self.pub_keys[n].encrypt(
                _encode_scalars((row.eval(j + 1),)), rng
            )
            for j, n in enumerate(self._ids)
        )
        return PartOutcome(ack=Ack(sender, values))

    def handle_ack(self, sender: Any, ack: Ack) -> AckOutcome:
        if sender not in self._index:
            return AckOutcome(fault=FAULT_UNKNOWN_SENDER)
        if not self._ack_shape_ok(ack):  # public check
            return AckOutcome(fault=FAULT_BAD_ACK)
        try:
            state = self.proposals.get(ack.proposer)
        except TypeError:  # unhashable garbage proposer
            state = None
        if state is None:
            # Part/Ack ordering is guaranteed by consensus; an ack for an
            # unknown proposal is Byzantine (or the proposer never dealt).
            return AckOutcome(fault=FAULT_ACK_BEFORE_PART)
        sender_idx = self._index[sender]
        if sender_idx in state.acks:
            return AckOutcome()  # duplicate: ignore
        # All public checks passed: the ack COUNTS at every node, even if
        # the value encrypted to us turns out bad (see invariant above).
        state.acks.add(sender_idx)

        our_idx = self.our_index
        if our_idx is None:
            return AckOutcome()
        # Native fast path: decrypt + decode + commitment consistency in
        # one C call (identical verdicts; _NativeDkg docstring).
        nd = _native_dkg(self.suite)
        ct = ack.values[our_idx]
        if (
            nd is not None
            and nd.kem.ct_ok(ct)
            and len(ct.v) == _SCALAR_BYTES
        ):
            cid = nd.commit_id(state.commitment)
            if cid >= 0:
                rc, nval = nd.ack_check(
                    cid, sender_idx + 1, our_idx + 1, ct, self.secret_key.x
                )
                if rc >= 0:
                    # Mirror SecretKey.decrypt's ciphertext-validity memo
                    # (rc 0 = invalid ct; 1/2 = valid ct).
                    object.__setattr__(ct, "_verify_ok", rc != 0)
                    if rc != 1:
                        return AckOutcome(fault=FAULT_BAD_ACK)
                    state.values[sender_idx + 1] = nval
                    return AckOutcome()
        val = self._decrypt_value(ack, our_idx)
        if val is not None:
            # Private consistency: v must equal p_d(sender+1, our+1); check
            # in the group against the committed row of the sender.
            expected = state.commitment.row(sender_idx + 1).eval(our_idx + 1)
            actual = self.suite.g1_generator() * val
            if expected.to_bytes() != actual.to_bytes():
                val = None
        if val is None:
            return AckOutcome(fault=FAULT_BAD_ACK)
        state.values[sender_idx + 1] = val
        return AckOutcome()

    # -- key derivation ------------------------------------------------
    def generate(self) -> Tuple["PublicKeySet", Optional[SecretKeyShare]]:
        """Derive the joint keys from the complete proposals.

        Deterministic across nodes: the proposal set and ack sets are
        identical everywhere because Part/Ack ordering came through
        consensus.
        """
        from hbbft_tpu.crypto.keys import PublicKeySet

        complete = [
            (d, s)
            for d, s in sorted(self.proposals.items(), key=lambda kv: str(kv[0]))
            if s.is_complete(self.threshold)
        ]
        if len(complete) <= self.threshold:
            raise RuntimeError(
                f"not ready: {len(complete)} complete proposals, "
                f"need {self.threshold + 1}"
            )
        commitment: Optional[Commitment] = None
        for _, s in complete:
            row0 = s.commitment.row(0)
            commitment = row0 if commitment is None else commitment + row0
        pk_set = PublicKeySet(commitment, self.suite)

        our_idx = self.our_index
        if our_idx is None:
            return pk_set, None
        modulus = self.suite.scalar_modulus
        secret = 0
        for d, s in complete:
            pts = sorted(s.values.items())[: self.threshold + 1]
            if len(pts) <= self.threshold:
                raise RuntimeError(
                    f"proposal {d!r} complete but only {len(pts)} values known"
                )
            secret = (secret + interpolate(pts, modulus)) % modulus
        return pk_set, SecretKeyShare(secret, self.suite)

    # -- internals -----------------------------------------------------
    def _shape_memo_key(self) -> tuple:
        # The verdict depends only on public data + these parameters, so
        # it can be cached on the (shared, immutable) message object —
        # at churn every node re-validates the same decoded Part/Ack
        # otherwise (N^3 ciphertext checks network-wide).
        return (self.threshold, len(self._ids), self.suite.name)

    def _part_shape_ok(self, part: Any) -> bool:
        """Public structural validation (fields may be arbitrary objects)."""
        from hbbft_tpu.crypto.backend import _ciphertext_well_formed

        key = self._shape_memo_key()
        try:
            cached = part.__dict__.get("_shape_ok")
            if cached is not None and cached[0] == key:
                return cached[1]
        except Exception:
            cached = None
        ok = self._part_shape_ok_uncached(part, _ciphertext_well_formed)
        try:
            object.__setattr__(part, "_shape_ok", (key, ok))
        except Exception:
            pass
        return ok

    def _part_shape_ok_uncached(self, part: Any, _ciphertext_well_formed) -> bool:
        try:
            n1 = self.threshold + 1
            return (
                isinstance(part, Part)
                and isinstance(part.commitment, BivarCommitment)
                and isinstance(part.commitment.elems, tuple)
                and len(part.commitment.elems) == n1
                and all(
                    isinstance(row, tuple)
                    and len(row) == n1
                    and all(self.suite.is_g1(e) for e in row)
                    for row in part.commitment.elems
                )
                and isinstance(part.rows, tuple)
                and len(part.rows) == len(self._ids)
                and all(_ciphertext_well_formed(self.suite, c) for c in part.rows)
            )
        except Exception:
            return False

    def _ack_shape_ok(self, ack: Any) -> bool:
        from hbbft_tpu.crypto.backend import _ciphertext_well_formed

        key = self._shape_memo_key()
        try:
            cached = ack.__dict__.get("_shape_ok")
            if cached is not None and cached[0] == key:
                return cached[1]
        except Exception:
            cached = None
        ok = self._ack_shape_ok_uncached(ack, _ciphertext_well_formed)
        try:
            object.__setattr__(ack, "_shape_ok", (key, ok))
        except Exception:
            pass
        return ok

    def _ack_shape_ok_uncached(self, ack: Any, _ciphertext_well_formed) -> bool:
        try:
            return (
                isinstance(ack, Ack)
                and isinstance(ack.values, tuple)
                and len(ack.values) == len(self._ids)
                and all(_ciphertext_well_formed(self.suite, c) for c in ack.values)
            )
        except Exception:
            return False

    def _decrypt_row(self, part: Part, our_idx: int) -> Optional[Poly]:
        try:
            data = self.secret_key.decrypt(part.rows[our_idx])
        except Exception:
            data = None
        if data is None:
            return None
        coeffs = _decode_scalars(
            data, self.threshold + 1, self.suite.scalar_modulus
        )
        if coeffs is None:
            return None
        row = Poly(coeffs, self.suite.scalar_modulus)
        # Validate the row against the public commitment (native fast
        # path: per-coefficient g*c comparison against the registered
        # commitment's row — same verdict as the to_bytes comparison).
        nd = _native_dkg(self.suite)
        if nd is not None:
            cid = nd.commit_id(part.commitment)
            if cid >= 0:
                rc = nd.row_check(
                    cid, our_idx + 1, data, self.threshold + 1
                )
                if rc >= 0:
                    return row if rc == 1 else None
        committed = part.commitment.row(our_idx + 1)
        ours = row.commitment(self.suite)
        if committed.to_bytes() != ours.to_bytes():
            return None
        return row

    def _decrypt_value(self, ack: Ack, our_idx: int) -> Optional[int]:
        try:
            data = self.secret_key.decrypt(ack.values[our_idx])
        except Exception:
            data = None
        if data is None:
            return None
        vals = _decode_scalars(data, 1, self.suite.scalar_modulus)
        return None if vals is None else vals[0]
