"""Wire-format registry: validating (un)packers for committed-boundary types.

The reference's equivalent is ``bincode``'s derive-generated codecs for the
types that ride inside HoneyBadger contributions (upstream
``src/honey_badger/honey_badger.rs``: contributions are bincode-serialized
before threshold encryption; ``src/dynamic_honey_badger/``: votes and DKG
messages ride inside them).  Every ``unpack`` below is a trust boundary:
its input tuple was authored by a possibly-Byzantine proposer, so it
validates field count, types, and value ranges before constructing, and
raises :class:`~hbbft_tpu.utils.serde.DecodeError` on anything off.

Registered types (everything reachable from a committed contribution):

* crypto:   ``Ciphertext``, ``Signature``, ``PublicKey``,
            ``Commitment``, ``BivarCommitment``
* honey_badger:  ``EncryptionSchedule``
* dynamic_honey_badger:  ``Change``, ``SignedVote``, ``SignedKeyGenMsg``,
            ``InternalContrib``, ``JoinPlan``
* sync_key_gen:  ``Part``, ``Ack``

Transport-boundary types (everything reachable from a live wire
message of the SenderQueue(QueueingHoneyBadger) stack, so a whole
protocol message can ride in one TCP frame —
:mod:`hbbft_tpu.transport.framing`):

* crypto shares:  ``SignatureShare``, ``DecryptionShare``
* merkle:   ``Proof``
* broadcast:  ``ValueMsg``, ``EchoMsg``, ``ReadyMsg``, ``EchoHashMsg``,
            ``CanDecodeMsg``
* agreement:  ``BoolSet``, ``BValMsg``, ``AuxMsg``, ``ConfMsg``,
            ``CoinMsg``, ``TermMsg``, ``AbaMessage``
* threshold:  ``SignMessage``, ``DecryptMessage``
* envelopes:  ``SubsetMessage``, ``HbMessage``, ``DhbMessage``,
            ``SqMessage``

These unpackers are *stricter* than the in-process handlers: a frame
whose payload could only have been authored by a broken or malicious
peer (wrong root length, round < 0, unknown envelope kind) is rejected
at the decode boundary — the transport drops the connection and counts
the fault — instead of being handed to a protocol instance.  Handlers
keep their own malformed-message fault paths for in-process use.

Group elements are encoded by the serde core (tag 0x11) through the suite
registry; suites validate structure/on-curve/subgroup in
``g1_from_bytes``/``g2_from_bytes``.

Subgroup-check policy (CLAUDE.md invariant: wire-sourced points MUST get
subgroup checks somewhere): decode does the FULL check, even though the
threshold-decrypt path's verify backend re-checks, because the same
``Ciphertext`` type also reaches ``SecretKey.decrypt`` (DKG rows), where
``ct.u`` is multiplied by a long-term secret with no backend pass — a
torsion component there is the classic invalid-point key-leak.  Cost
context: serde decode handles O(N) committed payloads per epoch; the
O(N^2) share-verification hot loop never crosses this codec (shares are
in-process message objects), so this does not reintroduce round 1's
host-side flush bottleneck.  If decode ever shows up in profiles, the
fast x-based membership tests (Scott 2021: phi/psi endomorphism checks)
cut the torsion cost ~2-4x before any batching is needed.
"""

from __future__ import annotations

from typing import Any

from hbbft_tpu.crypto.backend import (
    CIPHERTEXT,
    DEC_SHARE,
    SIG_SHARE,
    VerifyRequest,
)
from hbbft_tpu.crypto.keys import (
    Ciphertext,
    DecryptionShare,
    PublicKey,
    PublicKeyShare,
    Signature,
    SignatureShare,
)
from hbbft_tpu.crypto.poly import BivarCommitment, Commitment
from hbbft_tpu.crypto.suite import ScalarG, ScalarSuite
from hbbft_tpu.ops.merkle import Proof
from hbbft_tpu.protocols.binary_agreement import (
    AbaMessage,
    ConfMsg,
    CoinMsg,
    TermMsg,
)
from hbbft_tpu.protocols.bool_set import BoolSet
from hbbft_tpu.protocols.broadcast import (
    CanDecodeMsg,
    EchoHashMsg,
    EchoMsg,
    ReadyMsg,
    ValueMsg,
)
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    DhbMessage,
    InternalContrib,
    JoinPlan,
    SignedKeyGenMsg,
    SignedVote,
)
from hbbft_tpu.protocols.honey_badger import DECRYPT, SUBSET, EncryptionSchedule, HbMessage
from hbbft_tpu.protocols.sbv_broadcast import AuxMsg, BValMsg
from hbbft_tpu.protocols.sender_queue import SqMessage
from hbbft_tpu.protocols.subset import BA, BC, SubsetMessage
from hbbft_tpu.protocols.sync_key_gen import Ack, Part
from hbbft_tpu.protocols.threshold_decrypt import DecryptMessage
from hbbft_tpu.protocols.threshold_sign import SignMessage
from hbbft_tpu.utils import serde
from hbbft_tpu.utils.serde import (
    DecodeError,
    get_suite,
    register_struct,
    register_suite,
    register_token_struct,
)

# -- suites -----------------------------------------------------------------

from hbbft_tpu.crypto.bls.suite import BLSSuite  # pure Python, no jax dep

register_suite(ScalarSuite())
register_suite(BLSSuite())


def _suite(name: Any):
    if not isinstance(name, str):
        raise DecodeError("suite name must be a string")
    return get_suite(name)


# -- field validators -------------------------------------------------------


def _need(cond: bool, what: str) -> None:
    if not cond:
        raise DecodeError(what)


def _int(v: Any, what: str) -> int:
    _need(type(v) is int, f"{what}: not an int")
    return v


def _nonneg(v: Any, what: str) -> int:
    _need(type(v) is int and v >= 0, f"{what}: not a non-negative int")
    return v


def _bytes(v: Any, what: str) -> bytes:
    _need(type(v) is bytes, f"{what}: not bytes")
    return v


def _node_id(v: Any, what: str) -> Any:
    """Node ids crossing the boundary must be plain hashable scalars."""
    _need(type(v) in (int, str, bytes), f"{what}: bad node id")
    return v


def _fields(fields: tuple, n: int, what: str) -> tuple:
    _need(len(fields) == n, f"{what}: want {n} fields, got {len(fields)}")
    return fields


def _g1(suite: Any, v: Any, what: str) -> Any:
    # from_bytes already validated; re-check the element belongs to the
    # suite named in the enclosing struct (mixed-suite confusion).
    _need(suite.is_g1(v, check_subgroup=False), f"{what}: not a G1 element")
    return v


def _g2(suite: Any, v: Any, what: str) -> Any:
    _need(suite.is_g2(v, check_subgroup=False), f"{what}: not a G2 element")
    return v


# -- crypto types -----------------------------------------------------------


def _pack_ciphertext(ct: Ciphertext) -> tuple:
    return (ct.suite.name, ct.u, ct.v, ct.w)


# Token-level fast builder for the scalar "ct" struct on the native-scan
# decode path (serde.register_token_struct).  A DKG-epoch contribution
# carries ~N^2 of these, and the generic build (recursive field
# construction + validating unpack) was the measured bulk of the
# committed-payload decode at era changes (round-6 contrib_cb split).
# Accepts ONLY the exact canonical shape the encoder emits — tuple(4),
# scalar suite name, 32-byte in-range group values with group id 1/2,
# bytes v — and constructs precisely what _unpack_ciphertext would;
# ANYTHING else returns None so the generic path applies the canonical
# validation and error behavior (the scan/pure fuzz-equivalence test
# sweeps corruptions over a ct encoding to pin this).
_SCALAR_NAME_RAW = b"scalar-insecure"
_T_GROUP_CT = 0x11


def _fast_build_ct(t: Any, ti: int, data: bytes, suite_name: Any):
    base = 3 * ti
    if t[base] != 0x06 or t[base + 1] != 4:  # fields tuple(4)
        return None
    ti += 1
    base = 3 * ti
    if t[base] != 0x05:  # field 0: suite-name str
        return None
    off = t[base + 1]
    if data[off : off + t[base + 2]] != _SCALAR_NAME_RAW:
        return None  # other suites / junk: generic path decides
    if suite_name is not None and suite_name != "scalar-insecure":
        return None  # pin mismatch: generic path raises
    suite = serde._SUITES.get("scalar-insecure")
    if suite is None:
        return None
    mod = suite.scalar_modulus
    ti += 1

    def group(ti: int):
        # GROUP token + extra (group_id, payload) triple; mirrors
        # ScalarSuite.g1_from_bytes (== g2_from_bytes): 32 bytes, < r.
        base = 3 * ti
        if t[base] != _T_GROUP_CT:
            return None
        off = t[base + 1]
        if data[off : off + t[base + 2]] != _SCALAR_NAME_RAW:
            return None
        base += 3
        grp = t[base]
        if (grp != 1 and grp != 2) or t[base + 2] != 32:
            return None
        poff = base + 1
        v = int.from_bytes(data[t[poff] : t[poff] + 32], "big")
        if v >= mod:
            return None
        return ScalarG(v, mod), ti + 2

    res = group(ti)
    if res is None:
        return None
    u, ti = res
    base = 3 * ti
    if t[base] != 0x04:  # field 2: v bytes
        return None
    off = t[base + 1]
    v = data[off : off + t[base + 2]]
    ti += 1
    res = group(ti)
    if res is None:
        return None
    w, ti = res
    return Ciphertext(u, v, w, suite), ti


def _unpack_ciphertext(f: tuple) -> Ciphertext:
    name, u, v, w = _fields(f, 4, "Ciphertext")
    suite = _suite(name)
    return Ciphertext(
        _g1(suite, u, "Ciphertext.u"),
        _bytes(v, "Ciphertext.v"),
        _g2(suite, w, "Ciphertext.w"),
        suite,
    )


def _pack_signature(sig: Signature) -> tuple:
    return (sig.suite.name, sig.g2)


def _unpack_signature(f: tuple) -> Signature:
    name, g2 = _fields(f, 2, "Signature")
    suite = _suite(name)
    return Signature(_g2(suite, g2, "Signature.g2"), suite)


def _pack_public_key(pk: PublicKey) -> tuple:
    return (pk.suite.name, pk.g1)


def _unpack_public_key(f: tuple) -> PublicKey:
    name, g1 = _fields(f, 2, "PublicKey")
    suite = _suite(name)
    return PublicKey(_g1(suite, g1, "PublicKey.g1"), suite)


def _pack_commitment(c: Commitment) -> tuple:
    return (c.elems,)


def _unpack_commitment(f: tuple) -> Commitment:
    (elems,) = _fields(f, 1, "Commitment")
    _need(type(elems) is tuple and len(elems) >= 1, "Commitment: bad elems")
    cls = type(elems[0])
    _need(
        all(type(e) is cls and hasattr(e, "serde_group") for e in elems),
        "Commitment: mixed/bad element types",
    )
    return Commitment(elems)


def _pack_bivar_commitment(c: BivarCommitment) -> tuple:
    return (c.elems,)


def _unpack_bivar_commitment(f: tuple) -> BivarCommitment:
    (elems,) = _fields(f, 1, "BivarCommitment")
    _need(type(elems) is tuple and len(elems) >= 1, "BivarCommitment: bad elems")
    n = len(elems)
    flat = []
    for row in elems:
        _need(type(row) is tuple and len(row) == n, "BivarCommitment: not square")
        flat.extend(row)
    cls = type(flat[0])
    _need(
        all(type(e) is cls and hasattr(e, "serde_group") for e in flat),
        "BivarCommitment: mixed/bad element types",
    )
    return BivarCommitment(elems)


# -- crypto-plane RPC -------------------------------------------------------


def _pack_verify_request(r: VerifyRequest) -> tuple:
    # Opaque-to-the-engine RPC payload (cryptoplane/proc_service.py).
    # The public-key share rides as its bare G1 element: the share (or
    # ciphertext) in the same tuple pins the suite in-band, so unpack
    # reconstructs PublicKeyShare without a separate registered type.
    if r.kind == SIG_SHARE:
        pk, msg, share = r.payload
        return (r.kind, pk.g1, msg, share)
    if r.kind == DEC_SHARE:
        pk, ct, share = r.payload
        return (r.kind, pk.g1, ct, share)
    (ct,) = r.payload
    return (r.kind, ct)


def _unpack_verify_request(f: tuple) -> VerifyRequest:
    _need(len(f) >= 1, "VerifyRequest: empty")
    kind = f[0]
    if kind == SIG_SHARE:
        _, g1, msg, share = _fields(f, 4, "VerifyRequest[sig]")
        _need(isinstance(share, SignatureShare), "VerifyRequest: bad share")
        suite = share.suite
        return VerifyRequest.sig_share(
            PublicKeyShare(_g1(suite, g1, "VerifyRequest.pk"), suite),
            _bytes(msg, "VerifyRequest.msg"),
            share,
        )
    if kind == DEC_SHARE:
        _, g1, ct, share = _fields(f, 4, "VerifyRequest[dec]")
        _need(isinstance(ct, Ciphertext), "VerifyRequest: bad ciphertext")
        _need(isinstance(share, DecryptionShare), "VerifyRequest: bad share")
        suite = share.suite
        return VerifyRequest.dec_share(
            PublicKeyShare(_g1(suite, g1, "VerifyRequest.pk"), suite),
            ct,
            share,
        )
    if kind == CIPHERTEXT:
        _, ct = _fields(f, 2, "VerifyRequest[ct]")
        _need(isinstance(ct, Ciphertext), "VerifyRequest: bad ciphertext")
        return VerifyRequest.ciphertext(ct)
    raise DecodeError("VerifyRequest: bad kind")


# -- honey badger -----------------------------------------------------------

_SCHEDULE_KINDS = ("always", "never", "every_nth", "tick_tock")


def _pack_schedule(s: EncryptionSchedule) -> tuple:
    return (s.kind, s.n)


def _unpack_schedule(f: tuple) -> EncryptionSchedule:
    kind, n = _fields(f, 2, "EncryptionSchedule")
    _need(kind in _SCHEDULE_KINDS, "EncryptionSchedule: bad kind")
    _need(type(n) is int and n >= 1, "EncryptionSchedule: bad n")
    return EncryptionSchedule(kind, n)


# -- dynamic honey badger ---------------------------------------------------

_CHANGE_KINDS = ("node_change", "encryption_schedule")


def _pack_change(c: Change) -> tuple:
    return (c.kind, c.new_validators, c.schedule)


def _unpack_change(f: tuple) -> Change:
    # Cross-field invariants match the Change.node_change /
    # Change.encryption_schedule constructors: a decoded Change must be
    # one an honest node could have built (a schedule change always
    # carries a schedule; a node change carries >= 1 validator and no
    # schedule) — otherwise adopting a committed winner could crash
    # honest nodes (None.encrypt_on) or derive threshold -1.
    kind, validators, schedule = _fields(f, 3, "Change")
    _need(kind in _CHANGE_KINDS, "Change: bad kind")
    _need(type(validators) is tuple, "Change: bad validators")
    for pair in validators:
        _need(
            type(pair) is tuple and len(pair) == 2, "Change: bad validator pair"
        )
        _node_id(pair[0], "Change validator id")
        _need(isinstance(pair[1], PublicKey), "Change: validator key")
    if kind == "encryption_schedule":
        _need(isinstance(schedule, EncryptionSchedule), "Change: missing schedule")
        _need(len(validators) == 0, "Change: schedule change with validators")
    else:
        _need(schedule is None, "Change: node change with schedule")
        _need(len(validators) >= 1, "Change: empty validator set")
    return Change(kind, validators, schedule)


def _pack_signed_vote(v: SignedVote) -> tuple:
    return (v.voter, v.era, v.num, v.change, v.signature)


def _unpack_signed_vote(f: tuple) -> SignedVote:
    voter, era, num, change, sig = _fields(f, 5, "SignedVote")
    _node_id(voter, "SignedVote.voter")
    _need(isinstance(change, Change), "SignedVote: bad change")
    _need(isinstance(sig, Signature), "SignedVote: bad signature")
    return SignedVote(
        voter, _int(era, "SignedVote.era"), _int(num, "SignedVote.num"), change, sig
    )


def _pack_signed_kg(m: SignedKeyGenMsg) -> tuple:
    return (m.era, m.sender, m.payload, m.signature)


def _unpack_signed_kg(f: tuple) -> SignedKeyGenMsg:
    era, sender, payload, sig = _fields(f, 4, "SignedKeyGenMsg")
    _node_id(sender, "SignedKeyGenMsg.sender")
    _need(isinstance(payload, (Part, Ack)), "SignedKeyGenMsg: bad payload")
    _need(isinstance(sig, Signature), "SignedKeyGenMsg: bad signature")
    return SignedKeyGenMsg(_int(era, "SignedKeyGenMsg.era"), sender, payload, sig)


def _pack_internal_contrib(c: InternalContrib) -> tuple:
    return (c.contribution, c.key_gen_messages, c.votes)


def _unpack_internal_contrib(f: tuple) -> InternalContrib:
    contribution, kg, votes = _fields(f, 3, "InternalContrib")
    _need(type(kg) is tuple, "InternalContrib: bad key_gen_messages")
    _need(
        all(isinstance(m, SignedKeyGenMsg) for m in kg),
        "InternalContrib: bad key_gen message",
    )
    _need(type(votes) is tuple, "InternalContrib: bad votes")
    _need(
        all(isinstance(v, SignedVote) for v in votes), "InternalContrib: bad vote"
    )
    return InternalContrib(contribution, kg, votes)


def _pack_join_plan(p: JoinPlan) -> tuple:
    return (
        p.era,
        p.public_key_set.suite.name,
        p.public_key_set.commitment,
        p.validators,
        p.encryption_schedule,
    )


def _unpack_join_plan(f: tuple) -> JoinPlan:
    from hbbft_tpu.crypto.keys import PublicKeySet

    era, suite_name, commitment, validators, schedule = _fields(f, 5, "JoinPlan")
    suite = _suite(suite_name)
    _need(isinstance(commitment, Commitment), "JoinPlan: bad commitment")
    _need(
        all(suite.is_g1(e, check_subgroup=False) for e in commitment.elems),
        "JoinPlan: commitment elements not in suite G1",
    )
    _need(
        type(validators) is tuple and len(validators) >= 1,
        "JoinPlan: empty validator set",  # (0-1)//3 thresholds go negative
    )
    for pair in validators:
        _need(type(pair) is tuple and len(pair) == 2, "JoinPlan: bad pair")
        _node_id(pair[0], "JoinPlan validator id")
        _need(isinstance(pair[1], PublicKey), "JoinPlan: validator key")
    _need(isinstance(schedule, EncryptionSchedule), "JoinPlan: bad schedule")
    return JoinPlan(
        _nonneg(era, "JoinPlan.era"),
        PublicKeySet(commitment, suite),
        validators,
        schedule,
    )


# -- sync key gen -----------------------------------------------------------


def _pack_part(p: Part) -> tuple:
    return (p.commitment, p.rows)


def _unpack_part(f: tuple) -> Part:
    commitment, rows = _fields(f, 2, "Part")
    _need(isinstance(commitment, BivarCommitment), "Part: bad commitment")
    _need(type(rows) is tuple, "Part: bad rows")
    _need(all(isinstance(c, Ciphertext) for c in rows), "Part: bad row ciphertext")
    return Part(commitment, rows)


def _pack_ack(a: Ack) -> tuple:
    return (a.proposer, a.values)


def _unpack_ack(f: tuple) -> Ack:
    proposer, values = _fields(f, 2, "Ack")
    _node_id(proposer, "Ack.proposer")
    _need(type(values) is tuple, "Ack: bad values")
    _need(
        all(isinstance(c, Ciphertext) for c in values), "Ack: bad value ciphertext"
    )
    return Ack(proposer, values)


# -- transport-boundary types (live wire messages) --------------------------


def _bool(v: Any, what: str) -> bool:
    _need(type(v) is bool, f"{what}: not a bool")
    return v


def _root(v: Any, what: str) -> bytes:
    _need(type(v) is bytes and len(v) == 32, f"{what}: not a 32-byte root")
    return v


def _pack_sig_share(s: SignatureShare) -> tuple:
    return (s.suite.name, s.g2)


def _unpack_sig_share(f: tuple) -> SignatureShare:
    name, g2 = _fields(f, 2, "SignatureShare")
    suite = _suite(name)
    return SignatureShare(_g2(suite, g2, "SignatureShare.g2"), suite)


def _pack_dec_share(s: DecryptionShare) -> tuple:
    return (s.suite.name, s.g1)


def _unpack_dec_share(f: tuple) -> DecryptionShare:
    name, g1 = _fields(f, 2, "DecryptionShare")
    suite = _suite(name)
    return DecryptionShare(_g1(suite, g1, "DecryptionShare.g1"), suite)


def _pack_proof(p: Proof) -> tuple:
    return (p.value, p.index, p.path, p.root)


def _unpack_proof(f: tuple) -> Proof:
    value, index, path, root = _fields(f, 4, "Proof")
    _bytes(value, "Proof.value")
    _nonneg(index, "Proof.index")
    _need(
        type(path) is tuple
        and all(type(h) is bytes and len(h) == 32 for h in path),
        "Proof.path: not a tuple of 32-byte hashes",
    )
    return Proof(value, index, path, _root(root, "Proof.root"))


def _pack_value_msg(m: ValueMsg) -> tuple:
    return (m.proof,)


def _unpack_value_msg(f: tuple) -> ValueMsg:
    (proof,) = _fields(f, 1, "ValueMsg")
    _need(isinstance(proof, Proof), "ValueMsg: bad proof")
    return ValueMsg(proof)


def _pack_echo_msg(m: EchoMsg) -> tuple:
    return (m.proof,)


def _unpack_echo_msg(f: tuple) -> EchoMsg:
    (proof,) = _fields(f, 1, "EchoMsg")
    _need(isinstance(proof, Proof), "EchoMsg: bad proof")
    return EchoMsg(proof)


def _pack_root_msg(m: Any) -> tuple:
    return (m.root,)


def _unpack_ready_msg(f: tuple) -> ReadyMsg:
    (root,) = _fields(f, 1, "ReadyMsg")
    return ReadyMsg(_root(root, "ReadyMsg.root"))


def _unpack_echo_hash_msg(f: tuple) -> EchoHashMsg:
    (root,) = _fields(f, 1, "EchoHashMsg")
    return EchoHashMsg(_root(root, "EchoHashMsg.root"))


def _unpack_can_decode_msg(f: tuple) -> CanDecodeMsg:
    (root,) = _fields(f, 1, "CanDecodeMsg")
    return CanDecodeMsg(_root(root, "CanDecodeMsg.root"))


def _pack_bool_set(b: BoolSet) -> tuple:
    return (b.mask,)


def _unpack_bool_set(f: tuple) -> BoolSet:
    (mask,) = _fields(f, 1, "BoolSet")
    _need(type(mask) is int and 0 <= mask <= 3, "BoolSet: bad mask")
    return BoolSet(mask)


def _pack_bval_msg(m: BValMsg) -> tuple:
    return (m.value,)


def _unpack_bval_msg(f: tuple) -> BValMsg:
    (value,) = _fields(f, 1, "BValMsg")
    return BValMsg(_bool(value, "BValMsg.value"))


def _pack_aux_msg(m: AuxMsg) -> tuple:
    return (m.value,)


def _unpack_aux_msg(f: tuple) -> AuxMsg:
    (value,) = _fields(f, 1, "AuxMsg")
    return AuxMsg(_bool(value, "AuxMsg.value"))


def _pack_conf_msg(m: ConfMsg) -> tuple:
    return (m.vals,)


def _unpack_conf_msg(f: tuple) -> ConfMsg:
    (vals,) = _fields(f, 1, "ConfMsg")
    _need(isinstance(vals, BoolSet), "ConfMsg: bad vals")
    return ConfMsg(vals)


def _pack_term_msg(m: TermMsg) -> tuple:
    return (m.value,)


def _unpack_term_msg(f: tuple) -> TermMsg:
    (value,) = _fields(f, 1, "TermMsg")
    return TermMsg(_bool(value, "TermMsg.value"))


def _pack_sign_msg(m: SignMessage) -> tuple:
    return (m.share,)


def _unpack_sign_msg(f: tuple) -> SignMessage:
    (share,) = _fields(f, 1, "SignMessage")
    _need(isinstance(share, SignatureShare), "SignMessage: bad share")
    return SignMessage(share)


def _pack_coin_msg(m: CoinMsg) -> tuple:
    return (m.inner,)


def _unpack_coin_msg(f: tuple) -> CoinMsg:
    (inner,) = _fields(f, 1, "CoinMsg")
    _need(isinstance(inner, SignMessage), "CoinMsg: bad inner")
    return CoinMsg(inner)


def _pack_decrypt_msg(m: DecryptMessage) -> tuple:
    return (m.share,)


def _unpack_decrypt_msg(f: tuple) -> DecryptMessage:
    (share,) = _fields(f, 1, "DecryptMessage")
    _need(isinstance(share, DecryptionShare), "DecryptMessage: bad share")
    return DecryptMessage(share)


def _pack_aba_msg(m: AbaMessage) -> tuple:
    return (m.round, m.content)


def _unpack_aba_msg(f: tuple) -> AbaMessage:
    rnd, content = _fields(f, 2, "AbaMessage")
    # explicit type tuple (not the _ABA_CONTENT alias): the HBT005
    # delegation analysis reads isinstance targets by name
    _need(
        isinstance(content, (BValMsg, AuxMsg, ConfMsg, CoinMsg, TermMsg)),
        "AbaMessage: bad content",
    )
    return AbaMessage(_nonneg(rnd, "AbaMessage.round"), content)


_BC_CONTENT = (ValueMsg, EchoMsg, ReadyMsg, EchoHashMsg, CanDecodeMsg)


def _pack_subset_msg(m: SubsetMessage) -> tuple:
    return (m.proposer, m.kind, m.inner)


def _unpack_subset_msg(f: tuple) -> SubsetMessage:
    proposer, kind, inner = _fields(f, 3, "SubsetMessage")
    _node_id(proposer, "SubsetMessage.proposer")
    if kind == BC:
        _need(isinstance(inner, _BC_CONTENT), "SubsetMessage: bad bc inner")
    elif kind == BA:
        _need(isinstance(inner, AbaMessage), "SubsetMessage: bad ba inner")
    else:
        raise DecodeError("SubsetMessage: bad kind")
    return SubsetMessage(proposer, kind, inner)


def _pack_hb_msg(m: HbMessage) -> tuple:
    return (m.epoch, m.kind, m.proposer, m.inner)


def _unpack_hb_msg(f: tuple) -> HbMessage:
    epoch, kind, proposer, inner = _fields(f, 4, "HbMessage")
    _nonneg(epoch, "HbMessage.epoch")
    if kind == SUBSET:
        _need(proposer is None, "HbMessage: subset with proposer")
        _need(isinstance(inner, SubsetMessage), "HbMessage: bad subset inner")
    elif kind == DECRYPT:
        _node_id(proposer, "HbMessage.proposer")
        _need(isinstance(inner, DecryptMessage), "HbMessage: bad decrypt inner")
    else:
        raise DecodeError("HbMessage: bad kind")
    return HbMessage(epoch, kind, proposer, inner)


def _pack_dhb_msg(m: DhbMessage) -> tuple:
    return (m.era, m.inner)


def _unpack_dhb_msg(f: tuple) -> DhbMessage:
    era, inner = _fields(f, 2, "DhbMessage")
    _need(isinstance(inner, HbMessage), "DhbMessage: bad inner")
    return DhbMessage(_nonneg(era, "DhbMessage.era"), inner)


def _pack_sq_msg(m: SqMessage) -> tuple:
    return (m.kind, m.value)


def _unpack_sq_msg(f: tuple) -> SqMessage:
    kind, value = _fields(f, 2, "SqMessage")
    if kind == "epoch_started":
        _need(
            type(value) is tuple
            and len(value) == 2
            and all(type(x) is int and x >= 0 for x in value),
            "SqMessage: bad epoch",
        )
    elif kind == "algo":
        # Both the dynamic (DhbMessage) and static (HbMessage) stacks
        # ride through SenderQueue.
        _need(isinstance(value, (DhbMessage, HbMessage)), "SqMessage: bad algo")
    elif kind == "join_plan":
        _need(isinstance(value, JoinPlan), "SqMessage: bad plan")
    else:
        raise DecodeError("SqMessage: bad kind")
    return SqMessage(kind, value)


# -- registration -----------------------------------------------------------
# mirror: wire-grammar — this registration table IS the Python half of
#     the wire grammar; the C++ half is engine.cpp's wire codec
#     (wenc_* emitters + WireWalk acceptance).  HBX001 diffs the two
#     tag sets; tags the engine carries only as opaque committed-
#     contribution bytes are annotated `# lint: wire-oneside (...)`.

# lint: wire-oneside (engine carries ciphertexts as opaque contribution
#     bytes; only the Python batch path decodes them)
register_struct("ct", Ciphertext, _pack_ciphertext, _unpack_ciphertext)
register_token_struct("ct", _fast_build_ct)
# lint: wire-oneside (combined signatures live inside committed batches,
#     opaque to the engine wire codec)
register_struct("sig", Signature, _pack_signature, _unpack_signature)
register_struct("pk", PublicKey, _pack_public_key, _unpack_public_key)
register_struct("comm", Commitment, _pack_commitment, _unpack_commitment)
# lint: wire-oneside (DKG bivariate commitments ride inside Part/Ack
#     contribution payloads the engine never parses)
register_struct(
    "bicomm", BivarCommitment, _pack_bivar_commitment, _unpack_bivar_commitment
)
register_struct("encsched", EncryptionSchedule, _pack_schedule, _unpack_schedule)
# lint: wire-oneside (DHB vote payloads are committed-batch content,
#     opaque contribution bytes to the engine)
register_struct("change", Change, _pack_change, _unpack_change)
# lint: wire-oneside (signed votes are committed-batch content, opaque
#     contribution bytes to the engine)
register_struct("svote", SignedVote, _pack_signed_vote, _unpack_signed_vote)
# lint: wire-oneside (signed key-gen messages are committed-batch
#     content, opaque contribution bytes to the engine)
register_struct("skg", SignedKeyGenMsg, _pack_signed_kg, _unpack_signed_kg)
# lint: wire-oneside (InternalContrib is the committed-contribution
#     envelope itself — the engine hands its bytes to Python whole)
register_struct(
    "icontrib", InternalContrib, _pack_internal_contrib, _unpack_internal_contrib
)
register_struct("joinplan", JoinPlan, _pack_join_plan, _unpack_join_plan)
# lint: wire-oneside (DKG Part rides inside key-gen contribution
#     payloads the engine never parses)
register_struct("part", Part, _pack_part, _unpack_part)
# lint: wire-oneside (DKG Ack rides inside key-gen contribution
#     payloads the engine never parses)
register_struct("ack", Ack, _pack_ack, _unpack_ack)
# Crypto-plane RPC payloads only: the service process boundary of
# cryptoplane/proc_service.py.  The engine wire codec never carries
# verification requests (native nodes hand an attached ext backend
# fully-decoded request objects), so this tag is Python-side by design.
# lint: wire-oneside (crypto-plane RPC only; engine codec never
#     carries verification requests)
register_struct("vreq", VerifyRequest, _pack_verify_request, _unpack_verify_request)

# transport-boundary (live wire) types
register_struct("sigshare", SignatureShare, _pack_sig_share, _unpack_sig_share)
register_struct("decshare", DecryptionShare, _pack_dec_share, _unpack_dec_share)
register_struct("proof", Proof, _pack_proof, _unpack_proof)
register_struct("bc_value", ValueMsg, _pack_value_msg, _unpack_value_msg)
register_struct("bc_echo", EchoMsg, _pack_echo_msg, _unpack_echo_msg)
register_struct("bc_ready", ReadyMsg, _pack_root_msg, _unpack_ready_msg)
register_struct("bc_echohash", EchoHashMsg, _pack_root_msg, _unpack_echo_hash_msg)
register_struct(
    "bc_candecode", CanDecodeMsg, _pack_root_msg, _unpack_can_decode_msg
)
register_struct("bools", BoolSet, _pack_bool_set, _unpack_bool_set)
register_struct("ba_bval", BValMsg, _pack_bval_msg, _unpack_bval_msg)
register_struct("ba_aux", AuxMsg, _pack_aux_msg, _unpack_aux_msg)
register_struct("ba_conf", ConfMsg, _pack_conf_msg, _unpack_conf_msg)
register_struct("ba_coin", CoinMsg, _pack_coin_msg, _unpack_coin_msg)
register_struct("ba_term", TermMsg, _pack_term_msg, _unpack_term_msg)
register_struct("ba", AbaMessage, _pack_aba_msg, _unpack_aba_msg)
register_struct("signmsg", SignMessage, _pack_sign_msg, _unpack_sign_msg)
register_struct("decmsg", DecryptMessage, _pack_decrypt_msg, _unpack_decrypt_msg)
register_struct("subsetmsg", SubsetMessage, _pack_subset_msg, _unpack_subset_msg)
register_struct("hbmsg", HbMessage, _pack_hb_msg, _unpack_hb_msg)
register_struct("dhbmsg", DhbMessage, _pack_dhb_msg, _unpack_dhb_msg)
register_struct("sqmsg", SqMessage, _pack_sq_msg, _unpack_sq_msg)
