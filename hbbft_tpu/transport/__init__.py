"""Real socket transport + cluster runtime (ISSUE 4).

The first subsystem that runs hbbft nodes over actual TCP connections
instead of the in-process simulator: length-prefixed serde frames
(:mod:`.framing`), a selectors-based per-node event loop with
backpressure, reconnect, and sendmsg vectored egress
(:mod:`.transport`), a thread-per-node cluster harness
(:mod:`.cluster`), a process-per-node runtime (:mod:`.proc_cluster`
over :mod:`.cluster_worker` — ``node_impl="native_proc"``), and a
deterministic byte-level fault injector (:mod:`.faults`).  See
docs/TRANSPORT.md.
"""

from hbbft_tpu.transport.cluster import ClusterNode, LocalCluster
from hbbft_tpu.transport.native_node import NativeClusterNode
from hbbft_tpu.transport.proc_cluster import ProcCluster
from hbbft_tpu.transport.faults import (
    FaultInjector,
    FaultStats,
    LinkFaults,
    PartitionSpec,
    wan_profile,
)
from hbbft_tpu.transport.framing import (
    KIND_HELLO,
    KIND_MSG,
    KIND_MSGB,
    MAX_FRAME_LEN,
    PROTO_VERSION,
    RECV_CHUNK,
    FrameDecoder,
    FrameError,
    decode_hello,
    decode_msgb,
    encode_frame,
    encode_hello,
    encode_msgb,
    frame_message_count,
    msgb_body,
    validate_msgb,
)
from hbbft_tpu.transport.transport import PeerStats, TcpTransport
