"""Length-prefixed frames over the serde codec: the TCP trust boundary.

A peer socket delivers an untrusted byte stream.  This module slices it
into bounded frames before any of those bytes reach object construction:

    frame   := len:u32  crc:u32  kind:u8  payload[len-1]

``len`` counts the kind byte plus the payload and is hard-capped by
``max_frame_len`` — a declared length past the cap is rejected from the
4-byte prefix alone, so a malicious peer can never make a node buffer
(let alone parse) an unbounded message.  The cap is deliberately far
below serde's own 256 MiB per-field bound (``serde._MAX_LEN``, enforced
byte-identically by the C token scanner ``hbe_serde_scan``): serde
bounds any *one* length field, the frame cap bounds the *whole* message
— both limits apply on the read path, framing first.

``crc`` is CRC32 over ``kind || payload``.  It is NOT an integrity MAC
(a Byzantine peer computes valid CRCs for garbage); it pins down
*channel* corruption — without it, a bit flip inside the payload could
still frame-parse (or, worse, a flipped length prefix could re-frame
the remainder into bogus frames that get consumed and ACKed, desyncing
the resume layer's cumulative count and silently discarding a clean
frame).  With the CRC, any flipped transmission dies at the framing
layer: connection dropped un-ACKed, and the resume layer retransmits
the CLEAN original — which is exactly the channel-fault model
:mod:`hbbft_tpu.transport.faults` injects.

Frame kinds:

* ``KIND_HELLO`` — connection handshake.  Payload is the serde encoding
  of ``(PROTO_VERSION, cluster_id, node_id)``; the acceptor learns who
  is talking and rejects version/cluster mismatches (a node from a
  different cluster config speaks a disjoint session id, so its
  protocol messages must never reach a handler).
* ``KIND_MSG`` — one protocol message; payload is the serde encoding of
  an :class:`~hbbft_tpu.protocols.sender_queue.SqMessage` tree, decoded
  with the cluster's suite pin.
* ``KIND_ACK`` — cumulative delivery acknowledgement, payload a fixed
  8-byte big-endian count of MSG frames the acceptor has consumed on
  this link *ever* (across reconnects).  Flows acceptor -> dialer on
  the otherwise-unused reverse direction of a connection; the dialer
  retains unacked frames and retransmits them after a reconnect, which
  is what makes a mid-epoch disconnect lossless for a surviving process
  (transport.py "resume layer").

Decode errors raise :class:`FrameError`; the transport's uniform
response is: count the fault in metrics, drop the connection (the
stream is unsynchronized garbage from that point), and let reconnect
establish a fresh one (tests/test_transport.py pins this never crashes
a node).
"""

from __future__ import annotations

import zlib
from typing import Any, List, Optional, Tuple

from hbbft_tpu.utils import serde

#: Default whole-frame cap (16 MiB).  An N=1024 DKG-era contribution is
#: ~1 MB; nothing the current stack emits approaches this.  Configurable
#: per transport, but every read path MUST enforce *some* cap (lint rule
#: HBT006 pins the recv plumbing).
MAX_FRAME_LEN = 1 << 24

#: Bounded socket read size.  recv() callers use this constant so a
#: single syscall can never hand us more than 64 KiB to buffer before
#: the frame-length check applies (HBT006).
RECV_CHUNK = 1 << 16

PROTO_VERSION = 1

KIND_HELLO = 0x01
KIND_MSG = 0x02
KIND_ACK = 0x03

_KINDS = (KIND_HELLO, KIND_MSG, KIND_ACK)

#: Crypto-plane RPC kinds (hbbft_tpu.cryptoplane.proc_service).  They
#: share the frame grammar (same length/CRC slicing, same caps) but are
#: a DISJOINT kind set passed explicitly via ``kinds=`` — the consensus
#: transport keeps rejecting them, so a crypto-service socket
#: accidentally pointed at a node port (or vice versa) dies at the
#: framing layer instead of smuggling frames across trust boundaries.
KIND_CRYPTO_HELLO = 0x21
KIND_CRYPTO_REQ = 0x22
KIND_CRYPTO_RESP = 0x23

CRYPTO_KINDS = (KIND_CRYPTO_HELLO, KIND_CRYPTO_REQ, KIND_CRYPTO_RESP)


def encode_ack(count: int) -> bytes:
    """Cumulative-consumed ACK frame (fixed 17 bytes on the wire)."""
    return encode_frame(KIND_ACK, count.to_bytes(8, "big"))


def decode_ack(payload: bytes) -> int:
    if len(payload) != 8:
        raise FrameError("ACK payload must be 8 bytes")
    return int.from_bytes(payload, "big")

_LEN_BYTES = 4
_CRC_BYTES = 4
_HDR_BYTES = _LEN_BYTES + _CRC_BYTES


class FrameError(ValueError):
    """Malformed, oversized, corrupted, or version-mismatched frame."""


def encode_frame(
    kind: int,
    payload: bytes,
    max_frame_len: int = MAX_FRAME_LEN,
    kinds: Tuple[int, ...] = _KINDS,
) -> bytes:
    """One wire frame.  Raises :class:`FrameError` if the frame would
    exceed ``max_frame_len`` (the local cap: never emit what a peer
    honoring the same limits would have to reject).  ``kinds`` is the
    plane's accepted kind set (transport default; the crypto-plane RPC
    passes :data:`CRYPTO_KINDS`)."""
    if kind not in kinds:
        raise FrameError(f"unknown frame kind 0x{kind:02x}")
    length = 1 + len(payload)
    if length > max_frame_len:
        raise FrameError(
            f"frame of {length} bytes exceeds max_frame_len={max_frame_len}"
        )
    body = bytes([kind]) + payload
    return (
        length.to_bytes(_LEN_BYTES, "big")
        + zlib.crc32(body).to_bytes(_CRC_BYTES, "big")
        + body
    )


class FrameDecoder:
    """Incremental frame slicer over an untrusted byte stream.

    ``feed(data)`` buffers; ``next_frame()`` returns ``(kind, payload)``
    or ``None`` when the buffer holds no complete frame.  Any violation
    raises :class:`FrameError` and poisons the decoder (the stream has
    no recoverable sync point) — callers drop the connection.
    """

    __slots__ = ("max_frame_len", "kinds", "_buf", "_poisoned")

    def __init__(
        self,
        max_frame_len: int = MAX_FRAME_LEN,
        kinds: Tuple[int, ...] = _KINDS,
    ) -> None:
        self.max_frame_len = max_frame_len
        self.kinds = kinds
        self._buf = bytearray()
        self._poisoned = False

    def feed(self, data: bytes) -> None:
        if self._poisoned:
            raise FrameError("decoder poisoned by an earlier frame error")
        self._buf += data

    def buffered(self) -> int:
        return len(self._buf)

    def next_frame(self) -> Optional[Tuple[int, bytes]]:
        if self._poisoned:
            raise FrameError("decoder poisoned by an earlier frame error")
        buf = self._buf
        if len(buf) < _LEN_BYTES:
            return None
        length = int.from_bytes(buf[:_LEN_BYTES], "big")
        if length < 1 or length > self.max_frame_len:
            self._poisoned = True
            raise FrameError(
                f"declared frame length {length} outside [1, {self.max_frame_len}]"
            )
        if len(buf) < _HDR_BYTES + length:
            return None
        crc = int.from_bytes(buf[_LEN_BYTES:_HDR_BYTES], "big")
        body = bytes(buf[_HDR_BYTES : _HDR_BYTES + length])
        if zlib.crc32(body) != crc:
            self._poisoned = True
            raise FrameError("frame CRC mismatch (channel corruption)")
        kind = body[0]
        if kind not in self.kinds:
            self._poisoned = True
            raise FrameError(f"unknown frame kind 0x{kind:02x}")
        del buf[: _HDR_BYTES + length]
        return kind, body[1:]

    def frames(self) -> List[Tuple[int, bytes]]:
        out = []
        while True:
            f = self.next_frame()
            if f is None:
                return out
            out.append(f)


# -- handshake ---------------------------------------------------------------


def encode_hello(
    node_id: Any, cluster_id: bytes, max_frame_len: int = MAX_FRAME_LEN
) -> bytes:
    return encode_frame(
        KIND_HELLO,
        serde.dumps((PROTO_VERSION, cluster_id, node_id)),
        max_frame_len,
    )


def decode_hello(payload: bytes, cluster_id: bytes) -> Any:
    """Validate a HELLO payload; returns the announced node id.

    Raises :class:`FrameError` on malformed serde, version mismatch, or
    foreign cluster id (never a crash: this is peer-authored input).
    """
    obj = serde.try_loads(payload)
    if (
        not isinstance(obj, tuple)
        or len(obj) != 3
        or type(obj[0]) is not int
        or type(obj[1]) is not bytes
    ):
        raise FrameError("malformed HELLO")
    version, cid, node_id = obj
    if version != PROTO_VERSION:
        raise FrameError(f"protocol version {version} != {PROTO_VERSION}")
    if cid != cluster_id:
        raise FrameError("HELLO from a different cluster")
    if type(node_id) not in (int, str, bytes):
        raise FrameError("bad node id in HELLO")
    return node_id
