"""Length-prefixed frames over the serde codec: the TCP trust boundary.

A peer socket delivers an untrusted byte stream.  This module slices it
into bounded frames before any of those bytes reach object construction:

    frame   := len:u32  crc:u32  kind:u8  payload[len-1]

``len`` counts the kind byte plus the payload and is hard-capped by
``max_frame_len`` — a declared length past the cap is rejected from the
4-byte prefix alone, so a malicious peer can never make a node buffer
(let alone parse) an unbounded message.  The cap is deliberately far
below serde's own 256 MiB per-field bound (``serde._MAX_LEN``, enforced
byte-identically by the C token scanner ``hbe_serde_scan``): serde
bounds any *one* length field, the frame cap bounds the *whole* message
— both limits apply on the read path, framing first.

``crc`` is CRC32 over ``kind || payload``.  It is NOT an integrity MAC
(a Byzantine peer computes valid CRCs for garbage); it pins down
*channel* corruption — without it, a bit flip inside the payload could
still frame-parse (or, worse, a flipped length prefix could re-frame
the remainder into bogus frames that get consumed and ACKed, desyncing
the resume layer's cumulative count and silently discarding a clean
frame).  With the CRC, any flipped transmission dies at the framing
layer: connection dropped un-ACKed, and the resume layer retransmits
the CLEAN original — which is exactly the channel-fault model
:mod:`hbbft_tpu.transport.faults` injects.

Frame kinds:

* ``KIND_HELLO`` — connection handshake.  Payload is the serde encoding
  of ``(PROTO_VERSION, cluster_id, node_id)``; the acceptor learns who
  is talking and rejects version/cluster mismatches (a node from a
  different cluster config speaks a disjoint session id, so its
  protocol messages must never reach a handler).
* ``KIND_MSG`` — one protocol message; payload is the serde encoding of
  an :class:`~hbbft_tpu.protocols.sender_queue.SqMessage` tree, decoded
  with the cluster's suite pin.
* ``KIND_ACK`` — cumulative delivery acknowledgement, payload a fixed
  8-byte big-endian count of MSG/MSGB frames the acceptor has consumed
  on this link *ever* (across reconnects).  Flows acceptor -> dialer on
  the otherwise-unused reverse direction of a connection; the dialer
  retains unacked frames and retransmits them after a reconnect, which
  is what makes a mid-epoch disconnect lossless for a surviving process
  (transport.py "resume layer").
* ``KIND_MSGB`` — one frame carrying a BATCH of protocol messages for
  the same destination (round 20 coalescing: the per-message frame
  header, CRC, ACK-accounting, and Python dispatch costs were the
  measured message-plane bound once decode moved native).  The body
  grammar is :func:`msgb_body`; the ACK unit stays the FRAME, consumed
  batch-atomically — a receiver never acknowledges an MSGB it only
  partially consumed, so the resume layer's cumulative count is
  unchanged.  Every decoder accepts MSGB regardless of the
  ``HBBFT_TPU_COALESCE`` knob (accept-both interop: the knob gates
  EMISSION only, so mixed clusters never desync).

Decode errors raise :class:`FrameError`; the transport's uniform
response is: count the fault in metrics, drop the connection (the
stream is unsynchronized garbage from that point), and let reconnect
establish a fresh one (tests/test_transport.py pins this never crashes
a node).
"""

from __future__ import annotations

import zlib
from typing import Any, List, Optional, Tuple

from hbbft_tpu.utils import serde

#: Default whole-frame cap (16 MiB).  An N=1024 DKG-era contribution is
#: ~1 MB; nothing the current stack emits approaches this.  Configurable
#: per transport, but every read path MUST enforce *some* cap (lint rule
#: HBT006 pins the recv plumbing).
MAX_FRAME_LEN = 1 << 24

#: Bounded socket read size.  recv() callers use this constant so a
#: single syscall can never hand us more than 64 KiB to buffer before
#: the frame-length check applies (HBT006).
RECV_CHUNK = 1 << 16

PROTO_VERSION = 1

KIND_HELLO = 0x01
KIND_MSG = 0x02
KIND_ACK = 0x03
KIND_MSGB = 0x04

_KINDS = (KIND_HELLO, KIND_MSG, KIND_ACK, KIND_MSGB)

#: Crypto-plane RPC kinds (hbbft_tpu.cryptoplane.proc_service).  They
#: share the frame grammar (same length/CRC slicing, same caps) but are
#: a DISJOINT kind set passed explicitly via ``kinds=`` — the consensus
#: transport keeps rejecting them, so a crypto-service socket
#: accidentally pointed at a node port (or vice versa) dies at the
#: framing layer instead of smuggling frames across trust boundaries.
KIND_CRYPTO_HELLO = 0x21
KIND_CRYPTO_REQ = 0x22
KIND_CRYPTO_RESP = 0x23

CRYPTO_KINDS = (KIND_CRYPTO_HELLO, KIND_CRYPTO_REQ, KIND_CRYPTO_RESP)


def encode_ack(count: int) -> bytes:
    """Cumulative-consumed ACK frame (fixed 17 bytes on the wire)."""
    return encode_frame(KIND_ACK, count.to_bytes(8, "big"))


def decode_ack(payload: bytes) -> int:
    if len(payload) != 8:
        raise FrameError("ACK payload must be 8 bytes")
    return int.from_bytes(payload, "big")


# MSGB body grammar (# mirror: msgb-grammar — native/engine.cpp emits
# and consumes the identical layout in hbe_node_egress_drain_msgb /
# hbe_node_ingest_wire):
#
#     body := count:u32  ( len:u32  bytes[len] ) * count
#
# Both u32 fields are big-endian like the frame header.  The element
# lengths must sum EXACTLY to the body: trailing bytes, a short
# element, or count == 0 are FrameErrors — a Byzantine batch never
# partially parses, so the frame-unit ACK can treat MSGB consumption as
# all-or-nothing.  A bogus count dies on arithmetic alone (each element
# needs at least its 4-byte length header) before any walking.

_MSGB_COUNT_BYTES = 4
_MSGB_LEN_BYTES = 4


def msgb_body(payloads: List[bytes]) -> bytes:
    """The MSGB body carrying ``payloads`` in order (trusted input: our
    own egress path; peers go through :func:`validate_msgb`)."""
    parts = [len(payloads).to_bytes(_MSGB_COUNT_BYTES, "big")]
    for p in payloads:
        parts.append(len(p).to_bytes(_MSGB_LEN_BYTES, "big"))
        parts.append(p)
    return b"".join(parts)


def encode_msgb(
    payloads: List[bytes], max_frame_len: int = MAX_FRAME_LEN
) -> bytes:
    """One MSGB frame carrying ``payloads`` in order."""
    return encode_frame(KIND_MSGB, msgb_body(payloads), max_frame_len)


def validate_msgb(body: bytes) -> int:
    """Bounds-check an MSGB body without slicing any element; returns
    the message count.  Raises :class:`FrameError` on any grammar
    violation (peer-authored input: never a crash)."""
    n = len(body)
    if n < _MSGB_COUNT_BYTES:
        raise FrameError("MSGB body shorter than its count field")
    count = int.from_bytes(body[:_MSGB_COUNT_BYTES], "big")
    if count < 1:
        raise FrameError("MSGB with zero messages")
    if _MSGB_COUNT_BYTES + _MSGB_LEN_BYTES * count > n:
        raise FrameError(f"MSGB count {count} exceeds body size {n}")
    # Single-accumulator walk (this runs once per MSGB element on the
    # ingress hot path): the final exactness check alone rejects every
    # violation.  An overlong element or truncated header pushes ``off``
    # strictly past ``n`` — a short/empty length slice yields ln parsed
    # from k < 4 bytes, and off + 4 + ln > n whenever off + 4 > n — and
    # once past, off only grows, so it can never land back on n; a
    # trailing-bytes violation leaves off < n.  Loop length is bounded
    # by the count pre-check above (count <= n/4).
    off = _MSGB_COUNT_BYTES
    for _ in range(count):
        off += _MSGB_LEN_BYTES + int.from_bytes(
            body[off : off + _MSGB_LEN_BYTES], "big"
        )
    if off != n:
        raise FrameError("malformed MSGB element layout")
    return count


def decode_msgb(body: bytes) -> List[bytes]:
    """The payload list of an MSGB body (validates first; raises
    :class:`FrameError` on violation)."""
    count = validate_msgb(body)
    out: List[bytes] = []
    off = _MSGB_COUNT_BYTES
    for _ in range(count):
        ln = int.from_bytes(body[off : off + _MSGB_LEN_BYTES], "big")
        off += _MSGB_LEN_BYTES
        out.append(body[off : off + ln])
        off += ln
    return out


def frame_message_count(frame: bytes) -> int:
    """Protocol messages a fully-encoded wire frame carries: 1 for MSG,
    the count field for MSGB, 0 for anything else.  Trusted input (the
    egress path's own encoder output) — no validation."""
    if len(frame) <= _HDR_BYTES:
        return 0
    kind = frame[_HDR_BYTES]
    if kind == KIND_MSG:
        return 1
    if kind == KIND_MSGB:
        start = _HDR_BYTES + 1
        return int.from_bytes(frame[start : start + _MSGB_COUNT_BYTES], "big")
    return 0


_LEN_BYTES = 4
_CRC_BYTES = 4
_HDR_BYTES = _LEN_BYTES + _CRC_BYTES


class FrameError(ValueError):
    """Malformed, oversized, corrupted, or version-mismatched frame."""


def encode_frame(
    kind: int,
    payload: bytes,
    max_frame_len: int = MAX_FRAME_LEN,
    kinds: Tuple[int, ...] = _KINDS,
) -> bytes:
    """One wire frame.  Raises :class:`FrameError` if the frame would
    exceed ``max_frame_len`` (the local cap: never emit what a peer
    honoring the same limits would have to reject).  ``kinds`` is the
    plane's accepted kind set (transport default; the crypto-plane RPC
    passes :data:`CRYPTO_KINDS`)."""
    if kind not in kinds:
        raise FrameError(f"unknown frame kind 0x{kind:02x}")
    length = 1 + len(payload)
    if length > max_frame_len:
        raise FrameError(
            f"frame of {length} bytes exceeds max_frame_len={max_frame_len}"
        )
    body = bytes([kind]) + payload
    return (
        length.to_bytes(_LEN_BYTES, "big")
        + zlib.crc32(body).to_bytes(_CRC_BYTES, "big")
        + body
    )


class FrameDecoder:
    """Incremental frame slicer over an untrusted byte stream.

    ``feed(data)`` buffers; ``next_frame()`` returns ``(kind, payload)``
    or ``None`` when the buffer holds no complete frame.  Any violation
    raises :class:`FrameError` and poisons the decoder (the stream has
    no recoverable sync point) — callers drop the connection.
    """

    __slots__ = ("max_frame_len", "kinds", "_buf", "_pos", "_poisoned")

    def __init__(
        self,
        max_frame_len: int = MAX_FRAME_LEN,
        kinds: Tuple[int, ...] = _KINDS,
    ) -> None:
        self.max_frame_len = max_frame_len
        self.kinds = kinds
        self._buf = bytearray()
        # Consumed-prefix cursor: deleting each frame's bytes off the
        # buffer head (`del buf[:n]`) was quadratic over a large read
        # burst — every frame moved the whole remainder.  The cursor
        # just advances; the consumed prefix is dropped in ONE compaction
        # when parsing stops (no complete frame left), so a burst costs
        # one move total regardless of how many frames it held.
        self._pos = 0
        self._poisoned = False

    def feed(self, data: bytes) -> None:
        if self._poisoned:
            raise FrameError("decoder poisoned by an earlier frame error")
        if self._pos and self._pos == len(self._buf):
            # fully drained: reset instead of growing behind the cursor
            self._buf.clear()
            self._pos = 0
        self._buf += data

    def buffered(self) -> int:
        return len(self._buf) - self._pos

    def _compact(self) -> None:
        if self._pos:
            del self._buf[: self._pos]
            self._pos = 0

    def next_frame(self) -> Optional[Tuple[int, bytes]]:
        if self._poisoned:
            raise FrameError("decoder poisoned by an earlier frame error")
        buf, pos = self._buf, self._pos
        avail = len(buf) - pos
        if avail < _LEN_BYTES:
            self._compact()
            return None
        length = int.from_bytes(buf[pos : pos + _LEN_BYTES], "big")
        if length < 1 or length > self.max_frame_len:
            self._poisoned = True
            raise FrameError(
                f"declared frame length {length} outside [1, {self.max_frame_len}]"
            )
        if avail < _HDR_BYTES + length:
            self._compact()
            return None
        crc = int.from_bytes(buf[pos + _LEN_BYTES : pos + _HDR_BYTES], "big")
        body = bytes(buf[pos + _HDR_BYTES : pos + _HDR_BYTES + length])
        if zlib.crc32(body) != crc:
            self._poisoned = True
            raise FrameError("frame CRC mismatch (channel corruption)")
        kind = body[0]
        if kind not in self.kinds:
            self._poisoned = True
            raise FrameError(f"unknown frame kind 0x{kind:02x}")
        self._pos = pos + _HDR_BYTES + length
        return kind, body[1:]

    def frames(self) -> List[Tuple[int, bytes]]:
        out = []
        while True:
            f = self.next_frame()
            if f is None:
                return out
            out.append(f)


# -- handshake ---------------------------------------------------------------


def encode_hello(
    node_id: Any, cluster_id: bytes, max_frame_len: int = MAX_FRAME_LEN
) -> bytes:
    return encode_frame(
        KIND_HELLO,
        serde.dumps((PROTO_VERSION, cluster_id, node_id)),
        max_frame_len,
    )


def decode_hello(payload: bytes, cluster_id: bytes) -> Any:
    """Validate a HELLO payload; returns the announced node id.

    Raises :class:`FrameError` on malformed serde, version mismatch, or
    foreign cluster id (never a crash: this is peer-authored input).
    """
    obj = serde.try_loads(payload)
    if (
        not isinstance(obj, tuple)
        or len(obj) != 3
        or type(obj[0]) is not int
        or type(obj[1]) is not bytes
    ):
        raise FrameError("malformed HELLO")
    version, cid, node_id = obj
    if version != PROTO_VERSION:
        raise FrameError(f"protocol version {version} != {PROTO_VERSION}")
    if cid != cluster_id:
        raise FrameError("HELLO from a different cluster")
    if type(node_id) not in (int, str, bytes):
        raise FrameError("bad node id in HELLO")
    return node_id
