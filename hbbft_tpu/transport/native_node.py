"""Native-engine cluster node: C++ decode+handle loop on real sockets.

The round-8 Python cluster node plateaued at ~4.8k handled msgs/s
regardless of N because every frame was serde-decoded and protocol-
stepped by a Python thread, while the same engine moves 1.7M msgs/s
in-process (BASELINE.md round 8).  This runtime closes that gap with
the engine's message-boundary API (round 9):

* the transport's burst consumer (``TcpTransport.on_wire_batch``)
  queues one inbox item per read burst — a list of ``(nmsg, data)``
  wire records (plain MSG payloads and raw MSGB bodies), not one
  Python callback per frame;
* the protocol thread packs each burst into ONE ctypes call
  (``hbe_node_ingest_wire``: MSGB body walk + decode + epoch-announce
  handling + enqueue, all in C — no Python slicing of batch bodies),
  drains the engine's delivery queue with one ``hbe_run``, and hands
  the accumulated egress back as per-destination MSGB bodies built in
  C (``hbe_node_egress_drain_msgb`` — the round-20 coalescing fast
  path; ``HBBFT_TPU_COALESCE=0`` or a pre-20 engine snapshot falls
  back to the round-9 per-frame drain);
* the per-BATCH layers stay the reused Python stack
  (``QueueingHoneyBadger`` over :class:`~hbbft_tpu.native_engine.
  NativeDhb`), fired through the engine's batch callbacks exactly as in
  :class:`~hbbft_tpu.native_engine.NativeQhbNet`.

The Python :class:`~hbbft_tpu.transport.cluster.ClusterNode` is kept as
the cross-check oracle: same keys, same rng ritual, same eager
(``flush_every=1``) crypto cadence — a native cluster at seed s commits
byte-identical batches to the Python-node cluster at seed s
(tests/test_transport_native.py pins this, plus the fault drills).

Threading: the protocol thread is the ONLY caller into the engine
(ingest / run / drain / stats / fault counters — the engine is not
thread-safe); the transport thread only feeds the bounded inbox, and
readers snapshot ``outputs`` (a plain list) under the GIL.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, List, Optional, Tuple

from hbbft_tpu.crypto.suite import Suite
from hbbft_tpu.native_engine import NativeNodeEngine
from hbbft_tpu.obs.trace import TraceBuffer
from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.transport.cluster import track_commits
from hbbft_tpu.transport.transport import TcpTransport
from hbbft_tpu.utils.metrics import EpochTracker, Metrics

#: Max inbox items coalesced into one processing sweep.  Bounds how
#: long egress draining can starve behind a flood of inbound bursts;
#: each item is already a whole read burst, so 256 sweeps ~16 MiB.
_MAX_COALESCE = 256


class NativeClusterNode:
    """One cluster node backed by a :class:`NativeNodeEngine`.

    Public surface mirrors :class:`~hbbft_tpu.transport.cluster.
    ClusterNode` (``submit`` / ``batches`` / ``start`` / ``stop`` /
    ``metrics`` / ``transport``), so :class:`LocalCluster` drives both
    implementations through one code path.
    """

    def __init__(
        self,
        node_id: int,
        netinfo: NetworkInfo,
        all_ids: List[int],
        transport: TcpTransport,
        suite: Suite,
        seed: int,
        batch_size: int = 8,
        session_id: bytes = b"tcp-cluster",
        metrics: Optional[Metrics] = None,
        inbox_cap: int = 50_000,
        trace: Optional[TraceBuffer] = None,
        crypto_backend: Optional[Any] = None,
        flush_every: Optional[int] = None,
    ) -> None:
        self.id = node_id
        self.netinfo = netinfo
        self.all_ids = list(all_ids)
        self.transport = transport
        self.metrics = metrics if metrics is not None else transport.metrics
        # Flight recorder (round 12): the engine's bounded event log is
        # drained into this ring once per sweep (one ctypes call); the
        # engine side emits with no per-event allocation.
        self.trace = trace
        self.epochs = EpochTracker()
        self._last_commit_t = time.time()
        self._seen_batches = 0
        self._prof_last: dict = {}  # (kind, type) -> last published value
        self._next_prof_sync = 0.0
        # crypto_backend (round 13): run the engine's external-crypto
        # mode with share verification routed through this backend —
        # the cluster crypto-service arm (a ServiceClient of the shared
        # CryptoPlaneService).  The deferred cadence (flush_every=0 =
        # flush per ingest sweep at queue-dry) maximizes what each
        # service batch can merge; output-identical to the inline
        # scalar arm by the deferred-verification invariant.
        engine_kwargs: dict = {}
        if crypto_backend is not None:
            engine_kwargs["backend"] = crypto_backend
            engine_kwargs["flush_every"] = (
                0 if flush_every is None else flush_every
            )
        elif flush_every is not None:
            engine_kwargs["flush_every"] = flush_every
        self.engine = NativeNodeEngine(
            node_id,
            netinfo,
            seed=seed,
            batch_size=batch_size,
            session_id=session_id,
            suite=suite,
            trace_capacity=8192 if trace is not None else 0,
            **engine_kwargs,
        )
        # Bounded, like ClusterNode.inbox: a peer streaming faster than
        # the engine drains hits receive-side backpressure (the burst is
        # refused, the transport drops the connection un-acked, resume
        # retransmits later) instead of growing memory.
        self.inbox: "queue.Queue[tuple]" = queue.Queue(maxsize=inbox_cap)
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._synced_faults = 0  # engine fault entries already exported
        # Engine-ring drop count, cached by the protocol thread's sync
        # (the engine is not thread-safe, so trace_dropped() must not
        # call into it from a scraper thread); GIL-atomic int read.
        self._engine_trace_dropped = 0
        # Ingress: the wire-record consumer (raw MSGB bodies cross as
        # one record, walked in C) when the engine exports the round-20
        # fast path; otherwise the round-9 payload-burst consumer (the
        # transport unpacks MSGB bodies for it — accept-both interop
        # either way, regardless of the coalesce knob).
        if self.engine.supports_wire_batch:
            transport.on_wire_batch = self._on_wire_burst
        else:
            transport.on_batch = self._on_frame_burst

    # -- transport thread ----------------------------------------------
    def _on_frame_burst(self, sender: Any, payloads: List[bytes]) -> int:
        try:
            self.inbox.put_nowait(("msgs", sender, payloads))
        except queue.Full:
            self.metrics.count("cluster.inbox_overflow")
            return 0  # nothing consumed: connection drops un-acked
        return len(payloads)

    def _on_wire_burst(
        self, sender: Any, records: List[Tuple[int, bytes]]
    ) -> int:
        try:
            self.inbox.put_nowait(("wire", sender, records))
        except queue.Full:
            self.metrics.count("cluster.inbox_overflow")
            return 0  # nothing consumed: connection drops un-acked
        return len(records)  # frames, all-or-nothing (batch-atomic ACK)

    # -- any thread ----------------------------------------------------
    def submit(self, input: Any) -> None:
        try:
            self.inbox.put_nowait(("input", input, None))
        except queue.Full:
            self.metrics.count("cluster.input_dropped")

    def batches(self) -> List[DhbBatch]:
        # outputs is append-only on the protocol thread; list() under
        # the GIL is a consistent snapshot (same guarantee ClusterNode's
        # lock provides for its outputs list).
        return list(self.engine.outputs)

    def batch_count(self) -> int:
        return len(self.engine.outputs)  # len() is GIL-atomic

    def batches_from(self, start: int) -> List[DhbBatch]:
        return self.engine.outputs[start:]

    def last_committed(self) -> Optional[Tuple[int, int]]:
        """(era, epoch) of the newest committed batch, or None."""
        outs = self.engine.outputs
        if not outs:
            return None
        b = outs[-1]  # GIL-atomic tail read of an append-only list
        return (b.era, b.epoch)

    def trace_dropped(self) -> int:
        """Total trace events lost to overflow: the Python ring's drop
        count plus the engine ring's (as of the last protocol-thread
        sync) — the honest-truncation gauge behind ``trace.<i>.dropped``."""
        py = self.trace.dropped if self.trace is not None else 0
        return py + self._engine_trace_dropped

    def start(self) -> None:
        assert self._thread is None
        self._stop = False
        self._last_commit_t = time.time()
        self._thread = threading.Thread(
            target=self._run, name=f"native-node-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop = True  # flag, not a queue item: survives a full inbox
        t = self._thread
        t.join(timeout=10)
        self._thread = None
        # Final export only once the protocol thread has ACTUALLY
        # exited: then this (main-thread) engine access preserves the
        # one-caller rule temporally and end-of-run metrics carry the
        # full counters.  A thread that outlived the timed join (wedged
        # handler) still owns the engine — touching the non-thread-safe
        # vectors concurrently would race it, so skip the sync.
        if not t.is_alive():
            self._sync_engine_counters(force=True)

    # -- protocol thread -----------------------------------------------
    def _run(self) -> None:
        eng = self.engine
        egress: List[tuple] = []
        def collect(dest: int, payload: bytes) -> None:
            egress.append((dest, payload))
        # Egress arm, resolved once: C-built MSGB bodies when the knob
        # is on AND the engine exports the fast path, else the round-9
        # per-frame drain (send_many still respects the knob for the
        # Python-side packing of those frames).
        coalesce_out = self.transport.coalesce and eng.supports_wire_batch
        while not self._stop:
            try:
                item = self.inbox.get(timeout=0.2)
            except queue.Empty:
                self._guarded_sync()
                continue
            burst = [item]
            while len(burst) < _MAX_COALESCE:
                try:
                    burst.append(self.inbox.get_nowait())
                except queue.Empty:
                    break
            # Exception scope is per ingest-group/input, NOT the whole
            # sweep: the coalesced items behind a failing one were
            # already consumed + ACKed by the transport, so skipping
            # them would lose acknowledged frames with no retransmit
            # (the Python node's blast radius is one inbox item —
            # cluster.py keeps the same stance).  A handler bug must
            # not take the thread down mid-run either way — count it
            # loudly; tests assert the counter stays zero.
            i = 0
            while i < len(burst):
                if burst[i][0] == "msgs":
                    senders: List[int] = []
                    payloads: List[bytes] = []
                    while i < len(burst) and burst[i][0] == "msgs":
                        _, s, pp = burst[i]
                        senders.extend([s] * len(pp))
                        payloads.extend(pp)
                        i += 1
                    try:
                        handled = eng.ingest(senders, payloads)
                        self.metrics.count("cluster.msgs_handled", handled)
                        bad = len(payloads) - handled
                        if bad:
                            self.metrics.count("cluster.bad_payload", bad)
                        eng.run()
                    except Exception:
                        self.metrics.count("cluster.handler_errors")
                elif burst[i][0] == "wire":
                    wsenders: List[int] = []
                    records: List[Tuple[int, bytes]] = []
                    nmsgs = 0
                    while i < len(burst) and burst[i][0] == "wire":
                        _, s, recs = burst[i]
                        wsenders.extend([s] * len(recs))
                        records.extend(recs)
                        nmsgs += sum(nm if nm else 1 for nm, _ in recs)
                        i += 1
                    try:
                        handled = eng.ingest_wire(wsenders, records)
                        self.metrics.count("cluster.msgs_handled", handled)
                        bad = nmsgs - handled
                        if bad:
                            self.metrics.count("cluster.bad_payload", bad)
                        eng.run()
                    except Exception:
                        self.metrics.count("cluster.handler_errors")
                else:  # input
                    item_input = burst[i][1]
                    i += 1
                    try:
                        eng.handle_input(item_input)
                    except Exception:
                        self.metrics.count("cluster.handler_errors")
            try:
                if coalesce_out:
                    self._drain_egress_coalesced(egress)
                else:
                    egress.clear()
                    eng.drain_egress(collect)
                    if egress:
                        # one control-plane hand-off for the whole
                        # sweep's emissions (send_many: one wakeup,
                        # one drain op)
                        self.transport.send_many(egress)
            except Exception:
                self.metrics.count("cluster.handler_errors")
            self._guarded_sync()

    def _drain_egress_coalesced(self, scratch: List[tuple]) -> None:
        """Egress sweep on the MSGB fast path: the engine hands back
        per-destination MSGB bodies built in C; multi-message groups
        stay pre-packed bodies (one frame each, zero Python re-packing)
        and singleton groups are stripped to plain MSG payloads —
        byte-identical to the uncoalesced arm.  The WHOLE sweep leaves
        as one :meth:`TcpTransport.send_wire` call (one wakeup byte,
        one loop-thread drain op for all destinations — not one post
        per group), and since send_wire preserves emission order,
        per-destination FIFO holds with no buffering dance."""
        scratch.clear()  # (dest, count, data) wire records, in order

        def emit(dest: int, nmsg: int, body: bytes) -> None:
            if nmsg <= 1:
                scratch.append((dest, 1, body[8:]))
            else:
                scratch.append((dest, nmsg, body))

        self.engine.drain_egress_msgb(emit, self.transport.max_frame_len - 1)
        if scratch:
            self.transport.send_wire(list(scratch))
            scratch.clear()

    def _guarded_sync(self) -> None:
        """Protocol-thread sync with the standard never-die guard: the
        exporter grew real work in round 12 (ring drain + struct
        decode + tracker math + prof reads) and an exporter bug must
        not take the protocol thread down mid-run — count it loudly
        like every other handler error (tests assert the counter stays
        zero)."""
        try:
            self._sync_engine_counters()
        except Exception:
            self.metrics.count("cluster.handler_errors")

    def _sync_engine_counters(self, force: bool = False) -> None:
        """Export engine-side observables into Metrics / the trace ring
        (protocol thread only while it runs: none of the engine's
        vectors are thread-safe).  Per call: fault deltas, committed-
        batch commit latencies, and the engine trace drain; the typed
        profiling counters (``engine.cyc.* / engine.msgs.*``) publish on
        a ~1 s throttle (32 ctypes reads — too heavy per sweep, cheap
        per second) and unconditionally with ``force`` (node stop)."""
        eng = self.engine
        if not eng.handle:
            return
        total = int(eng.lib.hbe_fault_count(eng.handle, self.id))
        if total > self._synced_faults:
            self.metrics.count(
                "cluster.protocol_faults", total - self._synced_faults
            )
            self._synced_faults = total
        outs = eng.outputs
        committed = len(outs) > self._seen_batches
        if committed:
            new = outs[self._seen_batches:]
            self._seen_batches = len(outs)
            self._last_commit_t = track_commits(
                self.epochs, new, self._last_commit_t
            )
        if self.trace is not None:
            events = eng.drain_trace()
            if events:
                self.trace.extend(events)
            self._engine_trace_dropped = eng.trace_dropped
        now = time.monotonic()
        # Also publish on commit sweeps (at most once per epoch): a
        # mid-run scrape right after an epoch lands must see its cycles
        # without waiting out the idle throttle.
        if force or committed or now >= self._next_prof_sync:
            self._next_prof_sync = now + 1.0
            # Deltas as COUNTERS (not gauges): counters sum across the
            # per-node Metrics in merged_metrics(), so the cluster dump
            # carries cluster-wide native cycle splits.
            for tname, st in eng.prof_stats().items():
                for field, kind in (("cycles", "cyc"), ("count", "msgs")):
                    cur = st[field]
                    key = (kind, tname)
                    delta = cur - self._prof_last.get(key, 0)
                    if delta > 0:
                        self.metrics.count(f"engine.{kind}.{tname}", delta)
                        self._prof_last[key] = cur
