"""Process-per-node cluster runtime (round 14): ``node_impl="native_proc"``.

:class:`ProcCluster` spawns one :mod:`~hbbft_tpu.transport.
cluster_worker` OS process per node and plays the parent side of the
spawn protocol:

1. spawn every worker with ``--port 0`` and no ``--peers`` (handshake
   mode) — each binds an ephemeral listener and prints ONE ready line
   with its actual port (and obs port);
2. collect the ready lines, assemble the full address map, and write it
   as one JSON line to every worker's stdin — the workers then dial
   each other directly; the parent is out of the data path;
3. drive: ``drive="presubmit"`` workers self-submit the config6
   deterministic workload and run to ``epochs`` committed batches
   (cross-arm ``batches_sha`` identity); ``drive="self"`` workers pace
   txns against their own commits and stream per-batch JSON lines up
   (the kill/restart drill watches those);
4. teardown: a ``{"stop": true}`` line (or just closing stdin) ends an
   open-ended worker; summaries carry ``batches_sha`` + merged
   counters, so the parent asserts cross-process byte-identity without
   scraping.

Key material never crosses the process boundary: every worker re-derives
its keys from ``(n, f, seed)`` (the ``deal_keys`` dealer ritual).

Failure drills: :meth:`kill` SIGKILLs a worker (a REAL process death —
kernel buffers, inbox, protocol state all gone); :meth:`restart`
respawns it on its old port (still handshake mode, so the parent can
re-send the address map and keep the stop channel).  Surviving workers'
resume layers retransmit across the death exactly as in thread mode —
tests/test_transport_proc.py pins losslessness from the batch streams.

The parent process stays out of the hot path by construction: after the
address map is delivered it only reads worker stdout lines and polls
process liveness, so N workers put ~3 threads each on the box (selector
loop, protocol/engine sweep, driver) instead of 2N threads in ONE
interpreter sharing a GIL.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.obs.export import merge_chrome_traces

#: Repo root (the directory holding the ``hbbft_tpu`` package) — pinned
#: onto the workers' PYTHONPATH so spawning works from any cwd AND the
#: axon TPU sitecustomize (CLAUDE.md) is displaced in one stroke.
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class _Worker:
    """Parent-side handle: process + stdout pump + parsed line state."""

    def __init__(self, node_id: int, proc: subprocess.Popen) -> None:
        self.id = node_id
        self.proc = proc
        self.ready: Optional[dict] = None
        self.summary: Optional[dict] = None
        self.batch_lines: List[dict] = []
        self.ready_evt = threading.Event()
        self.done_evt = threading.Event()
        self.lock = threading.Lock()
        self.thread = threading.Thread(
            target=self._pump, name=f"proc-worker-{node_id}", daemon=True
        )
        self.thread.start()

    def _pump(self) -> None:
        # One blocking reader per worker: stdout lines are the worker's
        # only upward channel (ready line, per-batch lines, summary).
        for line in self.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("ready"):
                self.ready = obj
                self.ready_evt.set()
            elif "done" in obj:
                self.summary = obj
                self.done_evt.set()
            elif "era" in obj:
                with self.lock:
                    self.batch_lines.append(obj)
        self.done_evt.set()  # EOF: the process is gone either way

    @property
    def port(self) -> Optional[int]:
        return self.ready["port"] if self.ready else None

    @property
    def obs_port(self) -> Optional[int]:
        return self.ready.get("obs_port") if self.ready else None

    def batches(self) -> List[dict]:
        with self.lock:
            return list(self.batch_lines)

    def batch_count(self) -> int:
        with self.lock:
            return len(self.batch_lines)

    def send(self, obj: dict) -> None:
        try:
            self.proc.stdin.write(json.dumps(obj) + "\n")
            self.proc.stdin.flush()
        except (OSError, ValueError):
            pass  # already dead / stdin closed


class ProcCluster:
    """N cluster-worker processes on localhost ephemeral ports."""

    def __init__(
        self,
        n: int,
        seed: int = 0,
        batch_size: int = 8,
        impl: str = "native",
        epochs: int = 5,
        drive: str = "presubmit",
        presubmit: Optional[int] = None,
        timeout_s: float = 300.0,
        num_faulty: Optional[int] = None,
        session_id: str = "tcp-cluster",
        cluster_id: str = "hbbft-tpu/cluster/v1",
        obs: bool = False,
        trace_dir: Optional[str] = None,
        metrics_in_summary: bool = False,
        ready_timeout_s: Optional[float] = None,
        stderr: str = "devnull",
        python: str = sys.executable,
        crypto: str = "inline",
        crypto_service: Any = None,
        service_kwargs: Optional[Dict[str, Any]] = None,
    ) -> None:
        if impl not in ("python", "native"):
            raise ValueError(f"impl must be python|native, got {impl!r}")
        if drive not in ("presubmit", "self"):
            raise ValueError(f"drive must be presubmit|self, got {drive!r}")
        # crypto (round 18): "service-proc" points every worker at ONE
        # crypto-plane service process (--crypto-service host:port), so
        # all N node processes' share checks batch through one backend
        # flush — the cross-node amortization plane ProcCluster could
        # not reach with the round-13 in-thread service.  crypto_service
        # may be a pre-started ServiceProcess or a (host, port) tuple;
        # None spawns an owned worker (or attaches to
        # HBBFT_TPU_CRYPTO_SERVICE).  Workers keep local fallbacks —
        # killing the service process never stalls the cluster.
        if crypto not in ("inline", "service-proc"):
            raise ValueError(
                f"crypto must be inline|service-proc, got {crypto!r}"
            )
        if crypto_service is not None and crypto != "service-proc":
            raise ValueError("crypto_service requires crypto='service-proc'")
        if service_kwargs and crypto != "service-proc":
            raise ValueError("service_kwargs requires crypto='service-proc'")
        self.crypto = crypto
        self.crypto_service = crypto_service
        self._service_kwargs = dict(service_kwargs or {})
        self._crypto_timeout_s = self._service_kwargs.pop("timeout_s", None)
        self._owns_service = False
        self._service_addr: Optional[Tuple[str, int]] = None
        self.n = n
        self.seed = seed
        self.batch_size = batch_size
        self.impl = impl
        self.epochs = epochs
        self.drive = drive
        self.presubmit = presubmit
        self.timeout_s = timeout_s
        self.num_faulty = num_faulty
        self.session_id = session_id
        self.cluster_id = cluster_id
        self.obs = obs
        self.trace_dir = trace_dir
        self.metrics_in_summary = metrics_in_summary
        # Spawn is CPU-serialized on a 1-core box (one interpreter boot
        # per worker): scale the ready deadline with the fleet size.
        self.ready_timeout_s = (
            ready_timeout_s if ready_timeout_s is not None else 30.0 + 2.0 * n
        )
        self._stderr_mode = stderr
        self.python = python
        self.workers: Dict[int, _Worker] = {}
        self.addr_map: Dict[int, Tuple[str, int]] = {}
        self._started = False

    # -- spawn protocol -------------------------------------------------
    def _spawn(self, node_id: int, port: int = 0) -> _Worker:
        cmd = [
            self.python,
            "-m",
            "hbbft_tpu.transport.cluster_worker",
            "--node-id", str(node_id),
            "--n", str(self.n),
            "--seed", str(self.seed),
            "--batch-size", str(self.batch_size),
            "--impl", self.impl,
            "--port", str(port),
            "--drive", self.drive,
            "--epochs", str(self.epochs),
            "--timeout-s", str(self.timeout_s),
            "--session-id", self.session_id,
            "--cluster-id", self.cluster_id,
        ]
        if self.num_faulty is not None:
            cmd += ["--num-faulty", str(self.num_faulty)]
        if self.presubmit is not None:
            cmd += ["--presubmit", str(self.presubmit)]
        if self.obs:
            cmd += ["--obs-port", "0"]
        if self.metrics_in_summary:
            cmd += ["--metrics"]
        if self.trace_dir:
            os.makedirs(self.trace_dir, exist_ok=True)
            cmd += [
                "--trace-file",
                os.path.join(self.trace_dir, f"node{node_id}.trace.json"),
            ]
        if self._service_addr is not None:
            cmd += [
                "--crypto-service",
                f"{self._service_addr[0]}:{self._service_addr[1]}",
            ]
            if self._crypto_timeout_s is not None:
                cmd += ["--crypto-timeout-s", str(self._crypto_timeout_s)]
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            cmd,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=(
                subprocess.DEVNULL
                if self._stderr_mode == "devnull"
                else None
            ),
            text=True,
            env=env,
            cwd=_REPO_ROOT,
        )
        return _Worker(node_id, proc)

    def _resolve_service(self) -> None:
        """Resolve the crypto-service address BEFORE any worker spawns
        (the address rides each worker's argv)."""
        if self.crypto != "service-proc" or self._service_addr is not None:
            return
        from hbbft_tpu.cryptoplane.proc_service import (
            ServiceProcess,
            service_addr_from_env,
        )

        if isinstance(self.crypto_service, tuple):
            self._service_addr = self.crypto_service
            self.crypto_service = None
            return
        if self.crypto_service is not None:
            self._service_addr = self.crypto_service.addr
            return
        env_addr = service_addr_from_env()
        if env_addr is not None:
            self._service_addr = env_addr
            return
        self.crypto_service = ServiceProcess(
            suite="scalar",
            backend=self._service_kwargs.pop("backend", "batched"),
            python=self.python,
            **self._service_kwargs,
        ).start()
        self._owns_service = True
        self._service_addr = self.crypto_service.addr

    def kill_service(self) -> None:
        """SIGKILL the crypto-service process mid-run (the fallback
        drill): workers' flushes fall back locally, commits continue."""
        if self.crypto_service is None:
            raise RuntimeError("no crypto-service process to kill")
        self.crypto_service.kill()

    def restart_service(self) -> None:
        """Respawn the killed service on its old port; workers'
        bounded-backoff re-dials re-attach automatically."""
        if self.crypto_service is None:
            raise RuntimeError("no crypto-service process to restart")
        self.crypto_service.restart()

    def start(self) -> "ProcCluster":
        assert not self._started
        self._resolve_service()
        for i in range(self.n):
            self.workers[i] = self._spawn(i)
        deadline = time.monotonic() + self.ready_timeout_s
        for i, w in self.workers.items():
            if not w.ready_evt.wait(max(0.0, deadline - time.monotonic())):
                rcs = {
                    j: ww.proc.poll() for j, ww in self.workers.items()
                }
                self.stop()
                raise TimeoutError(
                    f"worker {i} never printed its ready line "
                    f"(exit codes so far: {rcs})"
                )
        self.addr_map = {
            i: ("127.0.0.1", w.port) for i, w in self.workers.items()
        }
        peers_line = {
            "peers": {str(i): list(a) for i, a in self.addr_map.items()}
        }
        for w in self.workers.values():
            w.send(peers_line)
        self._started = True
        return self

    def restart(self, node_id: int) -> None:
        """Respawn a killed worker on its OLD port (peers' backoff dials
        find the reborn listener).  Still handshake mode: the fresh
        process prints a ready line, then receives the SAME address map
        — so the parent keeps its stop channel and the worker re-derives
        its keys; nothing is replayed from the dead process."""
        old = self.workers[node_id]
        port = self.addr_map[node_id][1]
        if old.proc.poll() is None:
            old.proc.kill()
            old.proc.wait(timeout=10)
        w = self._spawn(node_id, port=port)
        self.workers[node_id] = w
        if not w.ready_evt.wait(self.ready_timeout_s):
            raise TimeoutError(f"restarted worker {node_id} never got ready")
        w.send(
            {"peers": {str(i): list(a) for i, a in self.addr_map.items()}}
        )

    # -- failure drills -------------------------------------------------
    def kill(self, node_id: int) -> None:
        """A real process death: SIGKILL, no teardown, no goodbyes."""
        self.workers[node_id].proc.kill()

    # -- driving / observing --------------------------------------------
    def batch_count(self, node_id: int) -> int:
        return self.workers[node_id].batch_count()

    def batches(self, node_id: int) -> List[dict]:
        return self.workers[node_id].batches()

    def wait(
        self, pred, timeout_s: float, poll_s: float = 0.05
    ) -> bool:
        """LocalCluster's predicate wait, against the worker handles."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred(self):
                return True
            time.sleep(poll_s)
        return pred(self)

    def join(self, timeout_s: Optional[float] = None) -> Dict[int, dict]:
        """Wait for every worker's summary (or exit); returns summaries
        keyed by node id (a worker that died without one maps to None)."""
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.timeout_s + 60.0
        )
        for w in self.workers.values():
            w.done_evt.wait(max(0.0, deadline - time.monotonic()))
        return {i: w.summary for i, w in self.workers.items()}

    def summaries(self) -> Dict[int, Optional[dict]]:
        return {i: w.summary for i, w in self.workers.items()}

    def shas(self) -> Dict[int, Optional[str]]:
        return {
            i: (w.summary or {}).get("batches_sha")
            for i, w in self.workers.items()
        }

    def scrape(self, node_id: int, path: str = "/metrics") -> bytes:
        """GET an endpoint from one worker's obs server (requires
        ``obs=True``; the port came back in the ready line)."""
        import urllib.request

        port = self.workers[node_id].obs_port
        if not port:
            raise RuntimeError(f"worker {node_id} serves no obs port")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.read()

    def diag(self, stall_after_s: float = 5.0) -> Dict[str, Any]:
        """Parent-side cluster diagnosis: scrape every live worker's
        ``/diag`` (the analyzer over ITS rings, with the cluster's real
        consensus size) and fold them with
        :func:`~hbbft_tpu.obs.analyze.merge_diags` — the same verdict
        rule as a thread-mode cluster, so both runtimes name the same
        stuck (proposer, phase).  Dead workers are reported, not
        scraped (requires ``obs=True``)."""
        from hbbft_tpu.obs.analyze import merge_diags

        per_worker: Dict[int, Optional[dict]] = {}
        dead: List[int] = []
        for i, w in self.workers.items():
            if w.proc.poll() is not None or not w.obs_port:
                dead.append(i)
                continue
            try:
                per_worker[i] = json.loads(
                    self.scrape(i, f"/diag?stall_s={stall_after_s}")
                )
            except Exception:
                dead.append(i)  # mid-scrape death: same as dead
        merged = merge_diags(
            list(per_worker.values()), stall_after_s=stall_after_s
        )
        if dead:
            merged["dead_nodes"] = sorted(dead)
        return merged

    def merged_chrome_trace(self) -> Dict[str, Any]:
        """Merge the per-worker trace files (``trace_dir`` mode) into
        one Chrome trace on the shared wall clock."""
        if not self.trace_dir:
            raise RuntimeError("ProcCluster(trace_dir=...) not set")
        parts = []
        for i in range(self.n):
            path = os.path.join(self.trace_dir, f"node{i}.trace.json")
            try:
                with open(path) as fh:
                    parts.append(json.load(fh))
            except (OSError, ValueError):
                continue  # killed worker: no exit dump — merge the rest
        return merge_chrome_traces(parts)

    # -- teardown -------------------------------------------------------
    def stop(self, grace_s: float = 10.0) -> None:
        for w in self.workers.values():
            if w.proc.poll() is None:
                w.send({"stop": True})
            try:
                if w.proc.stdin:
                    w.proc.stdin.close()
            except OSError:
                pass
        deadline = time.monotonic() + grace_s
        for w in self.workers.values():
            while w.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if w.proc.poll() is None:
                w.proc.terminate()
        for w in self.workers.values():
            try:
                w.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                w.proc.kill()
                w.proc.wait(timeout=5)
            w.thread.join(timeout=5)
        # Service AFTER the workers (same ordering rule as
        # LocalCluster.stop): in-flight flushes drain or fall back
        # before the plane goes away.  Only a service THIS cluster
        # spawned — an externally-run one belongs to its owner.
        if self._owns_service and self.crypto_service is not None:
            self.crypto_service.stop()
        self._started = False

    def __enter__(self) -> "ProcCluster":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
