"""Byte-level fault injection at the transport send path.

:mod:`hbbft_tpu.net.adversary` owns *scheduling* adversaries inside the
in-process simulator; this module mirrors those semantics one layer
down, on the encoded frames a real node writes to real sockets:

* **drop** — the frame never leaves the sender;
* **duplicate** — the frame is queued twice;
* **delay / reorder** — the frame is held for a bounded time before
  queueing, so later frames overtake it (per-link frame order is the
  only order TCP gives us; delaying is how reordering manifests at this
  layer);
* **corrupt** — bit-flips in the encoded bytes.  Downstream, the frame
  decoder / serde boundary must reject these by dropping the connection
  — never by crashing (tests/test_transport.py);
* **partition / heal** — a schedule of time windows during which links
  between node groups drop every frame; outside the windows the links
  are clean.

Determinism: decisions are drawn from a per-*link* ``random.Random``
seeded by ``(seed, src, dst)``, so the k-th frame on a given link gets
the same verdict on every run regardless of thread interleaving across
links.  Partition windows are wall-clock offsets from ``start()`` —
coarse enough (seconds) that scheduling jitter does not move a frame
across a window edge in practice; tests drive the windows explicitly.

One injector instance is shared by all nodes of an in-process cluster
(:class:`~hbbft_tpu.transport.cluster.LocalCluster` passes it to every
transport); its per-link state needs no lock beyond the GIL because
each ``(src, dst)`` link is only ever touched by src's transport
thread.
"""

from __future__ import annotations

import math
import random
import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

from hbbft_tpu.utils.metrics import Metrics


@dataclass(frozen=True)
class PartitionSpec:
    """Nodes split into ``groups`` from ``start_s`` until ``heal_s``
    (offsets in seconds from injector start; ``heal_s=None`` = never
    heals).  Frames between different groups are dropped; frames inside
    one group pass.  A node in no group is unrestricted."""

    groups: Tuple[FrozenSet, ...]
    start_s: float = 0.0
    heal_s: Optional[float] = None

    def blocks(self, src, dst, t: float) -> bool:
        if t < self.start_s or (self.heal_s is not None and t >= self.heal_s):
            return False
        sg = dg = None
        for i, g in enumerate(self.groups):
            if src in g:
                sg = i
            if dst in g:
                dg = i
        return sg is not None and dg is not None and sg != dg


@dataclass
class LinkFaults:
    """Per-link fault probabilities (applied frame-by-frame, in order).

    Two delay models coexist:

    * ``delay_p``/``delay_s`` — the ROUND-8 *reorder fault*: occasional
      frames are held while later ones overtake them (per-frame delay
      with no ordering constraint — how reordering manifests on this
      layer).
    * ``latency_s``/``jitter_s``/``jitter_dist`` — the ROUND-10 *WAN
      stream shape*: EVERY frame pays a base one-way latency plus a
      seeded jitter draw, and release times are clamped monotone per
      link, because a talking pair shares one TCP stream — a real WAN
      delays the stream, it does not reorder inside it.  Jitter
      distributions (all driven by one uniform draw via inverse CDF, so
      the per-link verdict stream stays a pure function of the frame
      index): ``"uniform"`` (U(0,1)·jitter_s), ``"exp"`` (mean
      jitter_s — heavy-ish tail, the default), ``"lognormal"``
      (median jitter_s, shape 0.6 — the long-tail shape WAN RTT
      studies report).

    Both models compose (WAN shape + occasional reorder fault); loss on
    a WAN link is the existing ``drop_p``.
    """

    drop_p: float = 0.0
    dup_p: float = 0.0
    delay_p: float = 0.0
    delay_s: Tuple[float, float] = (0.01, 0.05)  # uniform range when delayed
    corrupt_p: float = 0.0
    max_flips: int = 3  # bit flips per corrupted frame (>= 1)
    # WAN stream shape (applies to every frame when nonzero)
    latency_s: float = 0.0
    jitter_s: float = 0.0
    jitter_dist: str = "exp"  # "exp" | "uniform" | "lognormal"

    def wan_delay(self, u: float) -> float:
        """Map one uniform draw to this link's per-frame WAN delay."""
        if self.latency_s <= 0.0 and self.jitter_s <= 0.0:
            return 0.0
        j = 0.0
        if self.jitter_s > 0.0:
            if self.jitter_dist == "uniform":
                j = self.jitter_s * u
            elif self.jitter_dist == "lognormal":
                z = statistics.NormalDist().inv_cdf(
                    min(max(u, 1e-12), 1.0 - 1e-12)
                )
                j = self.jitter_s * math.exp(0.6 * z)
            else:  # "exp" (default): inverse CDF of Exp(1/jitter_s)
                j = -self.jitter_s * math.log(max(1.0 - u, 1e-300))
        return self.latency_s + j


def wan_profile(name: str, scale: float = 1.0) -> Optional[LinkFaults]:
    """Named WAN link shapes for benchmarks/tests (``config7_traffic``).

    ``"clean"`` → None (no injector needed); ``"wan"`` → ~30 ms base
    one-way latency + exponential jitter (mean 10 ms), lossless —
    the continental-WAN shape of the original HoneyBadgerBFT
    evaluation, scaled down so localhost epochs still close inside
    test budgets; ``"wan-lossy"`` → the same shape plus 0.5% frame
    loss and 0.2% duplication.  ``scale`` multiplies the time
    constants (1.0 = the named shape).  Loss is real loss — dropped
    frames are never retransmitted unless the connection itself
    cycles (docs/TRANSPORT.md "loss model") — so lossy profiles on
    EVERY link erode liveness; the config7 "faulty" arm instead puts
    loss on one node's links, inside the f-tolerance envelope.
    """
    if name == "clean":
        return None
    if name == "wan":
        return LinkFaults(
            latency_s=0.030 * scale, jitter_s=0.010 * scale, jitter_dist="exp"
        )
    if name == "wan-lossy":
        return LinkFaults(
            latency_s=0.030 * scale,
            jitter_s=0.010 * scale,
            jitter_dist="exp",
            drop_p=0.005,
            dup_p=0.002,
        )
    raise ValueError(f"unknown WAN profile {name!r} (clean|wan|wan-lossy)")


@dataclass
class FaultStats:
    """Cross-link totals.  Unlike the per-link rngs (single-writer by
    construction), these are incremented from every node's transport
    thread — the lock keeps the read-modify-writes from losing counts."""

    dropped: int = 0
    duplicated: int = 0
    delayed: int = 0
    corrupted: int = 0
    partitioned: int = 0
    shaped: int = 0  # frames that paid a WAN latency/jitter delay
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    NAMES = ("dropped", "duplicated", "delayed", "corrupted", "partitioned",
             "shaped")

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def export_metrics(self, m: Metrics, prefix: str = "faults") -> None:
        """Publish the totals as gauges (``faults.dropped`` etc.) so
        injected faults land in the same Prometheus dump as the
        transport/cluster counters (ISSUE 6 satellite).  Gauges, not
        counters: these are cross-link running totals owned here, and
        re-exporting monotone totals through ``Metrics.count`` would
        double-add on every export."""
        with self._lock:
            vals = [(name, getattr(self, name)) for name in self.NAMES]
        for name, v in vals:
            m.gauge(f"{prefix}.{name}", v)


class FaultInjector:
    """Deterministic-by-seed frame mangler for the TCP transport.

    ``on_send(src, dst, data) -> [(extra_delay_s, bytes), ...]`` is the
    whole interface the transport uses: an empty list means the frame
    was dropped; multiple entries mean duplication; a nonzero delay
    means the transport holds that copy on its timer heap before
    queueing it.  Without an injector the transport sends
    ``[(0.0, data)]`` — the injector is pure policy, never plumbing.
    """

    def __init__(
        self,
        seed: int = 0,
        default: Optional[LinkFaults] = None,
        links: Optional[Dict[Tuple, LinkFaults]] = None,
        partitions: Optional[List[PartitionSpec]] = None,
    ) -> None:
        self.seed = seed
        self.default = default or LinkFaults()
        self.links = dict(links or {})
        self.partitions = list(partitions or [])
        self.stats = FaultStats()
        self._rngs: Dict[Tuple, random.Random] = {}
        # WAN FIFO state: last scheduled release time per link (only
        # touched by src's transport thread, like _rngs)
        self._wan_last: Dict[Tuple, float] = {}
        self._t0: Optional[float] = None

    # -- lifecycle -----------------------------------------------------
    def start(self, now: Optional[float] = None) -> None:
        """Anchor partition-window offsets; called by the cluster when
        the transports come up (idempotent: first call wins, so every
        node shares one clock origin)."""
        if self._t0 is None:
            self._t0 = time.monotonic() if now is None else now

    def elapsed(self) -> float:
        if self._t0 is None:
            return 0.0
        return time.monotonic() - self._t0

    def export_metrics(self, m: Metrics, prefix: str = "faults") -> None:
        """Mirror :meth:`FaultStats.export_metrics` at the injector
        level (what :meth:`LocalCluster.merged_metrics` calls)."""
        self.stats.export_metrics(m, prefix)

    # -- dynamic schedule edits (tests drive heal explicitly) ----------
    def add_partition(self, spec: PartitionSpec) -> None:
        self.partitions.append(spec)

    def heal_all(self) -> None:
        """End every ACTIVE partition now (explicit heal, no clock).
        Windows scheduled to start in the future are left untouched."""
        t = self.elapsed()
        self.partitions = [
            p
            if p.start_s > t
            else PartitionSpec(
                p.groups,
                p.start_s,
                min(p.heal_s, t) if p.heal_s is not None else t,
            )
            for p in self.partitions
        ]

    # -- the send hook -------------------------------------------------
    def _rng(self, src, dst) -> random.Random:
        key = (src, dst)
        rng = self._rngs.get(key)
        if rng is None:
            rng = self._rngs[key] = random.Random(f"{self.seed}|{src}|{dst}")
        return rng

    def on_send(self, src, dst, data: bytes) -> List[Tuple[float, bytes]]:
        t = self.elapsed()
        for p in self.partitions:
            if p.blocks(src, dst, t):
                self.stats.bump('partitioned')
                return []
        lf = self.links.get((src, dst), self.default)
        rng = self._rng(src, dst)
        # Draw every decision unconditionally so the per-link sequence
        # of verdicts is a pure function of (seed, src, dst, frame
        # index) — independent of which faults are enabled elsewhere.
        r_drop = rng.random()
        r_dup = rng.random()
        r_delay = rng.random()
        u_delay = rng.random()
        r_corrupt = rng.random()
        u_jitter = rng.random()  # round 10: WAN jitter draw
        if lf.drop_p and r_drop < lf.drop_p:
            self.stats.bump('dropped')
            return []
        if lf.corrupt_p and r_corrupt < lf.corrupt_p:
            # flip positions come from a rng DERIVED from this frame's
            # unconditional corrupt draw, not from the verdict stream —
            # otherwise enabling corruption would shift every later
            # frame's drop/dup/delay verdicts on the link
            data = self._corrupt(data, random.Random(r_corrupt), lf.max_flips)
            self.stats.bump('corrupted')
        delay = 0.0
        if lf.delay_p and r_delay < lf.delay_p:
            lo, hi = lf.delay_s
            delay = lo + (hi - lo) * u_delay
            self.stats.bump('delayed')
        wan = lf.wan_delay(u_jitter)
        if wan > 0.0:
            # WAN stream shape: base+jitter on every frame, release
            # times clamped monotone per link — a talking pair shares
            # one TCP stream, so the WAN delays the stream without
            # reordering inside it.  The reorder fault (delay_p above)
            # is added AFTER the clamp: a delay-faulted frame is held
            # past its WAN slot and CAN still be overtaken by later
            # frames, so composing the shape with delay_p keeps real
            # reorder coverage (feeding the reorder delay into the
            # clamp would silently FIFO it away).
            release = t + wan
            last = self._wan_last.get((src, dst), 0.0)
            if release < last:
                release = last
            self._wan_last[(src, dst)] = release
            delay += release - t
            self.stats.bump('shaped')
        out = [(delay, data)]
        if lf.dup_p and r_dup < lf.dup_p:
            self.stats.bump('duplicated')
            out.append((delay, data))
        return out

    @staticmethod
    def _corrupt(data: bytes, rng: random.Random, max_flips: int) -> bytes:
        buf = bytearray(data)
        # max(1, ...) twice: a corrupted frame always flips >= 1 bit,
        # and a user-supplied max_flips of 0 must not raise from inside
        # the sender's protocol thread
        for _ in range(rng.randrange(max(1, max_flips)) + 1):
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        return bytes(buf)
