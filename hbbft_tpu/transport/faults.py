"""Byte-level fault injection at the transport send path.

:mod:`hbbft_tpu.net.adversary` owns *scheduling* adversaries inside the
in-process simulator; this module mirrors those semantics one layer
down, on the encoded frames a real node writes to real sockets:

* **drop** — the frame never leaves the sender;
* **duplicate** — the frame is queued twice;
* **delay / reorder** — the frame is held for a bounded time before
  queueing, so later frames overtake it (per-link frame order is the
  only order TCP gives us; delaying is how reordering manifests at this
  layer);
* **corrupt** — bit-flips in the encoded bytes.  Downstream, the frame
  decoder / serde boundary must reject these by dropping the connection
  — never by crashing (tests/test_transport.py);
* **partition / heal** — a schedule of time windows during which links
  between node groups drop every frame; outside the windows the links
  are clean.

Determinism: decisions are drawn from a per-*link* ``random.Random``
seeded by ``(seed, src, dst)``, so the k-th frame on a given link gets
the same verdict on every run regardless of thread interleaving across
links.  Partition windows are wall-clock offsets from ``start()`` —
coarse enough (seconds) that scheduling jitter does not move a frame
across a window edge in practice; tests drive the windows explicitly.

One injector instance is shared by all nodes of an in-process cluster
(:class:`~hbbft_tpu.transport.cluster.LocalCluster` passes it to every
transport); its per-link state needs no lock beyond the GIL because
each ``(src, dst)`` link is only ever touched by src's transport
thread.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple


@dataclass(frozen=True)
class PartitionSpec:
    """Nodes split into ``groups`` from ``start_s`` until ``heal_s``
    (offsets in seconds from injector start; ``heal_s=None`` = never
    heals).  Frames between different groups are dropped; frames inside
    one group pass.  A node in no group is unrestricted."""

    groups: Tuple[FrozenSet, ...]
    start_s: float = 0.0
    heal_s: Optional[float] = None

    def blocks(self, src, dst, t: float) -> bool:
        if t < self.start_s or (self.heal_s is not None and t >= self.heal_s):
            return False
        sg = dg = None
        for i, g in enumerate(self.groups):
            if src in g:
                sg = i
            if dst in g:
                dg = i
        return sg is not None and dg is not None and sg != dg


@dataclass
class LinkFaults:
    """Per-link fault probabilities (applied frame-by-frame, in order)."""

    drop_p: float = 0.0
    dup_p: float = 0.0
    delay_p: float = 0.0
    delay_s: Tuple[float, float] = (0.01, 0.05)  # uniform range when delayed
    corrupt_p: float = 0.0
    max_flips: int = 3  # bit flips per corrupted frame (>= 1)


@dataclass
class FaultStats:
    """Cross-link totals.  Unlike the per-link rngs (single-writer by
    construction), these are incremented from every node's transport
    thread — the lock keeps the read-modify-writes from losing counts."""

    dropped: int = 0
    duplicated: int = 0
    delayed: int = 0
    corrupted: int = 0
    partitioned: int = 0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)


class FaultInjector:
    """Deterministic-by-seed frame mangler for the TCP transport.

    ``on_send(src, dst, data) -> [(extra_delay_s, bytes), ...]`` is the
    whole interface the transport uses: an empty list means the frame
    was dropped; multiple entries mean duplication; a nonzero delay
    means the transport holds that copy on its timer heap before
    queueing it.  Without an injector the transport sends
    ``[(0.0, data)]`` — the injector is pure policy, never plumbing.
    """

    def __init__(
        self,
        seed: int = 0,
        default: Optional[LinkFaults] = None,
        links: Optional[Dict[Tuple, LinkFaults]] = None,
        partitions: Optional[List[PartitionSpec]] = None,
    ) -> None:
        self.seed = seed
        self.default = default or LinkFaults()
        self.links = dict(links or {})
        self.partitions = list(partitions or [])
        self.stats = FaultStats()
        self._rngs: Dict[Tuple, random.Random] = {}
        self._t0: Optional[float] = None

    # -- lifecycle -----------------------------------------------------
    def start(self, now: Optional[float] = None) -> None:
        """Anchor partition-window offsets; called by the cluster when
        the transports come up (idempotent: first call wins, so every
        node shares one clock origin)."""
        if self._t0 is None:
            self._t0 = time.monotonic() if now is None else now

    def elapsed(self) -> float:
        if self._t0 is None:
            return 0.0
        return time.monotonic() - self._t0

    # -- dynamic schedule edits (tests drive heal explicitly) ----------
    def add_partition(self, spec: PartitionSpec) -> None:
        self.partitions.append(spec)

    def heal_all(self) -> None:
        """End every ACTIVE partition now (explicit heal, no clock).
        Windows scheduled to start in the future are left untouched."""
        t = self.elapsed()
        self.partitions = [
            p
            if p.start_s > t
            else PartitionSpec(
                p.groups,
                p.start_s,
                min(p.heal_s, t) if p.heal_s is not None else t,
            )
            for p in self.partitions
        ]

    # -- the send hook -------------------------------------------------
    def _rng(self, src, dst) -> random.Random:
        key = (src, dst)
        rng = self._rngs.get(key)
        if rng is None:
            rng = self._rngs[key] = random.Random(f"{self.seed}|{src}|{dst}")
        return rng

    def on_send(self, src, dst, data: bytes) -> List[Tuple[float, bytes]]:
        t = self.elapsed()
        for p in self.partitions:
            if p.blocks(src, dst, t):
                self.stats.bump('partitioned')
                return []
        lf = self.links.get((src, dst), self.default)
        rng = self._rng(src, dst)
        # Draw every decision unconditionally so the per-link sequence
        # of verdicts is a pure function of (seed, src, dst, frame
        # index) — independent of which faults are enabled elsewhere.
        r_drop = rng.random()
        r_dup = rng.random()
        r_delay = rng.random()
        u_delay = rng.random()
        r_corrupt = rng.random()
        if lf.drop_p and r_drop < lf.drop_p:
            self.stats.bump('dropped')
            return []
        if lf.corrupt_p and r_corrupt < lf.corrupt_p:
            # flip positions come from a rng DERIVED from this frame's
            # unconditional corrupt draw, not from the verdict stream —
            # otherwise enabling corruption would shift every later
            # frame's drop/dup/delay verdicts on the link
            data = self._corrupt(data, random.Random(r_corrupt), lf.max_flips)
            self.stats.bump('corrupted')
        delay = 0.0
        if lf.delay_p and r_delay < lf.delay_p:
            lo, hi = lf.delay_s
            delay = lo + (hi - lo) * u_delay
            self.stats.bump('delayed')
        out = [(delay, data)]
        if lf.dup_p and r_dup < lf.dup_p:
            self.stats.bump('duplicated')
            out.append((delay, data))
        return out

    @staticmethod
    def _corrupt(data: bytes, rng: random.Random, max_flips: int) -> bytes:
        buf = bytearray(data)
        # max(1, ...) twice: a corrupted frame always flips >= 1 bit,
        # and a user-supplied max_flips of 0 must not raise from inside
        # the sender's protocol thread
        for _ in range(rng.randrange(max(1, max_flips)) + 1):
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        return bytes(buf)
