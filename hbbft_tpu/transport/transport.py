"""Non-blocking TCP transport: one node's socket plane.

Design (ISSUE 4 tentpole; patterned after thetacrypt's networked
threshold-service deployments, PAPERS.md):

* One ``selectors``-based event loop per node, running on its own
  thread.  All socket state is owned by that thread; other threads talk
  to it through a control deque + self-pipe wakeup (``send``,
  ``set_offline``, ``stop``).
* **Connection topology:** each node *dials* every peer it sends to and
  *accepts* from every peer that sends to it — two unidirectional TCP
  connections per talking pair.  The dialer writes frames; the acceptor
  only reads.  This removes the simultaneous-connect dedupe dance
  entirely (both sides dialing each other is the normal state, not a
  conflict).
* **Handshake:** the dialer's first frame is ``HELLO(version,
  cluster_id, node_id)``; the acceptor learns the sender's identity
  from it and drops version/cluster mismatches.  Protocol frames on a
  connection before its HELLO are a protocol violation (dropped
  connection).
* **Outbound queues + backpressure:** per-peer FIFO of encoded frames,
  capped in frames and bytes (``max_queue_frames`` /
  ``max_queue_bytes``).  Overflow drops the NEWEST frame and counts it
  (``queue_overflow``) — HoneyBadger tolerates message loss to f nodes,
  and the sender queue re-gates per-epoch traffic, so bounded loss
  under backpressure is protocol-safe; unbounded buffering toward a
  dead peer is not memory-safe.  Queues survive disconnects: frames not
  yet written when a connection dies are re-sent on the next connect
  (bytes already in the kernel buffer of a dead peer are gone — that is
  the loss window a mid-epoch crash produces).
* **Vectored egress (round 14):** when the platform has
  ``socket.sendmsg`` (and ``HBBFT_TPU_SENDMSG`` != 0), outbound bursts
  leave as ONE gather syscall over the queue's own frame bytes instead
  of a per-frame copy into ``sendbuf``; partial sends fall back to the
  buffered path with identical ``pending_write``/``write_prog``/ACK
  accounting (see :meth:`TcpTransport._flush_outbound_vectored`).
* **Reconnect:** failed dials retry with exponential backoff + jitter
  (``backoff_base_s * 2^attempts`` capped at ``backoff_cap_s``, times
  ``1 + jitter * u``), seeded per node for reproducible tests.
* **Fault injection:** an optional
  :class:`~hbbft_tpu.transport.faults.FaultInjector` sits exactly at
  the send boundary (encoded frame -> list of delayed/mangled copies).
* **Observability:** per-peer :class:`PeerStats` (bytes/frames in+out,
  queue depth, drops, reconnects, frame errors) exported into
  :class:`~hbbft_tpu.utils.metrics.Metrics` as counters + gauges.
* **Misbehavior accounting (round 11):** frame-level violations on an
  identified inbound connection charge the announced peer a strike;
  every ``ban_threshold`` strikes earn a deterministic escalating
  reconnect ban (:func:`ban_duration`), refusing the peer's HELLOs
  until it lapses — a Byzantine peer can no longer corrupt one frame
  per reconnect forever at zero cost.  Exported as ``peer.*`` gauges.

Read-path safety: every ``recv`` is bounded by ``RECV_CHUNK`` and every
received byte goes through a :class:`FrameDecoder` capped at
``max_frame_len`` *before* any parsing (lint rule HBT006 machine-checks
both).  A frame error never crashes the node: the connection is
dropped, the fault is counted, and reconnect recovers.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import os
import random
import selectors
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.transport.framing import (
    KIND_ACK,
    KIND_HELLO,
    KIND_MSG,
    KIND_MSGB,
    MAX_FRAME_LEN,
    RECV_CHUNK,
    FrameDecoder,
    FrameError,
    decode_ack,
    decode_hello,
    decode_msgb,
    encode_ack,
    encode_frame,
    encode_hello,
    frame_message_count,
    msgb_body,
    validate_msgb,
)
from hbbft_tpu.utils.metrics import Metrics


@dataclass
class PeerStats:
    """One peer's transport counters (single-writer: the loop thread)."""

    bytes_out: int = 0
    frames_out: int = 0
    bytes_in: int = 0
    frames_in: int = 0
    # Coalescing efficiency (round 20): protocol messages carried by
    # the frames above — an MSG frame counts 1, an MSGB frame counts
    # its batch size.  msgs/frames is the msgs-per-frame ratio the
    # config6/config7 JSON lines surface, so A/B arms self-describe
    # how much the wire actually coalesced.
    msgs_out: int = 0
    msgs_in: int = 0
    queue_frames: int = 0
    queue_bytes: int = 0
    queue_overflow: int = 0
    dials: int = 0
    connects: int = 0
    reconnects: int = 0
    accepts: int = 0
    frame_errors: int = 0
    # Byzantine accounting (round 11): protocol violations on an
    # IDENTIFIED inbound connection (frame errors after a valid HELLO)
    # are misbehavior strikes; every ``ban_threshold`` strikes earn an
    # escalating reconnect ban, and HELLOs refused during a ban count
    # as ban_rejects.  Without the ban, a peer could corrupt one frame
    # per reconnect forever at zero cost (the corrupt-frame/reconnect
    # loop): each violation costs only the attacker's own connection,
    # which backoff restores in milliseconds.
    misbehavior: int = 0
    bans: int = 0
    ban_rejects: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


def ban_duration(offense: int, base_s: float, cap_s: float) -> float:
    """Length of a peer's ``offense``-th reconnect ban (0-based): pure
    exponential escalation with NO jitter, so the schedule is a
    deterministic function of the strike count alone — the chaos tier
    pins this (seed-deterministic ban escalation).  The exponent is
    clamped: 2.0**offense overflows float at offense >= 1024, and a
    sustained corrupt-frame loop reaches that many bans in under an
    hour — an OverflowError here would tear down the VICTIM's whole
    transport loop (the attack the ban exists to price)."""
    return min(cap_s, base_s * (2.0 ** min(offense, 64)))


class _BanReject(FrameError):
    """HELLO refused because the announced peer is under an active
    reconnect ban.  A distinct type so the read path's FrameError
    handler can close the connection WITHOUT counting a frame error:
    ban rejects are the defense working, not channel corruption, and
    conflating them would inflate ``transport.frame_errors`` by one
    per refused redial for the whole ban window."""


class _BanState:
    """Per-peer misbehavior ledger (loop thread only)."""

    __slots__ = ("strikes", "bans", "until")

    def __init__(self) -> None:
        self.strikes = 0   # violations since the last ban
        self.bans = 0      # escalation level (total bans issued)
        self.until = 0.0   # monotonic deadline of the active ban


#: Outbound write-coalescing bound: frames are packed into the write
#: buffer up to this size before ONE send() syscall covers them all.
#: With the native-engine node the per-frame syscall (plus its selector
#: churn) was the measured socket-plane bound once decode moved to C;
#: matching RECV_CHUNK keeps one write ~= one peer read burst.
SEND_COALESCE = RECV_CHUNK

#: ACK coalescing: a cumulative ACK is written immediately once this
#: many frames are unacknowledged, else a short timer batches it.  One
#: ACK per read burst was the next measured socket-plane bound after
#: write coalescing (2 syscalls + a wakeup on EACH side per burst);
#: cumulative counts make delay harmless — a reconnect's initial ACK is
#: always the receiver's authoritative count, so resume never double-
#: delivers, and the sender just retains the unacked tail a little
#: longer (bounded by the queue caps, which inflight counts toward).
ACK_EVERY = 64
ACK_DELAY_S = 0.02

#: Vectored egress (round 14): one ``sendmsg()`` over the pending frame
#: list replaces the per-frame copy into ``sendbuf`` — MSG bursts leave
#: as a gather array of the frame bytes the queue already holds.  The
#: buffered ``sendbuf`` path remains the fallback for partial sends
#: (the unsent tail is retained there, byte-identical accounting) and
#: for platforms without ``socket.sendmsg``.  ``HBBFT_TPU_SENDMSG=0``
#: forces the buffered path on the same build (the A/B switch).
SENDMSG_AVAILABLE = hasattr(socket.socket, "sendmsg")

#: Gather-array length cap per sendmsg call.  Linux UIO_MAXIOV is 1024;
#: half that leaves headroom for any platform with a smaller limit
#: while still covering a whole SEND_COALESCE window of small frames.
SENDMSG_MAX_BUFS = 512


def _sendmsg_default() -> bool:
    return SENDMSG_AVAILABLE and os.environ.get("HBBFT_TPU_SENDMSG", "1") != "0"


def _coalesce_default() -> bool:
    """Message coalescing (round 20): ``HBBFT_TPU_COALESCE=0`` restores
    per-message MSG frames on the same build — the A/B arm.  The knob
    gates EMISSION only; every decoder keeps accepting MSGB, so mixed
    clusters interoperate in either setting."""
    return os.environ.get("HBBFT_TPU_COALESCE", "1") != "0"


class _Outbound:
    """Dialer-side state toward one peer.

    The resume layer: ``queue`` holds frames not yet written, as
    ``(orig, wire)`` pairs (``wire`` is a fault-injector-mangled copy to
    put on the wire ONCE; retransmissions always send ``orig`` — a
    corrupted transmission models a transient channel fault, not a
    poisoned message).  ``inflight`` holds originals fully written but
    not yet covered by the peer's cumulative ACK; after a reconnect the
    un-acked tail is retransmitted ahead of new traffic, so a surviving
    peer misses nothing across a disconnect.  ``await_ack`` gates MSG
    writes on a fresh connection until the acceptor's initial ACK tells
    us where to resume.

    Writes are coalesced: ``pending_write`` tracks the frames currently
    inside ``sendbuf`` as ``(wire_len, orig)`` in order, and
    ``write_prog`` counts bytes of the FIRST of them already accepted by
    the kernel — fully-covered frames graduate to ``inflight``; on a
    drop, every not-fully-written frame's original re-queues at the head
    (the peer never consumed them).
    """

    __slots__ = (
        "addr", "sock", "state", "queue", "queue_bytes", "sendbuf",
        "attempts", "next_dial", "inflight", "inflight_bytes", "acked",
        "await_ack", "pending_write", "pending_write_bytes", "write_prog",
        "decoder", "want_w",
    )

    def __init__(self, addr: Tuple[str, int]) -> None:
        self.addr = addr
        self.sock: Optional[socket.socket] = None
        self.state = "idle"  # idle | connecting | connected
        self.queue: collections.deque = collections.deque()  # (orig, wire)
        self.queue_bytes = 0
        self.sendbuf = bytearray()
        self.attempts = 0
        self.next_dial = 0.0  # monotonic deadline for the next dial try
        self.inflight: collections.deque = collections.deque()  # orig bytes
        self.inflight_bytes = 0
        self.acked = 0
        self.await_ack = False
        # frames currently in sendbuf: (wire_len, orig), write progress
        self.pending_write: collections.deque = collections.deque()
        self.pending_write_bytes = 0  # sum of ORIG lens (cap accounting)
        self.write_prog = 0
        self.decoder: Optional[FrameDecoder] = None  # ACK stream parser
        self.want_w = False  # selector write-interest memo (syscall dedup)

    def pending_frames(self) -> int:
        return len(self.queue) + len(self.inflight) + len(self.pending_write)

    def pending_bytes(self) -> int:
        return self.queue_bytes + self.inflight_bytes + self.pending_write_bytes

    def has_pending(self) -> bool:
        return self.pending_frames() > 0


class _Inbound:
    """Acceptor-side state for one accepted connection."""

    __slots__ = ("sock", "decoder", "peer_id", "sendbuf", "last_ack",
                 "ack_timer", "want_w")

    def __init__(self, sock: socket.socket, max_frame_len: int) -> None:
        self.sock = sock
        self.decoder = FrameDecoder(max_frame_len)
        self.peer_id: Any = None
        self.sendbuf = bytearray()  # pending ACK frames
        self.last_ack = 0       # cumulative count last written as an ACK
        self.ack_timer = False  # a coalescing ack flush is scheduled
        self.want_w = False     # selector write-interest memo


class _ConsumerOverload(Exception):
    """on_message refused a frame (consumer queue full): drop the
    connection WITHOUT acking, so the dialer resumes from the acked
    prefix — the cumulative count means "first n frames consumed" and
    skipping one frame mid-stream would misalign it forever."""


class TcpTransport:
    def __init__(
        self,
        node_id: Any,
        cluster_id: bytes,
        peers: Optional[Dict[Any, Tuple[str, int]]] = None,
        on_message: Optional[Callable[[Any, bytes], None]] = None,
        on_batch: Optional[Callable[[Any, List[bytes]], int]] = None,
        on_wire_batch: Optional[
            Callable[[Any, List[Tuple[int, bytes]]], int]
        ] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_frame_len: int = MAX_FRAME_LEN,
        max_queue_frames: int = 20_000,
        max_queue_bytes: int = 64 << 20,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        backoff_jitter: float = 0.3,
        metrics: Optional[Metrics] = None,
        injector: Any = None,
        seed: int = 0,
        accept_unknown_peers: bool = False,
        ban_threshold: int = 3,
        ban_base_s: float = 0.25,
        ban_cap_s: float = 2.0,
        vectored: Optional[bool] = None,
        coalesce: Optional[bool] = None,
    ) -> None:
        self.node_id = node_id
        self.cluster_id = cluster_id
        self.on_message = on_message
        # Burst consumer (round 9): when set, all MSG frames of one read
        # burst are handed over in a single call — ``on_batch(peer,
        # payloads) -> frames consumed`` — instead of one ``on_message``
        # per frame.  A return short of the full burst means the
        # consumer stopped at a prefix (inbox full): the connection is
        # dropped WITHOUT acking the remainder, exactly the per-frame
        # path's _ConsumerOverload semantics, and the peer's resume
        # layer retransmits.  This is what lets a native-engine node
        # move a whole RECV_CHUNK of frames per Python call.
        self.on_batch = on_batch
        # Wire-burst consumer (round 20): like ``on_batch`` but frames
        # arrive in WIRE form — ``on_wire_batch(peer, records) ->
        # frames consumed`` with each record ``(nmsg, data)``: nmsg ==
        # 0 is a plain MSG payload, nmsg >= 1 a validated raw MSGB body
        # (grammar-checked here, NOT sliced — the native engine walks
        # the body in C, which is the whole point).  Precedence:
        # on_wire_batch > on_batch > on_message.
        self.on_wire_batch = on_wire_batch
        self.max_frame_len = max_frame_len
        self.max_queue_frames = max_queue_frames
        self.max_queue_bytes = max_queue_bytes
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.backoff_jitter = backoff_jitter
        self.metrics = metrics if metrics is not None else Metrics()
        self.injector = injector
        # Per-peer acceptor state (PeerStats, _rx_counts) is keyed by the
        # HELLO-announced id; without this gate one unauthenticated local
        # client could grow both maps without bound by announcing fresh
        # ids.  True is for topologies where inbound peers are not known
        # up front (joining nodes); the in-process clusters never need it.
        self.accept_unknown_peers = accept_unknown_peers
        # Misbehavior/ban policy (round 11).  ban_threshold <= 0
        # disables banning (strikes are still counted).  The ban caps
        # at ban_cap_s per offense, so an honest peer on a corrupting
        # CHANNEL (injector corrupt_p) is delayed, never locked out —
        # its dialer retries past the ban and the resume layer replays
        # the clean originals (losslessness is test-pinned).
        self.ban_threshold = ban_threshold
        self.ban_base_s = ban_base_s
        self.ban_cap_s = ban_cap_s
        # Vectored egress (round 14): None = auto (on when the platform
        # has sendmsg and HBBFT_TPU_SENDMSG != 0).  Explicit True on a
        # sendmsg-less platform is downgraded, not an error — the two
        # paths are output-identical by construction.
        if vectored is None:
            vectored = _sendmsg_default()
        self.vectored = bool(vectored) and SENDMSG_AVAILABLE
        # Message coalescing (round 20): None = auto (HBBFT_TPU_COALESCE
        # != 0).  Gates egress packing only — ingress accepts MSGB
        # unconditionally (accept-both interop).
        if coalesce is None:
            coalesce = _coalesce_default()
        self.coalesce = bool(coalesce)
        self._bans: Dict[Any, _BanState] = {}
        # Flight recorder (round 12): an optional TraceBuffer the owner
        # (LocalCluster) installs; connect/disconnect/ban milestones land
        # on the same per-node timeline as the protocol events.
        self.tracer: Any = None
        self._rng = random.Random(f"transport|{seed}|{node_id}")
        self._host = host
        self._sel = selectors.DefaultSelector()
        self._listener: Optional[socket.socket] = None
        self._port = 0
        self._bind(host, port)
        self._out: Dict[Any, _Outbound] = {}
        for pid, addr in (peers or {}).items():
            self._out[pid] = _Outbound(tuple(addr))
        # accepted-connection cap: every peer may hold a live connection
        # plus a few churning replacements; beyond that is abuse
        self.max_inbound = 4 * max(1, len(self._out)) + 8
        self.peer_stats: Dict[Any, PeerStats] = collections.defaultdict(PeerStats)
        self._inbound: List[_Inbound] = []
        # Cumulative MSG frames consumed per sending peer, across
        # reconnects — the number the resume layer ACKs back.  Dies with
        # the process (a restarted node ACKs 0; dialers adopt the reset).
        self._rx_counts: Dict[Any, int] = collections.defaultdict(int)
        # control plane: any thread appends + wakes; loop thread drains
        self._control: collections.deque = collections.deque()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, ("wake", None))
        self._timers: List[Tuple[float, int, str, Any]] = []
        self._timer_seq = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self.offline = False
        self._desired_offline = False  # last requested state (rebind retry)

    # -- lifecycle -----------------------------------------------------
    @property
    def port(self) -> int:
        return self._port

    @property
    def addr(self) -> Tuple[str, int]:
        return (self._host, self._port)

    def _bind(self, host: str, port: int) -> None:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(128)
        ls.setblocking(False)
        self._listener = ls
        self._port = ls.getsockname()[1]
        self._sel.register(ls, selectors.EVENT_READ, ("listen", None))

    def set_peers(self, peers: Dict[Any, Tuple[str, int]]) -> None:
        """Install the peer address map (before start())."""
        assert self._thread is None, "set_peers before start"
        for pid, addr in peers.items():
            if pid == self.node_id:
                continue
            self._out[pid] = _Outbound(tuple(addr))
        self.max_inbound = 4 * max(1, len(self._out)) + 8

    def start(self) -> None:
        assert self._thread is None
        if self.injector is not None:
            self.injector.start()
        self._thread = threading.Thread(
            target=self._run, name=f"transport-{self.node_id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._post(("stop", None))
        self._thread.join(timeout=10)
        self._thread = None

    def set_offline(self, offline: bool) -> None:
        """Sever all connections and stop listening/dialing (True), or
        rebind the same port and resume (False).  Outbound queues are
        preserved — this simulates a network outage around a live
        process, the sender-queue churn scenario."""
        self._post(("offline", bool(offline)))

    # -- data plane (any thread) ---------------------------------------
    def send(self, dest: Any, payload: bytes) -> None:
        """Frame + queue one protocol message toward ``dest``.

        Each injector-planned copy becomes its own logical frame; a
        mangled copy keeps its original alongside so a retransmission
        (after the receiver drops the corrupted connection) carries the
        clean bytes — the channel is faulty, the message is not.
        """
        frame = encode_frame(KIND_MSG, payload, self.max_frame_len)
        if self.injector is not None:
            plan = self.injector.on_send(self.node_id, dest, frame)
        else:
            plan = [(0.0, frame)]
        for delay_s, data in plan:
            wire = data if data != frame else None
            self._post(("enqueue", (dest, delay_s, frame, wire)))

    def send_many(self, items: List[Tuple[Any, bytes]]) -> None:
        """Frame + queue a batch of ``(dest, payload)`` messages with ONE
        control-plane hand-off (one wakeup byte and one loop-thread drain
        op instead of one per message).  With coalescing on (round 20,
        the default) each destination's run leaves as the fewest frames
        the caps allow — MSGB batches bounded by ``max_frame_len``,
        singletons as plain MSG — making the FRAME the ACK/resume unit
        for the whole batch; ``coalesce=False`` restores one MSG frame
        per message (the A/B arm).  Either way the fault injector still
        plans each *frame* individually, and per-dest FIFO order — the
        only order the transport guarantees — is preserved (grouping by
        dest never reorders within a dest)."""
        by_dest: Dict[Any, List[bytes]] = {}
        for dest, payload in items:
            by_dest.setdefault(dest, []).append(payload)
        batch: List[Tuple[Any, float, bytes, Optional[bytes]]] = []
        for dest, payloads in by_dest.items():
            for frame in self._pack_frames(payloads):
                if self.injector is not None:
                    plan = self.injector.on_send(self.node_id, dest, frame)
                else:
                    plan = ((0.0, frame),)
                for delay_s, data in plan:
                    wire = data if data != frame else None
                    batch.append((dest, delay_s, frame, wire))
        if batch:
            self._post(("enqueue_many", batch))

    def _pack_frames(self, payloads: List[bytes]) -> List[bytes]:
        """Encode one destination's payload run as wire frames.  With
        coalescing off: one MSG frame per payload.  On: greedy MSGB
        groups bounded by ``max_frame_len``; a group that ends up with
        a single payload stays a plain MSG frame (byte-identical to the
        uncoalesced arm — no count/length overhead for singletons)."""
        limit = self.max_frame_len
        if not self.coalesce or len(payloads) == 1:
            return [encode_frame(KIND_MSG, p, limit) for p in payloads]
        frames: List[bytes] = []
        group: List[bytes] = []
        group_len = 5  # frame length counts the kind byte + count field

        def close() -> None:
            if len(group) == 1:
                frames.append(encode_frame(KIND_MSG, group[0], limit))
            elif group:
                frames.append(encode_frame(KIND_MSGB, msgb_body(group), limit))

        for p in payloads:
            need = 4 + len(p)  # element length header + bytes
            if group and group_len + need > limit:
                close()
                group = []
                group_len = 5
            group.append(p)
            group_len += need
        close()
        return frames

    def send_msgb(self, dest: Any, body: bytes, count: int) -> None:
        """Frame + queue a pre-built MSGB body of ``count`` messages
        toward ``dest``.  The native engine's egress drain emits bodies
        already in the wire grammar (framing.py "msgb-grammar"), so the
        hot path is ONE ``encode_frame`` per (peer, sweep) — no
        per-message Python at all.  With coalescing off (or a
        degenerate count) the body is unpacked and routed through
        :meth:`send_many`, so the A/B knob governs the wire uniformly;
        the chaos plane wraps this method to keep its per-message
        egress seam (chaos/nodes.py)."""
        if count <= 1 or not self.coalesce:
            self.send_many([(dest, p) for p in decode_msgb(body)])
            return
        frame = encode_frame(KIND_MSGB, body, self.max_frame_len)
        if self.injector is not None:
            plan = self.injector.on_send(self.node_id, dest, frame)
        else:
            plan = ((0.0, frame),)
        batch = []
        for delay_s, data in plan:
            wire = data if data != frame else None
            batch.append((dest, delay_s, frame, wire))
        self._post(("enqueue_many", batch))

    def send_wire(
        self, records: List[Tuple[Any, int, bytes]]
    ) -> None:
        """Frame + queue a whole egress sweep of pre-packed wire records
        with ONE control-plane hand-off.  Each record is ``(dest, count,
        data)``: a plain MSG payload when ``count <= 1``, else a
        pre-built MSGB body of ``count`` messages (the native drain's
        output shape — see :meth:`send_msgb`).  Emission order is
        preserved end to end (one ``enqueue_many`` op), so per-dest
        FIFO holds with no caller-side buffering; the drain's per-dest
        grouping also keeps same-dest records adjacent, which the loop
        thread's run-batching exploits.  With coalescing off, MSGB
        records are unpacked and the whole sweep routes through
        :meth:`send_many` — the A/B knob governs the wire uniformly.
        The chaos plane wraps this method alongside send/send_many/
        send_msgb (chaos/nodes.py)."""
        if not self.coalesce:
            flat: List[Tuple[Any, bytes]] = []
            for dest, count, data in records:
                if count <= 1:
                    flat.append((dest, data))
                else:
                    flat.extend((dest, p) for p in decode_msgb(data))
            if flat:
                self.send_many(flat)
            return
        limit = self.max_frame_len
        batch: List[Tuple[Any, float, bytes, Optional[bytes]]] = []
        for dest, count, data in records:
            kind = KIND_MSG if count <= 1 else KIND_MSGB
            frame = encode_frame(kind, data, limit)
            if self.injector is not None:
                plan = self.injector.on_send(self.node_id, dest, frame)
            else:
                plan = ((0.0, frame),)
            for delay_s, d in plan:
                wire = d if d != frame else None
                batch.append((dest, delay_s, frame, wire))
        if batch:
            self._post(("enqueue_many", batch))

    def _post(self, item: Tuple[str, Any]) -> None:
        self._control.append(item)
        try:
            self._wake_w.send(b"\x00")
        except BlockingIOError:
            pass  # a wakeup byte is already pending
        except OSError:
            pass  # loop already torn down

    # -- stats ---------------------------------------------------------
    def stats(self) -> Dict[Any, Dict[str, int]]:
        # list(): the loop thread inserts new peers concurrently
        return {pid: st.as_dict() for pid, st in list(self.peer_stats.items())}

    def export_metrics(self) -> Metrics:
        """Refresh per-peer gauges/counters in :attr:`metrics`."""
        m = self.metrics
        for pid, st in list(self.peer_stats.items()):
            base = f"transport.{self.node_id}->{pid}"
            m.gauge(f"{base}.queue_frames", st.queue_frames)
            m.gauge(f"{base}.queue_bytes", st.queue_bytes)
            m.gauge(f"{base}.bytes_out", st.bytes_out)
            m.gauge(f"{base}.frames_out", st.frames_out)
            m.gauge(f"{base}.bytes_in", st.bytes_in)
            m.gauge(f"{base}.frames_in", st.frames_in)
            # coalescing efficiency (round 20): msgs/frames per
            # direction is the msgs-per-frame ratio of the MSGB plane
            m.gauge(f"{base}.msgs_out", st.msgs_out)
            m.gauge(f"{base}.msgs_in", st.msgs_in)
            m.gauge(f"{base}.reconnects", st.reconnects)
            m.gauge(f"{base}.frame_errors", st.frame_errors)
            # peer.* misbehavior gauges (round 11): the <- direction
            # marks these as judgements about INBOUND traffic from pid,
            # exported next to the faults.* injector gauges so one
            # Prometheus dump carries both sides of the Byzantine story
            peer = f"peer.{self.node_id}<-{pid}"
            m.gauge(f"{peer}.misbehavior", st.misbehavior)
            m.gauge(f"{peer}.bans", st.bans)
            m.gauge(f"{peer}.ban_rejects", st.ban_rejects)
        return m

    # -- event loop ----------------------------------------------------
    def _run(self) -> None:
        try:
            while True:
                timeout = self._next_timeout()
                for key, events in self._sel.select(timeout):
                    kind, data = key.data
                    if kind == "wake":
                        try:
                            while self._wake_r.recv(RECV_CHUNK):
                                pass
                        except BlockingIOError:
                            pass
                    elif kind == "listen":
                        self._accept()
                    elif kind == "in":
                        if events & selectors.EVENT_READ:
                            self._read_inbound(data)
                        if data.sock is not None and events & selectors.EVENT_WRITE:
                            self._flush_inbound(data)
                    elif kind == "out":
                        self._service_outbound(data, events)
                if self._drain_control():
                    return  # stop requested
                self._fire_timers()
        finally:
            self._teardown()

    def _next_timeout(self) -> Optional[float]:
        if self._control:
            return 0.0
        if not self._timers:
            return 0.5
        return max(0.0, min(0.5, self._timers[0][0] - time.monotonic()))

    def _drain_control(self) -> bool:
        while self._control:
            op, arg = self._control.popleft()
            if op == "stop":
                return True
            if op == "enqueue":
                dest, delay_s, orig, wire = arg
                if delay_s > 0:
                    self._add_timer(delay_s, "enqueue", (dest, orig, wire))
                else:
                    self._enqueue(dest, orig, wire)
            elif op == "enqueue_many":
                # runs of a common dest share one state lookup + one
                # dial/arm decision (the per-frame _enqueue body was a
                # measured slice of the loop thread at native-node rates)
                run_dest: Any = None
                run: List[Tuple[bytes, Optional[bytes]]] = []
                for dest, delay_s, orig, wire in arg:
                    if delay_s > 0:
                        self._add_timer(delay_s, "enqueue", (dest, orig, wire))
                        continue
                    if dest != run_dest and run:
                        self._enqueue_run(run_dest, run)
                        run = []
                    run_dest = dest
                    run.append((orig, wire))
                if run:
                    self._enqueue_run(run_dest, run)
            elif op == "offline":
                self._desired_offline = bool(arg)
                self._go_offline() if arg else self._go_online()
        return False

    def _add_timer(self, delay_s: float, kind: str, arg: Any) -> None:
        heapq.heappush(
            self._timers,
            (time.monotonic() + delay_s, next(self._timer_seq), kind, arg),
        )

    def _fire_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _, _, kind, arg = heapq.heappop(self._timers)
            if kind == "enqueue":
                self._enqueue(*arg)
            elif kind == "dial":
                ob = self._out.get(arg)
                if (
                    ob is not None
                    and ob.state == "idle"
                    and not self.offline
                    and ob.has_pending()
                ):
                    self._dial(arg, ob)
            elif kind == "rebind":
                if self.offline and not self._desired_offline:
                    self._go_online()
            elif kind == "ack":
                conn = arg
                conn.ack_timer = False
                if (
                    conn.sock is not None
                    and conn.peer_id is not None
                    and self._rx_counts[conn.peer_id] > conn.last_ack
                ):
                    self._send_ack(conn)

    # -- outbound ------------------------------------------------------
    def _enqueue_run(
        self, dest: Any, items: List[Tuple[bytes, Optional[bytes]]]
    ) -> None:
        """Queue a run of frames toward one dest: same admission rules
        as :meth:`_enqueue` per frame, but the peer-state lookups, stat
        writes, and the dial/write-arm decision happen once."""
        ob = self._out.get(dest)
        if ob is None:
            self.metrics.count("transport.unknown_dest", len(items))
            return
        st = self.peer_stats[dest]
        pending_frames = ob.pending_frames()
        pending_bytes = ob.pending_bytes()
        overflow = 0
        for orig, wire in items:
            if (
                pending_frames >= self.max_queue_frames
                or pending_bytes + len(orig) > self.max_queue_bytes
            ):
                overflow += 1
                continue
            ob.queue.append((orig, wire))
            ob.queue_bytes += len(orig)
            pending_frames += 1
            pending_bytes += len(orig)
        if overflow:
            st.queue_overflow += overflow
            self.metrics.count("transport.queue_overflow", overflow)
        st.queue_frames = len(ob.queue)
        st.queue_bytes = ob.queue_bytes
        if ob.state == "idle" and not self.offline:
            if time.monotonic() >= ob.next_dial:
                self._dial(dest, ob)
        elif ob.state == "connected":
            # opportunistic flush: the socket is almost always writable,
            # so sending NOW (one syscall for the whole run) beats
            # arming write interest and paying a full select cycle plus
            # a per-peer event dispatch; _flush_outbound re-arms by
            # itself when the kernel buffer pushes back
            self._flush_outbound(dest, ob)

    def _enqueue(self, dest: Any, orig: bytes, wire: Optional[bytes]) -> None:
        ob = self._out.get(dest)
        if ob is None:
            self.metrics.count("transport.unknown_dest")
            return
        st = self.peer_stats[dest]
        # inflight counts toward BOTH caps: the resume layer retains
        # unacked frames, and retention must stay bounded too (a peer
        # that reads but stops ACKing must not grow memory past the cap)
        if (
            ob.pending_frames() >= self.max_queue_frames
            or ob.pending_bytes() + len(orig) > self.max_queue_bytes
        ):
            st.queue_overflow += 1
            self.metrics.count("transport.queue_overflow")
            return
        ob.queue.append((orig, wire))
        ob.queue_bytes += len(orig)
        st.queue_frames = len(ob.queue)
        st.queue_bytes = ob.queue_bytes
        if ob.state == "idle" and not self.offline:
            now = time.monotonic()
            if now >= ob.next_dial:
                self._dial(dest, ob)
            # else: a backoff timer is already pending
        elif ob.state == "connected":
            self._want_write(ob, True)

    def _dial(self, dest: Any, ob: _Outbound) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.peer_stats[dest].dials += 1
        try:
            sock.connect_ex(ob.addr)
        except OSError:
            sock.close()
            self._schedule_redial(dest, ob)
            return
        ob.sock = sock
        ob.state = "connecting"
        ob.want_w = True  # registered with write interest below
        self._sel.register(
            sock, selectors.EVENT_WRITE | selectors.EVENT_READ, ("out", dest)
        )

    def _schedule_redial(self, dest: Any, ob: _Outbound) -> None:
        ob.attempts += 1
        delay = min(
            self.backoff_cap_s, self.backoff_base_s * (2 ** (ob.attempts - 1))
        )
        delay *= 1.0 + self.backoff_jitter * self._rng.random()
        ob.next_dial = time.monotonic() + delay
        self._add_timer(delay, "dial", dest)

    def _service_outbound(self, dest: Any, events: int) -> None:
        ob = self._out.get(dest)
        if ob is None or ob.sock is None:
            return
        st = self.peer_stats[dest]
        if ob.state == "connecting":
            err = ob.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                self._drop_outbound(dest, ob, redial=True)
                return
            ob.state = "connected"
            ob.attempts = 0
            ob.await_ack = True  # resume point comes from the peer's ACK
            ob.decoder = FrameDecoder(self.max_frame_len)
            st.connects += 1
            if st.connects > 1:
                st.reconnects += 1
                self.metrics.count("transport.reconnects")
            self._trace(
                "transport.connect", peer=dest, reconnect=st.connects > 1
            )
            # handshake first, then whatever queued up.  The HELLO gets
            # a pending_write SENTINEL (orig None) so write_prog stays
            # frame-aligned: without it the handshake bytes inflate
            # write_prog for the connection's lifetime and a later
            # partial send() can graduate a frame to inflight while its
            # tail is still in sendbuf.  A sentinel is never retained or
            # retransmitted — each connection regenerates its HELLO.
            hello = encode_hello(
                self.node_id, self.cluster_id, self.max_frame_len
            )
            ob.sendbuf += hello
            ob.pending_write.append((len(hello), None))
        if events & selectors.EVENT_READ and ob.state == "connected":
            # the reverse direction carries only cumulative ACKs
            try:
                got = ob.sock.recv(RECV_CHUNK)
            except BlockingIOError:
                got = None  # spurious readable wakeup: NOT an EOF
            except OSError:
                got = b""
            if got == b"":
                self._drop_outbound(dest, ob, redial=True)
                return
            try:
                ob.decoder.feed(got or b"")
                for kind, payload in ob.decoder.frames():
                    if kind != KIND_ACK:
                        raise FrameError("only ACK frames flow dialer-ward")
                    self._handle_ack(dest, ob, decode_ack(payload))
            except FrameError:
                self.metrics.count("transport.frame_errors")
                st.frame_errors += 1
                self._drop_outbound(dest, ob, redial=True)
                return
        self._flush_outbound(dest, ob)

    def _handle_ack(self, dest: Any, ob: _Outbound, n: int) -> None:
        """Apply a cumulative consumed-count from the acceptor."""
        while ob.inflight and ob.acked < n:
            ob.inflight_bytes -= len(ob.inflight.popleft())
            ob.acked += 1
        if n < ob.acked:
            # peer lost its counter (process restart): adopt its origin;
            # we can only replay what we still hold
            ob.acked = n
        elif n > ob.acked:
            # WE lost our counter (our restart, their surviving count):
            # resync so future ACKs pop exactly the frames they cover —
            # leaving acked behind would make `acked < n` drain frames
            # the peer never consumed
            ob.acked = n
        if ob.await_ack:
            ob.await_ack = False
            # retransmit the unacked tail ahead of new traffic (originals
            # only — any corruption belonged to the dead connection)
            if ob.inflight:
                retrans = [(data, None) for data in ob.inflight]
                ob.inflight.clear()
                ob.inflight_bytes = 0
                ob.queue.extendleft(reversed(retrans))
                ob.queue_bytes += sum(len(d) for d, _ in retrans)

    def _flush_outbound(self, dest: Any, ob: _Outbound) -> None:
        if ob.state != "connected" or ob.sock is None:
            return
        if self.vectored:
            self._flush_outbound_vectored(dest, ob)
            return
        st = self.peer_stats[dest]
        while ob.sendbuf or (ob.queue and not ob.await_ack):
            # Pack a burst of frames into the write buffer before the
            # syscall (SEND_COALESCE): one send() per frame was the
            # measured socket-plane bound once decode moved native.
            while (
                ob.queue
                and not ob.await_ack
                and len(ob.sendbuf) < SEND_COALESCE
            ):
                orig, wire = ob.queue.popleft()
                ob.queue_bytes -= len(orig)
                data = wire if wire is not None else orig
                ob.sendbuf += data
                ob.pending_write.append((len(data), orig))
                ob.pending_write_bytes += len(orig)
                st.frames_out += 1
                # msgs carried (1 per MSG, batch count per MSGB) read
                # straight off the clean frame bytes: no extra state
                # threads through queue/inflight/retransmit tuples, and
                # retransmits recount exactly like frames_out does
                st.msgs_out += frame_message_count(orig)
            try:
                n = ob.sock.send(ob.sendbuf)
            except BlockingIOError:
                break
            except OSError:
                self._drop_outbound(dest, ob, redial=True)
                return
            if n == 0:
                break
            st.bytes_out += n
            del ob.sendbuf[:n]
            # graduate fully-written frames to the unacked retention
            ob.write_prog += n
            while ob.pending_write and ob.write_prog >= ob.pending_write[0][0]:
                wire_len, orig = ob.pending_write.popleft()
                ob.write_prog -= wire_len
                if orig is None:  # handshake sentinel: nothing to retain
                    continue
                ob.pending_write_bytes -= len(orig)
                ob.inflight.append(orig)
                ob.inflight_bytes += len(orig)
        st.queue_frames = len(ob.queue)
        st.queue_bytes = ob.queue_bytes
        self._want_write(ob, bool(ob.sendbuf or (ob.queue and not ob.await_ack)))

    def _flush_outbound_vectored(self, dest: Any, ob: _Outbound) -> None:
        """sendmsg gather egress: frames go on the wire straight from
        the queue's bytes objects — no per-frame copy into ``sendbuf``.

        Accounting is IDENTICAL to the buffered path: each gathered
        frame appends ``(wire_len, orig)`` to ``pending_write`` before
        the syscall, ``write_prog`` counts accepted bytes, and the
        graduate loop promotes fully-covered frames to ``inflight``.
        The one structural difference is where unsent bytes live: the
        kernel accepting a PARTIAL gather leaves the tail with no
        backing buffer, so the remainder is copied into ``sendbuf`` and
        the next flush (still this method) drains ``sendbuf`` first —
        the copy only happens on kernel pushback, where the buffered
        path would have paid it up front on every frame.
        """
        st = self.peer_stats[dest]
        while ob.sendbuf or (ob.queue and not ob.await_ack):
            bufs: List[Any] = []
            total = 0
            if ob.sendbuf:
                bufs.append(ob.sendbuf)
                total = len(ob.sendbuf)
            while (
                ob.queue
                and not ob.await_ack
                and total < SEND_COALESCE
                and len(bufs) < SENDMSG_MAX_BUFS
            ):
                orig, wire = ob.queue.popleft()
                ob.queue_bytes -= len(orig)
                data = wire if wire is not None else orig
                bufs.append(data)
                total += len(data)
                ob.pending_write.append((len(data), orig))
                ob.pending_write_bytes += len(orig)
                st.frames_out += 1
                st.msgs_out += frame_message_count(orig)
            try:
                n = ob.sock.sendmsg(bufs)
            except BlockingIOError:
                n = 0
            except OSError:
                self._drop_outbound(dest, ob, redial=True)
                return
            if n:
                st.bytes_out += n
                ob.write_prog += n
                while (
                    ob.pending_write
                    and ob.write_prog >= ob.pending_write[0][0]
                ):
                    wire_len, orig = ob.pending_write.popleft()
                    ob.write_prog -= wire_len
                    if orig is None:  # handshake sentinel
                        continue
                    ob.pending_write_bytes -= len(orig)
                    ob.inflight.append(orig)
                    ob.inflight_bytes += len(orig)
            if n < total:
                # Kernel pushback: retain the unsent tail in sendbuf so
                # the resume accounting sees exactly the bytes a
                # buffered flush would still be holding, then stop and
                # arm write interest.
                rem = n
                tail = bytearray()
                for b in bufs:
                    if rem >= len(b):
                        rem -= len(b)
                        continue
                    tail += memoryview(b)[rem:]
                    rem = 0
                ob.sendbuf = tail
                break
            ob.sendbuf = bytearray()
        st.queue_frames = len(ob.queue)
        st.queue_bytes = ob.queue_bytes
        self._want_write(ob, bool(ob.sendbuf or (ob.queue and not ob.await_ack)))

    def _want_write(self, ob: _Outbound, want: bool) -> None:
        if ob.sock is None or ob.state != "connected":
            return
        if ob.want_w == want:
            return  # already armed as requested: skip the epoll_ctl
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if want else 0)
        try:
            self._sel.modify(ob.sock, events, self._sel.get_key(ob.sock).data)
        except (KeyError, ValueError):
            return
        ob.want_w = want

    def _trace(self, name: str, **args: Any) -> None:
        t = self.tracer
        if t is not None:
            t.emit(name, **args)

    def _drop_outbound(self, dest: Any, ob: _Outbound, redial: bool) -> None:
        if ob.sock is not None:
            try:
                self._sel.unregister(ob.sock)
            except (KeyError, ValueError):
                pass
            ob.sock.close()
            ob.sock = None
        if ob.state == "connected":
            self._trace("transport.disconnect", peer=dest)
        ob.state = "idle"
        ob.decoder = None
        ob.await_ack = False
        ob.want_w = False
        # partially-written frames die with their connection (the wire
        # remainder would desync the peer), but their ORIGINALS go back
        # to the queue head in order — the peer never consumed them
        ob.sendbuf.clear()
        if ob.pending_write:
            retrans = [
                (orig, None) for _, orig in ob.pending_write if orig is not None
            ]
            ob.pending_write.clear()
            ob.pending_write_bytes = 0
            ob.queue.extendleft(reversed(retrans))
            ob.queue_bytes += sum(len(o) for o, _ in retrans)
        ob.write_prog = 0
        if redial and not self.offline and ob.has_pending():
            self._schedule_redial(dest, ob)

    # -- inbound -------------------------------------------------------
    def _accept(self) -> None:
        if self._listener is None:
            return
        while True:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            # bound accepted-connection state: each connection can buffer
            # up to max_frame_len before any frame completes, so an
            # unbounded accept loop is an easy local memory DoS
            if len(self._inbound) >= self.max_inbound:
                self.metrics.count("transport.accept_overflow")
                sock.close()
                continue
            sock.setblocking(False)
            conn = _Inbound(sock, self.max_frame_len)
            self._inbound.append(conn)
            self._sel.register(sock, selectors.EVENT_READ, ("in", conn))

    def _read_inbound(self, conn: _Inbound) -> None:
        if conn.sock is None:
            return
        try:
            data = conn.sock.recv(RECV_CHUNK)
        except BlockingIOError:
            return
        except OSError:
            data = b""
        if data == b"":
            self._close_inbound(conn)
            return
        if conn.peer_id is not None:
            self.peer_stats[conn.peer_id].bytes_in += len(data)
        consumed_before = (
            self._rx_counts[conn.peer_id] if conn.peer_id is not None else 0
        )
        try:
            conn.decoder.feed(data)
            burst: List[bytes] = []
            burst_frames: List[int] = []  # msgs per batched frame, in order
            wire_burst: List[Tuple[int, bytes]] = []
            batching = self.on_batch is not None or self.on_wire_batch is not None
            # Parse + dispatch one frame at a time (NOT decoder.frames(),
            # which would collect the whole burst before any dispatch):
            # a violation mid-burst must not void the frames before it —
            # in particular, a HELLO followed by a corrupt frame in the
            # SAME recv must identify the peer first, so the violation
            # is charged to its misbehavior account (round 11) instead
            # of dying anonymously.
            while True:
                frame = conn.decoder.next_frame()
                if frame is None:
                    break
                kind, payload = frame
                if (
                    batching
                    and conn.peer_id is not None
                    and kind in (KIND_MSG, KIND_MSGB)
                ):
                    # Batch path: queue the read burst's MSG/MSGB frames
                    # for ONE consumer call.  MSGB bodies are grammar-
                    # checked HERE (validate_msgb raises FrameError →
                    # the uniform drop/strike/ban response, identical on
                    # both node impls) but only the wire path skips the
                    # slicing.  Kind violations in the same burst still
                    # raise below; frames batched before the violation
                    # are simply never consumed or acked (the resume
                    # layer covers them).
                    nmsg = 0 if kind == KIND_MSG else validate_msgb(payload)
                    if self.on_wire_batch is not None:
                        wire_burst.append((nmsg, payload))
                    elif kind == KIND_MSG:
                        burst.append(payload)
                        burst_frames.append(1)
                    else:
                        burst.extend(decode_msgb(payload))
                        burst_frames.append(nmsg)
                    continue
                self._handle_frame(conn, kind, payload)
            if wire_burst:
                self._dispatch_wire_burst(conn, wire_burst)
            elif burst:
                self._dispatch_burst(conn, burst, burst_frames)
        except FrameError as exc:
            if isinstance(exc, _BanReject):
                # The defense firing, not a framing violation: counted
                # as ban_rejects at the raise site, never frame_errors.
                self._close_inbound(conn)
                return
            self.metrics.count("transport.frame_errors")
            if conn.peer_id is not None:
                self.peer_stats[conn.peer_id].frame_errors += 1
                # A violation on an IDENTIFIED connection is this
                # peer's misbehavior (a pre-HELLO violation has no one
                # to charge).  Channel corruption is indistinguishable
                # from Byzantine framing here by design — the ban is
                # short either way, and resume keeps honest peers
                # lossless across it.
                self._note_misbehavior(conn.peer_id)
            self._close_inbound(conn)
            return
        except _ConsumerOverload:
            # receive-side backpressure: the consumer queue is full, so
            # stop consuming at a prefix point; the peer's reconnect +
            # retransmit (paced by dial backoff) delivers the rest later
            self.metrics.count("transport.consumer_overflow")
            self._close_inbound(conn)
            return
        # coalesced cumulative ACK: immediate past ACK_EVERY unacked
        # frames, else one short timer batches bursts into one ACK
        if (
            conn.peer_id is not None
            and self._rx_counts[conn.peer_id] != consumed_before
        ):
            self._maybe_ack(conn)

    def _maybe_ack(self, conn: _Inbound) -> None:
        unacked = self._rx_counts[conn.peer_id] - conn.last_ack
        if unacked >= ACK_EVERY:
            self._send_ack(conn)
        elif unacked > 0 and not conn.ack_timer:
            conn.ack_timer = True
            self._add_timer(ACK_DELAY_S, "ack", conn)

    # -- misbehavior accounting (round 11) -----------------------------
    def _banned(self, pid: Any) -> bool:
        b = self._bans.get(pid)
        return b is not None and time.monotonic() < b.until

    def _note_misbehavior(self, pid: Any) -> None:
        """Charge one protocol-violation strike to ``pid``; every
        ``ban_threshold`` strikes issue an escalating reconnect ban
        (:func:`ban_duration` — deterministic, no jitter)."""
        st = self.peer_stats[pid]
        st.misbehavior += 1
        self.metrics.count("transport.peer_misbehavior")
        if self.ban_threshold <= 0:
            return
        b = self._bans.setdefault(pid, _BanState())
        b.strikes += 1
        if b.strikes >= self.ban_threshold:
            b.strikes = 0
            dur = ban_duration(b.bans, self.ban_base_s, self.ban_cap_s)
            b.bans += 1
            b.until = time.monotonic() + dur
            st.bans = b.bans
            self.metrics.count("transport.peer_bans")
            self._trace(
                "transport.ban", peer=pid, offense=b.bans, duration_s=dur
            )

    def _send_ack(self, conn: _Inbound) -> None:
        count = self._rx_counts[conn.peer_id]
        conn.last_ack = count
        conn.sendbuf += encode_ack(count)
        self._flush_inbound(conn)

    def _handle_frame(self, conn: _Inbound, kind: int, payload: bytes) -> None:
        if conn.peer_id is None:
            if kind != KIND_HELLO:
                raise FrameError("first frame must be HELLO")
            announced = decode_hello(payload, self.cluster_id)
            if announced not in self._out and not self.accept_unknown_peers:
                raise FrameError(f"HELLO from unconfigured peer {announced!r}")
            if self._banned(announced):
                # Escalating reconnect ban: the peer's recent frame
                # violations crossed ban_threshold, so its reconnects
                # are refused until the ban lapses — the corrupt-frame/
                # reconnect loop is no longer free.  A ban reject is
                # NOT itself a strike (it would self-extend forever).
                self.peer_stats[announced].ban_rejects += 1
                self.metrics.count("transport.ban_rejects")
                raise _BanReject(f"HELLO from banned peer {announced!r}")
            # A fresh HELLO supersedes any stale connection from the same
            # peer: close it WITHOUT consuming its buffered frames.  The
            # cumulative count is shared per peer id — draining a dead
            # connection after ACKing the new one would double-count
            # frames the dialer retransmits (it treats them as unacked),
            # over-acknowledging and breaking the lossless-resume
            # guarantee.  Unconsumed frames are covered by retransmit.
            for stale in list(self._inbound):
                if stale is not conn and stale.peer_id == announced:
                    self._close_inbound(stale)
            conn.peer_id = announced
            self.peer_stats[conn.peer_id].accepts += 1
            self.metrics.count("transport.accepts")
            # initial ACK = the dialer's resume point (always immediate:
            # MSG writes are gated on it)
            self._send_ack(conn)
            return
        if kind == KIND_HELLO:
            raise FrameError("duplicate HELLO")
        if kind == KIND_ACK:
            raise FrameError("ACK frames only flow acceptor->dialer")
        st = self.peer_stats[conn.peer_id]
        st.frames_in += 1
        if kind == KIND_MSGB:
            # Per-frame consumer path for a batch frame: unpack (grammar
            # violations raise FrameError — uniform strike/ban response)
            # and feed each message through on_message; the ack unit
            # stays the FRAME, granted only once every message was
            # offered.  An overload mid-frame leaves the whole frame
            # unacked (batch-atomic) — the in-repo burst consumers are
            # all-or-nothing, and protocol dedup covers re-delivery.
            msgs = decode_msgb(payload)
            st.msgs_in += len(msgs)
            if self.on_message is not None:
                for p in msgs:
                    try:
                        res = self.on_message(conn.peer_id, p)
                    except Exception:
                        self.metrics.count("transport.on_message_errors")
                        res = None
                    if res is False:
                        raise _ConsumerOverload()
            self._rx_counts[conn.peer_id] += 1
            return
        st.msgs_in += 1
        if self.on_message is not None:
            try:
                res = self.on_message(conn.peer_id, payload)
            except Exception:
                # the consumer's problem must not kill the socket plane;
                # a poison frame is counted and acked (never retransmit
                # what deterministically explodes)
                self.metrics.count("transport.on_message_errors")
                res = None
            if res is False:
                raise _ConsumerOverload()
        # consumed == handed to the node's inbox; the frame now survives
        # a disconnect on our side, so it is safe to acknowledge
        self._rx_counts[conn.peer_id] += 1

    def _dispatch_burst(
        self, conn: _Inbound, burst: List[bytes], frame_counts: List[int]
    ) -> None:
        """Hand one read burst's MSG/MSGB messages to ``on_batch``; ack
        exactly the fully-consumed FRAME prefix (the cumulative count
        stays frame-aligned).  ``frame_counts`` maps the flat message
        list back to frames; a frame whose messages were only partially
        consumed is NOT acked — batch-atomic consumption.  (The in-repo
        consumers are all-or-nothing whole-burst inbox puts, so a
        partial prefix only ever re-delivers whole frames on resume;
        protocol-level dedup covers the theoretical partial case.)"""
        st = self.peer_stats[conn.peer_id]
        st.frames_in += len(frame_counts)
        st.msgs_in += len(burst)
        try:
            consumed = self.on_batch(conn.peer_id, burst)
        except Exception:
            # same stance as the per-frame path: a consumer bug must not
            # kill the socket plane, and deterministic poison must never
            # be retransmitted — count, ack the burst, move on
            self.metrics.count("transport.on_message_errors")
            consumed = len(burst)
        consumed = max(0, min(int(consumed), len(burst)))
        frames_done = 0
        covered = 0
        for c in frame_counts:
            if covered + c > consumed:
                break
            covered += c
            frames_done += 1
        self._rx_counts[conn.peer_id] += frames_done
        if consumed < len(burst):
            raise _ConsumerOverload()

    def _dispatch_wire_burst(
        self, conn: _Inbound, records: List[Tuple[int, bytes]]
    ) -> None:
        """Hand one read burst's frames to ``on_wire_batch`` in wire
        form — ``(nmsg, data)`` per frame, nmsg == 0 a plain MSG
        payload, nmsg >= 1 a validated raw MSGB body.  The return value
        counts FRAMES consumed (all-or-nothing per frame by contract),
        which is exactly the ack unit."""
        st = self.peer_stats[conn.peer_id]
        st.frames_in += len(records)
        st.msgs_in += sum(n if n else 1 for n, _ in records)
        try:
            consumed = self.on_wire_batch(conn.peer_id, records)
        except Exception:
            self.metrics.count("transport.on_message_errors")
            consumed = len(records)
        consumed = max(0, min(int(consumed), len(records)))
        self._rx_counts[conn.peer_id] += consumed
        if consumed < len(records):
            raise _ConsumerOverload()

    def _flush_inbound(self, conn: _Inbound) -> None:
        if conn.sock is None:
            return
        try:
            while conn.sendbuf:
                n = conn.sock.send(conn.sendbuf)
                if n == 0:
                    break
                del conn.sendbuf[:n]
        except BlockingIOError:
            pass
        except OSError:
            self._close_inbound(conn)
            return
        want = bool(conn.sendbuf)
        if want == conn.want_w:
            return  # interest unchanged: skip the epoll_ctl
        events = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if want else 0
        )
        try:
            self._sel.modify(conn.sock, events, ("in", conn))
        except (KeyError, ValueError):
            return
        conn.want_w = want

    def _close_inbound(self, conn: _Inbound) -> None:
        if conn.sock is None:
            return
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conn.sock.close()
        conn.sock = None
        if conn in self._inbound:
            self._inbound.remove(conn)

    # -- offline / teardown --------------------------------------------
    def _go_offline(self) -> None:
        if self.offline:
            return
        self.offline = True
        if self._listener is not None:
            try:
                self._sel.unregister(self._listener)
            except (KeyError, ValueError):
                pass
            self._listener.close()
            self._listener = None
        for conn in list(self._inbound):
            self._close_inbound(conn)
        for dest, ob in self._out.items():
            if ob.sock is not None:
                self._drop_outbound(dest, ob, redial=False)
            ob.attempts = 0
            ob.next_dial = 0.0

    def _go_online(self) -> None:
        if not self.offline:
            return
        try:
            self._bind(self._host, self._port)
        except OSError:
            # the freed port can be transiently taken (another process
            # raced it, or lingering TIME_WAIT states on some stacks);
            # stay offline and retry — an escaped exception here would
            # silently kill the whole selector thread
            self.metrics.count("transport.rebind_errors")
            self._add_timer(0.5, "rebind", None)
            return
        self.offline = False
        for dest, ob in self._out.items():
            if ob.has_pending():  # queued OR unacked-inflight frames
                self._dial(dest, ob)

    def _teardown(self) -> None:
        self._stopping = True
        for conn in list(self._inbound):
            self._close_inbound(conn)
        for dest, ob in self._out.items():
            if ob.sock is not None:
                self._drop_outbound(dest, ob, redial=False)
        if self._listener is not None:
            try:
                self._sel.unregister(self._listener)
            except (KeyError, ValueError):
                pass
            self._listener.close()
            self._listener = None
        self._wake_r.close()
        self._wake_w.close()
        self._sel.close()
