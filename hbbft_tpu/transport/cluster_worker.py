"""Subprocess cluster worker: one hbbft node per OS process.

``python -m hbbft_tpu.transport.cluster_worker --node-id I --n N
--seed S --port P --peers host:port,host:port,... --epochs E`` runs one
node of a TCP cluster to ``E`` committed epochs and prints one JSON
line per committed batch (``{"era":..,"epoch":..,"contributions":..}``)
followed by a final ``{"done": true, ...}`` summary — the parent (a
``slow``-marked test, or a human) compares the batch lines across
workers for byte-identical commits.

Key material is DERIVED, not transported: every worker replays the
dealer ritual (:func:`~hbbft_tpu.transport.cluster.deal_keys`) from
``(n, f, seed)``, so nothing secret crosses the process boundary.
Inputs are self-submitted (``tx-<node>-<k>`` whenever the committed
count grows), which keeps the worker driver-free.

This is the flag-gated subprocess mode of ISSUE 4; the thread-per-node
:class:`~hbbft_tpu.transport.cluster.LocalCluster` is the default on
this 1-core box.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.transport.cluster import ClusterNode, build_netinfo
from hbbft_tpu.transport.cluster import _default_protocol_factory
from hbbft_tpu.crypto.backend import BatchedBackend
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.transport.transport import TcpTransport


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node-id", type=int, required=True)
    ap.add_argument("--n", type=int, required=True)
    ap.add_argument("--num-faulty", type=int, default=-1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument(
        "--peers",
        required=True,
        help="comma list host:port indexed by node id (our own slot included)",
    )
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--session-id", default="tcp-cluster")
    ap.add_argument("--cluster-id", default="hbbft-tpu/cluster/v1")
    args = ap.parse_args(argv)

    n = args.n
    f = args.num_faulty if args.num_faulty >= 0 else (n - 1) // 3
    suite = ScalarSuite()
    addrs = []
    for slot in args.peers.split(","):
        host, _, port = slot.rpartition(":")
        addrs.append((host, int(port)))
    assert len(addrs) == n, "--peers must list every node"

    transport = TcpTransport(
        node_id=args.node_id,
        cluster_id=args.cluster_id.encode(),
        peers={j: addrs[j] for j in range(n) if j != args.node_id},
        port=args.port,
        seed=args.seed,
    )
    node = ClusterNode(
        node_id=args.node_id,
        netinfo=build_netinfo(n, f, args.seed, suite, args.node_id),
        all_ids=list(range(n)),
        transport=transport,
        backend=BatchedBackend(suite),
        suite=suite,
        seed=args.seed,
        protocol_factory=_default_protocol_factory(
            args.batch_size, args.session_id.encode(), n
        ),
    )
    transport.start()
    node.start()

    reported = 0
    submitted = 0
    deadline = time.monotonic() + args.timeout_s
    try:
        while reported < args.epochs and time.monotonic() < deadline:
            batches = node.batches()
            if submitted <= len(batches):
                node.submit(Input.user(f"tx-{args.node_id}-{submitted}"))
                submitted += 1
            for b in batches[reported:]:
                print(
                    json.dumps(
                        {
                            "era": b.era,
                            "epoch": b.epoch,
                            "contributions": [
                                [p, list(c)] for p, c in b.contributions
                            ],
                        },
                        sort_keys=True,
                    ),
                    flush=True,
                )
                reported += 1
            time.sleep(0.02)
        print(
            json.dumps(
                {
                    "done": reported >= args.epochs,
                    "node": args.node_id,
                    "batches": reported,
                    "faults": len(node.faults),
                },
                sort_keys=True,
            ),
            flush=True,
        )
        return 0 if reported >= args.epochs else 1
    finally:
        node.stop()
        transport.stop()


if __name__ == "__main__":
    sys.exit(main())
