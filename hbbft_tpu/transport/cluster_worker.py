"""Subprocess cluster worker: one hbbft node per OS process.

Round 14 promotes this from a Python-only slow-tier demo into the REAL
process-per-node runtime behind ``node_impl="native_proc"``
(:class:`~hbbft_tpu.transport.proc_cluster.ProcCluster`):

* ``--impl native`` runs a :class:`~hbbft_tpu.transport.native_node.
  NativeClusterNode` (C++ engine + burst wire API) event loop in this
  process; ``--impl python`` keeps the oracle ClusterNode.
* **Ephemeral spawn protocol** (kills the fixed-port flake class):
  with ``--peers`` omitted the worker binds port 0, prints ONE ready
  line ``{"ready": true, "node": i, "port": p, "obs_port": q|null,
  "pid": ...}`` on stdout, then blocks for a single JSON line on stdin
  carrying the full address map (``{"peers": {"0": ["127.0.0.1", p0],
  ...}}``) the parent assembled from every worker's ready line.  The
  legacy fixed-port mode (``--port P --peers host:port,...``) still
  works byte-for-byte (no ready line, per-batch lines, summary) for
  the round-8 subprocess test.
* **Key material is DERIVED, not transported**: every worker replays
  the dealer ritual (:func:`~hbbft_tpu.transport.cluster.deal_keys`)
  from ``(n, f, seed)`` — nothing secret crosses the process boundary.
* **Driving**: ``--drive presubmit`` (the cross-arm identity mode)
  self-submits the config6 deterministic workload
  (``b-<k>-<node>``, ``k < --presubmit`` rounds) BEFORE start and runs
  to ``--epochs`` committed batches; ``--drive self`` paces one txn
  per observed commit and emits one JSON line per committed batch
  (``--epochs 0`` = run until a ``{"stop": true}`` line or EOF on
  stdin — the kill/restart drill's control channel; a dead parent
  means EOF, so orphaned workers tear down by themselves).
* **Final summary** line carries ``batches_sha`` (sha256 over the
  serde encoding of the first ``--epochs`` committed batches — the
  SAME digest config6 computes, so the parent asserts cross-process
  byte-identity without scraping) plus the merged counters of
  :func:`~hbbft_tpu.transport.cluster.merge_node_metrics`.
* **Obs across processes**: ``--obs-port N`` serves ``/metrics``,
  ``/trace.json`` and ``/healthz`` for THIS node (0 = ephemeral, the
  bound port is echoed in the ready line); ``--trace-file PATH`` dumps
  the node's Chrome trace at exit — the parent merges the per-worker
  files into one cluster trace on the shared wall clock
  (:func:`~hbbft_tpu.obs.export.merge_chrome_traces`).

Thread budget per process: the transport selector loop + the protocol
(engine-sweep) thread + this driver thread — not the 2N threads of a
thread-mode cluster in one interpreter.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.crypto.backend import BatchedBackend
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.obs.analyze import derived_summaries
from hbbft_tpu.obs.export import chrome_trace
from hbbft_tpu.obs.trace import TraceBuffer
from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.transport.cluster import (
    ClusterNode,
    _default_protocol_factory,
    build_netinfo,
    merge_node_metrics,
)
from hbbft_tpu.transport.transport import TcpTransport
from hbbft_tpu.utils import serde


class _SoloClusterView:
    """Single-node cluster facade: exactly the surface
    :class:`~hbbft_tpu.obs.server.ObsServer` and the metric merge
    expect from :class:`~hbbft_tpu.transport.cluster.LocalCluster`,
    backed by THIS process's one node."""

    def __init__(
        self,
        node_id: int,
        node: Any,
        trace: TraceBuffer,
        consensus_n: Optional[int] = None,
        crypto_trace: Optional[TraceBuffer] = None,
    ) -> None:
        self.node_id = node_id
        self.nodes = {node_id: node}
        self.n = 1
        # The CLUSTER's consensus size (proposer universe) — this view
        # holds one node, but its /diag must reason about all N
        # proposers' instances on this node's timeline.
        self.consensus_n = consensus_n
        self.byzantine: Dict[int, Any] = {}
        self.trace = trace
        # RPC crypto-plane mode (round 18): this node's flush spans
        # ride their own "cryptoplane" ring so the analyzer's flush
        # attribution works per worker (and survives the parent-side
        # Chrome-trace merge as its own track).
        self.crypto_trace = crypto_trace
        # Same 2 s phase-summary TTL cache as LocalCluster: a polling
        # scraper must not re-pay the ring walk + quantile sort per
        # request (a parent drill polls /metrics many times a second
        # while this process is busy catching up).
        self._phase_cache: Optional[Tuple[float, Dict[str, Any]]] = None

    def batch_count(self, i: int) -> int:
        return self.nodes[i].batch_count()

    def last_committed(self, i: int) -> Optional[Tuple[int, int]]:
        return self.nodes[i].last_committed()

    def trace_events(self) -> Dict[str, list]:
        events = self.trace.snapshot()
        out = {self.trace.track: events} if events else {}
        if self.crypto_trace is not None:
            cp = self.crypto_trace.snapshot()
            if cp:
                out[self.crypto_trace.track] = cp
        return out

    def merged_metrics(self, fresh: bool = False) -> Any:
        now = time.monotonic()
        cache = self._phase_cache
        if not fresh and cache is not None and now < cache[0]:
            sums = cache[1]
        else:
            sums = derived_summaries(self.trace_events())
            self._phase_cache = (now + 2.0, sums)
        return merge_node_metrics(self.nodes, summaries=sums)

    def chrome_trace(self) -> Dict[str, Any]:
        return chrome_trace(
            self.trace_events(), pids={self.trace.track: self.node_id}
        )


def batches_digest(batches: List[Any], upto: int) -> str:
    """config6's cross-arm identity digest, bit for bit."""
    digest = hashlib.sha256()
    for b in batches[:upto]:
        digest.update(serde.dumps((b.era, b.epoch, b.contributions)))
    return digest.hexdigest()[:16]


def _read_peer_map(n: int) -> Dict[int, Tuple[str, int]]:
    """Block for the parent's one-line address map on stdin."""
    line = sys.stdin.readline()
    if not line:
        raise RuntimeError("stdin closed before the peer map arrived")
    obj = json.loads(line)
    peers = {int(k): (v[0], int(v[1])) for k, v in obj["peers"].items()}
    if len(peers) != n:
        raise RuntimeError(f"peer map has {len(peers)} entries, want {n}")
    return peers


def _watch_stdin(stop: threading.Event) -> None:
    """Drain stdin until a stop command or EOF; either sets ``stop``.
    EOF doubles as orphan cleanup — a dead parent closes the pipe."""
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            if json.loads(line).get("stop"):
                break
        except ValueError:
            continue
    stop.set()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node-id", type=int, required=True)
    ap.add_argument("--n", type=int, required=True)
    ap.add_argument("--num-faulty", type=int, default=-1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--impl", choices=("python", "native"), default="python")
    ap.add_argument(
        "--port",
        type=int,
        default=0,
        help="listener port (0 = ephemeral; echoed in the ready line)",
    )
    ap.add_argument(
        "--peers",
        default=None,
        help="comma list host:port indexed by node id (our own slot "
        "included).  Omitted = handshake mode: bind port 0, print the "
        "ready line, read the address map from stdin.",
    )
    ap.add_argument(
        "--drive",
        choices=("self", "presubmit"),
        default="self",
        help="self = pace one txn per commit + emit per-batch lines "
        "(legacy; --epochs 0 runs until stdin stop/EOF); presubmit = "
        "deterministic pre-start workload, summary only",
    )
    ap.add_argument(
        "--presubmit",
        type=int,
        default=-1,
        help="presubmit rounds (default epochs+4, the config6 workload)",
    )
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--session-id", default="tcp-cluster")
    ap.add_argument("--cluster-id", default="hbbft-tpu/cluster/v1")
    ap.add_argument(
        "--obs-port",
        type=int,
        default=None,
        help="serve /metrics /trace.json /healthz for this node "
        "(0 = ephemeral, echoed in the ready line)",
    )
    ap.add_argument(
        "--trace-file",
        default=None,
        help="write this node's Chrome trace here at exit",
    )
    ap.add_argument(
        "--metrics",
        action="store_true",
        help="embed the full metrics JSON in the summary line",
    )
    ap.add_argument(
        "--crypto-service",
        default=None,
        help="host:port of a crypto-plane service process "
        "(hbbft_tpu.cryptoplane.proc_service); this node's share checks "
        "route there with a local-BatchedBackend fallback",
    )
    ap.add_argument(
        "--crypto-timeout-s",
        type=float,
        default=None,
        help="RPC round-trip budget before a flush falls back locally "
        "(default HBBFT_TPU_CRYPTO_RPC_TIMEOUT_S)",
    )
    args = ap.parse_args(argv)

    n = args.n
    node_id = args.node_id
    f = args.num_faulty if args.num_faulty >= 0 else (n - 1) // 3
    suite = ScalarSuite()
    handshake = args.peers is None

    peers: Optional[Dict[int, Tuple[str, int]]] = None
    if not handshake:
        addrs = []
        for slot in args.peers.split(","):
            host, _, port = slot.rpartition(":")
            addrs.append((host, int(port)))
        if len(addrs) != n:
            raise SystemExit("--peers must list every node")
        peers = {j: addrs[j] for j in range(n) if j != node_id}

    transport = TcpTransport(
        node_id=node_id,
        cluster_id=args.cluster_id.encode(),
        peers=peers,
        port=args.port,
        seed=args.seed,
    )
    trace = TraceBuffer(f"node{node_id}")
    transport.tracer = trace

    crypto_trace: Optional[TraceBuffer] = None
    crypto_backend: Any = None
    if args.crypto_service is not None:
        # Round 18: route this node's share checks through the crypto
        # service process.  Metrics land on the transport's Metrics (the
        # object merge_node_metrics already walks), spans on their own
        # cryptoplane ring; verdict purity makes the fallback safe.
        from hbbft_tpu.cryptoplane.proc_service import (
            RpcServiceClient,
            parse_addr,
        )

        crypto_trace = TraceBuffer("cryptoplane")
        crypto_backend = RpcServiceClient(
            parse_addr(args.crypto_service),
            suite,
            BatchedBackend(suite),
            timeout_s=args.crypto_timeout_s,
            metrics=transport.metrics,
            trace=crypto_trace,
            client_id=f"node{node_id}",
        )

    netinfo = build_netinfo(n, f, args.seed, suite, node_id)
    if args.impl == "native":
        from hbbft_tpu.transport.native_node import NativeClusterNode

        node: Any = NativeClusterNode(
            node_id=node_id,
            netinfo=netinfo,
            all_ids=list(range(n)),
            transport=transport,
            suite=suite,
            seed=args.seed,
            batch_size=args.batch_size,
            session_id=args.session_id.encode(),
            trace=trace,
            crypto_backend=crypto_backend,
        )
    else:
        node = ClusterNode(
            node_id=node_id,
            netinfo=netinfo,
            all_ids=list(range(n)),
            transport=transport,
            backend=(
                crypto_backend
                if crypto_backend is not None
                else BatchedBackend(suite)
            ),
            suite=suite,
            seed=args.seed,
            protocol_factory=_default_protocol_factory(
                args.batch_size, args.session_id.encode(), n
            ),
            trace=trace,
        )

    view = _SoloClusterView(
        node_id, node, trace, consensus_n=n, crypto_trace=crypto_trace
    )
    obs_server = None
    obs_port: Optional[int] = None
    if args.obs_port is not None:
        from hbbft_tpu.obs.server import ObsServer

        obs_server = ObsServer(view, port=args.obs_port).start()
        obs_port = obs_server.port

    if handshake:
        print(
            json.dumps(
                {
                    "ready": True,
                    "node": node_id,
                    "port": transport.port,
                    "obs_port": obs_port,
                    "impl": args.impl,
                    "pid": os.getpid(),
                },
                sort_keys=True,
            ),
            flush=True,
        )
        transport.set_peers(_read_peer_map(n))

    stop_flag = threading.Event()
    if handshake or args.epochs <= 0:
        # After the peer map, stdin becomes the stop/orphan channel.
        # Open-ended runs (--epochs 0) need it in EITHER mode — the
        # documented contract is "run until stdin stop/EOF"; bounded
        # legacy runs (--peers + --epochs N) skip it so a closed
        # inherited stdin can't end them early.
        threading.Thread(
            target=_watch_stdin, args=(stop_flag,), daemon=True
        ).start()

    presubmit = args.presubmit if args.presubmit >= 0 else args.epochs + 4
    if args.drive == "presubmit":
        # The config6 deterministic workload, submitted BEFORE start so
        # every arm's proposers see identical txn queues (per-node
        # order is k-ascending, exactly LocalCluster's presubmit loop).
        for k in range(presubmit):
            node.submit(Input.user(f"b-{k}-{node_id}"))

    t0 = time.perf_counter()
    transport.start()
    node.start()

    reported = 0
    submitted = 0
    deadline = time.monotonic() + args.timeout_s
    done = False
    try:
        while time.monotonic() < deadline and not stop_flag.is_set():
            count = node.batch_count()
            if args.drive == "self":
                if submitted <= count:
                    node.submit(Input.user(f"tx-{node_id}-{submitted}"))
                    submitted += 1
                for b in node.batches_from(reported):
                    print(
                        json.dumps(
                            {
                                "era": b.era,
                                "epoch": b.epoch,
                                "contributions": [
                                    [p, list(c)] for p, c in b.contributions
                                ],
                            },
                            sort_keys=True,
                        ),
                        flush=True,
                    )
                    reported += 1
            else:
                reported = count
            if args.epochs > 0 and reported >= args.epochs:
                done = True
                break
            time.sleep(0.02)
        if args.epochs <= 0:
            # open-ended run: a stop command (or parent EOF) is success
            done = stop_flag.is_set()
        wall = time.perf_counter() - t0
        batches = node.batches()
        upto = args.epochs if args.epochs > 0 else len(batches)
        m = view.merged_metrics(fresh=True)
        summary = {
            "done": done,
            "node": node_id,
            "impl": args.impl,
            "port": transport.port,
            "batches": len(batches),
            "batches_sha": batches_digest(batches, upto),
            # per-epoch contribution counts over the digest window: the
            # parent's "non-empty epochs" check, and the tell for the
            # cross-RUN flake class where one proposer's RBC misses an
            # epoch's BA cut (subset of n-1: still agreement-safe and
            # intra-run identical, but the digest differs from a
            # full-participation run)
            "epoch_contribs": [len(b.contributions) for b in batches[:upto]],
            "faults": len(getattr(node, "faults", ()))
            or m.counters.get("cluster.protocol_faults", 0),
            "msgs_handled": m.counters.get("cluster.msgs_handled", 0),
            "accepts": m.counters.get("transport.accepts", 0),
            "bad_payload": m.counters.get("cluster.bad_payload", 0),
            "handler_errors": m.counters.get("cluster.handler_errors", 0),
            # ring-overflow honesty: nonzero means this node's trace
            # (and everything derived from it) is silently partial
            "trace_dropped": int(m.gauges.get("trace.dropped", 0)),
            "wall_s": round(wall, 3),
        }
        if args.crypto_service is not None:
            # the crypto-plane RPC story in one glance: how many flushes
            # rode the service vs fell back locally (the kill drill's
            # fallback flip shows up here)
            summary["crypto_rpc"] = {
                "calls": m.counters.get("crypto.rpc.calls", 0),
                "requests": m.counters.get("crypto.rpc.requests", 0),
                "fallbacks": m.counters.get("crypto.rpc.fallbacks", 0),
                "fallback_requests": m.counters.get(
                    "crypto.rpc.fallback_requests", 0
                ),
                "reconnects": m.counters.get("crypto.rpc.reconnects", 0),
                "merged_requests": m.counters.get(
                    "crypto.rpc.merged_requests", 0
                ),
            }
        if args.metrics:
            summary["metrics"] = m.to_json()
        print(json.dumps(summary, sort_keys=True), flush=True)
        return 0 if done else 1
    finally:
        node.stop()
        transport.stop()
        if obs_server is not None:
            obs_server.stop()
        if args.trace_file:
            with open(args.trace_file, "w") as fh:
                json.dump(view.chrome_trace(), fh)


if __name__ == "__main__":
    sys.exit(main())
