"""Cluster runtime: N hbbft nodes talking over real localhost TCP.

This is the first harness that takes the stack off the in-process
simulator (:mod:`hbbft_tpu.net.virtual_net`): every node owns a
:class:`~hbbft_tpu.transport.transport.TcpTransport` plus a protocol
thread running the SenderQueue(QueueingHoneyBadger) stack, and the only
way protocol state crosses nodes is serde-encoded frames on sockets.

Per node, two threads:

* the transport's selector loop (socket plane, owns all fds);
* the protocol thread (consensus plane): drains an inbox of decoded-
  frame events and local inputs, steps the protocol, serde-encodes each
  outgoing :class:`TargetedMessage` once per payload and hands it to
  the transport, then flushes the node's
  :class:`~hbbft_tpu.crypto.pool.VerifyPool` through the configured
  backend (eager ``flush_every=1`` semantics — reference-equivalent, the
  deferred-batching invariant applies unchanged if a larger cadence is
  ever wanted here).

Keys are dealt exactly like :class:`~hbbft_tpu.net.virtual_net.
NetBuilder` (same rng ritual at the same seed), so a TCP cluster at
seed s agrees batch-for-batch with a VirtualNet run at seed s modulo
scheduling; more importantly, a *subprocess* worker
(:mod:`hbbft_tpu.transport.cluster_worker`) can derive its own keys
from ``(seed, n, f)`` alone — no key material ever crosses a process
boundary.

Failure drills the tests lean on:

* :meth:`LocalCluster.kill` / :meth:`LocalCluster.restart` — process
  death: protocol state is discarded (fresh instance at era 0), the
  listener port is reused so peers' backoff dials find the reborn node.
* :meth:`LocalCluster.disconnect` / :meth:`LocalCluster.reconnect` —
  network outage around a live process: sockets sever, protocol state
  and both sides' outbound queues survive, and the sender-queue window
  machinery replays/gates traffic on reconnect (churn test).

Untrusted-input policy at this layer: a frame whose payload fails
``serde.loads`` under the cluster's suite pin is counted
(``cluster.bad_payload``) and dropped — framing-level violations
already cost the sender its connection inside the transport.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from hbbft_tpu.crypto.backend import BatchedBackend, CryptoBackend
from hbbft_tpu.crypto.keys import SecretKey, SecretKeySet
from hbbft_tpu.crypto.pool import VerifyPool
from hbbft_tpu.crypto.suite import ScalarSuite, Suite
from hbbft_tpu.obs import trace as _trace
from hbbft_tpu.obs.analyze import derived_summaries, diagnose
from hbbft_tpu.obs.export import chrome_trace, summarize
from hbbft_tpu.obs.trace import TraceBuffer, TraceEvent
from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import SenderQueue, SqMessage
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step
from hbbft_tpu.transport.transport import TcpTransport
from hbbft_tpu.utils import serde
from hbbft_tpu.utils.metrics import EpochTracker, Metrics


def deal_keys(
    n: int, f: int, seed: int, suite: Suite
) -> Tuple[SecretKeySet, Dict[int, SecretKey]]:
    """NetBuilder's dealer ritual, factored out so every process of a
    cluster derives identical keys from ``(n, f, seed)`` (rng draw
    ORDER is part of the wire contract between processes — change it
    only with a version bump in the cluster id)."""
    rng = random.Random(seed)
    sks = SecretKeySet.random(f, rng, suite)
    node_sks = {i: SecretKey.random(rng, suite) for i in range(n)}
    return sks, node_sks


def build_netinfo(
    n: int, f: int, seed: int, suite: Suite, our_id: int
) -> NetworkInfo:
    sks, node_sks = deal_keys(n, f, seed, suite)
    val_ids = list(range(n))
    node_pks = {i: node_sks[i].public_key() for i in val_ids}
    return NetworkInfo(
        our_id=our_id,
        val_ids=val_ids,
        public_key_set=sks.public_keys(),
        secret_key_share=sks.secret_key_share(our_id),
        public_keys=node_pks,
        secret_key=node_sks[our_id],
    )


def track_commits(
    epochs: EpochTracker, batches: Sequence[DhbBatch], last_t: float
) -> float:
    """Record commit latency for ``batches`` (both node impls route
    committed batches through here): each epoch's latency is the
    commit-to-commit interval at this node — ``started_at`` is the
    previous commit (or node start), so the first measurement includes
    cluster ramp-up honestly.  Returns the new last-commit time."""
    for b in batches:
        now = time.time()
        key = (b.era, b.epoch)
        epochs.start(key, last_t)
        txns = sum(
            len(c) if isinstance(c, (list, tuple)) else 1
            for _, c in b.contributions
            if c
        )
        epochs.finish(key, now, contributions=len(b.contributions), txns=txns)
        last_t = now
    return last_t


def merge_node_metrics(
    nodes: Dict[int, Any],
    base: Optional[Metrics] = None,
    summaries: Optional[
        Dict[str, Tuple[Dict[float, float], int, float]]
    ] = None,
) -> Metrics:
    """Merge per-node metrics plus the derived observability families
    (per-node transport export, ``epoch.latency`` summary, per-node
    committed gauges, the ring-derived ``summaries`` — ``phase.*`` +
    ``ba.rounds``) — the shared half of
    :meth:`LocalCluster.merged_metrics`, factored out so the
    process-per-node worker (:mod:`~hbbft_tpu.transport.cluster_worker`)
    exports the same metric families for ONE node that a cluster dump
    carries for N, and the parent-side merge stays a plain counter sum."""
    m = Metrics()
    for node in nodes.values():
        node.transport.export_metrics()
        m.merge(node.metrics)
    if base is not None:
        m.merge(base)
    lats: List[float] = []
    dropped_total = 0
    for i, node in nodes.items():
        # Trace-ring overflow (round-16 satellite): silently truncated
        # traces make every ring-derived number (phase.*, ba.rounds,
        # critical_path) quietly partial — export the drop counters so
        # a scrape or bench line shows the truncation.
        drop_fn = getattr(node, "trace_dropped", None)
        dropped = int(drop_fn()) if callable(drop_fn) else 0
        m.gauge(f"trace.{i}.dropped", dropped)
        dropped_total += dropped
        tracker = getattr(node, "epochs", None)
        if tracker is None:
            continue
        node_lats = tracker.latencies()
        lats.extend(node_lats)
        m.gauge(f"epoch.{i}.committed", len(node_lats))
    m.gauge("trace.dropped", dropped_total)
    sm = summarize(lats)
    if sm is not None:
        quant, count, total = sm
        m.summary("epoch.latency", quant, count, total)
    for name, (quant, count, total) in sorted((summaries or {}).items()):
        m.summary(name, quant, count, total)
    return m


class ClusterNode:
    """One node: protocol thread + transport, joined by an inbox."""

    def __init__(
        self,
        node_id: int,
        netinfo: NetworkInfo,
        all_ids: List[int],
        transport: TcpTransport,
        backend: CryptoBackend,
        suite: Suite,
        seed: int,
        protocol_factory: Callable[[NetworkInfo, Any, random.Random], ConsensusProtocol],
        metrics: Optional[Metrics] = None,
        inbox_cap: int = 50_000,
        trace: Optional[TraceBuffer] = None,
    ) -> None:
        self.id = node_id
        self.netinfo = netinfo
        self.all_ids = list(all_ids)
        self.transport = transport
        self.backend = backend
        self.suite = suite
        self.metrics = metrics if metrics is not None else transport.metrics
        # Flight recorder (round 12): the protocol thread installs this
        # buffer as its thread-local tracer, so the protocol modules'
        # milestone emits land here; epoch commit latency feeds the
        # epoch.latency summary via the tracker.
        self.trace = trace
        self.epochs = EpochTracker()
        self._last_commit_t = time.time()
        self.rng = random.Random((seed << 16) ^ (node_id + 1))
        self.pool = VerifyPool()
        self.protocol = protocol_factory(netinfo, self.pool, self.rng)
        self.outputs: List[Any] = []
        self._batches: List[DhbBatch] = []  # outputs filtered once, at append
        self.faults: List[Any] = []
        # Bounded: a peer streaming faster than the protocol thread
        # drains must hit receive-side backpressure (the transport drops
        # its connection un-acked and it resumes later), not grow memory.
        self.inbox: "queue.Queue[Tuple[str, Any, Any]]" = queue.Queue(
            maxsize=inbox_cap
        )
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._ran_before = False
        self._lock = threading.Lock()  # snapshot vs append on outputs
        # Burst consumer (round 20): one inbox item per read burst with
        # all-or-nothing consumption — the frame-atomic unit the MSGB
        # ACK contract needs (a partially-consumed batch frame would be
        # re-delivered whole after a reconnect).  The transport unpacks
        # MSGB bodies before this callback, so mixed clusters interop
        # regardless of the peer's coalesce arm.
        transport.on_batch = self._on_frame_burst

    # -- transport thread ----------------------------------------------
    def _on_frame_burst(self, sender: Any, payloads: List[bytes]) -> int:
        try:
            self.inbox.put_nowait(("msgs", sender, payloads))
        except queue.Full:
            self.metrics.count("cluster.inbox_overflow")
            return 0  # nothing consumed: transport drops the conn un-acked
        return len(payloads)

    # -- any thread ----------------------------------------------------
    def submit(self, input: Any) -> None:
        try:
            self.inbox.put_nowait(("input", input, None))
        except queue.Full:
            # local inputs are droppable under overload (drivers pace);
            # silently blocking the submitter could deadlock a test
            self.metrics.count("cluster.input_dropped")

    def batches(self) -> List[DhbBatch]:
        with self._lock:
            return list(self._batches)

    def batch_count(self) -> int:
        """O(1) committed-batch count (the traffic driver polls this
        every tick — copying the whole list just for len() is O(epochs)
        and QHB grows it forever)."""
        with self._lock:
            return len(self._batches)

    def batches_from(self, start: int) -> List[DhbBatch]:
        """Batches from index ``start`` on — copies only the new tail."""
        with self._lock:
            return self._batches[start:]

    def start(self) -> None:
        assert self._thread is None
        self._stop = False
        self._last_commit_t = time.time()
        self._thread = threading.Thread(
            target=self._run, name=f"node-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop = True  # the flag, not a queue item: survives a full inbox
        self._thread.join(timeout=10)
        self._thread = None

    def last_committed(self) -> Optional[Tuple[int, int]]:
        """(era, epoch) of the newest committed batch, or None."""
        with self._lock:
            if not self._batches:
                return None
            b = self._batches[-1]
            return (b.era, b.epoch)

    def trace_dropped(self) -> int:
        """Events this node's trace ring dropped to overflow (0 when
        the recorder is off) — the honest-truncation gauge."""
        return self.trace.dropped if self.trace is not None else 0

    def _track_commits(self, batches: List[DhbBatch]) -> None:
        if batches:
            self._last_commit_t = track_commits(
                self.epochs, batches, self._last_commit_t
            )

    # -- protocol thread -----------------------------------------------
    def _run(self) -> None:
        _trace.install(self.trace)
        if not self._ran_before:
            # The first epoch's state was built in __init__ on the MAIN
            # thread (no tracer installed): re-emit its open here so
            # epoch 0 gets a complete span.  A fresh node is always at
            # (era 0, epoch 0) before its protocol thread first runs.
            self._ran_before = True
            _trace.emit("epoch.open", era=0, epoch=0)
        egress: List[Tuple[Any, bytes]] = []
        while not self._stop:
            try:
                kind, a, b = self.inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            egress.clear()
            if kind == "msgs":
                # Exception scope is per MESSAGE, not the burst: the
                # frames behind a failing one were already consumed +
                # ACKed by the transport, so skipping them would lose
                # acknowledged traffic with no retransmit.  A handler
                # bug must not take the thread down either way — count
                # it loudly; tests assert this stays zero.
                for payload in b:
                    try:
                        msg = serde.try_loads(payload, suite=self.suite)
                        # any well-formed-but-wrong-type payload is
                        # still peer-authored garbage, not a local
                        # handler bug
                        if msg is None or not isinstance(msg, SqMessage):
                            self.metrics.count("cluster.bad_payload")
                            continue
                        self.metrics.count("cluster.msgs_handled")
                        step = self.protocol.handle_message(a, msg, self.rng)
                        self._process_step(step, egress)
                    except Exception:
                        self.metrics.count("cluster.handler_errors")
            else:  # input
                try:
                    step = self.protocol.handle_input(a, self.rng)
                    self._process_step(step, egress)
                except Exception:
                    self.metrics.count("cluster.handler_errors")
            try:
                while self.pool:
                    self._process_step(self.pool.flush(self.backend), egress)
                if egress:
                    # One control-plane hand-off per inbox item: the
                    # transport packs each peer's payloads into MSGB
                    # frames (or per-message MSG frames, coalesce off).
                    self.transport.send_many(list(egress))
            except Exception:
                self.metrics.count("cluster.handler_errors")

    def _process_step(
        self, step: Step, egress: Optional[List[Tuple[Any, bytes]]] = None
    ) -> None:
        if step.output:
            batches = [o for o in step.output if isinstance(o, DhbBatch)]
            with self._lock:
                self.outputs.extend(step.output)
                self._batches.extend(batches)
            self._track_commits(batches)
        if step.fault_log.faults:
            self.faults.extend(step.fault_log.faults)
            self.metrics.count("cluster.protocol_faults", len(step.fault_log.faults))
        for tm in step.messages:
            data = serde.dumps(tm.message)
            for dest in tm.target.recipients(self.all_ids, self.id):
                if egress is not None:
                    egress.append((dest, data))
                else:
                    self.transport.send(dest, data)


def _default_protocol_factory(
    batch_size: int, session_id: bytes, n: int
) -> Callable[[NetworkInfo, Any, random.Random], ConsensusProtocol]:
    def factory(ni: NetworkInfo, sink: Any, rng: random.Random) -> ConsensusProtocol:
        return SenderQueue.wrap(
            lambda s: QueueingHoneyBadger(
                ni, s, batch_size=batch_size, session_id=session_id
            ),
            sink,
            peers=list(range(n)),
        )

    return factory


class LocalCluster:
    """N thread-per-node TCP nodes on localhost.

    ``injector`` (a :class:`~hbbft_tpu.transport.faults.FaultInjector`)
    is shared by every node's transport, so one schedule partitions /
    degrades the whole cluster deterministically.
    """

    def __init__(
        self,
        n: int,
        seed: int = 0,
        batch_size: int = 8,
        num_faulty: Optional[int] = None,
        session_id: bytes = b"tcp-cluster",
        cluster_id: bytes = b"hbbft-tpu/cluster/v1",
        suite: Optional[Suite] = None,
        backend_factory: Callable[[Suite], CryptoBackend] = BatchedBackend,
        protocol_factory: Optional[
            Callable[[NetworkInfo, Any, random.Random], ConsensusProtocol]
        ] = None,
        injector: Any = None,
        max_frame_len: Optional[int] = None,
        max_queue_frames: int = 20_000,
        node_impl: Any = "python",
        byzantine: Optional[Dict[int, Any]] = None,
        transport_kwargs: Optional[Dict[str, Any]] = None,
        crypto: str = "inline",
        crypto_service: Any = None,
        service_kwargs: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.n = n
        self.seed = seed
        self.f = num_faulty if num_faulty is not None else (n - 1) // 3
        # A real error, not an assert: ``python -O`` strips asserts, and
        # a cluster sized below the BFT bound silently voids every
        # agreement guarantee downstream (the failure shows up later as
        # an inexplicable stall or divergence, never here).
        if self.f < 0 or n < 3 * self.f + 1:
            raise ValueError(
                f"BFT bound violated: need n >= 3*num_faulty + 1 "
                f"(got n={n}, f={self.f})"
            )
        # byzantine (round 11): {node_id: strategy} — those nodes run
        # live-socket adversary arms (hbbft_tpu.chaos) instead of honest
        # ones.  A strategy is a registry name ("crash-stop" |
        # "equivocate" | "corrupt-share" | "stale-replay" | "flood"), a
        # ByzantineStrategy instance, or a zero-arg factory.  Byzantine
        # nodes spend the fault budget: more than f of them voids the
        # oracle's guarantees, so that is rejected too.
        self.byzantine: Dict[int, Any] = dict(byzantine or {})
        for nid in self.byzantine:
            if not (0 <= nid < n):
                raise ValueError(f"byzantine id {nid} outside 0..{n - 1}")
        if len(self.byzantine) > self.f:
            raise ValueError(
                f"{len(self.byzantine)} Byzantine nodes exceed the fault "
                f"budget f={self.f} (n={n})"
            )
        if self.byzantine:
            # Fail on a bad registry name HERE, not after n listeners
            # and a stack of node threads exist (there is no stop()
            # path for a half-built cluster).  Instances/factories are
            # resolved per-bind in _make_node as before.
            from hbbft_tpu.chaos.strategies import STRATEGIES

            for spec in self.byzantine.values():
                if isinstance(spec, str) and spec not in STRATEGIES:
                    raise ValueError(
                        f"unknown Byzantine strategy {spec!r} "
                        f"(known: {sorted(STRATEGIES)})"
                    )
        self.suite = suite if suite is not None else ScalarSuite()
        self.cluster_id = cluster_id
        self.injector = injector
        self.metrics = Metrics()
        # Flight recorder (round 12): one bounded event ring per node
        # plus a cluster-level ring (chaos schedule events).  The rings
        # live HERE, not on the node objects, so a kill/restart drill
        # keeps one continuous timeline per node id across rebirths.
        self.trace = TraceBuffer("cluster")
        self.traces: Dict[int, TraceBuffer] = {
            i: TraceBuffer(f"node{i}") for i in range(n)
        }
        self._obs_server: Any = None
        # Phase-summary TTL cache: deriving spans re-walks every ring
        # snapshot, which is fine once per run but not once per scrape —
        # a Prometheus poller must not re-pay it per request (stop()
        # invalidates, so end-of-run reads are exact).
        self._phase_cache: Optional[Tuple[float, Dict[str, Any]]] = None
        # node_impl (round 9): "python" (the oracle ClusterNode above),
        # "native" (engine-per-node NativeClusterNode — the whole
        # decode+handle loop in C), or a {node_id: impl} mapping for
        # mixed clusters (interop tests).  Native nodes run the stock
        # SenderQueue(QHB) semantics natively, so they only compose with
        # the DEFAULT protocol stack and the scalar suite.
        self._node_impl = node_impl
        self._batch_size = batch_size
        self._session_id = session_id
        if any(self._impl_for(i) == "native" for i in range(n)):
            if protocol_factory is not None:
                raise ValueError(
                    "node_impl='native' runs the stock SenderQueue(QHB) "
                    "stack in the engine; custom protocol_factory needs "
                    "node_impl='python'"
                )
            if not isinstance(self.suite, ScalarSuite):
                raise ValueError(
                    "node_impl='native' requires the scalar suite "
                    "(the engine's internal-crypto mode)"
                )
        factory = protocol_factory or _default_protocol_factory(
            batch_size, session_id, n
        )
        self._factory = factory
        self._backend_factory = backend_factory
        # crypto (round 13): "inline" verifies shares where they always
        # were (scalar C in native nodes, a per-node backend in Python
        # nodes); "service" routes BOTH arms' share checks through ONE
        # shared CryptoPlaneService that batches requests across all
        # nodes into single backend flushes (hbbft_tpu/cryptoplane/,
        # docs/CRYPTO_PLANE.md).  The service's backend comes from
        # backend_factory(suite) unless a pre-built service (e.g. over
        # TpuBackend) is passed in; every node keeps a local
        # BatchedBackend fallback, so a dead/slow service degrades to
        # inline verification instead of stalling the cluster.
        # "service-proc" (round 18): the same service in its own OS
        # process behind the socket RPC boundary
        # (hbbft_tpu/cryptoplane/proc_service.py).  crypto_service may
        # be a pre-started ServiceProcess, a (host, port) address of an
        # externally-run worker, or None — None consults
        # HBBFT_TPU_CRYPTO_SERVICE and otherwise spawns an owned worker
        # (Batched backend over this cluster's suite).  Per-node
        # RpcServiceClients keep the local-BatchedBackend fallback, so
        # a killed service process degrades to inline verification.
        if crypto not in ("inline", "service", "service-proc"):
            raise ValueError(
                f"unknown crypto arm {crypto!r} "
                "(inline | service | service-proc)"
            )
        if crypto_service is not None and crypto == "inline":
            raise ValueError("crypto_service requires a service crypto arm")
        self.crypto = crypto
        self.crypto_service = crypto_service
        self._owns_service = False
        self._service_timeout_s = 30.0
        self._service_addr: Optional[Tuple[str, int]] = None
        self._cryptoplane_trace: Optional[TraceBuffer] = None
        if crypto == "service":
            from hbbft_tpu.cryptoplane import CryptoPlaneService

            kw = dict(service_kwargs or {})
            self._service_timeout_s = float(kw.pop("timeout_s", 30.0))
            if self.crypto_service is None:
                self.crypto_service = CryptoPlaneService(
                    backend_factory(self.suite),
                    trace=TraceBuffer("cryptoplane"),
                    **kw,
                )
                self._owns_service = True
            elif kw:
                # Construction kwargs cannot be applied to a pre-built
                # service — silently ignoring them would misconfigure
                # the run with no symptom beyond odd batch sizes.
                raise ValueError(
                    f"service_kwargs {sorted(kw)} cannot be applied to a "
                    "pre-built crypto_service (only timeout_s, which "
                    "configures the per-node clients)"
                )
        elif crypto == "service-proc":
            from hbbft_tpu.cryptoplane.proc_service import (
                ServiceProcess,
                default_rpc_timeout_s,
                service_addr_from_env,
                suite_arg_for,
            )

            kw = dict(service_kwargs or {})
            self._service_timeout_s = float(
                kw.pop("timeout_s", default_rpc_timeout_s())
            )
            # one client-side span ring for all nodes: RPC flush spans
            # carry per-client span ids, so the analyzer can pair them
            # even though clients flush concurrently
            self._cryptoplane_trace = TraceBuffer("cryptoplane")
            if isinstance(self.crypto_service, tuple):
                if kw:
                    raise ValueError(
                        f"service_kwargs {sorted(kw)} cannot be applied "
                        "to an externally-run crypto service address"
                    )
                self._service_addr = self.crypto_service
                self.crypto_service = None
            elif self.crypto_service is not None:
                if kw:
                    raise ValueError(
                        f"service_kwargs {sorted(kw)} cannot be applied "
                        "to a pre-started crypto_service process"
                    )
                self._service_addr = self.crypto_service.addr
            else:
                env_addr = service_addr_from_env()
                if env_addr is not None:
                    if kw:
                        raise ValueError(
                            f"service_kwargs {sorted(kw)} cannot be "
                            "applied to the HBBFT_TPU_CRYPTO_SERVICE "
                            "external service"
                        )
                    self._service_addr = env_addr
                else:
                    self.crypto_service = ServiceProcess(
                        suite=suite_arg_for(self.suite),
                        backend=kw.pop("backend", "batched"),
                        **kw,
                    ).start()
                    self._owns_service = True
                    self._service_addr = self.crypto_service.addr
        elif service_kwargs:
            raise ValueError("service_kwargs requires a service crypto arm")
        self._transport_kwargs: Dict[str, Any] = dict(
            max_queue_frames=max_queue_frames,
        )
        if max_frame_len is not None:
            self._transport_kwargs["max_frame_len"] = max_frame_len
        if transport_kwargs:
            self._transport_kwargs.update(transport_kwargs)

        # Bind every listener first so the full address map exists
        # before any node is constructed.
        self.nodes: Dict[int, ClusterNode] = {}
        transports: Dict[int, TcpTransport] = {}
        for i in range(n):
            transports[i] = TcpTransport(
                node_id=i,
                cluster_id=cluster_id,
                metrics=Metrics(),
                injector=injector,
                seed=seed,
                **self._transport_kwargs,
            )
        self.addr_map: Dict[int, Tuple[str, int]] = {
            i: t.addr for i, t in transports.items()
        }
        for i, t in transports.items():
            t.set_peers({j: a for j, a in self.addr_map.items() if j != i})
            self.nodes[i] = self._make_node(i, t)
        self._started = False

    def _impl_for(self, node_id: int) -> str:
        if isinstance(self._node_impl, str):
            return self._node_impl
        return self._node_impl.get(node_id, "python")

    @property
    def honest_ids(self) -> List[int]:
        return [i for i in range(self.n) if i not in self.byzantine]

    def _service_client(self, i: int, t: TcpTransport):
        """A fresh per-node facade onto the shared verification service
        (each carries its own local-CPU fallback backend; restart()
        re-enters here, so a reborn node gets a live client even after
        drills killed its predecessor mid-wait).  In RPC mode the
        client writes ``crypto.rpc.*`` into the node's transport
        metrics — the path every merge/scrape already walks — and its
        flush spans onto the shared ``cryptoplane`` ring."""
        if self.crypto == "service-proc":
            from hbbft_tpu.cryptoplane.proc_service import RpcServiceClient

            return RpcServiceClient(
                self._service_addr,
                self.suite,
                BatchedBackend(self.suite),
                timeout_s=self._service_timeout_s,
                metrics=t.metrics,
                trace=self._cryptoplane_trace,
                client_id=f"node{i}",
            )
        return self.crypto_service.client(
            BatchedBackend(self.suite), timeout_s=self._service_timeout_s
        )

    def _make_node(self, i: int, t: TcpTransport):
        netinfo = build_netinfo(self.n, self.f, self.seed, self.suite, i)
        t.tracer = self.traces[i]  # transport milestones share the ring
        service = self.crypto in ("service", "service-proc")
        if self._impl_for(i) == "native":
            from hbbft_tpu.transport.native_node import NativeClusterNode

            node = NativeClusterNode(
                node_id=i,
                netinfo=netinfo,
                all_ids=list(range(self.n)),
                transport=t,
                suite=self.suite,
                seed=self.seed,
                batch_size=self._batch_size,
                session_id=self._session_id,
                trace=self.traces[i],
                crypto_backend=self._service_client(i, t) if service else None,
            )
        else:
            node = ClusterNode(
                node_id=i,
                netinfo=netinfo,
                all_ids=list(range(self.n)),
                transport=t,
                backend=(
                    self._service_client(i, t)
                    if service
                    else self._backend_factory(self.suite)
                ),
                suite=self.suite,
                seed=self.seed,
                protocol_factory=self._factory,
                trace=self.traces[i],
            )
        spec = self.byzantine.get(i)
        if spec is not None:
            # restart() re-enters here, so a reborn Byzantine node gets
            # its strategy re-armed with fresh per-bind state
            from hbbft_tpu.chaos.nodes import install_byzantine

            node = install_byzantine(
                node,
                spec,
                seed=self.seed,
                suite=self.suite,
                cluster_id=self.cluster_id,
                peer_addrs={j: a for j, a in self.addr_map.items() if j != i},
                impl=self._impl_for(i),
            )
        return node

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self.injector is not None:
            self.injector.start()
        for node in self.nodes.values():
            node.transport.start()
            node.start()
        self._started = True

    def stop(self) -> None:
        if self._obs_server is not None:
            self._obs_server.stop()
            self._obs_server = None
        for node in self.nodes.values():
            node.stop()
            node.transport.stop()
        # Service AFTER the nodes: a protocol thread blocked in a
        # verify wait fails over to its local fallback and exits
        # cleanly; stopping the service first would only route the
        # final flushes through the fallback needlessly.  Only the
        # service THIS cluster built — stop() is terminal, and a
        # caller-supplied service (e.g. config9's TpuBackend arm) may
        # outlive the cluster; its owner stops it.
        if self._owns_service and self.crypto_service is not None:
            self.crypto_service.stop()
        self._phase_cache = None  # end-of-run reads must be exact
        self._started = False

    def __enter__(self) -> "LocalCluster":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- failure drills ------------------------------------------------
    def kill(self, node_id: int) -> None:
        """Process death: the node's threads stop, its sockets reset,
        its protocol state is GONE (restart() builds a fresh instance)."""
        node = self.nodes[node_id]
        node.stop()
        node.transport.stop()

    def restart(self, node_id: int) -> None:
        """Re-create the killed node on its old port with fresh state."""
        old = self.nodes[node_id]
        port = old.transport.port
        t = TcpTransport(
            node_id=node_id,
            cluster_id=self.cluster_id,
            peers={j: a for j, a in self.addr_map.items() if j != node_id},
            metrics=Metrics(),
            injector=self.injector,
            seed=self.seed,
            port=port,
            **self._transport_kwargs,
        )
        node = self._make_node(node_id, t)
        self.nodes[node_id] = node
        if self._started:
            t.start()
            node.start()

    def disconnect(self, node_id: int) -> None:
        """Network outage around a live process (state survives)."""
        self.nodes[node_id].transport.set_offline(True)

    def reconnect(self, node_id: int) -> None:
        self.nodes[node_id].transport.set_offline(False)

    # -- driving -------------------------------------------------------
    def submit(self, node_id: int, input: Any) -> None:
        self.nodes[node_id].submit(input)

    def submit_all(self, input_fn: Callable[[int], Any]) -> None:
        for i in sorted(self.nodes):
            self.submit(i, input_fn(i))

    def batches(self, node_id: int) -> List[DhbBatch]:
        return self.nodes[node_id].batches()

    def batch_count(self, node_id: int) -> int:
        return self.nodes[node_id].batch_count()

    def batches_from(self, node_id: int, start: int) -> List[DhbBatch]:
        return self.nodes[node_id].batches_from(start)

    def last_committed(self, node_id: int) -> Optional[Tuple[int, int]]:
        """(era, epoch) of the node's newest committed batch (None
        before its first commit) — the /healthz liveness payload."""
        return self.nodes[node_id].last_committed()

    def wait(
        self,
        pred: Callable[["LocalCluster"], bool],
        timeout_s: float,
        poll_s: float = 0.02,
    ) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred(self):
                return True
            time.sleep(poll_s)
        return pred(self)

    def drive_to(
        self,
        ids: Sequence[int],
        target: int,
        timeout_s: float = 60.0,
        tag: str = "d",
        tick: Optional[Callable[[], Any]] = None,
    ) -> None:
        """Feed txns to every live node until every node in ``ids`` has
        committed >= ``target`` batches; raises on timeout.

        Submission is PACED against committed epochs (at most ~2 rounds
        of txns ahead of the slowest observed node): an unpaced feeder
        builds a transaction backlog that keeps committing epochs long
        after the target — the CLAUDE.md pacing invariant, held here
        ONCE for tests, benchmarks, and examples.

        ``tick`` (optional) runs once per poll iteration — the chaos
        scheduler pumps its timed fault events through it so a drive
        and a fault schedule share one loop.
        """
        deadline = time.monotonic() + timeout_s
        # batch_count (O(1) under the node lock) not batches() — this
        # poll fires every 50 ms and a list copy grows with the stream.
        base = min(self.batch_count(i) for i in ids)
        k = 0
        while time.monotonic() < deadline:
            if tick is not None:
                tick()
            mn = min(self.batch_count(i) for i in ids)
            if mn >= target:
                return
            if k < (mn - base) + 3:
                for i in sorted(self.nodes):
                    if self.nodes[i]._thread is not None:
                        self.submit(i, Input.user(f"{tag}-{k}-{i}"))
                k += 1
            time.sleep(0.05)
        counts = {i: self.batch_count(i) for i in sorted(self.nodes)}
        raise TimeoutError(
            f"no progress to {target} batches within {timeout_s}s: {counts}"
        )

    # -- observability -------------------------------------------------
    def merged_metrics(self, fresh: bool = False) -> Metrics:
        """Merge every node's metrics plus the derived observability
        summaries.  ``fresh=True`` bypasses the phase-summary TTL cache
        — end-of-run snapshots (benchmark JSON lines) must be exact
        even when a live scraper primed the cache seconds earlier."""
        # phase.* (round 12) + ba.rounds (round 16): the per-epoch
        # ring-derived summaries (obs/export.py + obs/analyze.py),
        # TTL-cached so a polling scraper pays the ring walk at most
        # once per 2 s.
        now = time.monotonic()
        # local read: stop() clears the attribute from another thread
        # between a scrape handler's check and its dereference
        cache = self._phase_cache
        if not fresh and cache is not None and now < cache[0]:
            sums = cache[1]
        else:
            sums = derived_summaries(self.trace_events())
            self._phase_cache = (now + 2.0, sums)
        # epoch.latency + per-node export (round 12) via the shared
        # merge helper; the cluster-only extras (injector, crypto
        # service) layer on top.
        m = merge_node_metrics(self.nodes, base=self.metrics, summaries=sums)
        if self.injector is not None:
            # injected-fault totals land in the same Prometheus dump as
            # the transport/cluster counters (faults.* gauges)
            self.injector.export_metrics(m)
        if self.crypto_service is not None and hasattr(
            self.crypto_service, "export_metrics"
        ):
            # crypto.* service plane (round 13): flush count/latency,
            # batch-size summary, queue depth, fallback totals.  The
            # RPC-mode ServiceProcess has no in-process metrics to
            # merge — its clients' crypto.rpc.* counters already ride
            # the per-node transport metrics merged above, and the
            # service process's own counters come back through its
            # stats RPC (config9 queries it directly).
            self.crypto_service.export_metrics(m)
        return m

    def trace_events(self) -> Dict[str, List[TraceEvent]]:
        """Snapshot of every trace ring, keyed by track name (the
        per-node rings plus the cluster ring when non-empty)."""
        out: Dict[str, List[TraceEvent]] = {
            buf.track: buf.snapshot() for buf in self.traces.values()
        }
        cluster_events = self.trace.snapshot()
        if cluster_events:
            out[self.trace.track] = cluster_events
        # in-thread service: the service's own ring; RPC mode: the
        # cluster-held ring the per-node clients' flush spans land on
        svc_trace = getattr(self.crypto_service, "trace", None)
        if svc_trace is None:
            svc_trace = self._cryptoplane_trace
        if svc_trace is not None:
            svc_events = svc_trace.snapshot()
            if svc_events:
                out[svc_trace.track] = svc_events
        return out

    def chrome_trace(self) -> Dict[str, Any]:
        """The merged Chrome trace-event JSON object (one track per
        node; loads in Perfetto / ``chrome://tracing``)."""
        pids = {self.traces[i].track: i for i in self.traces}
        return chrome_trace(self.trace_events(), pids=pids)

    def diag(self, stall_after_s: float = 5.0) -> Dict[str, Any]:
        """The live stall diagnosis (obs/analyze.py) over this
        cluster's rings: stalled?, the open epoch per node, which
        proposer's RBC / BA / decrypt each node is waiting on, link
        state, and a verdict naming the most-implicated (proposer,
        phase).  Served as ``/diag`` by :meth:`serve_obs`."""
        return diagnose(
            self.trace_events(), n=self.n, stall_after_s=stall_after_s
        )

    def write_trace(self, path: str) -> str:
        """Write :meth:`chrome_trace` to ``path``; returns the path."""
        import json

        with open(path, "w") as fh:
            json.dump(self.chrome_trace(), fh)
        return path

    def serve_obs(self, host: str = "127.0.0.1", port: int = 0) -> Any:
        """Start (or return) the live scrape server (``/metrics``,
        ``/trace.json``, ``/healthz``) — usable mid-run; stopped by
        :meth:`stop`."""
        if self._obs_server is None:
            from hbbft_tpu.obs.server import ObsServer

            self._obs_server = ObsServer(self, host=host, port=port).start()
        return self._obs_server

    def transport_stats(self) -> Dict[int, Dict[Any, Dict[str, int]]]:
        return {i: node.transport.stats() for i, node in self.nodes.items()}
