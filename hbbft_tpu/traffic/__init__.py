"""Traffic plane: open-loop clients, mempools, submit→commit latency.

The first subsystem that makes the stack look like a *served* system
rather than a harness (ISSUE 6): seeded open-loop client fleets
(:mod:`.clients`), bounded per-node mempools with duplicate
suppression and commit-paced release (:mod:`.mempool`), bounded-memory
latency percentiles (:mod:`.latency`), and the driver tying them to a
live :class:`~hbbft_tpu.transport.cluster.LocalCluster`
(:mod:`.driver`).  WAN link shapes live with the rest of the fault
machinery (:func:`hbbft_tpu.transport.faults.wan_profile`).  See
docs/TRANSPORT.md "traffic plane".
"""

from hbbft_tpu.traffic.clients import (
    ClientFleet,
    OpenLoopClient,
    make_txn,
    txn_id_of,
)
from hbbft_tpu.traffic.driver import TrafficDriver
from hbbft_tpu.traffic.latency import (
    QUANTILES,
    LatencyHistogram,
    LatencyRecorder,
)
from hbbft_tpu.traffic.mempool import Mempool
