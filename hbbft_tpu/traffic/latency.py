"""Per-transaction submit→commit latency with bounded memory.

The latency price of decrypt-after-order designs (PAPERS.md, arxiv
2407.12172) is only visible with a per-transaction clock: throughput
numbers cannot distinguish "fast epochs" from "transactions waiting
three extra rounds for threshold decryption".  This module is that
clock, built to run unattended next to a live cluster:

* :class:`LatencyHistogram` — log-spaced buckets (HDR style): O(1)
  insert, fixed memory, ~7% relative quantile error across seven
  decades.  No raw-observation list anywhere.
* :class:`LatencyRecorder` — the submit→commit pairing: a bounded
  in-flight map (txn_id → submit time, O(1) per transaction in
  flight, capped overall — past the cap new transactions are counted
  ``untracked`` and simply not clocked, never buffered), committing
  into the histogram, exporting through
  :meth:`hbbft_tpu.utils.metrics.Metrics.summary`.

The recorder is intentionally single-writer (the traffic driver
thread): commit attribution must pair a pop with an observe, and the
driver is the only component that sees both sides.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Optional, Tuple

from hbbft_tpu.utils.metrics import Metrics

#: Default quantiles every export publishes (the config7 JSON line and
#: the Prometheus summary share these).
QUANTILES: Tuple[float, ...] = (0.5, 0.9, 0.99)


class LatencyHistogram:
    """Log-bucketed streaming histogram: fixed memory, O(1) observe.

    Buckets are geometric: bucket k covers ``[lo * growth^k,
    lo * growth^(k+1))``, so the quantile estimate's relative error is
    bounded by ``growth - 1`` (~7% at the default) at every scale —
    the HDR-histogram idea without the library.  Values below ``lo``
    land in bucket 0; values above ``hi`` land in the last bucket;
    exact ``min``/``max`` are tracked separately and clamp the
    estimates, so the tails are never reported wider than observed.
    """

    def __init__(
        self, lo: float = 1e-4, hi: float = 3.6e3, growth: float = 1.07
    ) -> None:
        if not (0 < lo < hi) or growth <= 1.0:
            raise ValueError("need 0 < lo < hi and growth > 1")
        self._lo = lo
        self._log_growth = math.log(growth)
        self._growth = growth
        nbuckets = int(math.ceil(math.log(hi / lo) / self._log_growth)) + 1
        self._counts = [0] * nbuckets
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0

    def __len__(self) -> int:  # bounded-memory assertion hook
        return len(self._counts)

    def observe(self, v: float) -> None:
        v = max(v, 0.0)
        if v <= self._lo:
            k = 0
        else:
            k = int(math.log(v / self._lo) / self._log_growth)
            if k >= len(self._counts):
                k = len(self._counts) - 1
        self._counts[k] += 1
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1] (0.0 when empty)."""
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        for k, c in enumerate(self._counts):
            cum += c
            if cum >= rank:
                # geometric midpoint of the bucket, clamped to the
                # exact observed range
                mid = self._lo * (self._growth ** (k + 0.5))
                return min(max(mid, self.min), self.max)
        return self.max  # unreachable; defensive

    def quantiles(
        self, qs: Iterable[float] = QUANTILES
    ) -> Dict[float, float]:
        return {q: self.quantile(q) for q in qs}


class LatencyRecorder:
    """Pairs submits with commits; everything bounded.

    ``submit(txn_id, now)`` opens the clock for one transaction (False
    + ``untracked`` count when the in-flight cap is hit, or when the
    id is already open — a resubmit keeps its ORIGINAL submit time:
    end-to-end latency includes the failure the resubmit recovered
    from).  ``commit(txn_id, now)`` closes it and returns the latency,
    or None for ids not in flight (already committed, or never
    tracked) — which is exactly the driver's first-sighting test, so
    duplicate commit observations across N nodes' batch streams clock
    each transaction once.  ``drop(txn_id)`` abandons the clock for a
    transaction the mempool shed.
    """

    def __init__(
        self,
        max_inflight: int = 1 << 16,
        hist: Optional[LatencyHistogram] = None,
    ) -> None:
        self.max_inflight = max_inflight
        self.hist = hist if hist is not None else LatencyHistogram()
        self._inflight: Dict[str, float] = {}
        self.submitted = 0
        self.committed = 0
        self.dropped = 0
        self.untracked = 0

    def inflight(self) -> int:
        return len(self._inflight)

    def submit(self, txn_id: str, now: float) -> bool:
        if txn_id in self._inflight:
            return False  # resubmit: keep the original clock
        if len(self._inflight) >= self.max_inflight:
            self.untracked += 1
            return False
        self._inflight[txn_id] = now
        self.submitted += 1
        return True

    def commit(self, txn_id: str, now: float) -> Optional[float]:
        t0 = self._inflight.pop(txn_id, None)
        if t0 is None:
            return None
        dt = max(now - t0, 0.0)
        self.hist.observe(dt)
        self.committed += 1
        return dt

    def drop(self, txn_id: str) -> None:
        if self._inflight.pop(txn_id, None) is not None:
            self.dropped += 1

    def export(
        self,
        m: Metrics,
        name: str = "traffic.latency_s",
        qs: Iterable[float] = QUANTILES,
    ) -> None:
        """Publish the current percentile snapshot + flow gauges (all
        derived from ``name``, so multiple recorders exported under
        distinct names never clobber each other's gauges)."""
        m.summary(name, self.hist.quantiles(qs), self.hist.count,
                  self.hist.total)
        m.gauge(f"{name}.max", self.hist.max if self.hist.count else 0.0)
        m.gauge(f"{name}.inflight", len(self._inflight))
        m.gauge(f"{name}.untracked", self.untracked)
