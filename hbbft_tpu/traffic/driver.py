"""TrafficDriver: clients → mempools → cluster → latency accounting.

One driver thread owns the whole traffic plane of a
:class:`~hbbft_tpu.transport.cluster.LocalCluster`:

* pulls due arrivals from a :class:`~hbbft_tpu.traffic.clients.
  ClientFleet` (open-loop: the offered rate never waits for commits);
* routes each transaction to a node (default: ``client_id % n`` — one
  home node per client, so a transaction enters exactly one
  TransactionQueue and exactly-once commits are the protocol's own
  property, not a dedup artifact);
* admits into that node's :class:`~hbbft_tpu.traffic.mempool.Mempool`
  and opens the latency clock at admission — submit→commit latency
  INCLUDES mempool queueing time, which is the honest open-loop
  number (an overloaded cluster shows up as latency, not as silently
  reduced load);
* paces each mempool against its node's OWN committed batch count;
* polls every node's committed batches, attributes transactions back
  to their ids, closes latency clocks on FIRST sighting (the recorder
  pop is the first-sighting test), and fans committed ids to every
  mempool so duplicate suppression is cluster-wide.

Works identically over ``node_impl="python"`` and ``"native"``
clusters — the driver only uses the shared ClusterNode surface
(``submit`` / ``batches``).

Two drive modes:

* :meth:`run_open_loop` — wall-clock arrivals for a duration, then
  :meth:`drain` until every admitted transaction committed (or
  timeout).  Throughput + latency percentiles are meaningful;
  cross-arm batch digests are NOT (pacing races the faster arm ahead).
* :meth:`run_presubmit` — a fixed deterministic workload admitted and
  released in full BEFORE ``cluster.start()``; both node arms at one
  seed commit byte-identical streams (the config6 determinism recipe,
  now fed by the client fleet).  Latency clocks all start at release
  time, so percentiles from this mode measure commit ORDER, not
  client-visible latency — use it for identity checks and A/B
  digests, not for latency claims.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.traffic.clients import ClientFleet, txn_id_of
from hbbft_tpu.traffic.latency import LatencyRecorder
from hbbft_tpu.traffic.mempool import Mempool
from hbbft_tpu.utils.metrics import Metrics

#: One take_until sweep is bounded so a stalled driver thread cannot
#: materialize an unbounded arrival backlog in a single tick.
ARRIVALS_PER_TICK = 2_000


class TrafficDriver:
    def __init__(
        self,
        cluster: Any,
        fleet: ClientFleet,
        *,
        recorder: Optional[LatencyRecorder] = None,
        metrics: Optional[Metrics] = None,
        mempool_cap: int = 10_000,
        ahead: int = 3,
        round_txns: Optional[int] = None,
        assign: Optional[Callable[[int], int]] = None,
    ) -> None:
        self.cluster = cluster
        self.fleet = fleet
        self.recorder = recorder if recorder is not None else LatencyRecorder()
        # default: the cluster's own Metrics, so merged_metrics() shows
        # the traffic plane next to transport/cluster counters
        self.metrics = metrics if metrics is not None else cluster.metrics
        n = cluster.n
        if round_txns is None:
            # QHB proposes ~batch_size/N transactions per node per epoch
            round_txns = max(1, cluster._batch_size // n)
        self.round_txns = round_txns
        self.assign = assign if assign is not None else (lambda cid: cid % n)
        self.mempools: Dict[int, Mempool] = {
            i: Mempool(
                (lambda txn, _i=i: cluster.submit(_i, Input.user(txn))),
                cap=mempool_cap,
                round_txns=round_txns,
                ahead=ahead,
                metrics=self.metrics,
                on_drop=self.recorder.drop,
            )
            for i in cluster.nodes
        }
        self._consumed: Dict[int, int] = {i: 0 for i in cluster.nodes}
        # restart detection: kill()/restart() builds a FRESH node
        # object, so identity is the exact signal — a count-decrease
        # heuristic alone misses a reborn stream that climbed past the
        # old consumed offset between polls
        self._node_ref: Dict[int, Any] = dict(cluster.nodes)
        self.arrived = 0
        self.admitted = 0

    # -- plumbing ------------------------------------------------------
    def outstanding(self) -> int:
        """Admitted transactions not yet observed committed (queued in
        mempools + released to nodes)."""
        return sum(
            len(mp) + mp.inflight_count() for mp in self.mempools.values()
        )

    def _admit(self, cid: int, tid: str, txn: str, now: float) -> bool:
        self.arrived += 1
        node = self.assign(cid)
        if self.mempools[node].admit(tid, txn):
            self.admitted += 1
            self.recorder.submit(tid, now)
            return True
        return False

    def _check_restarts(self) -> None:
        """Exact restart detection, once per tick for BOTH consumers:
        kill()/restart() builds a fresh node object, so identity is the
        signal — the count-decrease heuristics in pace()/poll_commits
        alone miss a reborn stream that climbed past the old offset
        between polls."""
        for i in self.cluster.nodes:
            node = self.cluster.nodes[i]
            if node is not self._node_ref[i]:
                self._node_ref[i] = node
                self._consumed[i] = 0
                self.mempools[i].force_rebase()

    def pace_all(self) -> int:
        self._check_restarts()
        n = 0
        for i, mp in self.mempools.items():
            n += mp.pace(self.cluster.batch_count(i))
        return n

    def poll_commits(self, now: Optional[float] = None) -> int:
        """Scan every node's new batches; close latency clocks on first
        sighting and fan committed ids to all mempools.  Returns the
        number of transactions newly clocked."""
        if now is None:
            now = time.monotonic()
        self._check_restarts()
        newly = 0
        for i in self.cluster.nodes:
            if self.cluster.batch_count(i) < self._consumed[i]:
                # fallback for cluster impls that reuse the node object
                self._consumed[i] = 0
            # tail-only fetch: the full batch list grows forever (QHB
            # commits empty epochs continuously) and this runs every tick
            fresh = self.cluster.batches_from(i, self._consumed[i])
            self._consumed[i] += len(fresh)
            for b in fresh:
                ids: List[str] = []
                for _proposer, contrib in b.contributions:
                    if not isinstance(contrib, (list, tuple)):
                        continue
                    for txn in contrib:
                        if isinstance(txn, str):
                            ids.append(txn_id_of(txn))
                if not ids:
                    continue
                for tid in ids:
                    if self.recorder.commit(tid, now) is not None:
                        newly += 1
                for mp in self.mempools.values():
                    mp.mark_committed(ids)
        if newly:
            self.metrics.count("traffic.committed", newly)
        return newly

    def resubmit_lost(self, dead_id: int, to_id: int) -> int:
        """Fail a dead node's whole mempool backlog (released in-flight
        AND still-queued transactions) over to another node's mempool —
        the client resubmit path.  Duplicate suppression filters
        everything already observed committed; resubmitted transactions
        keep their ORIGINAL latency clock.  Let the survivors advance a
        couple of epochs and :meth:`poll_commits` BEFORE calling this,
        so commits the dead node's final proposals still produced are
        in the committed window and are not resubmitted.  (Queued
        transactions move too: a plain restart has no JoinPlan, so the
        reborn era-0 instance may never commit its own proposals.)"""
        moved = 0
        for tid, txn in self.mempools[dead_id].take_all():
            if self.mempools[to_id].admit(tid, txn):
                moved += 1
        if moved:
            self.metrics.count("traffic.resubmitted", moved)
        return moved

    # -- drive modes ---------------------------------------------------
    def run_open_loop(
        self,
        duration_s: float,
        *,
        poll_s: float = 0.02,
        drain_timeout_s: float = 45.0,
    ) -> Dict[str, Any]:
        """Offer the fleet's load for ``duration_s`` wall seconds, then
        drain.  Returns a summary dict (also exported via metrics)."""
        t0 = time.monotonic()
        while True:
            now = time.monotonic()
            el = now - t0
            if el >= duration_s:
                break
            for _vt, cid, tid, txn in self.fleet.take_until(
                el, limit=ARRIVALS_PER_TICK
            ):
                self._admit(cid, tid, txn, now)
            self.pace_all()
            self.poll_commits(time.monotonic())
            time.sleep(poll_s)
        self.drain(drain_timeout_s, poll_s=poll_s)
        wall = time.monotonic() - t0
        self.export_metrics()
        return {
            "wall_s": wall,
            "arrived": self.arrived,
            "admitted": self.admitted,
            "committed": self.recorder.committed,
            "outstanding": self.outstanding(),
        }

    def drain(self, timeout_s: float, poll_s: float = 0.02) -> bool:
        """Keep pacing/polling (no new arrivals) until every admitted
        transaction is observed committed; False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.pace_all()
            self.poll_commits()
            if self.outstanding() == 0:
                return True
            time.sleep(poll_s)
        self.pace_all()
        self.poll_commits()
        return self.outstanding() == 0

    def run_presubmit(self, total_txns: int) -> List[str]:
        """Deterministic-workload mode: admit + release the first
        ``total_txns`` fleet arrivals in full, BEFORE the cluster
        starts, so every arm's proposers see identical queues (cross-
        arm byte-identity).  Returns the admitted txn ids; the caller
        starts the cluster and then uses :meth:`drain`."""
        assert not self.cluster._started, "presubmit before cluster.start()"
        now = time.monotonic()
        ids: List[str] = []
        for _vt, cid, tid, txn in self.fleet.take(total_txns):
            if self._admit(cid, tid, txn, now):
                ids.append(tid)
        for mp in self.mempools.values():
            mp.flush_all()
        return ids

    # -- observability -------------------------------------------------
    def export_metrics(self) -> None:
        self.recorder.export(self.metrics)
        self.metrics.gauge("traffic.outstanding", self.outstanding())
        self.metrics.gauge("traffic.arrived", self.arrived)
        self.metrics.gauge("traffic.admitted", self.admitted)
