"""Deterministic open-loop transaction generators.

Every benchmark before round 10 presubmitted a fixed workload; nothing
modeled *clients*.  This module is the arrival process of the traffic
plane: many simulated users, each a seeded :class:`OpenLoopClient`
emitting tagged transactions at a Poisson or fixed rate, merged into
one deterministic arrival stream by :class:`ClientFleet`.

Open-loop means arrivals never wait for commits — the load offered to
the cluster is a property of the clients, not of the cluster's speed
(the closed-loop alternative hides overload by slowing the offered
rate down to whatever the system sustains).  Backpressure is the
*mempool's* job (:mod:`hbbft_tpu.traffic.mempool`): the arrival stream
here is pure data.

Transaction format: ``"c{client}.{seq}"`` (+ ``"#"`` padding when a
payload size is requested), so every committed transaction is
attributable back to exactly one (client, seq) pair — the handle the
submit→commit latency clock keys on.  Plain strings: they serde-encode
(``QueueingHoneyBadger`` validates at push) and compare across the
Python and native node arms byte-identically.

Clocks: arrival timestamps are virtual seconds from stream start.  A
wall-clock driver releases arrivals whose timestamp has elapsed
(:meth:`ClientFleet.take_until`); a deterministic workload takes the
first n arrivals with no clock at all (:meth:`ClientFleet.take` — the
mode cross-arm byte-identity tests use).
"""

from __future__ import annotations

import heapq
import random
from typing import List, Optional, Tuple


def txn_id_of(txn: str) -> str:
    """The attributable id of a traffic-plane transaction (strips the
    payload padding).  Foreign transactions pass through unchanged —
    callers treat unknown ids as not-ours."""
    return txn.split("#", 1)[0]


def make_txn(client: int, seq: int, payload_len: int = 0) -> str:
    tid = f"c{client}.{seq}"
    if payload_len > 0:
        return tid + "#" + "x" * payload_len
    return tid


class OpenLoopClient:
    """One simulated user: seeded arrival process + monotone sequence.

    ``arrival="poisson"`` draws i.i.d. exponential interarrivals (mean
    ``1/rate_tps``); ``"fixed"`` emits exactly every ``1/rate_tps``
    virtual seconds.  The rng is seeded by ``(seed, client_id)`` so a
    fleet's stream is reproducible client-by-client regardless of how
    the merge interleaves draws.
    """

    def __init__(
        self,
        client_id: int,
        rate_tps: float,
        seed: int = 0,
        arrival: str = "poisson",
        payload_len: int = 0,
    ) -> None:
        if rate_tps <= 0:
            raise ValueError("rate_tps must be > 0")
        if arrival not in ("poisson", "fixed"):
            raise ValueError(f"unknown arrival process {arrival!r}")
        self.client_id = client_id
        self.rate_tps = rate_tps
        self.arrival = arrival
        self.payload_len = payload_len
        self._rng = random.Random(f"traffic-client|{seed}|{client_id}")
        self._t = 0.0
        self._seq = 0

    def next(self) -> Tuple[float, str, str]:
        """The next arrival: ``(virtual_time_s, txn_id, txn)``."""
        if self.arrival == "poisson":
            self._t += self._rng.expovariate(self.rate_tps)
        else:
            self._t += 1.0 / self.rate_tps
        txn = make_txn(self.client_id, self._seq, self.payload_len)
        self._seq += 1
        return (self._t, txn_id_of(txn), txn)


class ClientFleet:
    """Many clients merged into one deterministic arrival stream.

    The merge is a heap on ``(virtual_time, client_id)`` — client id
    breaks timestamp ties — so the stream order is a pure function of
    ``(num_clients, rate, seed, arrival)``: the property the
    deterministic-workload byte-identity tests stand on.
    """

    def __init__(
        self,
        num_clients: int,
        rate_tps_each: float,
        seed: int = 0,
        arrival: str = "poisson",
        payload_len: int = 0,
    ) -> None:
        if num_clients < 1:
            raise ValueError("num_clients must be >= 1")
        self.clients = [
            OpenLoopClient(
                cid, rate_tps_each, seed=seed, arrival=arrival,
                payload_len=payload_len,
            )
            for cid in range(num_clients)
        ]
        # one buffered next-arrival per client, merged lazily
        self._heap: List[Tuple[float, int, str, str]] = []
        for c in self.clients:
            t, tid, txn = c.next()
            heapq.heappush(self._heap, (t, c.client_id, tid, txn))

    @property
    def offered_tps(self) -> float:
        return sum(c.rate_tps for c in self.clients)

    def _pop(self) -> Tuple[float, int, str, str]:
        t, cid, tid, txn = heapq.heappop(self._heap)
        nt, ntid, ntxn = self.clients[cid].next()
        heapq.heappush(self._heap, (nt, cid, ntid, ntxn))
        return (t, cid, tid, txn)

    def take_until(
        self, t: float, limit: Optional[int] = None
    ) -> List[Tuple[float, int, str, str]]:
        """All arrivals with virtual timestamp <= ``t`` (wall-clock
        drivers call this each poll tick).  ``limit`` bounds one call
        so a stalled driver cannot materialize an unbounded backlog in
        one sweep — the remainder stays buffered for the next tick."""
        out: List[Tuple[float, int, str, str]] = []
        while self._heap[0][0] <= t:
            out.append(self._pop())
            if limit is not None and len(out) >= limit:
                break
        return out

    def take(self, n: int) -> List[Tuple[float, int, str, str]]:
        """The first ``n`` arrivals in stream order (virtual clock only
        — the deterministic-workload mode)."""
        return [self._pop() for _ in range(n)]
