"""Bounded per-node admission queue with pacing and dup suppression.

This is the ``drive()`` lesson from tests/test_transport.py promoted
into a real component: QueueingHoneyBadger commits empty epochs
continuously, and an unpaced feeder builds a transaction backlog that
keeps epochs churning long after the offered load stopped.  The
mempool sits between the traffic plane and ``ClusterNode.submit``
(i.e. in front of ``SenderQueue.push`` on the protocol thread) and
holds three rules:

* **bounded admission** — a deque capped at ``cap``; overflow drops
  the OLDEST queued transaction (counted, ``traffic.mempool_overflow``
  + an ``on_drop`` callback so the latency clock abandons it).  Oldest,
  not newest: under sustained overload the oldest queued transaction
  is the one whose latency target is already blown, and an open-loop
  client will resubmit what it still cares about.
* **duplicate suppression** — a transaction id is admitted at most
  once across queued / released-in-flight / recently-committed states
  (``traffic.dup_suppressed``).  The committed side is a bounded LRU
  (``committed_cache``), not an ever-growing set: resubmits arrive
  within a failure-recovery window, so a recency window is the right
  memory/coverage trade — evictions are counted
  (``traffic.committed_evicted``) so a too-small cache is visible.
* **pacing** — :meth:`pace` releases at most ``round_txns`` per
  committed batch plus an ``ahead`` allowance, keyed on the node's OWN
  committed count, with automatic rebase when that count goes
  backwards (the node was restarted with wiped state).

Single-writer by design: the traffic driver thread is the only caller
(admit/pace/mark_committed all mutate the same structures; the node's
``submit`` target is itself thread-safe).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.utils.metrics import Metrics


class Mempool:
    def __init__(
        self,
        submit: Callable[[Any], None],
        *,
        cap: int = 10_000,
        round_txns: int = 2,
        ahead: int = 3,
        committed_cache: int = 1 << 16,
        metrics: Optional[Metrics] = None,
        name: str = "traffic",
        on_drop: Optional[Callable[[str], None]] = None,
    ) -> None:
        if cap < 1 or round_txns < 1 or ahead < 0:
            raise ValueError("cap/round_txns >= 1 and ahead >= 0")
        self._submit = submit
        self.cap = cap
        self.round_txns = round_txns
        self.ahead = ahead
        self.metrics = metrics if metrics is not None else Metrics()
        self.name = name
        self.on_drop = on_drop
        self._queue: "collections.deque[Tuple[str, Any]]" = collections.deque()
        self._queued: set = set()
        # released to the node, commit not yet observed (txn kept for
        # the resubmit drill; bounded by pacing in steady state, and
        # drained by take_all() when a node dies holding some)
        self._released: Dict[str, Any] = {}
        # recently-committed LRU for resubmit suppression
        self._committed: "collections.OrderedDict[str, None]" = (
            collections.OrderedDict()
        )
        self._committed_cap = committed_cache
        self.released_count = 0
        # pacing base: rebased when the node's committed count resets
        self._base_released = 0
        self._base_committed = 0
        self._last_committed = 0

    # -- admission -----------------------------------------------------
    def __len__(self) -> int:
        return len(self._queued)  # live entries (tombstones excluded)

    def admit(self, txn_id: str, txn: Any) -> bool:
        """Admit one transaction; False = suppressed as a duplicate.
        May shed the oldest queued transaction to stay under ``cap``."""
        if (
            txn_id in self._queued
            or txn_id in self._released
            or txn_id in self._committed
        ):
            self.metrics.count(f"{self.name}.dup_suppressed")
            return False
        while len(self._queued) >= self.cap:
            old_id, _ = self._queue.popleft()
            if old_id not in self._queued:
                continue  # tombstone (committed elsewhere while queued)
            self._queued.discard(old_id)
            self.metrics.count(f"{self.name}.mempool_overflow")
            if self.on_drop is not None:
                self.on_drop(old_id)
        self._queue.append((txn_id, txn))
        self._queued.add(txn_id)
        return True

    # -- pacing --------------------------------------------------------
    def pace(self, committed: int) -> int:
        """Release queued transactions against the node's committed
        batch count; returns how many were submitted this call.

        Budget: ``(committed_since_base + ahead) * round_txns``
        releases since base.  A committed count LOWER than the last
        one observed means the node restarted with wiped state — the
        budget is rebased so the fresh instance is fed again instead
        of being starved by the old instance's released total.
        """
        if committed < self._last_committed:
            self._base_released = self.released_count
            self._base_committed = committed
        self._last_committed = committed
        budget = (
            (committed - self._base_committed + self.ahead) * self.round_txns
        )
        n = 0
        while (
            self.released_count - self._base_released
        ) < budget and self._release_one():
            n += 1
        return n

    def _release_one(self) -> bool:
        """Release the next live queued transaction to the node (the
        ONE copy of the release bookkeeping — pace and flush_all both
        go through here).  False when nothing live is queued."""
        while self._queue:
            txn_id, txn = self._queue.popleft()
            if txn_id not in self._queued:
                continue  # tombstone (committed elsewhere while queued)
            self._queued.discard(txn_id)
            self._released[txn_id] = txn
            self.released_count += 1
            self._submit(txn)
            return True
        return False

    def flush_all(self) -> int:
        """Release EVERYTHING queued, ignoring the pacing budget, then
        rebase the budget so later :meth:`pace` calls are unaffected.
        This is the deterministic-workload (presubmit) mode — the
        whole point of pacing is moot when the workload is admitted
        before the cluster starts."""
        n = 0
        while self._release_one():
            n += 1
        self._base_released = self.released_count
        return n

    def force_rebase(self) -> None:
        """Rebase the pacing budget at the next :meth:`pace` call.  The
        driver calls this on exact restart detection (node identity
        changed) — a reborn node's committed count may never VISIBLY
        decrease if it climbed past the old count between polls, so the
        count-decrease heuristic inside pace() alone can compute the
        budget from the dead instance's base."""
        self._last_committed = float("inf")

    # -- commit / failure feedback ------------------------------------
    def mark_committed(self, txn_ids: List[str]) -> None:
        """Record observed commits (the driver fans every commit to ALL
        mempools, so the dup-suppression window is cluster-wide)."""
        for tid in txn_ids:
            self._released.pop(tid, None)
            # committed elsewhere while still queued here (a resubmit
            # raced its original): tombstone — pace()/admit() skip
            # deque entries whose id left _queued
            self._queued.discard(tid)
            self._committed[tid] = None
            self._committed.move_to_end(tid)
            while len(self._committed) > self._committed_cap:
                self._committed.popitem(last=False)
                self.metrics.count(f"{self.name}.committed_evicted")

    def inflight_count(self) -> int:
        """Released-but-uncommitted count (O(1): the driver sums this
        every poll tick — materializing the items just for len() is
        per-tick garbage)."""
        return len(self._released)

    def inflight_released(self) -> List[Tuple[str, Any]]:
        """Released-but-uncommitted transactions (what a client must
        consider resubmitting after this node dies)."""
        return list(self._released.items())

    def take_all(self) -> List[Tuple[str, Any]]:
        """Drain EVERYTHING (released in-flight first, then queued) —
        the full failover path when this mempool's node died.  A
        restarted node re-joins with wiped protocol state (era 0), and
        a plain restart has no JoinPlan, so routing held-back
        transactions to the reborn instance may never commit them;
        the traffic plane fails the whole backlog over instead."""
        out = list(self._released.items())
        self._released.clear()
        while self._queue:
            txn_id, txn = self._queue.popleft()
            if txn_id in self._queued:
                out.append((txn_id, txn))
        self._queued.clear()
        return out
