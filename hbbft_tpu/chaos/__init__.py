"""Byzantine chaos plane (ISSUE 7).

Live-socket adversary node arms for the TCP cluster (crash-stop,
equivocation, corrupt shares, stale replay, garbage flooding — all
speaking the real wire protocol over the untouched transport), a
seeded scenario scheduler composing Byzantine strategies with link
faults and kill/restart churn, and safety/liveness oracles over the
honest side.  See docs/TRANSPORT.md "Byzantine drills & chaos tier".
"""

from hbbft_tpu.chaos.nodes import install_byzantine
from hbbft_tpu.chaos.oracle import (
    ChaosOracle,
    batches_sha,
    fault_entries,
    stream_txns,
)
from hbbft_tpu.chaos.scheduler import ChaosEvent, ChaosRunner, build_schedule
from hbbft_tpu.chaos.strategies import (
    EQUIVOCABLE_KINDS,
    SHARE_KINDS,
    STRATEGIES,
    ByzantineStrategy,
    CorruptShareSender,
    CrashStop,
    Equivocator,
    GarbageFlooder,
    StaleReplayer,
    StrategyContext,
    make_strategy,
    tamper_payload,
)
