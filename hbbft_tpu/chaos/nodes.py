"""Byzantine node arms: arm a live cluster node with a strategy.

Both ``node_impl`` arms keep their REAL protocol stack and their
untouched :class:`~hbbft_tpu.transport.transport.TcpTransport` — the
Byzantine behavior is installed at the one seam both arms share, the
transport's send surface:

* the Python :class:`~hbbft_tpu.transport.cluster.ClusterNode` emits
  via per-message ``transport.send(dest, payload)``;
* the native :class:`~hbbft_tpu.transport.native_node.
  NativeClusterNode` emits via batched ``transport.send_many(items)``
  and, on the round-20 coalescing fast path, pre-packed MSGB bodies
  via ``transport.send_wire`` / ``transport.send_msgb`` (unpacked here
  so strategies keep seeing logical messages).

:func:`install_byzantine` wraps both entry points on the node's OWN
transport instance (nobody else sends through it), mapping every
``(dest, payload)`` through ``strategy.on_egress`` and appending
``strategy.extra_frames()`` once per send call/batch.  The wrapped
calls run on the node's protocol thread only, so strategies need no
locking.

The corrupt-share strategy on the native arm instead installs the
engine tamper hooks (``hbe_set_tamper`` + ``hbe_set_tampered``): the
rewrite happens on the engine's outgoing-message clone before the C
encoder, exactly like :class:`~hbbft_tpu.net.adversary.
TamperingAdversary` runs in-process (the engine's ``outgoing()`` path
tampers the shared clone once per logical message, cluster mode
included).  The ``tampered`` flag survives ``hbe_restart_node``, so
era changes keep the node Byzantine.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Tuple

from hbbft_tpu.chaos.strategies import (
    ByzantineStrategy,
    StrategyContext,
    make_strategy,
)


def install_byzantine(
    node: Any,
    spec: Any,
    *,
    seed: int,
    suite: Any,
    cluster_id: bytes,
    peer_addrs: Dict[Any, Tuple[str, int]],
    impl: str = "python",
) -> Any:
    """Arm ``node`` (ClusterNode or NativeClusterNode) with a Byzantine
    strategy; returns the node.  Called by ``LocalCluster._make_node``
    for every id in its ``byzantine`` map — including on restart(), so
    a reborn Byzantine node is re-armed with fresh per-bind state."""
    strategy = make_strategy(spec)
    ctx = StrategyContext(
        node_id=node.id,
        peer_ids=sorted(peer_addrs),
        peer_addrs=dict(peer_addrs),
        cluster_id=cluster_id,
        suite=suite,
        rng=random.Random(f"chaos|{seed}|{node.id}|{strategy.name}"),
        metrics=node.metrics,
        impl=impl,
    )
    strategy.bind(ctx)
    node.byzantine_strategy = strategy
    if impl == "native" and strategy.native_tamper:
        _install_native_tamper(node, strategy)
    else:
        _wrap_transport(node, strategy)
    return node


def _wrap_transport(node: Any, strategy: ByzantineStrategy) -> None:
    t = node.transport
    orig_send, orig_send_many = t.send, t.send_many

    def send(dest: Any, payload: bytes) -> None:
        for d, p in strategy.on_egress(dest, payload):
            orig_send(d, p)
        for d, p in strategy.extra_frames():
            orig_send(d, p)

    def send_many(items):
        out = []
        for dest, payload in items:
            out.extend(strategy.on_egress(dest, payload))
        out.extend(strategy.extra_frames())
        if out:
            orig_send_many(out)

    def send_msgb(dest: Any, body: bytes, count: int) -> None:
        # The round-20 native fast path emits pre-packed MSGB bodies;
        # strategies operate on logical messages, so unpack here and
        # route through the wrapped send_many (which re-coalesces the
        # survivors).  decode_msgb is cheap next to the strategy work.
        from hbbft_tpu.transport.framing import decode_msgb

        send_many([(dest, p) for p in decode_msgb(body)])

    def send_wire(records) -> None:
        # Whole-sweep fast path (round 20): same unpacking stance as
        # send_msgb — flatten every record to logical messages and let
        # the wrapped send_many re-coalesce the survivors.
        from hbbft_tpu.transport.framing import decode_msgb

        flat = []
        for dest, count, data in records:
            if count <= 1:
                flat.append((dest, data))
            else:
                flat.extend((dest, p) for p in decode_msgb(data))
        send_many(flat)

    t.send, t.send_many = send, send_many
    t.send_msgb, t.send_wire = send_msgb, send_wire


def _install_native_tamper(node: Any, strategy: ByzantineStrategy) -> None:
    from hbbft_tpu.native_engine import _TAMPER_CB

    eng = node.engine
    cb = strategy.native_tamper_cb(eng)
    # the ctypes callback object must outlive the engine
    node._chaos_tamper_cb = _TAMPER_CB(cb)
    eng.lib.hbe_set_tamper(eng.handle, node._chaos_tamper_cb)
    eng.lib.hbe_set_tampered(eng.handle, node.id, 1)
