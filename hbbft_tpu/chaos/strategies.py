"""Byzantine strategies: adversary arms that speak the real wire.

Every strategy operates at the WIRE boundary of one cluster node — the
serde-encoded ``SqMessage`` payloads the node hands its (untouched)
:class:`~hbbft_tpu.transport.transport.TcpTransport` — so one strategy
implementation serves both ``node_impl`` arms: the Python node's
per-message ``transport.send`` and the native node's batched
``transport.send_many`` are wrapped identically
(:func:`hbbft_tpu.chaos.nodes.install_byzantine`).  The one exception
is the corrupt-share sender on the NATIVE arm, which reuses the
engine's tamper hooks (``hbe_set_tamper`` / ``hbe_set_tampered``, the
round-7 :class:`~hbbft_tpu.net.adversary.TamperingAdversary` mirror)
so the rewrite happens before the C encoder, exactly like the
in-process tampering runs.

The strategy catalog (ISSUE 7):

* **crash-stop** — behaves honestly, then falls silent forever at a
  deadline (the weakest Byzantine class; the cluster must not notice
  beyond f-tolerance).
* **equivocate** — splits the peers into two fixed halves and sends
  CONFLICTING protocol messages per half: one gets the honest
  message, the other a :class:`TamperingAdversary`-rewritten variant
  (flipped BVal/Aux, corrupted Echo proofs/roots...).  Safety is the
  target: honest nodes must still commit identical batches.
* **corrupt-share** — wrong-but-well-formed COIN/DECRYPT threshold
  shares (doubled scalars), the class the share-verification plane
  must detect AND attribute (fault logs name the sender).
* **stale-replay** — re-sends its own old traffic (earlier epochs);
  peers' epoch gates must drop it without damage.
* **flood** — garbage at two layers: framing-valid serde garbage
  through its own transport (the ``cluster.bad_payload`` path) and
  raw-socket CRC-corrupt frames under its own HELLO identity (the
  ``transport.frame_errors`` -> misbehavior-strike -> escalating-ban
  path).

Determinism: each strategy draws every decision from a
``random.Random`` seeded by ``(cluster seed, node id, strategy name)``
(:class:`StrategyContext`), so a strategy's decision stream is a pure
function of its own egress order.  The chaos plane adds NO new serde
structs or frame kinds — everything it emits is either existing
registered wire traffic or deliberately-invalid bytes, so the HBT005
wire-tag classification is unchanged.

Thread-safety: a strategy instance belongs to ONE node and is only
ever called from that node's protocol thread.  All mutable state is
created in :meth:`ByzantineStrategy.bind` so a restarted node re-arms
from a clean slate.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import socket
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from hbbft_tpu.net.adversary import TamperingAdversary
from hbbft_tpu.protocols.sender_queue import SqMessage
from hbbft_tpu.transport.framing import KIND_MSG, encode_frame, encode_hello
from hbbft_tpu.utils import serde
from hbbft_tpu.utils.metrics import Metrics

#: Leaf message types whose rewrite yields a *conflicting* (equivocating)
#: variant — the BVAL/Echo family plus the root/proof carriers.
EQUIVOCABLE_KINDS = frozenset(
    {
        "BValMsg", "AuxMsg", "ConfMsg", "TermMsg",
        "ReadyMsg", "EchoHashMsg", "CanDecodeMsg", "ValueMsg", "EchoMsg",
    }
)

#: Leaf message types carrying threshold shares (COIN / DECRYPT).
SHARE_KINDS = frozenset({"SignMessage", "DecryptMessage"})

_VARIANT_CACHE_MAX = 4096


def _cache_put(cache: Dict[Any, Any], key: Any, value: Any) -> None:
    cache[key] = value
    if len(cache) > _VARIANT_CACHE_MAX:
        cache.pop(next(iter(cache)))


def _rewrite(obj: Any, rng: Any, adv: TamperingAdversary,
             kinds: frozenset) -> Any:
    """Recurse into the envelope chain like TamperingAdversary._tamper,
    but rewrite ONLY leaves whose type name is in ``kinds`` (the stock
    adversary rewrites the first leaf of any type it knows)."""
    if type(obj).__name__ in kinds:
        return adv._tamper(obj, rng)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        changes = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            nv = _rewrite(v, rng, adv, kinds)
            if nv is not v:
                changes[f.name] = nv
        if changes:
            return dataclasses.replace(obj, **changes)
    return obj


def tamper_payload(
    data: bytes, rng: Any, suite: Any, kinds: Iterable[str]
) -> Optional[bytes]:
    """Decode one wire payload, rewrite its innermost protocol content
    with the stock :class:`TamperingAdversary` mutations (restricted to
    leaf types named in ``kinds``), and re-encode.  Returns None when
    the payload is not an SqMessage or carries none of the targeted
    leaves — the variant, when returned, is VALID wire traffic (well-
    formed, wrong contents): the hardest Byzantine class."""
    msg = serde.try_loads(data, suite=suite)
    if not isinstance(msg, SqMessage):
        return None
    adv = TamperingAdversary(tamper_p=1.0)
    out = _rewrite(msg, rng, adv, frozenset(kinds))
    if out is msg:
        return None
    return serde.dumps(out)


@dataclass
class StrategyContext:
    """Everything a strategy may touch, handed over at bind time."""

    node_id: Any
    peer_ids: List[Any]
    peer_addrs: Dict[Any, Tuple[str, int]]
    cluster_id: bytes
    suite: Any
    rng: random.Random
    metrics: Metrics = field(default_factory=Metrics)
    impl: str = "python"


class ByzantineStrategy:
    """Base: an honest node (identity mapping on egress)."""

    name = "byzantine"
    #: True = on the native arm, install the engine tamper hooks
    #: instead of the wire-level wrapper (corrupt-share only).
    native_tamper = False

    def bind(self, ctx: StrategyContext) -> None:
        """(Re)arm against one node instance; all mutable state is
        created here so restart() starts clean."""
        self.ctx = ctx

    def on_egress(
        self, dest: Any, payload: bytes
    ) -> Iterable[Tuple[Any, bytes]]:
        """Map one outgoing ``(dest, payload)`` to the frames actually
        sent (empty = suppressed)."""
        return ((dest, payload),)

    def extra_frames(self) -> Iterable[Tuple[Any, bytes]]:
        """Additional frames to inject this egress sweep (the strategy
        rate-limits itself)."""
        return ()


class CrashStop(ByzantineStrategy):
    """Honest until ``after_s`` past its first emission, then silent
    forever (still receives and ACKs — a zombie, which is the harder
    variant of crash for the peers' resume layers)."""

    name = "crash-stop"

    def __init__(self, after_s: float = 0.75) -> None:
        self.after_s = after_s

    def bind(self, ctx: StrategyContext) -> None:
        super().bind(ctx)
        self._deadline: Optional[float] = None
        self._crashed = False

    def on_egress(self, dest, payload):
        now = time.monotonic()
        if self._deadline is None:
            self._deadline = now + self.after_s
        if now >= self._deadline:
            if not self._crashed:
                self._crashed = True
                self.ctx.metrics.count("chaos.crash_stopped")
            return ()
        return ((dest, payload),)


class Equivocator(ByzantineStrategy):
    """Conflicting messages per peer: a fixed half of the peers gets
    the honest payload, the other half a tampered-but-well-formed
    variant of the SAME logical message.  The variant is computed once
    per distinct payload (a broadcast is one logical message however
    many ``send`` calls carry it)."""

    name = "equivocate"

    def __init__(self, eq_p: float = 1.0) -> None:
        self.eq_p = eq_p

    def bind(self, ctx: StrategyContext) -> None:
        super().bind(ctx)
        ids = list(ctx.peer_ids)
        ctx.rng.shuffle(ids)
        self._flip = frozenset(ids[: max(1, len(ids) // 2)])
        self._variants: Dict[bytes, Optional[bytes]] = {}

    def _variant(self, payload: bytes) -> Optional[bytes]:
        if payload not in self._variants:
            rng = self.ctx.rng
            v = None
            if rng.random() < self.eq_p:
                v = tamper_payload(
                    payload, rng, self.ctx.suite, EQUIVOCABLE_KINDS
                )
            _cache_put(self._variants, payload, v)
            if v is not None:
                self.ctx.metrics.count("chaos.equivocated")
        return self._variants[payload]

    def on_egress(self, dest, payload):
        v = self._variant(payload)
        if v is not None and dest in self._flip:
            return ((dest, v),)
        return ((dest, payload),)


class CorruptShareSender(ByzantineStrategy):
    """Wrong-but-well-formed COIN/DECRYPT shares with probability
    ``tamper_p`` per logical message — the TamperingAdversary share
    mutations (doubled scalar/point), applied at the wire boundary on
    the Python arm and through the engine tamper hooks on the native
    arm (``native_tamper``).  All peers see the SAME corrupt share, so
    honest fault logs must converge on this sender."""

    name = "corrupt-share"
    native_tamper = True

    #: engine MsgType values (native/engine.cpp): BA_COIN / HB_DECRYPT
    _MT_COIN, _MT_DECRYPT = 8, 10

    def __init__(self, tamper_p: float = 0.5) -> None:
        self.tamper_p = tamper_p

    def bind(self, ctx: StrategyContext) -> None:
        super().bind(ctx)
        self._variants: Dict[bytes, Optional[bytes]] = {}

    def on_egress(self, dest, payload):
        if payload not in self._variants:
            rng = self.ctx.rng
            v = None
            if rng.random() < self.tamper_p:
                v = tamper_payload(payload, rng, self.ctx.suite, SHARE_KINDS)
            _cache_put(self._variants, payload, v)
            if v is not None:
                self.ctx.metrics.count("chaos.tampered_shares")
        v = self._variants[payload]
        return ((dest, v if v is not None else payload),)

    def native_tamper_cb(self, engine: Any):
        """Build the engine tamper callback (shares are 32-byte
        big-endian scalars — NativeNodeEngine is scalar-suite-only by
        contract, so the ``ln != 32`` guard below is defensive, not a
        reachable silent no-op; the rewrite is the sanitizer driver's
        ``2*s mod r``).  Must never raise across ctypes."""
        import ctypes

        lib, h = engine.lib, engine.handle
        rng = self.ctx.rng
        mod = engine._suite.scalar_modulus
        metrics = self.ctx.metrics

        def cb(sender, mtype, era, epoch, proposer, rnd):
            try:
                if mtype not in (self._MT_COIN, self._MT_DECRYPT):
                    return
                if rng.random() >= self.tamper_p:
                    return
                ln = int(lib.hbe_tamper_share_len(h))
                if ln != 32:
                    return
                buf = (ctypes.c_uint8 * 32)()
                lib.hbe_tamper_share(h, buf)
                s = int.from_bytes(bytes(buf), "big")
                out = (2 * s % mod).to_bytes(32, "big")
                ob = (ctypes.c_uint8 * 32).from_buffer_copy(out)
                lib.hbe_tamper_set_share(h, ob, 32)
                metrics.count("chaos.tampered_shares")
            except Exception:  # pragma: no cover - defensive
                metrics.count("chaos.strategy_errors")

        return cb


class StaleReplayer(ByzantineStrategy):
    """Re-sends its own recorded traffic from earlier epochs: replayed
    frames are consumed and ACKed like any frame, then must die at the
    peers' epoch gates (``dropped_stale`` / protocol dedup) without
    disturbing agreement."""

    name = "stale-replay"

    def __init__(self, replay_p: float = 0.3, history: int = 512) -> None:
        self.replay_p = replay_p
        self.history = history

    def bind(self, ctx: StrategyContext) -> None:
        super().bind(ctx)
        self._hist: "collections.deque" = collections.deque(
            maxlen=self.history
        )

    def on_egress(self, dest, payload):
        self._hist.append((dest, payload))
        return ((dest, payload),)

    def extra_frames(self):
        if len(self._hist) < 64:
            return ()
        rng = self.ctx.rng
        if rng.random() >= self.replay_p:
            return ()
        # oldest half of the window = the stalest epochs we still hold
        dest, payload = self._hist[rng.randrange(len(self._hist) // 2)]
        self.ctx.metrics.count("chaos.replayed")
        return ((dest, payload),)


class GarbageFlooder(ByzantineStrategy):
    """Garbage at both layers of the read path:

    * framing-VALID serde garbage through its own transport — lands in
      the peers' ``cluster.bad_payload`` codec rejections;
    * raw-socket CRC-corrupt frames under its own HELLO identity — the
      frame decoder drops the connection, charges a misbehavior strike,
      and the escalating reconnect ban prices the loop
      (``max_raw`` bounds it so the strategy's own honest-traffic
      identity is not banned into uselessness forever).
    """

    name = "flood"

    def __init__(
        self, garbage_p: float = 0.3, raw_p: float = 0.05, max_raw: int = 8
    ) -> None:
        self.garbage_p = garbage_p
        self.raw_p = raw_p
        self.max_raw = max_raw

    def bind(self, ctx: StrategyContext) -> None:
        super().bind(ctx)
        self._raw_sent = 0

    def extra_frames(self):
        rng = self.ctx.rng
        out: List[Tuple[Any, bytes]] = []
        if rng.random() < self.garbage_p:
            dest = self.ctx.peer_ids[rng.randrange(len(self.ctx.peer_ids))]
            mode = rng.randrange(3)
            if mode == 0:  # valid serde, not an SqMessage
                junk = serde.dumps(rng.randrange(1 << 30))
            elif mode == 1:  # valid serde tree, still not an SqMessage
                junk = serde.dumps((b"chaos", [rng.randrange(255)]))
            else:  # not serde at all
                junk = bytes(
                    rng.randrange(256) for _ in range(rng.randrange(1, 48))
                )
            out.append((dest, junk))
            self.ctx.metrics.count("chaos.garbage_payloads")
        if self._raw_sent < self.max_raw and rng.random() < self.raw_p:
            self._send_raw_corrupt_frame(rng)
        return out

    def _send_raw_corrupt_frame(self, rng: random.Random) -> None:
        dest = self.ctx.peer_ids[rng.randrange(len(self.ctx.peer_ids))]
        addr = self.ctx.peer_addrs[dest]
        frame = bytearray(encode_frame(KIND_MSG, b"chaos-junk"))
        # flip a body bit: the CRC check fails at the peer's decoder
        frame[8 + rng.randrange(len(frame) - 8)] ^= 1 << rng.randrange(8)
        try:
            with socket.create_connection(addr, timeout=0.5) as s:
                s.sendall(
                    encode_hello(self.ctx.node_id, self.ctx.cluster_id)
                    + bytes(frame)
                )
        except OSError:
            return  # peer offline/banned us: the loop being priced IS the point
        self._raw_sent += 1
        self.ctx.metrics.count("chaos.raw_corrupt_frames")


STRATEGIES = {
    CrashStop.name: CrashStop,
    Equivocator.name: Equivocator,
    CorruptShareSender.name: CorruptShareSender,
    StaleReplayer.name: StaleReplayer,
    GarbageFlooder.name: GarbageFlooder,
}


def make_strategy(spec: Any) -> ByzantineStrategy:
    """Resolve a LocalCluster ``byzantine`` spec: a registry name, a
    strategy instance (bind() re-arms it), or a zero-arg factory."""
    if isinstance(spec, ByzantineStrategy):
        return spec
    if isinstance(spec, str):
        cls = STRATEGIES.get(spec)
        if cls is None:
            raise ValueError(
                f"unknown Byzantine strategy {spec!r} "
                f"(known: {sorted(STRATEGIES)})"
            )
        return cls()
    if callable(spec):
        s = spec()
        if not isinstance(s, ByzantineStrategy):
            raise ValueError("strategy factory must return a ByzantineStrategy")
        return s
    raise ValueError(f"bad Byzantine strategy spec: {spec!r}")
