"""Seeded chaos schedules: Byzantine × link faults × churn, composed.

A schedule is a time-budgeted list of :class:`ChaosEvent`s fired
against a live :class:`~hbbft_tpu.transport.cluster.LocalCluster`:
kill/restart (process death + rebirth), disconnect/reconnect (network
outage around a live process), partition/heal (injector windows).  The
WAN link *shape* composes orthogonally — it lives in the
:class:`~hbbft_tpu.transport.faults.FaultInjector` the cluster was
built with (``wan_profile``), while this module drives the injector's
partition windows dynamically.

**Fault-budget discipline:** every disruptive event targets a
BYZANTINE id.  The Byzantine nodes already spend the cluster's f
budget; killing or isolating an honest node on top would exceed 3f+1
tolerance and make a liveness assertion vacuous (any stall would be
"expected").  Composed chaos therefore means: the adversary nodes
misbehave AND churn AND get partitioned, over WAN-shaped links, while
the honest quorum must keep committing — which is exactly the claim
HoneyBadgerBFT makes.

Determinism: :func:`build_schedule` is a pure function of its seed (a
dedicated ``random.Random`` stream, no wall clock), so a chaos test
names its scenario by ``(seed, duration)`` alone.  Event *firing*
happens at wall offsets from :meth:`ChaosRunner.start` — coarse
seconds, like the injector's partition windows.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Iterable, List, Optional, Sequence

from hbbft_tpu.transport.faults import PartitionSpec

#: Event kinds that need a later counter-event to restore liveness.
_PAIRED = {"kill": "restart", "disconnect": "reconnect", "partition": "heal"}


@dataclass(frozen=True)
class ChaosEvent:
    at_s: float          # offset from runner start
    kind: str            # kill | restart | disconnect | reconnect | partition | heal
    node: Optional[int] = None


def build_schedule(
    seed: int,
    byzantine_ids: Sequence[int],
    duration_s: float,
    *,
    churn: bool = True,
    outage: bool = False,
    partition: bool = True,
) -> List[ChaosEvent]:
    """One composed schedule inside ``[0, duration_s]``: optionally a
    kill→restart, a disconnect→reconnect, and a partition→heal, each
    against a seeded-chosen Byzantine id, at seeded offsets.  Pure in
    ``seed`` — same seed, same schedule."""
    rng = random.Random(f"chaos-schedule|{seed}")
    ids = sorted(byzantine_ids)
    ev: List[ChaosEvent] = []
    if not ids:
        return ev

    def pick() -> int:
        return ids[rng.randrange(len(ids))]

    if churn:
        t0 = duration_s * (0.10 + 0.15 * rng.random())
        dt = duration_s * (0.10 + 0.15 * rng.random())
        v = pick()
        ev += [ChaosEvent(t0, "kill", v), ChaosEvent(t0 + dt, "restart", v)]
    if outage:
        t0 = duration_s * (0.35 + 0.15 * rng.random())
        dt = duration_s * (0.08 + 0.12 * rng.random())
        v = pick()
        ev += [
            ChaosEvent(t0, "disconnect", v),
            ChaosEvent(t0 + dt, "reconnect", v),
        ]
    if partition:
        t0 = duration_s * (0.55 + 0.15 * rng.random())
        dt = duration_s * (0.10 + 0.15 * rng.random())
        v = pick()
        ev += [ChaosEvent(t0, "partition", v), ChaosEvent(t0 + dt, "heal", v)]
    return sorted(ev, key=lambda e: (e.at_s, e.kind, e.node))


class ChaosRunner:
    """Fires a schedule against a cluster from the driving thread.

    No thread of its own: the test/benchmark loop calls :meth:`pump`
    each tick (``LocalCluster.drive_to(..., tick=runner.pump)`` wires
    it into the standard paced drive), and :meth:`drain` at the end of
    the window fires whatever is left immediately — every restorative
    counter-event (restart/reconnect/heal) is guaranteed to run, so a
    timeout can never strand the cluster mid-fault.
    """

    def __init__(
        self,
        cluster: Any,
        schedule: Iterable[ChaosEvent],
        injector: Any = None,
    ) -> None:
        self.cluster = cluster
        self.schedule = sorted(schedule, key=lambda e: (e.at_s, e.kind))
        self.injector = injector
        if injector is None and any(
            e.kind in ("partition", "heal") for e in self.schedule
        ):
            raise ValueError(
                "schedule contains partition/heal events but the runner "
                "was given no FaultInjector"
            )
        self._i = 0
        self._t0: Optional[float] = None
        self.fired: List[ChaosEvent] = []

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = time.monotonic()

    def pending(self) -> int:
        return len(self.schedule) - self._i

    def pump(self) -> bool:
        """Fire every due event; True while any event remains."""
        if self._t0 is None:
            self.start()
        now = time.monotonic() - self._t0
        while self._i < len(self.schedule) and self.schedule[self._i].at_s <= now:
            self._fire(self.schedule[self._i])
            self._i += 1
        return self._i < len(self.schedule)

    def drain(self) -> None:
        """Fire all remaining events NOW, in schedule order."""
        while self._i < len(self.schedule):
            self._fire(self.schedule[self._i])
            self._i += 1

    def _fire(self, e: ChaosEvent) -> None:
        c = self.cluster
        # Flight recorder: chaos disruptions land on the cluster track so
        # the trace shows the fault window next to the nodes' recovery.
        buf = getattr(c, "trace", None)
        if buf is not None:
            buf.emit(f"chaos.{e.kind}", node=e.node, at_s=e.at_s)
        if e.kind == "kill":
            c.kill(e.node)
        elif e.kind == "restart":
            c.restart(e.node)
        elif e.kind == "disconnect":
            c.disconnect(e.node)
        elif e.kind == "reconnect":
            c.reconnect(e.node)
        elif e.kind == "partition":
            groups = (
                frozenset(i for i in c.nodes if i != e.node),
                frozenset([e.node]),
            )
            self.injector.add_partition(
                PartitionSpec(groups, start_s=self.injector.elapsed())
            )
        elif e.kind == "heal":
            self.injector.heal_all()
        else:  # pragma: no cover - schedule construction is closed
            raise ValueError(f"unknown chaos event kind {e.kind!r}")
        self.fired.append(e)
