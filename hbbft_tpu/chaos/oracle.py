"""Safety/liveness oracles for Byzantine cluster runs.

The oracle contract (ISSUE 7) — what a chaos run must uphold, checked
over the HONEST nodes of a :class:`~hbbft_tpu.transport.cluster.
LocalCluster` built with a ``byzantine`` map:

* **safety** — every honest node's committed batch stream is
  byte-identical over the common prefix (``assert_safety``;
  :func:`batches_sha` digests a stream for benchmark JSON lines);
* **liveness** — honest commit counts keep growing inside the standard
  45 s phase caps (``assert_progress`` — the paced
  ``LocalCluster.drive_to`` under the hood, with an optional ``tick``
  for pumping a :class:`~hbbft_tpu.chaos.scheduler.ChaosRunner`);
* **exactly-once** — traffic-plane transactions appear at most once in
  every honest node's committed stream, and every admitted transaction
  was observed committed (``assert_exactly_once`` over a
  :class:`~hbbft_tpu.traffic.driver.TrafficDriver`);
* **attribution** — honest fault logs name ONLY Byzantine ids: the
  evidence channel never frames an honest node
  (``assert_attribution``).  Both node arms are read — the Python
  node's ``Step.fault_log`` entries and the native node's engine fault
  vector (``hbe_fault_subject``/``hbe_fault_kind``) — through one
  :func:`fault_entries` view.

Attribution caveat: injected frame *duplication* (``dup_p``) makes
honest peers deliver duplicates, which some protocol layers log as
faults against the (honest) sender.  Chaos schedules therefore compose
with dup-free link shapes (``wan``); put duplication on Byzantine
links only if attribution is being asserted.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, List, Optional, Tuple

from hbbft_tpu.traffic.clients import txn_id_of
from hbbft_tpu.utils import serde


def batch_keys(cluster: Any, nid: int, upto: Optional[int] = None) -> List[tuple]:
    bs = cluster.batches(nid)
    if upto is not None:
        bs = bs[:upto]
    return [(b.era, b.epoch, serde.dumps(b.contributions)) for b in bs]


def batches_sha(cluster: Any, nid: int, upto: Optional[int] = None) -> str:
    """SHA-256 digest of one node's committed stream (the cross-node /
    cross-arm identity handle benchmarks report)."""
    h = hashlib.sha256()
    for era, epoch, contrib in batch_keys(cluster, nid, upto):
        h.update(serde.dumps((era, epoch)))
        h.update(contrib)
    return h.hexdigest()


def fault_entries(node: Any) -> List[Tuple[Any, str]]:
    """(subject, kind) fault entries of one cluster node, either arm."""
    eng = getattr(node, "engine", None)
    if eng is not None:  # native arm: the engine's fault vector
        return eng.faults(node.id)
    return [(f.node_id, f.kind) for f in node.faults]


def stream_txns(cluster: Any, nid: int) -> List[str]:
    """All transactions in node ``nid``'s committed stream, in order."""
    out: List[str] = []
    for b in cluster.batches(nid):
        for _proposer, contrib in b.contributions:
            if isinstance(contrib, (list, tuple)):
                out.extend(t for t in contrib if isinstance(t, str))
    return out


class ChaosOracle:
    """Safety/liveness/exactly-once/attribution checks over the honest
    side of a Byzantine cluster.  Raises ``AssertionError`` with a
    named verdict on violation; check methods return evidence (prefix
    length, fault counts) for the caller's own assertions."""

    def __init__(self, cluster: Any, driver: Any = None) -> None:
        self.cluster = cluster
        self.byzantine_ids = frozenset(cluster.byzantine)
        self.honest_ids = list(cluster.honest_ids)
        self.driver = driver

    # -- safety --------------------------------------------------------
    def assert_safety(self, min_prefix: int = 1) -> int:
        """Honest streams agree byte-for-byte over the common prefix;
        returns the prefix length (>= ``min_prefix``)."""
        keys = {i: batch_keys(self.cluster, i) for i in self.honest_ids}
        k = min(len(v) for v in keys.values())
        if k < min_prefix:
            raise AssertionError(
                f"SAFETY(vacuous): honest common prefix {k} < {min_prefix}"
            )
        ref_id = self.honest_ids[0]
        ref = keys[ref_id][:k]
        for i in self.honest_ids[1:]:
            if keys[i][:k] != ref:
                d = next(
                    j for j in range(k) if keys[i][j] != ref[j]
                )
                raise AssertionError(
                    f"SAFETY: honest nodes {ref_id} and {i} diverge at "
                    f"batch {d} ({ref[d][:2]} vs {keys[i][d][:2]})"
                )
        return k

    # -- liveness ------------------------------------------------------
    def assert_progress(
        self,
        extra: int = 2,
        timeout_s: float = 45.0,
        tick: Optional[Callable[[], Any]] = None,
        tag: str = "oracle",
    ) -> int:
        """Honest nodes commit >= ``extra`` MORE batches within the
        phase cap (paced drive; raises TimeoutError on a stall).
        Returns the new minimum honest commit count."""
        base = min(self.cluster.batch_count(i) for i in self.honest_ids)
        self.cluster.drive_to(
            self.honest_ids, base + extra, timeout_s=timeout_s, tag=tag,
            tick=tick,
        )
        return min(self.cluster.batch_count(i) for i in self.honest_ids)

    # -- exactly-once --------------------------------------------------
    def assert_exactly_once(self) -> int:
        """Every honest committed stream is duplicate-free, and every
        admitted traffic transaction was observed committed (call after
        ``driver.drain()``).  Returns the committed count."""
        assert self.driver is not None, "exactly-once needs a TrafficDriver"
        d = self.driver
        if d.outstanding() != 0:
            raise AssertionError(
                f"EXACTLY-ONCE: {d.outstanding()} admitted txns never "
                "observed committed (drain incomplete?)"
            )
        for i in self.honest_ids:
            txns = stream_txns(self.cluster, i)
            if len(txns) != len(set(txns)):
                dup = sorted(
                    t for t in set(txns) if txns.count(t) > 1
                )[:4]
                raise AssertionError(
                    f"EXACTLY-ONCE: node {i} committed duplicates {dup}"
                )
        return d.recorder.committed

    def committed_ids(self, nid: int) -> set:
        return {txn_id_of(t) for t in stream_txns(self.cluster, nid)}

    # -- attribution ---------------------------------------------------
    def assert_attribution(self) -> int:
        """No honest fault log names a non-Byzantine subject; returns
        the total number of fault entries naming Byzantine ids (the
        caller asserts > 0 when the strategy should be detectable)."""
        named = 0
        for i in self.honest_ids:
            for subject, kind in fault_entries(self.cluster.nodes[i]):
                if subject in self.byzantine_ids:
                    named += 1
                else:
                    raise AssertionError(
                        f"ATTRIBUTION: honest node {i} logged {kind!r} "
                        f"against non-Byzantine {subject!r}"
                    )
        return named

    # -- composite -----------------------------------------------------
    def check_all(
        self,
        extra: int = 2,
        timeout_s: float = 45.0,
        tick: Optional[Callable[[], Any]] = None,
    ) -> dict:
        """Progress, then safety + attribution (+ exactly-once when a
        driver is attached); returns the evidence dict benchmarks
        embed in their JSON lines."""
        committed = self.assert_progress(
            extra=extra, timeout_s=timeout_s, tick=tick
        )
        # One safety pass: the cluster keeps committing while we look,
        # so a second assert_safety() could see a longer prefix than
        # the one the sha is reported for (and re-digests every stream).
        prefix = self.assert_safety()
        out = {
            "honest_committed_min": committed,
            "safety_prefix": prefix,
            "byzantine_faults_named": self.assert_attribution(),
            "batches_sha": batches_sha(
                self.cluster, self.honest_ids[0], upto=prefix
            ),
        }
        if self.driver is not None:
            out["exactly_once_committed"] = self.assert_exactly_once()
        return out
