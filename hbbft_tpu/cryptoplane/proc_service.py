"""Crypto plane as a process: the RPC boundary around the service.

Round 18 (ISSUE 18) promotes :class:`~hbbft_tpu.cryptoplane.service.
CryptoPlaneService` from an in-process thread to its own OS process so
one accelerator plane can serve nodes that are THEMSELVES processes
(:class:`~hbbft_tpu.transport.proc_cluster.ProcCluster`), batching ALL
nodes' COIN/DECRYPT/sig checks into single ``verify_batch`` flushes on
a real backend — the Thetacrypt "threshold crypto as a service" shape
(arxiv 2502.03247) carrying the repo's TPU flush kernel to a live
network.  Three pieces:

* **Worker** (``python -m hbbft_tpu.cryptoplane.proc_service``): wraps
  the unchanged in-process service + a socket acceptor.  Spawn protocol
  is ``cluster_worker``'s, byte-for-byte in spirit: bind ``--port 0``,
  print ONE ready JSON line with the bound port, then stdin is the stop
  channel (EOF = orphan cleanup).  Requests from ALL connections merge
  through the service's one batching window, so cross-NODE amortization
  happens exactly where cross-THREAD amortization already did.
* **Wire**: the transport's length-prefixed frame grammar
  (:mod:`~hbbft_tpu.transport.framing`) with a DISJOINT kind set
  (``CRYPTO_KINDS``) — a service socket pointed at a consensus port (or
  vice versa) dies at the framing layer.  Payloads are serde, suite-
  pinned; requests ride as the registered ``"vreq"`` struct
  (:mod:`hbbft_tpu.wire`), so shares are opaque bytes to this module
  and any :class:`~hbbft_tpu.crypto.backend.CryptoBackend` rides
  behind the boundary.  One outstanding request per connection: the
  caller is a node's protocol thread that cannot progress past the
  share check anyway, and it keeps the framing strictly sequential
  (req/resp alternation; a mismatched ``req_id`` is a protocol error).
* **Client** (:class:`RpcServiceClient`): a drop-in ``CryptoBackend``
  with the in-thread :class:`~hbbft_tpu.cryptoplane.service.
  ServiceClient`'s failure stance — the service is an OPTIMIZATION
  plane, never a liveness dependency.  Any socket error, timeout,
  malformed response, or service-side flush failure routes the SAME
  requests through the local fallback backend (counted:
  ``crypto.rpc.fallbacks``), and the next flush re-dials (bounded
  backoff), so a restarted service is re-attached automatically.
  Verdicts are pure functions of request content (the standing
  deferred-verification invariant), so the two paths are
  interchangeable per request: no lost or duplicated fault
  attributions across a mid-flush SIGKILL (tests/
  test_cryptoplane_proc.py pins both).

Requests that fail to serde-encode (protocol handlers can be handed
arbitrary Byzantine objects) ride as ``None`` placeholders and verify
``False`` — the same verdict ``request_well_formed`` gives them on
every local backend, so the RPC boundary never changes a verdict.

Observability: the client stamps ``crypto.rpc.*`` metrics (round-trip
timer, queued gauge, fallback counters) into its node's metrics and
emits ``crypto.flush.open/done`` spans (batch size + a ``span`` id for
concurrent-client pairing) onto the cluster's ``cryptoplane`` trace
track, which /diag's critical-path analyzer already folds into
per-epoch flush attribution.  The server reports each response's
merged flush size (``flush_requests``/``flush_jobs``) so a client can
see the amortization it actually got; config9 carries the numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import hbbft_tpu.wire  # noqa: F401  (registers the "vreq" serde struct)
from hbbft_tpu.crypto.backend import CryptoBackend, VerifyRequest
from hbbft_tpu.cryptoplane.service import CryptoPlaneService
from hbbft_tpu.transport.framing import (
    CRYPTO_KINDS,
    KIND_CRYPTO_HELLO,
    KIND_CRYPTO_REQ,
    KIND_CRYPTO_RESP,
    MAX_FRAME_LEN,
    RECV_CHUNK,
    FrameDecoder,
    FrameError,
    encode_frame,
)
from hbbft_tpu.utils import serde
from hbbft_tpu.utils.metrics import Metrics

RPC_VERSION = 1

#: Default RPC-mode client timeout (seconds); overridden per client or
#: via the env knob.  Generous on purpose: the fallback exists for
#: DEATH, not jitter — a busy 1-core box can hold a flush for a while.
DEF_TIMEOUT_S = 30.0

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def default_rpc_timeout_s() -> float:
    """``HBBFT_TPU_CRYPTO_RPC_TIMEOUT_S`` (seconds a client waits on one
    RPC round trip before falling back locally)."""
    return float(os.environ.get("HBBFT_TPU_CRYPTO_RPC_TIMEOUT_S", DEF_TIMEOUT_S))


def default_window_s() -> float:
    """``HBBFT_TPU_CRYPTO_WINDOW_S`` (the service's cross-client batching
    window; the worker's ``--window-s`` default)."""
    return float(os.environ.get("HBBFT_TPU_CRYPTO_WINDOW_S", 0.002))


def service_addr_from_env() -> Optional[Tuple[str, int]]:
    """``HBBFT_TPU_CRYPTO_SERVICE`` (``host:port`` of an already-running
    service process to attach to instead of spawning one)."""
    spec = os.environ.get("HBBFT_TPU_CRYPTO_SERVICE")
    return parse_addr(spec) if spec else None


def parse_addr(spec: str) -> Tuple[str, int]:
    host, _, port = spec.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad service address {spec!r} (want host:port)")
    return host, int(port)


# -- suites / backends (worker argv vocabulary) ------------------------------

def _build_suite(name: str):
    if name == "scalar":
        from hbbft_tpu.crypto.suite import ScalarSuite

        return ScalarSuite()
    if name == "bls":
        from hbbft_tpu.crypto.bls.suite import BLSSuite

        return BLSSuite()
    raise ValueError(f"unknown suite {name!r} (scalar | bls)")


def suite_arg_for(suite: Any) -> str:
    """The ``--suite`` argv token for a live suite instance."""
    return "bls" if suite.name == "bls12-381" else "scalar"


def _build_backend(name: str, suite: Any) -> CryptoBackend:
    if name == "batched":
        from hbbft_tpu.crypto.backend import BatchedBackend

        return BatchedBackend(suite)
    if name == "eager":
        from hbbft_tpu.crypto.backend import EagerBackend

        return EagerBackend(suite)
    if name == "tpu":
        # jax import happens HERE, in the service process only — node
        # processes stay jax-free whatever backend serves them.
        from hbbft_tpu.crypto.tpu import TpuBackend

        return TpuBackend(suite)
    raise ValueError(f"unknown backend {name!r} (batched | eager | tpu)")


# -- wire helpers ------------------------------------------------------------

def _hello_frame(suite_name: str, max_frame_len: int) -> bytes:
    return encode_frame(
        KIND_CRYPTO_HELLO,
        serde.dumps((RPC_VERSION, suite_name)),
        max_frame_len,
        kinds=CRYPTO_KINDS,
    )


def _check_hello(payload: bytes, suite_name: str) -> None:
    obj = serde.try_loads(payload)
    if (
        not isinstance(obj, tuple)
        or len(obj) != 2
        or type(obj[0]) is not int
        or type(obj[1]) is not str
    ):
        raise FrameError("malformed crypto HELLO")
    if obj[0] != RPC_VERSION:
        raise FrameError(f"crypto RPC version {obj[0]} != {RPC_VERSION}")
    if obj[1] != suite_name:
        raise FrameError(
            f"crypto suite mismatch: peer={obj[1]!r} local={suite_name!r}"
        )


def _recv_frame(
    sock: socket.socket, dec: FrameDecoder, deadline: Optional[float]
) -> Tuple[int, bytes]:
    """Block for the next complete frame (honoring ``deadline``,
    monotonic).  EOF and timeout both raise OSError subclasses — the
    caller's uniform response is drop-the-connection."""
    while True:
        got = dec.next_frame()
        if got is not None:
            return got
        if deadline is not None:
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise socket.timeout("crypto RPC deadline")
            sock.settimeout(min(remain, 5.0) if remain > 0 else 0.001)
        data = sock.recv(RECV_CHUNK)
        if not data:
            raise ConnectionError("crypto RPC peer closed")
        dec.feed(data)


# -- server ------------------------------------------------------------------

class CryptoRpcServer:
    """Socket front of one :class:`CryptoPlaneService`.

    Accept loop + one reader thread per connection; every reader
    submits into the SAME service, whose batching window merges the
    requests of all connected nodes into one backend flush.  A
    malformed frame (bad CRC, unknown kind, oversized, undecodable
    payload) drops THAT connection only — the listener and every other
    client live on, and the disconnected client's next flush falls
    back locally then re-dials.
    """

    def __init__(
        self,
        service: CryptoPlaneService,
        suite: Any,
        host: str = "127.0.0.1",
        port: int = 0,
        max_frame_len: int = MAX_FRAME_LEN,
        job_wait_s: float = 600.0,
    ) -> None:
        self.service = service
        self.suite = suite
        self.max_frame_len = max_frame_len
        self.job_wait_s = job_wait_s
        self.metrics = service.metrics
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._conns: Dict[int, socket.socket] = {}
        self._conn_seq = 0
        self._lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "CryptoRpcServer":
        self.service.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="crypto-rpc-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        self.service.stop()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                self._conn_seq += 1
                cid = self._conn_seq
                self._conns[cid] = sock
            self.metrics.count("crypto.rpc.accepts")
            threading.Thread(
                target=self._serve_conn,
                args=(cid, sock),
                name=f"crypto-rpc-conn-{cid}",
                daemon=True,
            ).start()

    def _serve_conn(self, cid: int, sock: socket.socket) -> None:
        dec = FrameDecoder(self.max_frame_len, kinds=CRYPTO_KINDS)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            kind, payload = _recv_frame(sock, dec, None)
            if kind != KIND_CRYPTO_HELLO:
                raise FrameError("first crypto frame must be HELLO")
            _check_hello(payload, self.suite.name)
            sock.sendall(_hello_frame(self.suite.name, self.max_frame_len))
            while not self._stop.is_set():
                kind, payload = _recv_frame(sock, dec, None)
                if kind != KIND_CRYPTO_REQ:
                    raise FrameError("expected crypto REQ")
                sock.sendall(self._handle_req(payload))
        except (FrameError, serde.DecodeError):
            self.metrics.count("crypto.rpc.bad_frames")
        except OSError:
            pass  # peer went away / timeout / we are stopping
        finally:
            try:
                sock.close()
            except OSError:
                pass
            with self._lock:
                self._conns.pop(cid, None)

    def _handle_req(self, payload: bytes) -> bytes:
        obj = serde.loads(payload, suite=self.suite)  # DecodeError -> drop
        if not isinstance(obj, tuple) or len(obj) != 3:
            raise FrameError("malformed crypto REQ")
        req_id, op, body = obj
        if type(req_id) is not int or type(op) is not str:
            raise FrameError("malformed crypto REQ header")
        if op == "stats":
            return self._resp(req_id, op, (self._stats_json(),))
        if op != "verify":
            raise FrameError(f"unknown crypto RPC op {op!r}")
        if not isinstance(body, tuple) or not all(
            item is None or isinstance(item, VerifyRequest) for item in body
        ):
            raise FrameError("malformed crypto verify body")
        return self._resp(req_id, op, self._verify(body))

    def _verify(self, items: Tuple[Any, ...]) -> tuple:
        # None placeholders (client-side unserializable junk) verify
        # False without touching the backend — the verdict every local
        # backend's request_well_formed gate would produce for them.
        real = [r for r in items if r is not None]
        verdicts = [False] * len(items)
        ok = True
        flush_requests = flush_jobs = 0
        if real:
            job = self.service.submit(real)
            ok = (
                job is not None
                and job.done.wait(self.job_wait_s)
                and job.results is not None
            )
            if job is not None and not ok:
                job.cancelled = True  # timed out: drop if still queued
            if ok:
                it = iter(job.results)
                verdicts = [
                    (next(it) if r is not None else False) for r in items
                ]
                flush_requests = job.flush_requests
                flush_jobs = job.flush_jobs
        self.metrics.count("crypto.rpc.served_requests", len(items))
        return (
            ok,
            bytes(bytearray(1 if v else 0 for v in verdicts)),
            flush_requests,
            flush_jobs,
        )

    def _resp(self, req_id: int, op: str, rest: tuple) -> bytes:
        return encode_frame(
            KIND_CRYPTO_RESP,
            serde.dumps((req_id, op) + rest),
            self.max_frame_len,
            kinds=CRYPTO_KINDS,
        )

    def _stats_json(self) -> bytes:
        # Stats are parent-side diagnostics (config9's JSON line), not
        # protocol objects: JSON bytes, not serde structs.
        return json.dumps(self.metrics.to_json(), sort_keys=True).encode()


# -- client ------------------------------------------------------------------

class RpcServiceClient(CryptoBackend):
    """RPC-mode drop-in backend with local-fallback semantics.

    One instance per node (protocol thread is the only caller — same
    one-caller rule as every other per-node backend).  ``metrics``
    should be the node's own :class:`Metrics` so ``crypto.rpc.*`` rides
    every existing merge/scrape path; ``trace`` an (optionally shared)
    ``cryptoplane`` TraceBuffer — emits carry a ``span`` id so the
    analyzer can pair open/done across concurrently-flushing clients.
    """

    def __init__(
        self,
        addr: Tuple[str, int],
        suite: Any,
        fallback: CryptoBackend,
        *,
        timeout_s: Optional[float] = None,
        connect_timeout_s: float = 5.0,
        reconnect_backoff_s: float = 0.5,
        max_frame_len: int = MAX_FRAME_LEN,
        metrics: Optional[Metrics] = None,
        trace: Any = None,
        client_id: str = "",
    ) -> None:
        self.addr = (addr[0], int(addr[1]))
        self.suite = suite
        self.fallback = fallback
        self.timeout_s = float(
            timeout_s if timeout_s is not None else default_rpc_timeout_s()
        )
        self.connect_timeout_s = float(connect_timeout_s)
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self.max_frame_len = max_frame_len
        self.metrics = metrics if metrics is not None else Metrics()
        self.trace = trace
        self.client_id = client_id or f"rpc-{id(self) & 0xFFFF:04x}"
        self._sock: Optional[socket.socket] = None
        self._dec: Optional[FrameDecoder] = None
        self._seq = 0
        self._next_dial = 0.0
        self._ever_connected = False

    # -- connection management -----------------------------------------
    def _ensure_conn(self) -> Optional[socket.socket]:
        if self._sock is not None:
            return self._sock
        now = time.monotonic()
        if now < self._next_dial:
            return None  # inside the backoff window: fall back fast
        try:
            sock = socket.create_connection(
                self.addr, timeout=self.connect_timeout_s
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            dec = FrameDecoder(self.max_frame_len, kinds=CRYPTO_KINDS)
            sock.sendall(_hello_frame(self.suite.name, self.max_frame_len))
            deadline = time.monotonic() + self.connect_timeout_s
            kind, payload = _recv_frame(sock, dec, deadline)
            if kind != KIND_CRYPTO_HELLO:
                raise FrameError("service HELLO expected")
            _check_hello(payload, self.suite.name)
        except (OSError, FrameError):
            self._next_dial = now + self.reconnect_backoff_s
            return None
        self._sock, self._dec = sock, dec
        if self._ever_connected:
            # a successful dial after a drop = the re-attach drill's
            # observable (service restarted, client found it again)
            self.metrics.count("crypto.rpc.reconnects")
        self._ever_connected = True
        self.metrics.count("crypto.rpc.connects")
        return sock

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._dec = None
        self._next_dial = time.monotonic() + self.reconnect_backoff_s

    def close(self) -> None:
        self._drop_conn()

    # -- the backend interface -----------------------------------------
    def verify_batch(self, reqs: Sequence[VerifyRequest]) -> List[bool]:
        reqs = list(reqs)
        if not reqs:
            return []
        items = self._encode_items(reqs)
        sock = self._ensure_conn()
        if sock is None:
            return self._local(reqs, "unavailable")
        self._seq += 1
        req_id = self._seq
        span = f"{self.client_id}:{req_id}"
        if self.trace is not None:
            self.trace.emit(
                "crypto.flush.open",
                requests=len(reqs), backend="rpc", span=span,
            )
        self.metrics.gauge("crypto.rpc.queued", len(reqs))
        ok = False
        try:
            try:
                with self.metrics.timer("crypto.rpc.round_trip"):
                    sock.sendall(
                        encode_frame(
                            KIND_CRYPTO_REQ,
                            serde.dumps((req_id, "verify", tuple(items))),
                            self.max_frame_len,
                            kinds=CRYPTO_KINDS,
                        )
                    )
                    resp = self._read_resp(req_id)
            except (OSError, FrameError, serde.DecodeError):
                # timeout / death / garbage: the connection state is
                # unknown (a late response would desync req ids), so
                # drop it; the next flush re-dials after backoff.
                self._drop_conn()
                return self._local(reqs, "error")
            ok, verdict_bytes, flush_requests, flush_jobs = resp
            if not ok:
                # service alive but ITS flush failed: same degradation
                # as the in-thread arm — keep the connection.
                return self._local(reqs, "flush-failed")
            self.metrics.count("crypto.rpc.calls")
            self.metrics.count("crypto.rpc.requests", len(reqs))
            self.metrics.count("crypto.rpc.merged_requests", flush_requests)
            self.metrics.count("crypto.rpc.merged_jobs", flush_jobs)
            return [b != 0 for b in verdict_bytes]
        finally:
            self.metrics.gauge("crypto.rpc.queued", 0)
            if self.trace is not None:
                self.trace.emit(
                    "crypto.flush.done",
                    requests=len(reqs), backend="rpc", span=span, ok=ok,
                )

    def _encode_items(self, reqs: List[VerifyRequest]) -> List[Any]:
        # The common case (every payload a real suite object) costs one
        # serde encode later; only when something refuses to encode do
        # we probe per item and ship None placeholders.
        try:
            serde.dumps(tuple(reqs))
            return list(reqs)
        except Exception:
            items: List[Any] = []
            for r in reqs:
                try:
                    serde.dumps(r)
                    items.append(r)
                except Exception:
                    items.append(None)
            return items

    def _read_resp(self, req_id: int) -> Tuple[bool, bytes, int, int]:
        assert self._sock is not None and self._dec is not None
        deadline = time.monotonic() + self.timeout_s
        kind, payload = _recv_frame(self._sock, self._dec, deadline)
        if kind != KIND_CRYPTO_RESP:
            raise FrameError("expected crypto RESP")
        obj = serde.loads(payload, suite=self.suite)
        if (
            not isinstance(obj, tuple)
            or len(obj) != 6
            or obj[0] != req_id
            or obj[1] != "verify"
            or type(obj[2]) is not bool
            or type(obj[3]) is not bytes
            or type(obj[4]) is not int
            or type(obj[5]) is not int
        ):
            raise FrameError("malformed crypto RESP")
        return obj[2], obj[3], obj[4], obj[5]

    def _local(self, reqs: List[VerifyRequest], why: str) -> List[bool]:
        self.metrics.count("crypto.rpc.fallbacks")
        self.metrics.count("crypto.rpc.fallback_requests", len(reqs))
        self.metrics.count(f"crypto.rpc.fallback.{why}")
        return self.fallback.verify_batch(reqs)


def fetch_stats(
    addr: Tuple[str, int], suite: Any, timeout_s: float = 10.0
) -> Dict[str, Any]:
    """One-shot stats RPC (the parent/benchmark side of the JSON line)."""
    sock = socket.create_connection(addr, timeout=timeout_s)
    try:
        dec = FrameDecoder(kinds=CRYPTO_KINDS)
        deadline = time.monotonic() + timeout_s
        sock.sendall(_hello_frame(suite.name, MAX_FRAME_LEN))
        kind, payload = _recv_frame(sock, dec, deadline)
        if kind != KIND_CRYPTO_HELLO:
            raise FrameError("service HELLO expected")
        _check_hello(payload, suite.name)
        sock.sendall(
            encode_frame(
                KIND_CRYPTO_REQ,
                serde.dumps((1, "stats", None)),
                kinds=CRYPTO_KINDS,
            )
        )
        kind, payload = _recv_frame(sock, dec, deadline)
        if kind != KIND_CRYPTO_RESP:
            raise FrameError("expected crypto RESP")
        obj = serde.loads(payload, suite=suite)
        if (
            not isinstance(obj, tuple)
            or len(obj) != 3
            or obj[1] != "stats"
            or type(obj[2]) is not bytes
        ):
            raise FrameError("malformed stats RESP")
        return json.loads(obj[2])
    finally:
        sock.close()


# -- parent-side process handle ----------------------------------------------

class ServiceProcess:
    """Spawn/kill/restart handle for one service worker process.

    Spawn protocol is ProcCluster's: subprocess with a pipe stdin (the
    stop channel) + a stdout pump collecting the ready and summary
    lines.  ``kill()`` is a REAL SIGKILL (the mid-flush drill);
    ``restart()`` respawns on the OLD port so clients' bounded-backoff
    re-dials find the reborn listener without re-configuration.
    """

    def __init__(
        self,
        suite: str = "scalar",
        backend: str = "batched",
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        window_s: Optional[float] = None,
        max_batch: Optional[int] = None,
        python: str = sys.executable,
        stderr: str = "devnull",
        force_cpu_jax: bool = True,
        ready_timeout_s: float = 60.0,
        env_overrides: Optional[Dict[str, str]] = None,
    ) -> None:
        self.suite_arg = suite
        self.backend_arg = backend
        self.host = host
        self._want_port = port
        self.window_s = window_s
        self.max_batch = max_batch
        self.python = python
        self._stderr_mode = stderr
        self.force_cpu_jax = force_cpu_jax
        self.ready_timeout_s = ready_timeout_s
        self.env_overrides = dict(env_overrides or {})
        self.proc: Optional[subprocess.Popen] = None
        self.ready: Optional[dict] = None
        self.summary: Optional[dict] = None
        self._ready_evt = threading.Event()
        self._done_evt = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self.ready["port"] if self.ready else None

    @property
    def addr(self) -> Tuple[str, int]:
        if not self.ready:
            raise RuntimeError("service process not started")
        return (self.host, self.ready["port"])

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def start(self) -> "ServiceProcess":
        self._spawn(self._want_port)
        if not self._ready_evt.wait(self.ready_timeout_s):
            rc = self.proc.poll() if self.proc else None
            self.stop()
            raise TimeoutError(
                f"crypto service never printed its ready line (rc={rc})"
            )
        return self

    def _spawn(self, port: int) -> None:
        cmd = [
            self.python,
            "-m",
            "hbbft_tpu.cryptoplane.proc_service",
            "--suite", self.suite_arg,
            "--backend", self.backend_arg,
            "--host", self.host,
            "--port", str(port),
        ]
        if self.window_s is not None:
            cmd += ["--window-s", str(self.window_s)]
        if self.max_batch is not None:
            cmd += ["--max-batch", str(self.max_batch)]
        env = dict(os.environ)
        if self.force_cpu_jax:
            # the Batched/Eager service needs no accelerator: displace
            # the axon sitecustomize exactly like ProcCluster workers
            env["PYTHONPATH"] = _REPO_ROOT
            env["JAX_PLATFORMS"] = "cpu"
        else:
            # TpuBackend arm: keep the caller's PYTHONPATH (the axon
            # plugin rides there) with the repo root pinned in front
            prior = env.get("PYTHONPATH", "")
            env["PYTHONPATH"] = (
                _REPO_ROOT + (os.pathsep + prior if prior else "")
            )
        env.update(self.env_overrides)
        self.ready = None
        self.summary = None
        self._ready_evt = threading.Event()
        self._done_evt = threading.Event()
        self.proc = subprocess.Popen(
            cmd,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=(
                subprocess.DEVNULL if self._stderr_mode == "devnull" else None
            ),
            text=True,
            env=env,
            cwd=_REPO_ROOT,
        )
        self._pump_thread = threading.Thread(
            target=self._pump, name="crypto-svc-pump", daemon=True
        )
        self._pump_thread.start()

    def _pump(self) -> None:
        proc = self.proc
        assert proc is not None and proc.stdout is not None
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("ready"):
                self.ready = obj
                self._ready_evt.set()
            elif "done" in obj:
                self.summary = obj
                self._done_evt.set()
        self._done_evt.set()

    def kill(self) -> None:
        """SIGKILL, no goodbyes: the mid-flush drill."""
        if self.proc is not None:
            self.proc.kill()

    def restart(self) -> None:
        """Respawn on the OLD port (clients re-attach via backoff dials)."""
        old_port = self.port
        if old_port is None:
            raise RuntimeError("restart() before a successful start()")
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)
        self._spawn(old_port)
        if not self._ready_evt.wait(self.ready_timeout_s):
            raise TimeoutError("restarted crypto service never got ready")

    def stats(self) -> Dict[str, Any]:
        return fetch_stats(self.addr, _build_suite(self.suite_arg))

    def stop(self, grace_s: float = 10.0) -> None:
        proc = self.proc
        if proc is None:
            return
        if proc.poll() is None:
            try:
                if proc.stdin:
                    proc.stdin.write(json.dumps({"stop": True}) + "\n")
                    proc.stdin.flush()
            except (OSError, ValueError):
                pass
        try:
            if proc.stdin:
                proc.stdin.close()
        except OSError:
            pass
        try:
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5)

    def __enter__(self) -> "ServiceProcess":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


# -- worker entry ------------------------------------------------------------

def _watch_stdin(stop: threading.Event) -> None:
    """Drain stdin until a stop command or EOF (dead parent = EOF, so
    orphaned service processes tear down by themselves)."""
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            if json.loads(line).get("stop"):
                break
        except ValueError:
            continue
    stop.set()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--suite", choices=("scalar", "bls"), default="scalar")
    ap.add_argument(
        "--backend", choices=("batched", "eager", "tpu"), default="batched"
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--port",
        type=int,
        default=0,
        help="listener port (0 = ephemeral; echoed in the ready line)",
    )
    ap.add_argument(
        "--window-s",
        type=float,
        default=default_window_s(),
        help="cross-client batching window (HBBFT_TPU_CRYPTO_WINDOW_S)",
    )
    ap.add_argument("--max-batch", type=int, default=512)
    args = ap.parse_args(argv)

    suite = _build_suite(args.suite)
    backend = _build_backend(args.backend, suite)
    service = CryptoPlaneService(
        backend, window_s=args.window_s, max_batch=args.max_batch
    )
    server = CryptoRpcServer(
        service, suite, host=args.host, port=args.port
    ).start()
    print(
        json.dumps(
            {
                "ready": True,
                "port": server.port,
                "suite": args.suite,
                "backend": args.backend,
                "window_s": args.window_s,
                "pid": os.getpid(),
            },
            sort_keys=True,
        ),
        flush=True,
    )
    stop = threading.Event()
    threading.Thread(target=_watch_stdin, args=(stop,), daemon=True).start()
    stop.wait()
    m = service.metrics
    summary = {
        "done": True,
        "flushes": m.counters.get("crypto.flushes", 0),
        "requests": m.counters.get("crypto.requests", 0),
        "served_requests": m.counters.get("crypto.rpc.served_requests", 0),
        "accepts": m.counters.get("crypto.rpc.accepts", 0),
        "bad_frames": m.counters.get("crypto.rpc.bad_frames", 0),
        "flush_errors": m.counters.get("crypto.flush_errors", 0),
    }
    server.stop()
    try:
        print(json.dumps(summary, sort_keys=True), flush=True)
    except OSError:
        pass  # parent died first: the summary has no reader
    return 0


if __name__ == "__main__":
    sys.exit(main())
