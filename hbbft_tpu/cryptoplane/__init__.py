"""Cluster crypto plane (ISSUE 12): the shared batched
share-verification service behind :class:`~hbbft_tpu.crypto.backend.
CryptoBackend`, serving both cluster node arms.  Round 18 adds the
process form — the service in its own OS process behind a socket RPC
boundary (:mod:`hbbft_tpu.cryptoplane.proc_service`), serving
process-per-node clusters and cross-node-batching onto one accelerator
backend.  See docs/CRYPTO_PLANE.md.
"""

from hbbft_tpu.cryptoplane.service import CryptoPlaneService, ServiceClient
from hbbft_tpu.cryptoplane.proc_service import (
    CryptoRpcServer,
    RpcServiceClient,
    ServiceProcess,
)

__all__ = [
    "CryptoPlaneService",
    "ServiceClient",
    "CryptoRpcServer",
    "RpcServiceClient",
    "ServiceProcess",
]
