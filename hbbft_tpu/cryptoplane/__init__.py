"""Cluster crypto plane (ISSUE 12): the shared batched
share-verification service behind :class:`~hbbft_tpu.crypto.backend.
CryptoBackend`, serving both cluster node arms.  See
docs/CRYPTO_PLANE.md and :mod:`hbbft_tpu.cryptoplane.service`.
"""

from hbbft_tpu.cryptoplane.service import CryptoPlaneService, ServiceClient

__all__ = ["CryptoPlaneService", "ServiceClient"]
