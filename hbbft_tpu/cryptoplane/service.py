"""Cluster crypto plane: a shared batched share-verification service.

The two halves of this repo meet here (ROADMAP item 2): cluster nodes
verify COIN/DECRYPT/sig shares either inline (scalar C for native
nodes, a per-node :class:`~hbbft_tpu.crypto.backend.CryptoBackend` for
Python nodes) or — with ``LocalCluster(crypto="service")`` — through
ONE shared :class:`CryptoPlaneService` that merges the share-check
requests of ALL nodes into single ``CryptoBackend.verify_batch``
flushes.  This is the "threshold cryptography as a distributed
service" architecture of Thetacrypt (PAPERS.md, arxiv 2502.03247):
with ``TpuBackend`` attached, the flush kernel that verifies 3,348
shares/s on TPU (BENCH_r05) serves an actual running network; with
``BatchedBackend`` (CI / relay-down) the RLC pairing collapse still
amortizes across nodes.

Correctness stance — the standing deferred-verification invariant:
verification verdicts are PURE functions of request content, so
merging requests across nodes, reordering flushes, or falling back to
a local backend can never change a verdict, only its timing.  The
service arm therefore commits byte-identical batches to the inline
arm, and per-sender fault attribution is preserved exactly
(``BatchedBackend`` bisects aggregate failures down to the offending
request — the RLC bisection contract, docs/INVARIANTS.md).  Pinned by
tests/test_cryptoplane.py (``batches_sha`` across arms, fault-multiset
parity under a corrupt-share adversary).

Failure stance: the service is an OPTIMIZATION plane, never a
liveness dependency.  Every :class:`ServiceClient` carries a local
fallback backend; a flush that times out, a killed service, or a
worker crash routes the same requests through the fallback (counted:
``crypto.fallbacks``) and the cluster keeps committing on the scalar
path — the relay-down story for ``TpuBackend``.

Threading: ``submit`` may be called from any number of node protocol
threads; the single worker thread owns the backend (JAX dispatch is
not assumed thread-safe).  Callers block on their job's event — a
node cannot progress past a share check anyway, and the window is the
measured "latency price of threshold cryptography" (arxiv 2407.12172)
that benchmarks/config9_crypto_plane.py prices against epochs/s.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional, Sequence

from hbbft_tpu.crypto.backend import CryptoBackend, VerifyRequest
from hbbft_tpu.traffic.latency import LatencyHistogram
from hbbft_tpu.utils.metrics import Metrics


class _Job:
    """One client's submitted batch: requests in, verdicts out."""

    __slots__ = ("reqs", "results", "done", "cancelled",
                 "flush_requests", "flush_jobs")

    def __init__(self, reqs: List[VerifyRequest]) -> None:
        self.reqs = reqs
        self.results: Optional[List[bool]] = None  # None = failed/killed
        self.done = threading.Event()
        # Stamped by _flush: the size of the MERGED batch this job rode
        # in (requests / jobs across all clients) — the cross-node
        # amortization observable the RPC server reports back to its
        # clients (proc_service.py).
        self.flush_requests = 0
        self.flush_jobs = 0
        # Set by a client that timed out and re-verified locally: the
        # worker drops still-queued cancelled jobs instead of paying a
        # backend flush nobody is waiting for (best-effort — a job the
        # worker already collected still flushes).
        self.cancelled = False


class CryptoPlaneService:
    """The shared verification service: one worker, one backend.

    * ``window_s`` — how long the worker holds the first pending job
      open for more arrivals before flushing (the cross-node batching
      window; 0 flushes immediately).
    * ``max_batch`` — pending-request count that triggers an immediate
      flush regardless of the window.
    * ``trace`` — optional :class:`~hbbft_tpu.obs.trace.TraceBuffer`;
      every flush emits ``crypto.flush.open`` / ``crypto.flush.done``
      milestone events (requests/jobs/backend args) onto it, so the
      flight recorder's merged timeline shows device flushes next to
      the per-node epoch phases.

    Metrics (exported via :meth:`export_metrics` into
    ``LocalCluster.merged_metrics``): ``crypto.flushes`` /
    ``crypto.requests`` counters, ``crypto.flush`` timer (latency),
    ``crypto.batch_size`` summary (log-bucket histogram),
    ``crypto.queue_depth`` gauge, ``crypto.fallbacks`` (client-side,
    counted here so the cluster sees one total), ``crypto.flush_errors``.
    """

    def __init__(
        self,
        backend: CryptoBackend,
        *,
        window_s: float = 0.002,
        max_batch: int = 512,
        metrics: Optional[Metrics] = None,
        trace: Any = None,
    ) -> None:
        self.backend = backend
        self.window_s = float(window_s)
        self.max_batch = int(max_batch)
        self.metrics = metrics if metrics is not None else Metrics()
        self.trace = trace
        self._cv = threading.Condition()
        self._jobs: List[_Job] = []
        self._pending_reqs = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._killed = False
        # batch-size distribution (requests per backend flush): the
        # log-bucket estimator bounds memory like the traffic plane's
        # latency clocks; re-published as the crypto.batch_size summary.
        self._batch_hist = LatencyHistogram(lo=1.0, hi=65536.0, growth=1.25)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "CryptoPlaneService":
        """Start the worker.  stop()/kill() are TERMINAL: a stopped
        service never restarts (clients fall back locally forever) —
        restartability would make LocalCluster.stop() racy against
        late in-flight submits."""
        with self._cv:
            if self._thread is None and not self._killed and not self._stop:
                self._start_locked()
        return self

    def _start_locked(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="cryptoplane", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Graceful shutdown: drains nothing — outstanding jobs fail
        over to their clients' fallbacks (same path as kill)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        self._fail_pending()
        self._thread = None

    def kill(self) -> None:
        """Simulated crash (the fallback drill): the service goes dead
        NOW and stays dead — outstanding and future submissions fail
        immediately, clients fall back to their local backend."""
        with self._cv:
            self._killed = True
            self._stop = True
            self._cv.notify_all()
        self._fail_pending()

    @property
    def alive(self) -> bool:
        return not self._killed and not self._stop

    def _fail_pending(self) -> None:
        with self._cv:
            jobs, self._jobs = self._jobs, []
            self._pending_reqs = 0
            # scrapes of the surviving cluster must not show a stale
            # nonzero queue on a dead service
            self.metrics.gauge("crypto.queue_depth", 0)
        for j in jobs:
            j.done.set()  # results stay None -> client falls back

    # -- submission (any thread) ---------------------------------------
    def submit(self, reqs: Sequence[VerifyRequest]) -> Optional[_Job]:
        """Enqueue one batch; returns the job to wait on, or None when
        the service is dead (caller falls back immediately).  Lazily
        starts the worker so a cluster built before ``start()`` still
        gets service semantics."""
        job = _Job(list(reqs))
        with self._cv:
            if self._killed or self._stop:
                return None
            if self._thread is None:
                # Lazy start UNDER the lock: a submit racing stop()
                # must never resurrect a worker after shutdown.
                self._start_locked()
            self._jobs.append(job)
            self._pending_reqs += len(job.reqs)
            self.metrics.gauge("crypto.queue_depth", self._pending_reqs)
            self._cv.notify_all()
        return job

    # -- worker ---------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._jobs and not self._stop:
                    self._cv.wait(timeout=0.2)
                if self._stop:
                    return
                # Hold the window open from the FIRST pending arrival:
                # more nodes' flushes pile into the same device batch.
                deadline = time.monotonic() + self.window_s
                while (
                    not self._stop
                    and self._pending_reqs < self.max_batch
                    and (remain := deadline - time.monotonic()) > 0
                ):
                    self._cv.wait(timeout=remain)
                if self._stop:
                    return
                # Timed-out clients already re-verified locally; drop
                # their abandoned jobs rather than flushing for nobody.
                jobs = [j for j in self._jobs if not j.cancelled]
                self._jobs = []
                self._pending_reqs = 0
                self.metrics.gauge("crypto.queue_depth", 0)
            if jobs:
                self._flush(jobs)

    def _flush(self, jobs: List[_Job]) -> None:
        reqs = [r for j in jobs for r in j.reqs]
        backend = type(self.backend).__name__
        if self.trace is not None:
            self.trace.emit(
                "crypto.flush.open",
                requests=len(reqs), jobs=len(jobs), backend=backend,
            )
        ok = False
        try:
            with self.metrics.timer("crypto.flush"):
                results = self.backend.verify_batch(reqs)
            if len(results) != len(reqs):  # a broken backend is a crash
                raise RuntimeError(
                    f"backend returned {len(results)} verdicts "
                    f"for {len(reqs)} requests"
                )
            pos = 0
            for j in jobs:
                j.results = [bool(v) for v in results[pos:pos + len(j.reqs)]]
                pos += len(j.reqs)
            ok = True
            self.metrics.count("crypto.flushes")
            self.metrics.count("crypto.requests", len(reqs))
            self._batch_hist.observe(float(len(reqs)))
            self._publish_batch_summary()
        except Exception:
            # One bad flush must not take the plane down: these jobs
            # fail over to their clients' fallbacks, the worker lives.
            self.metrics.count("crypto.flush_errors")
        finally:
            if self.trace is not None:
                self.trace.emit(
                    "crypto.flush.done",
                    requests=len(reqs), jobs=len(jobs), backend=backend,
                    ok=ok,
                )
            for j in jobs:
                j.flush_requests = len(reqs)
                j.flush_jobs = len(jobs)
                j.done.set()

    def _publish_batch_summary(self) -> None:
        h = self._batch_hist
        self.metrics.summary(
            "crypto.batch_size",
            {q: h.quantile(q) for q in (0.5, 0.9, 0.99)},
            h.count,
            h.total,
        )

    # -- clients --------------------------------------------------------
    def client(
        self,
        fallback: CryptoBackend,
        *,
        timeout_s: float = 30.0,
    ) -> "ServiceClient":
        return ServiceClient(self, fallback, timeout_s=timeout_s)

    def export_metrics(self, into: Metrics) -> None:
        into.merge(self.metrics)


class ServiceClient(CryptoBackend):
    """Per-node facade: a drop-in :class:`CryptoBackend` whose
    ``verify_batch`` routes through the shared service and falls back
    to ``fallback`` (a local CPU backend) when the service is dead,
    killed mid-wait, or slower than ``timeout_s``.  Verdicts are pure,
    so the two paths are interchangeable per request."""

    def __init__(
        self,
        service: CryptoPlaneService,
        fallback: CryptoBackend,
        *,
        timeout_s: float = 30.0,
    ) -> None:
        self.service = service
        self.fallback = fallback
        self.timeout_s = float(timeout_s)

    def verify_batch(self, reqs: Sequence[VerifyRequest]) -> List[bool]:
        reqs = list(reqs)
        if not reqs:
            return []
        job = self.service.submit(reqs)
        if job is not None:
            if job.done.wait(self.timeout_s):
                results = job.results
                if results is not None:
                    return results
            else:
                job.cancelled = True  # worker drops it if still queued
        m = self.service.metrics
        m.count("crypto.fallbacks")
        m.count("crypto.fallback_requests", len(reqs))
        return self.fallback.verify_batch(reqs)
