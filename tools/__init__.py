"""Repo-native developer tooling (not shipped with the hbbft_tpu package)."""
