"""Regenerate the golden sim-net trace fixtures for tests/test_analyze.py.

Runs a deterministic N=4 QHB simulation on BOTH sim-net impls — the
Python :class:`~hbbft_tpu.net.virtual_net.VirtualNet` (with the
round-16 per-node tracer) and the native :class:`~hbbft_tpu.
native_engine.NativeQhbNet` (engine ring) — records each run's trace
tracks, and writes:

* ``tests/fixtures/golden_trace_<impl>.json`` — the frozen event
  streams (timestamps are wall clock, frozen at generation time; the
  event STRUCTURE is seed-deterministic);
* ``tests/fixtures/golden_cp_<impl>.json`` — the critical-path
  analyzer's output over those exact streams, which
  tests/test_analyze.py pins byte-for-byte (after a JSON round trip).

Regenerate ONLY when the analyzer's output schema or the milestone
taxonomy deliberately changes:

    python tools/make_golden_trace.py

and commit both file pairs together with the change that moved them.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.obs.analyze import critical_path  # noqa: E402
from hbbft_tpu.obs.trace import TraceEvent  # noqa: E402

FIXDIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests",
    "fixtures",
)
SEED = 0
N = 4
EPOCHS = 3
BATCH = 4


def gen_python_tracks() -> Dict[str, List[TraceEvent]]:
    from hbbft_tpu.net import NetBuilder
    from hbbft_tpu.protocols.queueing_honey_badger import (
        Input,
        QueueingHoneyBadger,
    )
    from hbbft_tpu.protocols.sender_queue import SenderQueue

    def factory(ni: Any, sink: Any, rng: Any) -> Any:
        return SenderQueue.wrap(
            lambda s: QueueingHoneyBadger(
                ni, s, batch_size=BATCH, session_id=b"golden"
            ),
            sink,
            peers=list(range(N)),
        )

    net = NetBuilder(N, seed=SEED).num_faulty(0).protocol(factory).build()
    net.enable_trace()
    for i in range(N):
        net.send_input(i, Input.user(f"g-0-{i}"))
        net.send_input(i, Input.user(f"g-1-{i}"))
    net.crank_until(
        lambda n: all(len(n.node(i).outputs) >= EPOCHS for i in range(N)),
        max_cranks=200_000,
    )
    return net.trace_events()


def gen_native_tracks() -> Dict[str, List[TraceEvent]]:
    from hbbft_tpu.native_engine import NativeQhbNet
    from hbbft_tpu.protocols.queueing_honey_badger import Input

    net = NativeQhbNet(N, seed=SEED, batch_size=BATCH, num_faulty=0)
    net.enable_trace(65536)
    for i in range(N):
        net.send_input(i, Input.user(f"g-0-{i}"))
        net.send_input(i, Input.user(f"g-1-{i}"))
    # small chunks: QHB commits empty epochs forever if the predicate is
    # only checked after a huge run (CLAUDE.md run_until note)
    net.run_until(
        lambda n: all(
            len(n.nodes[i].outputs) >= EPOCHS for i in range(N)
        ),
        chunk=2_000,
    )
    tracks: Dict[str, List[TraceEvent]] = {}
    for ev in net.drain_trace():
        tracks.setdefault(f"node{ev.args['node']}", []).append(ev)
    return tracks


def dump(impl: str, tracks: Dict[str, List[TraceEvent]]) -> None:
    os.makedirs(FIXDIR, exist_ok=True)
    ser = {
        "impl": impl,
        "seed": SEED,
        "n": N,
        "tracks": {
            t: [[ev.ts, ev.name, ev.args] for ev in evs]
            for t, evs in sorted(tracks.items())
        },
    }
    trace_path = os.path.join(FIXDIR, f"golden_trace_{impl}.json")
    with open(trace_path, "w") as fh:
        json.dump(ser, fh, indent=1, sort_keys=True)
    recs = critical_path(tracks)
    cp_path = os.path.join(FIXDIR, f"golden_cp_{impl}.json")
    with open(cp_path, "w") as fh:
        json.dump(recs, fh, indent=1, sort_keys=True)
    print(
        f"{impl}: {sum(len(v) for v in tracks.values())} events, "
        f"{len(recs)} epochs -> {trace_path}, {cp_path}"
    )


def main() -> int:
    dump("python", gen_python_tracks())
    try:
        tracks = gen_native_tracks()
    except RuntimeError as exc:  # no compiler on this box
        print(f"native fixture SKIPPED: {exc}", file=sys.stderr)
        return 1
    dump("native", tracks)
    return 0


if __name__ == "__main__":
    sys.exit(main())
