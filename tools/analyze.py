"""Critical-path / stall analysis CLI over a flight-recorder trace.

Runs the IDENTICAL code (:mod:`hbbft_tpu.obs.analyze`) the live
``/diag`` endpoint runs, over a dumped ``trace.json`` — so post-mortem
and live diagnosis can never disagree.

Usage::

    python tools/analyze.py /tmp/run.trace.json            # critical paths
    python tools/analyze.py /tmp/run.trace.json --diag     # post-mortem stall
    python tools/analyze.py --url http://127.0.0.1:9100    # scrape a live run
    python tools/analyze.py --demo 4                       # live N=4 demo,
                                                           # /diag printed
    ... --json                                             # machine output

Trace sources: any ``trace.json`` the recorder writes —
``LocalCluster.write_trace``, a worker's ``--trace-file``, a
``ProcCluster`` parent merge, or ``BENCH_TRACE`` benchmark dumps.
``--url`` fetches ``<url>/trace.json`` from a live scrape server and
analyzes it client-side (plus ``<url>/diag`` with ``--diag``, which is
the server's own verdict).

For post-mortem ``--diag`` the clock is frozen at the newest event
stamp: "stalled" then means the RUN ended in a stall, not that the file
is old.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.obs.analyze import (  # noqa: E402
    critical_path,
    diagnose,
    summarize_critical_paths,
    tracks_from_chrome,
)


def _fmt_s(dt: float) -> str:
    return f"{dt * 1e3:8.2f} ms"


def render_paths(records: List[Dict[str, Any]]) -> str:
    """Human rendering of per-epoch critical paths + the summary."""
    lines: List[str] = []
    for rec in records:
        strag = rec["straggler"]
        lines.append(
            f"epoch (era {rec['era']}, {rec['epoch']}): "
            f"wall {rec['wall_s'] * 1e3:.2f} ms, "
            f"commit skew {rec['commit_skew_s'] * 1e3:.2f} ms, "
            f"coins {rec['coins']}"
            + (
                f", straggler {strag['node']}"
                f" {strag['phase']}"
                + (
                    f" (proposer {strag['proposer']})"
                    if strag.get("proposer") is not None
                    else ""
                )
                if strag
                else ""
            )
        )
        for p in rec["path"]:
            extra = []
            if "proposer" in p:
                extra.append(f"proposer {p['proposer']}")
            if p.get("round") is not None:
                extra.append(f"round {p['round']}")
            lines.append(
                f"  +{_fmt_s(p['dt_s'])}  {p['stage']:<14} {p['node']}"
                + (f"  ({', '.join(extra)})" if extra else "")
            )
        if rec.get("flush"):
            fl = rec["flush"]
            lines.append(
                f"  cryptoplane: {fl['flushes']} flushes, "
                f"{fl['total_s'] * 1e3:.2f} ms total"
            )
    lines.append("")
    lines.append("summary: " + json.dumps(summarize_critical_paths(records)))
    return "\n".join(lines)


def render_diag(d: Dict[str, Any]) -> str:
    lines = [
        f"stalled: {d['stalled']}"
        + (
            f" (no commit for {d['since_s']:.1f} s"
            f" > {d['stall_after_s']} s)"
            if d["stalled"] and d.get("since_s") is not None
            else ""
        ),
        f"last commit: {d.get('last_commit')}",
        f"open epochs: {json.dumps(d.get('open_epochs', {}))}",
    ]
    v = d.get("verdict")
    if v and v.get("phase") == "link":
        lines.append(
            f"verdict: peers {v['peers']} down on {v['nodes']} node(s) "
            "(quorum lost at the link layer)"
        )
    elif v:
        lines.append(
            f"verdict: proposer {v['proposer']} stuck in {v['phase']}"
            + (f" at round {v['round']}" if v.get("round") is not None else "")
            + f" on {v['nodes']} node(s)"
        )
    for s in d.get("stuck", ()):
        lines.append(
            f"  {s['node']} e{s['era']}/{s['epoch']}"
            f" proposer {s['proposer']}: {s['phase']} — {s['detail']}"
            f" (idle {s['age_s']:.1f} s)"
        )
    for track, st in sorted(d.get("links", {}).items()):
        if st.get("disconnected"):
            lines.append(f"  {track}: disconnected peers {st['disconnected']}")
        for ban in st.get("banned", ()):
            lines.append(
                f"  {track}: peer {ban['peer']} banned ({ban['offense']})"
            )
    if d.get("dead_nodes"):
        lines.append(f"  dead honest nodes: {d['dead_nodes']}")
    return "\n".join(lines)


def _demo(n: int, as_json: bool) -> int:
    """Live demo: drive an N-node cluster, print its critical paths,
    then partition an honest minority and print the resulting /diag —
    over HTTP, so what you see is exactly what a scraper sees."""
    import time
    import urllib.request

    from hbbft_tpu.transport import LocalCluster

    with LocalCluster(n, seed=0) as c:
        base = f"http://127.0.0.1:{c.serve_obs().port}"
        print(f"# scrape endpoints live at {base} (/metrics /trace.json "
              f"/healthz /diag)", file=sys.stderr)
        c.drive_to(range(n), 3, timeout_s=60, tag="demo")
        doc = json.loads(
            urllib.request.urlopen(base + "/trace.json", timeout=10).read()
        )
        records = critical_path(tracks_from_chrome(doc))
        if not as_json:
            print(render_paths(records))
        # now demonstrate the stall diagnostician: sever f+1 nodes —
        # one more than the cluster tolerates — so commits stop and
        # /diag has something real to explain
        victims = list(range(n - (c.f + 1), n))
        print(f"\n# partitioning nodes {victims}; /diag after quiescence:",
              file=sys.stderr)
        for v in victims:
            c.disconnect(v)
        survivors = [i for i in range(n) if i not in victims]
        try:
            c.drive_to(survivors, 10**9, timeout_s=2, tag="stall")
        except TimeoutError:
            pass
        time.sleep(3.2)
        d = json.loads(
            urllib.request.urlopen(base + "/diag?stall_s=3", timeout=10).read()
        )
        if as_json:
            print(json.dumps({"critical_path": records, "diag": d}))
        else:
            print(render_diag(d))
        for v in victims:
            c.reconnect(v)
    return 0


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="hbbft-tpu flight-recorder critical-path analyzer"
    )
    ap.add_argument("trace", nargs="?", help="path to a dumped trace.json")
    ap.add_argument(
        "--url", help="base URL of a live obs server (fetches /trace.json)"
    )
    ap.add_argument(
        "--diag", action="store_true",
        help="print the stall diagnosis instead of just critical paths",
    )
    ap.add_argument(
        "--stall-s", type=float, default=5.0,
        help="quiescence threshold for --diag (default 5)",
    )
    ap.add_argument(
        "--n", type=int, default=None,
        help="consensus size for --diag (needed for a single-worker "
        "dump, whose one node track hides the other proposers; "
        "inferred from the node tracks otherwise)",
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    ap.add_argument(
        "--demo", type=int, metavar="N",
        help="run a live N-node demo cluster and print its /diag",
    )
    args = ap.parse_args(argv)

    if args.demo:
        return _demo(args.demo, args.json)

    if args.url:
        import urllib.request

        doc = json.loads(
            urllib.request.urlopen(
                args.url.rstrip("/") + "/trace.json", timeout=10
            ).read()
        )
    elif args.trace:
        try:
            with open(args.trace) as fh:
                doc = json.load(fh)
        except OSError as exc:
            print(f"analyze: cannot read {args.trace}: {exc}", file=sys.stderr)
            return 2
        except json.JSONDecodeError as exc:
            print(
                f"analyze: {args.trace} is not a complete JSON document"
                f" (truncated dump?): {exc}",
                file=sys.stderr,
            )
            return 2
    else:
        ap.error("need a trace.json path, --url, or --demo N")
        return 2

    try:
        tracks = tracks_from_chrome(doc)
    except (AttributeError, KeyError, TypeError, ValueError) as exc:
        print(
            f"analyze: not a Chrome-trace document ({exc!r}) — expected "
            "the recorder's trace.json shape (traceEvents + otherData)",
            file=sys.stderr,
        )
        return 2
    if not tracks:
        # Valid document, zero recorder events (e.g. a dump taken before
        # any epoch opened): an honest empty analysis, not a crash.
        print(
            "analyze: trace contains no recorder events (empty tracks)",
            file=sys.stderr,
        )
    records = critical_path(tracks)
    out: Dict[str, Any] = {
        "critical_path": records,
        "summary": summarize_critical_paths(records),
    }
    if args.diag:
        if args.url:
            # live run: the server's own /diag IS the verdict — its
            # clock is real, so quiescence (no new events at all) reads
            # as stalled, which a frozen-clock local pass would miss
            out["diag"] = json.loads(
                urllib.request.urlopen(
                    args.url.rstrip("/")
                    + f"/diag?stall_s={args.stall_s}",
                    timeout=10,
                ).read()
            )
        else:
            # post-mortem: freeze the clock at the capture instant —
            # "stalled" must describe the run, not the file's age
            now = max(
                (ev.ts for evs in tracks.values() for ev in evs),
                default=None,
            )
            out["diag"] = diagnose(
                tracks, n=args.n, now=now, stall_after_s=args.stall_s
            )
    if args.json:
        print(json.dumps(out))
    else:
        print(render_paths(records))
        if args.diag:
            print()
            print(render_diag(out["diag"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
