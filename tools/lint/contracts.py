"""Cross-language contract analyzer (HBX001-003).

Three implementations of one protocol (Python oracle, C++ thread
engine, proc-per-node engine) must stay byte-identical; the contracts
binding them live on both sides of the language boundary and drift
silently when only one side is edited.  This module machine-checks the
three contract surfaces:

* **HBX001 — wire-codec parity.**  The Python registry (every
  ``register_struct(tag, ...)`` in ``hbbft_tpu/wire.py``) and the
  engine's mirror (``wenc_struct``/``wenc_share_emsg`` emit sites, the
  ``WireWalk`` decode acceptance, ``take_share_struct``) must agree tag
  for tag, and the caller-supplied ``hbe_serde_scan`` limits in
  ``native/engine.cpp`` must equal serde.py's ``MAX_DEPTH``/``_MAX_LEN``.
  A tag the engine carries that Python cannot decode (or vice versa) is
  a finding.  Tags that legitimately cross only the committed-
  contribution boundary (the engine sees them as opaque bytes) are
  annotated ``# lint: wire-oneside (<reason>)`` at the registration;
  an annotation on a tag the engine DOES carry is itself a finding
  (stale escape).  Decode-only engine tags are fine by design (the
  classifier accepts more than the engine emits), but every emitted tag
  must also be accepted.

* **HBX002 — knob registry.**  Every ``HBBFT_TPU_*`` env knob
  referenced anywhere in the tree must be registered in
  :mod:`tools.lint.knob_registry` (default, owning layer, A/B
  semantics), every registered knob must still be referenced, and the
  committed ``docs/KNOBS.md`` must byte-match the generated output
  (``python -m tools.lint --knobs-md``).  ``tools/lint/`` and
  ``tests/test_lint.py`` are excluded from the reference scan — they
  hold the registry and the mutation fixtures themselves.

* **HBX003 — mirror obligations.**  CLAUDE.md's prose "must be
  mirrored in BOTH continuations" becomes paired anchors: a
  ``# mirror: <key>`` comment in Python and a ``// mirror: <key>``
  comment in C++ mark the two halves of one obligation.  A key present
  on one side only fails, so deleting or renaming either anchor (or the
  code around it) trips the linter and points at the surviving twin.

These are repo-level rules: they read a fixed file set, so they run
only when ``python -m tools.lint`` lints the whole repo (explicit-path
invocations skip them).  All file access goes through an ``overrides``
dict (repo-relative path -> source) so the mutation self-tests in
tests/test_lint.py can seed one-line drifts without touching disk.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from tools.lint import Finding, _REPO, knob_registry

WIRE_PY = "hbbft_tpu/wire.py"
SERDE_PY = "hbbft_tpu/utils/serde.py"
ENGINE_CPP = "native/engine.cpp"
KNOBS_MD = "docs/KNOBS.md"
KNOB_REGISTRY_PY = "tools/lint/knob_registry.py"

Overrides = Optional[Dict[str, str]]


def _read_rel(rel: str, overrides: Overrides) -> Optional[str]:
    if overrides and rel in overrides:
        return overrides[rel]
    path = os.path.join(_REPO, rel)
    if not os.path.isfile(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


# -- C++ text utilities ------------------------------------------------------
#
# cxxlints._strip blanks string CONTENTS (its rules only need structure);
# here the string literals ARE the data — wire tags and knob names — so
# this stripper blanks comments and preserves strings, keeping offsets
# and line structure intact.


def _cxx_strip_comments(src: str) -> str:
    out: List[str] = []
    i, n = 0, len(src)
    quote = ""
    while i < n:
        c = src[i]
        if quote:
            if c == "\\" and i + 1 < n:
                out.append(src[i : i + 2])
                i += 2
            else:
                out.append(c)
                if c == quote or c == "\n":
                    quote = ""
                i += 1
        elif c in "\"'":
            quote = c
            out.append(c)
            i += 1
        elif c == "/" and i + 1 < n and src[i + 1] == "/":
            while i < n and src[i] != "\n":
                out.append(" ")
                i += 1
        elif c == "/" and i + 1 < n and src[i + 1] == "*":
            out.append("  ")
            i += 2
            while i < n and not (c == "*" and src[i] == "/"):
                c = src[i]
                out.append("\n" if c == "\n" else " ")
                i += 1
            if i < n:
                out.append(" ")
                i += 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _balanced_args(text: str, open_pos: int) -> str:
    """The argument text between ``(`` at open_pos and its matching
    ``)``, tracking nesting and skipping over string literals."""
    depth = 0
    quote = ""
    i, n = open_pos, len(text)
    while i < n:
        c = text[i]
        if quote:
            if c == "\\" and i + 1 < n:
                i += 1
            elif c == quote or c == "\n":
                quote = ""
        elif c in "\"'":
            quote = c
        elif c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return text[open_pos + 1 : i]
        i += 1
    return text[open_pos + 1 :]


def _split_top(args: str) -> List[str]:
    """Split an argument string on top-level commas (paren/string aware)."""
    parts: List[str] = []
    depth = 0
    quote = ""
    cur: List[str] = []
    i, n = 0, len(args)
    while i < n:
        c = args[i]
        if quote:
            if c == "\\" and i + 1 < n:
                cur.append(args[i : i + 2])
                i += 2
                continue
            if c == quote or c == "\n":
                quote = ""
        elif c in "\"'":
            quote = c
        elif c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
        elif c == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    parts.append("".join(cur))
    return parts


def _cxx_int(expr: str) -> Optional[int]:
    """Evaluate a C++ integer constant expression of the shapes the
    serde-limit call sites use: a literal (with u/l suffixes, decimal
    or hex) or ``a << b``.  None for anything else."""
    expr = expr.strip()
    if "<<" in expr:
        a, _, b = expr.partition("<<")
        va, vb = _cxx_int(a), _cxx_int(b)
        return None if va is None or vb is None else va << vb
    m = re.fullmatch(r"\(?\s*(0[xX][0-9a-fA-F]+|\d+)\s*[uUlL]*\s*\)?", expr)
    return int(m.group(1), 0) if m else None


# -- HBX001: wire-codec parity ----------------------------------------------

ONESIDE_RE = re.compile(r"#\s*lint:\s*wire-oneside\s*\(\S", re.IGNORECASE)
_TAG_LIT_RE = re.compile(r'"([A-Za-z0-9_]+)"')
_ENC_CALL_RE = re.compile(r"\b(?:wenc_struct|wenc_share_emsg)\s*\(")
_ENTER_RE = re.compile(r"\benter_struct\s*\(\s*(\w+)\s*,\s*(\w+)\s*\)")
_EQ_RE = re.compile(r"\beq\s*\(\s*(\w+)\s*,\s*(\w+)\s*,\s*\"([A-Za-z0-9_]+)\"")
_TAKE_SHARE_RE = re.compile(r"\btake_share_struct\s*\(\s*\"([A-Za-z0-9_]+)\"")
_SCAN_CALL_RE = re.compile(r"\bhbe_serde_scan\s*\(")


def engine_wire_tags(code: str) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(encoded, decoded) tag -> first line, from comment-stripped
    engine source.

    Encode side: every string literal inside a ``wenc_struct`` /
    ``wenc_share_emsg`` call's argument list (paren-tracked, so the
    multi-line ternary emit sites count every branch).  Decode side:
    ``eq(name, len, "tag")`` where ``(name, len)`` is a variable pair
    bound by some ``enter_struct(name, len)`` — kind-string
    comparisons over ``take_str`` vars never bind that way — plus the
    ``take_share_struct("tag", ...)`` literals.
    """
    enc: Dict[str, int] = {}
    dec: Dict[str, int] = {}
    for m in _ENC_CALL_RE.finditer(code):
        args = _balanced_args(code, m.end() - 1)
        for tag in _TAG_LIT_RE.findall(args):
            enc.setdefault(tag, _line_of(code, m.start()))
    pairs = set(_ENTER_RE.findall(code))
    for m in _EQ_RE.finditer(code):
        if (m.group(1), m.group(2)) in pairs:
            dec.setdefault(m.group(3), _line_of(code, m.start()))
    for m in _TAKE_SHARE_RE.finditer(code):
        dec.setdefault(m.group(1), _line_of(code, m.start()))
    return enc, dec


def engine_scan_limits(code: str) -> List[Tuple[int, int, int]]:
    """Every ``hbe_serde_scan(...)`` call whose depth/len arguments are
    integer constant expressions, as (max_depth, max_len, line).  The
    extern declaration and the definition carry parameter names there,
    not literals, so only real caller sites qualify."""
    out: List[Tuple[int, int, int]] = []
    for m in _SCAN_CALL_RE.finditer(code):
        parts = _split_top(_balanced_args(code, m.end() - 1))
        if len(parts) != 6:
            continue
        depth, length = _cxx_int(parts[4]), _cxx_int(parts[5])
        if depth is None or length is None:
            continue
        out.append((depth, length, _line_of(code, m.start())))
    return out


def python_wire_registry(src: str) -> Dict[str, int]:
    """tag -> line of every ``register_struct(tag, ...)`` call (the
    call's first line, so the two-line annotation window above it works
    for multi-line registrations too)."""
    tags: Dict[str, int] = {}
    for node in ast.walk(ast.parse(src)):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (
            fn.id
            if isinstance(fn, ast.Name)
            else fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if (
            name == "register_struct"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            tags.setdefault(node.args[0].value, node.lineno)
    return tags


def _py_const_eval(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp):
        a, b = _py_const_eval(node.left), _py_const_eval(node.right)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.LShift):
            return a << b
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
        if isinstance(node.op, ast.Pow):
            return a**b
    return None


def python_serde_limits(
    src: str,
) -> Tuple[Optional[Tuple[int, int]], Optional[Tuple[int, int]]]:
    """((MAX_DEPTH, line), (_MAX_LEN, line)) from serde.py, either None
    if the assignment is missing or not a constant expression."""
    depth = length = None
    for node in ast.walk(ast.parse(src)):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            val = _py_const_eval(node.value)
            if val is None:
                continue
            if node.targets[0].id == "MAX_DEPTH" and depth is None:
                depth = (val, node.lineno)
            elif node.targets[0].id == "_MAX_LEN" and length is None:
                length = (val, node.lineno)
    return depth, length


def _annotated(raw_lines: List[str], line: int, rx: re.Pattern) -> bool:
    for ln in range(max(1, line - 2), min(line, len(raw_lines)) + 1):
        if rx.search(raw_lines[ln - 1]):
            return True
    return False


def rule_wire_parity(overrides: Overrides = None) -> List[Finding]:
    findings: List[Finding] = []
    wire_src = _read_rel(WIRE_PY, overrides)
    engine_src = _read_rel(ENGINE_CPP, overrides)
    if wire_src is None or engine_src is None:
        return findings
    py_tags = python_wire_registry(wire_src)
    code = _cxx_strip_comments(engine_src)
    enc, dec = engine_wire_tags(code)
    raw_wire = wire_src.splitlines()
    # Extraction failure must be loud, never silently green: a rename of
    # register_struct/wenc_struct would otherwise turn the rule off.
    if not py_tags:
        findings.append(
            Finding(
                "HBX001",
                WIRE_PY,
                1,
                "extraction failed: no register_struct(tag, ...) calls "
                "found — if the registration API was renamed, teach "
                "tools/lint/contracts.py the new shape",
            )
        )
    if not enc or not dec:
        findings.append(
            Finding(
                "HBX001",
                ENGINE_CPP,
                1,
                "extraction failed: no engine wire "
                f"{'emit' if not enc else 'accept'} sites found — if "
                "wenc_struct/enter_struct were renamed, teach "
                "tools/lint/contracts.py the new shape",
            )
        )
    if findings:
        return findings
    engine_tags = set(enc) | set(dec)
    for tag, line in sorted(py_tags.items()):
        has_escape = _annotated(raw_wire, line, ONESIDE_RE)
        if tag in engine_tags and has_escape:
            findings.append(
                Finding(
                    "HBX001",
                    WIRE_PY,
                    line,
                    f'stale escape: wire tag "{tag}" carries a '
                    "wire-oneside annotation but native/engine.cpp "
                    "mirrors it — drop the annotation",
                )
            )
        elif tag not in engine_tags and not has_escape:
            findings.append(
                Finding(
                    "HBX001",
                    WIRE_PY,
                    line,
                    f'wire tag "{tag}" is registered in the Python codec '
                    "but native/engine.cpp neither emits nor accepts it "
                    "— mirror it in the engine wire codec, or annotate "
                    "the registration `# lint: wire-oneside (<reason>)` "
                    "if it legitimately crosses only the committed-"
                    "contribution boundary",
                )
            )
    for tag in sorted(engine_tags - set(py_tags)):
        findings.append(
            Finding(
                "HBX001",
                ENGINE_CPP,
                enc.get(tag) or dec[tag],
                f'engine wire tag "{tag}" has no register_struct twin in '
                "hbbft_tpu/wire.py — the Python oracle could not decode "
                "engine frames carrying it",
            )
        )
    for tag in sorted(set(enc) - set(dec)):
        findings.append(
            Finding(
                "HBX001",
                ENGINE_CPP,
                enc[tag],
                f'engine emits wire tag "{tag}" but its decode path '
                "never accepts it — a native peer could not parse its "
                "own frames",
            )
        )
    # serde scan limits: Python constants vs the engine's literal-arg
    # hbe_serde_scan call(s).
    serde_src = _read_rel(SERDE_PY, overrides)
    py_depth = py_len = None
    if serde_src is not None:
        py_depth, py_len = python_serde_limits(serde_src)
    limits = engine_scan_limits(code)
    if serde_src is None or py_depth is None or py_len is None:
        findings.append(
            Finding(
                "HBX001",
                SERDE_PY,
                1,
                "extraction failed: MAX_DEPTH/_MAX_LEN constants not "
                "found in serde.py — the serde-limit parity check "
                "cannot run",
            )
        )
    elif not limits:
        findings.append(
            Finding(
                "HBX001",
                ENGINE_CPP,
                1,
                "extraction failed: no hbe_serde_scan call with literal "
                "depth/len arguments found — the serde-limit parity "
                "check cannot run",
            )
        )
    else:
        for depth, length, line in limits:
            if depth != py_depth[0]:
                findings.append(
                    Finding(
                        "HBX001",
                        ENGINE_CPP,
                        line,
                        f"serde scan max_depth {depth} != serde.py "
                        f"MAX_DEPTH {py_depth[0]} "
                        f"({SERDE_PY}:{py_depth[1]}) — the two decoders "
                        "would accept different nesting",
                    )
                )
            if length != py_len[0]:
                findings.append(
                    Finding(
                        "HBX001",
                        ENGINE_CPP,
                        line,
                        f"serde scan max_len {length} != serde.py "
                        f"_MAX_LEN {py_len[0]} ({SERDE_PY}:{py_len[1]}) "
                        "— the two decoders would accept different "
                        "payload sizes",
                    )
                )
    return findings


# -- HBX002: knob registry ---------------------------------------------------

KNOB_FULL_RE = re.compile(r"HBBFT_TPU_[A-Z0-9_]+\Z")
_C_KNOB_RE = re.compile(r'"(HBBFT_TPU_[A-Z0-9_]+)"')

# The scan surface: every tree that reads env knobs.  tools/lint/ (the
# registry + rule sources name knobs) and tests/test_lint.py (mutation
# fixtures) are excluded — they are the checker, not the checked.
_PY_SCAN_ROOTS = ("hbbft_tpu", "benchmarks", "tests", "tools", "examples")
_PY_SCAN_EXTRA = ("bench.py",)
_SKIP_DIRS = {"__pycache__", "build", ".jax_cache", ".git"}


def _scan_excluded(rel: str) -> bool:
    return rel.startswith("tools/lint/") or rel == "tests/test_lint.py"


def _py_scan_files(overrides: Overrides) -> List[str]:
    rels = set()
    for root in _PY_SCAN_ROOTS:
        absroot = os.path.join(_REPO, root)
        if not os.path.isdir(absroot):
            continue
        for dirpath, dirnames, filenames in os.walk(absroot):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith(".py"):
                    rels.add(os.path.relpath(os.path.join(dirpath, fn), _REPO))
    for rel in _PY_SCAN_EXTRA:
        if os.path.isfile(os.path.join(_REPO, rel)):
            rels.add(rel)
    if overrides:
        for rel in overrides:
            if rel.endswith(".py") and (
                rel in _PY_SCAN_EXTRA or rel.split("/", 1)[0] in _PY_SCAN_ROOTS
            ):
                rels.add(rel)
    return sorted(r for r in rels if not _scan_excluded(r))


def _c_scan_files(overrides: Overrides) -> List[str]:
    rels = set()
    absroot = os.path.join(_REPO, "native")
    if os.path.isdir(absroot):
        for dirpath, dirnames, filenames in os.walk(absroot):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith((".cpp", ".cc", ".c", ".h", ".hpp")):
                    rels.add(os.path.relpath(os.path.join(dirpath, fn), _REPO))
    if overrides:
        for rel in overrides:
            if rel.startswith("native/") and rel.endswith(
                (".cpp", ".cc", ".c", ".h", ".hpp")
            ):
                rels.add(rel)
    return sorted(rels)


def knob_references(overrides: Overrides = None) -> Dict[str, Tuple[str, int]]:
    """knob name -> (path, line) of its first reference site.

    Python side: AST string constants that ARE a knob name (getenv
    keys, environ subscripts, env-dict literals); prose mentions inside
    docstrings never fullmatch, so they don't count as references.  C
    side: string literals in comment-stripped source (getenv keys)."""
    refs: Dict[str, Tuple[str, int]] = {}
    for rel in _py_scan_files(overrides):
        src = _read_rel(rel, overrides)
        if src is None:
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and KNOB_FULL_RE.fullmatch(node.value)
            ):
                refs.setdefault(node.value, (rel, node.lineno))
    for rel in _c_scan_files(overrides):
        src = _read_rel(rel, overrides)
        if src is None:
            continue
        code = _cxx_strip_comments(src)
        for m in _C_KNOB_RE.finditer(code):
            refs.setdefault(m.group(1), (rel, _line_of(code, m.start())))
    return refs


def _registry_line(name: str, overrides: Overrides) -> int:
    src = _read_rel(KNOB_REGISTRY_PY, overrides)
    if src:
        for i, ln in enumerate(src.splitlines(), 1):
            if f'"{name}"' in ln:
                return i
    return 1


def rule_knob_registry(overrides: Overrides = None) -> List[Finding]:
    findings: List[Finding] = []
    refs = knob_references(overrides)
    registered = knob_registry.KNOBS
    for name, (path, line) in sorted(refs.items()):
        if name not in registered:
            findings.append(
                Finding(
                    "HBX002",
                    path,
                    line,
                    f"env knob {name} is not registered in "
                    "tools/lint/knob_registry.py — add its default, "
                    "owning layer, and A/B semantics, then regenerate "
                    "docs/KNOBS.md (python -m tools.lint --knobs-md)",
                )
            )
    for name in sorted(registered):
        if name not in refs:
            findings.append(
                Finding(
                    "HBX002",
                    KNOB_REGISTRY_PY,
                    _registry_line(name, overrides),
                    f"registered knob {name} has no os.environ/getenv "
                    "reference anywhere in the tree — retire the "
                    "registry entry (and regenerate docs/KNOBS.md) or "
                    "restore the reference",
                )
            )
    committed = _read_rel(KNOBS_MD, overrides)
    generated = knob_registry.generate_knobs_md()
    if committed is None or committed.rstrip("\n") != generated.rstrip("\n"):
        findings.append(
            Finding(
                "HBX002",
                KNOBS_MD,
                1,
                "docs/KNOBS.md is "
                + ("missing" if committed is None else "stale")
                + " vs the knob registry — regenerate with "
                "`python -m tools.lint --knobs-md > docs/KNOBS.md`",
            )
        )
    return findings


# -- HBX003: mirror obligations ----------------------------------------------

PY_MIRROR_RE = re.compile(r"#\s*mirror:\s*([A-Za-z0-9_.\-]+)")
CXX_MIRROR_RE = re.compile(r"//\s*mirror:\s*([A-Za-z0-9_.\-]+)")


def _py_mirror_files(overrides: Overrides) -> List[str]:
    rels = set()
    absroot = os.path.join(_REPO, "hbbft_tpu")
    for dirpath, dirnames, filenames in os.walk(absroot):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for fn in filenames:
            if fn.endswith(".py"):
                rels.add(os.path.relpath(os.path.join(dirpath, fn), _REPO))
    if overrides:
        for rel in overrides:
            if rel.startswith("hbbft_tpu/") and rel.endswith(".py"):
                rels.add(rel)
    return sorted(rels)


def _collect_anchors(
    files: List[str], rx: re.Pattern, overrides: Overrides
) -> Dict[str, Tuple[str, int]]:
    anchors: Dict[str, Tuple[str, int]] = {}
    for rel in files:
        src = _read_rel(rel, overrides)
        if src is None:
            continue
        for i, ln in enumerate(src.splitlines(), 1):
            m = rx.search(ln)
            if m:
                anchors.setdefault(m.group(1), (rel, i))
    return anchors


def rule_mirror_obligations(overrides: Overrides = None) -> List[Finding]:
    findings: List[Finding] = []
    py = _collect_anchors(_py_mirror_files(overrides), PY_MIRROR_RE, overrides)
    cxx = _collect_anchors(_c_scan_files(overrides), CXX_MIRROR_RE, overrides)
    for key in sorted(set(py) - set(cxx)):
        path, line = py[key]
        findings.append(
            Finding(
                "HBX003",
                path,
                line,
                f'mirror anchor "{key}" has no C++ twin — add '
                f"`// mirror: {key}` at the mirrored site under "
                "native/, or remove this anchor if the obligation is "
                "gone (both halves, never one)",
            )
        )
    for key in sorted(set(cxx) - set(py)):
        path, line = cxx[key]
        findings.append(
            Finding(
                "HBX003",
                path,
                line,
                f'mirror anchor "{key}" has no Python twin — add '
                f"`# mirror: {key}` at the mirrored site under "
                "hbbft_tpu/, or remove this anchor if the obligation "
                "is gone (both halves, never one)",
            )
        )
    return findings


def lint_contracts(overrides: Overrides = None) -> List[Finding]:
    """All cross-language contract findings (HBX001-003)."""
    findings = rule_wire_parity(overrides)
    findings.extend(rule_knob_registry(overrides))
    findings.extend(rule_mirror_obligations(overrides))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
