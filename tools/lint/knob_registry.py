"""The ``HBBFT_TPU_*`` environment-knob registry (HBX002 ground truth).

Every env knob the repo reads must have an entry here: its default, the
layer that owns it, and what flipping it means for an A/B run.  HBX002
(tools/lint/contracts.py) diffs this registry against every
``os.environ`` / ``getenv`` reference site in the tree — an
unregistered knob, a registered-but-unreferenced knob, or a stale
``docs/KNOBS.md`` is a finding.

To add a knob: add the ``Knob`` entry here, reference it in code, and
regenerate the doc (``python -m tools.lint --knobs-md >
docs/KNOBS.md``).  To retire one: delete the entry, delete every
reference, regenerate.  Half-measures trip the linter by design.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict


@dataclass(frozen=True)
class Knob:
    """One env knob: default, owning layer, and A/B semantics."""

    name: str
    default: str
    layer: str
    semantics: str


def _k(name: str, default: str, layer: str, semantics: str) -> Knob:
    return Knob(name, default, layer, semantics)


# Ordered: the generated docs/KNOBS.md table keeps this order.
KNOBS: Dict[str, Knob] = {
    k.name: k
    for k in (
        _k(
            "HBBFT_TPU_ARENA",
            "1 (on)",
            "native engine",
            "`0` makes the per-node epoch arena FREE its blocks at every "
            "watermark reset instead of recycling them (round-17 A/B "
            "arm).  Same containers, same carve order — outputs are "
            "byte-identical either way (docs/INVARIANTS.md \"epoch-state "
            "arena\"); only allocator traffic differs.  Read once at "
            "`hbe_create`.",
        ),
        _k(
            "HBBFT_TPU_CHUNK",
            "2048",
            "crypto/tpu backend (`TpuBackend`)",
            "Flush-kernel chunk rows.  Re-tune only with a fresh sweep: "
            "the round-4 kernel moved the optimum from 4096 to 2048 "
            "(BASELINE.md round 4); bigger buckets pay HBM pressure, "
            "smaller ones pay fixed pairing cost per chunk.",
        ),
        _k(
            "HBBFT_TPU_COIN_RLC",
            "1 (on)",
            "native engine + TS/TD protocols",
            "`0` restores per-share scalar COIN/DECRYPT verification on "
            "the same build (round-7 A/B arm).  Outputs are identical "
            "either way — RLC is an optimization, never a semantics "
            "change (docs/INVARIANTS.md \"RLC byte-identity\").",
        ),
        _k(
            "HBBFT_TPU_COALESCE",
            "1 (on)",
            "transport (TcpTransport egress)",
            "`0` restores one MSG frame per protocol message (round-20 "
            "A/B arm).  On, each egress sweep packs a peer's pending "
            "payloads into batched `KIND_MSGB` frames (bounded by "
            "`max_frame_len`), acked per FRAME with batch-atomic "
            "consumption — `batches_sha` is identical either way, and "
            "mixed clusters interop because ingress always accepts both "
            "kinds (docs/TRANSPORT.md \"Message coalescing\").",
        ),
        _k(
            "HBBFT_TPU_CRYPTO_RPC_TIMEOUT_S",
            "30.0",
            "cryptoplane/proc_service (RPC clients)",
            "Seconds an `RpcServiceClient` waits on one crypto-service "
            "RPC round trip before re-verifying THAT flush on its local "
            "fallback backend (verdict-identical — the deferred-"
            "verification invariant).  Generous by design: the fallback "
            "exists for service death, not scheduler jitter on a loaded "
            "1-core box.",
        ),
        _k(
            "HBBFT_TPU_CRYPTO_SERVICE",
            "unset (spawn per cluster)",
            "cryptoplane/proc_service + transport clusters",
            "`host:port` of an externally-run crypto-plane service "
            "process.  When set, `LocalCluster(crypto=\"service-proc\")` "
            "and `ProcCluster(crypto=\"service-proc\")` attach to it "
            "instead of spawning an owned worker — the way one "
            "TpuBackend service (started once, warm cache) serves many "
            "benchmark runs.",
        ),
        _k(
            "HBBFT_TPU_CRYPTO_SMOKE",
            "unset (off)",
            "tests (device tier)",
            "`1` makes tests/test_tpu_crypto.py skip the heavy "
            "pairing/flush compiles (~45 min warm full tier -> seconds).  "
            "The smoke tier is the time-boxed default; the full tier is "
            "for warm-cache/TPU sessions.",
        ),
        _k(
            "HBBFT_TPU_CT_HASH_CACHE",
            "1 (on)",
            "native engine",
            "`0` disables the shared-payload DKG-ciphertext hash cache "
            "(`Engine::ct_hash_by_payload`), restoring the round-5 "
            "per-(node, proposer) re-hash for era-change A/Bs "
            "(BASELINE.md round 6).",
        ),
        _k(
            "HBBFT_TPU_CRYPTO_WINDOW_S",
            "0.002",
            "cryptoplane/proc_service (service worker)",
            "The service process's cross-client batching window: how "
            "long the first pending verify request holds the flush open "
            "for more nodes' requests to merge in.  Bigger = larger "
            "amortized backend batches at higher per-check latency (the "
            "arxiv 2407.12172 trade); `0` flushes as soon as the worker "
            "wakes.  Worker `--window-s` overrides.",
        ),
        _k(
            "HBBFT_TPU_DKG_BATCH",
            "1 (on)",
            "crypto/keys + sync_key_gen",
            "`0` restores the round-5 per-item DKG ack/row checks, "
            "A/B-ing the whole round-6 batch plane (vectorized "
            "generate/combines, Part batch check, ack predigest) on one "
            "build.",
        ),
        _k(
            "HBBFT_TPU_ENGINE_LIB",
            "unset (build in-tree)",
            "native_engine loader",
            "Absolute path to a pre-built engine shared library "
            "(sanitizer builds use this).  A set-but-unloadable path is "
            "a loud failure, never a silent fallback.",
        ),
        _k(
            "HBBFT_TPU_JAX_CACHE",
            "`.jax_cache/`",
            "utils/jaxcache",
            "Persistent XLA compilation-cache directory.  Keep it "
            "between runs: cold flush-kernel compiles cost ~1.5-10 min "
            "per shape bucket on this box (CLAUDE.md).",
        ),
        _k(
            "HBBFT_TPU_NO_NATIVE",
            "unset (native on)",
            "ops/native builder",
            "Any value disables building/loading the native ops "
            "library; pure-Python fallbacks take over.  Correctness "
            "arm, not a perf arm.",
        ),
        _k(
            "HBBFT_TPU_SENDMSG",
            "unset (auto: gather egress)",
            "transport",
            "`0` forces buffered per-frame egress instead of the "
            "sendmsg/vectored gather path.  Perf-neutral at N=16 thread "
            "mode on this box (BASELINE.md round 14) — it exists for "
            "A/B honesty, not as a tuning lever here.",
        ),
        _k(
            "HBBFT_TPU_SHARD",
            "unset (off)",
            "crypto/tpu backend",
            "`1` shards the flush batch axis across all visible "
            "devices (virtual-CPU mesh or real chips).  Compiles a "
            "separate sharded flush pipeline — budget a cold compile.",
        ),
        _k(
            "HBBFT_TPU_SIMD",
            "unset (auto: cpuid)",
            "native field plane",
            "`0` pins the scalar Montgomery arm; `1` forces AVX-512 "
            "IFMA.  Arms are byte-identical by the SIMD dispatch "
            "identity invariant (docs/INVARIANTS.md); in-process flips "
            "use `hbe_simd_force(0|1|-1)`.",
        ),
        _k(
            "HBBFT_TPU_SKIP_BLS_ERA",
            "unset (test runs)",
            "tests (protocol tier)",
            "`1` skips the ~35 s real-BLS era-change test for quick "
            "protocol-tier loops (CLAUDE.md).",
        ),
        _k(
            "HBBFT_TPU_TESTS_ON_TPU",
            "unset (force CPU)",
            "tests/conftest",
            "`1` opts the test session out of the 8-device virtual-CPU "
            "forcing so device tests run against the real chip (relay "
            "required).",
        ),
    )
}


def generate_knobs_md() -> str:
    """The exact content of docs/KNOBS.md (HBX002 pins the committed
    file to this output byte-for-byte)."""
    lines = [
        "# HBBFT_TPU_* environment knobs",
        "",
        "<!-- GENERATED FILE — do not edit by hand.",
        "     Source of truth: tools/lint/knob_registry.py.",
        "     Regenerate: python -m tools.lint --knobs-md > docs/KNOBS.md -->",
        "",
        "Every environment knob the repo reads, with its default, owning",
        "layer, and A/B semantics.  The invariant linter (HBX002) keeps",
        "this file, the registry, and the reference sites in the code in",
        "three-way agreement: an unregistered knob, a dead registry",
        "entry, or a stale copy of this file fails `make lint`.",
        "",
    ]
    for k in KNOBS.values():
        lines.append(f"## `{k.name}`")
        lines.append("")
        lines.append(f"* **Default:** {k.default}")
        lines.append(f"* **Layer:** {k.layer}")
        lines.append(f"* **Semantics:** {k.semantics}")
        lines.append("")
    return "\n".join(lines)
