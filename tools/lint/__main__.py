"""CLI: ``python -m tools.lint [--json] [--knobs-md] [paths...]``.

No arguments lints the default surface (hbbft_tpu/**/*.py +
native/engine.cpp + the repo-level HBX contract rules).  Explicit paths
lint just those files (rules still scope by path, so fixture files must
carry repo-shaped names; the repo-level HBX rules are skipped); files no
rule applies to are reported as skipped, never silently blessed.  Exit
status 1 iff findings exist.

``--json`` emits one JSON object per finding per line
(``{"rule", "file", "line", "message"}``) on stdout — status chatter
stays on stderr, so CI can consume stdout without parsing human text.
``--knobs-md`` prints the generated docs/KNOBS.md content and exits
(``python -m tools.lint --knobs-md > docs/KNOBS.md`` is the regen
recipe HBX002 hints at).
"""

from __future__ import annotations

import json
import sys

from tools.lint import expand_paths, run_all


def main(argv: list[str]) -> int:
    as_json = "--json" in argv
    knobs_md = "--knobs-md" in argv
    argv = [a for a in argv if a not in ("--json", "--knobs-md")]
    flags = [a for a in argv if a.startswith("-")]
    if flags:
        print(
            f"tools.lint: unknown option(s) {flags} (usage:"
            " python -m tools.lint [--json] [--knobs-md] [paths...])",
            file=sys.stderr,
        )
        return 2
    if knobs_md:
        from tools.lint.knob_registry import generate_knobs_md

        sys.stdout.write(generate_knobs_md() + "\n")
        return 0
    if argv:
        files, skipped = expand_paths(argv)
        for p, reason in skipped:
            print(
                f"tools.lint: skipped {p} ({reason} — NOT checked)",
                file=sys.stderr,
            )
        if not files:
            print(
                "tools.lint: nothing lintable in the given paths",
                file=sys.stderr,
            )
            return 2
    findings = run_all(argv or None)
    for f in findings:
        if as_json:
            print(
                json.dumps(
                    {
                        "rule": f.rule,
                        "file": f.path,
                        "line": f.line,
                        "message": f.message,
                    }
                )
            )
        else:
            print(f.render())
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("tools.lint: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
