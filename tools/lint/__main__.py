"""CLI: ``python -m tools.lint [paths...]``.

No arguments lints the default surface (hbbft_tpu/**/*.py +
native/engine.cpp).  Explicit paths lint just those files (rules still
scope by path, so fixture files must carry repo-shaped names); files no
rule applies to are reported as skipped, never silently blessed.  Exit
status 1 iff findings exist.
"""

from __future__ import annotations

import sys

from tools.lint import expand_paths, run_all


def main(argv: list[str]) -> int:
    flags = [a for a in argv if a.startswith("-")]
    if flags:
        print(
            f"tools.lint: unknown option(s) {flags} (usage:"
            " python -m tools.lint [paths...])",
            file=sys.stderr,
        )
        return 2
    if argv:
        files, skipped = expand_paths(argv)
        for p, reason in skipped:
            print(
                f"tools.lint: skipped {p} ({reason} — NOT checked)",
                file=sys.stderr,
            )
        if not files:
            print(
                "tools.lint: nothing lintable in the given paths",
                file=sys.stderr,
            )
            return 2
    findings = run_all(argv or None)
    for f in findings:
        print(f.render())
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("tools.lint: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
