"""Invariant linter: machine-checks for the hand-maintained safety rules.

The protocol stack and native engine carry correctness rules that no
stock tool enforces (CLAUDE.md "Design invariants worth not breaking"
and the perf-state notes): ``add_unsafe`` call sites need a written
safety argument, every mutable ``Proposal``/``EpochState`` field must be
restored by the in-place resets, profiling counters are single-writer
under ``engine_run_mt``, interpret-mode ``pallas_call`` must never be
jitted, cross-``lax.scan`` accumulator chains crash XLA 0.9.0, and
wire-sourced group elements must reach a subgroup check.  This package
turns each of those prose invariants into a lint rule:

* :mod:`tools.lint.pylints` — Python AST rules (HBT0xx) over
  ``hbbft_tpu/``.
* :mod:`tools.lint.cxxlints` — lightweight structural rules (HBC0xx)
  over ``native/engine.cpp`` (no libclang on this box; the checks are
  regex/brace-tracking over comment-stripped source).
* :mod:`tools.lint.slot_registry` — the free/claimed profiling-slot
  registry HBC004 enforces.

Run ``python -m tools.lint`` from the repo root; exit status is nonzero
iff findings exist.  Each rule and its annotation escapes are documented
in docs/INVARIANTS.md.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclass(frozen=True)
class Finding:
    """One lint violation: rule id, file, 1-based line, message."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def default_python_files() -> Dict[str, str]:
    """path -> source for every tracked .py file under hbbft_tpu/."""
    out: Dict[str, str] = {}
    root = os.path.join(_REPO, "hbbft_tpu")
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r", encoding="utf-8") as f:
                out[os.path.relpath(path, _REPO)] = f.read()
    return out


def expand_paths(paths: List[str]) -> tuple[List[str], List[tuple[str, str]]]:
    """(lintable_files, skipped) for explicit CLI paths.

    Directories are walked for .py files and engine.cpp; anything the
    rules cannot apply to — or that does not exist — lands in
    ``skipped`` as (path, reason) so the caller can refuse to bless it
    silently.  The C++ rules encode engine.cpp-specific structure
    (Proposal/EpochState, the slot registry), so other C++ files have
    nothing for them to check.
    """
    files: List[str] = []
    skipped: List[tuple[str, str]] = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(_REPO, p)
        if os.path.isdir(ap):
            found = False
            for dirpath, _dirnames, filenames in os.walk(ap):
                for fn in sorted(filenames):
                    if fn.endswith(".py") or fn == "engine.cpp":
                        files.append(os.path.join(dirpath, fn))
                        found = True
            if not found:
                skipped.append((p, "no lintable files in directory"))
        elif not os.path.exists(ap):
            skipped.append((p, "not found"))
        elif ap.endswith(".py") or os.path.basename(ap) == "engine.cpp":
            files.append(ap)
        else:
            skipped.append((p, "no rules for this file"))
    return files, skipped


def run_all(paths: List[str] | None = None) -> List[Finding]:
    """Lint the repo (or just ``paths``); returns all findings.
    Explicit paths are expanded via :func:`expand_paths` (files no rule
    applies to are dropped — CLI callers surface those as skipped)."""
    from tools.lint import contracts, cxxlints, pylints

    findings: List[Finding] = []
    if paths:
        py: Dict[str, str] = {}
        files, _skipped = expand_paths(paths)
        for ap in files:
            rel = os.path.relpath(ap, _REPO)
            with open(ap, "r", encoding="utf-8") as f:
                src = f.read()
            if ap.endswith(".py"):
                py[rel] = src
            else:
                findings.extend(cxxlints.lint_source(src, rel))
        findings.extend(pylints.lint_files(py))
    else:
        findings.extend(pylints.lint_files(default_python_files()))
        engine = os.path.join(_REPO, "native", "engine.cpp")
        with open(engine, "r", encoding="utf-8") as f:
            findings.extend(cxxlints.lint_source(f.read(), "native/engine.cpp"))
        # Cross-language contract rules (HBX0xx) read a fixed repo-level
        # file set (wire.py <-> engine.cpp, the knob registry, mirror
        # anchors), so they only make sense for whole-repo runs —
        # explicit-path invocations skip them.
        findings.extend(contracts.lint_contracts())
    return sorted(findings, key=lambda x: (x.path, x.line, x.rule))
