"""Structural lint rules for native/engine.cpp (no libclang on this box).

The checks parse comment-stripped source with brace tracking — enough
structure for field lists, block extents, and guard scopes, which is all
these invariants need:

* HBC001 — every mutable field of ``Proposal``/``EpochState`` (and the
  nested ``Bcast``/``Ba``/``Sbv`` state) is restored by
  ``Proposal::reset`` / ``EpochState::reset_for_epoch``.  A missed field
  is cross-epoch contamination (the reset-in-place recycling relies on
  the resets being exhaustive; CLAUDE.md round-5 notes).  Intentionally
  persistent fields carry a ``// lint: not-reset (<why>)`` annotation on
  their declaration.  ``FlatMap``-typed fields (epoch-arena storage,
  round 17) must specifically call ``.drop()`` in the reset — a
  ``.clear()`` or whole-object assignment would carry a carve pointer
  into arena memory across the watermark reset (dangling after the
  next epoch's carves) — and the file must contain the single
  ``arena.reset(`` watermark site the drops rely on.
* HBC002 — profiling-counter writes are single-writer: each literal
  ``prof_cycles``/``prof_count`` write sits under an ``if
  (!e.mt_active))`` guard or in code annotated ``// lint: st-only``.
* HBC003 — worker-shared state (``decoded_roots``/``decoded_order``,
  ``mask_by_acc``/``mask_order`` under ``cache_mu``; ``cur_batch`` under
  ``cb_mu``) is only touched inside a matching ``std::lock_guard`` block
  or code annotated ``// lint: holds-<mutex>`` / ``// lint: st-only``.
* HBC004 — literal profiling-slot indices must be claimed in
  :mod:`tools.lint.slot_registry`; FREE slots fail lint until claimed,
  stale claims fail lint until released.

Annotations apply to their own line or the two lines above the use —
close enough that a reviewer sees claim and use together.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from tools.lint import Finding, _REPO
from tools.lint.slot_registry import CLAIMED_SLOTS, FREE_SLOTS, TYPED_DELIVERY_SLOTS

# Structs whose reset exhaustiveness is checked, with their reset method
# (None = reset via the parent that embeds them).
RESET_STRUCTS = ("Sbv", "Bcast", "Ba", "Proposal", "EpochState")
RESET_METHODS = {"Proposal": "reset", "EpochState": "reset_for_epoch"}

MUTEX_FOR = {
    "decoded_roots": "cache_mu",
    "decoded_order": "cache_mu",
    "mask_by_acc": "cache_mu",
    "mask_order": "cache_mu",
    "ct_hash_by_payload": "cache_mu",
    "ct_hash_order": "cache_mu",
    "cur_batch": "cb_mu",
}

NOT_RESET_RE = re.compile(r"lint:\s*not-reset")
ST_ONLY_RE = re.compile(r"lint:\s*st-only")
HOLDS_RE = re.compile(r"lint:\s*holds-(\w+)")


# ---------------------------------------------------------------------------
# Lightweight C++ preprocessing
# ---------------------------------------------------------------------------


def _strip(src: str) -> Tuple[List[str], List[str]]:
    """(code_lines, raw_lines): code has //, /* */ comments and string/char
    literals blanked (same length per line, so columns/regexes line up)."""
    raw_lines = src.splitlines()
    out: List[str] = []
    in_block = False
    for line in raw_lines:
        buf = []
        i = 0
        n = len(line)
        in_str: Optional[str] = None
        while i < n:
            c = line[i]
            if in_block:
                if c == "*" and i + 1 < n and line[i + 1] == "/":
                    in_block = False
                    buf.append("  ")
                    i += 2
                    continue
                buf.append(" ")
                i += 1
                continue
            if in_str:
                if c == "\\" and i + 1 < n:
                    buf.append("  ")
                    i += 2
                    continue
                if c == in_str:
                    in_str = None
                    buf.append(c)
                else:
                    buf.append(" ")
                i += 1
                continue
            if c == "/" and i + 1 < n and line[i + 1] == "/":
                break
            if c == "/" and i + 1 < n and line[i + 1] == "*":
                in_block = True
                buf.append("  ")
                i += 2
                continue
            if c in "\"'":
                in_str = c
                buf.append(c)
                i += 1
                continue
            buf.append(c)
            i += 1
        out.append("".join(buf))
    return out, raw_lines


class _Blocks:
    """Brace intervals: for each '{', its (open_line, close_line), 1-based."""

    def __init__(self, code_lines: List[str]) -> None:
        self.intervals: List[Tuple[int, int]] = []
        stack: List[int] = []
        for ln, line in enumerate(code_lines, 1):
            for c in line:
                if c == "{":
                    stack.append(ln)
                elif c == "}":
                    if stack:
                        self.intervals.append((stack.pop(), ln))
        # Unclosed braces: treat as extending to EOF.
        for open_ln in stack:
            self.intervals.append((open_ln, len(code_lines)))
        self.intervals.sort()

    def innermost_containing(self, line: int) -> Optional[Tuple[int, int]]:
        best = None
        for o, c in self.intervals:
            if o <= line <= c and (
                best is None or (o >= best[0] and c <= best[1])
            ):
                best = (o, c)
        return best

    def block_opening_at(self, line: int) -> Optional[Tuple[int, int]]:
        """The block whose '{' is on ``line`` or the next line (guard/if
        bodies)."""
        cands = [iv for iv in self.intervals if iv[0] in (line, line + 1)]
        if not cands:
            return None
        return max(cands, key=lambda iv: iv[0] * 100000 - iv[1])


def _annotated(raw_lines: List[str], line: int, regex: re.Pattern) -> bool:
    lo = max(line - 2, 1)
    return any(regex.search(raw_lines[i - 1]) for i in range(lo, line + 1))


def _not_reset_annotated(raw_lines: List[str], line: int) -> bool:
    """not-reset applies only to the declaration's own line or
    comment-ONLY lines immediately above it — an inline trailer on the
    PREVIOUS field must not leak onto this one (that would silently
    exempt its neighbor from the reset check)."""
    if NOT_RESET_RE.search(raw_lines[line - 1]):
        return True
    i = line - 1  # 1-based line above the declaration
    while i >= 1 and raw_lines[i - 1].strip().startswith("//"):
        if NOT_RESET_RE.search(raw_lines[i - 1]):
            return True
        i -= 1
    return False


def _find_struct_body(
    code_lines: List[str], name: str
) -> Optional[Tuple[int, int]]:
    """(body_open_line, body_close_line) of ``struct <name> {``."""
    pat = re.compile(rf"\bstruct\s+{name}\s*{{")
    blocks = _Blocks(code_lines)
    for ln, line in enumerate(code_lines, 1):
        if pat.search(line):
            iv = blocks.block_opening_at(ln)
            if iv:
                return iv
    return None


# ---------------------------------------------------------------------------
# Struct field extraction
# ---------------------------------------------------------------------------

_CXX_KEYWORDS = {
    "public", "private", "protected", "using", "typedef", "friend",
    "static", "constexpr", "enum",
}


def _split_top_commas(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for c in s:
        if c in "<([{":
            depth += 1
        elif c in ">)]}":
            depth -= 1
        if c == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    parts.append("".join(cur))
    return parts


def _field_name(declarator: str) -> Optional[str]:
    d = declarator.split("=", 1)[0]
    d = d.split("[", 1)[0]
    idents = re.findall(r"[A-Za-z_]\w*", d)
    if not idents:
        return None
    name = idents[-1]
    if name in _CXX_KEYWORDS:
        return None
    return name


def _type_of(statement: str, first_field: str) -> str:
    """The full type text before the first declarator name ('Bcast bc'
    -> 'Bcast'; 'std::map<int, Root> x' -> 'std::map<int, Root>') — the
    reset checker classifies it by its identifiers (a template holding a
    tracked struct must not slip past the nested-reset check)."""
    m = re.search(rf"\b{re.escape(first_field)}\b", statement)
    if not m:
        return ""
    return statement[: m.start()].strip()


def _body_chars(
    code_lines: List[str], body: Tuple[int, int]
) -> Tuple[str, List[int]]:
    """Struct body as one string (between the outer braces) + per-char
    line numbers."""
    open_ln, close_ln = body
    chars: List[str] = []
    lines: List[int] = []
    for ln in range(open_ln, close_ln + 1):
        line = code_lines[ln - 1]
        lo = line.find("{") + 1 if ln == open_ln else 0
        hi = line.rfind("}") if ln == close_ln else len(line)
        if hi < lo:
            hi = lo
        for c in line[lo:hi]:
            chars.append(c)
            lines.append(ln)
        chars.append("\n")
        lines.append(ln)
    return "".join(chars), lines


def _struct_fields(
    code_lines: List[str], raw_lines: List[str], body: Tuple[int, int]
) -> List[Tuple[str, str, int, bool]]:
    """[(field, type_token, line, not_reset_annotated)] for depth-1
    declarations; method bodies and nested types are skipped."""
    text, linemap = _body_chars(code_lines, body)
    fields: List[Tuple[str, str, int, bool]] = []
    seg: List[str] = []
    seg_lines: List[int] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "{":
            # Initializer braces ('= { ... }') stay part of the segment;
            # any other brace opens a method/ctor/nested-type body, which
            # voids the pending segment.
            tail = "".join(seg).rsplit(";", 1)[-1]
            is_init = re.search(r"=\s*[^;{}]*$", tail) is not None
            depth = 1
            j = i + 1
            while j < n and depth:
                if text[j] == "{":
                    depth += 1
                elif text[j] == "}":
                    depth -= 1
                j += 1
            if is_init:
                seg.append(text[i:j])
                seg_lines.append(linemap[i])
            else:
                seg = []
                seg_lines = []
            i = j
            continue
        if c == ";":
            stmt = "".join(seg).strip().replace("\n", " ")
            first_line = seg_lines[0] if seg_lines else linemap[i]
            last_line = linemap[i]
            seg = []
            seg_lines = []
            i += 1
            if not stmt or "(" in stmt.split("=", 1)[0]:
                continue
            if any(re.match(rf"\b{k}\b", stmt) for k in _CXX_KEYWORDS):
                continue
            decls = _split_top_commas(stmt)
            first = _field_name(decls[0])
            if not first:
                continue
            ftype = _type_of(stmt, first)
            annotated = _not_reset_annotated(raw_lines, last_line)
            fields.append((first, ftype, last_line, annotated))
            for d in decls[1:]:
                nm = _field_name(d)
                if nm:
                    fields.append((nm, ftype, last_line, annotated))
            continue
        if c.strip():
            if not seg:
                seg_lines = [linemap[i]]
            seg.append(c)
        elif seg:
            seg.append(" ")
        i += 1
    return fields


# ---------------------------------------------------------------------------
# HBC001: exhaustive in-place resets
# ---------------------------------------------------------------------------


def _method_body_text(
    code_lines: List[str], struct_body: Tuple[int, int], method: str
) -> Optional[str]:
    """Flat text of ``void <method>() { ... }`` inside the struct body."""
    pat = re.compile(rf"\bvoid\s+{method}\s*\(\s*\)")
    blocks = _Blocks(code_lines)
    for ln in range(struct_body[0], struct_body[1] + 1):
        if pat.search(code_lines[ln - 1]):
            iv = blocks.block_opening_at(ln)
            if iv:
                return "\n".join(code_lines[iv[0] - 1 : iv[1]])
    return None


def _mentioned(body: str, dotted: str) -> bool:
    """Is ``a.b.c`` (or a bare field) mentioned as a reset target?  Any
    word-boundary mention counts — the failure mode this rule defends
    against is a field FORGOTTEN entirely, which name-mention catches."""
    head = dotted.split(".")[0]
    pat = re.escape(dotted).replace(r"\.", r"\s*\.\s*")
    return (
        re.search(rf"(?<![\w.]){pat}(?![\w])", body) is not None
        if "." in dotted
        else re.search(rf"(?<![\w.]){re.escape(head)}\b", body) is not None
    )


def _check_reset_coverage(
    structs: Dict[str, List[Tuple[str, str, int, bool]]],
    struct_name: str,
    prefix: str,
    body: str,
    path: str,
    reset_line: int,
    findings: List[Finding],
) -> None:
    for field, ftype, decl_line, annotated in structs[struct_name]:
        if annotated:
            continue
        dotted = f"{prefix}{field}"
        type_idents = re.findall(r"[A-Za-z_]\w*", ftype)
        direct = type_idents[-1] if type_idents else ""
        if direct in structs:
            # Nested protocol state: a whole-object assignment
            # ('ba.sbv = Sbv()') resets every nested field at once;
            # otherwise require each nested field via 'prefix.field.*'.
            pat = re.escape(dotted).replace(r"\.", r"\s*\.\s*")
            if re.search(rf"(?<![\w.]){pat}\s*=(?!=)", body):
                continue
            _check_reset_coverage(
                structs, direct, dotted + ".", body, path, reset_line, findings
            )
            continue
        if "FlatMap" in type_idents:
            # Epoch-arena storage (round 17): the reset must forget the
            # carve with .drop() — name-mention via .clear() or an
            # assignment would keep v/present pointing into arena
            # memory that the watermark reset is about to recycle.
            pat = re.escape(dotted).replace(r"\.", r"\s*\.\s*")
            if re.search(rf"(?<![\w.]){pat}\s*\.\s*drop\s*\(", body):
                continue
            findings.append(
                Finding(
                    "HBC001",
                    path,
                    decl_line,
                    f"FlatMap field '{dotted}' of {struct_name} must be"
                    " restored with '.drop()' in the in-place reset"
                    f" (line {reset_line}): its storage lives in the"
                    " epoch arena, so '.clear()' or assignment would"
                    " carry a dangling carve pointer across the"
                    " watermark reset (docs/INVARIANTS.md 'epoch-state"
                    " arena')",
                )
            )
            continue
        if any(t in structs for t in type_idents):
            # Container of tracked structs (std::vector<Proposal>,
            # std::array<Ba, 2>, ...): per-element resets cannot be
            # verified statically, so a bare mention must not pass.
            findings.append(
                Finding(
                    "HBC001",
                    path,
                    decl_line,
                    f"'{dotted}' of {struct_name} holds"
                    " reset-tracked structs inside a container: the"
                    " checker cannot verify per-element resets — reset"
                    " each element explicitly and annotate the"
                    " declaration '// lint: not-reset (elements reset"
                    " via ...)'",
                )
            )
            continue
        if _mentioned(body, dotted):
            continue
        findings.append(
            Finding(
                "HBC001",
                path,
                decl_line,
                f"mutable field '{dotted}' of {struct_name} is not restored"
                f" by the in-place reset (line {reset_line}): a missed field"
                " is cross-epoch contamination (reset-in-place recycling,"
                " CLAUDE.md round 5). Reset it, or annotate the declaration"
                " '// lint: not-reset (<why>)' if it is intentionally"
                " persistent",
            )
        )


def rule_field_reset(
    code_lines: List[str], raw_lines: List[str], path: str
) -> List[Finding]:
    findings: List[Finding] = []
    structs: Dict[str, List[Tuple[str, str, int, bool]]] = {}
    bodies: Dict[str, Tuple[int, int]] = {}
    for name in RESET_STRUCTS:
        body = _find_struct_body(code_lines, name)
        if body is None:
            continue
        bodies[name] = body
        structs[name] = _struct_fields(code_lines, raw_lines, body)
    for owner, method in RESET_METHODS.items():
        if owner not in bodies:
            findings.append(
                Finding("HBC001", path, 1, f"struct {owner} not found")
            )
            continue
        mbody = _method_body_text(code_lines, bodies[owner], method)
        if mbody is None:
            findings.append(
                Finding(
                    "HBC001",
                    path,
                    bodies[owner][0],
                    f"{owner}::{method} not found (the reset-in-place"
                    " recycling depends on it)",
                )
            )
            continue
        reset_line = bodies[owner][0]
        _check_reset_coverage(
            structs, owner, "", mbody, path, reset_line, findings
        )
    # Arena watermark site (round 17): the FlatMap .drop() idiom above
    # only reclaims storage because ONE per-epoch arena.reset( call
    # exists — if it disappears, every dropped carve leaks until the
    # node dies.
    code = "\n".join(code_lines)
    if re.search(r"\bFlatMap\s*<", code) and not re.search(
        r"\barena\s*\.\s*reset\s*\(", code
    ):
        findings.append(
            Finding(
                "HBC001",
                path,
                1,
                "FlatMap fields exist but no 'arena.reset(' watermark"
                " site does: dropped carves are never reclaimed"
                " (docs/INVARIANTS.md 'epoch-state arena')",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# HBC002: profiling counters are single-writer
# ---------------------------------------------------------------------------

# Any identifier may hold the engine reference ('e', 'eng', 'engine'):
# restricting the receiver to a literal 'e' would let one renamed
# parameter disable the whole rule.
_REF = r"(?:[A-Za-z_]\w*\s*(?:\.|->)\s*)?"
_PROF_WRITE_RE = re.compile(
    rf"(?<![\w.]){_REF}prof_(?:cycles|count)\s*\[[^\]]*\]\s*"
    r"(\+\+|--|\+=|-=|\|=|&=|\^=|=(?!=))"
)
_DECL_RE = re.compile(r"\buint64_t\s+prof_(?:cycles|count)\b")
_MT_GUARD_RE = re.compile(rf"if\s*\(\s*!\s*{_REF}mt_active\s*\)")


def _guard_intervals(
    code_lines: List[str], blocks: _Blocks, guard_re: re.Pattern
) -> List[Tuple[int, int]]:
    """Line ranges covered by each guard.  The guarded region is located
    from the text AFTER the condition — a brace on an unrelated next
    line must not be mistaken for the guard's block (that would bless
    ungoverned writes inside it)."""

    def _block_from(open_line: int) -> Tuple[int, int]:
        ivs = [iv for iv in blocks.intervals if iv[0] == open_line]
        # Smallest block opening on that line: over-covering risks
        # blessing writes the guard does not actually govern.
        return min(ivs, key=lambda iv: iv[1]) if ivs else (open_line, open_line)

    out = []
    for ln, line in enumerate(code_lines, 1):
        m = guard_re.search(line)
        if not m:
            continue
        rest = line[m.end():].strip()
        if "{" in rest:
            out.append(_block_from(ln))  # if (...) { ... }
        elif rest:
            out.append((ln, ln))  # braceless, statement on the same line
        else:
            nxt = code_lines[ln].strip() if ln < len(code_lines) else ""
            if nxt.startswith("{"):
                out.append(_block_from(ln + 1))  # Allman brace
            else:
                out.append((ln + 1, ln + 1))  # braceless, next line
    return out


def rule_prof_guard(
    code_lines: List[str], raw_lines: List[str], path: str
) -> List[Finding]:
    findings: List[Finding] = []
    blocks = _Blocks(code_lines)
    guards = _guard_intervals(code_lines, blocks, _MT_GUARD_RE)
    for ln, line in enumerate(code_lines, 1):
        if _DECL_RE.search(line):
            continue
        if not _PROF_WRITE_RE.search(line):
            continue
        if any(o <= ln <= c for o, c in guards):
            continue
        if _annotated(raw_lines, ln, ST_ONLY_RE):
            continue
        findings.append(
            Finding(
                "HBC002",
                path,
                ln,
                "profiling-counter write outside an 'if (!e.mt_active)'"
                " guard: counters are single-writer (engine_run_mt workers"
                " must never stamp them; CLAUDE.md multicore rules)."
                " Guard it, or annotate '// lint: st-only (<why>)' for"
                " code unreachable from worker threads",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# HBC003: shared caches / batch staging only under their mutex
# ---------------------------------------------------------------------------

_LOCK_RE = re.compile(
    r"lock_guard\s*<[^>]*>\s*\w+\s*\(\s*(?:[A-Za-z_]\w*\s*(?:\.|->)\s*)?(\w+)\s*\)"
)
_SHARED_DECL_RE = re.compile(
    r"^\s*(?:std::|mutable\s|const\s)\S*\s*<.*>\s*\w+\s*;\s*$"
)


def rule_lock_guard(
    code_lines: List[str], raw_lines: List[str], path: str
) -> List[Finding]:
    findings: List[Finding] = []
    blocks = _Blocks(code_lines)
    # lock_guard coverage: from the lock statement to the close of the
    # innermost block containing it.
    locks: List[Tuple[str, int, int]] = []  # (mutex, from_line, to_line)
    for ln, line in enumerate(code_lines, 1):
        for m in _LOCK_RE.finditer(line):
            iv = blocks.innermost_containing(ln)
            locks.append((m.group(1), ln, iv[1] if iv else len(code_lines)))
    for name, mutex in MUTEX_FOR.items():
        for ln, line in enumerate(code_lines, 1):
            if not re.search(rf"\b{name}\b", line):
                continue
            if _SHARED_DECL_RE.match(line):
                continue  # the declaration inside struct Engine
            if any(mx == mutex and lo <= ln <= hi for mx, lo, hi in locks):
                continue
            if _annotated(raw_lines, ln, ST_ONLY_RE):
                continue
            holds = [
                hm.group(1)
                for i in range(max(ln - 2, 1), ln + 1)
                for hm in HOLDS_RE.finditer(raw_lines[i - 1])
            ]
            if mutex in holds:
                continue
            findings.append(
                Finding(
                    "HBC003",
                    path,
                    ln,
                    f"'{name}' is touched without holding {mutex}:"
                    " worker-reachable shared state (CLAUDE.md multicore"
                    " rules). Take a std::lock_guard, or annotate"
                    f" '// lint: holds-{mutex} (<why>)' when the caller"
                    " provably holds it (or '// lint: st-only')",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# HBC004: profiling-slot registry
# ---------------------------------------------------------------------------

_SLOT_RE = re.compile(r"\bprof_(?:cycles|count)\s*\[\s*(\d+)\s*\]")


def rule_slot_registry(
    code_lines: List[str], raw_lines: List[str], path: str
) -> List[Finding]:
    findings: List[Finding] = []
    seen: Dict[int, int] = {}
    is_engine = path.endswith("engine.cpp")
    for ln, line in enumerate(code_lines, 1):
        if _DECL_RE.search(line):
            continue  # the [16] in the array declaration
        for m in _SLOT_RE.finditer(line):
            slot = int(m.group(1))
            seen.setdefault(slot, ln)
            if slot in CLAIMED_SLOTS:
                continue
            if slot in FREE_SLOTS:
                findings.append(
                    Finding(
                        "HBC004",
                        path,
                        ln,
                        f"literal profiling slot {slot} is FREE in"
                        " tools/lint/slot_registry.py: claim it there (in"
                        " this change) before stamping, so concurrent"
                        " instrumentation never corrupts a profile",
                    )
                )
            elif slot in TYPED_DELIVERY_SLOTS:
                findings.append(
                    Finding(
                        "HBC004",
                        path,
                        ln,
                        f"literal profiling slot {slot} is in the typed"
                        " delivery range (prof_cycles[ty], MsgType 0..10):"
                        " a literal stamp there corrupts the per-type"
                        " delivery profile",
                    )
                )
            else:
                findings.append(
                    Finding(
                        "HBC004",
                        path,
                        ln,
                        f"literal profiling slot {slot} is out of range"
                        " (the engine has 16 slots)",
                    )
                )
    # Stale-claim detection is only meaningful against the registry's
    # single source of truth (the real engine.cpp) — fixtures and
    # partial sources legitimately omit claimed slots.
    for slot, owner in CLAIMED_SLOTS.items() if is_engine else ():
        if slot not in seen:
            findings.append(
                Finding(
                    "HBC004",
                    path,
                    1,
                    f"slot {slot} is claimed in tools/lint/slot_registry.py"
                    f" ('{owner}') but never used in {path}: release the"
                    " stale claim so the slot returns to the free pool",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# HBC005: trace-event taxonomy parity (enum TraceKind <-> exporter table)
# ---------------------------------------------------------------------------

_TRACE_ENUM_OPEN_RE = re.compile(r"\benum\s+TraceKind\b")
_TRACE_ENTRY_RE = re.compile(r"\b(TR_[A-Z0-9_]+)\s*=\s*(\d+)")
_EXPORTER_REL = os.path.join("hbbft_tpu", "native_engine.py")
_TAXONOMY_DOC_REL = os.path.join("docs", "OBSERVABILITY.md")


def _enum_to_name(entry: str) -> str:
    """``TR_EPOCH_OPEN`` -> ``epoch.open`` (the documented mapping:
    strip the prefix, lowercase, underscores become dots)."""
    return entry[len("TR_"):].lower().replace("_", ".")


def _parse_trace_enum(
    code_lines: List[str],
) -> Optional[Dict[int, Tuple[str, int]]]:
    """value -> (TR_ name, line) from the ``enum TraceKind`` block;
    None when the source has no such enum (fixtures)."""
    for ln, line in enumerate(code_lines, 1):
        if _TRACE_ENUM_OPEN_RE.search(line):
            out: Dict[int, Tuple[str, int]] = {}
            for off, body in enumerate(code_lines[ln - 1:]):
                for m in _TRACE_ENTRY_RE.finditer(body):
                    out[int(m.group(2))] = (m.group(1), ln + off)
                if "}" in body:
                    return out
            return out
    return None


def _exporter_table() -> Optional[Dict[int, str]]:
    """The ``TRACE_KIND_NAMES`` dict literal from native_engine.py,
    parsed via ast (never imported — lint must not load ctypes libs)."""
    import ast

    path = os.path.join(_REPO, _EXPORTER_REL)
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "TRACE_KIND_NAMES"
            for t in node.targets
        ):
            try:
                return ast.literal_eval(node.value)
            except ValueError:
                return None
    return None


def rule_trace_taxonomy(
    code_lines: List[str], raw_lines: List[str], path: str
) -> List[Finding]:
    """Every ``TraceKind`` enum value must have a matching entry in the
    exporter's taxonomy table (``native_engine.TRACE_KIND_NAMES``) and
    vice versa, and every mapped name must appear in the
    docs/OBSERVABILITY.md taxonomy table — the shared-taxonomy contract
    was prose-only before round 16.  A kind the exporter cannot name
    surfaces as an opaque ``engine.k<N>`` event; a name the engine never
    emits is a dead taxonomy row."""
    enum = _parse_trace_enum(code_lines)
    if enum is None:
        return []  # fixture / partial source: nothing to check
    findings: List[Finding] = []
    table = _exporter_table()
    if table is None:
        return [
            Finding(
                "HBC005",
                path,
                1,
                f"cannot parse TRACE_KIND_NAMES from {_EXPORTER_REL}:"
                " the TraceKind taxonomy check needs the exporter table"
                " as a plain dict literal",
            )
        ]
    try:
        with open(
            os.path.join(_REPO, _TAXONOMY_DOC_REL), "r", encoding="utf-8"
        ) as f:
            doc = f.read()
    except OSError:
        doc = ""
    for value, (entry, ln) in sorted(enum.items()):
        want = _enum_to_name(entry)
        got = table.get(value)
        if got is None:
            findings.append(
                Finding(
                    "HBC005",
                    path,
                    ln,
                    f"TraceKind {entry} = {value} has no entry in"
                    f" {_EXPORTER_REL} TRACE_KIND_NAMES: the exporter"
                    f" would surface it as opaque engine.k{value} —"
                    f" add {value}: \"{want}\" (and decode its args)",
                )
            )
        elif got != want:
            findings.append(
                Finding(
                    "HBC005",
                    path,
                    ln,
                    f"TraceKind {entry} = {value} maps to"
                    f" {got!r} in TRACE_KIND_NAMES but the naming rule"
                    f" (strip TR_, lowercase, '_' -> '.') says {want!r}:"
                    " rename one side so grep finds both",
                )
            )
        if f"`{want}`" not in doc:
            findings.append(
                Finding(
                    "HBC005",
                    path,
                    ln,
                    f"milestone `{want}` ({entry}) is missing from the"
                    f" {_TAXONOMY_DOC_REL} event-taxonomy table: document"
                    " its args and emit point",
                )
            )
    for value, name in sorted(table.items()):
        if value not in enum:
            findings.append(
                Finding(
                    "HBC005",
                    path,
                    1,
                    f"TRACE_KIND_NAMES maps {value} -> {name!r} but"
                    f" enum TraceKind has no value {value}: dead taxonomy"
                    " row (or the engine entry was removed without the"
                    " exporter)",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

_RULES = (
    rule_field_reset,
    rule_prof_guard,
    rule_lock_guard,
    rule_slot_registry,
    rule_trace_taxonomy,
)


def lint_source(src: str, path: str = "native/engine.cpp") -> List[Finding]:
    """Lint C++ source text (tests feed patched strings through this)."""
    code_lines, raw_lines = _strip(src)
    findings: List[Finding] = []
    for rule in _RULES:
        findings.extend(rule(code_lines, raw_lines, path))
    return findings
