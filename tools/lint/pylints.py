"""Python AST lint rules for the documented hbbft_tpu invariants.

Rules (ids referenced from docs/INVARIANTS.md):

* HBT001 — every ``add_unsafe`` call in ``hbbft_tpu/crypto/tpu/`` needs
  a written safety argument: a ``# safety:`` comment on the call (or
  within two lines above it) or an enclosing function docstring that
  mentions ``safety``.
* HBT002 — a child :class:`Step` must not be reused after
  ``map_messages`` (it mutates in place; the old name now aliases the
  wrapped step).
* HBT003 — never ``jax.jit`` a function that constructs an
  interpret-mode ``pallas_call`` (the interpreter's expansion has
  unbounded XLA/LLVM compile time; CLAUDE.md environment gotchas).
* HBT004 — no accumulator chain updated *between* sequential
  ``lax.scan`` segments (XLA 0.9.0 "Unknown MLIR failure", bisected
  round 4; collect per-segment values and reduce once after all scans —
  see ``_tree_sum_axis0`` in ``crypto/tpu/curve.py``).
* HBT005 — wire-deserialization and verify-batch surfaces must reach a
  subgroup check on point inputs (CLAUDE.md: "wire-sourced points MUST
  get subgroup checks somewhere").
* HBT006 — every socket read in ``hbbft_tpu/`` honors the max-frame
  plumbing: ``.recv(...)`` must pass the shared ``RECV_CHUNK`` bound (or
  a literal <= 65536), so no syscall hands the process more untrusted
  bytes than the :class:`FrameDecoder` cap logic admits per read
  (docs/TRANSPORT.md; ``# lint: raw-recv`` escapes non-socket recv()s).

All rules work on (virtual) repo-relative paths, so tests can feed
fixture sources through :func:`lint_files` without touching disk.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.lint import Finding

SAFETY_COMMENT_RE = re.compile(r"#\s*safety:", re.IGNORECASE)
NO_SUBGROUP_RE = re.compile(r"#\s*lint:\s*no-subgroup", re.IGNORECASE)
RAW_RECV_RE = re.compile(r"#\s*lint:\s*raw-recv", re.IGNORECASE)

#: recv() bound HBT006 accepts as a literal; matches framing.RECV_CHUNK.
MAX_RECV_LITERAL = 1 << 16


def _call_name(node: ast.expr) -> Optional[str]:
    """Bare name of a call target: ``foo`` and ``a.b.foo`` -> ``foo``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _dotted(node: ast.expr) -> str:
    """Best-effort dotted name (``jax.lax.scan``); '' if not a name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_scan_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _call_name(node.func) == "scan"
        and "lax" in _dotted(node.func)
    )


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _function_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


# ---------------------------------------------------------------------------
# HBT001: add_unsafe safety annotations
# ---------------------------------------------------------------------------


def rule_add_unsafe_safety(path: str, src: str, tree: ast.AST) -> List[Finding]:
    if "crypto/tpu/" not in path.replace("\\", "/"):
        return []
    lines = src.splitlines()
    safety_lines = {
        i for i, line in enumerate(lines, 1) if SAFETY_COMMENT_RE.search(line)
    }

    findings: List[Finding] = []

    def docstring_covers(fn: ast.AST) -> bool:
        doc = ast.get_docstring(fn, clean=False) if isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) else None
        return bool(doc and "safety" in doc.lower())

    def visit(node: ast.AST, stack: Tuple[ast.AST, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            child_stack = stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_stack = stack + (child,)
            if (
                isinstance(child, ast.Call)
                and _call_name(child.func) == "add_unsafe"
            ):
                covered = any(
                    ln in safety_lines
                    for ln in range(child.lineno - 2, child.lineno + 1)
                ) or any(docstring_covers(fn) for fn in stack)
                if not covered:
                    findings.append(
                        Finding(
                            "HBT001",
                            path,
                            child.lineno,
                            "add_unsafe call without a safety argument: add a"
                            " '# safety: ...' comment here or a 'safety'"
                            " argument in the enclosing docstring"
                            " (add_unsafe is WRONG for P == ±Q; CLAUDE.md"
                            " invariant)",
                        )
                    )
            visit(child, child_stack)

    visit(tree, ())
    return findings


# ---------------------------------------------------------------------------
# HBT002: no reuse of a child Step after map_messages
# ---------------------------------------------------------------------------


def rule_step_reuse(path: str, src: str, tree: ast.AST) -> List[Finding]:
    findings: List[Finding] = []

    for fn in _function_defs(tree):
        # Events within THIS function's immediate body (nested defs get
        # their own pass; their closures see names at call time, which
        # lexical order cannot rank — excluded to avoid false positives).
        own_nodes: List[ast.AST] = []

        def collect(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                own_nodes.append(child)
                collect(child)

        collect(fn)

        # map_messages calls on a simple name, excluding self-rebinding
        # (step = step.map_messages(...) leaves no stale alias behind).
        calls: List[Tuple[str, int]] = []
        for node in own_nodes:
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "map_messages"
                and isinstance(node.func.value, ast.Name)
            ):
                calls.append((node.func.value.id, node.lineno))
        if not calls:
            continue

        for name, call_line in calls:
            # >= call_line: the call statement's own assignment target
            # counts — 'step = step.map_messages(...)' rebinds the name
            # to the wrapped step, leaving no stale alias.
            stores = [
                n.lineno
                for n in own_nodes
                if isinstance(n, ast.Name)
                and n.id == name
                and isinstance(n.ctx, ast.Store)
                and n.lineno >= call_line
            ]
            rebound_at = min(stores) if stores else None
            for n in own_nodes:
                if (
                    isinstance(n, ast.Name)
                    and n.id == name
                    and isinstance(n.ctx, ast.Load)
                    and n.lineno > call_line
                    and (rebound_at is None or n.lineno < rebound_at)
                ):
                    findings.append(
                        Finding(
                            "HBT002",
                            path,
                            n.lineno,
                            f"'{name}' is reused after map_messages (line"
                            f" {call_line}): map_messages mutates the child"
                            " step in place; never reuse it (CLAUDE.md"
                            " invariant)",
                        )
                    )
    return findings


# ---------------------------------------------------------------------------
# HBT003: no jit of interpret-mode pallas_call constructors
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jit", "pjit"}


def _interpret_kw(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "interpret":
            return kw.value
    return None


def _pallas_interpret_status(fn: ast.AST) -> Optional[str]:
    """'capable' (interpret is an expression/param), 'always'
    (interpret=True literal), or None (no interpret-mode pallas_call)."""
    status = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _call_name(node.func) == "pallas_call":
            kw = _interpret_kw(node)
            if kw is None:
                continue  # defaults to compiled mode
            if isinstance(kw, ast.Constant):
                if kw.value is True:
                    return "always"
                continue  # interpret=False literal
            status = "capable"
    return status


def rule_jit_interpret_pallas(path: str, src: str, tree: ast.AST) -> List[Finding]:
    status_by_name: Dict[str, str] = {}
    for fn in _function_defs(tree):
        st = _pallas_interpret_status(fn)
        if st is not None:
            # Prefer 'always' if any same-named def has it.
            prev = status_by_name.get(fn.name)
            status_by_name[fn.name] = (
                "always" if "always" in (st, prev) else st
            )
    findings: List[Finding] = []

    def flag(line: int, fname: str, how: str) -> None:
        findings.append(
            Finding(
                "HBT003",
                path,
                line,
                f"jit wraps '{fname}', which constructs an interpret-mode"
                f" pallas_call ({how}): jitting the interpreter's expansion"
                " has unbounded XLA/LLVM compile time (CLAUDE.md gotcha);"
                " pin interpret=False under jit, run interpret mode eagerly",
            )
        )

    def check_jit_arg(arg: ast.expr, line: int) -> None:
        if isinstance(arg, ast.Name) and arg.id in status_by_name:
            how = (
                "interpret=True"
                if status_by_name[arg.id] == "always"
                else "interpret not statically pinned False"
            )
            flag(line, arg.id, how)
        elif (
            isinstance(arg, ast.Call)
            and _call_name(arg.func) == "partial"
            and arg.args
            and isinstance(arg.args[0], ast.Name)
            and arg.args[0].id in status_by_name
        ):
            fname = arg.args[0].id
            kw = _interpret_kw(arg)
            pinned_false = (
                isinstance(kw, ast.Constant) and kw.value is False
            )
            if status_by_name[fname] == "always":
                flag(line, fname, "interpret=True")
            elif not pinned_false:
                flag(line, fname, "interpret not statically pinned False")

    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and _call_name(node.func) in _JIT_NAMES
            and node.args
        ):
            check_jit_arg(node.args[0], node.lineno)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dec_name = (
                    _call_name(dec.func)
                    if isinstance(dec, ast.Call)
                    else _call_name(dec)
                )
                # @partial(jax.jit, static_argnums=...) — the standard
                # idiom for jitting with options — is a jit decorator.
                if (
                    dec_name == "partial"
                    and isinstance(dec, ast.Call)
                    and dec.args
                    and _call_name(dec.args[0]) in _JIT_NAMES
                ):
                    dec_name = _call_name(dec.args[0])
                if dec_name in _JIT_NAMES and _pallas_interpret_status(node):
                    how = (
                        "interpret=True"
                        if _pallas_interpret_status(node) == "always"
                        else "interpret not statically pinned False"
                    )
                    flag(node.lineno, node.name, how)
    return findings


# ---------------------------------------------------------------------------
# HBT004: cross-scan accumulator chains (the XLA 0.9.0 killer)
# ---------------------------------------------------------------------------


def rule_scan_accumulator(path: str, src: str, tree: ast.AST) -> List[Finding]:
    findings: List[Finding] = []

    for fn in _function_defs(tree):
        # Statements of this function only (nested defs excluded: a scan
        # inside a nested def does not run interleaved with our stmts).
        own_stmts: List[ast.stmt] = []

        def collect(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                if isinstance(child, ast.stmt):
                    own_stmts.append(child)
                collect(child)

        collect(fn)

        scan_stmts = [s for s in own_stmts if any(
            _is_scan_call(n) for n in ast.walk(s)
            if not isinstance(n, (ast.FunctionDef, ast.Lambda))
        )]
        if not scan_stmts:
            continue

        # Names that flow through any scan (carry in or out): those form
        # the scan dataflow and are exactly the SAFE pattern (pow_x_abs,
        # the run-length Miller loop).  The killer is a side accumulator
        # that bypasses the scans.
        scan_flow: Set[str] = set()
        for s in scan_stmts:
            for node in ast.walk(s):
                if _is_scan_call(node):
                    for arg in node.args:
                        scan_flow |= _names_in(arg)
            if isinstance(s, ast.Assign):
                for tgt in s.targets:
                    scan_flow |= _names_in(tgt)

        scan_lines = sorted(s.lineno for s in scan_stmts)
        loops_with_scans: List[ast.stmt] = [
            loop
            for loop in own_stmts
            if isinstance(loop, (ast.For, ast.While))
            and any(s in ast.walk(loop) for s in scan_stmts)
        ]
        multi_segment = len(scan_lines) >= 2 or bool(loops_with_scans)
        if not multi_segment:
            continue

        for stmt in own_stmts:
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            tgt = stmt.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            val = stmt.value
            if not isinstance(val, ast.Call) or _is_scan_call(val):
                continue
            name = tgt.id
            arg_names: Set[str] = set()
            for a in list(val.args) + [kw.value for kw in val.keywords]:
                arg_names |= _names_in(a)
            if name not in arg_names or name in scan_flow:
                continue
            between = (
                len(scan_lines) >= 2
                and scan_lines[0] < stmt.lineno < scan_lines[-1]
            )
            in_scan_loop = any(
                stmt in ast.walk(loop) for loop in loops_with_scans
            )
            if between or in_scan_loop:
                findings.append(
                    Finding(
                        "HBT004",
                        path,
                        stmt.lineno,
                        f"accumulator '{name}' is updated between sequential"
                        " lax.scan segments without flowing through the scan"
                        " carry: XLA 0.9.0 dies with 'Unknown MLIR failure'"
                        " on this shape (bisected round 4). Collect the"
                        " per-segment values and reduce once AFTER all scans"
                        " (see _tree_sum_axis0 in crypto/tpu/curve.py)",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# HBT005: wire/backends must reach a subgroup check on point inputs
# ---------------------------------------------------------------------------

# Functions whose reachability satisfies the invariant.  _g1/_g2 are the
# wire.py funnels (suite-membership re-checks over elements the serde
# core already subgroup-checked in from_bytes); the rest are the real
# membership tests (host oracle and device mirror).
SUBGROUP_SINKS = {
    "is_g1",
    "is_g2",
    "g1_in_subgroup",
    "g2_in_subgroup",
    "in_subgroup_slow",
    "request_well_formed",
    "endo_subgroup_eq",
    "_g1",
    "_g2",
}

# Entry points that MUST reach a sink wherever they are defined.
SUBGROUP_ENTRY_NAMES = {"g1_from_bytes", "g2_from_bytes", "verify_batch"}

# Struct tags registered in wire.py, classified by whether the struct
# (transitively) carries group elements.  A NEW register_struct tag must
# be added to one of these sets — the linter fails on unknown tags so
# the classification (and, for point structs, the subgroup-check
# obligation) is decided consciously, not by default.
POINT_STRUCT_TAGS = {
    "ct", "sig", "pk", "comm", "bicomm", "change", "svote", "skg",
    "icontrib", "joinplan", "part", "ack",
    # crypto-plane RPC: the pk share's bare G1 plus nested share/ct
    # structs (each re-checked by its own unpacker; the bare G1 goes
    # through _g1's subgroup check in _unpack_verify_request)
    "vreq",
    # transport-boundary live-message tree (group elements ride in the
    # share leaves; envelopes delegate via isinstance of nested types)
    "sigshare", "decshare", "signmsg", "decmsg", "ba_coin", "ba",
    "subsetmsg", "hbmsg", "dhbmsg", "sqmsg",
}
NONPOINT_STRUCT_TAGS = {
    "encsched",
    # transport-boundary types with no group elements anywhere below
    "proof", "bc_value", "bc_echo", "bc_ready", "bc_echohash",
    "bc_candecode", "bools", "ba_bval", "ba_aux", "ba_conf", "ba_term",
}

# Types whose isinstance check counts as delegation: the value was
# decoded by its own registered unpacker (serde core dispatches nested
# structs), so its points were already validated there.
_POINT_TYPE_NAMES = {
    "Ciphertext", "Signature", "PublicKey", "PublicKeySet", "Commitment",
    "BivarCommitment", "Part", "Ack", "Change", "SignedVote",
    "SignedKeyGenMsg",
    "SignatureShare", "DecryptionShare", "SignMessage", "DecryptMessage",
    "CoinMsg", "AbaMessage", "SubsetMessage", "HbMessage", "DhbMessage",
}

_WIRE_MODULES = ("wire.py",)


def _matches(path: str, suffixes: Iterable[str]) -> bool:
    p = path.replace("\\", "/")
    return any(p.endswith(s) for s in suffixes)


def _trivial_body(fn: ast.FunctionDef) -> bool:
    """Protocol stubs: docstring and/or a bare ``...``/``pass``."""
    body = list(fn.body)
    if body and isinstance(body[0], ast.Expr) and isinstance(
        body[0].value, ast.Constant
    ) and isinstance(body[0].value.value, str):
        body = body[1:]
    if not body:
        return True
    return all(
        isinstance(s, ast.Pass)
        or (
            isinstance(s, ast.Expr)
            and isinstance(s.value, ast.Constant)
            and s.value.value is Ellipsis
        )
        or isinstance(s, ast.Raise)
        for s in body
    )


def _has_annotation(src: str, fn: ast.FunctionDef, regex: re.Pattern) -> bool:
    lines = src.splitlines()
    end = getattr(fn, "end_lineno", fn.lineno)
    lo = max(fn.lineno - 2, 1)
    return any(
        regex.search(lines[i - 1]) for i in range(lo, min(end, len(lines)) + 1)
    )


class _CallGraph:
    """Name-resolved call graph over a set of parsed modules.  Edges are
    by bare callee name (``x.foo()`` -> ``foo``): coarse, but sound for
    reachability-to-sink checks (over- rather than under-connects)."""

    def __init__(self) -> None:
        self.calls: Dict[str, Set[str]] = {}
        self.defs: Dict[str, List[Tuple[str, ast.FunctionDef, str]]] = {}

    def add_module(self, path: str, src: str, tree: ast.AST) -> None:
        for fn in _function_defs(tree):
            self.calls.setdefault(fn.name, set()).update(
                self._own_callees(fn)
            )
            self.defs.setdefault(fn.name, []).append((path, fn, src))

    def _own_callees(self, fn: ast.FunctionDef) -> Set[str]:
        return {
            name
            for node in ast.walk(fn)
            if isinstance(node, ast.Call)
            and (name := _call_name(node.func)) is not None
        }

    def reaches_sink(self, name: str) -> bool:
        return self._closure_hits_sink(self.calls.get(name, set()))

    def def_reaches_sink(self, fn: ast.FunctionDef) -> bool:
        """Reachability seeded from THIS def's own calls (same-named
        defs in other classes don't vouch for it)."""
        return self._closure_hits_sink(self._own_callees(fn))

    def _closure_hits_sink(self, seeds: Set[str]) -> bool:
        seen: Set[str] = set()
        work = list(seeds)
        while work:
            cur = work.pop()
            if cur in SUBGROUP_SINKS:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(self.calls.get(cur, ()))
        return False


def _wire_registrations(tree: ast.AST) -> List[Tuple[str, str, int]]:
    """(tag, unpack_function_name, lineno) per register_struct call."""
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and _call_name(node.func) == "register_struct"
            and len(node.args) >= 4
        ):
            tag = node.args[0]
            unpack = node.args[3]
            if isinstance(tag, ast.Constant) and isinstance(tag.value, str):
                uname = unpack.id if isinstance(unpack, ast.Name) else None
                out.append((tag.value, uname or "", node.lineno))
    return out


def _delegates(graph: _CallGraph, fname: str) -> bool:
    """True if fname (or a same-module callee) funnels its group-bearing
    fields through isinstance checks against registered point types or
    the serde_group structural marker."""
    seen: Set[str] = set()
    work = [fname]
    while work:
        cur = work.pop()
        if cur in seen or cur not in graph.defs:
            continue
        seen.add(cur)
        for _path, fn, _src in graph.defs[cur]:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    cname = _call_name(node.func)
                    if cname == "isinstance" and len(node.args) == 2:
                        types = node.args[1]
                        elts = (
                            types.elts
                            if isinstance(types, ast.Tuple)
                            else [types]
                        )
                        for t in elts:
                            tn = _call_name(t) or (
                                t.id if isinstance(t, ast.Name) else None
                            )
                            if tn in _POINT_TYPE_NAMES:
                                return True
                    elif cname == "hasattr" and len(node.args) == 2:
                        marker = node.args[1]
                        if (
                            isinstance(marker, ast.Constant)
                            and marker.value == "serde_group"
                        ):
                            return True
                    elif cname in graph.calls:
                        work.append(cname)
    return False


def rule_subgroup_checks(files: Dict[str, ast.AST], sources: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    graph = _CallGraph()
    # EVERY analyzed module joins the graph: a new Suite/backend added
    # anywhere must reach a check "wherever it is defined" — a fixed
    # module list would silently exempt future implementations.
    for path, tree in files.items():
        graph.add_module(path, sources[path], tree)

    # (a) from_bytes / verify_batch entry points reach a sink.
    for name in SUBGROUP_ENTRY_NAMES:
        for path, fn, src in graph.defs.get(name, ()):
            if _trivial_body(fn):
                continue
            if _has_annotation(src, fn, NO_SUBGROUP_RE):
                continue
            if not graph.def_reaches_sink(fn):
                findings.append(
                    Finding(
                        "HBT005",
                        path,
                        fn.lineno,
                        f"'{name}' never reaches a subgroup/membership check"
                        f" (one of {sorted(SUBGROUP_SINKS)}): wire-sourced"
                        " points MUST get subgroup checks somewhere"
                        " (CLAUDE.md invariant). Annotate '# lint:"
                        " no-subgroup (<why>)' only for groups with no"
                        " torsion to confine (e.g. prime-field scalars)",
                    )
                )

    # (b) wire.py struct registry: classified tags; point tags validate.
    for path, tree in files.items():
        if not _matches(path, _WIRE_MODULES):
            continue
        for tag, uname, lineno in _wire_registrations(tree):
            if tag in NONPOINT_STRUCT_TAGS:
                continue
            if tag not in POINT_STRUCT_TAGS:
                findings.append(
                    Finding(
                        "HBT005",
                        path,
                        lineno,
                        f"register_struct tag '{tag}' is not classified in"
                        " tools/lint/pylints.py (POINT_STRUCT_TAGS /"
                        " NONPOINT_STRUCT_TAGS): decide whether the struct"
                        " carries group elements and record it",
                    )
                )
                continue
            if not uname:
                continue
            ok = graph.reaches_sink(uname) or _delegates(graph, uname)
            if not ok:
                findings.append(
                    Finding(
                        "HBT005",
                        path,
                        lineno,
                        f"unpacker '{uname}' for point struct '{tag}'"
                        " neither reaches a subgroup/membership check nor"
                        " delegates via isinstance against a registered"
                        " point type: Byzantine-authored points would"
                        " construct unchecked",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# HBT006: socket reads honor the max-frame plumbing
# ---------------------------------------------------------------------------


def rule_bounded_recv(path: str, src: str, tree: ast.AST) -> List[Finding]:
    """Every ``.recv(...)`` call in the product tree must be bounded by
    the shared ``RECV_CHUNK`` constant (or an int literal within it).

    The frame decoder enforces ``max_frame_len`` per frame, but the
    *syscall* is the first place untrusted bytes enter the process — an
    unbounded or over-large recv would let a peer make one event-loop
    iteration buffer arbitrary data before any frame check runs.  The
    escape comment ``# lint: raw-recv`` exists for recv()s that are not
    socket reads of untrusted peers.
    """
    if not path.replace("\\", "/").startswith("hbbft_tpu/"):
        return []
    lines = src.splitlines()
    escapes = {
        i for i, line in enumerate(lines, 1) if RAW_RECV_RE.search(line)
    }
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "recv"
        ):
            continue
        if any(ln in escapes for ln in range(node.lineno - 2, node.lineno + 1)):
            continue
        ok = False
        if len(node.args) == 1 and not node.keywords:
            a = node.args[0]
            if isinstance(a, ast.Name) and a.id == "RECV_CHUNK":
                ok = True
            elif (
                isinstance(a, ast.Constant)
                and type(a.value) is int
                and 0 < a.value <= MAX_RECV_LITERAL
            ):
                ok = True
        if not ok:
            findings.append(
                Finding(
                    "HBT006",
                    path,
                    node.lineno,
                    "unbounded/over-large socket read: pass RECV_CHUNK (or"
                    f" a literal <= {MAX_RECV_LITERAL}) so one syscall never"
                    " buffers more untrusted bytes than the frame decoder"
                    " admits; '# lint: raw-recv' escapes non-socket recv()s"
                    " (docs/TRANSPORT.md read-path rules)",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

_PER_FILE_RULES = (
    rule_add_unsafe_safety,
    rule_step_reuse,
    rule_jit_interpret_pallas,
    rule_scan_accumulator,
    rule_bounded_recv,
)


def lint_files(sources: Dict[str, str]) -> List[Finding]:
    """Lint a path->source mapping (paths repo-relative, '/'-separated)."""
    findings: List[Finding] = []
    trees: Dict[str, ast.AST] = {}
    for path, src in sources.items():
        try:
            trees[path] = ast.parse(src, filename=path)
        except SyntaxError as exc:
            findings.append(
                Finding("HBT000", path, exc.lineno or 1, f"syntax error: {exc.msg}")
            )
    for path, tree in trees.items():
        for rule in _PER_FILE_RULES:
            findings.extend(rule(path, sources[path], tree))
    findings.extend(rule_subgroup_checks(trees, sources))
    return findings
