"""Profiling-slot registry for native/engine.cpp (enforced by HBC004).

The engine keeps 16 rdtsc counter slots (``Engine::prof_cycles`` /
``prof_count``).  Slots 0..10 are indexed dynamically by delivered
message type (``enum MsgType``); the rest are claimed by literal index
for specific instrumentation.  Claiming a slot == editing this file in
the same change that adds the stamp; the linter fails on any literal
slot index in engine.cpp that is FREE here (use without claiming would
silently corrupt an existing profile) and on claimed slots that no
longer appear (stale claims hide genuinely free slots).

History: round 4 claimed 11/13/14; round-5 cleanup returned 12/15 to
the free pool; round 6 claimed both for the era-change batch-tail
split (batch_cb / contrib_cb wall).  Round 7 retired the two SETTLED
round-4 diagnosis slots (11 = continuation max watermark, 13 = the
>1M continuation tail — CLAUDE.md era-change envelope notes) and
re-claimed them for the RLC work, since no slot was free
(retire-and-reuse, never squat): 11 = scalar RLC group stats, 13 =
the epoch-advance wall — which IS what the old tail heuristic was
measuring, now stamped exactly and borrowed out of the typed
per-message slots so COIN/DECRYPT cyc/delivery means share work.
Round 15 retired the round-4 slot-14 pool-flush total (its diagnosis
was SETTLED in round 4 and the deferred-flush folding into the typed
COIN/DECRYPT slots carries the continuation wall since round 7) and
re-claimed 14 for the SIMD field plane's combine-kernel stats — the
COIN/DECRYPT combine component the HBBFT_TPU_SIMD A/B adjudicates.
Round 17 retired the round-6 slot-15 contrib_cb stamp (its era-change
tail split was SETTLED in round 6; the decode half has been stable
since) and re-claimed 15 for the epoch-arena stats — NOT a cycle
counter: cycles = max per-node arena high-water mark in bytes, count
= watermark resets (hb_reset_state; exported as arena_stats() and as
the engine.cyc.arena counter on cluster nodes).
"""

# Dynamic range: prof_cycles[ty] / prof_count[ty], ty = MsgType 0..10.
TYPED_DELIVERY_SLOTS = frozenset(range(0, 11))

# Literal-index claims: slot -> owner/purpose.
CLAIMED_SLOTS = {
    11: "scalar RLC groups (cycles = group dispatch wall incl. chunked "
        "checks, count = groups; engine_flush_pool/scalar_rlc_verdicts, "
        "round 7)",
    12: "Python batch_cb wall cycles (commit_events, round 6 batch-digest A/B)",
    13: "epoch-advance wall (hb_reset_state recycle + coin setup; "
        "borrowed out of typed slots, round 7)",
    14: "SIMD combine-kernel wall (cycles = Lagrange coefficients + "
        "batched combine-sum at ts/td_try_output, count = scalar-mode "
        "combines; the HBBFT_TPU_SIMD A/B component readout, round 15)",
    15: "epoch-arena stats (cycles = max per-node high-water mark bytes, "
        "count = watermark resets; hb_reset_state, round 17)",
}

# Free for temporary instrumentation: claim here before stamping.
FREE_SLOTS = frozenset()
