"""Adversarial delivery in the native engine (round-3 VERDICT item #6).

The engine exposes a pre-crank hook; the seeded Python scheduling
adversaries (Reordering / Random / NodeOrder — upstream
``tests/net/adversary.rs`` stock set) are replayed against the engine
queue, consuming the same net-rng stream as the VirtualNet at the same
seed.  The fidelity pin upgrades from FIFO-only: under every seeded
adversarial schedule the engine must commit byte-identical batch
sequences, fault logs, and delivery counts to the Python stack.
"""

import pytest

from hbbft_tpu import native_engine
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.net.adversary import (
    NodeOrderAdversary,
    RandomAdversary,
    ReorderingAdversary,
)
from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger

pytestmark = pytest.mark.skipif(
    not native_engine.available(), reason="native engine unavailable"
)

SESSION = b"qhb-test"
BATCH_SIZE = 8


def batch_key(b):
    return (b.era, b.epoch, b.contributions, b.change, b.join_plan)


def py_batches(net, nid):
    return [o for o in net.node(nid).outputs if isinstance(o, DhbBatch)]


ADVERSARIES = {
    "reordering": ReorderingAdversary,
    "random": RandomAdversary,
    "nodeorder": NodeOrderAdversary,
}


@pytest.mark.parametrize("adv_name", sorted(ADVERSARIES))
@pytest.mark.parametrize("n,f,seed", [(7, 2, 5), (10, 3, 6)])
def test_equivalence_under_scheduling_adversary(adv_name, n, f, seed):
    make = ADVERSARIES[adv_name]
    pynet = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .max_cranks(10_000_000)
        .adversary(make())
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=BATCH_SIZE, session_id=SESSION
            )
        )
        .build()
    )
    nat = native_engine.NativeQhbNet(
        n, seed=seed, batch_size=BATCH_SIZE, num_faulty=f, session_id=SESSION,
        adversary=make(),
    )
    for k in range(2):
        for nid in pynet.correct_ids:
            pynet.send_input(nid, Input.user(f"t{nid}.{k}"))
            nat.send_input(nid, Input.user(f"t{nid}.{k}"))
    pynet.crank_until(
        lambda net: all(len(py_batches(net, i)) >= 2 for i in net.correct_ids),
        max_cranks=10_000_000,
    )
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 2 for i in e.correct_ids),
        chunk=1,
    )
    for nid in pynet.correct_ids:
        assert [batch_key(b) for b in py_batches(pynet, nid)] == [
            batch_key(b) for b in nat.nodes[nid].outputs
        ], f"node {nid} batches diverge under {adv_name}"
        assert [(x.node_id, x.kind) for x in pynet.node(nid).faults] == nat.faults(
            nid
        ), f"node {nid} fault logs diverge under {adv_name}"
    assert nat.delivered == pynet.delivered
    nat.close()


@pytest.mark.parametrize("n,f,seed,tp", [(7, 2, 5, 1.0), (10, 3, 6, 0.5)])
def test_equivalence_under_tampering_adversary(n, f, seed, tp):
    """Round-4 VERDICT item #8: the engine's parse/fault paths face
    hostile (valid-type, wrong-content) bytes from Byzantine senders,
    and the run stays byte-identical to the Python VirtualNet under the
    same seeded TamperingAdversary — batches, fault logs, deliveries."""
    from hbbft_tpu.net.adversary import TamperingAdversary

    pynet = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .max_cranks(10_000_000)
        .adversary(TamperingAdversary(tamper_p=tp))
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=BATCH_SIZE, session_id=SESSION
            )
        )
        .build()
    )
    py_adv = pynet.adversary
    nat_adv = TamperingAdversary(tamper_p=tp)
    nat = native_engine.NativeQhbNet(
        n, seed=seed, batch_size=BATCH_SIZE, num_faulty=f, session_id=SESSION,
        adversary=nat_adv,
    )
    # broadcast_input order: correct ids first, then faulty through the
    # adversary (VirtualNet.broadcast_input).
    for k in range(2):
        pynet.broadcast_input(lambda nid, k=k: Input.user(f"t{nid}.{k}"))
        for nid in sorted(nat.correct_ids) + sorted(nat.faulty_ids):
            nat.send_input(nid, Input.user(f"t{nid}.{k}"))
    pynet.crank_until(
        lambda net: all(len(py_batches(net, i)) >= 2 for i in net.correct_ids),
        max_cranks=10_000_000,
    )
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 2 for i in e.correct_ids),
        chunk=1,
    )
    for nid in pynet.correct_ids:
        assert [batch_key(b) for b in py_batches(pynet, nid)] == [
            batch_key(b) for b in nat.nodes[nid].outputs
        ], f"node {nid} batches diverge under tampering"
        assert [(x.node_id, x.kind) for x in pynet.node(nid).faults] == nat.faults(
            nid
        ), f"node {nid} fault logs diverge under tampering"
    assert nat.delivered == pynet.delivered
    # the adversary actually rewrote traffic, identically on both sides
    assert nat_adv.tampered_count == py_adv.tampered_count > 0
    # evidence only ever names faulty nodes
    for nid in pynet.correct_ids:
        assert {s for s, _ in nat.faults(nid)} <= set(nat.faulty_ids)
    nat.close()


def test_tampering_with_external_crypto():
    """Tampered Byzantine traffic + the external-crypto path compose:
    same outputs and faults as the internal-scalar engine run."""
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.net.adversary import TamperingAdversary

    def drive(**kw):
        nat = native_engine.NativeQhbNet(
            7, seed=9, batch_size=BATCH_SIZE, num_faulty=2, session_id=SESSION,
            adversary=TamperingAdversary(tamper_p=0.5), **kw,
        )
        for nid in sorted(nat.correct_ids) + sorted(nat.faulty_ids):
            nat.send_input(nid, Input.user(f"x{nid}"))
        nat.run_until(
            lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
            chunk=1,
        )
        out = (
            {i: [batch_key(b) for b in nat.nodes[i].outputs] for i in nat.correct_ids},
            {i: nat.faults(i) for i in range(7)},
        )
        nat.close()
        return out

    assert drive() == drive(suite=ScalarSuite(), external_crypto=True)


def test_reordering_with_external_crypto():
    """Adversarial schedule + the external-crypto path together (scalar
    suite): the two features compose without breaking equivalence."""
    from hbbft_tpu.crypto.suite import ScalarSuite

    def drive(**kw):
        nat = native_engine.NativeQhbNet(
            7, seed=9, batch_size=BATCH_SIZE, num_faulty=2, session_id=SESSION,
            adversary=ReorderingAdversary(), **kw,
        )
        for nid in nat.correct_ids:
            nat.send_input(nid, Input.user(f"x{nid}"))
        nat.run_until(
            lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
            chunk=1,
        )
        out = (
            {i: [batch_key(b) for b in nat.nodes[i].outputs] for i in nat.correct_ids},
            {i: nat.faults(i) for i in range(7)},
        )
        nat.close()
        return out

    assert drive() == drive(suite=ScalarSuite(), external_crypto=True)
