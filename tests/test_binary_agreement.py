"""BinaryAgreement tests.

Reference analogs: upstream ``tests/binary_agreement.rs`` (all correct
nodes decide the same bool; if all inputs agree, that value is decided)
and ``tests/binary_agreement_mitm.rs`` (a scheduler that delays common-
coin shares cannot kill liveness).
"""

import pytest

from hbbft_tpu.net import NetBuilder, NullAdversary, RandomAdversary, ReorderingAdversary
from hbbft_tpu.net.adversary import Adversary
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement, CoinMsg


def build_net(n=4, seed=0, adversary=None):
    b = NetBuilder(n, seed=seed).protocol(
        lambda ni, sink, rng: BinaryAgreement(ni, b"aba-session", sink)
    )
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


def run_and_check(net, expect=None):
    net.run_to_termination()
    decisions = {nid: net.node(nid).outputs for nid in net.correct_ids}
    assert all(len(d) == 1 for d in decisions.values()), decisions
    values = {d[0] for d in decisions.values()}
    assert len(values) == 1, f"disagreement: {decisions}"
    if expect is not None:
        assert values == {expect}
    assert net.correct_faults() == []
    return values.pop()


@pytest.mark.parametrize("value", [False, True])
@pytest.mark.parametrize("n", [1, 4, 7])
def test_unanimous_input_decides_that_value(n, value):
    net = build_net(n=n, seed=17)
    net.broadcast_input(lambda nid: value)
    run_and_check(net, expect=value)


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("adversary_cls", [NullAdversary, ReorderingAdversary, RandomAdversary])
def test_mixed_inputs_agree(seed, adversary_cls):
    net = build_net(n=7, seed=seed, adversary=adversary_cls())
    net.broadcast_input(lambda nid: nid % 2 == 0)
    run_and_check(net)


class CoinDelayAdversary(Adversary):
    """MITM on the common coin: starves coin-share delivery for a while,
    forcing rounds to stack up behind the conf stage, then relents.
    An adversary that cannot break threshold crypto can only *delay* the
    coin — liveness must survive."""

    def __init__(self, delay_cranks: int = 200) -> None:
        self.delay_cranks = delay_cranks
        self.cranks = 0

    def pre_crank(self, net, rng) -> None:
        self.cranks += 1
        if self.cranks <= self.delay_cranks and len(net.queue) > 1:
            non_coin = [m for m in net.queue if not isinstance(getattr(m.payload, "content", None), CoinMsg)]
            coin = [m for m in net.queue if isinstance(getattr(m.payload, "content", None), CoinMsg)]
            if coin and non_coin:
                reordered = non_coin + coin
                for i in range(len(net.queue)):
                    net.queue[i] = reordered[i]


@pytest.mark.parametrize("seed", range(3))
def test_coin_mitm_liveness(seed):
    net = build_net(n=4, seed=seed, adversary=CoinDelayAdversary())
    net.broadcast_input(lambda nid: nid % 2 == 0)
    run_and_check(net)


def test_term_shortcut():
    # A node joining late (no input) can still decide from f+1 Terms.
    net = build_net(n=4, seed=3)
    # Give input to all but node 2.
    for nid in (0, 1):
        net.send_input(nid, True)
    net.crank_until(
        lambda n: sum(1 for i in n.correct_ids if n.node(i).protocol.terminated) >= 2,
        max_cranks=50_000,
    )
    # Now node 2 should be able to finish purely from Term evidence.
    net.crank_until(lambda n: n.node(2).protocol.terminated, max_cranks=50_000)
    decisions = {net.node(i).outputs[0] for i in net.correct_ids if net.node(i).outputs}
    assert decisions == {True}
