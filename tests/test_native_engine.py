"""Native engine vs pure-Python VirtualNet: byte-identical batches.

The C++ engine (native/engine.cpp) re-runs the HoneyBadger stack's
message loop natively; these tests pin its FIDELITY CONTRACT: at the
same seed, driven the same way, the engine-backed net commits the same
DhbBatch sequence (eras, epochs, contributions, change states) and the
same fault logs as the Python stack.
"""

import pytest

from hbbft_tpu import native_engine
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.dynamic_honey_badger import Change, DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger

pytestmark = pytest.mark.skipif(
    not native_engine.available(), reason="native engine unavailable"
)

BATCH_SIZE = 8
SESSION = b"qhb-test"


def build_python_net(n, seed, f=None):
    b = (
        NetBuilder(n, seed=seed)
        .max_cranks(10_000_000)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=BATCH_SIZE, session_id=SESSION
            )
        )
    )
    if f is not None:
        b = b.num_faulty(f)
    return b.build()


def py_batches(net, nid):
    return [o for o in net.node(nid).outputs if isinstance(o, DhbBatch)]


def batch_key(b):
    return (b.era, b.epoch, b.contributions, b.change, b.join_plan)


def drive_pair(n, seed, f, steps):
    """Run the same script against both nets; return (python, native)."""
    pynet = build_python_net(n, seed, f=f)
    nat = native_engine.NativeQhbNet(
        n, seed=seed, batch_size=BATCH_SIZE, num_faulty=f, session_id=SESSION
    )
    for kind, nid, value, until in steps:
        if kind == "input":
            pynet.send_input(nid, value)
            nat.send_input(nid, value)
        elif kind == "run_until_batches":
            want = value
            pynet.crank_until(
                lambda net: all(
                    len(py_batches(net, i)) >= want for i in net.correct_ids
                ),
                max_cranks=10_000_000,
            )
            # chunk=1: check the predicate between every delivery, the
            # same cadence as VirtualNet.crank_until — both stacks stop
            # at the same instant, so whole batch SEQUENCES compare.
            nat.run_until(
                lambda e: all(
                    len(e.nodes[i].outputs) >= want for i in e.correct_ids
                ),
                chunk=1,
            )
    return pynet, nat


def assert_equivalent(pynet, nat):
    for nid in pynet.correct_ids:
        pyb = [batch_key(b) for b in py_batches(pynet, nid)]
        nab = [batch_key(b) for b in nat.nodes[nid].outputs]
        # compare the common prefix: the runs are stopped by the same
        # predicate, so lengths match unless extra batches surfaced
        assert pyb == nab, f"node {nid} diverged:\n py={pyb}\n nat={nab}"
        pyf = [(fl.node_id, fl.kind) for fl in pynet.node(nid).faults]
        naf = nat.faults(nid)
        assert pyf == naf, f"node {nid} fault logs diverged: {pyf} vs {naf}"


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_equivalence_n4_all_correct(seed):
    steps = [("input", nid, Input.user(f"tx-{nid}-{k}"), None)
             for k in range(3) for nid in range(4)]
    steps.append(("run_until_batches", None, 3, None))
    pynet, nat = drive_pair(4, seed, 0, steps)
    assert_equivalent(pynet, nat)
    # sanity: all transactions actually committed
    committed = [
        t
        for b in nat.nodes[0].outputs
        for _, c in b.contributions
        if isinstance(c, (list, tuple))
        for t in c
    ]
    assert sorted(committed) == sorted(
        f"tx-{nid}-{k}" for k in range(3) for nid in range(4)
    )


@pytest.mark.parametrize("seed", [5, 6])
def test_equivalence_n7_with_silent_faulty(seed):
    steps = [("input", nid, Input.user(f"t{nid}.{k}"), None)
             for k in range(2) for nid in range(5)]  # correct ids 0..4 (f=2)
    steps.append(("run_until_batches", None, 2, None))
    pynet, nat = drive_pair(7, seed, 2, steps)
    assert pynet.correct_ids == nat.correct_ids
    assert_equivalent(pynet, nat)


@pytest.mark.parametrize("seed", [11, 12])
def test_equivalence_era_change(seed):
    """Vote a validator out: the embedded DKG rides through consensus
    and both stacks must restart the era identically."""
    pynet = build_python_net(4, seed, f=0)
    nat = native_engine.NativeQhbNet(
        4, seed=seed, batch_size=BATCH_SIZE, num_faulty=0, session_id=SESSION
    )
    keep = dict(pynet.node(0).netinfo.public_key_map)
    keep.pop(3)
    change = Change.node_change(keep)
    for nid in range(4):
        pynet.send_input(nid, Input.change(change))
        nat.send_input(nid, Input.change(change))

    def py_done(net):
        return all(
            any(b.change.kind == "complete" for b in py_batches(net, i))
            for i in net.correct_ids
        )

    def nat_done(e):
        return all(
            any(b.change.kind == "complete" for b in e.nodes[i].outputs)
            for i in e.correct_ids
        )

    for r in range(8):
        if py_done(pynet) and nat_done(nat):
            break
        for nid in range(4):
            pynet.send_input(nid, Input.user(f"e{r}-{nid}"))
            nat.send_input(nid, Input.user(f"e{r}-{nid}"))
        want = r + 1
        pynet.crank_until(
            lambda net, w=want: all(
                len(py_batches(net, i)) >= w for i in net.correct_ids
            ),
            max_cranks=10_000_000,
        )
        nat.run_until(
            lambda e, w=want: all(
                len(e.nodes[i].outputs) >= w for i in e.correct_ids
            ),
            chunk=1,
        )
    assert py_done(pynet) and nat_done(nat)
    assert_equivalent(pynet, nat)
    # era actually advanced on both sides (the change-complete batch
    # itself carries the OLD era; the DHB layer then restarts)
    assert nat.nodes[0].qhb.dhb.era >= 1
    assert pynet.node(0).protocol.dhb.era == nat.nodes[0].qhb.dhb.era


def test_native_determinism():
    def run_once():
        nat = native_engine.NativeQhbNet(4, seed=9, batch_size=BATCH_SIZE)
        for nid in range(4):
            nat.send_input(nid, Input.user(f"d{nid}"))
        nat.run_until(
            lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids)
        )
        return [
            [batch_key(b) for b in nat.nodes[i].outputs] for i in nat.correct_ids
        ], nat.delivered

    a, b = run_once(), run_once()
    assert a == b


@pytest.mark.parametrize("sched_kind", ["never", "tick_tock"])
def test_equivalence_encryption_schedules(sched_kind):
    """Plaintext epochs take the _accept_plaintext fast path (no
    ThresholdDecrypt); tick_tock alternates both paths."""
    from hbbft_tpu.protocols.honey_badger import EncryptionSchedule

    sched = (
        EncryptionSchedule.never()
        if sched_kind == "never"
        else EncryptionSchedule.tick_tock(1)
    )
    pynet = (
        NetBuilder(4, seed=41)
        .num_faulty(0)
        .max_cranks(10_000_000)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni,
                sink,
                batch_size=BATCH_SIZE,
                session_id=SESSION,
                encryption_schedule=sched,
            )
        )
        .build()
    )
    nat = native_engine.NativeQhbNet(
        4,
        seed=41,
        batch_size=BATCH_SIZE,
        num_faulty=0,
        session_id=SESSION,
        encryption_schedule=sched,
    )
    for k in range(3):
        for nid in range(4):
            pynet.send_input(nid, Input.user(f"s{k}-{nid}"))
            nat.send_input(nid, Input.user(f"s{k}-{nid}"))
    pynet.crank_until(
        lambda net: all(len(py_batches(net, i)) >= 3 for i in net.correct_ids),
        max_cranks=10_000_000,
    )
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 3 for i in e.correct_ids),
        chunk=1,
    )
    assert_equivalent(pynet, nat)


def test_equivalence_era_change_n10():
    """Deeper fidelity: a 10-node era change (f=3 silent faulty would
    change correct_ids; keep all-correct) with per-delivery predicate
    checks — several hundred thousand deliveries compared batch-for-batch."""
    seed = 21
    pynet = build_python_net(10, seed, f=0)
    nat = native_engine.NativeQhbNet(
        10, seed=seed, batch_size=10, num_faulty=0, session_id=SESSION
    )
    keep = dict(pynet.node(0).netinfo.public_key_map)
    keep.pop(9)
    change = Change.node_change(keep)
    for nid in range(10):
        pynet.send_input(nid, Input.change(change))
        nat.send_input(nid, Input.change(change))

    def py_done(net):
        return all(
            any(b.change.kind == "complete" for b in py_batches(net, i))
            for i in net.correct_ids
        )

    def nat_done(e):
        return all(
            any(b.change.kind == "complete" for b in e.nodes[i].outputs)
            for i in e.correct_ids
        )

    for r in range(8):
        if py_done(pynet) and nat_done(nat):
            break
        for nid in range(10):
            pynet.send_input(nid, Input.user(f"x{r}-{nid}"))
            nat.send_input(nid, Input.user(f"x{r}-{nid}"))
        want = r + 1
        pynet.crank_until(
            lambda net, w=want: all(
                len(py_batches(net, i)) >= w for i in net.correct_ids
            ),
            max_cranks=10_000_000,
        )
        nat.run_until(
            lambda e, w=want: all(
                len(e.nodes[i].outputs) >= w for i in e.correct_ids
            ),
            chunk=1,
        )
    assert py_done(pynet) and nat_done(nat)
    assert_equivalent(pynet, nat)
    assert pynet.node(0).protocol.dhb.era == nat.nodes[0].qhb.dhb.era >= 1


def test_equivalence_subset_handling_all_at_end():
    """The engine honors SubsetHandlingStrategy: all_at_end defers every
    decrypt until Subset completes, byte-identically to Python."""
    pynet = (
        NetBuilder(4, seed=47)
        .num_faulty(0)
        .max_cranks(10_000_000)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni,
                sink,
                batch_size=BATCH_SIZE,
                session_id=SESSION,
                subset_handling="all_at_end",
            )
        )
        .build()
    )
    nat = native_engine.NativeQhbNet(
        4,
        seed=47,
        batch_size=BATCH_SIZE,
        num_faulty=0,
        session_id=SESSION,
        subset_handling="all_at_end",
    )
    for k in range(3):
        for nid in range(4):
            pynet.send_input(nid, Input.user(f"a{k}-{nid}"))
            nat.send_input(nid, Input.user(f"a{k}-{nid}"))
    pynet.crank_until(
        lambda net: all(len(py_batches(net, i)) >= 3 for i in net.correct_ids),
        max_cranks=10_000_000,
    )
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 3 for i in e.correct_ids),
        chunk=1,
    )
    assert_equivalent(pynet, nat)


@pytest.mark.parametrize("n,seed", [(4, 101), (5, 202), (7, 303), (6, 404)])
def test_equivalence_fuzz(n, seed):
    """Breadth: assorted (N, seed) combos, two epochs each, compared
    batch-for-batch and fault-for-fault."""
    f = (n - 1) // 3
    steps = [("input", nid, Input.user(f"f{seed}-{nid}-{k}"), None)
             for k in range(2) for nid in range(n - f)]
    steps.append(("run_until_batches", None, 2, None))
    pynet, nat = drive_pair(n, seed, f, steps)
    assert_equivalent(pynet, nat)


def test_equivalence_era_change_with_silent_faulty():
    """Era change at N=7 with 2 silent crash-faulty validators: the
    remaining 5 vote one of the FAULTY nodes out and both stacks restart
    identically."""
    seed = 31
    pynet = build_python_net(7, seed, f=2)
    nat = native_engine.NativeQhbNet(
        7, seed=seed, batch_size=8, num_faulty=2, session_id=SESSION
    )
    assert pynet.correct_ids == nat.correct_ids == [0, 1, 2, 3, 4]
    keep = dict(pynet.node(0).netinfo.public_key_map)
    keep.pop(6)  # remove a faulty validator
    change = Change.node_change(keep)
    for nid in pynet.correct_ids:
        pynet.send_input(nid, Input.change(change))
        nat.send_input(nid, Input.change(change))

    def py_done(net):
        return all(
            any(b.change.kind == "complete" for b in py_batches(net, i))
            for i in net.correct_ids
        )

    def nat_done(e):
        return all(
            any(b.change.kind == "complete" for b in e.nodes[i].outputs)
            for i in e.correct_ids
        )

    for r in range(10):
        if py_done(pynet) and nat_done(nat):
            break
        for nid in pynet.correct_ids:
            pynet.send_input(nid, Input.user(f"sf{r}-{nid}"))
            nat.send_input(nid, Input.user(f"sf{r}-{nid}"))
        want = r + 1
        pynet.crank_until(
            lambda net, w=want: all(
                len(py_batches(net, i)) >= w for i in net.correct_ids
            ),
            max_cranks=10_000_000,
        )
        nat.run_until(
            lambda e, w=want: all(
                len(e.nodes[i].outputs) >= w for i in e.correct_ids
            ),
            chunk=1,
        )
    assert py_done(pynet) and nat_done(nat)
    assert_equivalent(pynet, nat)


def test_multicore_with_silent_faulty_matches_sequential():
    """MT worker-loop silent skips + the epilogue's delivered accounting
    must match the sequential loop's at-pop silent check."""
    def run_one(threads_):
        nat = native_engine.NativeQhbNet(
            7, seed=3, batch_size=BATCH_SIZE, session_id=SESSION,
            threads=threads_,
        )  # default faulty: last f=2 nodes silent
        assert nat.faulty_ids
        for nid in nat.correct_ids:
            nat.send_input(nid, Input.user(f"s{nid}"))
        nat.run_until(
            lambda e: all(
                len(e.nodes[i].outputs) >= 1 for i in e.correct_ids
            ),
            chunk=5000,
        )
        out = {
            "delivered": nat.delivered,
            "outputs": [
                [batch_key(b) for b in nat.nodes[i].outputs]
                for i in nat.correct_ids
            ],
            "faults": [nat.faults(i) for i in nat.correct_ids],
        }
        nat.close()
        return out

    assert run_one(3) == run_one(1)


@pytest.mark.parametrize("threads", [2, 4])
def test_multicore_byte_identical_to_sequential(threads):
    """The generation-parallel scheduler (engine_run_mt) must produce
    BYTE-identical outputs, faults, and delivery counts to the
    sequential loop at the same seed — including a full era change (the
    hairiest path: batch callbacks proposing re-entrantly from worker
    threads).  On this 1-core box this proves CORRECTNESS of the
    sharded-queue design; speedups need a multi-core host
    (BASELINE.md round-5 design note)."""
    from hbbft_tpu.protocols.dynamic_honey_badger import Change

    def run_one(threads_):
        nat = native_engine.NativeQhbNet(
            10, seed=5, batch_size=BATCH_SIZE, num_faulty=0,
            session_id=SESSION, threads=threads_,
        )
        keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
        keep.pop(9)
        for nid in range(10):
            nat.send_input(nid, Input.change(Change.node_change(keep)))

        def done(e):
            return all(
                any(b.change.kind == "complete" for b in e.nodes[i].outputs)
                for i in e.correct_ids
            )

        for r in range(8):
            if done(nat):
                break
            for nid in range(10):
                nat.send_input(nid, Input.user(f"e{r}-{nid}"))
            want = len(nat.nodes[0].outputs) + 1
            nat.run_until(
                lambda e, w=want: all(
                    len(e.nodes[i].outputs) >= w for i in e.correct_ids
                ),
                chunk=5000,
            )
        assert done(nat)
        out = {
            "delivered": nat.delivered,
            "eras": [nat.nodes[i].qhb.dhb.era for i in range(10)],
            "outputs": [
                [batch_key(b) for b in nat.nodes[i].outputs]
                for i in range(10)
            ],
            "faults": [nat.faults(i) for i in range(10)],
        }
        nat.close()
        return out

    seq = run_one(1)
    par = run_one(threads)
    assert par == seq


def test_multicore_rejects_sequential_only_modes():
    from hbbft_tpu.crypto.bls import BLSSuite
    from hbbft_tpu.net.adversary import ReorderingAdversary

    with pytest.raises(ValueError):
        native_engine.NativeQhbNet(4, seed=1, suite=BLSSuite(), threads=2)
    with pytest.raises(ValueError):
        native_engine.NativeQhbNet(
            4, seed=1, adversary=ReorderingAdversary(), threads=2
        )
