"""Device crypto plane vs the pure-Python oracle (SURVEY.md §7 step 1).

Every layer of the TPU path — limb field arithmetic, Fq2, Jacobian curve
ops, the Fq12 tower, Miller loop/final exponentiation, and the
``TpuBackend`` RLC flush — is cross-checked against the oracle suite.
Runs on the virtual-CPU platform from conftest; the persistent XLA cache
keeps recompiles out of repeat runs.
"""

import os
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.bls import curve as oc
from hbbft_tpu.crypto.bls import fields as OF
from hbbft_tpu.crypto.bls.suite import BLSSuite
from hbbft_tpu.crypto.tpu import curve as dc
from hbbft_tpu.crypto.tpu import fq, fq2
from hbbft_tpu.crypto.tpu import pairing as dp
from hbbft_tpu.crypto.backend import BatchedBackend, VerifyRequest
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.crypto.tpu.backend import TpuBackend

P = OF.P

# Smoke tier (VERDICT round 1, weak #9): a cold-cache full run of this
# file costs 20-30 min of XLA compile (Miller loop / flush kernels on
# the virtual-CPU platform), which no time-boxed driver can finish.
# HBBFT_TPU_CRYPTO_SMOKE=1 skips the heavy-compile tests, keeping the
# limb/field/curve layers (seconds to compile) runnable anywhere; the
# full tier runs on warm caches and real TPU.
_SMOKE = bool(os.environ.get("HBBFT_TPU_CRYPTO_SMOKE"))
heavy_compile = pytest.mark.skipif(
    _SMOKE, reason="smoke tier: heavy pairing/flush compiles skipped"
)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(42)


# ---------------------------------------------------------------------------
# Fq limbs
# ---------------------------------------------------------------------------


def test_fq_ops_match_ints(rng):
    n = 32
    avals = [int.from_bytes(rng.bytes(48), "big") % P for _ in range(n)]
    bvals = [int.from_bytes(rng.bytes(48), "big") % P for _ in range(n)]
    A = jnp.asarray(np.stack([fq.to_mont_np(a) for a in avals]))
    B = jnp.asarray(np.stack([fq.to_mont_np(b) for b in bvals]))

    @jax.jit
    def ops(A, B):
        # includes a deep alternating chain — the historic failure mode of
        # the signed-limb design was corruption after repeated sub+mul.
        s = A
        for _ in range(8):
            s = fq.mont_mul(fq.sub(s, B), fq.add(s, s))
        return (fq.mont_mul(A, B), fq.add(A, B), fq.sub(A, B),
                fq.small_mul(A, 8), fq.neg(A), s,
                fq.is_zero(fq.sub(A, A)), fq.is_zero(A))

    mul, ad, su, sm, ng, s, iz0, izn = [np.asarray(x) for x in ops(A, B)]
    for i in range(n):
        a, b = avals[i], bvals[i]
        ss = a
        for _ in range(8):
            ss = (ss - b) * (2 * ss) % P
        assert fq.from_mont_int(mul[i]) == a * b % P
        assert fq.from_mont_int(ad[i]) == (a + b) % P
        assert fq.from_mont_int(su[i]) == (a - b) % P
        assert fq.from_mont_int(sm[i]) == 8 * a % P
        assert fq.from_mont_int(ng[i]) == -a % P
        assert fq.from_mont_int(s[i]) == ss
        assert bool(iz0[i])
        assert bool(izn[i]) == (a % P == 0)


def test_fq_limb_invariant_zero_and_identity():
    z = jnp.asarray(fq.ZERO)
    one = jnp.asarray(fq.ONE_MONT)
    assert bool(fq.is_zero(z))
    assert not bool(fq.is_zero(one))
    assert fq.from_mont_int(np.asarray(fq.mont_mul(one, one))) == 1


# ---------------------------------------------------------------------------
# Fq2
# ---------------------------------------------------------------------------


def test_fq2_ops_match_oracle(rng):
    a = (int.from_bytes(rng.bytes(48), "big") % P, int.from_bytes(rng.bytes(48), "big") % P)
    b = (int.from_bytes(rng.bytes(48), "big") % P, int.from_bytes(rng.bytes(48), "big") % P)
    da, db = jnp.asarray(fq2.to_mont_np(a)), jnp.asarray(fq2.to_mont_np(b))

    assert fq2.from_mont_int(np.asarray(fq2.mul(da, db))) == OF.fq2_mul(a, b)
    assert fq2.from_mont_int(np.asarray(fq2.sqr(da))) == OF.fq2_sqr(a)
    assert fq2.from_mont_int(np.asarray(fq2.conj(da))) == OF.fq2_conj(a)
    assert fq2.from_mont_int(np.asarray(fq2.mul_by_xi(da))) == OF.fq2_mul(a, OF.XI)
    got_inv = fq2.from_mont_int(np.asarray(fq2.inv(da)))
    assert OF.fq2_eq(OF.fq2_mul(got_inv, a), OF.FQ2_ONE)


# ---------------------------------------------------------------------------
# Curve (G1/G2): double/add/scalar-mul/tree-sum
# ---------------------------------------------------------------------------


def _rand_points(rng, n):
    g1s = [oc.jac_mul(oc.FQ_OPS, oc.G1_GEN, int.from_bytes(rng.bytes(32), "big") % OF.R)
           for _ in range(n)]
    g2s = [oc.jac_mul(oc.FQ2_OPS, oc.G2_GEN, int.from_bytes(rng.bytes(32), "big") % OF.R)
           for _ in range(n)]
    return g1s, g2s


def test_curve_g1_g2_vs_oracle(rng):
    n = 4
    g1s, g2s = _rand_points(rng, n)
    scalars = [int.from_bytes(rng.bytes(8), "big") | 1 for _ in range(n)]
    P1, P2 = dc.g1_to_dev(g1s), dc.g2_to_dev(g2s)
    bits = dc.scalars_to_bits(scalars, 64)

    @jax.jit
    def work(P1, P2, bits):
        d1 = dc.double(dc.G1_OPS, P1)
        s1 = dc.add_unsafe(dc.G1_OPS, P1, d1)
        m1 = dc.scalar_mul(dc.G1_OPS, P1, bits)
        t1 = dc.tree_sum(dc.G1_OPS, m1)
        d2 = dc.double(dc.G2_OPS, P2)
        s2 = dc.add_unsafe(dc.G2_OPS, P2, d2)
        m2 = dc.scalar_mul(dc.G2_OPS, P2, bits)
        t2 = dc.tree_sum(dc.G2_OPS, m2)
        return d1, s1, m1, t1, d2, s2, m2, t2

    d1, s1, m1, t1, d2, s2, m2, t2 = work(P1, P2, bits)
    acc1, acc2 = oc.jac_identity(oc.FQ_OPS), oc.jac_identity(oc.FQ2_OPS)
    for i in range(n):
        assert oc.jac_eq(oc.FQ_OPS, dc.g1_from_dev(d1, i), oc.jac_double(oc.FQ_OPS, g1s[i]))
        assert oc.jac_eq(oc.FQ_OPS, dc.g1_from_dev(s1, i), oc.jac_mul(oc.FQ_OPS, g1s[i], 3))
        assert oc.jac_eq(oc.FQ_OPS, dc.g1_from_dev(m1, i), oc.jac_mul(oc.FQ_OPS, g1s[i], scalars[i]))
        assert oc.jac_eq(oc.FQ2_OPS, dc.g2_from_dev(d2, i), oc.jac_double(oc.FQ2_OPS, g2s[i]))
        assert oc.jac_eq(oc.FQ2_OPS, dc.g2_from_dev(s2, i), oc.jac_mul(oc.FQ2_OPS, g2s[i], 3))
        assert oc.jac_eq(oc.FQ2_OPS, dc.g2_from_dev(m2, i), oc.jac_mul(oc.FQ2_OPS, g2s[i], scalars[i]))
        acc1 = oc.jac_add(oc.FQ_OPS, acc1, oc.jac_mul(oc.FQ_OPS, g1s[i], scalars[i]))
        acc2 = oc.jac_add(oc.FQ2_OPS, acc2, oc.jac_mul(oc.FQ2_OPS, g2s[i], scalars[i]))
    assert oc.jac_eq(oc.FQ_OPS, dc.g1_from_dev(t1), acc1)
    assert oc.jac_eq(oc.FQ2_OPS, dc.g2_from_dev(t2), acc2)


def test_curve_identity_flags(rng):
    g1s, _ = _rand_points(rng, 2)
    P1 = dc.g1_to_dev(g1s)
    z = dc.scalar_mul(dc.G1_OPS, P1, jnp.zeros((2, 16), jnp.int32))
    assert all(int(v) for v in np.asarray(z[3]))
    # identity + P = P through add_unsafe
    s = dc.add_unsafe(dc.G1_OPS, dc.identity(dc.G1_OPS, (2,)), P1)
    for i in range(2):
        assert oc.jac_eq(oc.FQ_OPS, dc.g1_from_dev(s, i), g1s[i])


def test_add_safe_degenerate_cases(rng):
    g1s, _ = _rand_points(rng, 2)
    P1 = dc.g1_to_dev(g1s)
    dbl = dc.add_safe(dc.G1_OPS, P1, P1)  # equal inputs -> doubling
    cancel = dc.add_safe(dc.G1_OPS, P1, dc.neg(dc.G1_OPS, P1))  # P + (-P)
    for i in range(2):
        assert oc.jac_eq(oc.FQ_OPS, dc.g1_from_dev(dbl, i), oc.jac_double(oc.FQ_OPS, g1s[i]))
    assert all(int(v) for v in np.asarray(cancel[3]))


# ---------------------------------------------------------------------------
# Fq12 tower + pairing
# ---------------------------------------------------------------------------


def _rand_fq12(rng):
    return tuple(
        (int.from_bytes(rng.bytes(48), "big") % P, int.from_bytes(rng.bytes(48), "big") % P)
        for _ in range(6)
    )


def _to_dev12(a):
    return jnp.asarray(np.stack([fq2.to_mont_np(c) for c in a]))


def _from_dev12(x):
    arr = np.asarray(x)
    return tuple(fq2.from_mont_int(arr[i]) for i in range(6))


@heavy_compile
def test_fq12_ops_vs_oracle(rng):
    A, B = _rand_fq12(rng), _rand_fq12(rng)
    dA, dB = _to_dev12(A), _to_dev12(B)
    assert _from_dev12(dp.mul(dA, dB)) == OF.fq12_mul(A, B)
    for k in (1, 2, 6):
        assert _from_dev12(dp.frobenius(dA, k)) == OF.fq12_frobenius(A, k)
    got_inv = _from_dev12(dp.inv(dA))
    assert OF.fq12_eq(OF.fq12_mul(got_inv, A), OF.FQ12_ONE)
    assert bool(dp.is_one(jnp.asarray(dp.FQ12_ONE)))
    assert not bool(dp.is_one(dA))


@heavy_compile
def test_pairing_product_vs_oracle(rng):
    """BLS verification equation on device: valid and corrupted."""
    sk = int.from_bytes(rng.bytes(32), "big") % OF.R
    pk = oc.jac_mul(oc.FQ_OPS, oc.G1_GEN, sk)
    h = oc.hash_to_g2(b"device pairing test")
    sig = oc.jac_mul(oc.FQ2_OPS, h, sk)
    g1s = dc.g1_to_dev([oc.G1_GEN, oc.jac_neg(oc.FQ_OPS, pk)])
    fn = jax.jit(dp.pairing_product_is_one)
    assert bool(fn(g1s, dc.g2_to_dev([sig, h])))
    badsig = oc.jac_mul(oc.FQ2_OPS, h, (sk + 1) % OF.R)
    assert not bool(fn(g1s, dc.g2_to_dev([badsig, h])))
    # all-identity pairs -> vacuous truth
    idg1 = dc.g1_to_dev([(1, 1, 0), (1, 1, 0)])
    assert bool(fn(idg1, dc.g2_to_dev([badsig, h])))


# ---------------------------------------------------------------------------
# TpuBackend end-to-end flush
# ---------------------------------------------------------------------------


def _mixed_requests(suite, rngpy, n_sig=5, n_ct=2):
    sks = SecretKeySet.random(1, rngpy, suite)
    pks = sks.public_keys()
    msg = b"flush epoch"
    reqs = []
    for i in range(n_sig):
        share = sks.secret_key_share(i % 4).sign(msg)
        reqs.append(VerifyRequest.sig_share(pks.public_key_share(i % 4), msg, share))
    for i in range(n_ct):
        ct = pks.public_key().encrypt(b"tx-%d" % i, rngpy)
        reqs.append(VerifyRequest.ciphertext(ct))
        ds = sks.secret_key_share(i % 4).decryption_share(ct)
        reqs.append(VerifyRequest.dec_share(pks.public_key_share(i % 4), ct, ds))
    return reqs


@heavy_compile
def test_tpu_backend_matches_batched_backend():
    suite = BLSSuite()
    rngpy = random.Random(77)
    reqs = _mixed_requests(suite, rngpy)
    want = BatchedBackend(suite).verify_batch(reqs)
    got = TpuBackend(suite).verify_batch(reqs)
    assert got == want
    assert all(got)


@heavy_compile
def test_tpu_backend_isolates_bad_shares():
    suite = BLSSuite()
    rngpy = random.Random(78)
    reqs = _mixed_requests(suite, rngpy, n_sig=4, n_ct=1)
    sks = SecretKeySet.random(1, rngpy, suite)
    bad = sks.secret_key_share(0).sign(b"wrong document")
    reqs.append(VerifyRequest.sig_share(
        SecretKeySet.random(1, rngpy, suite).public_keys().public_key_share(0),
        b"flush epoch", bad))
    got = TpuBackend(suite).verify_batch(reqs)
    assert got[:-1] == [True] * (len(reqs) - 1)
    assert got[-1] is False or got[-1] == False  # noqa: E712


@heavy_compile
def test_device_subgroup_check_and_rejection():
    """TpuBackend rejects a share forged from a non-subgroup point (the
    host does only structural checks — the membership test lives in the
    kernel as the batched endomorphism chain; its direct device-vs-
    oracle pin is test_device_endo_subgroup_matches_oracle)."""
    from hbbft_tpu.crypto.bls.suite import G2Elem
    from hbbft_tpu.crypto.keys import SignatureShare

    suite = BLSSuite()
    # A G2 curve point NOT in the r-torsion subgroup: a twist point
    # without cofactor clearing.
    pt = oc._twist_sample_point()
    rogue = G2Elem(pt)
    assert suite.is_g2(rogue, check_subgroup=False)
    assert not suite.is_g2(rogue)  # oracle agrees it's outside

    # End-to-end: a forged share built on the rogue point must fail in
    # TpuBackend (and the honest shares around it must still pass).
    rng_ = random.Random(77)
    sks = SecretKeySet.random(1, rng_, suite)
    pks = sks.public_keys()
    msg = b"subgroup test doc"
    reqs = [
        VerifyRequest.sig_share(
            pks.public_key_share(i), msg, sks.secret_key_share(i).sign(msg)
        )
        for i in range(3)
    ]
    reqs.append(
        VerifyRequest.sig_share(pks.public_key_share(3), msg, SignatureShare(rogue, suite))
    )
    got = TpuBackend(suite).verify_batch(reqs)
    assert got == [True, True, True, False]


@heavy_compile
def test_tpu_backend_sharded_flush_matches():
    """shard=True lays the verify batch over the virtual 8-device CPU
    mesh (conftest); results must match the single-device path."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device platform")
    suite = BLSSuite()
    rng_ = random.Random(31)
    sks = SecretKeySet.random(2, rng_, suite)
    pks = sks.public_keys()
    msg = b"sharded flush doc"
    reqs = [
        VerifyRequest.sig_share(
            pks.public_key_share(i % 8), msg, sks.secret_key_share(i % 8).sign(msg)
        )
        for i in range(16)
    ]
    reqs[5] = VerifyRequest.sig_share(
        pks.public_key_share(5), msg, sks.secret_key_share(4).sign(msg)
    )  # bad share
    sharded = TpuBackend(suite, shard=True)
    assert sharded._mesh is not None
    got = sharded.verify_batch(reqs)
    want = [True] * 16
    want[5] = False
    assert got == want


@heavy_compile
def test_device_endo_subgroup_matches_oracle():
    """The 128-step endomorphism membership chain (the flush kernel's
    round-3 subgroup check) agrees with the oracle on G1 and G2 for
    members, non-members, and the identity."""
    suite = BLSSuite()
    gen2 = suite.g2_generator()
    rogue2 = oc._twist_sample_point()  # on E'(Fq2), outside G2
    cof2 = oc.jac_mul(oc.FQ2_OPS, rogue2, OF.R)  # order | h2
    g2_jacs = [rogue2, cof2, gen2.jac, (gen2 * 9999).jac,
               suite.g2_identity().jac]
    pts2 = dc.g2_to_dev(g2_jacs)
    n2 = len(g2_jacs)
    bits_dummy = jnp.zeros((n2, dc.ENDO_NBITS), jnp.int32)
    endo2 = jnp.asarray(dc.endo_bits(True, n2))
    _, chain2 = dc.scalar_mul2(dc.G2_OPS, pts2, bits_dummy, endo2)
    ok2 = np.asarray(dc.endo_subgroup_eq(dc.G2_OPS, pts2, chain2))
    want2 = [oc.g2_in_subgroup(j) for j in g2_jacs]
    assert list(map(bool, ok2)) == want2 == [False, False, True, True, True]

    gen1 = suite.g1_generator()
    # an E(Fq) point outside G1: search a curve x, clear nothing
    x = 1
    while True:
        rhs = (x * x * x + oc.B1) % P
        y = pow(rhs, (P + 1) // 4, P)
        if y * y % P == rhs and not oc.g1_in_subgroup((x, y, 1)):
            rogue1 = (x, y, 1)
            break
        x += 1
    g1_jacs = [rogue1, gen1.jac, (gen1 * 31337).jac, suite.g1_identity().jac]
    pts1 = dc.g1_to_dev(g1_jacs)
    n1 = len(g1_jacs)
    endo1 = jnp.asarray(dc.endo_bits(False, n1))
    _, chain1 = dc.scalar_mul2(
        dc.G1_OPS, pts1, jnp.zeros((n1, dc.ENDO_NBITS), jnp.int32), endo1
    )
    ok1 = np.asarray(dc.endo_subgroup_eq(dc.G1_OPS, pts1, chain1))
    want1 = [oc.g1_in_subgroup(j) for j in g1_jacs]
    assert list(map(bool, ok1)) == want1 == [False, True, True, True]

    # Round-4 static-endo scans (the flush kernel's current path): same
    # verdicts AND correct RLC multiples for the members.  The rogue
    # rows exercise the fail-closed argument — the psi decomposition is
    # only sound for subgroup points, so for non-members the check must
    # reject regardless of what the RLC accumulator contains.
    rng5 = random.Random(5)
    coeffs = [rng5.getrandbits(128) for _ in range(n2)]
    sq = [dc.decompose_g2_scalar(c) for c in coeffs]
    bs = dc.scalars_to_bits([s for s, _ in sq], dc.G2_SCAN_NBITS)
    bq = dc.scalars_to_bits([q for _, q in sq], dc.G2_SCAN_NBITS)
    scaled2b, chain2b = dc.scalar_mul_rlc_g2(pts2, bs, bq)
    ok2b = np.asarray(dc.endo_subgroup_eq(dc.G2_OPS, pts2, chain2b))
    assert list(map(bool, ok2b)) == want2
    for i, j in enumerate(g2_jacs):
        if want2[i]:
            assert oc.jac_eq(
                oc.FQ2_OPS,
                dc.g2_from_dev(scaled2b, i),
                oc.jac_mul(oc.FQ2_OPS, j, coeffs[i]),
            )

    bits1 = dc.scalars_to_bits_lsb(coeffs[:n1], dc.ENDO_NBITS)
    scaled1b, chain1b = dc.scalar_mul_rlc_g1(pts1, bits1)
    ok1b = np.asarray(dc.endo_subgroup_eq(dc.G1_OPS, pts1, chain1b))
    assert list(map(bool, ok1b)) == want1
    for i, j in enumerate(g1_jacs):
        if want1[i]:
            assert oc.jac_eq(
                oc.FQ_OPS,
                dc.g1_from_dev(scaled1b, i),
                oc.jac_mul(oc.FQ_OPS, j, coeffs[i]),
            )


@heavy_compile
def test_tpu_backend_multi_chunk_combined():
    """The round-5 cross-chunk path: CHUNK=8 over 24 same-message
    sig-share requests -> 3 chunks, whose pairs combine into ONE batched
    Miller loop + final exponentiation.  A bad share in chunk 1 makes
    the combined verdict False, exercising the per-chunk recheck +
    bisection fallback; verdicts must match the host RLC backend
    (CLAUDE.md: every device-path change needs an oracle cross-check).

    Shapes deliberately mirror the round-5 validation drive (scan bucket
    16/16/2, pair buckets 9 and 3) so a warm cache reuses its entries.
    """
    suite = BLSSuite()
    rngpy = random.Random(99)
    sks = SecretKeySet.random(2, rngpy, suite)
    pks = sks.public_keys()
    msg = b"two-stage flush doc"
    reqs = [
        VerifyRequest.sig_share(
            pks.public_key_share(i % 8), msg,
            sks.secret_key_share(i % 8).sign(msg),
        )
        for i in range(24)
    ]
    reqs[13] = VerifyRequest.sig_share(
        pks.public_key_share(5), msg, sks.secret_key_share(4).sign(msg)
    )  # bad share in the middle chunk
    want = BatchedBackend(suite).verify_batch(reqs)
    be = TpuBackend(suite)
    be.CHUNK = 8
    got = be.verify_batch(reqs)
    assert got == want
    assert got[13] is False and sum(got) == 23

    # All-good: the combined fast path must short-circuit to all True.
    reqs[13] = VerifyRequest.sig_share(
        pks.public_key_share(5), msg, sks.secret_key_share(5).sign(msg)
    )
    assert be.verify_batch(reqs) == [True] * 24


def test_hybrid_backend_routing():
    """HybridBackend: device for big flushes, host for small, host-only
    when no accelerator is present (routing logic is platform-free)."""
    from hbbft_tpu.crypto.tpu.backend import HybridBackend

    calls = []

    class Stub:
        def __init__(self, name):
            self.name = name

        def verify_batch(self, reqs):
            calls.append((self.name, len(reqs)))
            return [True] * len(reqs)

    suite = BLSSuite()
    hy = HybridBackend(
        suite, min_device_batch=4, device=Stub("dev"), host=Stub("host")
    )
    small = [object()] * 3
    big = [object()] * 9
    assert hy.verify_batch(small) == [True] * 3
    assert hy.verify_batch(big) == [True] * 9
    assert calls == [("host", 3), ("dev", 9)]

    # Forced host-only (the relay-down operating mode) — explicit
    # sentinel, so this asserts on every platform.
    calls.clear()
    hy2 = HybridBackend(
        suite, min_device_batch=4, device=HybridBackend.NO_DEVICE,
        host=Stub("host"),
    )
    assert hy2.device is None
    assert hy2.verify_batch(big) == [True] * 9
    assert calls == [("host", 9)]

    # Mid-run device failure fails over to the host and disables the
    # device for later flushes.
    calls.clear()

    class Dying:
        def verify_batch(self, reqs):
            raise RuntimeError("relay dropped")

    hy3 = HybridBackend(
        suite, min_device_batch=4, device=Dying(), host=Stub("host")
    )
    assert hy3.verify_batch(big) == [True] * 9
    assert hy3.device is None
    assert hy3.verify_batch(big) == [True] * 9
    assert calls == [("host", 9), ("host", 9)]
