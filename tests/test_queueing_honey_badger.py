"""QueueingHoneyBadger + SenderQueue tests.

Reference analogs: upstream ``tests/queueing_honey_badger.rs`` (every
pushed transaction eventually commits, exactly once per node's view) and
the sender-queue epoch-gating semantics of ``src/sender_queue/``.
"""

from hbbft_tpu.net import NetBuilder, ReorderingAdversary
from hbbft_tpu.protocols.dynamic_honey_badger import Change, DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import SenderQueue


def build_qhb_net(n=4, seed=0, batch_size=8, adversary=None, sender_queue=False, f=0):
    def factory(ni, sink, rng):
        if sender_queue:
            return SenderQueue.wrap(
                lambda s: QueueingHoneyBadger(
                    ni, s, batch_size=batch_size, session_id=b"qhb-test"
                ),
                sink,
                peers=list(range(n)),
            )
        return QueueingHoneyBadger(
            ni, sink, batch_size=batch_size, session_id=b"qhb-test"
        )

    b = NetBuilder(n, seed=seed).num_faulty(f).protocol(factory)
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


def committed_txns(net, nid):
    txns = []
    for out in net.node(nid).outputs:
        if isinstance(out, DhbBatch):
            for _, contrib in out.contributions:
                txns.extend(contrib)
    return txns


def test_all_transactions_commit():
    net = build_qhb_net(n=4, seed=11, adversary=ReorderingAdversary())
    all_txns = [f"txn-{nid}-{k}" for nid in net.correct_ids for k in range(6)]
    for nid in net.correct_ids:
        for k in range(6):
            net.send_input(nid, Input.user(f"txn-{nid}-{k}"))
    net.crank_until(
        lambda n: all(
            set(all_txns) <= set(committed_txns(n, i)) for i in n.correct_ids
        ),
        max_cranks=2_000_000,
    )
    for nid in net.correct_ids:
        got = committed_txns(net, nid)
        # exactly-once: no transaction commits twice
        assert len(got) == len(set(got))
    assert net.correct_faults() == []


def test_change_via_input():
    net = build_qhb_net(n=4, seed=12)
    victim = 3
    ni = net.node(0).protocol.netinfo
    new_map = {i: ni.public_key(i) for i in ni.all_ids if i != victim}
    for nid in net.correct_ids:
        net.send_input(nid, Input.change(Change.node_change(new_map)))
        net.send_input(nid, Input.user(f"seed-{nid}"))
    net.crank_until(
        lambda n: all(
            any(
                isinstance(o, DhbBatch) and o.change.kind == "complete"
                for o in n.node(i).outputs
            )
            for i in n.correct_ids
        ),
        max_cranks=2_000_000,
    )
    assert net.node(victim).protocol.netinfo.is_validator() is False
    assert net.correct_faults() == []


def test_sender_queue_wrapped_progress():
    net = build_qhb_net(n=4, seed=13, sender_queue=True)
    all_txns = [f"sq-{nid}-{k}" for nid in net.correct_ids for k in range(3)]
    for nid in net.correct_ids:
        for k in range(3):
            net.send_input(nid, Input.user(f"sq-{nid}-{k}"))
    net.crank_until(
        lambda n: all(
            set(all_txns) <= set(committed_txns(n, i)) for i in n.correct_ids
        ),
        max_cranks=2_000_000,
    )
    assert net.correct_faults() == []


def test_sender_queue_gates_future_messages():
    """A peer stuck at (0,0) only receives messages within its window."""
    net = build_qhb_net(n=4, seed=14, sender_queue=True)
    sq: SenderQueue = net.node(0).protocol
    assert isinstance(sq, SenderQueue)
    far_future = (0, 99)
    step = type(sq.inner.dhb)._make_hb  # just to assert type wiring exists
    verdict = sq._admits((0, 0), far_future)
    assert verdict == "hold"
    assert sq._admits((0, 99), (0, 99)) == "send"
    assert sq._admits((1, 0), (0, 5)) == "drop"
    assert sq._admits((0, 5), (0, 3)) == "drop"
    assert sq._admits((0, 0), (1, 0)) == "hold"


def test_transaction_queue_multiset_removal():
    """remove_multiple: one pass, multiset semantics (each committed
    occurrence removes at most one queued occurrence), order preserved."""
    from hbbft_tpu.protocols.transaction_queue import TransactionQueue

    q = TransactionQueue(["a", "b", "a", "c", "a"])
    q.remove_multiple(["a", "c", "zzz"])
    assert q._txns == ["b", "a", "a"]
    q.remove_multiple([])
    assert q._txns == ["b", "a", "a"]
    q.remove_multiple(["a", "a", "a", "b"])
    assert q._txns == []
    # unhashable transactions: equality-scan fallback
    q2 = TransactionQueue([["x"], ["y"], ["x"]])
    q2.remove_multiple([["x"]])
    assert q2._txns == [["y"], ["x"]]


def test_transaction_queue_removal_linear_shape():
    """Firehose shape: 20k-item queue minus 10k committed completes
    instantly (the old quadratic path took seconds at this size)."""
    import time

    from hbbft_tpu.protocols.transaction_queue import TransactionQueue

    n = 20000
    q = TransactionQueue([f"t{i}" for i in range(n)])
    committed = [f"t{i}" for i in range(0, n, 2)]
    t0 = time.perf_counter()
    q.remove_multiple(committed)
    assert time.perf_counter() - t0 < 0.5
    assert len(q) == n // 2


def test_subset_handling_all_at_end_same_batches():
    """SubsetHandlingStrategy parity (upstream builder option): the
    all-at-end strategy must commit identical batches to incremental."""
    from hbbft_tpu.net import NetBuilder
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    def run(strategy):
        net = (
            NetBuilder(4, seed=29)
            .num_faulty(0)
            .protocol(
                lambda ni, sink, rng: HoneyBadger(
                    ni, sink, subset_handling=strategy
                )
            )
            .build()
        )
        net.broadcast_input(lambda nid: [f"tx-{nid}-{i}" for i in range(3)])
        net.crank_until(
            lambda n: all(len(n.node(i).outputs) >= 1 for i in n.correct_ids)
        )
        assert net.correct_faults() == []
        return [net.node(i).outputs[0] for i in net.correct_ids]

    inc = run("incremental")
    aae = run("all_at_end")
    assert all(b == inc[0] for b in inc)
    assert all(b == aae[0] for b in aae)
    assert inc[0] == aae[0]


def test_subset_handling_plumbs_through_qhb():
    net = build_qhb_net(n=4, seed=31)
    # rebuild with the option to prove the kwarg path end-to-end
    from hbbft_tpu.net import NetBuilder

    net = (
        NetBuilder(4, seed=31)
        .num_faulty(0)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=8, subset_handling="all_at_end"
            )
        )
        .build()
    )
    for nid in net.correct_ids:
        net.send_input(nid, Input.user(f"txn-{nid}"))
    net.crank_until(
        lambda n: all(len(committed_txns(n, i)) >= 4 for i in n.correct_ids)
    )
    views = [sorted(committed_txns(net, i)) for i in net.correct_ids]
    assert all(v == views[0] for v in views)
    assert views[0] == sorted(f"txn-{nid}" for nid in net.correct_ids)
