"""QueueingHoneyBadger + SenderQueue tests.

Reference analogs: upstream ``tests/queueing_honey_badger.rs`` (every
pushed transaction eventually commits, exactly once per node's view) and
the sender-queue epoch-gating semantics of ``src/sender_queue/``.
"""

from hbbft_tpu.net import NetBuilder, ReorderingAdversary
from hbbft_tpu.protocols.dynamic_honey_badger import Change, DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import SenderQueue, SqMessage


def build_qhb_net(n=4, seed=0, batch_size=8, adversary=None, sender_queue=False, f=0):
    def factory(ni, sink, rng):
        if sender_queue:
            return SenderQueue.wrap(
                lambda s: QueueingHoneyBadger(
                    ni, s, batch_size=batch_size, session_id=b"qhb-test"
                ),
                sink,
                peers=list(range(n)),
            )
        return QueueingHoneyBadger(
            ni, sink, batch_size=batch_size, session_id=b"qhb-test"
        )

    b = NetBuilder(n, seed=seed).num_faulty(f).protocol(factory)
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


def committed_txns(net, nid):
    txns = []
    for out in net.node(nid).outputs:
        if isinstance(out, DhbBatch):
            for _, contrib in out.contributions:
                txns.extend(contrib)
    return txns


def test_all_transactions_commit():
    net = build_qhb_net(n=4, seed=11, adversary=ReorderingAdversary())
    all_txns = [f"txn-{nid}-{k}" for nid in net.correct_ids for k in range(6)]
    for nid in net.correct_ids:
        for k in range(6):
            net.send_input(nid, Input.user(f"txn-{nid}-{k}"))
    net.crank_until(
        lambda n: all(
            set(all_txns) <= set(committed_txns(n, i)) for i in n.correct_ids
        ),
        max_cranks=2_000_000,
    )
    for nid in net.correct_ids:
        got = committed_txns(net, nid)
        # exactly-once: no transaction commits twice
        assert len(got) == len(set(got))
    assert net.correct_faults() == []


def test_change_via_input():
    net = build_qhb_net(n=4, seed=12)
    victim = 3
    ni = net.node(0).protocol.netinfo
    new_map = {i: ni.public_key(i) for i in ni.all_ids if i != victim}
    for nid in net.correct_ids:
        net.send_input(nid, Input.change(Change.node_change(new_map)))
        net.send_input(nid, Input.user(f"seed-{nid}"))
    net.crank_until(
        lambda n: all(
            any(
                isinstance(o, DhbBatch) and o.change.kind == "complete"
                for o in n.node(i).outputs
            )
            for i in n.correct_ids
        ),
        max_cranks=2_000_000,
    )
    assert net.node(victim).protocol.netinfo.is_validator() is False
    assert net.correct_faults() == []


def test_sender_queue_wrapped_progress():
    net = build_qhb_net(n=4, seed=13, sender_queue=True)
    all_txns = [f"sq-{nid}-{k}" for nid in net.correct_ids for k in range(3)]
    for nid in net.correct_ids:
        for k in range(3):
            net.send_input(nid, Input.user(f"sq-{nid}-{k}"))
    net.crank_until(
        lambda n: all(
            set(all_txns) <= set(committed_txns(n, i)) for i in n.correct_ids
        ),
        max_cranks=2_000_000,
    )
    assert net.correct_faults() == []


def test_sender_queue_gates_future_messages():
    """A peer stuck at (0,0) only receives messages within its window."""
    net = build_qhb_net(n=4, seed=14, sender_queue=True)
    sq: SenderQueue = net.node(0).protocol
    assert isinstance(sq, SenderQueue)
    far_future = (0, 99)
    step = type(sq.inner.dhb)._make_hb  # just to assert type wiring exists
    verdict = sq._admits((0, 0), far_future)
    assert verdict == "hold"
    assert sq._admits((0, 99), (0, 99)) == "send"
    assert sq._admits((1, 0), (0, 5)) == "drop"
    assert sq._admits((0, 5), (0, 3)) == "drop"
    assert sq._admits((0, 0), (1, 0)) == "hold"
