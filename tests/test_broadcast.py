"""Broadcast (RBC) protocol tests — benchmark config 2 shape (10 nodes, 1KB).

Reference analog: upstream ``tests/broadcast.rs``: all correct nodes
deliver the proposer's value; Byzantine proposers can't cause divergent
delivery.
"""

import random

import pytest

from hbbft_tpu.net import NetBuilder, NullAdversary, RandomAdversary, ReorderingAdversary
from hbbft_tpu.protocols.broadcast import Broadcast

PAYLOAD = bytes(random.Random(0).randrange(256) for _ in range(1024))


def build_net(n=10, seed=0, adversary=None, proposer=0):
    b = NetBuilder(n, seed=seed).protocol(
        lambda ni, sink, rng: Broadcast(ni, proposer)
    )
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


@pytest.mark.parametrize(
    "adversary", [NullAdversary(), ReorderingAdversary(), RandomAdversary()]
)
@pytest.mark.parametrize("seed", [0, 1])
def test_all_deliver_1kb(adversary, seed):
    net = build_net(seed=seed, adversary=adversary)
    net.send_input(0, PAYLOAD)
    net.run_to_termination()
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [PAYLOAD]
    assert net.correct_faults() == []


@pytest.mark.parametrize("n", [1, 2, 4, 7, 16])
def test_various_sizes(n):
    net = build_net(n=n, seed=3)
    net.send_input(0, b"hello broadcast")
    net.run_to_termination()
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [b"hello broadcast"]


def test_empty_and_large_values():
    for payload in (b"", b"x", bytes(range(256)) * 40):
        net = build_net(seed=4)
        net.send_input(0, payload)
        net.run_to_termination()
        assert net.node(3).outputs == [payload]


def test_non_proposer_input_ignored():
    net = build_net(seed=5)
    net.send_input(1, b"not my turn")
    assert not net.queue
    assert not net.node(1).protocol.terminated


def test_echo_hash_counts_toward_ready_threshold():
    """An EchoHash counts as an Echo for the N-f threshold without
    carrying a shard (upstream EchoHash optimization)."""
    from hbbft_tpu.protocols.broadcast import (
        CanDecodeMsg,
        EchoHashMsg,
        EchoMsg,
        ValueMsg,
    )

    net = build_net(n=4, seed=21)
    # Drive node 2 manually (node 3 is crash-faulty): 2 echos + 1 hash.
    node = net.node(2).protocol
    import random as _r

    # Build real proofs by running the proposer's input through another net
    donor = build_net(n=4, seed=21)
    step = donor.node(0).protocol.handle_input(b"payload", _r.Random(0))
    proofs = {}
    for tm in step.messages:
        msg = tm.message
        if hasattr(msg, "proof"):
            for dest in tm.target.recipients(list(range(4)), 0):
                proofs[dest] = msg.proof
    proofs[0] = donor.node(0).protocol._echos[0]

    rng = _r.Random(1)
    node.handle_message(0, ValueMsg(proofs[2]), rng)
    # node 2 echoed (1); deliver a full echo from 0, hash-echo from 3.
    node.handle_message(0, EchoMsg(proofs[0]), rng)
    assert not node._ready_sent
    node.handle_message(3, EchoHashMsg(proofs[0].root), rng)
    assert node._ready_sent  # 2 echos + 1 hash = N - f = 3
    assert 3 in node._echo_hashes


def test_can_decode_switches_to_hash_echo():
    """A peer that declared CanDecode receives EchoHash instead of a full
    Echo when we later send our Echo."""
    from hbbft_tpu.protocols.broadcast import CanDecodeMsg, EchoHashMsg, EchoMsg, ValueMsg

    import random as _r

    donor = build_net(n=4, seed=22)
    step = donor.node(0).protocol.handle_input(b"payload2", _r.Random(0))
    proofs = {}
    for tm in step.messages:
        for dest in tm.target.recipients(list(range(4)), 0):
            proofs[dest] = tm.message.proof

    net = build_net(n=4, seed=22)
    node = net.node(2).protocol
    rng = _r.Random(2)
    # Peer 1 declares CanDecode before our Value arrives.
    node.handle_message(1, CanDecodeMsg(proofs[2].root), rng)
    s = node.handle_message(0, ValueMsg(proofs[2]), rng)
    sent = {(tm.target, type(tm.message)) for tm in s.messages}
    # Full echo to 0 and 2; hash-only to 1.
    by_dest = {}
    for tm in s.messages:
        for dest in tm.target.recipients(list(range(4)), 2):
            by_dest.setdefault(dest, []).append(type(tm.message).__name__)
    assert "EchoHashMsg" in by_dest[1] and "EchoMsg" not in by_dest[1]
    assert "EchoMsg" in by_dest[0] and "EchoMsg" in by_dest[3]


def test_can_decode_announced_at_k_shards():
    """A node broadcasts CanDecode once it holds K shards."""
    from hbbft_tpu.protocols.broadcast import CanDecodeMsg, EchoMsg, ValueMsg

    import random as _r

    donor = build_net(n=4, seed=23)
    step = donor.node(0).protocol.handle_input(b"payload3", _r.Random(0))
    proofs = {}
    for tm in step.messages:
        for dest in tm.target.recipients(list(range(4)), 0):
            proofs[dest] = tm.message.proof
    proofs[0] = donor.node(0).protocol._echos[0]

    net = build_net(n=4, seed=23)
    node = net.node(2).protocol
    rng = _r.Random(3)
    node.handle_message(0, ValueMsg(proofs[2]), rng)  # our echo = 1 shard
    s = node.handle_message(0, EchoMsg(proofs[0]), rng)  # K=2 shards now
    assert any(isinstance(tm.message, CanDecodeMsg) for tm in s.messages)
    # only announced once
    s2 = node.handle_message(1, EchoMsg(proofs[1]), rng)
    assert not any(isinstance(tm.message, CanDecodeMsg) for tm in s2.messages)


def test_full_run_with_optimization_messages_no_faults():
    for seed in (31, 32):
        net = build_net(n=7, seed=seed, adversary=ReorderingAdversary())
        net.send_input(0, PAYLOAD)
        net.run_to_termination()
        for nid in net.correct_ids:
            assert net.node(nid).outputs == [PAYLOAD]
        assert net.correct_faults() == []


def test_batch_propose_matches_individual():
    """batch_propose (device data plane) == per-instance handle_input."""
    import random as _r

    from hbbft_tpu.protocols.broadcast import batch_propose

    payloads = [(_r.Random(i).randbytes(300)) for i in range(4)]
    # Separate nets: each a fresh Broadcast with proposer 0.
    nets_a = [build_net(n=7, seed=40 + i) for i in range(4)]
    nets_b = [build_net(n=7, seed=40 + i) for i in range(4)]

    steps = batch_propose([net.node(0).protocol for net in nets_a], payloads)
    for net, step in zip(nets_a, steps):
        net._process_step(net.node(0), step)
        net.run_to_termination()
    for net, payload in zip(nets_a, payloads):
        for nid in net.correct_ids:
            assert net.node(nid).outputs == [payload]
        assert net.correct_faults() == []

    # Identical message payloads (proofs) as the host path.
    for net, payload in zip(nets_b, payloads):
        net.send_input(0, payload)
        net.run_to_termination()
    for na, nb in zip(nets_a, nets_b):
        assert [n_.outputs for _, n_ in sorted(na.nodes.items())] == [
            n_.outputs for _, n_ in sorted(nb.nodes.items())
        ]
