"""Broadcast (RBC) protocol tests — benchmark config 2 shape (10 nodes, 1KB).

Reference analog: upstream ``tests/broadcast.rs``: all correct nodes
deliver the proposer's value; Byzantine proposers can't cause divergent
delivery.
"""

import random

import pytest

from hbbft_tpu.net import NetBuilder, NullAdversary, RandomAdversary, ReorderingAdversary
from hbbft_tpu.protocols.broadcast import Broadcast

PAYLOAD = bytes(random.Random(0).randrange(256) for _ in range(1024))


def build_net(n=10, seed=0, adversary=None, proposer=0):
    b = NetBuilder(n, seed=seed).protocol(
        lambda ni, sink, rng: Broadcast(ni, proposer)
    )
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


@pytest.mark.parametrize(
    "adversary", [NullAdversary(), ReorderingAdversary(), RandomAdversary()]
)
@pytest.mark.parametrize("seed", [0, 1])
def test_all_deliver_1kb(adversary, seed):
    net = build_net(seed=seed, adversary=adversary)
    net.send_input(0, PAYLOAD)
    net.run_to_termination()
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [PAYLOAD]
    assert net.correct_faults() == []


@pytest.mark.parametrize("n", [1, 2, 4, 7, 16])
def test_various_sizes(n):
    net = build_net(n=n, seed=3)
    net.send_input(0, b"hello broadcast")
    net.run_to_termination()
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [b"hello broadcast"]


def test_empty_and_large_values():
    for payload in (b"", b"x", bytes(range(256)) * 40):
        net = build_net(seed=4)
        net.send_input(0, payload)
        net.run_to_termination()
        assert net.node(3).outputs == [payload]


def test_non_proposer_input_ignored():
    net = build_net(seed=5)
    net.send_input(1, b"not my turn")
    assert not net.queue
    assert not net.node(1).protocol.terminated
