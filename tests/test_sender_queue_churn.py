"""SenderQueue membership-change duties (upstream ``src/sender_queue/``).

Two capabilities beyond epoch gating:

* JoinPlan handover: when a change-complete batch adds validators, each
  SenderQueue hands the ``JoinPlan`` to the new peers through the queue;
  a :class:`JoiningSenderQueue` node constructs its protocol from the
  received plan and commits the next era's batches — no manual plumbing.
* Deferred removal: a validator removed by a change keeps receiving its
  final era's messages (so it can commit the change-complete batch) and
  is only dropped from the peer set once it announces the new era.
"""

import random

from hbbft_tpu.crypto.keys import SecretKey
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.dynamic_honey_badger import Change, DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import JoiningSenderQueue, SenderQueue


def build_sq_net(n=4, seed=0, batch_size=8):
    def factory(ni, sink, rng):
        return SenderQueue.wrap(
            lambda s: QueueingHoneyBadger(
                ni, s, batch_size=batch_size, session_id=b"sq-churn"
            ),
            sink,
            peers=list(range(n)),
        )

    return (
        NetBuilder(n, seed=seed)
        .num_faulty(0)
        .max_cranks(10_000_000)
        .protocol(factory)
        .build()
    )


def batches_of(net, nid):
    return [o for o in net.node(nid).outputs if isinstance(o, DhbBatch)]


def drive_epochs(net, txn_prefix, rounds=6, stop=None):
    def sq_ids(n):
        return [
            i for i in n.correct_ids if isinstance(n.node(i).protocol, SenderQueue)
        ]

    for r in range(rounds):
        if stop is not None and stop(net):
            return
        # target = one more batch than the slowest node currently has
        # (absolute r+1 would be pre-satisfied after earlier phases)
        base = min((len(batches_of(net, i)) for i in sq_ids(net)), default=0)
        for nid in sorted(net.nodes):
            net.send_input(nid, Input.user(f"{txn_prefix}-{r}-{nid}"))
        net.crank_until(
            lambda n, want=base + 1: all(
                len(batches_of(n, i)) >= want for i in sq_ids(n)
            ),
            max_cranks=400_000,
        )
    if stop is not None:
        assert stop(net), "condition not reached within driven epochs"


def test_join_via_sender_queue_mid_era_change():
    """A brand-new node joins THROUGH the queue: existing validators vote
    it in, the change-complete batch's JoinPlan is delivered by peers'
    SenderQueues, the joiner self-constructs and commits era-1 batches."""
    net = build_sq_net(n=4, seed=71)
    suite = ScalarSuite()
    sk4 = SecretKey.random(random.Random(999), suite)
    pk4 = sk4.public_key()

    # The joining node exists on the network (transport-wise) but has no
    # protocol state: only a JoiningSenderQueue awaiting a plan.
    def joiner_factory(sink, rng):
        return JoiningSenderQueue(
            4,
            sk4,
            sink,
            peers=[0, 1, 2, 3],
            make_inner=lambda plan, s: QueueingHoneyBadger.from_join_plan(
                4, sk4, plan, s, batch_size=8, session_id=b"sq-churn"
            ),
        )

    net.add_node(4, joiner_factory)

    # Vote to add node 4 (complete new map, upstream Change::NodeChange).
    new_map = dict(net.node(0).netinfo.public_key_map)
    new_map[4] = pk4
    change = Change.node_change(new_map)
    for nid in [0, 1, 2, 3]:
        net.send_input(nid, Input.change(change))

    def joined_and_committed(n):
        j = n.node(4).protocol
        if not j.joined:
            return False
        era1 = [b for b in batches_of(n, 4) if b.era == 1]
        return len(era1) >= 1

    drive_epochs(net, "tx", rounds=8, stop=joined_and_committed)

    joiner = net.node(4).protocol
    assert joiner.joined
    # The joiner's era-1 batches match the validators' era-1 batches.
    j_batches = {(b.era, b.epoch): b for b in batches_of(net, 4)}
    v_batches = {(b.era, b.epoch): b for b in batches_of(net, 0)}
    common = set(j_batches) & set(v_batches)
    assert common, "no common era-1 batch committed"
    for key in common:
        assert j_batches[key].contributions == v_batches[key].contributions
    assert net.correct_faults() == []
    # Peers handed the plan exactly once each and now treat 4 as a peer.
    sq0 = net.node(0).protocol
    assert 4 in sq0._peers and 4 in sq0._join_plan_sent


def test_deferred_removal_of_departing_validator():
    """A removed validator still commits the change-complete batch
    (its final era's messages keep flowing), and is dropped from peers
    only after announcing the new era."""
    net = build_sq_net(n=4, seed=73)
    keep = dict(net.node(0).netinfo.public_key_map)
    keep.pop(3)
    change = Change.node_change(keep)
    for nid in [0, 1, 2, 3]:
        net.send_input(nid, Input.change(change))

    def change_done_everywhere(n):
        return all(
            any(b.change.kind == "complete" for b in batches_of(n, i))
            for i in [0, 1, 2, 3]
        )

    drive_epochs(net, "rm", rounds=8, stop=change_done_everywhere)

    # Node 3 (departing) committed the change-complete batch of its era.
    b3 = [b for b in batches_of(net, 3) if b.change.kind == "complete"]
    assert b3, "departing validator missed the change-complete batch"
    # Drive a little more so node 3's (1, 0) announcement is delivered
    # and peers complete the deferred removal.
    net.crank_until(
        lambda n: all(3 not in n.node(i).protocol._peers for i in [0, 1, 2]),
        max_cranks=200_000,
    )
    for i in [0, 1, 2]:
        sq = net.node(i).protocol
        assert 3 not in sq._peers
        assert 3 not in sq._outbox
        assert 3 not in sq._departing
    assert net.correct_faults() == []
    # era 1 still commits among the remaining three validators
    for r in range(2):
        for nid in [0, 1, 2]:
            net.send_input(nid, Input.user(f"post-{r}-{nid}"))
        net.crank_until(
            lambda n, want=len(batches_of(net, 0)) + 1: all(
                len(batches_of(n, i)) >= want for i in [0, 1, 2]
            ),
            max_cranks=200_000,
        )
    era1 = [b for b in batches_of(net, 0) if b.era == 1]
    assert era1, "no post-removal batches committed"


def test_removed_validator_rejoins_with_fresh_join_plan():
    """A validator removed in one era and voted back in a LATER era must
    receive the new era's JoinPlan (the sent-plans memo is cleared on
    removal): its restarted JoiningSenderQueue joins and commits."""
    net = build_sq_net(n=5, seed=77)
    keep = dict(net.node(0).netinfo.public_key_map)
    removed_pk = keep.pop(4)
    for nid in [0, 1, 2, 3, 4]:
        net.send_input(nid, Input.change(Change.node_change(keep)))

    def change_done(n, era):
        return all(
            any(
                b.change.kind == "complete" and b.era == era
                for b in batches_of(n, i)
            )
            for i in [0, 1, 2, 3]
        )

    drive_epochs(net, "rm", rounds=8, stop=lambda n: change_done(n, 0))
    # Node 4 announces era 1; peers complete its deferred removal.
    net.crank_until(
        lambda n: all(4 not in n.node(i).protocol._peers for i in [0, 1, 2, 3]),
        max_cranks=400_000,
    )

    # "Process restart" of node 4: a fresh JoiningSenderQueue with only
    # its long-term key (its old protocol state is gone).
    sk4 = net.node(4).netinfo.secret_key
    old_outputs = list(net.node(4).outputs)

    def factory(sink, rng):
        return JoiningSenderQueue(
            4,
            sk4,
            sink,
            peers=[0, 1, 2, 3],
            make_inner=lambda plan, s: QueueingHoneyBadger.from_join_plan(
                4, sk4, plan, s, batch_size=8, session_id=b"sq-churn"
            ),
        )

    node4 = net.nodes.pop(4)
    net.node_order = sorted(net.nodes) + sorted(net.faulty_ids)
    net.add_node(4, factory)

    # Vote node 4 back in (era 1 -> era 2).
    back = dict(keep)
    back[4] = removed_pk
    for nid in [0, 1, 2, 3]:
        net.send_input(nid, Input.change(Change.node_change(back)))
    drive_epochs(net, "re", rounds=8, stop=lambda n: change_done(n, 1))

    def rejoined(n):
        j = n.node(4).protocol
        return j.joined and any(b.era >= 2 for b in batches_of(n, 4))

    drive_epochs(net, "post", rounds=8, stop=rejoined)
    # The rejoined node's era-2 batches match the validators'.
    j_batches = {(b.era, b.epoch): b for b in batches_of(net, 4) if b.era >= 2}
    v_batches = {(b.era, b.epoch): b for b in batches_of(net, 0) if b.era >= 2}
    common = set(j_batches) & set(v_batches)
    assert common, "no common era-2 batch"
    for key in common:
        assert j_batches[key].contributions == v_batches[key].contributions
    assert net.correct_faults() == []


def test_join_quorum_resists_forged_plan():
    """join_quorum=2: one forged plan from a single (Byzantine) peer is
    not enough; the node joins on the real plan once two peers deliver
    matching copies, and commits with the network."""
    net = build_sq_net(n=4, seed=79)
    suite = ScalarSuite()
    sk4 = SecretKey.random(random.Random(321), suite)
    pk4 = sk4.public_key()

    def joiner_factory(sink, rng):
        return JoiningSenderQueue(
            4,
            sk4,
            sink,
            peers=[0, 1, 2, 3],
            join_quorum=2,
            make_inner=lambda plan, s: QueueingHoneyBadger.from_join_plan(
                4, sk4, plan, s, batch_size=8, session_id=b"sq-churn"
            ),
        )

    net.add_node(4, joiner_factory)

    # A forged plan arrives first, from one "peer" only.
    from hbbft_tpu.crypto.keys import SecretKeySet
    from hbbft_tpu.net.virtual_net import NetMessage
    from hbbft_tpu.protocols.dynamic_honey_badger import JoinPlan
    from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
    from hbbft_tpu.protocols.sender_queue import SqMessage

    forged_keys = SecretKeySet.random(1, random.Random(99), suite).public_keys()
    forged = JoinPlan(
        1,
        forged_keys,
        tuple(sorted({i: pk4 for i in range(4)}.items())),
        EncryptionSchedule.always(),
    )
    net.inject(NetMessage(sender=2, dest=4, payload=SqMessage.join_plan(forged)))
    while net.queue:
        net.crank()
    assert not net.node(4).protocol.joined  # one vote is not a quorum

    # Legit era change: every peer sends the REAL plan -> quorum reached.
    new_map = dict(net.node(0).netinfo.public_key_map)
    new_map[4] = pk4
    for nid in [0, 1, 2, 3]:
        net.send_input(nid, Input.change(Change.node_change(new_map)))

    def joined_and_committed(n):
        j = n.node(4).protocol
        return j.joined and any(b.era == 1 for b in batches_of(n, 4))

    drive_epochs(net, "q", rounds=8, stop=joined_and_committed)
    assert net.node(4).protocol.joined
    # it joined on the REAL plan (its netinfo matches the validators')
    real_pks = net.node(0).protocol.inner.dhb.netinfo.public_key_set
    joined_pks = net.node(4).protocol.inner.dhb.netinfo.public_key_set
    assert joined_pks == real_pks
    assert joined_pks != forged_keys
