"""SyncKeyGen (DKG) tests.

Mirrors upstream ``src/sync_key_gen.rs`` doc-tests / ``tests/sync_key_gen.rs``
(SURVEY.md §2 #12, §4): full-participation key generation, threshold
signing with the generated keys, observer support, and resilience to a
dealer that corrupts a single node's row.
"""

import random

import pytest

from hbbft_tpu.crypto.keys import SecretKey
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.protocols.sync_key_gen import (
    FAULT_BAD_ACK,
    FAULT_BAD_PART,
    Ack,
    Part,
    SyncKeyGen,
)

SUITE = ScalarSuite()


def _setup(n, seed=7):
    rng = random.Random(seed)
    sks = {i: SecretKey.random(rng, SUITE) for i in range(n)}
    pks = {i: sks[i].public_key() for i in range(n)}
    return rng, sks, pks


def _run_dkg(n, threshold, seed=7, corrupt=None, observer=False):
    """Full in-process DKG; ``corrupt(dealer, part, rng)`` may rewrite parts."""
    rng, sks, pks = _setup(n, seed)
    nodes = {}
    parts = {}
    ids = list(range(n)) + (["obs"] if observer else [])
    for i in ids:
        sk = sks.get(i) or SecretKey.random(rng, SUITE)
        skg, part = SyncKeyGen.new(i, sk, pks, threshold, rng, SUITE)
        nodes[i] = skg
        if part is not None:
            parts[i] = part
    assert observer is False or "obs" not in parts

    acks = []
    for dealer in sorted(parts):
        part = parts[dealer]
        if corrupt is not None:
            part = corrupt(dealer, part, rng) or part
        for i in ids:
            outcome = nodes[i].handle_part(dealer, part, rng)
            if outcome.ack is not None:
                acks.append((i, outcome.ack))
    for sender, ack in acks:
        for i in ids:
            nodes[i].handle_ack(sender, ack)
    return nodes, rng


def test_full_dkg_generates_working_threshold_keys():
    n, t = 4, 1
    nodes, rng = _run_dkg(n, t)
    for skg in nodes.values():
        assert skg.is_ready()
        assert skg.count_complete() == n

    results = {i: skg.generate() for i, skg in nodes.items()}
    pk_bytes = {r[0].to_bytes() for r in results.values()}
    assert len(pk_bytes) == 1, "all nodes derive the same PublicKeySet"

    pk_set = results[0][0]
    assert pk_set.threshold == t
    msg = b"dkg signing test"
    shares = {i: results[i][1].sign(msg) for i in range(t + 1)}
    sig = pk_set.combine_signatures(shares)
    assert pk_set.public_key().verify(msg, sig)
    # Any other t+1 subset combines to the same signature.
    shares2 = {i: results[i][1].sign(msg) for i in range(2, 2 + t + 1)}
    sig2 = pk_set.combine_signatures(shares2)
    assert sig.to_bytes() == sig2.to_bytes()


def test_share_matches_public_key_share():
    n, t = 7, 2
    nodes, _ = _run_dkg(n, t, seed=11)
    pk_set, _ = nodes[0].generate()
    for i in range(n):
        _, share = nodes[i].generate()
        expected = pk_set.public_key_share(i)
        assert (SUITE.g1_generator() * share.x).to_bytes() == expected.to_bytes()


def test_observer_tracks_public_key_but_gets_no_share():
    n, t = 4, 1
    nodes, _ = _run_dkg(n, t, observer=True)
    pk_set, share = nodes["obs"].generate()
    assert share is None
    ref_pk, _ = nodes[0].generate()
    assert pk_set.to_bytes() == ref_pk.to_bytes()


def test_dealer_corrupting_one_row_is_detected_and_tolerated():
    n, t = 4, 1
    victim = 0
    evil_dealer = 3
    faults = []

    def corrupt(dealer, part, rng):
        if dealer != evil_dealer:
            return part
        # Replace the victim's encrypted row with garbage bytes.
        rows = list(part.rows)
        rng2 = random.Random(99)
        from hbbft_tpu.crypto.keys import SecretKey

        bogus_pk = SecretKey.random(rng2, SUITE).public_key()
        rows[victim] = bogus_pk.encrypt(b"garbage", rng2)
        return Part(part.commitment, tuple(rows))

    rng, sks, pks = _setup(n)
    nodes = {}
    parts = {}
    for i in range(n):
        skg, part = SyncKeyGen.new(i, sks[i], pks, t, rng, SUITE)
        nodes[i] = skg
        parts[i] = part

    acks = []
    for dealer in sorted(parts):
        part = corrupt(dealer, parts[dealer], rng)
        for i in range(n):
            outcome = nodes[i].handle_part(dealer, part, rng)
            if not outcome.is_valid:
                faults.append((i, dealer, outcome.fault))
            if outcome.ack is not None:
                acks.append((i, outcome.ack))
    for sender, ack in acks:
        for i in range(n):
            nodes[i].handle_ack(sender, ack)

    # The victim flagged the dealer...
    assert (victim, evil_dealer, FAULT_BAD_PART) in faults
    # ...but the proposal still completed via the other nodes' acks
    # (n-1 = 3 = 2t+1 acks), and the victim recovers its share from them.
    assert all(skg.is_node_ready(evil_dealer) for skg in nodes.values())
    results = {i: nodes[i].generate() for i in range(n)}
    assert len({r[0].to_bytes() for r in results.values()}) == 1
    pk_set = results[victim][0]
    msg = b"still works"
    shares = {i: results[i][1].sign(msg) for i in (victim, 1)}
    sig = pk_set.combine_signatures(shares)
    assert pk_set.public_key().verify(msg, sig)


def test_forged_ack_value_is_rejected():
    n, t = 4, 1
    rng, sks, pks = _setup(n)
    nodes = {}
    parts = {}
    for i in range(n):
        skg, part = SyncKeyGen.new(i, sks[i], pks, t, rng, SUITE)
        nodes[i] = skg
        parts[i] = part
    # Node 0 handles dealer 1's part and produces a genuine ack...
    out = nodes[0].handle_part(1, parts[1], rng)
    ack = out.ack
    nodes[2].handle_part(1, parts[1], rng)
    # ...which an attacker rewrites with wrong encrypted values.
    forged_values = tuple(
        pks[i].encrypt(b"\x00" * 8, rng) for i in range(n)
    )
    forged = Ack(ack.proposer, forged_values)
    outcome = nodes[2].handle_ack(0, forged)
    assert outcome.fault == FAULT_BAD_ACK


def test_bad_ack_value_still_counts_publicly_no_key_divergence():
    """Regression: ack acceptance must depend only on public data.

    A Byzantine acker that corrupts exactly one node's encrypted value
    slot must not make ack sets — and hence the generated keys — diverge
    across nodes.
    """
    n, t = 4, 1
    evil = 3
    victim = 1
    rng, sks, pks = _setup(n, seed=5)
    nodes = {}
    parts = {}
    for i in range(n):
        skg, part = SyncKeyGen.new(i, sks[i], pks, t, rng, SUITE)
        nodes[i] = skg
        parts[i] = part

    acks = []
    for dealer in sorted(parts):
        for i in range(n):
            out = nodes[i].handle_part(dealer, parts[dealer], rng)
            if out.ack is not None:
                ack = out.ack
                if i == evil:
                    # Corrupt only the victim's slot with a wrong value.
                    vals = list(ack.values)
                    vals[victim] = pks[victim].encrypt(
                        __import__("hbbft_tpu.utils.serde", fromlist=["serde"]).dumps(12345),
                        rng,
                    )
                    ack = Ack(ack.proposer, tuple(vals))
                acks.append((i, ack))
    fault_seen = False
    for sender, ack in acks:
        for i in range(n):
            out = nodes[i].handle_ack(sender, ack)
            if not out.is_valid:
                assert i == victim and sender == evil
                fault_seen = True
    assert fault_seen, "victim must detect the corrupted ack value"

    # Ack sets are identical everywhere -> identical keys and usable shares.
    results = {i: nodes[i].generate() for i in range(n)}
    assert len({r[0].to_bytes() for r in results.values()}) == 1
    pk_set = results[victim][0]
    msg = b"no divergence"
    shares = {i: results[i][1].sign(msg) for i in (victim, 2)}
    assert pk_set.public_key().verify(msg, pk_set.combine_signatures(shares))


def test_malformed_part_and_ack_fault_instead_of_crash():
    n, t = 4, 1
    rng, sks, pks = _setup(n)
    skg, part = SyncKeyGen.new(0, sks[0], pks, t, rng, SUITE)

    from hbbft_tpu.crypto.poly import BivarCommitment

    bad_parts = [
        42,
        Part(commitment="junk", rows=(1, 2, 3, 4)),
        Part(commitment=BivarCommitment(elems=5), rows=part.rows),
        Part(part.commitment, rows=("a",) * 4),
        Part(part.commitment, rows=part.rows[:2]),
    ]
    for bad in bad_parts:
        out = skg.handle_part(1, bad, rng)
        assert out.fault == FAULT_BAD_PART, bad

    skg.handle_part(0, part, rng)
    bad_acks = [
        "junk",
        Ack(proposer=[], values=part.rows),  # unhashable proposer
        Ack(proposer=0, values=5),
        Ack(proposer=0, values=("x",) * 4),
        Ack(proposer=0, values=part.rows[:1]),
    ]
    for bad in bad_acks:
        out = skg.handle_ack(1, bad)
        assert not out.is_valid, bad


def test_not_ready_generate_raises():
    n, t = 4, 1
    rng, sks, pks = _setup(n)
    skg, _part = SyncKeyGen.new(0, sks[0], pks, t, rng, SUITE)
    assert not skg.is_ready()
    with pytest.raises(RuntimeError):
        skg.generate()


def test_native_dkg_fast_path_matches_pure_python(monkeypatch):
    """The scalar-suite native fast path (registered commitments,
    one-call ack checks, batched ack building) must be BYTE-identical to
    the pure-Python path: same Acks (same rng stream!), same values,
    same fault outcomes.  The engine-vs-Python equivalence suites cannot
    catch a native bug here because BOTH nets share this module — this
    is the direct cross-check (CLAUDE.md oracle invariant).
    """
    import hbbft_tpu.protocols.sync_key_gen as skg_mod

    nd = skg_mod._native_dkg(SUITE)
    if nd is None:
        pytest.skip("native engine unavailable")

    def run(native: bool):
        if native:
            monkeypatch.setattr(skg_mod, "_NATIVE_DKG", {SUITE.name: nd})
        else:
            monkeypatch.setattr(skg_mod, "_NATIVE_DKG", {SUITE.name: None})
        n, t = 5, 1
        rng, sks, pks = _setup(n, seed=23)
        nodes, parts = {}, {}
        for i in range(n):
            skg, part = SyncKeyGen.new(i, sks[i], pks, t, rng, SUITE)
            nodes[i] = skg
            parts[i] = part
        transcripts = []
        acks = []
        for dealer in sorted(parts):
            part = parts[dealer]
            for i in range(n):
                out = nodes[i].handle_part(dealer, part, rng)
                transcripts.append((i, dealer, out.fault))
                if out.ack is not None:
                    acks.append((i, out.ack))
                    for ct in out.ack.values:
                        transcripts.append(
                            (ct.u.value, ct.v, ct.w.value)
                        )
        # one tampered ack value (valid ciphertext, wrong plaintext) and
        # one corrupted ciphertext exercise the fault paths
        from hbbft_tpu.crypto.keys import Ciphertext

        s0, a0 = acks[0]
        bad_vals = list(a0.values)
        bad_vals[2] = pks[2].encrypt(b"\x00" * 31 + b"\x07", rng)
        acks[0] = (s0, Ack(a0.proposer, tuple(bad_vals)))
        s1, a1 = acks[1]
        ct = a1.values[3]
        broken = Ciphertext(ct.u, ct.v, ct.u, SUITE)  # w = u: invalid
        vals1 = list(a1.values)
        vals1[3] = broken
        acks[1] = (s1, Ack(a1.proposer, tuple(vals1)))
        for sender, ack in acks:
            for i in range(n):
                out = nodes[i].handle_ack(sender, ack)
                transcripts.append((i, sender, ack.proposer, out.fault))
        results = {}
        for i in range(n):
            pk_set, share = nodes[i].generate()
            results[i] = (pk_set.to_bytes(), share.x)
            transcripts.append(sorted(nodes[i].proposals[0].values.items()))
        return transcripts, results

    t_pure, r_pure = run(native=False)
    t_nat, r_nat = run(native=True)
    assert t_pure == t_nat
    assert r_pure == r_nat


def test_native_batch_predigest_matches_pure_python(monkeypatch):
    """The round-6 batch-digest path (predigest_batch -> one C call per
    batch, consumed by handle_part/handle_ack) must be byte-identical to
    the pure-Python oracle: same rng stream, same ack values, same fault
    outcomes, same generated keys — including per-item fallbacks for a
    tampered value, a broken ciphertext, and an OVERSIZED value slot
    (which the digest must skip, not mis-verify)."""
    import hbbft_tpu.protocols.sync_key_gen as skg_mod
    from hbbft_tpu.crypto.keys import Ciphertext

    nd = skg_mod._native_dkg(SUITE)
    if nd is None:
        pytest.skip("native engine unavailable")

    def run(batched: bool):
        if batched:
            monkeypatch.setattr(skg_mod, "_NATIVE_DKG", {SUITE.name: nd})
        else:
            monkeypatch.setattr(skg_mod, "_NATIVE_DKG", {SUITE.name: None})
        n, t = 5, 1
        rng, sks, pks = _setup(n, seed=29)
        nodes, parts = {}, {}
        for i in range(n):
            skg, part = SyncKeyGen.new(i, sks[i], pks, t, rng, SUITE)
            nodes[i] = skg
            parts[i] = part
        transcripts = []
        part_msgs = [(d, parts[d]) for d in sorted(parts)]
        if batched:  # predigest draws NO rng: streams stay aligned
            for i in range(n):
                nodes[i].predigest_batch(part_msgs)
        acks = []
        for d, part in part_msgs:
            for i in range(n):
                out = nodes[i].handle_part(d, part, rng)
                transcripts.append((i, d, out.fault))
                if out.ack is not None:
                    acks.append((i, out.ack))
                    for ct in out.ack.values:
                        transcripts.append((ct.u.value, ct.v, ct.w.value))
        for i in range(n):
            nodes[i].clear_predigest()
        # Tampers: wrong value under a VALID ciphertext, a broken
        # ciphertext, and an oversized (64-byte) value slot.
        s0, a0 = acks[0]
        vals = list(a0.values)
        vals[2] = pks[2].encrypt(b"\x00" * 31 + b"\x05", rng)
        acks[0] = (s0, Ack(a0.proposer, tuple(vals)))
        s1, a1 = acks[1]
        ct1 = a1.values[3]
        vals1 = list(a1.values)
        vals1[3] = Ciphertext(ct1.u, ct1.v, ct1.u, SUITE)  # w = u: invalid
        acks[1] = (s1, Ack(a1.proposer, tuple(vals1)))
        s2, a2 = acks[2]
        vals2 = list(a2.values)
        vals2[1] = pks[1].encrypt(b"\x00" * 64, rng)  # oversized slot
        acks[2] = (s2, Ack(a2.proposer, tuple(vals2)))
        if batched:
            for i in range(n):
                nodes[i].predigest_batch(acks)
        for sender, ack in acks:
            for i in range(n):
                out = nodes[i].handle_ack(sender, ack)
                transcripts.append((i, sender, ack.proposer, out.fault))
        for i in range(n):
            nodes[i].clear_predigest()
        results = {}
        for i in range(n):
            pk_set, share = nodes[i].generate()
            results[i] = (pk_set.to_bytes(), share.x)
            transcripts.append(sorted(nodes[i].proposals[0].values.items()))
        return transcripts, results

    skg_mod.PREDIGEST_STATS.update(items=0, hits=0)
    t_bat, r_bat = run(batched=True)
    assert skg_mod.PREDIGEST_STATS["hits"] > 0, "digest path never engaged"
    t_pure, r_pure = run(batched=False)
    assert t_bat == t_pure
    assert r_bat == r_pure


def test_predigest_per_item_fallback_on_stale_cid(monkeypatch):
    """Fuzz the native-miss path: some batched checks report -1 (stale
    cid) AND the registry generation bumps between digest and handling —
    every miss must fall back per item (with the one-shot re-register)
    and the generated keys must equal the pure-Python run's."""
    import hbbft_tpu.protocols.sync_key_gen as skg_mod

    nd = skg_mod._native_dkg(SUITE)
    if nd is None:
        pytest.skip("native engine unavailable")

    orig = skg_mod._NativeDkg.ack_check_batch

    def flaky(self, items, our_pos, sk_x):
        res = orig(self, items, our_pos, sk_x)
        if res is None:
            return None
        return [(-1, 0) if i % 3 == 0 else rv for i, rv in enumerate(res)]

    def run(batched: bool):
        if batched:
            monkeypatch.setattr(skg_mod, "_NATIVE_DKG", {SUITE.name: nd})
            monkeypatch.setattr(skg_mod._NativeDkg, "ack_check_batch", flaky)
        else:
            monkeypatch.setattr(skg_mod, "_NATIVE_DKG", {SUITE.name: None})
        n, t = 4, 1
        rng, sks, pks = _setup(n, seed=37)
        nodes, parts = {}, {}
        for i in range(n):
            skg, part = SyncKeyGen.new(i, sks[i], pks, t, rng, SUITE)
            nodes[i] = skg
            parts[i] = part
        part_msgs = [(d, parts[d]) for d in sorted(parts)]
        acks = []
        for d, part in part_msgs:
            for i in range(n):
                out = nodes[i].handle_part(d, part, rng)
                assert out.is_valid
                if out.ack is not None:
                    acks.append((i, out.ack))
        if batched:
            for i in range(n):
                nodes[i].predigest_batch(acks)
            # generation bump strands every memoized cid: the per-item
            # fallback must take the refresh path, never a fault.
            nd._lib.hbe_dkg_clear()
        for sender, ack in acks:
            for i in range(n):
                assert nodes[i].handle_ack(sender, ack).is_valid
        for i in range(n):
            nodes[i].clear_predigest()
        return {
            i: (nodes[i].generate()[0].to_bytes(), nodes[i].generate()[1].x)
            for i in range(n)
        }

    assert run(batched=True) == run(batched=False)


def test_stale_cid_refresh_reregisters():
    """ADVICE round 5: a registry generation bump must not strand a
    live commitment on the slow path — the first rc == -1 clears the
    memo and re-registers once, after which the fast path works."""
    import hbbft_tpu.protocols.sync_key_gen as skg_mod

    nd = skg_mod._native_dkg(SUITE)
    if nd is None:
        pytest.skip("native engine unavailable")
    rng, sks, pks = _setup(4, seed=41)
    nodes = {}
    parts = {}
    for i in range(4):
        skg, part = SyncKeyGen.new(i, sks[i], pks, 1, rng, SUITE)
        nodes[i] = skg
        parts[i] = part
    ack = nodes[0].handle_part(1, parts[1], rng).ack
    assert ack is not None
    nodes[2].handle_part(1, parts[1], rng)
    cid_before = parts[1].commitment.__dict__.get("_native_cid")
    assert cid_before is not None and cid_before >= 0
    nd._lib.hbe_dkg_clear()
    out = nodes[2].handle_ack(0, ack)
    assert out.is_valid
    cid_after = parts[1].commitment.__dict__.get("_native_cid")
    assert cid_after is not None and cid_after >= 0
    assert cid_after != cid_before  # re-registered under the new generation
    assert int(nd._lib.hbe_dkg_registry_size()) >= 1
    # and the value actually landed via the refreshed fast path
    assert 1 in nodes[2].proposals[1].values


def test_native_dkg_registry_bounded_and_generation_safe():
    """One registration per distinct commitment (memoized on the shared
    object); hbe_dkg_clear bumps the generation so STALE cids fall back
    (rc -1) instead of ever resolving to a different entry."""
    import hbbft_tpu.protocols.sync_key_gen as skg_mod

    nd = skg_mod._native_dkg(SUITE)
    if nd is None:
        pytest.skip("native engine unavailable")
    lib = nd._lib
    rng, sks, pks = _setup(4, seed=31)
    skg, part = SyncKeyGen.new(0, sks[0], pks, 1, rng, SUITE)
    before = int(lib.hbe_dkg_registry_size())
    cid1 = nd.commit_id(part.commitment)
    assert cid1 >= 0
    assert int(lib.hbe_dkg_registry_size()) == before + 1
    # memoized: second call registers nothing
    assert nd.commit_id(part.commitment) == cid1
    assert int(lib.hbe_dkg_registry_size()) == before + 1
    # generation safety: a cleared registry must never let the stale cid
    # resolve — ack_check reports fall-back, and a NEW registration at
    # the same index gets a different (generation-tagged) cid.
    lib.hbe_dkg_clear()
    assert int(lib.hbe_dkg_registry_size()) == 0
    ct = pks[0].encrypt(b"\x00" * 32, rng)
    rc, _ = nd.ack_check(cid1, 1, 1, ct, sks[0].x)
    assert rc == -1
    skg2, part2 = SyncKeyGen.new(1, sks[1], pks, 1, rng, SUITE)
    cid2 = nd.commit_id(part2.commitment)
    assert cid2 >= 0 and cid2 != cid1
