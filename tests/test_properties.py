"""Property-based tests over (N, f, seed) — upstream ``tests/net/proptest.rs``.

The reference generates network dimensions and RNG seeds with proptest
and asserts the universal protocol invariants (all correct nodes
terminate, outputs agree, no faults recorded against correct nodes);
failures shrink to minimal configurations.  Hypothesis plays that role
here.  Everything is seeded — a failing example replays exactly.
"""

import random

import pytest

# hypothesis is an optional [test] extra (pyproject.toml) — absent on
# minimal boxes; skip at collection instead of erroring so the tier-1
# run doesn't need --continue-on-collection-errors to survive.
pytest.importorskip("hypothesis")

from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from hbbft_tpu.net import (
    NetBuilder,
    NodeOrderAdversary,
    NullAdversary,
    RandomAdversary,
    ReorderingAdversary,
)
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.honey_badger import HoneyBadger
from hbbft_tpu.protocols.subset import Subset, SubsetOutput
from hbbft_tpu.protocols.threshold_sign import ThresholdSign

SETTINGS = settings(
    max_examples=12,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)

# Network dimensions: N and an f <= (N-1)//3 (possibly under-provisioned
# with faulty nodes, like upstream's NetworkDimension strategy).
dims = st.integers(min_value=1, max_value=13).flatmap(
    lambda n: st.tuples(
        st.just(n), st.integers(min_value=0, max_value=(n - 1) // 3)
    )
)
seeds = st.integers(min_value=0, max_value=2**32 - 1)
adversaries = st.sampled_from(
    [NullAdversary, ReorderingAdversary, NodeOrderAdversary, RandomAdversary]
)


@SETTINGS
@given(dim=dims, seed=seeds, adv=adversaries)
def test_broadcast_agreement(dim, seed, adv):
    n, f = dim
    payload = random.Random(seed).randbytes(64)
    net = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .adversary(adv())
        .protocol(lambda ni, sink, rng: Broadcast(ni, 0))
        .build()
    )
    if 0 not in net.correct_ids:
        return  # proposer faulty: delivery is not guaranteed
    net.send_input(0, payload)
    net.run_to_termination(max_cranks=1_000_000)
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [payload]
    assert net.correct_faults() == []


@SETTINGS
@given(dim=dims, seed=seeds, adv=adversaries, inputs=st.integers(0, 2**13 - 1))
def test_binary_agreement_properties(dim, seed, adv, inputs):
    """Agreement + validity: one common decision; unanimous input wins."""
    n, f = dim
    net = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .adversary(adv())
        .protocol(lambda ni, sink, rng: BinaryAgreement(ni, b"prop-aba", sink))
        .build()
    )
    votes = {nid: bool((inputs >> i) & 1) for i, nid in enumerate(net.correct_ids)}
    for nid, vote in votes.items():
        net.send_input(nid, vote)
    net.run_to_termination(max_cranks=2_000_000)
    decisions = {tuple(net.node(nid).outputs) for nid in net.correct_ids}
    assert len(decisions) == 1
    (decision,) = decisions
    assert len(decision) == 1
    if len(set(votes.values())) == 1:
        assert decision[0] == next(iter(votes.values()))
    assert net.correct_faults() == []


@SETTINGS
@given(dim=dims, seed=seeds)
def test_subset_agreement(dim, seed):
    n, f = dim
    net = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .protocol(lambda ni, sink, rng: Subset(ni, b"prop-acs", sink))
        .build()
    )
    for nid in net.correct_ids:
        net.send_input(nid, f"contrib-{nid}".encode())
    net.run_to_termination(max_cranks=2_000_000)
    outs = {
        nid: {
            (o.proposer, o.value)
            for o in net.node(nid).outputs
            if isinstance(o, SubsetOutput) and o.kind == "contribution"
        }
        for nid in net.correct_ids
    }
    sets = list(outs.values())
    assert all(s == sets[0] for s in sets)
    n_val = len(net.correct_ids) + len(net.faulty_ids)
    assert len(sets[0]) >= n_val - f
    assert net.correct_faults() == []


@SETTINGS
@given(dim=dims, seed=seeds)
def test_threshold_sign_unique_signature(dim, seed):
    n, f = dim
    net = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, b"prop-doc", sink))
        .build()
    )
    for nid in net.correct_ids:
        net.send_input(nid, None)
    net.run_to_termination(max_cranks=1_000_000)
    sigs = {net.node(nid).outputs[0].to_bytes() for nid in net.correct_ids}
    assert len(sigs) == 1
    assert net.correct_faults() == []


@SETTINGS
@given(seed=seeds, n=st.integers(min_value=2, max_value=7))
def test_honey_badger_epoch_agreement(seed, n):
    f = (n - 1) // 3
    net = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .protocol(
            lambda ni, sink, rng: HoneyBadger(ni, sink, session_id=b"prop-hb")
        )
        .build()
    )
    for nid in net.correct_ids:
        net.send_input(nid, [f"tx-{nid}"])
    net.crank_until(
        lambda net_: all(net_.node(i).outputs for i in net_.correct_ids),
        max_cranks=2_000_000,
    )
    batches = {nid: net.node(nid).outputs[0] for nid in net.correct_ids}
    views = {
        tuple(
            (p, tuple(c) if isinstance(c, list) else c)
            for p, c in sorted(b.contributions)
        )
        for b in batches.values()
    }
    assert len(views) == 1
    assert net.correct_faults() == []


def test_determinism_same_seed_same_transcript():
    """Same seed ⇒ byte-identical run (SURVEY §5.2's sanitizer analog)."""

    def run(seed):
        net = (
            NetBuilder(6, seed=seed)
            .adversary(RandomAdversary())
            .protocol(
                lambda ni, sink, rng: HoneyBadger(ni, sink, session_id=b"det")
            )
            .build()
        )
        for nid in net.correct_ids:
            net.send_input(nid, [f"tx-{nid}"])
        net.crank_until(
            lambda net_: all(net_.node(i).outputs for i in net_.correct_ids),
            max_cranks=2_000_000,
        )
        return [
            (nid, [sorted(b.contributions) for b in net.node(nid).outputs])
            for nid in net.correct_ids
        ], net.delivered

    a1, a2, b = run(1234), run(1234), run(4321)
    assert a1 == a2
    # Different seed takes a different path (delivery order differs).
    assert a1[1] != b[1] or a1[0] == b[0]


@settings(max_examples=6, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(seed=seeds, n=st.integers(min_value=4, max_value=7))
def test_queueing_honey_badger_exactly_once(seed, n):
    """Every pushed transaction commits exactly once on every node."""
    from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
    from hbbft_tpu.protocols.queueing_honey_badger import (
        Input,
        QueueingHoneyBadger,
    )

    net = (
        NetBuilder(n, seed=seed)
        .adversary(ReorderingAdversary())
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=2 * n, session_id=b"prop-qhb"
            )
        )
        .build()
    )
    txns = [f"tx-{nid}-{k}" for nid in net.correct_ids for k in range(2)]
    for nid in net.correct_ids:
        for k in range(2):
            net.send_input(nid, Input.user(f"tx-{nid}-{k}"))

    def committed(net_, nid):
        out = []
        for o in net_.node(nid).outputs:
            if isinstance(o, DhbBatch):
                for _, c in o.contributions:
                    out.extend(c)
        return out

    net.crank_until(
        lambda net_: all(
            set(txns) <= set(committed(net_, i)) for i in net_.correct_ids
        ),
        max_cranks=3_000_000,
    )
    for nid in net.correct_ids:
        got = committed(net, nid)
        assert len(got) == len(set(got)), "a transaction committed twice"
    assert net.correct_faults() == []
