"""Tests for the invariant linter (tools/lint): one violating and one
clean fixture per rule, plus the whole-repo "HEAD is clean" gate.

The fixtures are source STRINGS fed through the same entry points the
CLI uses (pylints.lint_files / cxxlints.lint_source), so rule behavior
is pinned without touching disk; paths are virtual but repo-shaped
(several rules scope by path).
"""

import json
import os
import subprocess
import sys

import pytest

from tools.lint import contracts, knob_registry, run_all
from tools.lint.cxxlints import lint_source
from tools.lint.pylints import lint_files

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


def py_findings(src, path="hbbft_tpu/crypto/tpu/curve.py"):
    return lint_files({path: src})


# ---------------------------------------------------------------------------
# HBT001: add_unsafe safety annotations
# ---------------------------------------------------------------------------

HBT001_BAD = """
def caller(ops, p, q):
    return add_unsafe(ops, p, q)
"""

HBT001_COMMENT_OK = """
def caller(ops, p, q):
    # safety: inputs are distinct by construction (test fixture)
    return add_unsafe(ops, p, q)
"""

HBT001_DOCSTRING_OK = '''
def caller(ops, p, q):
    """Sum two points.

    add_unsafe safety: the caller guarantees p != ±q.
    """
    return add_unsafe(ops, p, q)
'''


def test_add_unsafe_without_annotation_flagged():
    assert "HBT001" in rules_of(py_findings(HBT001_BAD))


def test_add_unsafe_comment_annotation_passes():
    assert "HBT001" not in rules_of(py_findings(HBT001_COMMENT_OK))


def test_add_unsafe_docstring_annotation_passes():
    assert "HBT001" not in rules_of(py_findings(HBT001_DOCSTRING_OK))


def test_add_unsafe_rule_scoped_to_tpu_tree():
    # The same call outside crypto/tpu/ (e.g. the host oracle) is not
    # this rule's business.
    f = py_findings(HBT001_BAD, path="hbbft_tpu/crypto/bls/curve.py")
    assert "HBT001" not in rules_of(f)


# ---------------------------------------------------------------------------
# HBT002: Step reuse after map_messages
# ---------------------------------------------------------------------------

HBT002_BAD = """
def lift(child_step, wrap):
    step = child_step.map_messages(wrap)
    return child_step.output
"""

HBT002_OK = """
def lift(child_step, wrap):
    step = child_step.map_messages(wrap)
    outputs, step.output = step.output, []
    return step
"""

HBT002_REBIND_OK = """
def lift(child_step, wrap, fresh):
    step = child_step.map_messages(wrap)
    child_step = fresh()
    return child_step.output
"""


def test_step_reuse_flagged():
    f = py_findings(HBT002_BAD, path="hbbft_tpu/protocols/subset.py")
    assert "HBT002" in rules_of(f)


def test_step_no_reuse_passes():
    f = py_findings(HBT002_OK, path="hbbft_tpu/protocols/subset.py")
    assert "HBT002" not in rules_of(f)


def test_step_rebound_name_passes():
    f = py_findings(HBT002_REBIND_OK, path="hbbft_tpu/protocols/subset.py")
    assert "HBT002" not in rules_of(f)


# ---------------------------------------------------------------------------
# HBT003: jit of interpret-mode pallas_call
# ---------------------------------------------------------------------------

HBT003_BAD = """
import jax
import jax.experimental.pallas as pl

def kernel_host(x, interpret):
    return pl.pallas_call(_body, out_shape=x, interpret=interpret)(x)

kernel_jit = jax.jit(kernel_host)
"""

HBT003_PARTIAL_BAD = """
import functools, jax
import jax.experimental.pallas as pl

def kernel_host(x, interpret):
    return pl.pallas_call(_body, out_shape=x, interpret=interpret)(x)

kernel_jit = jax.jit(functools.partial(kernel_host, interpret=True))
"""

HBT003_OK = """
import functools, jax
import jax.experimental.pallas as pl

def kernel_host(x, interpret):
    return pl.pallas_call(_body, out_shape=x, interpret=interpret)(x)

kernel_jit = jax.jit(functools.partial(kernel_host, interpret=False))
"""


def test_jit_of_interpret_capable_flagged():
    f = py_findings(HBT003_BAD, path="hbbft_tpu/ops/jaxops/k.py")
    assert "HBT003" in rules_of(f)


def test_jit_of_partial_interpret_true_flagged():
    f = py_findings(HBT003_PARTIAL_BAD, path="hbbft_tpu/ops/jaxops/k.py")
    assert "HBT003" in rules_of(f)


def test_jit_of_partial_pinned_false_passes():
    f = py_findings(HBT003_OK, path="hbbft_tpu/ops/jaxops/k.py")
    assert "HBT003" not in rules_of(f)


def test_partial_jit_decorator_flagged():
    # @partial(jax.jit, ...) is the standard options-carrying jit idiom.
    src = """
from functools import partial
import jax
import jax.experimental.pallas as pl

@partial(jax.jit, static_argnums=(1,))
def kernel_host(x, interpret):
    return pl.pallas_call(_body, out_shape=x, interpret=interpret)(x)
"""
    f = py_findings(src, path="hbbft_tpu/ops/jaxops/k.py")
    assert "HBT003" in rules_of(f)


# ---------------------------------------------------------------------------
# HBT004: cross-scan accumulator chains
# ---------------------------------------------------------------------------

HBT004_BAD = """
import jax

def bad(segments, base, chain0):
    chain = chain0
    carry = base
    for seg in segments:
        carry, _ = jax.lax.scan(step, carry, seg)
        chain = add_unsafe(ops, chain, carry)  # safety: fixture
    return chain
"""

HBT004_OK_CARRY = """
import jax

def good(segments, base):
    carry = base
    for seg in segments:
        carry, _ = jax.lax.scan(step, carry, seg)
        carry = mul(carry, base)
    return carry
"""

HBT004_OK_COLLECT = """
import jax

def good(segments, base):
    carry = base
    curs = []
    for seg in segments:
        carry, _ = jax.lax.scan(step, carry, seg)
        curs.append(carry)
    return tree_sum(curs)
"""


def test_cross_scan_accumulator_flagged():
    f = py_findings(HBT004_BAD, path="hbbft_tpu/crypto/tpu/x.py")
    assert "HBT004" in rules_of(f)


def test_scan_carry_update_passes():
    # pow_x_abs / miller_loop shape: the updated name IS the scan carry.
    f = py_findings(HBT004_OK_CARRY, path="hbbft_tpu/crypto/tpu/x.py")
    assert "HBT004" not in rules_of(f)


def test_collect_then_reduce_passes():
    # The documented fix: collect per-segment values, reduce after.
    f = py_findings(HBT004_OK_COLLECT, path="hbbft_tpu/crypto/tpu/x.py")
    assert "HBT004" not in rules_of(f)


# ---------------------------------------------------------------------------
# HBT005: subgroup-check reachability
# ---------------------------------------------------------------------------

HBT005_SUITE_BAD = """
class LeakySuite:
    def g1_from_bytes(self, data):
        return G1Elem(decode(data))
"""

HBT005_SUITE_OK = """
class SafeSuite:
    def g1_from_bytes(self, data):
        elem = G1Elem(decode(data))
        if not self.is_g1(elem):
            raise ValueError("bad point")
        return elem
"""

HBT005_WIRE_BAD = """
def _unpack_ciphertext(f):
    name, u, v, w = f
    return Ciphertext(u, v, w, get_suite(name))

register_struct("ct", Ciphertext, _pack_ciphertext, _unpack_ciphertext)
"""

HBT005_WIRE_OK = """
def _g1(suite, v, what):
    return v

def _unpack_ciphertext(f):
    name, u, v, w = f
    suite = get_suite(name)
    return Ciphertext(_g1(suite, u, "u"), v, w, suite)

register_struct("ct", Ciphertext, _pack_ciphertext, _unpack_ciphertext)
"""

HBT005_WIRE_UNKNOWN_TAG = """
def _unpack_widget(f):
    return Widget(*f)

register_struct("widget", Widget, _pack_widget, _unpack_widget)
"""


def test_from_bytes_without_check_flagged():
    f = py_findings(HBT005_SUITE_BAD, path="hbbft_tpu/crypto/suite.py")
    assert "HBT005" in rules_of(f)


def test_from_bytes_checked_in_any_module_path():
    # The entry-point rule follows the definition wherever it lives — a
    # future suite in a new module is not exempt by its path.
    f = py_findings(
        HBT005_SUITE_BAD, path="hbbft_tpu/crypto/edwards/suite.py"
    )
    assert "HBT005" in rules_of(f)


def test_from_bytes_with_check_passes():
    f = py_findings(HBT005_SUITE_OK, path="hbbft_tpu/crypto/suite.py")
    assert "HBT005" not in rules_of(f)


def test_point_unpacker_without_check_flagged():
    f = py_findings(HBT005_WIRE_BAD, path="hbbft_tpu/wire.py")
    assert "HBT005" in rules_of(f)


def test_point_unpacker_with_funnel_passes():
    f = py_findings(HBT005_WIRE_OK, path="hbbft_tpu/wire.py")
    assert "HBT005" not in rules_of(f)


def test_unclassified_struct_tag_flagged():
    f = py_findings(HBT005_WIRE_UNKNOWN_TAG, path="hbbft_tpu/wire.py")
    assert "HBT005" in rules_of(f)


# ---------------------------------------------------------------------------
# HBT006: socket reads honor the max-frame plumbing
# ---------------------------------------------------------------------------

HBT006_UNBOUNDED_BAD = """
def read_all(sock):
    return sock.recv(1 << 30)
"""

HBT006_NO_ARG_BAD = """
def read_all(sock):
    return sock.recv()
"""

HBT006_CHUNK_OK = """
from hbbft_tpu.transport.framing import RECV_CHUNK

def read_some(sock):
    return sock.recv(RECV_CHUNK)
"""

HBT006_SMALL_LITERAL_OK = """
def read_some(sock):
    return sock.recv(4096)
"""

HBT006_ESCAPED_OK = """
def drain_wake_pipe(pipe):
    # lint: raw-recv (self-pipe, not peer input)
    return pipe.recv(1 << 20)
"""


def test_unbounded_recv_flagged():
    f = py_findings(HBT006_UNBOUNDED_BAD, path="hbbft_tpu/transport/transport.py")
    assert "HBT006" in rules_of(f)
    f = py_findings(HBT006_NO_ARG_BAD, path="hbbft_tpu/transport/transport.py")
    assert "HBT006" in rules_of(f)


def test_recv_chunk_and_small_literal_pass():
    f = py_findings(HBT006_CHUNK_OK, path="hbbft_tpu/transport/transport.py")
    assert "HBT006" not in rules_of(f)
    f = py_findings(
        HBT006_SMALL_LITERAL_OK, path="hbbft_tpu/transport/transport.py"
    )
    assert "HBT006" not in rules_of(f)


def test_recv_escape_comment_passes():
    f = py_findings(HBT006_ESCAPED_OK, path="hbbft_tpu/transport/transport.py")
    assert "HBT006" not in rules_of(f)


def test_recv_rule_scoped_to_package_tree():
    f = py_findings(HBT006_UNBOUNDED_BAD, path="tests/test_transport.py")
    assert "HBT006" not in rules_of(f)


# ---------------------------------------------------------------------------
# HBC001: C++ field resets (fixture structs + patched real source)
# ---------------------------------------------------------------------------

CXX_FIXTURE = """
struct Sbv {
  int n = 0;
  bool aux_sent = false;
};

struct Ba {
  int round = 0;
  Sbv sbv;
};

struct Proposal {
  Ba ba;
  int decision = -1;
  bool emitted = false;
  int forgotten = 0;

  void reset() {
    ba.round = 0;
    ba.sbv = Sbv();
    decision = -1;
    emitted = false;
  }
};

struct EpochState {
  int epoch = 0;  // lint: not-reset (advanced by caller)
  bool subset_done = false;
  void reset_for_epoch() {
    subset_done = false;
  }
};
"""


def test_cxx_unreset_field_flagged():
    f = [x for x in lint_source(CXX_FIXTURE, "fixture.cpp") if x.rule == "HBC001"]
    assert len(f) == 1 and "'forgotten'" in f[0].message


def test_cxx_fixture_clean_when_reset():
    fixed = CXX_FIXTURE.replace("emitted = false;\n  }", "emitted = false;\n    forgotten = 0;\n  }")
    f = [x for x in lint_source(fixed, "fixture.cpp") if x.rule == "HBC001"]
    assert f == []


def test_cxx_nested_field_requires_reset():
    # Remove the whole-object sbv reset: Sbv's fields must then be
    # reset one by one via ba.sbv.<field>.
    broken = CXX_FIXTURE.replace("    ba.sbv = Sbv();\n", "")
    broken = broken.replace("int forgotten = 0;\n", "")
    f = [x for x in lint_source(broken, "fixture.cpp") if x.rule == "HBC001"]
    assert any("ba.sbv." in x.message for x in f)


def test_cxx_container_of_reset_structs_flagged(engine_src):
    # A container holding reset-tracked structs cannot be verified
    # per-element: it must be annotated, never silently passed.
    patched = engine_src.replace(
        "struct Proposal {", "struct Proposal {\n  std::array<Ba, 2> spares;"
    )
    f = [x for x in lint_source(patched) if x.rule == "HBC001"]
    assert any("spares" in x.message for x in f)


def test_cxx_engine_alias_does_not_evade_prof_rule():
    # The engine reference may be named anything; a renamed parameter
    # must not disable the single-writer check (or its guard).
    bad = "void f(Engine& eng) {\n  eng.prof_count[14] += 1;\n}\n"
    f = [x for x in lint_source(bad, "f.cpp") if x.rule == "HBC002"]
    assert len(f) == 1
    ok = (
        "void f(Engine& eng) {\n  if (!eng.mt_active) {\n"
        "    eng.prof_count[14] += 1;\n  }\n}\n"
    )
    f = [x for x in lint_source(ok, "f.cpp") if x.rule == "HBC002"]
    assert f == []


def test_cxx_braceless_guard_covers_only_its_statement():
    # A braceless '!mt_active' guard must cover exactly its own
    # statement — not an unrelated block opening on the next line.
    fixture = """
void g(Engine& e) {
  if (!e.mt_active) e.prof_count[14]++;
  for (int i = 0; i < 3; ++i) {
    e.prof_cycles[13] += 1;
  }
}
"""
    f = [x for x in lint_source(fixture, "fixture.cpp") if x.rule == "HBC002"]
    assert len(f) == 1 and f[0].line == 5


def test_cxx_guard_brace_styles_all_recognized():
    for form in (
        "if (!e.mt_active) {\n    e.prof_count[14]++;\n  }",
        "if (!e.mt_active)\n  {\n    e.prof_count[14]++;\n  }",
        "if (!e.mt_active)\n    e.prof_count[14]++;",
        "if (!e.mt_active) e.prof_count[14]++;",
    ):
        src = "void g(Engine& e) {\n  %s\n}\n" % form
        f = [x for x in lint_source(src, "f.cpp") if x.rule == "HBC002"]
        assert f == [], (form, [x.render() for x in f])


def test_cxx_not_reset_annotation_does_not_leak_to_neighbor():
    # An inline '// lint: not-reset' trailer on one field must not
    # exempt the NEXT declaration from the reset check.
    fixture = """
struct Proposal {
  int cfg = 0;  // lint: not-reset (assigned at epoch open)
  int forgotten = 0;
  void reset() {}
};
struct EpochState {
  int x = 0;
  void reset_for_epoch() { x = 0; }
};
"""
    f = [x for x in lint_source(fixture, "fixture.cpp") if x.rule == "HBC001"]
    assert len(f) == 1 and "'forgotten'" in f[0].message


def test_cxx_stale_slot_claims_only_checked_on_engine_source():
    # Fixtures/partial sources legitimately omit claimed slots; only the
    # real engine.cpp is the registry's ground truth.
    f = [x for x in lint_source(CXX_FIXTURE, "fixture.cpp") if x.rule == "HBC004"]
    assert f == []


@pytest.fixture(scope="module")
def engine_src():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "native", "engine.cpp")) as fh:
        return fh.read()


def test_engine_patched_unreset_proposal_field_flagged(engine_src):
    # The acceptance demonstration: a deliberately added mutable
    # Proposal field with no reset fails lint on the REAL source.
    patched = engine_src.replace(
        "struct Proposal {", "struct Proposal {\n  int sneaky_counter = 0;"
    )
    f = [x for x in lint_source(patched) if x.rule == "HBC001"]
    assert any("sneaky_counter" in x.message for x in f)


def test_engine_patched_flatmap_clear_flagged(engine_src):
    # The arena reset model (round 17): a FlatMap field restored with
    # .clear() instead of .drop() keeps a carve pointer into arena
    # memory across the watermark reset — name-mention must NOT pass.
    patched = engine_src.replace("bc.echos.drop();", "bc.echos.clear();")
    assert patched != engine_src
    f = [x for x in lint_source(patched) if x.rule == "HBC001"]
    assert any("echos" in x.message and ".drop()" in x.message for x in f)


def test_engine_patched_missing_arena_watermark_flagged(engine_src):
    # Removing the single arena.reset( site must fail: every dropped
    # FlatMap carve relies on it for reclamation.
    patched = engine_src.replace("arena.reset(", "arena_reset_disabled(")
    assert patched != engine_src
    f = [x for x in lint_source(patched) if x.rule == "HBC001"]
    assert any("watermark" in x.message for x in f)


def test_engine_patched_free_slot_write_flagged(engine_src, monkeypatch):
    # Every slot is claimed as of round 6 (12/15 = batch/contrib wall),
    # so simulate releasing slot 12: the claim-before-stamp rule must
    # then flag the engine's existing slot-12 stamps as unclaimed.
    from tools.lint import cxxlints

    monkeypatch.setattr(
        cxxlints,
        "CLAIMED_SLOTS",
        {k: v for k, v in cxxlints.CLAIMED_SLOTS.items() if k != 12},
    )
    monkeypatch.setattr(cxxlints, "FREE_SLOTS", frozenset({12}))
    f = [x for x in lint_source(engine_src) if x.rule == "HBC004"]
    assert any("slot 12" in x.message for x in f)


def test_engine_rlc_slot_claim_matches_stamps(engine_src, monkeypatch):
    # Round 7 retired slot 11's settled round-4 continuation-max claim
    # and re-claimed it for the scalar RLC verdict-pass stats.  Releasing
    # the claim must flag scalar_rlc_verdicts' slot-11 stamps — pinning
    # both directions: the RLC instrumentation really stamps the slot it
    # claims, and the claim is not stale.
    from tools.lint import cxxlints

    monkeypatch.setattr(
        cxxlints,
        "CLAIMED_SLOTS",
        {k: v for k, v in cxxlints.CLAIMED_SLOTS.items() if k != 11},
    )
    monkeypatch.setattr(cxxlints, "FREE_SLOTS", frozenset({11}))
    f = [x for x in lint_source(engine_src) if x.rule == "HBC004"]
    assert any("slot 11" in x.message for x in f)


def test_engine_patched_unguarded_prof_write_flagged(engine_src):
    # A stamp added OUTSIDE the !mt_active guard (e.g. in pending_run,
    # which workers reach) must fail HBC002.
    patched = engine_src.replace(
        "void pending_run(Engine& e, Node& node, Pending& p, bool ok) {",
        "void pending_run(Engine& e, Node& node, Pending& p, bool ok) {\n"
        "  e.prof_count[13]++;",
    )
    f = [x for x in lint_source(patched) if x.rule == "HBC002"]
    assert len(f) == 1


def test_engine_patched_unlocked_cache_access_flagged(engine_src):
    patched = engine_src.replace(
        "void pending_run(Engine& e, Node& node, Pending& p, bool ok) {",
        "void pending_run(Engine& e, Node& node, Pending& p, bool ok) {\n"
        "  e.decoded_roots.clear();",
    )
    f = [x for x in lint_source(patched) if x.rule == "HBC003"]
    assert any("decoded_roots" in x.message for x in f)


# ---------------------------------------------------------------------------
# HBC005: TraceKind <-> exporter taxonomy parity
# ---------------------------------------------------------------------------


def test_cxx_fixture_without_trace_enum_skips_taxonomy():
    f = [x for x in lint_source(CXX_FIXTURE, "fixture.cpp") if x.rule == "HBC005"]
    assert f == []


def test_engine_taxonomy_is_in_parity(engine_src):
    assert [
        x.render() for x in lint_source(engine_src) if x.rule == "HBC005"
    ] == []


def test_engine_patched_new_trace_kind_without_exporter_entry_flagged(
    engine_src,
):
    # Adding an enum value without teaching the exporter its name must
    # fail: the event would surface as opaque engine.k99 and every
    # ring-derived analysis would silently miss it.
    patched = engine_src.replace(
        "enum TraceKind : int32_t {",
        "enum TraceKind : int32_t {\n  TR_SNEAKY_THING = 99,",
    )
    f = [x for x in lint_source(patched) if x.rule == "HBC005"]
    assert any(
        "TR_SNEAKY_THING" in x.message and "engine.k99" in x.message
        for x in f
    )
    # ...and the missing docs-table row is reported too
    assert any("sneaky.thing" in x.message for x in f)


def test_engine_removed_trace_kind_leaves_dead_exporter_row(engine_src):
    # Removing an enum value (here: renumbering TR_BA_INPUT away) while
    # TRACE_KIND_NAMES still maps it must flag the dead taxonomy row.
    patched = engine_src.replace("TR_BA_INPUT = 11,", "TR_BA_INPUT = 63,")
    f = [x for x in lint_source(patched) if x.rule == "HBC005"]
    assert any("11" in x.message and "dead taxonomy row" in x.message for x in f)


def test_trace_enum_name_mapping_rule():
    from tools.lint.cxxlints import _enum_to_name

    assert _enum_to_name("TR_EPOCH_OPEN") == "epoch.open"
    assert _enum_to_name("TR_BA_INPUT") == "ba.input"
    assert _enum_to_name("TR_DECRYPT_START") == "decrypt.start"


# ---------------------------------------------------------------------------
# HBX001-003: cross-language contracts (tools/lint/contracts.py).
# Mutation self-tests: seed a one-line drift into a string copy of the
# real sources (via the overrides dict — disk is never touched) and
# assert the rule fires, so the analyzer is provably live, not
# vacuously green.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def wire_src():
    with open(os.path.join(REPO, "hbbft_tpu", "wire.py")) as f:
        return f.read()


@pytest.fixture(scope="module")
def engine_src():
    with open(os.path.join(REPO, "native", "engine.cpp")) as f:
        return f.read()


def test_hbx001_clean_at_head():
    assert contracts.rule_wire_parity() == []


def test_hbx001_engine_tag_rename_fires(engine_src):
    # One-line drift: the engine starts emitting/accepting a tag the
    # Python codec has never heard of (and stops carrying ba_aux).
    mutated = engine_src.replace('"ba_aux"', '"ba_zux"')
    assert mutated != engine_src
    found = contracts.rule_wire_parity({"native/engine.cpp": mutated})
    assert any(f.rule == "HBX001" and "ba_zux" in f.message for f in found)
    # ...and the now-orphaned Python registration is flagged too.
    assert any(
        f.rule == "HBX001" and '"ba_aux"' in f.message and f.path.endswith("wire.py")
        for f in found
    )


def test_hbx001_python_registration_removed_fires(wire_src):
    lines = [
        ln
        for ln in wire_src.splitlines(keepends=True)
        if 'register_struct("ba_aux"' not in ln
    ]
    mutated = "".join(lines)
    assert mutated != wire_src
    found = contracts.rule_wire_parity({"hbbft_tpu/wire.py": mutated})
    assert any(
        f.rule == "HBX001"
        and f.path == "native/engine.cpp"
        and '"ba_aux"' in f.message
        for f in found
    )


def test_hbx001_oneside_annotation_removed_fires(wire_src):
    # Drop just the marker line above the "ct" registration: the tag is
    # still legitimately Python-only, but the explicit escape is gone.
    lines = [
        ln
        for ln in wire_src.splitlines(keepends=True)
        if "wire-oneside (engine carries ciphertexts" not in ln
    ]
    mutated = "".join(lines)
    assert mutated != wire_src
    found = contracts.rule_wire_parity({"hbbft_tpu/wire.py": mutated})
    assert any(
        f.rule == "HBX001" and '"ct"' in f.message and "wire-oneside" in f.message
        for f in found
    )


def test_hbx001_stale_oneside_annotation_fires(wire_src):
    # An escape on a tag the engine DOES mirror is itself a finding.
    mutated = wire_src.replace(
        'register_struct("sqmsg"',
        '# lint: wire-oneside (bogus escape)\nregister_struct("sqmsg"',
    )
    assert mutated != wire_src
    found = contracts.rule_wire_parity({"hbbft_tpu/wire.py": mutated})
    assert any(
        f.rule == "HBX001" and "stale escape" in f.message and '"sqmsg"' in f.message
        for f in found
    )


def test_hbx001_scan_limit_drift_fires(engine_src):
    mutated = engine_src.replace("1ull << 28", "1ull << 20")
    assert mutated != engine_src
    found = contracts.rule_wire_parity({"native/engine.cpp": mutated})
    assert any(f.rule == "HBX001" and "max_len" in f.message for f in found)


def test_hbx001_depth_limit_drift_fires(engine_src):
    mutated = engine_src.replace("bp, triples, 64,", "bp, triples, 63,")
    assert mutated != engine_src
    found = contracts.rule_wire_parity({"native/engine.cpp": mutated})
    assert any(f.rule == "HBX001" and "max_depth" in f.message for f in found)


def test_hbx001_extraction_failure_is_loud():
    # A refactor that renames the extraction landmarks must fail the
    # lint, never silently disable the rule.
    found = contracts.rule_wire_parity({"native/engine.cpp": "int main() {}\n"})
    assert any(
        f.rule == "HBX001" and "extraction failed" in f.message for f in found
    )


def test_hbx002_clean_at_head():
    assert contracts.rule_knob_registry() == []


def test_hbx002_unregistered_knob_fires():
    # The fixture file's AST joins the adjacent literals into one knob
    # name; this test file itself never contains it contiguously (the
    # scan excludes tests/test_lint.py anyway).
    sneaky = "HBBFT_TPU_" + "SNEAKY"
    fixture = 'import os\nX = os.environ.get("HBBFT_TPU_" "SNEAKY", "0")\n'
    found = contracts.rule_knob_registry({"hbbft_tpu/zz_knob_fixture.py": fixture})
    assert any(
        f.rule == "HBX002"
        and sneaky in f.message
        and f.path == "hbbft_tpu/zz_knob_fixture.py"
        for f in found
    )


def test_hbx002_unregistered_c_knob_fires():
    ghost = "HBBFT_TPU_" + "CGHOST"
    fixture = '#include <cstdlib>\nstatic int g = !!getenv("' + ghost + '");\n'
    found = contracts.rule_knob_registry({"native/zz_fixture.cpp": fixture})
    assert any(f.rule == "HBX002" and ghost in f.message for f in found)


def test_hbx002_dead_registry_entry_fires(monkeypatch):
    ghost = "HBBFT_TPU_" + "GHOST"
    patched = dict(knob_registry.KNOBS)
    patched[ghost] = knob_registry.Knob(ghost, "unset", "nowhere", "dead entry")
    monkeypatch.setattr(knob_registry, "KNOBS", patched)
    found = contracts.rule_knob_registry()
    assert any(
        f.rule == "HBX002" and ghost in f.message and "no os.environ" in f.message
        for f in found
    )
    # The committed KNOBS.md no longer matches the (patched) registry
    # either — staleness is part of the same contract.
    assert any(f.rule == "HBX002" and f.path == "docs/KNOBS.md" for f in found)


def test_hbx002_stale_knobs_md_fires():
    found = contracts.rule_knob_registry({"docs/KNOBS.md": "# stale\n"})
    assert any(
        f.rule == "HBX002"
        and f.path == "docs/KNOBS.md"
        and "--knobs-md" in f.message
        for f in found
    )


def test_hbx002_committed_knobs_md_matches_generated():
    with open(os.path.join(REPO, "docs", "KNOBS.md")) as f:
        committed = f.read()
    assert committed.rstrip("\n") == knob_registry.generate_knobs_md().rstrip("\n")


def test_hbx003_clean_at_head():
    assert contracts.rule_mirror_obligations() == []


def test_hbx003_orphan_python_anchor_fires():
    fixture = "# mirror: only-here-key — fixture orphan\n"
    found = contracts.rule_mirror_obligations(
        {"hbbft_tpu/zz_mirror_fixture.py": fixture}
    )
    assert any(
        f.rule == "HBX003"
        and "only-here-key" in f.message
        and "no C++ twin" in f.message
        for f in found
    )


def test_hbx003_deleted_cxx_anchor_fires(engine_src):
    # Deleting one half of a mirrored pair (here: the engine's
    # ts-acceptance-item anchor) must point at the surviving twin.
    mutated = engine_src.replace("// mirror: ts-acceptance-item", "//")
    assert mutated != engine_src
    found = contracts.rule_mirror_obligations({"native/engine.cpp": mutated})
    orphans = [
        f for f in found if f.rule == "HBX003" and "ts-acceptance-item" in f.message
    ]
    assert orphans and orphans[0].path == "hbbft_tpu/protocols/threshold_sign.py"


def test_hbx003_deleted_python_anchor_fires():
    rel = "hbbft_tpu/protocols/threshold_decrypt.py"
    with open(os.path.join(REPO, rel)) as f:
        src = f.read()
    mutated = src.replace("# mirror: td-acceptance-group", "#")
    assert mutated != src
    found = contracts.rule_mirror_obligations({rel: mutated})
    orphans = [
        f
        for f in found
        if f.rule == "HBX003" and "td-acceptance-group" in f.message
    ]
    assert orphans and orphans[0].path == "native/engine.cpp"


# ---------------------------------------------------------------------------
# Whole-repo gates
# ---------------------------------------------------------------------------


def test_repo_is_lint_clean():
    assert run_all() == []


def test_cli_exit_codes(tmp_path):
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo}
    # Clean repo -> 0; a violating fixture file -> nonzero.
    ok = subprocess.run(
        [sys.executable, "-m", "tools.lint"],
        capture_output=True,
        cwd=repo,
        env=env,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # Path-scoped rules key off the path, so give the fixture a
    # crypto/tpu/-shaped location.
    target = tmp_path / "hbbft_tpu" / "crypto" / "tpu"
    target.mkdir(parents=True)
    (target / "fixture.py").write_text(HBT001_BAD)
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint", str(target / "fixture.py")],
        capture_output=True,
        cwd=repo,
        env=env,
    )
    assert res.returncode == 1, res.stdout + res.stderr


def test_cli_json_mode(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO}
    # A violating fixture under --json: exit 1, every stdout line is one
    # JSON object with the (rule, file, line, message) schema; status
    # chatter stays on stderr.
    target = tmp_path / "hbbft_tpu" / "crypto" / "tpu"
    target.mkdir(parents=True)
    (target / "fixture.py").write_text(HBT001_BAD)
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--json", str(target / "fixture.py")],
        capture_output=True,
        cwd=REPO,
        env=env,
        text=True,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    lines = [ln for ln in res.stdout.splitlines() if ln.strip()]
    assert lines
    for ln in lines:
        obj = json.loads(ln)
        assert set(obj) == {"rule", "file", "line", "message"}
        assert isinstance(obj["line"], int)
    assert any(json.loads(ln)["rule"] == "HBT001" for ln in lines)
    # Clean whole-repo run under --json: exit 0, empty stdout.
    ok = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--json"],
        capture_output=True,
        cwd=REPO,
        env=env,
        text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert ok.stdout.strip() == ""


def test_cli_knobs_md_matches_committed():
    env = {**os.environ, "PYTHONPATH": REPO}
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--knobs-md"],
        capture_output=True,
        cwd=REPO,
        env=env,
        text=True,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    with open(os.path.join(REPO, "docs", "KNOBS.md")) as f:
        assert res.stdout.rstrip("\n") == f.read().rstrip("\n")
