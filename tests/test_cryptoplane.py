"""Cluster crypto plane (ISSUE 12 acceptance surface).

The shared batched share-verification service
(:mod:`hbbft_tpu.cryptoplane`) behind ``LocalCluster(crypto="service")``:

* **Output identity** — the service arm commits byte-identical batches
  (``batches_sha``) to the inline arm at N=4 seed 0 on BOTH node impls
  (deferred verification is an optimization, never a semantics change —
  the standing flush_every invariant, now spanning processes).
* **Fault attribution** — a corrupt-share adversary yields the same
  per-sender fault multiset through the service as through the scalar
  path: pinned DETERMINISTICALLY on the simulated net (seeded
  TamperingAdversary, exact multiset incl. order) and live-socket with
  the chaos tier's corrupt-share strategy (attribution-set parity —
  wall-clock scheduling makes live tamper counts non-reproducible).
* **Fallback** — the service dies mid-epoch and the cluster keeps
  committing on the local scalar path (counted, no handler errors).
* Service unit behavior (cross-thread batching, dead-service fallback,
  broken-backend robustness), the NativeNodeEngine cadence/threads
  validation pins, and the crypto.* metrics + crypto.flush trace spans.

Budget on the 1-core box: every driven phase keeps the standard 45 s
cap; the default tier is ~10-30 s warm (CLAUDE.md "cryptoplane tier").
No jax/XLA involvement — safe during crypto-cache cold states.  Native
halves skip cleanly without a C++ toolchain.
"""

from __future__ import annotations

import random
import threading

import pytest

from hbbft_tpu.chaos.oracle import batch_keys, batches_sha, fault_entries
from hbbft_tpu.crypto.backend import (
    BatchedBackend,
    CryptoBackend,
    EagerBackend,
    VerifyRequest,
)
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.cryptoplane import CryptoPlaneService
from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.transport import LocalCluster

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 3 s


def _lib_or_skip():
    from hbbft_tpu import native_engine

    lib = native_engine.get_lib()
    if lib is None:
        pytest.skip("native engine unavailable (no compiler?)")
    return lib


def _impl_or_skip(impl: str) -> str:
    if impl == "native":
        _lib_or_skip()
    return impl


# ---------------------------------------------------------------------------
# service unit behavior (no sockets, no engine)
# ---------------------------------------------------------------------------


def _scalar_fixture():
    suite = ScalarSuite()
    rng = random.Random(5)
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    good = VerifyRequest.sig_share(
        pks.public_key_share(0), b"doc", sks.secret_key_share(0).sign(b"doc")
    )
    # wrong signer key: well-formed, verifies False
    bad = VerifyRequest.sig_share(
        pks.public_key_share(1), b"doc", sks.secret_key_share(0).sign(b"doc")
    )
    return suite, good, bad


def test_service_merges_cross_thread_batches():
    """Concurrent clients' requests land in ONE backend flush (the
    cross-node batching claim) and every client gets its own verdict
    slice back, bad items attributed exactly."""
    suite, good, bad = _scalar_fixture()

    class CountingBackend(CryptoBackend):
        def __init__(self):
            self.inner = BatchedBackend(suite)
            self.calls = []

        def verify_batch(self, reqs):
            self.calls.append(len(reqs))
            return self.inner.verify_batch(reqs)

    backend = CountingBackend()
    svc = CryptoPlaneService(backend, window_s=0.05).start()
    client = svc.client(EagerBackend(suite))
    out = {}
    barrier = threading.Barrier(3)

    def worker(i):
        barrier.wait()
        out[i] = client.verify_batch([good, bad, good])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(out[i] == [True, False, True] for i in range(3)), out
    # all three 3-request jobs merged into one 9-request flush (the
    # barrier releases them together, well inside the 50 ms window)
    assert max(backend.calls) == 9, backend.calls
    assert svc.metrics.counters["crypto.requests"] == 9
    sm = svc.metrics.summaries["crypto.batch_size"]
    assert sm.count == len(backend.calls)
    svc.stop()


def test_service_malformed_request_is_false_not_fatal():
    suite, good, _ = _scalar_fixture()
    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.0).start()
    client = svc.client(EagerBackend(suite))
    junk = VerifyRequest("sig_share", (object(), b"m", object()))
    assert client.verify_batch([good, junk]) == [True, False]
    assert svc.metrics.counters.get("crypto.flush_errors", 0) == 0
    svc.stop()


def test_killed_service_falls_back_immediately():
    suite, good, bad = _scalar_fixture()
    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.0).start()
    client = svc.client(EagerBackend(suite))
    assert client.verify_batch([good]) == [True]
    svc.kill()
    assert client.verify_batch([good, bad]) == [True, False]  # fallback path
    assert svc.metrics.counters["crypto.fallbacks"] == 1
    assert svc.metrics.counters["crypto.fallback_requests"] == 2


def test_broken_backend_fails_over_and_worker_survives():
    """A backend that raises must not kill the worker: the flush is
    counted as an error, its jobs fall back, and the NEXT flush (the
    backend recovered) is served by the service again."""
    suite, good, bad = _scalar_fixture()

    class Flaky(CryptoBackend):
        def __init__(self):
            self.inner = BatchedBackend(suite)
            self.fail_next = True

        def verify_batch(self, reqs):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("device wedged")
            return self.inner.verify_batch(reqs)

    svc = CryptoPlaneService(Flaky(), window_s=0.0).start()
    client = svc.client(EagerBackend(suite))
    assert client.verify_batch([good, bad]) == [True, False]
    assert svc.metrics.counters["crypto.flush_errors"] == 1
    assert svc.metrics.counters["crypto.fallbacks"] == 1
    assert client.verify_batch([good]) == [True]
    assert svc.metrics.counters["crypto.flushes"] == 1  # the recovered one
    assert svc.metrics.counters["crypto.fallbacks"] == 1  # no new fallback
    svc.stop()


def test_lazy_start_on_first_submit():
    suite, good, _ = _scalar_fixture()
    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.0)
    client = svc.client(EagerBackend(suite))
    assert client.verify_batch([good]) == [True]
    assert svc.metrics.counters["crypto.flushes"] == 1
    svc.stop()


def test_stop_is_terminal_no_lazy_resurrection():
    """stop() is terminal like kill(): later submits must fall back
    locally and must NOT spawn a fresh worker (the submit/stop race the
    lazy start could otherwise lose)."""
    suite, good, _ = _scalar_fixture()
    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.0).start()
    client = svc.client(EagerBackend(suite))
    assert client.verify_batch([good]) == [True]
    svc.stop()
    assert client.verify_batch([good]) == [True]  # via fallback
    assert svc.metrics.counters["crypto.fallbacks"] == 1
    assert svc._thread is None  # nothing resurrected
    assert svc.start()._thread is None  # start() after stop() refuses too


def test_cluster_does_not_stop_external_service():
    """A caller-supplied service outlives the cluster (its owner stops
    it) — LocalCluster.stop() only stops the service it built, and
    construction kwargs for a pre-built service are a loud error."""
    suite = ScalarSuite()
    _suite, good, _ = _scalar_fixture()
    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.0)
    c = LocalCluster(4, seed=1, crypto="service", crypto_service=svc)
    c.stop()
    assert svc.alive
    client = svc.client(EagerBackend(suite))
    assert client.verify_batch([good]) == [True]  # still serving
    assert svc.metrics.counters.get("crypto.fallbacks", 0) == 0
    svc.stop()
    with pytest.raises(ValueError, match="pre-built crypto_service"):
        LocalCluster(
            4, seed=1, crypto="service", crypto_service=svc,
            service_kwargs=dict(window_s=0.5),
        )
    # message covers both service arms since round 18
    with pytest.raises(ValueError, match="requires a service crypto arm"):
        LocalCluster(4, seed=1, crypto_service=svc)


def test_timed_out_job_is_dropped_not_flushed():
    """A client that timed out cancels its queued job: the worker must
    not pay a backend flush nobody is waiting for (on TpuBackend that
    is a wasted multi-second device dispatch per timeout)."""
    suite, good, _ = _scalar_fixture()
    release = threading.Event()

    class Slow(CryptoBackend):
        def __init__(self):
            self.inner = BatchedBackend(suite)
            self.calls = 0

        def verify_batch(self, reqs):
            self.calls += 1
            release.wait(5)
            return self.inner.verify_batch(reqs)

    backend = Slow()
    # window large enough that the second job is still QUEUED (not yet
    # collected) when its client times out and cancels it
    svc = CryptoPlaneService(backend, window_s=10.0).start()
    client = svc.client(EagerBackend(suite), timeout_s=0.05)
    assert client.verify_batch([good]) == [True]  # timeout -> fallback
    assert svc.metrics.counters["crypto.fallbacks"] == 1
    release.set()  # let any in-flight flush finish
    svc.stop()
    assert backend.calls == 0, "cancelled job still reached the backend"


# ---------------------------------------------------------------------------
# NativeNodeEngine cadence/threads validation (satellite pin)
# ---------------------------------------------------------------------------


def test_native_node_engine_cadence_and_threads_rules():
    """The round-9 hard flush_every=1 pin is now conditional: scalar
    mode keeps it (byte-identity with the Python oracle), an attached
    ext backend unlocks the deferred cadence, and threads>1 composes
    only with scalar flush_every=1 — the NativeQhbNet rules, mirrored
    with clear errors."""
    from hbbft_tpu.native_engine import NativeNodeEngine
    from hbbft_tpu.transport.cluster import build_netinfo

    _lib_or_skip()
    suite = ScalarSuite()
    ni = build_netinfo(4, 1, 0, suite, 0)
    backend = BatchedBackend(suite)
    with pytest.raises(ValueError, match="pins flush_every=1"):
        NativeNodeEngine(0, ni, flush_every=0)
    with pytest.raises(ValueError, match="pins flush_every=1"):
        NativeNodeEngine(0, ni, flush_every=5)
    with pytest.raises(ValueError, match="external-crypto flush cadence"):
        NativeNodeEngine(0, ni, backend=backend, threads=2)
    with pytest.raises(ValueError, match="threads > 1 requires flush_every=1"):
        NativeNodeEngine(0, ni, flush_every=0, threads=2)
    with pytest.raises(ValueError, match="ScalarSuite"):
        from hbbft_tpu.crypto.bls import BLSSuite

        NativeNodeEngine(0, ni, suite=BLSSuite(), backend=backend)
    # the accepted arms construct
    for kw in (
        dict(),
        dict(threads=2),
        dict(backend=backend),                 # ext, eager default
        dict(backend=backend, flush_every=0),  # ext, queue-dry deferred
        dict(backend=backend, flush_every=7),
    ):
        eng = NativeNodeEngine(0, ni, **kw)
        assert eng.ext == ("backend" in kw)
        eng.close()


# ---------------------------------------------------------------------------
# output identity: service arm == inline arm, both node impls, N=4 seed 0
# ---------------------------------------------------------------------------


def _run_cluster_arm(impl: str, crypto: str, *, seed: int = 0, target: int = 4,
                     rounds: int = 6, **cluster_kw):
    """One presubmitted deterministic run (the test_transport_native
    cross-arm recipe); returns (per-node batch keys, batches_sha,
    merged counters, cluster-level extras dict)."""
    c = LocalCluster(4, seed=seed, node_impl=impl, crypto=crypto, **cluster_kw)
    for k in range(rounds):
        for i in range(4):
            c.submit(i, Input.user(f"tx-{k}-{i}"))
    c.start()
    try:
        ok = c.wait(
            lambda cl: all(len(cl.batches(i)) >= target for i in range(4)),
            EPOCH_TIMEOUT_S,
        )
        assert ok, {i: len(c.batches(i)) for i in range(4)}
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("cluster.bad_payload", 0) == 0
        keys = {i: batch_keys(c, i, upto=target) for i in range(4)}
        sha = batches_sha(c, 0, upto=target)
        extras = {
            "summaries": dict(m.summaries),
            "timers": dict(m.timers),
            "tracks": c.trace_events(),
        }
        return keys, sha, dict(m.counters), extras
    finally:
        c.stop()


def test_service_arm_output_identical_both_impls():
    """THE acceptance pin: ``batches_sha`` is identical across all four
    (impl x crypto) arms at N=4 seed 0, and the service arms actually
    routed shares through the shared service (flushes > 0, with
    multi-request batches on the native arm's sweep cadence).

    Live-socket caveat: which proposals land in an epoch's subset is
    arrival-timing-sensitive (the cluster.py "modulo scheduling"
    contract), so under background tier load any ONE run can commit a
    different — still cluster-consistent — stream (~1/15 observed on
    the loaded 1-core box, on the UNTOUCHED python-inline arm).  A
    dissenting arm is re-run a bounded number of times: a real
    service bug (a wrong verdict) diverges deterministically and no
    retry masks it, while scheduling luck converges."""
    _lib_or_skip()
    runs = {}
    for impl in ("python", "native"):
        for crypto in ("inline", "service"):
            runs[(impl, crypto)] = _run_cluster_arm(impl, crypto)
    for _retry in range(2):
        shas = {arm: sha for arm, (_, sha, _, _) in runs.items()}
        by_sha: dict = {}
        for arm, sha in shas.items():
            by_sha.setdefault(sha, []).append(arm)
        if len(by_sha) == 1:
            break
        majority = max(by_sha.values(), key=len)
        for sha, arms in by_sha.items():
            if arms is majority:
                continue
            for impl, crypto in arms:
                runs[(impl, crypto)] = _run_cluster_arm(impl, crypto)
    shas = {arm: sha for arm, (_, sha, _, _) in runs.items()}
    assert len(set(shas.values())) == 1, shas
    ref = runs[("python", "inline")][0]
    for arm, (keys, _, _, _) in runs.items():
        assert keys == ref, f"batch divergence in arm {arm}"
    for impl in ("python", "native"):
        counters = runs[(impl, "service")][2]
        assert counters.get("crypto.flushes", 0) > 0, (impl, counters)
        assert counters.get("crypto.requests", 0) > 0, (impl, counters)
        assert counters.get("crypto.fallbacks", 0) == 0, (impl, counters)
    # the native arm's queue-dry cadence hands multi-request batches to
    # the service (per-sweep pools, not per-share trickles)
    nat = runs[("native", "service")][2]
    assert nat["crypto.requests"] >= 2 * nat["crypto.flushes"], nat


def test_service_metrics_and_flush_spans_exported():
    """Satellite: crypto.* lands in merged_metrics() (counter + timer +
    batch-size summary + queue-depth gauge reach the Prometheus dump)
    and crypto.flush.open/done milestone events ride the flight
    recorder's cryptoplane track."""
    _keys, _sha, counters, extras = _run_cluster_arm("python", "service")
    assert counters.get("crypto.flushes", 0) > 0
    assert "crypto.flush" in extras["timers"]
    assert "crypto.batch_size" in extras["summaries"]
    tracks = extras["tracks"]
    assert "cryptoplane" in tracks, sorted(tracks)
    names = [ev.name for ev in tracks["cryptoplane"]]
    assert "crypto.flush.open" in names and "crypto.flush.done" in names
    opens = [ev for ev in tracks["cryptoplane"] if ev.name == "crypto.flush.open"]
    assert all(ev.args["requests"] >= 1 for ev in opens)
    # the prometheus dump carries the whole family (grammar pinned by
    # test_obs; here we only pin the names' presence)
    c = LocalCluster(4, seed=1, crypto="service")
    try:
        c.nodes  # constructed; no need to start for an export
        svc = c.crypto_service
        svc.metrics.count("crypto.flushes")
        svc.metrics.gauge("crypto.queue_depth", 0)
        text = c.merged_metrics().prometheus_text()
        assert 'name="crypto.flushes"' in text
        assert 'name="crypto.queue_depth"' in text
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# fault attribution: corrupt shares through the service
# ---------------------------------------------------------------------------


def test_fault_multiset_parity_deterministic_sim():
    """Seeded TamperingAdversary on the simulated net: the scalar
    engine path and the ext path with the verification routed through
    a CryptoPlaneService produce EXACTLY the same batches and fault
    logs (order included) — the service changes where shares verify,
    never what gets attributed.  This is the deterministic multiset
    pin; the live-socket drill below covers the cluster runtime."""
    from hbbft_tpu import native_engine
    from hbbft_tpu.net.adversary import TamperingAdversary

    _lib_or_skip()
    suite = ScalarSuite()

    def drive(**kw):
        nat = native_engine.NativeQhbNet(
            7, seed=9, batch_size=8, num_faulty=2, session_id=b"qhb-test",
            adversary=TamperingAdversary(tamper_p=0.5), **kw,
        )
        for nid in sorted(nat.correct_ids) + sorted(nat.faulty_ids):
            nat.send_input(nid, Input.user(f"x{nid}"))
        nat.run_until(
            lambda e: all(
                len(e.nodes[i].outputs) >= 1 for i in e.correct_ids
            ),
            chunk=1,
        )
        out = (
            {
                i: [
                    (b.era, b.epoch, b.contributions)
                    for b in nat.nodes[i].outputs
                ]
                for i in nat.correct_ids
            },
            {i: nat.faults(i) for i in range(7)},
        )
        nat.close()
        return out

    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.0).start()
    try:
        base = drive()
        via_service = drive(
            suite=suite, external_crypto=True, flush_every=1,
            backend=svc.client(BatchedBackend(suite)),
        )
        assert base == via_service
        share_faults = [
            (subj, kind)
            for faults in base[1].values()
            for subj, kind in faults
            if "invalid-share" in kind
        ]
        assert share_faults, "tampering never produced a share fault"
        assert svc.metrics.counters["crypto.flushes"] > 0
        assert svc.metrics.counters.get("crypto.fallbacks", 0) == 0
    finally:
        svc.stop()


@pytest.mark.parametrize("impl", ["python", "native"])
def test_corrupt_share_attribution_live(impl):
    """Chaos-tier corrupt-share adversary against the SERVICE arm: the
    shared verification plane detects the bad shares and honest fault
    logs converge on the adversary — and nobody else — while safety
    holds.  (A corrupt share that arrives after its coin/decrypt
    instance terminated is correctly IGNORED, so whether a given live
    run logs a fault at all is a scheduling race — the inline arm's
    attribution is pinned by the chaos tier, and the exact service-vs-
    scalar multiset parity by the deterministic sim test above; this
    drill drives the service arm until a rewrite actually lands.)"""
    _impl_or_skip(impl)
    with LocalCluster(
        4, seed=29, node_impl=impl, crypto="service",
        byzantine={3: "corrupt-share"},
    ) as c:

        def honest_faults():
            return [
                (subj, kind)
                for i in (0, 1, 2)
                for subj, kind in fault_entries(c.nodes[i])
            ]

        target = 3
        c.drive_to([0, 1, 2], target, timeout_s=EPOCH_TIMEOUT_S)
        for k in range(10):
            if honest_faults():
                break
            target += 2
            c.drive_to(
                [0, 1, 2], target, timeout_s=EPOCH_TIMEOUT_S, tag=f"more{k}",
            )
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("chaos.tampered_shares", 0) > 0
        assert m.counters.get("crypto.flushes", 0) > 0
        entries = honest_faults()
        assert entries, "no rewrite landed within the drive budget"
        assert {subj for subj, _ in entries} == {3}, entries
        assert all("invalid-share" in kind for _, kind in entries), entries
        want = batch_keys(c, 0, upto=2)
        for i in (1, 2):
            assert batch_keys(c, i, upto=2) == want


# ---------------------------------------------------------------------------
# fallback drill: service dies mid-epoch, the cluster keeps committing
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["python", "native"])
def test_service_death_falls_back_to_scalar(impl):
    _impl_or_skip(impl)
    with LocalCluster(
        4, seed=3, node_impl=impl, crypto="service",
        service_kwargs=dict(timeout_s=2.0),
    ) as c:
        c.drive_to([0, 1, 2, 3], 2, timeout_s=EPOCH_TIMEOUT_S)
        pre = dict(c.merged_metrics().counters)
        assert pre.get("crypto.flushes", 0) > 0  # the service WAS serving
        c.crypto_service.kill()
        c.drive_to([0, 1, 2, 3], 4, timeout_s=EPOCH_TIMEOUT_S, tag="post")
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("crypto.fallbacks", 0) > 0
        want = batch_keys(c, 0, upto=4)
        for i in (1, 2, 3):
            assert batch_keys(c, i, upto=4) == want
