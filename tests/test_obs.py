"""Flight recorder (round 12): trace rings, exports, live scrape.

Four pinned surfaces:

* the Prometheus exposition's LINE GRAMMAR (a strict golden parse —
  scrapers are unforgiving, and metric names embed untrusted peer ids
  that must be escaped, never interpolated raw);
* the Chrome trace-event schema (every emitted event carries the
  ``ts/pid/tid/ph/name`` quintet Perfetto requires) plus the phase-span
  derivation rules (epoch bracketing of leaf milestones);
* bounded memory: a flood into a TraceBuffer retains exactly
  ``capacity`` events and counts the drops;
* the live endpoints: ``urllib`` against ``/metrics``, ``/healthz``
  and ``/trace.json`` DURING a driven N=4 cluster (both arms where a
  compiler exists).

Budget: the cluster phases keep the standard 45 s caps (typical < 5 s
on this box); no jax/XLA involvement (``make obs-smoke``).
"""

from __future__ import annotations

import json
import re
import threading
import urllib.request

import pytest

from hbbft_tpu.obs import trace as trace_mod
from hbbft_tpu.obs.export import (
    chrome_trace,
    phase_spans,
    phase_summaries,
    summarize,
)
from hbbft_tpu.obs.trace import TraceBuffer, TraceEvent
from hbbft_tpu.transport import LocalCluster
from hbbft_tpu.utils.metrics import Metrics

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 5 s


def _native_available() -> bool:
    from hbbft_tpu import native_engine

    return native_engine.get_lib() is not None


# ---------------------------------------------------------------------------
# TraceBuffer + thread-local tracer
# ---------------------------------------------------------------------------


def test_trace_buffer_bounded_under_flood():
    buf = TraceBuffer("t", capacity=512)
    for i in range(50_000):
        buf.emit("flood", i=i)
    assert len(buf) == 512
    assert buf.dropped == 50_000 - 512
    snap = buf.snapshot()
    assert len(snap) == 512
    # Oldest-first order and drop-oldest semantics: the retained window
    # is exactly the newest `capacity` events.
    assert [e.args["i"] for e in snap] == list(range(50_000 - 512, 50_000))
    # The ring never grows: the backing list is still `capacity` slots.
    assert len(buf._ring) == 512


def test_trace_buffer_extend_applies_same_bound():
    buf = TraceBuffer("t", capacity=16)
    buf.extend([TraceEvent(float(i), "x", {}) for i in range(100)])
    assert len(buf) == 16 and buf.dropped == 84
    assert buf.snapshot()[0].ts == 84.0


def test_thread_local_tracer_noop_without_install():
    trace_mod.install(None)
    trace_mod.emit("nobody.listening", x=1)  # must not raise
    trace_mod.set_ctx(era=7)  # must not raise or leak anywhere
    buf = TraceBuffer("t", capacity=8)
    trace_mod.install(buf)
    try:
        trace_mod.set_ctx(era=3, proposer=1)
        trace_mod.emit("ba.coin", round=0, value=1)
        trace_mod.emit("ba.coin", proposer=2)  # explicit overrides ctx
    finally:
        trace_mod.install(None)
    a, b = buf.snapshot()
    assert a.args == {"era": 3, "proposer": 1, "round": 0, "value": 1}
    assert b.args["proposer"] == 2 and b.args["era"] == 3


def test_tracer_is_thread_local():
    buf = TraceBuffer("t", capacity=8)
    trace_mod.install(buf)
    try:
        seen = []

        def other():
            trace_mod.emit("other.thread")  # no tracer HERE: dropped
            seen.append(True)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert seen and len(buf) == 0
    finally:
        trace_mod.install(None)


# ---------------------------------------------------------------------------
# Phase spans + chrome trace schema
# ---------------------------------------------------------------------------


def _synthetic_track():
    # One epoch: open -> rbc -> ba(coin) -> decrypt -> commit, with the
    # leaf events NOT carrying epoch args (the Python-arm bracketing).
    mk = TraceEvent
    return [
        mk(10.0, "epoch.open", {"era": 0, "epoch": 5}),
        mk(10.1, "rbc.value", {"proposer": 1}),
        mk(10.2, "rbc.deliver", {"proposer": 1}),
        mk(10.3, "ba.coin", {"proposer": 1, "round": 0, "value": 1}),
        mk(10.4, "ba.decide", {"proposer": 1, "round": 0, "value": 1}),
        mk(10.5, "decrypt.start", {"proposer": 1}),
        mk(10.7, "decrypt.done", {"proposer": 1}),
        mk(11.0, "epoch.commit", {"era": 0, "epoch": 5, "contribs": 4}),
    ]


def test_phase_spans_bracketing_and_durations():
    spans = phase_spans({"node0": _synthetic_track()})
    by_phase = {s["phase"]: s for s in spans}
    assert set(by_phase) == {"epoch", "rbc", "ba", "coin", "decrypt"}
    for s in spans:
        assert (s["era"], s["epoch"]) == (0, 5)
    assert by_phase["epoch"]["t0"] == 10.0 and by_phase["epoch"]["t1"] == 11.0
    assert by_phase["rbc"]["t1"] == 10.2  # open -> last rbc.deliver
    assert by_phase["ba"]["t0"] == 10.3 and by_phase["ba"]["t1"] == 10.4
    assert abs(
        by_phase["decrypt"]["t1"] - by_phase["decrypt"]["t0"] - 0.2
    ) < 1e-9

    sums = phase_summaries({"node0": _synthetic_track()})
    quant, count, total = sums["epoch"]
    assert count == 1 and abs(total - 1.0) < 1e-9 and quant[0.5] == total


def test_unbracketed_leaf_events_are_skipped():
    # Leaf milestones before any epoch.open (ring overflow ate it) must
    # not crash or fabricate a span.
    evs = [TraceEvent(1.0, "ba.coin", {"proposer": 0})]
    assert phase_spans({"n": evs}) == []


def test_decrypt_span_requires_done():
    # decrypt.start alone (node killed mid-epoch / done lost to ring
    # overflow) must NOT fabricate a 0 s decrypt span — zeros would
    # drag the phase.decrypt quantiles down (review finding).
    evs = [
        TraceEvent(1.0, "epoch.open", {"era": 0, "epoch": 0}),
        TraceEvent(1.1, "decrypt.start", {"proposer": 2}),
    ]
    assert [s["phase"] for s in phase_spans({"n": evs})] == []


def test_epoch_events_drop_stale_proposer_ctx():
    # A Subset message sets ctx proposer; the next epoch-level emit must
    # not inherit it (schema parity with the native arm's records).
    buf = TraceBuffer("t", capacity=8)
    trace_mod.install(buf)
    try:
        trace_mod.set_ctx(era=0, proposer=3)
        trace_mod.clear_ctx("proposer")
        trace_mod.emit("epoch.commit", epoch=1, contribs=4)
    finally:
        trace_mod.install(None)
    (ev,) = buf.snapshot()
    assert "proposer" not in ev.args and ev.args["era"] == 0


def test_chrome_trace_schema_and_roundtrip():
    tracks = {"node0": _synthetic_track(), "cluster": [
        TraceEvent(10.6, "chaos.kill", {"node": 3}),
    ]}
    doc = chrome_trace(tracks, pids={"node0": 0})
    body = json.loads(json.dumps(doc))  # JSON-serializable end to end
    events = body["traceEvents"]
    assert events, "no events emitted"
    for ev in events:
        for key in ("ts", "pid", "tid", "ph", "name"):
            assert key in ev, f"event missing {key}: {ev}"
    # one process per track, metadata names present
    names = {
        (e["pid"], e["args"]["name"])
        for e in events
        if e["name"] == "process_name"
    }
    assert names == {(0, "node0"), (1, "cluster")}
    # phase spans became complete events with durations
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"].split()[0] for e in xs} >= {"epoch", "rbc", "ba"}
    assert all(e["dur"] >= 1 for e in xs)
    # instants reference the relative clock (µs from the earliest event)
    i0 = min(e["ts"] for e in events if e["ph"] == "i")
    assert i0 == 0.0


def test_summarize_quantiles():
    quant, count, total = summarize([3.0, 1.0, 2.0, 4.0])
    assert count == 4 and total == 10.0
    assert quant[0.5] == 3.0 and quant[0.99] == 4.0
    assert summarize([]) is None


# ---------------------------------------------------------------------------
# Prometheus exposition: strict golden parse
# ---------------------------------------------------------------------------

_COMMENT_RE = re.compile(
    r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* \S.*$"
)
_SAMPLE_RE = re.compile(
    r"^(?P<metric>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*\})?'
    r" (-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[+-]?Inf|NaN)$"
)


def test_prometheus_exposition_golden_parse():
    m = Metrics()
    m.count("transport.frames", 12)
    m.count('weird"name\\with\nall three', 1)  # escaping surface
    m.gauge("transport.0->1.queue_bytes", 123456789012.0)
    with m.timer("flush"):
        pass
    m.summary("epoch.latency", {0.5: 0.01, 0.99: 0.2}, count=10, total=0.5)
    text = m.prometheus_text()
    assert text.endswith("\n")

    seen_types: dict = {}
    for line in text.splitlines():
        assert line, "blank line in exposition"
        if line.startswith("#"):
            assert _COMMENT_RE.match(line), f"bad comment line: {line!r}"
            kind, family = line.split()[1:3]
            if kind == "TYPE":
                # HELP must precede TYPE for every family
                assert seen_types.get(family) == "help", line
                seen_types[family] = "type"
            else:
                assert family not in seen_types, f"duplicate HELP: {line!r}"
                seen_types[family] = "help"
        else:
            mt = _SAMPLE_RE.match(line)
            assert mt, f"bad sample line: {line!r}"
            metric = mt.group("metric")
            # every sample belongs to a declared family (summary
            # children share the family prefix)
            assert any(
                metric == fam or metric.startswith(fam + "_")
                for fam in seen_types
            ), f"sample before its TYPE: {line!r}"

    # round-12 satellite: HELP lines + the per-timer max gauge family
    assert "# HELP hbbft_count " in text
    assert "# TYPE hbbft_timer_seconds_max gauge" in text
    assert 'hbbft_timer_seconds_max{name="flush"} ' in text
    # escaping: the raw control bytes must never appear unescaped
    assert 'weird\\"name\\\\with\\nall three' in text


def test_era_change_rekeys_epoch_open_ctx():
    """An era change rebuilds HoneyBadger INSIDE batch processing; the
    new era's epoch-0 open must carry the NEW era (a stale thread-local
    ctx would corrupt both eras' phase spans — review finding)."""
    from hbbft_tpu.crypto.pool import VerifyPool
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.transport.cluster import build_netinfo

    ni = build_netinfo(4, 1, 0, ScalarSuite(), 0)
    buf = TraceBuffer("t", capacity=64)
    trace_mod.install(buf)
    try:
        dhb = DynamicHoneyBadger(ni, VerifyPool(), session_id=b"obs-era")
        ev = buf.snapshot()[-1]
        assert ev.name == "epoch.open"
        assert ev.args["era"] == 0 and ev.args["epoch"] == 0
        # era advance rebuilds the inner HB via _make_hb (the same call
        # _restart_era makes); its epoch.open must carry era=3
        dhb._era = 3
        dhb._hb = dhb._make_hb()
        ev = buf.snapshot()[-1]
        assert ev.name == "epoch.open"
        assert ev.args["era"] == 3 and ev.args["epoch"] == 0
    finally:
        trace_mod.install(None)


# ---------------------------------------------------------------------------
# Chaos events land on the cluster track
# ---------------------------------------------------------------------------


def test_chaos_runner_emits_cluster_track_events():
    from hbbft_tpu.chaos.scheduler import ChaosEvent, ChaosRunner

    class StubCluster:
        def __init__(self):
            self.trace = TraceBuffer("cluster", capacity=64)
            self.calls = []

        def kill(self, n):
            self.calls.append(("kill", n))

        def restart(self, n):
            self.calls.append(("restart", n))

    c = StubCluster()
    runner = ChaosRunner(
        c, [ChaosEvent(0.0, "kill", 3), ChaosEvent(0.0, "restart", 3)]
    )
    runner.start()
    runner.drain()
    assert c.calls == [("kill", 3), ("restart", 3)]
    assert [e.name for e in c.trace.snapshot()] == [
        "chaos.kill",
        "chaos.restart",
    ]
    assert c.trace.snapshot()[0].args["node"] == 3


# ---------------------------------------------------------------------------
# Live cluster: rings fill, endpoints answer mid-run, both arms trace
# ---------------------------------------------------------------------------


def _get(url: str) -> bytes:
    return urllib.request.urlopen(url, timeout=10).read()


def _drive(cluster, target: int) -> None:
    cluster.drive_to(
        range(cluster.n), target, timeout_s=EPOCH_TIMEOUT_S, tag="obs"
    )


def test_live_scrape_python_cluster():
    c = LocalCluster(4, seed=0)
    with c:
        port = c.serve_obs().port
        base = f"http://127.0.0.1:{port}"
        # scrape MID-RUN: before any epoch commits...
        health0 = json.loads(_get(base + "/healthz"))
        assert health0["ok"] and len(health0["nodes"]) == 4
        _drive(c, 2)
        # ...and while the cluster is still live after progress
        text = _get(base + "/metrics").decode()
        for line in text.splitlines():
            assert line.startswith("#") and _COMMENT_RE.match(line) or (
                _SAMPLE_RE.match(line)
            ), f"unparseable scrape line: {line!r}"
        assert 'hbbft_summary{name="epoch.latency"' in text
        assert 'name="phase.ba"' in text  # derived phase breakdown
        health = json.loads(_get(base + "/healthz"))
        assert health["ok"]
        assert all(n["alive"] for n in health["nodes"].values())
        assert health["nodes"]["0"]["last_committed"] is not None
        doc = json.loads(_get(base + "/trace.json"))
        pids = {
            e["args"]["name"]
            for e in doc["traceEvents"]
            if e["name"] == "process_name"
        }
        assert {"node0", "node1", "node2", "node3"} <= pids
        for ev in doc["traceEvents"]:
            for key in ("ts", "pid", "tid", "ph", "name"):
                assert key in ev
        # 404 surface
        with pytest.raises(urllib.error.HTTPError):
            _get(base + "/nope")
    # stop() tears the server down
    with pytest.raises(Exception):
        _get(base + "/healthz")


def test_trace_timeline_python_cluster_content():
    c = LocalCluster(4, seed=0)
    with c:
        _drive(c, 2)
    evs = c.trace_events()
    assert set(evs) >= {"node0", "node1", "node2", "node3"}
    for track in ("node0", "node1", "node2", "node3"):
        names = {e.name for e in evs[track]}
        # the full taxonomy appears on every node's timeline
        assert {
            "epoch.open",
            "epoch.commit",
            "rbc.value",
            "rbc.deliver",
            "ba.coin",
            "ba.decide",
            "decrypt.start",
            "transport.connect",
        } <= names, f"{track} missing milestones: {names}"
        opens = [e for e in evs[track] if e.name == "epoch.open"]
        assert any(e.args.get("epoch") == 0 for e in opens)
    # derived spans cover committed epochs on every node
    spans = phase_spans(evs)
    epochs_spanned = {
        (s["track"], s["epoch"]) for s in spans if s["phase"] == "epoch"
    }
    for track in ("node0", "node1", "node2", "node3"):
        assert (track, 0) in epochs_spanned
    # merged metrics carry the phase breakdown + epoch latency summary
    m = c.merged_metrics()
    assert m.summaries["epoch.latency"].count >= 8  # 4 nodes x >= 2 epochs
    assert "phase.rbc" in m.summaries and "phase.ba" in m.summaries


@pytest.mark.skipif(
    not _native_available(), reason="native engine unavailable"
)
def test_native_and_mixed_cluster_trace():
    c = LocalCluster(
        4, seed=0, node_impl={0: "python", 1: "native", 2: "python", 3: "native"}
    )
    with c:
        _drive(c, 2)
    evs = c.trace_events()
    for track in ("node1", "node3"):  # the native arms
        names = {e.name for e in evs[track]}
        assert {
            "epoch.open",
            "epoch.commit",
            "rbc.deliver",
            "ba.coin",
            "decrypt.done",
        } <= names, f"native {track} missing milestones: {names}"
        commits = [e for e in evs[track] if e.name == "epoch.commit"]
        # engine events carry explicit era/epoch (no bracketing needed)
        assert all(
            "era" in e.args and "epoch" in e.args for e in commits
        )
    m = c.merged_metrics()
    # native cycle splits are exported as counters (sum across nodes)
    assert m.counters.get("engine.cyc.COIN", 0) > 0
    assert m.counters.get("engine.msgs.BVAL", 0) > 0
    # both arms appear in one chrome trace with their own pids
    doc = c.chrome_trace()
    pids = {
        e["args"]["name"]: e["pid"]
        for e in doc["traceEvents"]
        if e["name"] == "process_name"
    }
    assert {"node0", "node1", "node2", "node3"} <= set(pids)
    assert pids["node1"] == 1  # pid pinned to the node id


@pytest.mark.skipif(
    not _native_available(), reason="native engine unavailable"
)
def test_engine_trace_ring_bounded():
    # The C ring must drop-oldest with an honest count, like the Python
    # ring (flood it by driving many epochs through a tiny capacity).
    from hbbft_tpu.native_engine import NativeQhbNet
    from hbbft_tpu.protocols.queueing_honey_badger import Input

    net = NativeQhbNet(4, seed=0)
    net.enable_trace(64)
    for i in range(4):
        net.send_input(i, Input.user(f"t{i}"))
    net.run(20_000)
    evs = net.drain_trace()
    assert len(evs) <= 64
    assert net.trace_dropped > 0
    assert int(net.lib.hbe_trace_pending(net.handle)) == 0
    # timestamps are wall-clock seconds, monotone within the ring
    ts = [e.ts for e in evs]
    assert ts == sorted(ts)
