"""Substrate unit tests: Target routing, Step algebra, NetworkInfo sizes."""

from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import Step, Target


def test_target_expansion():
    ids = [0, 1, 2, 3]
    assert Target.all().recipients(ids, 0) == [1, 2, 3]
    assert Target.all_except([2]).recipients(ids, 0) == [1, 3]
    assert sorted(Target.nodes([1, 3]).recipients(ids, 3)) == [1]
    assert Target.node(2).recipients(ids, 0) == [2]


def test_step_merge_and_map():
    a = Step().with_output("x").broadcast("m1")
    b = Step().send(3, "m2")
    b.fault(7, "some-kind")
    a.extend(b)
    assert a.output == ["x"]
    assert [m.message for m in a.messages] == ["m1", "m2"]
    assert len(a.fault_log) == 1

    wrapped = a.map_messages(lambda m: ("wrap", m))
    assert [m.message for m in wrapped.messages] == [("wrap", "m1"), ("wrap", "m2")]
    assert wrapped.output == ["x"]
    assert len(wrapped.fault_log) == 1
    # Targets preserved under wrapping.
    assert wrapped.messages[0].target == Target.all()
    assert wrapped.messages[1].target == Target.node(3)


def test_network_info_sizes():
    ni = NetworkInfo(
        our_id=2, val_ids=range(10), public_key_set=None, secret_key_share=object()
    )
    assert ni.num_nodes == 10
    assert ni.num_faulty == 3
    assert ni.num_correct == 7
    assert ni.index(5) == 5
    assert ni.is_validator()
    observer = NetworkInfo(our_id="obs", val_ids=range(4), public_key_set=None)
    assert not observer.is_validator()
    assert observer.num_faulty == 1
    # Listed in the validator set but share-less (JoinPlan joiner whose
    # DKG predates it): acts as observer, but peers still count it.
    joiner = NetworkInfo(our_id=1, val_ids=range(4), public_key_set=None)
    assert not joiner.is_validator()
    assert joiner.is_node_validator(1)
