"""ThresholdDecrypt over the VirtualNet.

Reference analog: decryption paths of upstream ``tests/honey_badger.rs``
plus ``src/threshold_decrypt.rs`` unit behavior.
"""

import random

from hbbft_tpu.crypto.keys import Ciphertext
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.net import NetBuilder, ReorderingAdversary
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecrypt

PLAINTEXT = b"batch contribution: txns 17, 42"


def test_all_nodes_decrypt():
    net = (
        NetBuilder(7, seed=3)
        .protocol(lambda ni, sink, rng: ThresholdDecrypt(ni, sink))
        .adversary(ReorderingAdversary())
        .build()
    )
    pk = net.node(0).netinfo.public_key_set.public_key()
    ct = pk.encrypt(PLAINTEXT, random.Random(99))
    net.broadcast_input(lambda nid: ct)
    net.run_to_termination()
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [PLAINTEXT]
    assert net.correct_faults() == []


def test_invalid_ciphertext_flagged():
    net = (
        NetBuilder(4, seed=5)
        .protocol(lambda ni, sink, rng: ThresholdDecrypt(ni, sink))
        .build()
    )
    suite = ScalarSuite()
    pk = net.node(0).netinfo.public_key_set.public_key()
    good = pk.encrypt(PLAINTEXT, random.Random(1))
    # Tamper with W so the validity pairing check fails.
    bad = Ciphertext(good.u, good.v, good.w + suite.g2_generator(), suite)
    net.send_input(0, bad)
    net.crank_until(lambda n: n.node(0).protocol.terminated, max_cranks=1000)
    assert net.node(0).protocol.ciphertext_invalid
    assert net.node(0).outputs == []
