"""Subset (ACS) tests.

Reference analog: upstream ``tests/subset.rs``: all correct nodes output
the identical set of contributions, containing at least N - f proposals,
including every correct proposer that got in.
"""

import pytest

from hbbft_tpu.net import NetBuilder, NullAdversary, RandomAdversary, ReorderingAdversary
from hbbft_tpu.protocols.subset import Subset, SubsetOutput


def run_subset(n=4, seed=0, adversary=None, inputs=None):
    b = NetBuilder(n, seed=seed).protocol(
        lambda ni, sink, rng: Subset(ni, b"acs-0", sink)
    )
    if adversary is not None:
        b = b.adversary(adversary)
    net = b.build()
    inputs = inputs or {nid: f"contrib-{nid}".encode() for nid in net.correct_ids}
    for nid, v in inputs.items():
        net.send_input(nid, v)
    net.run_to_termination(max_cranks=500_000)
    results = {}
    for nid in net.correct_ids:
        contribs = {
            o.proposer: o.value
            for o in net.node(nid).outputs
            if o.kind == "contribution"
        }
        assert net.node(nid).outputs[-1] == SubsetOutput.done()
        results[nid] = contribs
    return net, results


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize(
    "adversary_cls", [NullAdversary, ReorderingAdversary, RandomAdversary]
)
def test_all_agree_on_subset(seed, adversary_cls):
    net, results = run_subset(n=4, seed=seed, adversary=adversary_cls())
    first = next(iter(results.values()))
    assert all(r == first for r in results.values()), results
    assert len(first) >= net.node(0).netinfo.num_correct
    for pid, value in first.items():
        assert value == f"contrib-{pid}".encode()
    assert net.correct_faults() == []


def test_seven_nodes_with_silent_faulty():
    net, results = run_subset(n=7, seed=11)
    first = next(iter(results.values()))
    assert all(r == first for r in results.values())
    # The two crash-faulty nodes never proposed; at least N - f accepted.
    assert len(first) >= 5
    assert net.correct_faults() == []


def test_single_node_subset():
    net, results = run_subset(n=1, seed=0)
    assert results[0] == {0: b"contrib-0"}
