"""Byzantine chaos plane (ISSUE 7 acceptance surface).

Live-socket adversary nodes over the untouched transport: every
strategy in the catalog (crash-stop, equivocate, corrupt-share,
stale-replay, flood) on BOTH ``node_impl`` arms at N=4 (f=1), a mixed
three-adversary N=10 (f=3) cluster, a composed chaos schedule
(Byzantine + WAN shape + kill/restart + partition/heal), traffic-plane
exactly-once under an adversary, and the transport's misbehavior/ban
plane (escalating reconnect bans priced deterministically, peer.*
gauges, the >=12x corrupt-frame hammer).

Budget on the 1-core box: every driven phase keeps the standard 45 s
cap; the whole default tier is ~40-60 s warm (CLAUDE.md "chaos tier").
No jax/XLA involvement — safe during crypto-cache cold states.  Native
halves skip cleanly without a C++ toolchain.
"""

from __future__ import annotations

import socket
import time

import pytest

from hbbft_tpu.chaos import (
    ChaosOracle,
    ChaosRunner,
    CrashStop,
    build_schedule,
    tamper_payload,
)
from hbbft_tpu.chaos.oracle import (
    batch_keys,
    batches_sha,
    fault_entries,
    stream_txns,
)
from hbbft_tpu.chaos.strategies import EQUIVOCABLE_KINDS, SHARE_KINDS
from hbbft_tpu.traffic import ClientFleet, TrafficDriver
from hbbft_tpu.transport import (
    KIND_MSG,
    FaultInjector,
    LocalCluster,
    encode_frame,
    encode_hello,
    wan_profile,
)
from hbbft_tpu.transport.transport import ban_duration
from hbbft_tpu.utils import serde

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 2 s

STRATEGY_NAMES = [
    "crash-stop", "equivocate", "corrupt-share", "stale-replay", "flood",
]


def _lib_or_skip():
    from hbbft_tpu import native_engine

    lib = native_engine.get_lib()
    if lib is None:
        pytest.skip("native engine unavailable (no compiler?)")
    return lib


# ---------------------------------------------------------------------------
# satellite: construction-time BFT bound + fault-budget validation
# ---------------------------------------------------------------------------


def test_localcluster_validates_bft_bound():
    """n >= 3*num_faulty + 1 is a constructor-time ValueError (a real
    error, not an assert: -O must not turn the misconfiguration into a
    silent downstream stall)."""
    with pytest.raises(ValueError, match="BFT bound"):
        LocalCluster(4, num_faulty=2)
    with pytest.raises(ValueError, match="BFT bound"):
        LocalCluster(6, num_faulty=2)  # needs 7
    with pytest.raises(ValueError, match="BFT bound"):
        LocalCluster(3, num_faulty=-1)
    # exactly at the bound is fine (never started: no sockets driven)
    LocalCluster(7, num_faulty=2)


def test_localcluster_validates_byzantine_budget():
    with pytest.raises(ValueError, match="fault budget"):
        LocalCluster(4, byzantine={2: "flood", 3: "flood"})  # f=1
    with pytest.raises(ValueError, match="outside"):
        LocalCluster(4, byzantine={9: "flood"})
    with pytest.raises(ValueError, match="unknown Byzantine strategy"):
        with LocalCluster(4, byzantine={3: "no-such-strategy"}):
            pass


# ---------------------------------------------------------------------------
# misbehavior accounting + escalating reconnect bans
# ---------------------------------------------------------------------------


def test_ban_escalation_schedule_is_deterministic():
    """The ban schedule is a pure function of the strike count — no
    jitter, no rng: seed-determinism of the escalation by construction."""
    assert [ban_duration(k, 0.25, 2.0) for k in range(5)] == [
        0.25, 0.5, 1.0, 2.0, 2.0,
    ]
    assert ban_duration(0, 0.1, 0.4) == pytest.approx(0.1)
    assert ban_duration(10, 0.1, 0.4) == pytest.approx(0.4)


def test_corrupt_frame_ban_hammer_lossless():
    """Satellite flake-hammer (>=12x): a peer identity that corrupts a
    frame per reconnect gets charged a misbehavior strike each time and
    banned on a deterministic escalation (bans == strikes // threshold),
    while the REAL peer behind that identity stays lossless — the
    corrupt-frame -> drop -> ACK-resume loop survives repetition and
    is no longer free."""
    with LocalCluster(
        4, seed=21, transport_kwargs=dict(ban_base_s=0.1, ban_cap_s=0.4)
    ) as c:
        c.drive_to([0, 1, 2, 3], 1, timeout_s=EPOCH_TIMEOUT_S)
        addr = c.addr_map[0]
        cid = c.cluster_id
        t = c.nodes[0].transport

        def totals():
            st = t.peer_stats[2]
            return (st.misbehavior, st.ban_rejects)

        for k in range(12):
            before = totals()
            frame = bytearray(encode_frame(KIND_MSG, b"hammer-%d" % k))
            frame[9] ^= 0x10  # body bit flip: CRC fails at the decoder
            with socket.create_connection(addr, timeout=5) as s:
                s.sendall(encode_hello(2, cid) + bytes(frame))
                s.settimeout(5)
                try:
                    while s.recv(64):
                        pass
                except OSError:
                    pass
            # each attempt is accounted as a strike (HELLO accepted,
            # violation charged) or a ban reject (HELLO refused)
            assert c.wait(lambda cl, b=before: totals() != b, 10), (k, before)
        st = t.peer_stats[2]
        assert st.misbehavior >= 3          # enough strikes to ban
        assert st.bans == st.misbehavior // 3   # deterministic escalation
        assert st.ban_rejects > 0           # the loop was actually priced
        # losslessness: the REAL node 2 (same identity the attacker
        # spoofed and got banned) catches up via dial-backoff + resume
        c.drive_to(
            [0, 1, 2, 3], len(c.batches(0)) + 2,
            timeout_s=EPOCH_TIMEOUT_S, tag="after",
        )
        want = batch_keys(c, 0, upto=3)
        for i in (1, 2, 3):
            assert batch_keys(c, i, upto=3) == want
        m = c.merged_metrics()
        assert m.counters.get("transport.peer_misbehavior", 0) >= 3
        assert m.counters.get("transport.peer_bans", 0) >= 1
        assert m.counters.get("transport.ban_rejects", 0) >= 1
        assert m.counters.get("cluster.handler_errors", 0) == 0


def test_peer_misbehavior_gauges_in_prometheus_dump():
    """Satellite: the per-peer misbehavior counters ride the same
    Prometheus dump as the transport and faults.* gauges."""
    inj = FaultInjector(seed=1)
    with LocalCluster(4, seed=27, injector=inj) as c:
        c.drive_to([0, 1, 2, 3], 1, timeout_s=EPOCH_TIMEOUT_S)
        # one identified violation at node 0, charged to peer 2
        with socket.create_connection(c.addr_map[0], timeout=5) as s:
            bad = bytearray(encode_frame(KIND_MSG, b"x"))
            bad[9] ^= 1
            s.sendall(encode_hello(2, c.cluster_id) + bytes(bad))
            s.settimeout(5)
            try:
                while s.recv(64):
                    pass
            except OSError:
                pass
        assert c.wait(
            lambda cl: cl.nodes[0].transport.peer_stats[2].misbehavior >= 1,
            10,
        )
        text = c.merged_metrics().prometheus_text()
        assert 'hbbft_gauge{name="peer.0<-2.misbehavior"} 1' in text
        assert 'name="peer.0<-2.bans"' in text
        assert 'name="peer.0<-2.ban_rejects"' in text
        assert 'name="faults.dropped"' in text  # alongside round-10 gauges


# ---------------------------------------------------------------------------
# Byzantine strategy arms: every strategy, both node impls, N=4 f=1
# ---------------------------------------------------------------------------

#: per-strategy activity counter the run must have moved (a drill that
#: never fired its behavior is vacuous)
_ACTIVITY = {
    "crash-stop": "chaos.crash_stopped",
    "equivocate": "chaos.equivocated",
    "corrupt-share": "chaos.tampered_shares",
    "stale-replay": "chaos.replayed",
    "flood": "chaos.garbage_payloads",
}


def _run_byzantine(impl: str, name: str, seed: int = 29):
    spec = (lambda: CrashStop(after_s=0.3)) if name == "crash-stop" else name
    with LocalCluster(4, seed=seed, node_impl=impl, byzantine={3: spec}) as c:
        o = ChaosOracle(c)
        o.assert_progress(extra=2, timeout_s=EPOCH_TIMEOUT_S)
        if name == "crash-stop":
            # drive past the crash deadline, then require further
            # commits from the honest trio alone
            time.sleep(0.4)
            o.assert_progress(extra=2, timeout_s=EPOCH_TIMEOUT_S, tag="post")
        k = o.assert_safety()
        named = o.assert_attribution()
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get(_ACTIVITY[name], 0) > 0, name
        if name == "corrupt-share":
            # the share plane detected AND attributed the adversary
            assert named > 0
            kinds = {
                kind
                for i in o.honest_ids
                for _s, kind in fault_entries(c.nodes[i])
            }
            assert any("invalid-share" in kd for kd in kinds), kinds
        if name == "flood":
            assert m.counters.get("cluster.bad_payload", 0) > 0
        return k


def test_byzantine_strategies_python_arm():
    """Every strategy against Python nodes: honest trio commits
    byte-identical batches, faults name only the adversary."""
    for name in STRATEGY_NAMES:
        assert _run_byzantine("python", name) >= 2, name


def test_byzantine_strategies_native_arm():
    """Every strategy against native-engine nodes (corrupt-share runs
    through the engine tamper hooks)."""
    _lib_or_skip()
    for name in STRATEGY_NAMES:
        assert _run_byzantine("native", name) >= 2, name


# ---------------------------------------------------------------------------
# N=10, f=3: three different adversaries at once, both arms
# ---------------------------------------------------------------------------


def _run_mixed_n10(impl: str):
    byz = {7: "corrupt-share", 8: "equivocate", 9: "flood"}
    with LocalCluster(10, seed=41, node_impl=impl, byzantine=byz) as c:
        o = ChaosOracle(c)
        o.assert_progress(extra=2, timeout_s=EPOCH_TIMEOUT_S)
        assert o.assert_safety() >= 2
        assert o.assert_attribution() > 0  # the adversaries were named
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        for name in ("corrupt-share", "equivocate", "flood"):
            assert m.counters.get(_ACTIVITY[name], 0) > 0, name
        return batches_sha(c, 0, upto=2)


def test_mixed_byzantine_n10_f3_python():
    assert _run_mixed_n10("python")


def test_mixed_byzantine_n10_f3_native():
    _lib_or_skip()
    assert _run_mixed_n10("native")


# ---------------------------------------------------------------------------
# composed chaos: Byzantine + WAN shape + kill/restart + partition/heal
# ---------------------------------------------------------------------------


def test_chaos_schedule_is_seed_deterministic():
    a = build_schedule(5, [3], 3.0, outage=True)
    b = build_schedule(5, [3], 3.0, outage=True)
    assert a == b
    assert build_schedule(6, [3], 3.0, outage=True) != a
    kinds = [e.kind for e in a]
    assert kinds.index("kill") < kinds.index("restart")
    assert kinds.index("partition") < kinds.index("heal")
    assert all(e.node == 3 for e in a)  # disruption targets stay Byzantine
    assert all(0.0 <= e.at_s <= 3.0 for e in a)


def _run_composed(impl: str):
    inj = FaultInjector(seed=9, default=wan_profile("wan", scale=0.2))
    c = LocalCluster(
        4, seed=53, node_impl=impl, byzantine={3: "corrupt-share"},
        injector=inj,
    )
    sched = build_schedule(seed=7, byzantine_ids=[3], duration_s=3.0)
    runner = ChaosRunner(c, sched, injector=inj)
    with c:
        o = ChaosOracle(c)
        runner.start()
        while runner.pump():  # keep committing THROUGH the event window
            o.assert_progress(
                extra=1, timeout_s=EPOCH_TIMEOUT_S, tick=runner.pump,
                tag="chaos",
            )
        runner.drain()
        o.assert_progress(extra=2, timeout_s=EPOCH_TIMEOUT_S, tag="post")
        assert o.assert_safety() >= 3
        o.assert_attribution()
        fired = {e.kind for e in runner.fired}
        assert fired >= {"kill", "restart", "partition", "heal"}
        assert inj.stats.shaped > 0       # the WAN shape was live
        assert inj.stats.partitioned > 0  # the partition window bit
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0


def test_composed_chaos_schedule_python():
    _run_composed("python")


def test_composed_chaos_schedule_native():
    _lib_or_skip()
    _run_composed("native")


# ---------------------------------------------------------------------------
# traffic plane under an adversary: exactly-once end to end
# ---------------------------------------------------------------------------


def test_traffic_exactly_once_with_byzantine_node():
    """Open-loop clients homed on the honest trio while node 3 corrupts
    its shares: every admitted transaction commits exactly once on
    every honest node, and the latency clock closes for all of them."""
    fleet = ClientFleet(6, 4.0, seed=5)
    with LocalCluster(4, seed=59, byzantine={3: "corrupt-share"}) as c:
        d = TrafficDriver(c, fleet, assign=lambda cid: cid % 3)
        res = d.run_open_loop(1.5, drain_timeout_s=EPOCH_TIMEOUT_S)
        assert res["outstanding"] == 0, res
        assert res["committed"] == res["admitted"] > 0
        o = ChaosOracle(c, driver=d)
        expect = {
            tid
            for _, _, tid, _ in ClientFleet(6, 4.0, seed=5).take(
                res["admitted"]
            )
        }
        assert c.wait(
            lambda cl: all(
                expect <= o.committed_ids(i) for i in o.honest_ids
            ),
            EPOCH_TIMEOUT_S,
        )
        assert o.assert_exactly_once() == res["committed"]
        for i in o.honest_ids:
            assert {t.split("#", 1)[0] for t in stream_txns(c, i)} == expect
        o.assert_safety()
        o.assert_attribution()


# ---------------------------------------------------------------------------
# strategy unit seams: tamper_payload variants are valid wire traffic
# ---------------------------------------------------------------------------


def test_tamper_payload_variants_decode_and_differ():
    """An equivocation/corrupt-share variant must re-encode as VALID
    wire traffic (well-formed, wrong contents) and differ from the
    original; non-SqMessage payloads and untargeted flavors map to
    None.  (No new serde tags anywhere: the chaos plane only emits
    existing registered wire structs or deliberately-invalid bytes,
    so the HBT005 wire-tag classification is unchanged.)"""
    import random as _random

    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.protocols.sender_queue import SqMessage

    suite = ScalarSuite()
    # harvest live traffic from a tiny run
    corpus = []
    with LocalCluster(4, seed=3) as c:
        node = c.nodes[1]
        orig = node.transport.send
        orig_many = node.transport.send_many

        def send(dest, payload, _o=orig):
            corpus.append(payload)
            return _o(dest, payload)

        def send_many(items, _o=orig_many):
            corpus.extend(p for _, p in items)
            return _o(items)

        node.transport.send = send
        node.transport.send_many = send_many
        c.drive_to([0, 1, 2, 3], 1, timeout_s=EPOCH_TIMEOUT_S)
    rng = _random.Random(17)
    changed = 0
    for payload in sorted(set(corpus)):
        v = tamper_payload(
            payload, rng, suite, EQUIVOCABLE_KINDS | SHARE_KINDS
        )
        if v is None:
            continue
        changed += 1
        assert v != payload
        m = serde.try_loads(v, suite=suite)
        assert isinstance(m, SqMessage)  # valid wire traffic
    assert changed > 5  # a real epoch carries plenty of targeted flavors
    assert tamper_payload(serde.dumps(7), rng, suite, SHARE_KINDS) is None
    # epoch announces carry no targeted leaves -> untouched
    ann = serde.dumps(SqMessage.epoch_started((0, 1)))
    assert tamper_payload(ann, rng, suite, EQUIVOCABLE_KINDS) is None
