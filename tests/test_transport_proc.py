"""Process-per-node cluster runtime (round 14): ``native_proc``.

The first tier where every node of a cluster is its OWN OS process:
:class:`~hbbft_tpu.transport.proc_cluster.ProcCluster` spawns one
``cluster_worker`` interpreter per node (ephemeral port-0 bind + ready-
line handshake — no fixed-port flakes), the workers dial each other
directly, and the parent only reads JSON lines.  Pinned here:

* N=4 ``native_proc`` presubmit ``batches_sha`` identical across all
  four worker processes AND equal to the thread-mode native arm and
  the Python oracle arm at the same seed — cross-PROCESS byte-identity
  asserted from summaries alone, no scraping;
* the kill/restart drill with a REAL process death (SIGKILL): the
  surviving three keep committing byte-identically and gaplessly, the
  reborn worker (fresh keys re-derived from ``(n, f, seed)``) rejoins
  on its old port and commits again — the ACK/resume layer is lossless
  for survivors across a process death;
* per-worker obs: ``/metrics`` + ``/healthz`` scraped live from a
  worker process, and the per-worker Chrome trace files merge into one
  cluster trace on the shared wall clock (distinct pids, both tracks).

Budget: each test spawns 4 interpreters (~1 s ready on this box) and
drives single-digit-second phases under the standard 45 s caps; the
whole file is ~15-30 s warm.  Skips cleanly without a C++ toolchain
(the native arms).
"""

from __future__ import annotations

import hashlib
import json

import pytest

from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.transport import LocalCluster
from hbbft_tpu.transport.proc_cluster import ProcCluster
from hbbft_tpu.utils import serde

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 5 s


def _lib_or_skip():
    from hbbft_tpu import native_engine

    lib = native_engine.get_lib()
    if lib is None:
        pytest.skip("native engine unavailable (no compiler?)")
    return lib


def _thread_arm_sha(impl: str, seed: int, epochs: int) -> str:
    """config6's presubmit digest from a thread-mode LocalCluster.

    Retries once if an epoch in the digest window came up short of full
    participation: a proposer's RBC missing one epoch's BA cut under a
    scheduling outlier yields an (agreement-safe, intra-run identical)
    n-1 subset whose bytes differ from the full-participation history —
    the known cross-RUN flake class of presubmit comparisons, not a
    protocol divergence.
    """
    for attempt in range(2):
        c = LocalCluster(4, seed=seed, batch_size=8, node_impl=impl)
        for k in range(epochs + 4):
            for i in range(4):
                c.submit(i, Input.user(f"b-{k}-{i}"))
        c.start()
        try:
            ok = c.wait(
                lambda cl: all(
                    len(cl.batches(i)) >= epochs for i in range(4)
                ),
                EPOCH_TIMEOUT_S,
            )
            assert ok, {i: len(c.batches(i)) for i in range(4)}
            window = c.batches(0)[:epochs]
            if (
                all(len(b.contributions) == 4 for b in window)
                or attempt == 1
            ):
                digest = hashlib.sha256()
                for b in window:
                    digest.update(
                        serde.dumps((b.era, b.epoch, b.contributions))
                    )
                return digest.hexdigest()[:16]
        finally:
            c.stop()
    raise AssertionError("unreachable")


def _run_proc_arm(seed: int, epochs: int):
    """One presubmit native_proc run; returns (sha, summaries)."""
    with ProcCluster(
        4, seed=seed, impl="native", epochs=epochs, drive="presubmit",
        timeout_s=EPOCH_TIMEOUT_S,
    ) as c:
        sums = c.join(timeout_s=EPOCH_TIMEOUT_S + 30)
        assert all(s is not None and s["done"] for s in sums.values()), sums
        assert all(s["handler_errors"] == 0 for s in sums.values()), sums
        assert all(s["bad_payload"] == 0 for s in sums.values()), sums
        shas = {i: s["batches_sha"] for i, s in sums.items()}
        # cross-PROCESS agreement is the hard guarantee: four kernels,
        # four address spaces, one committed history
        assert len(set(shas.values())) == 1, (
            f"cross-process divergence: {shas}"
        )
        return shas[0], sums


def test_proc_cluster_matches_thread_arms_byte_identical():
    """The tentpole pin: N=4 native_proc commits the SAME bytes as the
    thread-mode native arm and the Python oracle at one seed, asserted
    across four real OS processes from their summary lines (full-
    participation runs compared; see _thread_arm_sha on the scheduling-
    outlier retry)."""
    _lib_or_skip()
    seed, epochs = 0, 3
    proc_sha = None
    for attempt in range(2):
        proc_sha, sums = _run_proc_arm(seed, epochs)
        if all(
            all(x == 4 for x in s["epoch_contribs"]) for s in sums.values()
        ) or attempt == 1:
            break
    assert proc_sha == _thread_arm_sha("native", seed, epochs)
    assert proc_sha == _thread_arm_sha("python", seed, epochs)


def test_proc_kill_restart_drill_lossless_for_survivors():
    """SIGKILL one worker mid-stream (a REAL process death), restart it
    on its old port: the surviving three never stall, their committed
    streams stay byte-identical and gapless, and the reborn process
    (fresh keys, fresh state — same semantics as the thread-mode drill,
    which also only guarantees survivors' progress: HoneyBadger has no
    state transfer, f-tolerance IS the recovery story) is dialed and
    ingesting again — the ACK/resume layer is lossless for survivors
    across an actual kernel-level death instead of a thread teardown."""
    _lib_or_skip()

    def counter(cl, node_id, name):
        # hbbft_count{name="transport.accepts"} 3
        try:
            text = cl.scrape(node_id, "/metrics").decode()
        except OSError:
            return 0
        for line in text.splitlines():
            if f'name="{name}"' in line and line.startswith("hbbft_count"):
                return int(float(line.rsplit(None, 1)[1]))
        return 0

    with ProcCluster(
        4, seed=3, impl="native", epochs=0, drive="self",
        timeout_s=120.0, obs=True,
    ) as c:
        survivors = [0, 1, 2]
        assert c.wait(
            lambda cl: all(cl.batch_count(i) >= 2 for i in range(4)),
            EPOCH_TIMEOUT_S,
        ), {i: c.batch_count(i) for i in range(4)}
        c.kill(3)
        base = max(c.batch_count(i) for i in survivors)
        assert c.wait(
            lambda cl: all(
                cl.batch_count(i) >= base + 2 for i in survivors
            ),
            EPOCH_TIMEOUT_S,
        ), {i: c.batch_count(i) for i in survivors}
        c.restart(3)
        # live-wait on the REBORN worker's own scrape endpoint until its
        # listener accepted a redial and it handled live traffic again
        # (the peers' dial backoff caps at 2 s — the summary would race
        # it otherwise)
        assert c.wait(
            lambda cl: counter(cl, 3, "transport.accepts") >= 1
            and counter(cl, 3, "cluster.msgs_handled") >= 1,
            EPOCH_TIMEOUT_S,
        ), "reborn worker never accepted a peer redial"
        post = max(c.batch_count(i) for i in survivors)
        assert c.wait(
            lambda cl: all(
                cl.batch_count(i) >= post + 2 for i in survivors
            ),
            EPOCH_TIMEOUT_S,
        ), {i: c.batch_count(i) for i in survivors}
        c.stop()
        reborn_summary = c.workers[3].summary
        # the reborn listener accepted fresh peer connections on the old
        # port and handled live protocol traffic again
        assert reborn_summary is not None
        assert reborn_summary["accepts"] >= 1, reborn_summary
        assert reborn_summary["msgs_handled"] > 0, reborn_summary
        assert reborn_summary["handler_errors"] == 0, reborn_summary

        streams = {i: c.batches(i) for i in survivors}
        by_key = {
            i: {(b["era"], b["epoch"]): b for b in bs}
            for i, bs in streams.items()
        }
        for i in survivors:
            keys = [(b["era"], b["epoch"]) for b in streams[i]]
            # no duplicate and no reordered commits in any stream
            assert keys == sorted(set(keys)), f"node {i} stream disordered"
        # byte-identical on every epoch two survivors both committed
        for a in survivors:
            for b in survivors:
                common = by_key[a].keys() & by_key[b].keys()
                assert common, (a, b)
                for k in common:
                    assert by_key[a][k] == by_key[b][k], (a, b, k)


def test_worker_obs_scrape_and_trace_merge(tmp_path):
    """Each worker process serves /metrics + /healthz on its ephemeral
    obs port (echoed in the ready line) and dumps a Chrome trace at
    exit; the parent merges the per-process files into ONE trace on the
    shared wall clock with distinct pids per node."""
    _lib_or_skip()
    trace_dir = str(tmp_path / "traces")
    with ProcCluster(
        4, seed=5, impl="native", epochs=0, drive="self",
        timeout_s=120.0, obs=True, trace_dir=trace_dir,
    ) as c:
        assert c.wait(
            lambda cl: all(cl.batch_count(i) >= 2 for i in range(4)),
            EPOCH_TIMEOUT_S,
        )
        metrics = c.scrape(1, "/metrics").decode()
        assert "cluster_msgs_handled" in metrics or "cluster.msgs_handled" in (
            metrics
        ), metrics[:400]
        health = json.loads(c.scrape(2, "/healthz"))
        assert health["ok"] is True
        assert health["nodes"]["2"]["alive"] is True
        assert health["nodes"]["2"]["batches"] >= 2
        c.stop()
        merged = c.merged_chrome_trace()
    events = merged["traceEvents"]
    tracks = {
        ev["args"]["name"]
        for ev in events
        if ev.get("ph") == "M" and ev.get("name") == "process_name"
    }
    assert {"node0", "node1", "node2", "node3"} <= tracks, tracks
    pids_per_track = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids_per_track[ev["args"]["name"]] = ev["pid"]
    assert len(set(pids_per_track.values())) == len(pids_per_track)
    opens = [ev for ev in events if ev.get("name") == "epoch.open"]
    commit_pids = {
        ev["pid"] for ev in events if ev.get("name") == "epoch.commit"
    }
    assert opens and len(commit_pids) >= 2, (len(opens), commit_pids)
    # shared-wall-clock alignment: no event sits before the merged t0
    assert all(
        ev["ts"] >= 0 for ev in events if ev.get("ph") != "M"
    )
