"""BLS12-381 oracle tests: curve self-validation, pairing laws, and the
threshold scheme + protocols running over the real curve (small N).
"""

import random

import pytest

from hbbft_tpu.crypto.backend import BatchedBackend, EagerBackend, VerifyRequest
from hbbft_tpu.crypto.bls import BLSSuite
from hbbft_tpu.crypto.bls import curve as C
from hbbft_tpu.crypto.bls import fields as F
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.threshold_sign import ThresholdSign


@pytest.fixture(scope="module")
def suite():
    return BLSSuite()


@pytest.fixture
def rng():
    return random.Random(7)


def test_curve_selfcheck():
    C.selfcheck()


def test_field_tower():
    rng = random.Random(3)
    a = (rng.randrange(F.P), rng.randrange(F.P))
    b = (rng.randrange(F.P), rng.randrange(F.P))
    # Fq2 inverse and sqrt round-trips.
    assert F.fq2_eq(F.fq2_mul(a, F.fq2_inv(a)), F.FQ2_ONE)
    sq = F.fq2_sqr(a)
    r = F.fq2_sqrt(sq)
    assert r is not None and (F.fq2_eq(r, a) or F.fq2_eq(F.fq2_neg(r), a))
    # Fq12 inverse and Frobenius composition.
    x = tuple((rng.randrange(F.P), rng.randrange(F.P)) for _ in range(6))
    assert F.fq12_is_one(F.fq12_mul(x, F.fq12_inv(x)))
    f2 = F.fq12_frobenius(F.fq12_frobenius(x, 1), 1)
    assert F.fq12_eq(f2, F.fq12_frobenius(x, 2))
    # Frobenius is the p-power map: check multiplicativity frob(xy)=frob(x)frob(y)
    y = tuple((rng.randrange(F.P), rng.randrange(F.P)) for _ in range(6))
    assert F.fq12_eq(
        F.fq12_frobenius(F.fq12_mul(x, y), 1),
        F.fq12_mul(F.fq12_frobenius(x, 1), F.fq12_frobenius(y, 1)),
    )


def test_pairing_bilinearity(suite):
    g1, g2 = suite.g1_generator(), suite.g2_generator()
    a, b = 0xDEADBEEF, 0xCAFE
    assert suite.pairing_product_is_one([(g1 * a, g2 * b), (-(g1 * (a * b)), g2)])
    assert suite.pairing_product_is_one([(g1 * a, g2 * b), (g1 * a, -(g2) * b)])
    assert not suite.pairing_product_is_one([(g1, g2)])  # non-degenerate
    # identity legs are neutral
    assert suite.pairing_product_is_one([(suite.g1_identity(), g2)])


def test_threshold_scheme_over_bls(suite, rng):
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"real curve signing"
    shares = {i: sks.secret_key_share(i).sign(msg) for i in range(4)}
    assert pks.public_key_share(2).verify_share(msg, shares[2])
    assert not pks.public_key_share(2).verify_share(b"other", shares[2])
    sig_a = pks.combine_signatures({i: shares[i] for i in (0, 3)})
    sig_b = pks.combine_signatures({i: shares[i] for i in (1, 2)})
    assert sig_a.g2 == sig_b.g2
    assert pks.verify_signature(msg, sig_a)

    ct = pks.public_key().encrypt(b"secret payload", rng)
    assert ct.verify()
    ds = {i: sks.secret_key_share(i).decryption_share(ct) for i in (0, 2)}
    assert pks.public_key_share(0).verify_decryption_share(ct, ds[0])
    assert pks.combine_decryption_shares(ds, ct) == b"secret payload"


def test_batched_backend_over_bls(suite, rng):
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"coin round 1"
    reqs = [
        VerifyRequest.sig_share(
            pks.public_key_share(i), msg, sks.secret_key_share(i).sign(msg)
        )
        for i in range(4)
    ]
    # One corrupted share (signed by the wrong share key).
    reqs[2] = VerifyRequest.sig_share(
        pks.public_key_share(2), msg, sks.secret_key_share(3).sign(msg)
    )
    batched = BatchedBackend(suite).verify_batch(reqs)
    assert batched == EagerBackend(suite).verify_batch(reqs)
    assert batched == [True, True, False, True]


@pytest.mark.slow
def test_threshold_sign_protocol_over_bls():
    doc = b"bls consensus doc"
    net = (
        NetBuilder(4, seed=5)
        .suite(BLSSuite())
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, doc, sink))
        .flush_every(4)
        .build()
    )
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    sigs = [net.node(nid).outputs[0] for nid in net.correct_ids]
    assert len({s.g2 for s in sigs}) == 1
    assert net.node(0).netinfo.public_key_set.verify_signature(doc, sigs[0])
    assert net.correct_faults() == []


# ---------------------------------------------------------------------------
# Endomorphism subgroup checks (curve.py g1_in_subgroup / g2_in_subgroup)
# ---------------------------------------------------------------------------


def _sample_e_fq(rng):
    """Random point on E(Fq) (full group, order h1*r w.h.p.)."""
    while True:
        x = rng.randrange(F.P)
        rhs = (x * x * x + C.B1) % F.P
        y = pow(rhs, (F.P + 1) // 4, F.P)  # P % 4 == 3
        if y * y % F.P == rhs:
            return (x, y, 1)


def _prime_factors(n, bound=1_000_000):
    """Primes of n found by trial division; perfect-square remainders
    are reduced (h1/h2's large factors appear squared: h1 = 3*m^2)."""
    import math

    out = {}
    d = 2
    while d * d <= n and d < bound:
        while n % d == 0:
            out[d] = out.get(d, 0) + 1
            n //= d
        d += 1
    while n > 1:
        s = math.isqrt(n)
        if s * s == n:
            n = s
            continue
        out[n] = out.get(n, 0) + 1  # treat remainder as prime (h1/h2: it is)
        break
    return out


def _point_of_prime_order(ops, cof, h, ell, k):
    """[h / ell^k]cof has order ell^s (s <= k); reduce to exact order ell.
    Returns None if cof has no ell-component."""
    q = C.jac_mul(ops, cof, h // (ell**k))
    if C.jac_is_identity(ops, q):
        return None
    while True:
        nxt = C.jac_mul(ops, q, ell)
        if C.jac_is_identity(ops, nxt):
            return q
        q = nxt


def test_endo_checks_match_definitional():
    rng = random.Random(11)
    for _ in range(4):
        k = rng.randrange(1, F.R)
        p1 = C.jac_mul(C.FQ_OPS, C.G1_GEN, k)
        q2 = C.jac_mul(C.FQ2_OPS, C.G2_GEN, k)
        assert C.g1_in_subgroup(p1) and C.in_subgroup_slow(C.FQ_OPS, p1)
        assert C.g2_in_subgroup(q2) and C.in_subgroup_slow(C.FQ2_OPS, q2)
    # identity is a member
    assert C.g1_in_subgroup(C.jac_identity(C.FQ_OPS))
    assert C.g2_in_subgroup(C.jac_identity(C.FQ2_OPS))


def test_endo_psi_is_endomorphism():
    """psi respects addition and has eigenvalue x on G2 — i.e. the
    derived constants really are the untwist-Frobenius-twist map."""
    rng = random.Random(13)
    a = C.jac_mul(C.FQ2_OPS, C.G2_GEN, rng.randrange(1, F.R))
    b = C.jac_mul(C.FQ2_OPS, C.G2_GEN, rng.randrange(1, F.R))
    lhs = C.g2_psi(C.jac_add(C.FQ2_OPS, a, b))
    rhs = C.jac_add(C.FQ2_OPS, C.g2_psi(a), C.g2_psi(b))
    assert C.jac_eq(C.FQ2_OPS, lhs, rhs)
    # psi also acts as an endomorphism on the FULL twist group (needed
    # for soundness reasoning): check on a non-G2 point.
    tw = C._twist_sample_point()
    lhs = C.g2_psi(C.jac_add(C.FQ2_OPS, tw, a))
    rhs = C.jac_add(C.FQ2_OPS, C.g2_psi(tw), C.g2_psi(a))
    assert C.jac_eq(C.FQ2_OPS, lhs, rhs)


def test_endo_g1_soundness_cofactor_primes():
    """The passing set is a subgroup of E(Fq); rejecting a point of
    exact order ell for every prime ell | h1 kills the ell-primary
    component of the passing set, so only G1 (plus nothing) passes."""
    rng = random.Random(17)
    h1 = C.H1
    factors = _prime_factors(h1)
    pt = _sample_e_fq(rng)
    cof = C.jac_mul(C.FQ_OPS, pt, F.R)  # order | h1
    assert not C.jac_is_identity(C.FQ_OPS, cof)
    assert not C.g1_in_subgroup(cof)
    checked = 0
    for ell, k in sorted(factors.items()):
        q = _point_of_prime_order(C.FQ_OPS, cof, h1, ell, k)
        if q is not None:
            assert not C.g1_in_subgroup(q), f"order-{ell} point passed"
            assert not C.in_subgroup_slow(C.FQ_OPS, q)
            checked += 1
    assert checked >= 2  # the sample point w.h.p. has most components


def test_endo_g2_soundness_cofactor_primes():
    h2 = C.h2_cofactor()
    factors = _prime_factors(h2)
    tw = C._twist_sample_point()
    cof = C.jac_mul(C.FQ2_OPS, tw, F.R)  # order | h2
    assert not C.jac_is_identity(C.FQ2_OPS, cof)
    assert not C.g2_in_subgroup(cof)
    checked = 0
    for ell, k in sorted(factors.items()):
        q = _point_of_prime_order(C.FQ2_OPS, cof, h2, ell, k)
        if q is not None:
            assert not C.g2_in_subgroup(q), f"order-{ell} point passed"
            checked += 1
    assert checked >= 2
    # full-order twist point agrees with the definitional check
    assert not C.g2_in_subgroup(tw)
    assert not C.in_subgroup_slow(C.FQ2_OPS, tw)


def test_endo_matches_suite_membership(suite):
    """suite.is_g1/is_g2 (which now ride the endomorphism checks) still
    reject wire points off the subgroup."""
    rng = random.Random(23)
    tw = C._twist_sample_point()
    cof = C.jac_mul(C.FQ2_OPS, tw, F.R)
    from hbbft_tpu.crypto.bls.suite import G2Elem

    bad = G2Elem(cof)
    assert not suite.is_g2(bad)
    good = suite.g2_generator() * rng.randrange(1, F.R)
    assert suite.is_g2(good)
