"""BLS12-381 oracle tests: curve self-validation, pairing laws, and the
threshold scheme + protocols running over the real curve (small N).
"""

import random

import pytest

from hbbft_tpu.crypto.backend import BatchedBackend, EagerBackend, VerifyRequest
from hbbft_tpu.crypto.bls import BLSSuite
from hbbft_tpu.crypto.bls import curve as C
from hbbft_tpu.crypto.bls import fields as F
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.threshold_sign import ThresholdSign


@pytest.fixture(scope="module")
def suite():
    return BLSSuite()


@pytest.fixture
def rng():
    return random.Random(7)


def test_curve_selfcheck():
    C.selfcheck()


def test_field_tower():
    rng = random.Random(3)
    a = (rng.randrange(F.P), rng.randrange(F.P))
    b = (rng.randrange(F.P), rng.randrange(F.P))
    # Fq2 inverse and sqrt round-trips.
    assert F.fq2_eq(F.fq2_mul(a, F.fq2_inv(a)), F.FQ2_ONE)
    sq = F.fq2_sqr(a)
    r = F.fq2_sqrt(sq)
    assert r is not None and (F.fq2_eq(r, a) or F.fq2_eq(F.fq2_neg(r), a))
    # Fq12 inverse and Frobenius composition.
    x = tuple((rng.randrange(F.P), rng.randrange(F.P)) for _ in range(6))
    assert F.fq12_is_one(F.fq12_mul(x, F.fq12_inv(x)))
    f2 = F.fq12_frobenius(F.fq12_frobenius(x, 1), 1)
    assert F.fq12_eq(f2, F.fq12_frobenius(x, 2))
    # Frobenius is the p-power map: check multiplicativity frob(xy)=frob(x)frob(y)
    y = tuple((rng.randrange(F.P), rng.randrange(F.P)) for _ in range(6))
    assert F.fq12_eq(
        F.fq12_frobenius(F.fq12_mul(x, y), 1),
        F.fq12_mul(F.fq12_frobenius(x, 1), F.fq12_frobenius(y, 1)),
    )


def test_pairing_bilinearity(suite):
    g1, g2 = suite.g1_generator(), suite.g2_generator()
    a, b = 0xDEADBEEF, 0xCAFE
    assert suite.pairing_product_is_one([(g1 * a, g2 * b), (-(g1 * (a * b)), g2)])
    assert suite.pairing_product_is_one([(g1 * a, g2 * b), (g1 * a, -(g2) * b)])
    assert not suite.pairing_product_is_one([(g1, g2)])  # non-degenerate
    # identity legs are neutral
    assert suite.pairing_product_is_one([(suite.g1_identity(), g2)])


def test_threshold_scheme_over_bls(suite, rng):
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"real curve signing"
    shares = {i: sks.secret_key_share(i).sign(msg) for i in range(4)}
    assert pks.public_key_share(2).verify_share(msg, shares[2])
    assert not pks.public_key_share(2).verify_share(b"other", shares[2])
    sig_a = pks.combine_signatures({i: shares[i] for i in (0, 3)})
    sig_b = pks.combine_signatures({i: shares[i] for i in (1, 2)})
    assert sig_a.g2 == sig_b.g2
    assert pks.verify_signature(msg, sig_a)

    ct = pks.public_key().encrypt(b"secret payload", rng)
    assert ct.verify()
    ds = {i: sks.secret_key_share(i).decryption_share(ct) for i in (0, 2)}
    assert pks.public_key_share(0).verify_decryption_share(ct, ds[0])
    assert pks.combine_decryption_shares(ds, ct) == b"secret payload"


def test_batched_backend_over_bls(suite, rng):
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"coin round 1"
    reqs = [
        VerifyRequest.sig_share(
            pks.public_key_share(i), msg, sks.secret_key_share(i).sign(msg)
        )
        for i in range(4)
    ]
    # One corrupted share (signed by the wrong share key).
    reqs[2] = VerifyRequest.sig_share(
        pks.public_key_share(2), msg, sks.secret_key_share(3).sign(msg)
    )
    batched = BatchedBackend(suite).verify_batch(reqs)
    assert batched == EagerBackend(suite).verify_batch(reqs)
    assert batched == [True, True, False, True]


@pytest.mark.slow
def test_threshold_sign_protocol_over_bls():
    doc = b"bls consensus doc"
    net = (
        NetBuilder(4, seed=5)
        .suite(BLSSuite())
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, doc, sink))
        .flush_every(4)
        .build()
    )
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    sigs = [net.node(nid).outputs[0] for nid in net.correct_ids]
    assert len({s.g2 for s in sigs}) == 1
    assert net.node(0).netinfo.public_key_set.verify_signature(doc, sigs[0])
    assert net.correct_faults() == []
