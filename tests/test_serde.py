"""Safe-codec tests: round trips, strictness, and Byzantine rejection.

The codec replaces the reference's ``bincode`` boundary (upstream
``src/honey_badger/honey_badger.rs`` serializes contributions before
threshold-encrypting them).  Committed payloads are attacker-authored, so
``loads`` must be total over arbitrary bytes: decode a registered value
or raise — never execute code, never construct unregistered types.
"""

import pickle
import random

import pytest

from hbbft_tpu.crypto.keys import Ciphertext, SecretKey
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    InternalContrib,
    JoinPlan,
    SignedKeyGenMsg,
    SignedVote,
)
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen
from hbbft_tpu.utils import serde
from hbbft_tpu.utils.serde import DecodeError

SUITE = ScalarSuite()


@pytest.fixture
def rng():
    return random.Random(42)


def roundtrip(obj):
    data = serde.dumps(obj)
    assert isinstance(data, bytes)
    out = serde.loads(data)
    assert out == obj
    # byte stability: same object -> same bytes
    assert serde.dumps(out) == data
    return out


# -- primitives -------------------------------------------------------------


def test_primitive_roundtrips():
    for obj in [
        None,
        True,
        False,
        0,
        1,
        -1,
        2**300,
        -(2**300),
        b"",
        b"\x00\xff" * 100,
        "",
        "unicode é中",
        (),
        (1, (2, (3,))),
        [],
        [1, "two", b"three", None],
        {},
        {"a": 1, 2: b"b", (1, 2): "tuple-key"},
    ]:
        roundtrip(obj)


def test_bool_int_distinction():
    assert serde.loads(serde.dumps(True)) is True
    assert serde.loads(serde.dumps(1)) == 1
    assert serde.dumps(True) != serde.dumps(1)


def test_unencodable_types_raise():
    with pytest.raises(serde.EncodeError):
        serde.dumps(object())
    with pytest.raises(serde.EncodeError):
        serde.dumps(lambda: None)
    with pytest.raises(serde.EncodeError):
        serde.dumps({1: object()})


# -- strictness over raw bytes ---------------------------------------------


def test_malformed_bytes_rejected():
    bad = [
        b"",
        b"\xff",
        b"\x03",  # truncated int
        b"\x03\x02\x00\x00\x00\x01\x05",  # bad sign byte
        b"\x03\x00\x00\x00\x00\x02\x00\x01",  # non-minimal int
        b"\x03\x01\x00\x00\x00\x00",  # negative zero
        b"\x04\xff\xff\xff\xff",  # bytes len >> input
        b"\x06\xff\xff\xff\xff",  # tuple count >> input
        b"\x05\x00\x00\x00\x01\xff",  # invalid utf-8
        b"\x10\x05bogus\x06\x00\x00\x00\x00",  # unknown struct
        b"\x11\x03xyz\x01\x00\x00\x00\x00",  # unknown suite
        serde.dumps((1, 2))[:-1],  # truncation
        serde.dumps((1, 2)) + b"\x00",  # trailing bytes
    ]
    for data in bad:
        assert serde.try_loads(data) is None, data
        with pytest.raises(DecodeError):
            serde.loads(data)


def test_depth_bomb_rejected():
    # 1000 nested tuples: encoder refuses to build it, decoder refuses
    # hand-rolled bytes at the same bound.
    data = b"\x06\x00\x00\x00\x01" * 1000 + b"\x00"
    assert serde.try_loads(data) is None


def test_pickle_bytes_rejected():
    for payload in [["tx"], {"a": 1}, object()]:
        try:
            blob = pickle.dumps(payload)
        except Exception:
            continue
        assert serde.try_loads(blob) is None


def test_duplicate_dict_key_rejected():
    one = serde.dumps(1)
    item = one + one
    data = b"\x08" + (2).to_bytes(4, "big") + item + item
    assert serde.try_loads(data) is None


# -- crypto types -----------------------------------------------------------


def test_ciphertext_roundtrip_and_decrypt(rng):
    sk = SecretKey.random(rng, SUITE)
    ct = sk.public_key().encrypt(b"payload", rng)
    ct2 = roundtrip(ct)
    assert isinstance(ct2, Ciphertext)
    assert sk.decrypt(ct2) == b"payload"


def test_group_element_range_enforced(rng):
    sk = SecretKey.random(rng, SUITE)
    ct = sk.public_key().encrypt(b"x", rng)
    data = bytearray(serde.dumps(ct))
    # Overwrite the first group element payload with r (out of range).
    idx = bytes(data).index(b"\x11")
    # tag(1) + namelen(1) + name + group(1) + len(4) -> payload
    name_len = data[idx + 1]
    payload_at = idx + 2 + name_len + 1 + 4
    data[payload_at : payload_at + 32] = SUITE.scalar_modulus.to_bytes(32, "big")
    assert serde.try_loads(bytes(data)) is None


def test_signature_and_votes_roundtrip(rng):
    sk = SecretKey.random(rng, SUITE)
    pk = sk.public_key()
    change = Change.node_change({"a": pk, "b": pk})
    vote = SignedVote("a", 0, 3, change, sk.sign(b"payload"))
    roundtrip(vote)
    roundtrip(InternalContrib(["t1", "t2"], (), (vote,)))
    roundtrip(EncryptionSchedule.tick_tock(2))


def test_vote_with_wrong_signature_type_rejected(rng):
    sk = SecretKey.random(rng, SUITE)
    change = Change.node_change({"a": sk.public_key()})
    vote = SignedVote("a", 0, 1, change, sk.sign(b"m"))
    data = serde.dumps(vote)
    # Splice: replace the struct name "svote"'s signature field by
    # re-encoding with a non-Signature: build the tuple by hand.
    forged = serde.dumps(("a", 0, 1, change, b"not-a-signature"))
    # direct unpack-level check via a hand-built struct frame
    frame = b"\x10" + bytes([len(b"svote")]) + b"svote" + forged
    assert serde.try_loads(frame) is None
    assert serde.loads(data) == vote


def test_change_cross_field_invariants_enforced(rng):
    sk = SecretKey.random(rng, SUITE)
    pk = sk.public_key()

    def frame(fields):
        return (
            b"\x10" + bytes([len(b"change")]) + b"change" + serde.dumps(fields)
        )

    # schedule change without a schedule -> would crash encrypt_on(None)
    assert serde.try_loads(frame(("encryption_schedule", (), None))) is None
    # schedule change smuggling validators
    assert (
        serde.try_loads(
            frame(
                (
                    "encryption_schedule",
                    (("a", pk),),
                    EncryptionSchedule.always(),
                )
            )
        )
        is None
    )
    # node change with empty validator set -> threshold -1
    assert serde.try_loads(frame(("node_change", (), None))) is None
    # node change smuggling a schedule
    assert (
        serde.try_loads(
            frame(("node_change", (("a", pk),), EncryptionSchedule.always()))
        )
        is None
    )
    # honest constructions still round-trip
    roundtrip(Change.node_change({"a": pk}))
    roundtrip(Change.encryption_schedule(EncryptionSchedule.tick_tock(2)))


def test_dkg_part_ack_roundtrip(rng):
    ids = ["n0", "n1", "n2", "n3"]
    sks = {i: SecretKey.random(rng, SUITE) for i in ids}
    pub = {i: sks[i].public_key() for i in ids}
    kg, part = SyncKeyGen.new("n0", sks["n0"], pub, 1, rng, SUITE)
    part2 = roundtrip(part)
    outcome = kg.handle_part("n0", part2, rng)
    assert outcome.is_valid and outcome.ack is not None
    roundtrip(outcome.ack)
    msg = SignedKeyGenMsg(0, "n0", part, sks["n0"].sign(b"kg"))
    roundtrip(msg)


def test_join_plan_roundtrip(rng):
    from hbbft_tpu.crypto.keys import SecretKeySet

    sks = SecretKeySet.random(1, rng, SUITE)
    pks = sks.public_keys()
    reg = {i: SecretKey.random(rng, SUITE).public_key() for i in "abcd"}
    plan = JoinPlan(
        2,
        pks,
        tuple(sorted(reg.items())),
        EncryptionSchedule.always(),
    )
    plan2 = roundtrip(plan)
    assert plan2.public_key_set.public_key() == pks.public_key()


def test_node_id_restricted_to_plain_scalars(rng):
    sk = SecretKey.random(rng, SUITE)
    change = Change.node_change({"a": sk.public_key()})
    # voter id as a tuple: encodable as a value, but rejected as node id
    forged = serde.dumps((("evil", "tuple"), 0, 1, change, sk.sign(b"m")))
    frame = b"\x10" + bytes([len(b"svote")]) + b"svote" + forged
    assert serde.try_loads(frame) is None


def test_unencodable_contribution_raises_at_input_boundary(rng):
    """API misuse raises a typed error BEFORE any state change — a bad
    transaction cannot crash the node epochs later (upstream analog:
    bincode's Serialize bound rejects at compile time)."""
    from hbbft_tpu.net import NetBuilder
    from hbbft_tpu.protocols.errors import ContributionNotEncodable
    from hbbft_tpu.protocols.honey_badger import HoneyBadger
    from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger

    class CustomTxn:
        pass

    net = (
        NetBuilder(4, seed=13)
        .num_faulty(0)
        .protocol(lambda ni, sink, rng: HoneyBadger(ni, sink))
        .build()
    )
    hb = net.node(0).protocol
    with pytest.raises(ContributionNotEncodable):
        hb.handle_input(CustomTxn(), rng)
    assert not hb.has_input  # no state change

    qnet = (
        NetBuilder(4, seed=13)
        .num_faulty(0)
        .protocol(lambda ni, sink, rng: QueueingHoneyBadger(ni, sink, batch_size=8))
        .build()
    )
    qhb = qnet.node(0).protocol
    with pytest.raises(ContributionNotEncodable):
        qhb.push_transaction(CustomTxn(), rng)
    assert len(qhb.queue) == 0  # never queued


def test_none_contribution_is_not_a_fault():
    """An honest proposer of None must not be faulted: decoded-None and
    decode-failure are distinct."""
    from hbbft_tpu.net import NetBuilder
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    net = (
        NetBuilder(4, seed=17)
        .num_faulty(0)
        .protocol(lambda ni, sink, rng: HoneyBadger(ni, sink))
        .build()
    )
    net.broadcast_input(lambda nid: None if nid == 0 else [f"tx-{nid}"])
    net.crank_until(
        lambda n: all(len(n.node(i).outputs) >= 1 for i in n.correct_ids)
    )
    assert net.correct_faults() == []
    batch = net.node(1).outputs[0]
    cm = batch.contribution_map()
    if 0 in cm:  # Subset may or may not include node 0's proposal
        assert cm[0] is None


# -- BLS suite --------------------------------------------------------------


def test_bls_ciphertext_roundtrip(rng):
    from hbbft_tpu.crypto.bls.suite import BLSSuite

    suite = BLSSuite()
    sk = SecretKey.random(rng, suite)
    ct = sk.public_key().encrypt(b"bls payload", rng)
    ct2 = roundtrip(ct)
    assert sk.decrypt(ct2) == b"bls payload"


def test_suite_pinning_rejects_other_suites(rng):
    """A deployment pins its suite: bytes naming any other suite (e.g.
    the INSECURE ScalarSuite in a BLS network) are rejected at the frame
    level, so a Byzantine proposer cannot select forgeable crypto."""
    from hbbft_tpu.crypto.bls.suite import BLSSuite

    bls = BLSSuite()
    sk = SecretKey.random(rng, SUITE)
    scalar_ct = sk.public_key().encrypt(b"x", rng)
    data = serde.dumps(scalar_ct)
    # unpinned: decodes fine; pinned to BLS: rejected
    assert serde.loads(data) == scalar_ct
    assert serde.try_loads(data, suite=bls) is None
    with pytest.raises(DecodeError, match="not allowed"):
        serde.loads(data, suite=bls)
    # pinned to its own suite: fine
    assert serde.loads(data, suite=SUITE) == scalar_ct


def test_honey_badger_decodes_with_pinned_suite():
    """HoneyBadger passes its network suite into serde decoding."""
    from hbbft_tpu.net import NetBuilder
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    net = (
        NetBuilder(4, seed=21)
        .num_faulty(0)
        .protocol(lambda ni, sink, rng: HoneyBadger(ni, sink))
        .build()
    )
    net.broadcast_input(lambda nid: [f"tx-{nid}"])
    net.crank_until(
        lambda n: all(len(n.node(i).outputs) >= 1 for i in n.correct_ids)
    )
    batches = [net.node(i).outputs[0] for i in net.correct_ids]
    assert all(b == batches[0] for b in batches)
    assert net.correct_faults() == []


def test_bls_identity_point_roundtrip_and_canonical():
    from hbbft_tpu.crypto.bls.suite import BLSSuite

    suite = BLSSuite()
    ident = suite.g1_identity()
    assert suite.g1_from_bytes(ident.to_bytes()) == ident
    # non-canonical identity (flag 0 but nonzero body) rejected
    bad = b"\x00" + b"\x01" * 96
    with pytest.raises(ValueError):
        suite.g1_from_bytes(bad)
    ident2 = suite.g2_identity()
    assert suite.g2_from_bytes(ident2.to_bytes()) == ident2


def test_bls_non_subgroup_point_rejected():
    """An on-curve G1 point OUTSIDE the r-torsion subgroup must be
    rejected at decode (CLAUDE.md invariant: wire-sourced points get
    subgroup checks).  A random on-curve point lies outside the subgroup
    with overwhelming probability (cofactor ~2^125)."""
    from hbbft_tpu.crypto.bls import fields as F
    from hbbft_tpu.crypto.bls.suite import BLSSuite

    suite = BLSSuite()
    P = F.P
    x = 5
    while True:
        rhs = (x * x * x + 4) % P
        y = pow(rhs, (P + 1) // 4, P)  # sqrt (p % 4 == 3)
        if y * y % P == rhs:
            break
        x += 1
    enc = b"\x01" + x.to_bytes(48, "big") + y.to_bytes(48, "big")
    with pytest.raises(ValueError):
        suite.g1_from_bytes(enc)
    # sanity: same encoding with a generator multiple IS accepted
    g = suite.g1_generator() * 12345
    assert suite.g1_from_bytes(g.to_bytes()) == g


def test_bls_subgroup_memo_single_check():
    """The torsion memo: a second is_g1 on the same element skips the
    scalar mult (observable via the private flag)."""
    from hbbft_tpu.crypto.bls.suite import BLSSuite

    suite = BLSSuite()
    g = suite.g1_generator() * 7
    assert not g._subgroup_ok
    assert suite.is_g1(g)
    assert g._subgroup_ok
    assert suite.is_g1(g)  # second call: memo hit


def test_bls_off_curve_point_rejected(rng):
    from hbbft_tpu.crypto.bls.suite import BLSSuite

    suite = BLSSuite()
    sk = SecretKey.random(rng, suite)
    ct = sk.public_key().encrypt(b"x", rng)
    data = bytearray(serde.dumps(ct))
    # find the G1 payload (97 bytes after the group header) and corrupt y
    idx = bytes(data).index(b"\x11")
    name_len = data[idx + 1]
    payload_at = idx + 2 + name_len + 1 + 4
    data[payload_at + 96] ^= 1  # flip a bit of y
    assert serde.try_loads(bytes(data)) is None


def test_scalar_ct_serde_cache_matches_recursive_encoder():
    """The pre-rendered `_serde_cache` memo the native KEM attaches must
    be byte-identical to what the recursive encoder emits — a wrong
    rendering would be a silent wire divergence."""
    import random

    from hbbft_tpu.crypto.keys import Ciphertext, SecretKey, scalar_ct_serde
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.utils import serde

    suite = ScalarSuite()
    rng = random.Random(9)
    sk = SecretKey.random(rng, suite)
    for msg in (b"\x00" * 32, b"hello world", b""):
        ct = sk.public_key().encrypt(msg, rng)
        # recursive-path encoding of an equal ciphertext WITHOUT a memo
        bare = Ciphertext(ct.u, ct.v, ct.w, suite)
        want = serde.dumps(bare)
        got = scalar_ct_serde(
            ct.u.value.to_bytes(32, "big"), ct.v,
            ct.w.value.to_bytes(32, "big"),
        )
        assert got == want
        # and the memo'd object round-trips identically
        assert serde.dumps(ct) == want
        assert serde.loads(want, suite=suite) == bare


def test_native_scan_decode_matches_pure_decoder():
    """The C token scan + builder must ACCEPT exactly what the recursive
    decoder accepts (same objects) and REJECT exactly what it rejects —
    checked over round-trips of representative structures, truncations,
    and byte-flip corruptions of real encodings."""
    import random

    from hbbft_tpu.crypto.keys import SecretKey
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.utils import serde

    lib = serde._native_scan(b"\x00")
    if lib is None:
        import pytest

        pytest.skip("native engine unavailable")

    suite = ScalarSuite()
    rng = random.Random(5)
    sk = SecretKey.random(rng, suite)
    ct = sk.public_key().encrypt(b"payload bytes", rng)
    samples = [
        None, True, False, 0, 1, -1, 2**300, -(2**300),
        b"", b"abc", "txt", "ünicode",
        (1, (2, b"x"), [None, True]), {"k": 1, 2: (3,)}, [],
        ct, (ct, ct), {"ct": ct},
        sk.public_key(),
    ]

    def pure_loads(data):
        r = serde._Reader(data, None)
        obj = serde._decode(r, 0)
        if r.pos != len(r.data):
            raise serde.DecodeError("trailing bytes")
        return obj

    encodings = []
    for obj in samples:
        try:
            enc = serde.dumps(obj)
        except serde.EncodeError:
            continue
        encodings.append(enc)
        assert serde.loads(enc, suite=suite if obj is ct else None) is not None or obj is None
        # native result equals pure result exactly
        assert serde.loads(enc) == pure_loads(enc)

    # corruption sweep: every truncation point of a short encoding plus
    # byte flips across a ciphertext encoding — accept/reject must agree
    rng2 = random.Random(7)
    enc = serde.dumps((1, b"ab", "c", ct))
    for cut in range(len(enc)):
        data = enc[:cut]
        try:
            want = pure_loads(data)
        except serde.DecodeError:
            want = "ERR"
        try:
            got = serde.loads(data)
        except serde.DecodeError:
            got = "ERR"
        assert (got == "ERR") == (want == "ERR"), cut
        if want != "ERR":
            assert got == want
    for _ in range(300):
        i = rng2.randrange(len(enc))
        data = enc[:i] + bytes([enc[i] ^ (1 << rng2.randrange(8))]) + enc[i + 1:]
        try:
            want = pure_loads(data)
        except serde.DecodeError:
            want = "ERR"
        try:
            got = serde.loads(data)
        except serde.DecodeError:
            got = "ERR"
        assert (got == "ERR") == (want == "ERR"), i
        if want != "ERR":
            assert got == want


def test_depth_and_memo_boundaries_match_both_paths():
    """Depth 64 accepted, 65 rejected — by BOTH decoders (the native
    scanner takes the limits as arguments, so a constant edit cannot
    make them diverge); and a memo'd ciphertext nested near MAX_DEPTH
    falls back to the recursive encoder so dumps never emits bytes
    loads rejects."""
    import random

    from hbbft_tpu.crypto.keys import SecretKey
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.utils import serde

    def pure_loads(data):
        r = serde._Reader(data, None)
        obj = serde._decode(r, 0)
        if r.pos != len(r.data):
            raise serde.DecodeError("trailing bytes")
        return obj

    def nested(depth):
        return b"\x06\x00\x00\x00\x01" * depth + b"\x00"

    ok = nested(serde.MAX_DEPTH)  # value at depth MAX_DEPTH: accepted
    bad = nested(serde.MAX_DEPTH + 1)
    assert pure_loads(ok) == serde.loads(ok)  # both accept, same value
    for data in (bad,):
        import pytest

        with pytest.raises(serde.DecodeError):
            pure_loads(data)
        with pytest.raises(serde.DecodeError):
            serde.loads(data)

    # memo near the depth limit: round-trip must hold whenever dumps
    # succeeds
    suite = ScalarSuite()
    rng = random.Random(3)
    ct = SecretKey.random(rng, suite).public_key().encrypt(b"x" * 8, rng)
    assert "_serde_cache" in ct.__dict__
    obj = ct
    for _ in range(serde.MAX_DEPTH - 2):
        obj = (obj,)
    enc = serde.dumps(obj)  # deepest legal nesting for the ct subtree
    assert serde.loads(enc, suite=suite) is not None
    try:
        serde.dumps(((obj,),))
        deeper_ok = True
    except serde.EncodeError:
        deeper_ok = False
    assert not deeper_ok  # encoder refuses past the limit either way


def test_transport_boundary_unpackers_reject_malformed():
    """The live-wire message codecs (wire.py "transport-boundary types")
    are stricter than the in-process handlers; pin each reject branch by
    dumping a structurally-valid-but-semantically-bad object (frozen
    dataclasses construct anything) and asserting loads() refuses it."""
    import pytest

    from hbbft_tpu.ops.merkle import Proof
    from hbbft_tpu.protocols.binary_agreement import AbaMessage, TermMsg
    from hbbft_tpu.protocols.bool_set import BoolSet
    from hbbft_tpu.protocols.broadcast import EchoMsg, ReadyMsg, ValueMsg
    from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage
    from hbbft_tpu.protocols.honey_badger import DECRYPT, SUBSET, HbMessage
    from hbbft_tpu.protocols.sbv_broadcast import BValMsg
    from hbbft_tpu.protocols.sender_queue import SqMessage
    from hbbft_tpu.protocols.subset import BC, SubsetMessage
    from hbbft_tpu.utils import serde

    good_proof = Proof(b"leaf", 0, (b"h" * 32,), b"r" * 32)
    good_subset = SubsetMessage(1, BC, ValueMsg(good_proof))
    good_hb = HbMessage(0, SUBSET, None, good_subset)

    bad = [
        ReadyMsg(b"short-root"),                      # root not 32 bytes
        ReadyMsg("r" * 32),                           # root not bytes
        EchoMsg(b"not-a-proof"),                      # proof wrong type
        ValueMsg(None),
        Proof(b"v", -1, (), b"r" * 32),               # negative index
        Proof(b"v", 0, (b"short",), b"r" * 32),       # path hash not 32B
        BValMsg(1),                                   # int, not bool
        AbaMessage(-1, TermMsg(True)),                # negative round
        AbaMessage(0, b"junk"),                       # content wrong type
        SubsetMessage(1, "neither", TermMsg(True)),   # bad kind
        SubsetMessage(1, BC, AbaMessage(0, TermMsg(True))),  # ba inner in bc
        HbMessage(0, SUBSET, 3, good_subset),         # subset with proposer
        HbMessage(0, DECRYPT, 3, good_subset),        # wrong decrypt inner
        HbMessage(-1, SUBSET, None, good_subset),     # negative epoch
        HbMessage(0, "nope", None, good_subset),      # bad kind
        DhbMessage(-1, good_hb),                      # negative era
        DhbMessage(0, good_subset),                   # inner not HbMessage
        SqMessage("nope", 1),                         # unknown kind
        SqMessage("epoch_started", (0,)),             # not a 2-tuple
        SqMessage("epoch_started", (0, -1)),          # negative epoch
        SqMessage("epoch_started", (0, True)),        # bool is not an epoch
        SqMessage("algo", good_subset),               # not a Dhb/Hb message
        SqMessage("join_plan", b"forged"),            # not a JoinPlan
    ]
    for obj in bad:
        enc = serde.dumps(obj)
        with pytest.raises(serde.DecodeError):
            serde.loads(enc)
        assert serde.try_loads(enc) is None

    # BoolSet's constructor forbids mask 4, so hand-assemble the struct
    # frame: STRUCT "bools" + fields tuple(1) + int 4.
    raw = bytes(
        [0x10, 5] + list(b"bools") + [0x06, 0, 0, 0, 1]
        + [0x03, 0, 0, 0, 0, 1, 4]
    )
    with pytest.raises(serde.DecodeError):
        serde.loads(raw)
    # sanity: valid masks decode
    assert serde.loads(serde.dumps(BoolSet.both())) == BoolSet.both()
    assert serde.loads(serde.dumps(good_hb)) == good_hb
