"""Device-side data-plane ops vs host implementations.

Reference behavior: ``reed-solomon-erasure`` + ``tiny-keccak`` as used by
upstream ``src/broadcast`` (SURVEY.md §2 #4), here as GF(2) bit-matmuls
and batched Keccak-f[1600] (hbbft_tpu/ops/jaxops/).
"""

import hashlib

import numpy as np
import pytest

from hbbft_tpu.ops import gf256 as host_gf
from hbbft_tpu.ops import merkle as host_merkle
from hbbft_tpu.ops.jaxops import gf256 as jgf
from hbbft_tpu.ops.jaxops import keccak as jk


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(9)


def test_sha3_matches_hashlib(rng):
    for m in (0, 1, 64, 65, 135):
        msgs = rng.integers(0, 256, size=(5, m), dtype=np.uint8)
        got = jk.sha3_256_batch(msgs)
        for i in range(5):
            assert bytes(got[i]) == hashlib.sha3_256(bytes(msgs[i])).digest()


def test_merkle_level_matches_host(rng):
    pairs = rng.integers(0, 256, size=(8, 64), dtype=np.uint8)
    got = jk.merkle_level(0x01, pairs)
    for i in range(8):
        left, right = bytes(pairs[i, :32]), bytes(pairs[i, 32:])
        assert bytes(got[i]) == host_merkle._h_branch(left, right)


@pytest.mark.parametrize("k,n", [(2, 3), (4, 7), (6, 10)])
def test_rs_encode_matches_host(rng, k, n):
    data = [bytes(rng.integers(0, 256, 48, dtype=np.uint8)) for _ in range(k)]
    assert jgf.ReedSolomonJax(k, n).encode(data) == host_gf.ReedSolomon(k, n).encode(data)


def test_rs_reconstruct_roundtrip(rng):
    k, n = 4, 7
    rs = jgf.ReedSolomonJax(k, n)
    data = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(k)]
    shards = rs.encode(data)
    # every k-subset of shards reconstructs the data
    import itertools

    for idxs in itertools.combinations(range(n), k):
        assert rs.reconstruct({i: shards[i] for i in idxs}) == data


def test_pallas_keccak_matches_jnp_and_hashlib():
    """Pallas permutation == jnp path == hashlib (TPU only).

    Interpret mode on CPU is not used: XLA/LLVM compile time for the
    interpreter's expansion of the 24-round kernel is unbounded in
    practice (observed 20s-10min for identical inputs).  The kernel is
    validated on real TPU hardware, where it compiles via Mosaic.
    """
    import hashlib

    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("pallas kernel targets TPU; interpret mode unreliable")

    from hbbft_tpu.ops.jaxops import keccak_pallas as kp

    rng = np.random.default_rng(9)
    msgs = rng.integers(0, 256, size=(33, 65), dtype=np.uint8)
    got = kp.sha3_256_batch(msgs)
    want = jk.sha3_256_batch(msgs)
    assert np.array_equal(got, want)
    for i in range(msgs.shape[0]):
        assert got[i].tobytes() == hashlib.sha3_256(msgs[i].tobytes()).digest()


def test_device_dataplane_matches_host_broadcast():
    """Batched device RS+Merkle proofs == the host Broadcast data plane."""
    import random

    from hbbft_tpu.ops.gf256 import ReedSolomon
    from hbbft_tpu.ops.jaxops import dataplane
    from hbbft_tpu.ops.merkle import MerkleTree

    rng = random.Random(17)
    k, n = 5, 7
    values = [rng.randbytes(rng.randrange(200, 220)) for _ in range(6)]
    # Force a common shard length by sizing values identically enough:
    values = [v.ljust(220, b"\x00") for v in values]
    proofs = dataplane.encode_and_prove(values, k, n)
    rs = ReedSolomon(k, n)
    for v, value in enumerate(values):
        packed, _ = dataplane._pack(value, k)
        shards = rs.encode([bytes(r) for r in packed])
        tree = MerkleTree(shards)
        for i in range(n):
            want = tree.proof(i)
            got = proofs[v][i]
            assert got == want, (v, i)
            assert got.validate(n)


def test_dataplane_rs_bitmatmul_sharded_over_mesh(rng):
    """VERDICT round 1, weak #6: shard the DATAPLANE batch (not just the
    crypto flush) over a device mesh.  The RS encode bit-matmul's value
    column axis (V values x shard bytes) is data-parallel; sharding it
    must reproduce the single-device (and host) parity bytes exactly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

    from hbbft_tpu.ops import gf256 as host_gf
    from hbbft_tpu.ops.jaxops import gf256 as jgf

    devices = np.array(jax.devices())
    if devices.size < 2:
        pytest.skip("needs a multi-device platform")
    mesh = Mesh(devices.reshape(-1), axis_names=("dp",))

    k, n = 6, 10
    V, shard_len = 16, 64  # 16 values' data shards, concatenated columns
    data = rng.integers(0, 256, size=(k, V * shard_len), dtype=np.uint8)

    enc = jgf._enc_bits(k, n)
    bits = jgf.bytes_to_bits(data)  # (8k, V*shard_len)
    sharded = jax.device_put(
        jnp.asarray(bits), NamedSharding(mesh, PS(None, "dp"))
    )

    @jax.jit
    def encode(b):
        return (jnp.asarray(enc) @ b) & 1

    parity_sharded = np.asarray(encode(sharded))
    parity_local = np.asarray(encode(jnp.asarray(bits)))
    np.testing.assert_array_equal(parity_sharded, parity_local)

    # and both equal the host GF(256) path
    parity_bytes = jgf.bits_to_bytes(parity_sharded)
    rs = host_gf.ReedSolomon(k, n)
    for c in range(0, V * shard_len, 997):  # spot-check columns
        full = rs.encode([bytes([data[r, c]]) for r in range(k)])
        for p in range(n - k):
            assert parity_bytes[p, c] == full[k + p][0]


def test_sha3_multiblock_matches_hashlib(rng):
    """Multi-block sponge absorption (round 3): any equal length, incl.
    the exact block-boundary edge cases, matches hashlib bit-for-bit."""
    for m in (136, 137, 200, 271, 272, 273, 500, 1024):
        msgs = rng.integers(0, 256, size=(4, m), dtype=np.uint8)
        got = jk.sha3_256_batch(msgs)
        for i in range(4):
            assert bytes(got[i]) == hashlib.sha3_256(bytes(msgs[i])).digest(), m


def test_dataplane_config2_shape_rides_device_path(rng):
    """Config 2's canonical shape (10 nodes, 1 KB payload -> 129-byte
    shards) must use the device data plane (round-2 VERDICT item #5) and
    produce proofs identical to the host path."""
    from hbbft_tpu.ops.jaxops import dataplane as dp
    from hbbft_tpu.ops.merkle import MerkleTree
    from hbbft_tpu.protocols.broadcast import _pack

    k, n = 4, 10  # f=3 -> k = n - 2f
    value = bytes(rng.integers(0, 256, 1000, dtype=np.uint8))
    _, shard_len = dp._pack(value, k)
    assert shard_len > jk.RATE - 2 - 32, "shape must exceed one block"
    assert shard_len <= dp.MAX_DEV_SHARD, "config-2 shape must be device-eligible"
    proofs = dp.encode_and_prove([value], k, n)[0]
    # host reference: same RS + Merkle pipeline
    host_shards = host_gf.ReedSolomon(k, n).encode(list(_pack(value, k)))
    tree = MerkleTree(host_shards)
    for i in range(n):
        want = tree.proof(i)
        assert proofs[i].value == want.value
        assert proofs[i].index == want.index
        assert tuple(proofs[i].path) == tuple(want.path)
        assert proofs[i].root == want.root
        assert want.validate(n)
