"""GF(2^16) Reed-Solomon: the large-validator-set RBC codec.

GF(256) has only 255 distinct Vandermonde evaluation points, so the old
engine cap at 256 nodes was load-bearing: past 255 shards, rows repeat
and decode subsets turn singular.  Networks with > 255 validators now
erasure-code over GF(2^16) (65535 points).  These tests pin:

* field arithmetic + primitivity of poly 0x1100B / generator 2,
* systematic encode/reconstruct roundtrips with adversarial erasure
  patterns — including the index pairs (i, i+255) that are IDENTICAL
  rows over GF(256),
* bit-identity between the numpy codec and the native C++ codec
  (native/sha3_gf.h), which the engine uses for N > 255,
* the Broadcast codec switch (`rs_codec`) and even-shard packing.
"""

import random

import pytest

from hbbft_tpu.ops import gf256
from hbbft_tpu.ops import native as native_ops
from hbbft_tpu.protocols.broadcast import _pack, _unpack


def test_gf16_field_basics():
    exp, log = gf256._tables16()
    # primitivity: generator cycles through all 65535 nonzero elements
    assert len(set(int(x) for x in exp[:65535])) == 65535
    rng = random.Random(0)
    for _ in range(200):
        a = rng.randrange(1, 65536)
        b = rng.randrange(1, 65536)
        ab = gf256.gf16_mul(a, b)
        assert gf256.gf16_mul(ab, gf256.gf16_inv(b)) == a
    assert gf256.gf16_mul(0, 12345) == 0
    assert gf256.gf16_inv(1) == 1


def test_gf16_matmul_matches_scalar():
    import numpy as np

    rng = random.Random(1)
    a = np.array(
        [[rng.randrange(65536) for _ in range(5)] for _ in range(4)],
        dtype=np.uint16,
    )
    b = np.array(
        [[rng.randrange(65536) for _ in range(3)] for _ in range(5)],
        dtype=np.uint16,
    )
    out = gf256.gf16_matmul(a, b)
    for i in range(4):
        for j in range(3):
            acc = 0
            for t in range(5):
                acc ^= gf256.gf16_mul(int(a[i, t]), int(b[t, j]))
            assert int(out[i, j]) == acc


def test_rs16_systematic_and_roundtrip_past_gf256_wall():
    """n=300 > 255: reconstruct from subsets that include (i, i+255)
    pairs — identical encoding rows over GF(256), distinct here."""
    k, n = 86, 300
    rng = random.Random(2)
    size = 8
    data = [bytes(rng.randrange(256) for _ in range(size)) for _ in range(k)]
    rs = gf256.ReedSolomon16(k, n)
    shards = rs.encode(data)
    assert len(shards) == n
    assert shards[:k] == data  # systematic
    # worst-case subset for GF(256): indices 0..44 and 255..295 overlap
    # mod 255 (rows 255+i == rows i over the smaller field)
    subset = {i: shards[i] for i in range(45)}
    subset.update({i: shards[i] for i in range(255, 296)})
    assert len(subset) == 86
    assert rs.reconstruct(subset) == data
    # random erasure patterns
    for _ in range(3):
        idxs = rng.sample(range(n), k)
        assert rs.reconstruct({i: shards[i] for i in idxs}) == data


def test_rs16_native_matches_numpy():
    if not native_ops.available():
        pytest.skip("native data plane unavailable")
    k, n = 12, 280
    rng = random.Random(3)
    size = 10
    data = [bytes(rng.randrange(256) for _ in range(size)) for _ in range(k)]
    rs = gf256.ReedSolomon16(k, n)
    # numpy path explicitly (bypass the native fast path)
    import numpy as np

    sym = np.stack([rs._sym(s) for s in data])
    parity_np = [rs._bytes(p) for p in gf256.gf16_matmul(rs.matrix[k:], sym)]
    native_out = native_ops.rs16_encode(data, n)
    assert native_out is not None
    assert native_out[k:] == parity_np
    idxs = rng.sample(range(n), k)
    subset = {i: native_out[i] for i in idxs}
    nat_rec = native_ops.rs16_reconstruct(subset, k, n)
    sub = rs.matrix[sorted(idxs)[:k]]
    dec = gf256.gf16_mat_inv(sub)
    have = np.stack([rs._sym(subset[i]) for i in sorted(idxs)[:k]])
    np_rec = [rs._bytes(r) for r in gf256.gf16_matmul(dec, have)]
    assert nat_rec == np_rec == data


def test_rs_codec_switch_and_pack_alignment():
    assert isinstance(gf256.rs_codec(3, 10), gf256.ReedSolomon)
    assert isinstance(gf256.rs_codec(86, 255), gf256.ReedSolomon)
    assert isinstance(gf256.rs_codec(86, 256), gf256.ReedSolomon16)
    # even-shard packing for the 2-byte-symbol codec, roundtrip intact
    value = b"x" * 101
    shards = _pack(value, 7, align=2)
    assert all(len(s) % 2 == 0 for s in shards)
    assert _unpack(shards) == value
    assert _unpack(_pack(b"", 5, align=2)) == b""


def test_gf256_reed_solomon_still_rejects_past_255():
    with pytest.raises(AssertionError):
        gf256.ReedSolomon(86, 256)


def test_engine_rbc_decodes_past_255_nodes():
    """The native engine at N=257 rides the GF(2^16) codec: broadcasts
    from a proposer must decode (every decode re-encodes the full
    codeword and re-verifies the Merkle root — a codec bug would fault
    the honest proposer within the first RBC)."""
    from hbbft_tpu import native_engine
    from hbbft_tpu.protocols.queueing_honey_badger import Input

    if not native_engine.available():
        pytest.skip("native engine unavailable")
    nat = native_engine.NativeQhbNet(257, seed=0, batch_size=8, num_faulty=0)
    nat.send_input(0, Input.user("big-n-tx"))
    nat.run(2_000_000)
    assert all(nat.faults(i) == [] for i in range(257))
    nat.close()
