"""HoneyBadger tests — benchmark config 3 shape (16 nodes, batched txns).

Reference analog: upstream ``tests/honey_badger.rs``: every epoch's batch
is identical across correct nodes and eventually contains every correct
node's contribution.
"""

import pytest

from hbbft_tpu.net import NetBuilder, NullAdversary, RandomAdversary, ReorderingAdversary
from hbbft_tpu.protocols.honey_badger import Batch, EncryptionSchedule, HoneyBadger


def build_hb_net(n=4, seed=0, adversary=None, schedule=None, max_future_epochs=3):
    schedule = schedule or EncryptionSchedule.always()
    b = NetBuilder(n, seed=seed).protocol(
        lambda ni, sink, rng: HoneyBadger(
            ni, sink, session_id=b"hb-test", max_future_epochs=max_future_epochs,
            encryption_schedule=schedule,
        )
    )
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


def batches_of(net, nid):
    return [o for o in net.node(nid).outputs if isinstance(o, Batch)]


def run_epochs(net, num_epochs, contribution_fn):
    """Propose per epoch and crank until all correct nodes emit the batch."""
    for epoch in range(num_epochs):
        for nid in net.correct_ids:
            net.send_input(nid, contribution_fn(nid, epoch))
        net.crank_until(
            lambda n: all(len(batches_of(n, i)) > epoch for i in n.correct_ids),
            max_cranks=2_000_000,
        )


@pytest.mark.parametrize("adversary_cls", [NullAdversary, ReorderingAdversary])
def test_single_epoch_agreement(adversary_cls):
    net = build_hb_net(n=4, seed=1, adversary=adversary_cls())
    run_epochs(net, 1, lambda nid, e: [f"tx-{nid}-{i}" for i in range(4)])
    batches = {nid: batches_of(net, nid)[0] for nid in net.correct_ids}
    first = next(iter(batches.values()))
    assert all(b == first for b in batches.values())
    assert len(first.contribution_map()) >= net.node(0).netinfo.num_correct
    for proposer, contrib in first.contribution_map().items():
        assert contrib == [f"tx-{proposer}-{i}" for i in range(4)]
    assert net.correct_faults() == []


def test_multi_epoch_progression():
    net = build_hb_net(n=4, seed=2, adversary=RandomAdversary())
    run_epochs(net, 3, lambda nid, e: {"node": nid, "epoch": e})
    for nid in net.correct_ids:
        bs = batches_of(net, nid)
        assert [b.epoch for b in bs[:3]] == [0, 1, 2]
    ref = batches_of(net, net.correct_ids[0])[:3]
    for nid in net.correct_ids[1:]:
        assert batches_of(net, nid)[:3] == ref
    # Contributions carry the right epoch (no cross-epoch leakage).
    for b in ref:
        for _, contrib in b.contributions:
            assert contrib["epoch"] == b.epoch


@pytest.mark.parametrize(
    "schedule",
    [EncryptionSchedule.never(), EncryptionSchedule.every_nth(2), EncryptionSchedule.tick_tock(1)],
)
def test_encryption_schedules(schedule):
    net = build_hb_net(n=4, seed=3, schedule=schedule)
    run_epochs(net, 2, lambda nid, e: (nid, e))
    ref = batches_of(net, 0)[:2]
    for nid in net.correct_ids[1:]:
        assert batches_of(net, nid)[:2] == ref
    assert net.correct_faults() == []


@pytest.mark.slow
def test_sixteen_nodes_256_tx():
    # Benchmark-config-3 shape: 16 nodes, 256 txns split across proposers.
    net = build_hb_net(n=16, seed=4)
    per_node = 256 // 16
    run_epochs(
        net, 1, lambda nid, e: [f"tx-{nid}-{i}" for i in range(per_node)]
    )
    ref = batches_of(net, 0)[0]
    committed = [tx for _, txs in ref.contributions for tx in txs]
    assert len(committed) >= per_node * net.node(0).netinfo.num_correct
    for nid in net.correct_ids[1:]:
        assert batches_of(net, nid)[0] == ref
    assert net.correct_faults() == []
