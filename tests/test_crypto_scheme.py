"""Threshold-scheme unit tests against the (insecure) scalar suite.

These exercise the suite-generic algebra: share interpolation, signature
combine stability across share subsets, encryption round-trips, bivariate
polynomial symmetry (the DKG invariant), and batch verification with
fault isolation.
"""

import random

import pytest

from hbbft_tpu.crypto.backend import BatchedBackend, EagerBackend, VerifyRequest
from hbbft_tpu.crypto.keys import SecretKey, SecretKeySet
from hbbft_tpu.crypto.poly import BivarPoly, Poly, interpolate, lagrange_coefficients
from hbbft_tpu.crypto.suite import ScalarSuite


@pytest.fixture
def suite():
    return ScalarSuite()


@pytest.fixture
def rng():
    return random.Random(1234)


def test_poly_interpolation(rng, suite):
    m = suite.scalar_modulus
    p = Poly.random(3, rng, m)
    pts = [(x, p.eval(x)) for x in (2, 5, 7, 11)]
    assert interpolate(pts, m) == p.eval(0)
    lam = lagrange_coefficients([1, 4, 6, 10], m)
    acc = sum(lam[i] * p.eval(i + 1) for i in lam) % m
    assert acc == p.eval(0)


def test_sign_combine_stable_across_subsets(rng, suite):
    sks = SecretKeySet.random(2, rng, suite)
    pks = sks.public_keys()
    msg = b"hello threshold world"
    shares = {i: sks.secret_key_share(i).sign(msg) for i in range(7)}
    sig_a = pks.combine_signatures({i: shares[i] for i in (0, 1, 2)})
    sig_b = pks.combine_signatures({i: shares[i] for i in (3, 5, 6)})
    sig_c = pks.combine_signatures(shares)
    assert sig_a.g2 == sig_b.g2 == sig_c.g2
    assert pks.verify_signature(msg, sig_a)
    assert not pks.verify_signature(b"other message", sig_a)


def test_share_verification(rng, suite):
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"doc"
    good = sks.secret_key_share(2).sign(msg)
    assert pks.public_key_share(2).verify_share(msg, good)
    assert not pks.public_key_share(3).verify_share(msg, good)  # wrong index
    assert not pks.public_key_share(2).verify_share(b"doc2", good)  # wrong msg


def test_encrypt_decrypt_roundtrip(rng, suite):
    sks = SecretKeySet.random(2, rng, suite)
    pks = sks.public_keys()
    msg = b"the quick brown fox jumps over the lazy dog"
    ct = pks.public_key().encrypt(msg, rng)
    assert ct.verify()
    shares = {i: sks.secret_key_share(i).decryption_share(ct) for i in (1, 3, 4)}
    for i, sh in shares.items():
        assert pks.public_key_share(i).verify_decryption_share(ct, sh)
    assert pks.combine_decryption_shares(shares, ct) == msg
    # A share from the wrong key fails verification.
    bad = sks.secret_key_share(0).decryption_share(ct)
    assert not pks.public_key_share(5).verify_decryption_share(ct, bad)


def test_regular_keys(rng, suite):
    sk = SecretKey.random(rng, suite)
    pk = sk.public_key()
    sig = sk.sign(b"vote payload")
    assert pk.verify(b"vote payload", sig)
    assert not pk.verify(b"other", sig)
    ct = pk.encrypt(b"dkg row bytes", rng)
    assert sk.decrypt(ct) == b"dkg row bytes"


def test_bivar_poly_symmetry_and_rows(rng, suite):
    m = suite.scalar_modulus
    bp = BivarPoly.random(2, rng, m)
    assert bp.eval(3, 8) == bp.eval(8, 3)
    row5 = bp.row(5)
    assert row5.eval(9) == bp.eval(5, 9)
    # Commitment consistency: committed row(x).eval(y) == committed eval(x, y)
    bc = bp.commitment(suite)
    assert bc.row(5).eval(9) == bc.eval(5, 9)
    assert bc.row(5).eval(9) == suite.g1_generator() * bp.eval(5, 9)
    # Interpolating row values at y=0 across t+1 x-points recovers p(0, y0):
    # node j learns p(i+1, j+1) from t+1 dealers' rows -> interpolate x->p(x, j+1) at 0.
    j = 4
    pts = [(i + 1, bp.eval(i + 1, j + 1)) for i in range(3)]
    assert interpolate(pts, m) == bp.eval(0, j + 1)


def test_batched_backend_matches_eager_and_isolates_faults(rng, suite):
    sks = SecretKeySet.random(2, rng, suite)
    pks = sks.public_keys()
    msg = b"common coin round 7"
    reqs = []
    for i in range(8):
        share = sks.secret_key_share(i).sign(msg)
        reqs.append(VerifyRequest.sig_share(pks.public_key_share(i), msg, share))
    # Corrupt two entries: wrong message and wrong signer index.
    bad1 = sks.secret_key_share(3).sign(b"tampered")
    reqs[3] = VerifyRequest.sig_share(pks.public_key_share(3), msg, bad1)
    reqs[6] = VerifyRequest.sig_share(
        pks.public_key_share(6), msg, sks.secret_key_share(5).sign(msg)
    )
    # Mix in ciphertext + decryption-share requests.
    ct = pks.public_key().encrypt(b"payload", rng)
    reqs.append(VerifyRequest.ciphertext(ct))
    ds = sks.secret_key_share(1).decryption_share(ct)
    reqs.append(VerifyRequest.dec_share(pks.public_key_share(1), ct, ds))
    reqs.append(VerifyRequest.dec_share(pks.public_key_share(2), ct, ds))  # bad

    eager = EagerBackend(suite).verify_batch(reqs)
    batched = BatchedBackend(suite).verify_batch(reqs)
    assert eager == batched
    expected = [True] * 8 + [True, True, False]
    expected[3] = False
    expected[6] = False
    assert batched == expected


def test_bls_elements_survive_pickling():
    """Serde round-trips (Broadcast pickles ciphertexts into RS shards)
    must not corrupt the lazy affine/bytes caches of point elements."""
    import pickle

    from hbbft_tpu.crypto.bls.suite import BLSSuite

    suite = BLSSuite()
    rng = random.Random(5)
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    ct = pks.public_key().encrypt(b"pickled payload", rng)
    ct2 = pickle.loads(pickle.dumps(ct))
    assert ct2.to_bytes() == ct.to_bytes()
    sig = sks.secret_key_share(0).sign(b"msg")
    sig2 = pickle.loads(pickle.dumps(sig))
    assert sig2.g2 == sig.g2 and sig2.g2.to_bytes() == sig.g2.to_bytes()
    # Pickled points still verify.
    from hbbft_tpu.crypto.backend import EagerBackend, VerifyRequest

    ok = EagerBackend(suite).verify_batch(
        [VerifyRequest.sig_share(pks.public_key_share(0), b"msg", sig2)]
    )
    assert ok == [True]


def test_batch_affine_edge_cases():
    """Montgomery batch inversion: duplicates, identity, cached, garbage."""
    from hbbft_tpu.crypto.bls.suite import BLSSuite

    suite = BLSSuite()
    g = suite.g2_generator()
    p1 = g * 5
    p2 = g * 9
    ident = suite.g2_identity()
    dup = p1  # same object twice in the list
    cached = g * 7
    cached.affine()  # pre-warm
    garbage = "not a point"
    suite.batch_affine([p1, dup, ident, cached, garbage, p2])
    # All finite points now have exact affine forms.
    for p in (p1, p2, cached):
        x, y = p.affine()
        import hbbft_tpu.crypto.bls.curve as oc

        assert oc.g2_on_curve(x, y)
    assert ident.affine() is None
    # Values agree with the lazy path.
    q = suite.g2_generator() * 5
    assert p1.affine() == q.affine()


def test_native_kem_matches_python():
    """The native scalar-suite KEM (hbe_kem_encrypt/decrypt) is
    byte-identical to the pure-Python path: same ciphertext for the same
    rng draw, same plaintext back, same rejection of tampered
    ciphertexts."""
    import random

    from hbbft_tpu.crypto import keys as K
    from hbbft_tpu.crypto.suite import ScalarSuite

    suite = ScalarSuite()
    kem = K._scalar_kem(suite)
    if kem is None:
        import pytest

        pytest.skip("native engine unavailable")

    sk = K.SecretKey.random(random.Random(1), suite)
    pk = sk.public_key()
    for trial in range(4):
        msg = bytes([trial]) * (32 * (trial + 1))
        r = random.Random(100 + trial).randrange(1, suite.scalar_modulus)
        ct_native = kem.encrypt(pk, msg, r)
        # pure-Python reference with the same r
        u = suite.g1_generator() * r
        from hbbft_tpu.utils import canonical_bytes, kdf_stream, xor_bytes

        mask = kdf_stream(
            canonical_bytes(b"kem", (pk.g1 * r).to_bytes()), len(msg)
        )
        v = xor_bytes(msg, mask)
        w = suite.hash_to_g2(K._ciphertext_hash_input(u, v)) * r
        assert ct_native.u == u and ct_native.v == v and ct_native.w == w
        # decrypt round-trips on both paths
        assert kem.decrypt(sk, ct_native) == msg
        ct_py = K.Ciphertext(u, v, w, suite)
        assert sk.decrypt(ct_py) == msg
        # tampered v: both paths reject
        bad = K.Ciphertext(u, b"\x00" + v[1:], w, suite)
        assert sk.decrypt(bad) is None
        K._KEM_CACHE[suite.name] = None  # force Python path
        try:
            assert sk.decrypt(bad) is None
            assert sk.decrypt(ct_py) == msg
        finally:
            K._KEM_CACHE.pop(suite.name, None)


def test_encrypt_rng_stream_unchanged_by_fast_path():
    """PublicKey.encrypt draws exactly one randrange from the caller's
    rng regardless of which path serves it — equivalence tests between
    Python and native nets depend on identical rng consumption."""
    import random

    from hbbft_tpu.crypto import keys as K
    from hbbft_tpu.crypto.suite import ScalarSuite

    suite = ScalarSuite()
    sk = K.SecretKey.random(random.Random(2), suite)
    pk = sk.public_key()
    r1, r2 = random.Random(7), random.Random(7)
    ct_a = pk.encrypt(b"x" * 64, r1)
    K._KEM_CACHE[suite.name] = None  # force Python path
    try:
        ct_b = pk.encrypt(b"x" * 64, r2)
    finally:
        K._KEM_CACHE.pop(suite.name, None)
    assert r1.getstate() == r2.getstate()
    assert (ct_a.u, ct_a.v, ct_a.w) == (ct_b.u, ct_b.v, ct_b.w)


def test_native_combine_matches_pure_and_rejects_oversized_indices():
    """The scalar combine fast path must be value-identical to the pure
    Lagrange path, and indices that would TRUNCATE in a ctypes c_int32
    array (no OverflowError — verified behavior) must fall back to the
    pure path instead of combining at a silently wrong point."""
    import os
    import random

    from hbbft_tpu.crypto import keys as K
    from hbbft_tpu.crypto.suite import ScalarSuite

    suite = ScalarSuite()
    rng = random.Random(9)
    sks = K.SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"combine-parity"

    def pure(fn, *a):
        os.environ["HBBFT_TPU_DKG_BATCH"] = "0"
        try:
            return fn(*a)
        finally:
            del os.environ["HBBFT_TPU_DKG_BATCH"]

    # ordinary indices: fast == pure
    shares = {i: sks.secret_key_share(i).sign(msg) for i in (0, 1)}
    assert (
        pks.combine_signatures(shares).to_bytes()
        == pure(pks.combine_signatures, shares).to_bytes()
    )
    ct = pks.public_key().encrypt(b"plain" * 20, rng)
    dshares = {i: sks.secret_key_share(i).decryption_share(ct) for i in (0, 1)}
    assert pks.combine_decryption_shares(dshares, ct) == pure(
        pks.combine_decryption_shares, dshares, ct
    )

    # an index past int32: x = i + 1 would truncate in the C call; the
    # fast path must defer so both paths agree ((i + 1) % r Lagrange).
    big = 2**32 + 2
    shares_big = {
        big: sks.secret_key_share(big).sign(msg),
        1: sks.secret_key_share(1).sign(msg),
    }
    assert (
        pks.combine_signatures(shares_big).to_bytes()
        == pure(pks.combine_signatures, shares_big).to_bytes()
    )
    dshares_big = {
        big: sks.secret_key_share(big).decryption_share(ct),
        1: dshares[1],
    }
    assert pks.combine_decryption_shares(dshares_big, ct) == pure(
        pks.combine_decryption_shares, dshares_big, ct
    )
