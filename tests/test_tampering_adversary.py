"""TamperingAdversary: faulty nodes send valid-type/wrong-content streams.

Upstream analog: ``tamper`` in ``tests/net/adversary.rs`` (SURVEY.md §4)
— rewrite messages originating from faulty nodes.  The assertions are
the upstream ones: correct nodes still terminate and agree, correct
nodes are never faulted, and the fault logs pin (only) faulty senders.
"""

import pytest

from hbbft_tpu.net import NetBuilder, TamperingAdversary
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.honey_badger import HoneyBadger
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger
from hbbft_tpu.protocols.threshold_sign import ThresholdSign

SEEDS = [101, 202, 303, 404, 505]


def faulty_fault_ids(net):
    """ids faulted by correct nodes (should be a subset of faulty_ids)."""
    return {f.node_id for n in net.nodes.values() for f in n.faults}


@pytest.mark.parametrize("seed", SEEDS)
def test_threshold_sign_under_tampering(seed):
    adv = TamperingAdversary(tamper_p=1.0)
    net = (
        NetBuilder(7, seed=seed)
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, b"tamper-doc", sink))
        .adversary(adv)
        .build()
    )
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    outs = [net.node(i).outputs[0] for i in net.correct_ids]
    assert all(o == outs[0] for o in outs)
    pks = net.node(0).netinfo.public_key_set
    assert pks.verify_signature(b"tamper-doc", outs[0])
    assert net.correct_faults() == []
    # every fault recorded names a faulty node (evidence is best-effort:
    # a node that terminates before a tampered share arrives correctly
    # ignores it, so not every seed records faults)
    assert faulty_fault_ids(net) <= set(net.faulty_ids)
    assert adv.tampered_count > 0


@pytest.mark.parametrize("seed", SEEDS)
def test_broadcast_under_tampering(seed):
    """Faulty (non-proposer) nodes corrupt Echo proofs/Ready roots; the
    proposer's value must still deliver identically everywhere."""
    net = (
        NetBuilder(10, seed=seed)
        .protocol(lambda ni, sink, rng: Broadcast(ni, 0))
        .adversary(TamperingAdversary(tamper_p=1.0))
        .build()
    )
    net.send_input(0, b"tamper-payload-" + bytes([seed % 256]))
    net.run_to_termination()
    outs = [net.node(i).outputs[0] for i in net.correct_ids]
    assert all(o == outs[0] for o in outs)
    assert outs[0] == b"tamper-payload-" + bytes([seed % 256])
    assert net.correct_faults() == []
    assert faulty_fault_ids(net) <= set(net.faulty_ids)


@pytest.mark.parametrize("seed", SEEDS)
def test_honey_badger_under_tampering(seed):
    net = (
        NetBuilder(4, seed=seed)
        .num_faulty(1)
        .protocol(lambda ni, sink, rng: HoneyBadger(ni, sink))
        .adversary(TamperingAdversary(tamper_p=0.5))
        .build()
    )
    net.broadcast_input(lambda nid: [f"tx-{nid}"])
    net.crank_until(
        lambda n: all(len(n.node(i).outputs) >= 1 for i in n.correct_ids),
        max_cranks=400_000,
    )
    # second epoch under continued tampering
    net.broadcast_input(lambda nid: [f"tx2-{nid}"])
    net.crank_until(
        lambda n: all(len(n.node(i).outputs) >= 2 for i in n.correct_ids),
        max_cranks=400_000,
    )
    for epoch in range(2):
        batches = [net.node(i).outputs[epoch] for i in net.correct_ids]
        assert all(b == batches[0] for b in batches), f"epoch {epoch} diverged"
    # every correct proposer's contribution committed in epoch 0
    cm = net.node(net.correct_ids[0]).outputs[0].contribution_map()
    for nid in net.correct_ids:
        if nid in cm:
            assert cm[nid] == [f"tx-{nid}"]
    assert net.correct_faults() == []
    assert faulty_fault_ids(net) <= set(net.faulty_ids)


@pytest.mark.parametrize("seed", SEEDS[:3])
def test_queueing_honey_badger_under_tampering(seed):
    """Full stack (QHB -> DHB -> HB) with a tampering faulty validator."""
    net = (
        NetBuilder(4, seed=seed)
        .num_faulty(1)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(ni, sink, batch_size=8)
        )
        .adversary(TamperingAdversary(tamper_p=0.5))
        .build()
    )
    txns = {nid: [f"txn-{nid}-{k}" for k in range(3)] for nid in net.correct_ids}
    for nid, ts in txns.items():
        for t in ts:
            net.send_input(nid, t)

    def committed(n, nid):
        out = []
        for b in n.node(nid).outputs:
            for _, contrib in b.contributions:
                if isinstance(contrib, (list, tuple)):
                    out.extend(contrib)
        return out

    want = sorted(t for ts in txns.values() for t in ts)
    net.crank_until(
        lambda n: all(
            sorted(committed(n, i)) == want for i in n.correct_ids
        ),
        max_cranks=400_000,
    )
    assert net.correct_faults() == []
    assert faulty_fault_ids(net) <= set(net.faulty_ids)


def test_tampering_actually_tampers():
    """Meta-check: the adversary rewrote a meaningful number of messages
    (guards against the tamper dispatch silently matching nothing)."""
    adv = TamperingAdversary(tamper_p=1.0)
    net = (
        NetBuilder(7, seed=1)
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, b"d", sink))
        .adversary(adv)
        .build()
    )
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    assert adv.tampered_count > 0


@pytest.mark.parametrize("seed", SEEDS)
def test_replay_adversary_on_honey_badger(seed):
    """RandomAdversary with replay enabled, across seeds and a deeper
    stack than the single monkeypatched ThresholdSign run (VERDICT round
    1, weak #8): replayed duplicates must neither break agreement nor
    get correct nodes faulted."""
    from hbbft_tpu.net import NetBuilder, RandomAdversary

    net = (
        NetBuilder(4, seed=seed)
        .num_faulty(1)
        .protocol(lambda ni, sink, rng: HoneyBadger(ni, sink))
        .adversary(RandomAdversary(replay_p=0.4))
        .build()
    )
    net.broadcast_input(lambda nid: [f"rp-{nid}"])
    net.crank_until(
        lambda n: all(len(n.node(i).outputs) >= 1 for i in n.correct_ids),
        max_cranks=400_000,
    )
    batches = [net.node(i).outputs[0] for i in net.correct_ids]
    assert all(b == batches[0] for b in batches)
    assert net.correct_faults() == []
    assert faulty_fault_ids(net) <= set(net.faulty_ids)


@pytest.mark.parametrize("seed", SEEDS[:3])
def test_replay_adversary_on_threshold_sign(seed):
    from hbbft_tpu.net import NetBuilder, RandomAdversary

    net = (
        NetBuilder(7, seed=seed)
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, b"rp-doc", sink))
        .adversary(RandomAdversary(replay_p=0.5))
        .build()
    )
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    outs = [net.node(i).outputs[0] for i in net.correct_ids]
    assert all(o == outs[0] for o in outs)
    assert net.correct_faults() == []


@pytest.mark.parametrize("seed", [31, 32, 33, 34, 35])
def test_era_change_under_tampering(seed):
    """A full DHB era change (votes -> embedded DKG -> restart) with a
    tampering faulty validator rewriting its outgoing streams (round-3
    VERDICT item #7): correct nodes must complete the era change and
    agree batch-for-batch; fault logs must only name faulty ids; no
    raise paths."""
    from hbbft_tpu.protocols.dynamic_honey_badger import Change, DhbBatch
    from hbbft_tpu.protocols.queueing_honey_badger import Input

    net = (
        NetBuilder(4, seed=seed)
        .num_faulty(1)
        .max_cranks(3_000_000)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(ni, sink, batch_size=8)
        )
        .adversary(TamperingAdversary(tamper_p=0.5))
        .build()
    )
    # vote out the last CORRECT validator (id 2), keeping 3 >= 3f+1
    # impossible at f=1... so instead vote out the FAULTY validator (3):
    # the era change must complete even though the departing node is the
    # tamperer.
    keep = dict(net.node(0).netinfo.public_key_map)
    keep.pop(net.faulty_ids[0])
    change = Change.node_change(keep)
    for nid in net.correct_ids:
        net.send_input(nid, Input.change(change))

    def batches(n, nid):
        return [o for o in n.node(nid).outputs if isinstance(o, DhbBatch)]

    def change_complete(n):
        return all(
            any(b.change.kind == "complete" for b in batches(n, i))
            for i in n.correct_ids
        )

    for r in range(10):
        if change_complete(net):
            break
        for nid in net.correct_ids:
            net.send_input(nid, Input.user(f"era-tx-{r}-{nid}"))
        want = r + 1
        net.crank_until(
            lambda n, w=want: all(
                len(batches(n, i)) >= w for i in n.correct_ids
            ),
            max_cranks=3_000_000,
        )
    assert change_complete(net), "era change did not complete under tampering"
    # all correct nodes agree on the whole batch sequence (common prefix)
    seqs = {
        i: [
            (b.era, b.epoch, b.contributions, b.change.kind)
            for b in batches(net, i)
        ]
        for i in net.correct_ids
    }
    shortest = min(len(s) for s in seqs.values())
    first = next(iter(seqs.values()))[:shortest]
    assert all(s[:shortest] == first for s in seqs.values())
    # the new era actually started and the departed (faulty) node is out
    eras = {net.node(i).protocol.dhb.era for i in net.correct_ids}
    assert eras == {1}, eras
    new_sets = {
        tuple(net.node(i).protocol.dhb._netinfo.all_ids)
        for i in net.correct_ids
    }
    assert new_sets == {tuple(sorted(keep))}
    # fault logs of correct nodes may only name faulty ids
    assert net.correct_faults() == []
    assert faulty_fault_ids(net) <= set(net.faulty_ids)
