"""bench.py battery-sweep parser against REAL battery row shapes.

Round-4 verdict weak #1: the parser read ``shares``/``value`` from the
top level of each row, but the battery writes them nested under
``results[]`` — executed against the repo's own BATTERY_r04.jsonl it
returned {} and BENCH_r04.json silently lost the sweep.  These tests
feed the parser verbatim r04 lines (nested), r03-style flat lines, and
the advisor's 0.0-rate edge case.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _battery_sweep_from_lines, _latest_battery_sweep

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Verbatim rows from BATTERY_r04.jsonl (trimmed to the fields the
# battery actually wrote — the full lines also carry argv/keccak keys).
R04_LINES = [
    json.dumps({"step": "probe", "tpu": True, "note": "tpu"}),
    json.dumps(
        {
            "step": "bench_flush_512",
            "rc": 0,
            "wall_s": 1303.7,
            "results": [
                {
                    "metric": "bls_sig_share_verifies_per_sec_per_chip",
                    "value": 624.77,
                    "unit": "verifies/sec",
                    "vs_baseline": 0.625,
                    "shares": 512,
                    "rates_by_batch": {"512": 624.77},
                    "device": "tpu",
                }
            ],
        }
    ),
    json.dumps(
        {
            "step": "bench_flush_2048",
            "rc": 0,
            "results": [{"value": 1224.89, "shares": 2048, "device": "tpu"}],
        }
    ),
    json.dumps(
        {
            "step": "bench_flush_10240_chunk2048",
            "rc": 0,
            "results": [{"value": 1516.2, "shares": 10240, "device": "tpu"}],
        }
    ),
]


def test_nested_results_rows_parse():
    sweep = _battery_sweep_from_lines(R04_LINES, "BATTERY_r04.jsonl")
    assert sweep["source"] == "BATTERY_r04.jsonl"
    assert sweep["rates"] == {"512": 624.8, "2048": 1224.9, "10240": 1516.2}


def test_flat_rows_still_parse():
    lines = [
        json.dumps({"step": "bench_flush_512", "shares": 512, "value": 414.0}),
        json.dumps({"step": "probe", "tpu": True}),
    ]
    sweep = _battery_sweep_from_lines(lines, "BATTERY_r03.jsonl")
    assert sweep["rates"] == {"512": 414.0}


def test_zero_rate_surfaces_not_dropped():
    # A 0.0 rate is a regression signal, not a missing value.
    lines = [
        json.dumps({"step": "bench_flush_512", "shares": 512, "value": 0.0})
    ]
    sweep = _battery_sweep_from_lines(lines, "x")
    assert sweep["rates"] == {"512": 0.0}


def test_non_flush_and_garbage_rows_skipped():
    lines = [
        "not json at all",
        json.dumps({"step": "config5_firehose", "results": [{"shares": 1, "value": 2}]}),
    ]
    assert _battery_sweep_from_lines(lines, "x") == {}


def test_later_rows_win():
    lines = [
        json.dumps({"step": "bench_flush_512", "results": [{"shares": 512, "value": 100.0}]}),
        json.dumps({"step": "bench_flush_512_rerun", "results": [{"shares": 512, "value": 200.0}]}),
    ]
    sweep = _battery_sweep_from_lines(lines, "x")
    assert sweep["rates"] == {"512": 200.0}


def test_repo_battery_file_yields_sweep():
    """The committed BATTERY_r04.jsonl itself must produce >=3 sizes —
    executing the parser against the repo's real artifact is the check
    the round-4 fix never had."""
    path = os.path.join(REPO, "BATTERY_r04.jsonl")
    with open(path) as fh:
        sweep = _battery_sweep_from_lines(fh.readlines(), "BATTERY_r04.jsonl")
    assert len(sweep.get("rates", {})) >= 3, sweep
    assert sweep["rates"]["10240"] == 1516.2


def test_latest_battery_sweep_reads_repo():
    # Newest battery by mtime; an in-flight round's file may hold only
    # a probe row (steps append as they complete), so {} is legitimate
    # here — the >=3-sizes bar is pinned on the committed r04 artifact
    # above, this only checks the end-to-end path returns a sane shape.
    sweep = _latest_battery_sweep()
    assert sweep == {} or len(sweep["rates"]) >= 1
