"""Traffic plane (ISSUE 6 acceptance surface).

Unit tier: client-fleet determinism, bounded-memory latency accounting
(fixed bucket array + capped in-flight map), mempool dedup/overflow/
pacing, WAN link shapes, injector→Metrics wiring.  Cluster tier: a
paced open-loop run on an N=4 TCP cluster commits every admitted
transaction exactly once (no loss, no dups) on BOTH node impls; a
deterministic presubmitted workload commits byte-identical streams
across the Python and native arms; a kill/restart drill where the
client resubmits in-flight transactions still yields an exactly-once
committed stream (duplicate suppression under churn).

Budget on the 1-core box: cluster phases are single-digit seconds each
with the standard 45 s caps; whole default tier ~15 s warm (CLAUDE.md
"traffic tier").  No jax/XLA involvement.
"""

from __future__ import annotations

import random
import time

import pytest

from hbbft_tpu.traffic import (
    ClientFleet,
    LatencyHistogram,
    LatencyRecorder,
    Mempool,
    TrafficDriver,
    txn_id_of,
)
from hbbft_tpu.transport import (
    FaultInjector,
    LinkFaults,
    LocalCluster,
    wan_profile,
)
from hbbft_tpu.utils import serde
from hbbft_tpu.utils.metrics import Metrics

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 2 s


def _lib_or_skip():
    from hbbft_tpu import native_engine

    lib = native_engine.get_lib()
    if lib is None:
        pytest.skip("native engine unavailable (no compiler?)")
    return lib


def _stream_txns(cluster, nid):
    """All transactions in node ``nid``'s committed stream, in order."""
    out = []
    for b in cluster.batches(nid):
        for _proposer, contrib in b.contributions:
            if isinstance(contrib, (list, tuple)):
                out.extend(t for t in contrib if isinstance(t, str))
    return out


def batch_keys(cluster, nid):
    return [
        (b.era, b.epoch, serde.dumps(b.contributions))
        for b in cluster.batches(nid)
    ]


def _wait_streams_cover(c, nodes, expect):
    """drain() returns on FIRST sighting of each commit (some node), so
    a lagging node's stream can still be a prefix — wait until every
    listed node's committed stream covers ``expect`` before asserting
    over per-node streams."""
    assert c.wait(
        lambda cl: all(
            expect <= {txn_id_of(t) for t in _stream_txns(cl, i)}
            for i in nodes
        ),
        EPOCH_TIMEOUT_S,
    ), "lagging node never caught up"


# ---------------------------------------------------------------------------
# clients
# ---------------------------------------------------------------------------


def test_client_fleet_deterministic_and_attributable():
    a = ClientFleet(4, 10.0, seed=7)
    b = ClientFleet(4, 10.0, seed=7)
    wa, wb = a.take(60), b.take(60)
    assert wa == wb  # same seed -> identical stream
    assert ClientFleet(4, 10.0, seed=8).take(60) != wa
    ts = [t for t, _, _, _ in wa]
    assert ts == sorted(ts)  # merged in arrival order
    ids = [tid for _, _, tid, _ in wa]
    assert len(ids) == len(set(ids))  # (client, seq) ids are unique
    for _, cid, tid, txn in wa:
        assert tid == txn == f"c{cid}." + tid.split(".")[1]
        assert txn_id_of(txn) == tid
    # fixed-rate arrivals are exactly periodic per client
    f = ClientFleet(2, 5.0, seed=0, arrival="fixed")
    w = f.take(10)
    assert [t for t, _, _, _ in w] == pytest.approx(
        [0.2, 0.2, 0.4, 0.4, 0.6, 0.6, 0.8, 0.8, 1.0, 1.0]
    )
    # payload padding is attributable back to the same id
    p = ClientFleet(1, 1.0, seed=1, payload_len=32).take(1)[0]
    assert len(p[3]) > len(p[2]) and txn_id_of(p[3]) == p[2]


# ---------------------------------------------------------------------------
# latency accounting: bounded memory, honest quantiles
# ---------------------------------------------------------------------------


def test_histogram_fixed_memory_and_quantiles():
    h = LatencyHistogram()
    nbuckets = len(h)
    assert h.quantile(0.5) == 0.0  # empty
    rng = random.Random(42)
    vals = [rng.uniform(0.001, 1.0) for _ in range(10_000)]
    for v in vals:
        h.observe(v)
    assert len(h) == nbuckets  # fixed bucket array, no growth
    assert h.count == 10_000 and h.max == max(vals) and h.min == min(vals)
    vs = sorted(vals)
    for q in (0.5, 0.9, 0.99):
        exact = vs[int(q * len(vs)) - 1]
        assert abs(h.quantile(q) - exact) / exact < 0.10  # ~7% buckets
    assert h.quantile(1.0) <= h.max
    # out-of-range values clamp into the edge buckets, never explode
    h.observe(0.0)
    h.observe(1e9)
    assert len(h) == nbuckets and h.max == 1e9


def test_recorder_inflight_bounded_and_first_sighting():
    r = LatencyRecorder(max_inflight=10)
    for i in range(15):
        r.submit(f"t{i}", 0.0)
    assert r.inflight() == 10 and r.untracked == 5
    assert r.submit("t0", 99.0) is False  # resubmit keeps original clock
    dt = r.commit("t0", 2.5)
    assert dt == 2.5 and r.committed == 1
    assert r.commit("t0", 3.0) is None  # second sighting: not clocked
    assert r.commit("never-seen", 1.0) is None
    r.drop("t1")
    assert r.dropped == 1 and r.inflight() == 8
    m = Metrics()
    r.export(m)
    assert m.summaries["traffic.latency_s"].count == 1
    assert m.gauges["traffic.latency_s.inflight"] == 8


# ---------------------------------------------------------------------------
# mempool: dedup, drop-oldest overflow, pacing
# ---------------------------------------------------------------------------


def test_mempool_dedup_overflow_pacing():
    released, dropped = [], []
    m = Metrics()
    mp = Mempool(
        released.append, cap=5, round_txns=2, ahead=1,
        committed_cache=4, metrics=m, on_drop=dropped.append,
    )
    assert mp.admit("a", "a-txn") and not mp.admit("a", "a-txn")
    assert m.counters["traffic.dup_suppressed"] == 1
    for x in "bcdef":
        mp.admit(x, x)
    # cap 5: admitting "f" shed the oldest ("a")
    assert len(mp) == 5 and dropped == ["a"]
    assert m.counters["traffic.mempool_overflow"] == 1
    # pacing: committed=0 -> (0+1)*2 = 2 released
    assert mp.pace(0) == 2 and released == ["b", "c"]
    assert mp.pace(0) == 0  # budget spent
    assert mp.pace(1) == 2 and released == ["b", "c", "d", "e"]
    # released-but-uncommitted ids are still dup-suppressed
    assert not mp.admit("b", "b")
    mp.mark_committed(["b", "c"])
    assert not mp.admit("b", "b")  # now suppressed by the committed LRU
    assert [t for t, _ in mp.inflight_released()] == ["d", "e"]
    # a committed id that was still queued is tombstoned, never released
    mp.mark_committed(["f"])
    assert mp.pace(2) == 0 and len(mp) == 0  # "f" skipped as a tombstone
    # node restart: committed count goes backwards -> budget rebases
    mp.admit("g", "g")
    assert mp.pace(0) == 1 and released[-1] == "g"
    # committed LRU is bounded and evictions are counted
    mp.mark_committed([f"z{i}" for i in range(10)])
    assert m.counters["traffic.committed_evicted"] > 0


# ---------------------------------------------------------------------------
# WAN link shapes + injector metrics wiring
# ---------------------------------------------------------------------------


def test_wan_profile_shapes_deterministic():
    assert wan_profile("clean") is None
    with pytest.raises(ValueError):
        wan_profile("marsnet")
    lf = wan_profile("wan")
    # pure function of the uniform draw: same u -> same delay
    assert lf.wan_delay(0.37) == lf.wan_delay(0.37) > lf.latency_s
    for dist in ("uniform", "exp", "lognormal"):
        d = LinkFaults(latency_s=0.01, jitter_s=0.005, jitter_dist=dist)
        lo, hi = d.wan_delay(0.05), d.wan_delay(0.95)
        assert 0.01 <= lo < hi  # monotone in u, floored at the base
    assert LinkFaults().wan_delay(0.5) == 0.0  # shape off by default


def test_wan_injector_fifo_and_stats():
    inj = FaultInjector(seed=5, default=wan_profile("wan"))
    inj.start()
    last = 0.0
    for k in range(200):
        plan = inj.on_send(0, 1, b"frame-%d" % k)
        assert len(plan) == 1 and plan[0][0] >= wan_profile("wan").latency_s
        rel = inj._wan_last[(0, 1)]
        assert rel >= last  # stream order preserved (FIFO clamp)
        last = rel
    assert inj.stats.shaped == 200 and inj.stats.dropped == 0
    m = Metrics()
    inj.export_metrics(m)
    assert m.gauges["faults.shaped"] == 200


def test_wan_shape_composes_with_reorder_fault():
    """The reorder fault (delay_p) must keep reordering when a WAN
    shape is on: the reorder delay rides ON TOP of the monotone WAN
    release clamp (folding it into the clamp would silently FIFO the
    fault away while still counting 'delayed')."""
    lf = LinkFaults(latency_s=0.01, delay_p=0.3, delay_s=(0.5, 0.5))
    inj = FaultInjector(seed=7, default=lf)
    inj.start()
    rel = []
    t0 = time.monotonic()
    for k in range(50):
        plan = inj.on_send(0, 1, b"f%d" % k)
        rel.append((time.monotonic() - t0) + plan[0][0])
    assert inj.stats.delayed > 0 and inj.stats.shaped == 50
    # delay-faulted frames (+0.5 s) are overtaken by later clean ones
    assert any(
        rel[i] > rel[j] for i in range(len(rel)) for j in range(i + 1, len(rel))
    ), "WAN shape FIFO'd the reorder fault away"


def test_fault_stats_reach_cluster_prometheus_dump():
    """Satellite: FaultInjector totals show up in the same Prometheus
    dump as the transport/cluster counters via merged_metrics()."""
    inj = FaultInjector(seed=1, default=LinkFaults(drop_p=1.0))
    assert inj.on_send(0, 1, b"abc") == []  # dropped
    cluster = LocalCluster(4, seed=2, injector=inj)  # never started
    text = cluster.merged_metrics().prometheus_text()
    assert 'hbbft_gauge{name="faults.dropped"} 1' in text


# ---------------------------------------------------------------------------
# acceptance: paced open-loop, exactly-once, both node impls
# ---------------------------------------------------------------------------


def _run_open_loop(impl):
    fleet = ClientFleet(8, 5.0, seed=3)  # 40 offered tps across 8 users
    with LocalCluster(4, seed=17, node_impl=impl) as c:
        d = TrafficDriver(c, fleet)
        res = d.run_open_loop(2.0, drain_timeout_s=EPOCH_TIMEOUT_S)
        assert res["outstanding"] == 0, res
        assert res["admitted"] == res["arrived"] > 20  # fresh ids: no dups
        assert res["committed"] == res["admitted"], res
        assert d.recorder.hist.count == res["committed"]
        assert d.recorder.hist.quantile(0.5) > 0.0
        # exactly-once in EVERY node's committed stream
        expect = set(
            tid for _, _, tid, _ in ClientFleet(8, 5.0, seed=3).take(
                res["admitted"]
            )
        )
        _wait_streams_cover(c, range(4), expect)
        for i in range(4):
            txns = _stream_txns(c, i)
            assert len(txns) == len(set(txns)), f"dup commit on node {i}"
            assert set(map(txn_id_of, txns)) == expect  # no loss either
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("traffic.mempool_overflow", 0) == 0
        # the latency summary rides the same Prometheus dump
        assert 'hbbft_summary{name="traffic.latency_s"' in m.prometheus_text()


def test_open_loop_exactly_once_python():
    _run_open_loop("python")


def test_open_loop_exactly_once_native():
    _lib_or_skip()
    _run_open_loop("native")


# ---------------------------------------------------------------------------
# acceptance: deterministic workload is byte-identical across arms
# ---------------------------------------------------------------------------


def test_deterministic_workload_byte_identical_across_arms():
    _lib_or_skip()
    streams = {}
    for impl in ("python", "native"):
        fleet = ClientFleet(6, 4.0, seed=11)
        c = LocalCluster(4, seed=23, node_impl=impl)
        d = TrafficDriver(c, fleet)
        ids = d.run_presubmit(32)
        assert len(ids) == 32
        with c:
            assert d.drain(EPOCH_TIMEOUT_S), d.outstanding()
            _wait_streams_cover(c, range(4), set(ids))
            keys = batch_keys(c, 0)
            for i in (1, 2, 3):
                other = batch_keys(c, i)
                k = min(len(keys), len(other))
                assert other[:k] == keys[:k]  # agreement inside the arm
        # cut at the last batch that carries traffic (the arms race
        # ahead by different numbers of trailing empty epochs)
        last = max(
            i for i, b in enumerate(c.batches(0))
            if any(contrib for _, contrib in b.contributions)
        )
        streams[impl] = keys[: last + 1]
    assert streams["python"] == streams["native"]


# ---------------------------------------------------------------------------
# satellite: duplicate suppression under churn (kill/restart + resubmit)
# ---------------------------------------------------------------------------


def test_kill_restart_resubmit_exactly_once():
    """A client whose home node dies resubmits its in-flight
    transactions to a survivor: after the restart the committed stream
    still contains every admitted transaction EXACTLY once — the
    resubmit path is covered by the cluster-wide committed window, and
    the survivors are given time to resolve the dead node's last
    proposals before the resubmit decision is taken."""
    fleet = ClientFleet(8, 6.0, seed=13)
    with LocalCluster(4, seed=31) as c:
        d = TrafficDriver(c, fleet)
        admitted = []

        def offer(until_s):
            t0 = time.monotonic()
            while True:
                el = time.monotonic() - t0
                if el >= until_s:
                    break
                for _vt, cid, tid, txn in fleet.take_until(el, limit=500):
                    if d._admit(cid, tid, txn, time.monotonic()):
                        admitted.append(tid)
                d.pace_all()
                d.poll_commits()
                time.sleep(0.02)

        offer(1.0)
        # park a few more transactions on node 3 and release them, so
        # the kill strikes with real in-flight traffic to resubmit
        extra = [a for a in fleet.take(64) if a[1] % 4 == 3][:6]
        now = time.monotonic()
        for _vt, cid, tid, txn in extra:
            if d._admit(cid, tid, txn, now):
                admitted.append(tid)
        d.pace_all()
        inflight = d.mempools[3].inflight_released()
        assert inflight  # the drill is not vacuous
        c.kill(3)
        # let the survivors resolve any epoch the dead node's proposals
        # were in flight for, THEN observe commits and resubmit
        target = c.batch_count(0) + 3
        assert c.wait(
            lambda cl: min(cl.batch_count(i) for i in (0, 1, 2)) >= target,
            EPOCH_TIMEOUT_S,
        )
        d.poll_commits()
        d.resubmit_lost(3, 0)
        c.restart(3)
        assert d.drain(EPOCH_TIMEOUT_S), d.outstanding()
        assert len(admitted) == len(set(admitted))
        _wait_streams_cover(c, (0, 1, 2), set(admitted))
        for i in (0, 1, 2):
            txns = _stream_txns(c, i)
            assert len(txns) == len(set(txns)), f"dup commit on node {i}"
            assert set(map(txn_id_of, txns)) == set(admitted)
        assert d.recorder.committed == len(admitted)
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
