"""Crypto plane as a process (round 18): the RPC boundary tier.

What this file pins, mirroring the round-13 in-thread tier one level
out:

* **Verdict identity through the socket**: an :class:`RpcServiceClient`
  returns exactly the local backend's verdicts — good, bad, and
  unserializable-junk requests included (the deferred-verification
  invariant survives the serialization boundary).
* **Framing fuzz parity** (the transport corrupt-frame tier's rules on
  the crypto kind set): corrupted/truncated/oversized/wrong-plane
  frames kill only the offending CONNECTION — the server keeps serving
  fresh dials, and a client fed garbage falls back locally instead of
  wedging its flush.
* **batches_sha identity** of the rpc-service vs in-thread-service vs
  inline arms at N=4 seed 0 (both node impls for the RPC arm).
* **SIGKILL-mid-flush drill**: clients fall back with no lost or
  duplicated fault attributions and re-attach when a new service
  process comes up on the old port (both impls, plus the
  process-per-node runtime via ``ProcCluster.kill_service``).
* **Fault-multiset parity at the RPC boundary**: the seeded
  TamperingAdversary sim commits identical batches AND identical fault
  logs whether shares verify in scalar C or through the service
  process.

Batched CPU backend only — no jax/XLA, safe during crypto-cache cold
states; native halves skip cleanly without g++.  ``make
cryptoplane-smoke`` runs this with the round-13 tier.
"""

from __future__ import annotations

import random
import socket
import threading
import time

import pytest

import hbbft_tpu.wire  # noqa: F401  (vreq struct registration)
from hbbft_tpu.chaos.oracle import batch_keys, batches_sha, fault_entries
from hbbft_tpu.crypto.backend import BatchedBackend, VerifyRequest
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.cryptoplane import CryptoPlaneService
from hbbft_tpu.cryptoplane.proc_service import (
    CryptoRpcServer,
    RpcServiceClient,
    ServiceProcess,
    fetch_stats,
    parse_addr,
)
from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.transport import LocalCluster
from hbbft_tpu.transport.framing import (
    CRYPTO_KINDS,
    KIND_CRYPTO_HELLO,
    KIND_CRYPTO_REQ,
    KIND_MSG,
    FrameDecoder,
    FrameError,
    encode_frame,
)
from hbbft_tpu.transport.proc_cluster import ProcCluster
from hbbft_tpu.utils import serde
from hbbft_tpu.utils.metrics import Metrics

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 3 s


def _lib_or_skip():
    from hbbft_tpu import native_engine

    lib = native_engine.get_lib()
    if lib is None:
        pytest.skip("native engine unavailable (no compiler?)")
    return lib


def _impl_or_skip(impl: str) -> str:
    if impl == "native":
        _lib_or_skip()
    return impl


def _scalar_fixture():
    suite = ScalarSuite()
    rng = random.Random(5)
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    good = VerifyRequest.sig_share(
        pks.public_key_share(0), b"doc", sks.secret_key_share(0).sign(b"doc")
    )
    bad = VerifyRequest.sig_share(
        pks.public_key_share(1), b"doc", sks.secret_key_share(0).sign(b"doc")
    )
    return suite, good, bad


def _server(suite, **kw):
    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.0, **kw)
    return CryptoRpcServer(svc, suite).start()


# ---------------------------------------------------------------------------
# verdict identity + protocol basics (in-process server, no subprocess)
# ---------------------------------------------------------------------------


def test_rpc_verdicts_identical_to_local_backend():
    suite, good, bad = _scalar_fixture()
    junk = VerifyRequest("sig_share", (object(), b"m", object()))
    batch = [good, bad, good, junk, bad]
    server = _server(suite)
    try:
        cli = RpcServiceClient(
            (server.host, server.port), suite, BatchedBackend(suite),
            metrics=Metrics(),
        )
        want = BatchedBackend(suite).verify_batch(batch)
        assert cli.verify_batch(batch) == want == [True, False, True,
                                                  False, False]
        assert cli.metrics.counters["crypto.rpc.calls"] == 1
        assert cli.metrics.counters.get("crypto.rpc.fallbacks", 0) == 0
        assert cli.verify_batch([]) == []
        # the response reported the merged flush size (the client's
        # amortization observable)
        assert cli.metrics.counters["crypto.rpc.merged_requests"] >= 4
    finally:
        server.stop()


def test_rpc_concurrent_clients_merge_into_one_flush():
    """Three clients on three sockets land in ONE backend flush when
    the window holds — the cross-PROCESS version of the round-13
    cross-thread merge test (here cross-connection; the process drill
    is the ProcCluster test below)."""
    suite, good, bad = _scalar_fixture()
    svc = CryptoPlaneService(BatchedBackend(suite), window_s=0.1)
    server = CryptoRpcServer(svc, suite).start()
    try:
        out = {}
        barrier = threading.Barrier(3)

        def worker(i):
            cli = RpcServiceClient(
                (server.host, server.port), suite, BatchedBackend(suite),
                client_id=f"c{i}",
            )
            barrier.wait()
            out[i] = (cli.verify_batch([good, bad, good]),
                      cli.metrics.counters.get("crypto.rpc.merged_requests",
                                               0))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(out[i][0] == [True, False, True] for i in range(3)), out
        # at least one client's flush rode a merged batch (all three
        # released together, well inside the 100 ms window; full 9-way
        # merging is scheduling-dependent on the 1-core box)
        assert max(out[i][1] for i in range(3)) >= 6, out
    finally:
        server.stop()


def test_stats_rpc_and_parse_addr():
    suite, good, _ = _scalar_fixture()
    server = _server(suite)
    try:
        cli = RpcServiceClient(
            (server.host, server.port), suite, BatchedBackend(suite)
        )
        assert cli.verify_batch([good]) == [True]
        stats = fetch_stats((server.host, server.port), suite)
        assert stats["counters"]["crypto.rpc.served_requests"] == 1
        assert stats["counters"]["crypto.flushes"] == 1
    finally:
        server.stop()
    assert parse_addr("127.0.0.1:9999") == ("127.0.0.1", 9999)
    for bad_spec in ("nohost", ":123", "host:", "host:abc"):
        with pytest.raises(ValueError):
            parse_addr(bad_spec)


# ---------------------------------------------------------------------------
# framing fuzz: garbage must kill connections, never the plane
# ---------------------------------------------------------------------------


def _dial_raw(server) -> socket.socket:
    s = socket.create_connection((server.host, server.port), timeout=5)
    s.settimeout(5)
    return s


def _poisoned(sock: socket.socket) -> bool:
    """True when the server dropped the connection (EOF / RST)."""
    try:
        return sock.recv(4096) == b""
    except OSError:
        return True


def test_server_survives_corrupt_frames():
    """Each corruption mode kills ITS connection; the listener and the
    service live on, and a well-behaved client still verifies."""
    suite, good, _ = _scalar_fixture()
    server = _server(suite)
    try:
        hello = serde.dumps((1, suite.name))
        attacks = []

        # raw garbage (fails CRC / length slicing)
        s = _dial_raw(server)
        s.sendall(b"\xff" * 64)
        attacks.append(s)
        # a consensus-plane frame on the crypto port (disjoint kind set)
        s = _dial_raw(server)
        s.sendall(encode_frame(KIND_MSG, b"x" * 10))
        attacks.append(s)
        # oversized declared length (rejected from the prefix alone)
        s = _dial_raw(server)
        s.sendall((1 << 30).to_bytes(4, "big") + b"\x00" * 16)
        attacks.append(s)
        # valid HELLO then a REQ whose payload is not serde
        s = _dial_raw(server)
        s.sendall(encode_frame(KIND_CRYPTO_HELLO, hello, kinds=CRYPTO_KINDS))
        dec = FrameDecoder(kinds=CRYPTO_KINDS)
        while dec.next_frame() is None:
            dec.feed(s.recv(4096))
        s.sendall(
            encode_frame(KIND_CRYPTO_REQ, b"\x99not-serde",
                         kinds=CRYPTO_KINDS)
        )
        attacks.append(s)
        # wrong-suite HELLO
        s = _dial_raw(server)
        s.sendall(
            encode_frame(
                KIND_CRYPTO_HELLO, serde.dumps((1, "bls12-381")),
                kinds=CRYPTO_KINDS,
            )
        )
        attacks.append(s)
        # truncated frame then close (half a header)
        s = _dial_raw(server)
        s.sendall(b"\x00\x00")
        s.close()

        for s in attacks:
            assert _poisoned(s)
            s.close()
        deadline = time.monotonic() + 5
        while (
            server.metrics.counters.get("crypto.rpc.bad_frames", 0) < 4
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert server.metrics.counters["crypto.rpc.bad_frames"] >= 4

        cli = RpcServiceClient(
            (server.host, server.port), suite, BatchedBackend(suite)
        )
        assert cli.verify_batch([good]) == [True]
        assert cli.metrics.counters.get("crypto.rpc.fallbacks", 0) == 0
    finally:
        server.stop()


class _EvilService:
    """A fake service that handshakes correctly, then answers every REQ
    with attacker-chosen bytes — the client-side fuzz half."""

    def __init__(self, suite, responses):
        self.suite = suite
        self.responses = list(responses)
        self._listener = socket.socket()
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(8)
        self.addr = self._listener.getsockname()[:2]
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while self.responses:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            try:
                sock.settimeout(5)
                dec = FrameDecoder(kinds=CRYPTO_KINDS)
                while True:
                    f = dec.next_frame()
                    if f is not None:
                        kind, payload = f
                        if kind == KIND_CRYPTO_HELLO:
                            sock.sendall(
                                encode_frame(
                                    KIND_CRYPTO_HELLO,
                                    serde.dumps((1, self.suite.name)),
                                    kinds=CRYPTO_KINDS,
                                )
                            )
                        else:
                            sock.sendall(self.responses.pop(0))
                            break
                        continue
                    data = sock.recv(1 << 16)
                    if not data:
                        break
                    dec.feed(data)
            except (OSError, FrameError):
                pass
            finally:
                sock.close()

    def close(self):
        self._listener.close()


def test_client_falls_back_on_malformed_responses():
    """Garbage, wrong-plane, wrong-req-id, and short responses each
    make the client re-verify locally (correct verdicts, counted
    fallback) instead of wedging the flush — and a later good service
    gets re-dialed."""
    suite, good, bad = _scalar_fixture()
    evil_responses = [
        b"\xff" * 32,                                     # not a frame
        encode_frame(KIND_MSG, b"zzz"),                   # wrong plane
        encode_frame(                                     # wrong req id
            0x23, serde.dumps((999, "verify", True, b"\x01", 1, 1)),
            kinds=CRYPTO_KINDS,
        ),
        encode_frame(                                     # short tuple
            0x23, serde.dumps((1, "verify")), kinds=CRYPTO_KINDS
        ),
    ]
    evil = _EvilService(suite, evil_responses)
    try:
        cli = RpcServiceClient(
            evil.addr, suite, BatchedBackend(suite),
            timeout_s=5.0, reconnect_backoff_s=0.0,
        )
        for k in range(4):
            assert cli.verify_batch([good, bad]) == [True, False], k
        assert cli.metrics.counters["crypto.rpc.fallbacks"] == 4
    finally:
        evil.close()


def test_client_times_out_on_silent_service_and_recovers():
    """A service that accepts and never answers: the flush falls back
    after timeout_s (bounded, no wedge)."""
    suite, good, _ = _scalar_fixture()
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(8)
    addr = listener.getsockname()[:2]
    conns = []

    def accept_and_hold():
        try:
            while True:
                sock, _ = listener.accept()
                sock.settimeout(5)
                dec = FrameDecoder(kinds=CRYPTO_KINDS)
                while dec.next_frame() is None:
                    dec.feed(sock.recv(1 << 16))
                sock.sendall(
                    encode_frame(
                        KIND_CRYPTO_HELLO, serde.dumps((1, suite.name)),
                        kinds=CRYPTO_KINDS,
                    )
                )
                conns.append(sock)  # then go silent
        except OSError:
            return

    t = threading.Thread(target=accept_and_hold, daemon=True)
    t.start()
    try:
        cli = RpcServiceClient(
            addr, suite, BatchedBackend(suite), timeout_s=0.5
        )
        t0 = time.monotonic()
        assert cli.verify_batch([good]) == [True]
        assert 0.4 < time.monotonic() - t0 < 10.0
        assert cli.metrics.counters["crypto.rpc.fallbacks"] == 1
    finally:
        listener.close()
        for s in conns:
            s.close()


# ---------------------------------------------------------------------------
# batches_sha identity: rpc-service vs in-thread-service vs inline
# ---------------------------------------------------------------------------


def _run_cluster_arm(impl: str, crypto: str, *, seed: int = 0,
                     target: int = 4, rounds: int = 6, **cluster_kw):
    c = LocalCluster(4, seed=seed, node_impl=impl, crypto=crypto,
                     **cluster_kw)
    for k in range(rounds):
        for i in range(4):
            c.submit(i, Input.user(f"tx-{k}-{i}"))
    c.start()
    try:
        ok = c.wait(
            lambda cl: all(len(cl.batches(i)) >= target for i in range(4)),
            EPOCH_TIMEOUT_S,
        )
        assert ok, {i: len(c.batches(i)) for i in range(4)}
        m = c.merged_metrics(fresh=True)
        assert m.counters.get("cluster.handler_errors", 0) == 0
        keys = {i: batch_keys(c, i, upto=target) for i in range(4)}
        sha = batches_sha(c, 0, upto=target)
        return keys, sha, dict(m.counters)
    finally:
        c.stop()


def test_rpc_arm_output_identical_three_crypto_arms():
    """THE round-18 acceptance pin: inline, in-thread service, and
    rpc-service arms commit identical batches at N=4 seed 0 — python
    impl for all three crypto arms, native for the RPC arm.  Same
    majority-retry stance as the round-13 pin (live-socket epoch
    composition is scheduling-sensitive; a real verdict bug diverges
    deterministically and no retry masks it)."""
    _lib_or_skip()
    arms = [
        ("python", "inline"),
        ("python", "service"),
        ("python", "service-proc"),
        ("native", "service-proc"),
    ]
    runs = {arm: _run_cluster_arm(*arm) for arm in arms}
    for _retry in range(2):
        by_sha: dict = {}
        for arm, (_, sha, _) in runs.items():
            by_sha.setdefault(sha, []).append(arm)
        if len(by_sha) == 1:
            break
        majority = max(by_sha.values(), key=len)
        for sha, arm_list in by_sha.items():
            if arm_list is majority:
                continue
            for arm in arm_list:
                runs[arm] = _run_cluster_arm(*arm)
    shas = {arm: sha for arm, (_, sha, _) in runs.items()}
    assert len(set(shas.values())) == 1, shas
    ref = runs[("python", "inline")][0]
    for arm, (keys, _, _) in runs.items():
        assert keys == ref, f"batch divergence in arm {arm}"
    for arm in (("python", "service-proc"), ("native", "service-proc")):
        counters = runs[arm][2]
        assert counters.get("crypto.rpc.calls", 0) > 0, (arm, counters)
        assert counters.get("crypto.rpc.fallbacks", 0) == 0, (arm, counters)


def test_fault_multiset_parity_through_rpc():
    """The deterministic attribution pin at the RPC boundary: a seeded
    TamperingAdversary sim commits the same batches AND the same fault
    logs (order included) whether shares verify in scalar C or through
    a service PROCESS — serialization changes where verdicts compute,
    never what gets attributed."""
    from hbbft_tpu import native_engine
    from hbbft_tpu.net.adversary import TamperingAdversary

    _lib_or_skip()
    suite = ScalarSuite()

    def drive(**kw):
        nat = native_engine.NativeQhbNet(
            7, seed=9, batch_size=8, num_faulty=2, session_id=b"qhb-test",
            adversary=TamperingAdversary(tamper_p=0.5), **kw,
        )
        for nid in sorted(nat.correct_ids) + sorted(nat.faulty_ids):
            nat.send_input(nid, Input.user(f"x{nid}"))
        nat.run_until(
            lambda e: all(
                len(e.nodes[i].outputs) >= 1 for i in e.correct_ids
            ),
            chunk=1,
        )
        out = (
            {
                i: [
                    (b.era, b.epoch, b.contributions)
                    for b in nat.nodes[i].outputs
                ]
                for i in nat.correct_ids
            },
            {i: nat.faults(i) for i in range(7)},
        )
        nat.close()
        return out

    with ServiceProcess(suite="scalar", backend="batched") as svc:
        base = drive()
        cli = RpcServiceClient(svc.addr, suite, BatchedBackend(suite))
        via_rpc = drive(
            suite=suite, external_crypto=True, flush_every=1, backend=cli,
        )
        assert base == via_rpc
        share_faults = [
            (subj, kind)
            for faults in base[1].values()
            for subj, kind in faults
            if "invalid-share" in kind
        ]
        assert share_faults, "tampering never produced a share fault"
        assert cli.metrics.counters["crypto.rpc.calls"] > 0
        assert cli.metrics.counters.get("crypto.rpc.fallbacks", 0) == 0


# ---------------------------------------------------------------------------
# SIGKILL-mid-flush drill + re-attach (both impls)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["python", "native"])
def test_service_process_sigkill_fallback_and_reattach(impl):
    """The round-13 service-death drill at the process boundary: a REAL
    SIGKILL mid-run flips every client to its local fallback (commits
    continue, no handler errors, no spurious fault attributions), and
    a restarted service on the old port gets re-attached."""
    _impl_or_skip(impl)
    with LocalCluster(
        4, seed=3, node_impl=impl, crypto="service-proc",
        service_kwargs=dict(timeout_s=2.0),
    ) as c:
        c.drive_to([0, 1, 2, 3], 2, timeout_s=EPOCH_TIMEOUT_S)
        pre = dict(c.merged_metrics(fresh=True).counters)
        assert pre.get("crypto.rpc.calls", 0) > 0  # the service WAS serving
        c.crypto_service.kill()
        c.drive_to([0, 1, 2, 3], 4, timeout_s=EPOCH_TIMEOUT_S, tag="post")
        m = c.merged_metrics(fresh=True)
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("crypto.rpc.fallbacks", 0) > 0
        # no lost/dup attributions: an honest-only cluster logs NO
        # protocol faults through the flip (a dropped or doubled
        # verdict would surface as one)
        for i in range(4):
            assert not [e for e in fault_entries(c.nodes[i])], i
        want = batch_keys(c, 0, upto=4)
        for i in (1, 2, 3):
            assert batch_keys(c, i, upto=4) == want

        c.crypto_service.restart()
        # scalar epochs commit in well under the client dial backoff
        # (0.5 s), so keep driving until a flush lands PAST the backoff
        # window and re-dials the reborn service
        target, deadline = 6, time.monotonic() + 30
        while True:
            c.drive_to(
                [0, 1, 2, 3], target, timeout_s=EPOCH_TIMEOUT_S,
                tag=f"reborn{target}",
            )
            m = c.merged_metrics(fresh=True)
            if m.counters.get("crypto.rpc.reconnects", 0) > 0:
                break
            assert time.monotonic() < deadline, dict(m.counters)
            target += 1
            time.sleep(0.3)
        assert m.counters.get("cluster.handler_errors", 0) == 0
        want = batch_keys(c, 0, upto=target)
        for i in (1, 2, 3):
            assert batch_keys(c, i, upto=target) == want


# ---------------------------------------------------------------------------
# process-per-node runtime: one service process serving N node processes
# ---------------------------------------------------------------------------


def test_proc_cluster_service_arm_identity_and_amortization():
    """ProcCluster's service arm commits the same stream as its inline
    arm, every worker's flushes rode the ONE service process, and the
    service's flush counters show cross-node merging."""
    _lib_or_skip()
    with ProcCluster(
        n=4, seed=0, impl="native", epochs=3, drive="presubmit",
        timeout_s=90.0, crypto="service-proc",
    ) as pc:
        sums = pc.join(timeout_s=120.0)
        assert all(s is not None for s in sums.values()), sums
        shas = pc.shas()
        assert len(set(shas.values())) == 1, shas
        for i, s in sums.items():
            rpc = s.get("crypto_rpc")
            assert rpc and rpc["calls"] > 0, (i, s)
            assert rpc["fallbacks"] == 0, (i, s)
            # every flush response carries the merged size; with 4
            # clients the merged total can only exceed this node's own
            assert rpc["merged_requests"] >= rpc["requests"], (i, s)
        stats = pc.crypto_service.stats()["counters"]
        assert stats["crypto.flushes"] > 0
        assert stats["crypto.requests"] > stats["crypto.flushes"], stats
        ref_sha = shas[0]

    with ProcCluster(
        n=4, seed=0, impl="native", epochs=3, drive="presubmit",
        timeout_s=90.0, crypto="inline",
    ) as pc:
        sums = pc.join(timeout_s=120.0)
        assert all(s is not None for s in sums.values()), sums
        inline_shas = set(pc.shas().values())
        assert inline_shas == {ref_sha}, (inline_shas, ref_sha)


def test_proc_cluster_service_kill_drill():
    """kill_service mid-run: worker processes keep committing via their
    local fallbacks; summaries record the fallback flip."""
    _lib_or_skip()
    with ProcCluster(
        n=4, seed=1, impl="native", epochs=0, drive="self",
        timeout_s=90.0, crypto="service-proc",
        service_kwargs=dict(timeout_s=2.0),
    ) as pc:
        assert pc.wait(
            lambda c: all(c.batch_count(i) >= 2 for i in range(4)),
            EPOCH_TIMEOUT_S,
        ), {i: pc.batch_count(i) for i in range(4)}
        pc.kill_service()
        base = {i: pc.batch_count(i) for i in range(4)}
        assert pc.wait(
            lambda c: all(
                c.batch_count(i) >= base[i] + 2 for i in range(4)
            ),
            EPOCH_TIMEOUT_S,
        ), ({i: pc.batch_count(i) for i in range(4)}, base)
        pc.stop()
        sums = pc.summaries()
        for i, s in sums.items():
            assert s is not None, (i, sums)
            rpc = s.get("crypto_rpc")
            assert rpc and rpc["calls"] > 0, (i, s)
            assert rpc["fallbacks"] > 0, (i, s)


# ---------------------------------------------------------------------------
# observability: spans on the cryptoplane track, paired by id
# ---------------------------------------------------------------------------


def test_flush_spans_on_cryptoplane_track_pair_by_id():
    """RPC flushes show up as crypto.flush.open/done pairs on the
    shared ``cryptoplane`` track, carry a span id (concurrent clients
    interleave), and the analyzer pairs them by that id."""
    from hbbft_tpu.obs.analyze import _flush_spans

    with LocalCluster(4, seed=0, crypto="service-proc") as c:
        c.drive_to([0, 1, 2, 3], 2, timeout_s=EPOCH_TIMEOUT_S)
        tracks = c.trace_events()
    evs = tracks.get("cryptoplane")
    assert evs, sorted(tracks)
    opens = [e for e in evs if e.name == "crypto.flush.open"]
    dones = [e for e in evs if e.name == "crypto.flush.done"]
    assert opens and dones, [e.name for e in evs[:8]]
    assert all(e.args.get("span") for e in opens + dones)
    assert all(e.args.get("backend") == "rpc" for e in opens)
    assert all(e.args.get("requests", 0) > 0 for e in opens)
    spans = _flush_spans(tracks)
    assert spans, "analyzer paired no flush spans"
    assert all(t1 >= t0 for t0, t1 in spans)
    # one span per completed open/done pair, id-matched
    done_ids = {e.args["span"] for e in dones}
    assert len(spans) == sum(
        1 for e in opens if e.args["span"] in done_ids
    )


# ---------------------------------------------------------------------------
# construction validation pins
# ---------------------------------------------------------------------------


def test_cluster_construction_validation():
    with pytest.raises(ValueError, match="unknown crypto arm"):
        LocalCluster(4, crypto="service-rpc")
    with pytest.raises(ValueError, match="service_kwargs"):
        LocalCluster(
            4, crypto="service-proc",
            crypto_service=("127.0.0.1", 1), service_kwargs=dict(backend="x"),
        )
    with pytest.raises(ValueError, match="crypto must be"):
        ProcCluster(4, crypto="service")
    with pytest.raises(ValueError, match="crypto_service requires"):
        ProcCluster(4, crypto_service=("127.0.0.1", 1))


def test_cluster_attach_does_not_own_external_service():
    """A cluster attached to an externally-run service process must not
    stop it on teardown (the config9 TpuBackend-arm contract: one warm
    service outlives many runs)."""
    suite = ScalarSuite()
    with ServiceProcess(suite="scalar", backend="batched") as svc:
        with LocalCluster(
            4, seed=0, crypto="service-proc", crypto_service=svc.addr,
        ) as c:
            c.drive_to([0, 1, 2, 3], 2, timeout_s=EPOCH_TIMEOUT_S)
        assert svc.alive  # survived the cluster teardown
        stats = fetch_stats(svc.addr, suite)
        assert stats["counters"]["crypto.flushes"] > 0
