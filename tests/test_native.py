"""Native (C++) data plane vs pure-Python oracles — bit-exact parity.

The reference's data plane is native (Rust ``tiny-keccak`` /
``reed-solomon-erasure``); ours is ``native/hbbft_native.cpp`` loaded
through ctypes (SURVEY.md §2 #4 + native-components note).  Every
operation must agree with the Python implementation byte-for-byte,
since Broadcast mixes both paths freely.
"""

import hashlib
import random

import numpy as np
import pytest

from hbbft_tpu.ops import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def test_sha3_matches_hashlib():
    rng = random.Random(0)
    for n in [0, 1, 31, 32, 135, 136, 137, 271, 272, 1000, 4096]:
        data = rng.randbytes(n)
        assert native.sha3_256(data) == hashlib.sha3_256(data).digest(), n


def test_sha3_batch():
    rng = random.Random(1)
    msgs = np.frombuffer(rng.randbytes(64 * 65), dtype=np.uint8).reshape(64, 65)
    out = native.sha3_256_batch(msgs)
    for i in range(64):
        assert out[i].tobytes() == hashlib.sha3_256(msgs[i].tobytes()).digest()


def test_merkle_levels_match_python():
    from hbbft_tpu.ops import merkle

    rng = random.Random(2)
    for n_leaves in [1, 2, 3, 4, 5, 8, 9, 16, 33]:
        leaves = [rng.randbytes(100) for _ in range(n_leaves)]
        got = native.merkle_levels(leaves)
        # Force the pure path for the oracle.
        old = merkle._native
        merkle._native = None
        try:
            want = merkle.MerkleTree(leaves).levels
        finally:
            merkle._native = old
        assert got == want, n_leaves


def test_merkle_tree_uses_native_and_proofs_validate():
    from hbbft_tpu.ops.merkle import MerkleTree

    rng = random.Random(3)
    leaves = [rng.randbytes(64) for _ in range(10)]
    tree = MerkleTree(leaves)
    for i in range(10):
        assert tree.proof(i).validate(10)


def test_rs_encode_reconstruct_match_python():
    from hbbft_tpu.ops import gf256

    rng = random.Random(4)
    for k, n in [(1, 1), (2, 3), (4, 7), (8, 10), (14, 16), (20, 30)]:
        shards = [rng.randbytes(128) for _ in range(k)]
        got = native.rs_encode(shards, n)
        old = gf256._native
        gf256._native = None
        try:
            rs = gf256.ReedSolomon(k, n)
            want = rs.encode(shards)
            assert got == want, (k, n)
            # Reconstruct from a random k-subset (parity-heavy).
            idxs = sorted(rng.sample(range(n), k))
            sub = {i: want[i] for i in idxs}
            assert native.rs_reconstruct(sub, k, n) == rs.reconstruct(sub)
        finally:
            gf256._native = old


def test_rs_bad_args():
    assert native.rs_encode([b"x"], 300) is None


def test_broadcast_end_to_end_with_native():
    """Full RBC run exercising the native Merkle + RS paths."""
    from hbbft_tpu.net import NetBuilder
    from hbbft_tpu.protocols.broadcast import Broadcast

    payload = random.Random(5).randbytes(2048)
    net = (
        NetBuilder(10, seed=6)
        .protocol(lambda ni, sink, rng: Broadcast(ni, 0))
        .build()
    )
    net.send_input(0, payload)
    net.run_to_termination()
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [payload]
