"""External-crypto engine mode: the native message loop fused with the
real crypto plane (VERDICT round-2 item #1).

Three fidelity pins:

1. **Scalar-external == scalar-native == Python** — the whole callback
   machinery (sign / verify-flush / combine / ct-parse) produces
   byte-identical batches and fault logs to both the engine's internal
   scalar path and the Python VirtualNet (cheap; runs on every suite
   pass).
2. **Flush-schedule invariance** — ``flush_every=0`` (flush only when
   the delivery queue runs dry: maximal batch amortization) commits the
   same outputs as eager verification, per the design invariant that
   deferred verification is an optimization, never a semantics change.
3. **BLS-external == BLS-Python** — a real BLS12-381 epoch under the
   native loop matches the pure-Python VirtualNet at the same seed
   (reference: real ``threshold_crypto`` under the native stack
   throughout, SURVEY.md §2 #14).
"""

import os

import pytest

from hbbft_tpu import native_engine
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.dynamic_honey_badger import Change, DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger

pytestmark = pytest.mark.skipif(
    not native_engine.available(), reason="native engine unavailable"
)

BATCH_SIZE = 8
SESSION = b"qhb-test"


def batch_key(b):
    return (b.era, b.epoch, b.contributions, b.change, b.join_plan)


def py_batches(net, nid):
    return [o for o in net.node(nid).outputs if isinstance(o, DhbBatch)]


def run_native(n, seed, f, inputs, want, chunk=1, **kw):
    nat = native_engine.NativeQhbNet(
        n, seed=seed, batch_size=BATCH_SIZE, num_faulty=f, session_id=SESSION,
        **kw,
    )
    for nid, value in inputs:
        nat.send_input(nid, value)
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= want for i in e.correct_ids),
        chunk=chunk,
    )
    out = {
        i: [batch_key(b) for b in nat.nodes[i].outputs] for i in nat.correct_ids
    }
    faults = {i: nat.faults(i) for i in range(n)}
    nat.close()
    return out, faults


STEPS_N4 = [(nid, Input.user(f"tx-{nid}-{k}")) for k in range(3) for nid in range(4)]


@pytest.mark.parametrize("seed", [1, 2])
def test_ext_scalar_matches_native_scalar(seed):
    base = run_native(4, seed, 0, STEPS_N4, 3)
    ext = run_native(
        4, seed, 0, STEPS_N4, 3, suite=ScalarSuite(), external_crypto=True,
        flush_every=1,
    )
    assert base == ext


@pytest.mark.parametrize("flush_every", [0, 7])
def test_ext_scalar_flush_schedule_invariance(flush_every):
    eager = run_native(
        4, 3, 0, STEPS_N4, 3, suite=ScalarSuite(), external_crypto=True,
        flush_every=1,
    )
    deferred = run_native(
        4, 3, 0, STEPS_N4, 3, suite=ScalarSuite(), external_crypto=True,
        flush_every=flush_every, chunk=10_000,
    )
    # Large chunks overshoot the stop predicate (more epochs commit
    # before it is re-checked), so compare the common prefix: the first
    # `want` batches per node must be identical.
    for i, seq in eager[0].items():
        assert deferred[0][i][: len(seq)] == seq
    assert eager[1] == {i: f[: len(eager[1][i])] for i, f in deferred[1].items()}


def test_ext_scalar_with_silent_faulty():
    inputs = [(nid, Input.user(f"t{nid}.{k}")) for k in range(2) for nid in range(5)]
    base = run_native(7, 5, 2, inputs, 2)
    ext = run_native(
        7, 5, 2, inputs, 2, suite=ScalarSuite(), external_crypto=True,
        flush_every=1,
    )
    assert base == ext


def test_ext_scalar_era_change():
    """The external path through a full era change (votes, embedded DKG,
    era restart): must match the engine's internal scalar path."""

    def drive(**kw):
        nat = native_engine.NativeQhbNet(
            4, seed=11, batch_size=BATCH_SIZE, num_faulty=0, session_id=SESSION,
            **kw,
        )
        keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
        keep.pop(3)
        change = Change.node_change(keep)
        for nid in range(4):
            nat.send_input(nid, Input.change(change))

        def done(e):
            return all(
                any(b.change.kind == "complete" for b in e.nodes[i].outputs)
                for i in e.correct_ids
            )

        for r in range(8):
            if done(nat):
                break
            for nid in range(4):
                nat.send_input(nid, Input.user(f"e{r}-{nid}"))
            want = r + 1
            nat.run_until(
                lambda e, w=want: all(
                    len(e.nodes[i].outputs) >= w for i in e.correct_ids
                ),
                chunk=1,
            )
        assert done(nat)
        era = nat.nodes[0].qhb.dhb.era
        out = {
            i: [batch_key(b) for b in nat.nodes[i].outputs]
            for i in nat.correct_ids
        }
        faults = {i: nat.faults(i) for i in range(4)}
        nat.close()
        return out, faults, era

    base = drive()
    ext = drive(suite=ScalarSuite(), external_crypto=True, flush_every=1)
    assert base == ext
    assert base[2] >= 1  # the era actually advanced


def _drive_era_change_n16():
    """One N=16 era change on the engine; returns (batch keys, faults,
    era, per-node new-era key material)."""
    from hbbft_tpu.protocols.dynamic_honey_badger import Change as Chg

    n = 16
    nat = native_engine.NativeQhbNet(
        n, seed=7, batch_size=BATCH_SIZE, num_faulty=0, session_id=SESSION
    )
    keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
    keep.pop(n - 1)
    for nid in range(n):
        nat.send_input(nid, Input.change(Chg.node_change(keep)))

    def done(e):
        return all(
            any(b.change.kind == "complete" for b in e.nodes[i].outputs)
            for i in e.correct_ids
        )

    rounds = 0
    while not done(nat) and rounds < 12:
        for nid in range(n):
            nat.send_input(nid, Input.user(f"e{rounds}-{nid}"))
        rounds += 1
        nat.run_until(
            lambda e, w=rounds: all(
                len(e.nodes[i].outputs) >= w for i in e.correct_ids
            ),
            chunk=1,
        )
    assert done(nat)
    out = {
        i: [batch_key(b) for b in nat.nodes[i].outputs] for i in nat.correct_ids
    }
    faults = {i: nat.faults(i) for i in range(n)}
    era = nat.nodes[0].qhb.dhb.era
    keysets = {}
    for i in nat.correct_ids:
        ni = nat.nodes[i].qhb.dhb.netinfo
        sk = ni.secret_key_share
        keysets[i] = (
            ni.public_key_set.to_bytes(),
            sk.x if sk is not None else None,
        )
    nat.close()
    return out, faults, era, keysets


def test_era_change_native_batch_matches_pure_python_dkg(monkeypatch):
    """The tentpole's byte-identity pin: a FULL N=16 era change with the
    round-6 native batch-digest DKG path vs the same run with the
    sync_key_gen native plane disabled (pure-Python oracle throughout;
    same seed).  Committed batches, fault logs, eras AND the generated
    key sets must be identical."""
    import hbbft_tpu.protocols.sync_key_gen as skg_mod

    if skg_mod._native_dkg(ScalarSuite()) is None:
        pytest.skip("native DKG unavailable")

    skg_mod.PREDIGEST_STATS.update(items=0, hits=0)
    base = _drive_era_change_n16()
    assert skg_mod.PREDIGEST_STATS["hits"] > 0, "batch digest never engaged"
    assert base[2] >= 1  # the era actually advanced

    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(skg_mod, "_NATIVE_DKG", {ScalarSuite().name: None})
        pure = _drive_era_change_n16()
    assert base == pure


def test_era_change_per_item_fallback_fuzz(monkeypatch):
    """Per-item fallback under fire: every 3rd batched ack check
    reports a stale cid AND part digests are disabled entirely — the
    era change must still commit the exact same batches/keys (the
    misses fall through the per-item native path to the oracle)."""
    import hbbft_tpu.protocols.sync_key_gen as skg_mod

    nd = skg_mod._native_dkg(ScalarSuite())
    if nd is None:
        pytest.skip("native DKG unavailable")

    base = _drive_era_change_n16()

    orig = skg_mod._NativeDkg.ack_check_batch

    def flaky(self, items, our_pos, sk_x):
        res = orig(self, items, our_pos, sk_x)
        if res is None:
            return None
        return [(-1, 0) if i % 3 == 0 else rv for i, rv in enumerate(res)]

    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(skg_mod._NativeDkg, "ack_check_batch", flaky)
        mp.setattr(
            skg_mod._NativeDkg, "part_check_batch", lambda *a, **k: None
        )
        fuzzed = _drive_era_change_n16()
    assert base == fuzzed


# ---------------------------------------------------------------------------
# Real BLS12-381 under the native loop
# ---------------------------------------------------------------------------


def _bls_inputs():
    return [(nid, Input.user(f"tx-{nid}-{k}")) for k in range(2) for nid in range(3)]


def test_bls_native_matches_python_net():
    """One real-BLS epoch: native engine vs Python VirtualNet, same seed,
    byte-identical batches + fault logs (and the same delivery count —
    the engine reproduces the Python net's schedule exactly)."""
    from hbbft_tpu.crypto.bls import BLSSuite

    pynet = (
        NetBuilder(4, seed=1)
        .num_faulty(1)
        .max_cranks(10_000_000)
        .suite(BLSSuite())
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=BATCH_SIZE, session_id=SESSION
            )
        )
        .build()
    )
    nat = native_engine.NativeQhbNet(
        4, seed=1, batch_size=BATCH_SIZE, num_faulty=1, session_id=SESSION,
        suite=BLSSuite(), flush_every=1,
    )
    for nid, value in _bls_inputs():
        pynet.send_input(nid, value)
        nat.send_input(nid, value)
    pynet.crank_until(
        lambda net: all(len(py_batches(net, i)) >= 1 for i in net.correct_ids),
        max_cranks=10_000_000,
    )
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
        chunk=1,
    )
    assert nat.delivered == pynet.delivered
    for nid in pynet.correct_ids:
        assert [batch_key(b) for b in py_batches(pynet, nid)] == [
            batch_key(b) for b in nat.nodes[nid].outputs
        ]
        assert [(f.node_id, f.kind) for f in pynet.node(nid).faults] == nat.faults(
            nid
        )
    nat.close()


def test_bls_native_deferred_flush_amortizes():
    """flush_every=0: same committed epoch, but verify requests actually
    batch (>1 request per backend flush) — the deferred-verify design's
    core claim, demonstrated end-to-end with real BLS."""
    from hbbft_tpu.crypto.bls import BLSSuite

    eager = run_native(
        4, 1, 1, _bls_inputs(), 1, suite=BLSSuite(), flush_every=1
    )
    nat = native_engine.NativeQhbNet(
        4, seed=1, batch_size=BATCH_SIZE, num_faulty=1, session_id=SESSION,
        suite=BLSSuite(), flush_every=0,
    )
    for nid, value in _bls_inputs():
        nat.send_input(nid, value)
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
        chunk=200,
    )
    deferred = (
        {i: [batch_key(b) for b in nat.nodes[i].outputs] for i in nat.correct_ids},
        {i: nat.faults(i) for i in range(4)},
    )
    stats = dict(nat.flush_stats)
    nat.close()
    assert eager == deferred
    assert stats["max_batch"] > 1, stats
    # Cross-node dedup: identical requests observed by several nodes hit
    # the backend once.
    assert stats["backend_requests"] < stats["requests"], stats


@pytest.mark.skipif(
    os.environ.get("HBBFT_TPU_SKIP_BLS_ERA") == "1",
    reason="HBBFT_TPU_SKIP_BLS_ERA=1 requested",
)
def test_bls_native_era_change():
    """The fused stack through a COMPLETE era change with real BLS12-381:
    votes sign/verify, the embedded DKG deals real BivarPoly rows over
    real KEM ciphertexts, and the new era's threshold keys come out of
    the distributed generation — all under the native message loop.

    Ungated round 4 (VERDICT r3 weak #3): ~35 s on this box
    (BASELINE.md round-4), cheap enough for the default tier; opt out
    with HBBFT_TPU_SKIP_BLS_ERA=1 on slower machines."""
    from hbbft_tpu.crypto.bls import BLSSuite
    from hbbft_tpu.protocols.dynamic_honey_badger import Change

    n = 4
    nat = native_engine.NativeQhbNet(
        n, seed=2, batch_size=BATCH_SIZE, num_faulty=0, session_id=SESSION,
        suite=BLSSuite(), flush_every=0,
    )
    keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
    keep.pop(n - 1)
    for nid in range(n):
        nat.send_input(nid, Input.change(Change.node_change(keep)))

    def done(e):
        return all(
            any(b.change.kind == "complete" for b in e.nodes[i].outputs)
            for i in e.correct_ids
        )

    for r in range(8):
        if done(nat):
            break
        for nid in range(n):
            nat.send_input(nid, Input.user(f"e{r}-{nid}"))
        want = len(nat.nodes[0].outputs) + 1
        nat.run_until(
            lambda e, w=want: all(
                len(e.nodes[i].outputs) >= w for i in e.correct_ids
            ),
            chunk=2000,
        )
    assert done(nat)
    assert {nat.nodes[i].qhb.dhb.era for i in nat.correct_ids} == {1}
    # all nodes derived the SAME new master key from the DKG
    new_pks = {
        nat.nodes[i].qhb.dhb.netinfo.public_key_set.to_bytes()
        for i in nat.correct_ids
    }
    assert len(new_pks) == 1
    assert all(nat.faults(i) == [] for i in range(n))
    nat.close()
