"""Metrics/observability subsystem (SURVEY.md §5.1/§5.5 analog)."""

import time

from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.threshold_sign import ThresholdSign
from hbbft_tpu.utils.metrics import EpochTracker, Metrics


def test_counters_and_timers():
    m = Metrics()
    m.count("a")
    m.count("a", 4)
    with m.timer("t"):
        time.sleep(0.01)
    with m.timer("t"):
        pass
    assert m.counters["a"] == 5
    st = m.timers["t"]
    assert st.count == 2 and st.total_s >= 0.01 and st.max_s >= 0.01
    rep = m.report()
    assert "a" in rep and "t" in rep


def test_merge():
    a, b = Metrics(), Metrics()
    a.count("x", 2)
    b.count("x", 3)
    with b.timer("u"):
        pass
    a.merge(b)
    assert a.counters["x"] == 5
    assert a.timers["u"].count == 1


def test_virtual_net_records_flush_metrics():
    net = (
        NetBuilder(4, seed=1)
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, b"mdoc", sink))
        .build()
    )
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    assert net.metrics.counters["verify_requests"] > 0
    assert net.metrics.timers["verify_flush"].count > 0


def test_gauges_last_write_wins_and_merge():
    a, b = Metrics(), Metrics()
    a.gauge("depth", 3)
    a.gauge("depth", 7)  # set semantics, not accumulate
    assert a.gauges["depth"] == 7
    b.gauge("depth", 1)
    b.gauge("other", 2.5)
    a.merge(b)
    assert a.gauges == {"depth": 1, "other": 2.5}
    assert "gauges:" in a.report()


def test_to_json_roundtrips_through_json():
    import json

    m = Metrics()
    m.count("c", 3)
    m.gauge("g", 1.5)
    with m.timer("t"):
        pass
    snap = json.loads(json.dumps(m.to_json()))
    assert snap["counters"]["c"] == 3
    assert snap["gauges"]["g"] == 1.5
    assert snap["timers"]["t"]["count"] == 1


def test_prometheus_text_format():
    m = Metrics()
    m.count("transport.frames", 12)
    m.gauge("transport.0->1.queue_frames", 4)
    with m.timer("flush"):
        pass
    text = m.prometheus_text()
    assert '# TYPE hbbft_count counter' in text
    assert 'hbbft_count{name="transport.frames"} 12' in text
    assert 'hbbft_gauge{name="transport.0->1.queue_frames"} 4' in text
    assert 'hbbft_timer_seconds_count{name="flush"} 1' in text
    assert text.endswith("\n")
    assert Metrics().prometheus_text() == ""


def test_summary_quantile_export():
    """Summary/quantile path (ISSUE 6 satellite): snapshot semantics
    like gauges (last write wins, newest wins on merge), and the
    Prometheus summary exposition triplet (quantile series + _sum +
    _count)."""
    m = Metrics()
    m.summary("lat", {0.5: 0.010, 0.99: 0.200}, count=100, total=1.5)
    m.summary("lat", {0.5: 0.012, 0.99: 0.250}, count=150, total=2.5)
    sm = m.summaries["lat"]
    assert sm.count == 150 and sm.total == 2.5
    assert sm.quantiles == {0.5: 0.012, 0.99: 0.250}

    other = Metrics()
    other.summary("lat", {0.5: 0.020}, count=7, total=0.2)
    m.merge(other)
    assert m.summaries["lat"].count == 7  # newest-wins, like gauges

    m.summary("lat", {0.5: 0.012, 0.99: 0.250}, count=150, total=2.5)
    text = m.prometheus_text()
    assert "# TYPE hbbft_summary summary" in text
    assert 'hbbft_summary{name="lat",quantile="0.5"} 0.012' in text
    assert 'hbbft_summary{name="lat",quantile="0.99"} 0.25' in text
    assert 'hbbft_summary_sum{name="lat"} 2.5' in text
    assert 'hbbft_summary_count{name="lat"} 150' in text

    import json

    snap = json.loads(json.dumps(m.to_json()))
    assert snap["summaries"]["lat"]["quantiles"]["0.99"] == 0.25
    assert "lat" in m.report() and "p99" in m.report()


def test_epoch_tracker():
    t = EpochTracker()
    t.start((0, 0), 1.0)
    t.finish((0, 0), 3.5, contributions=4, txns=12)
    t.finish((0, 0), 9.0, contributions=9, txns=99)  # first finish wins
    (st,) = t.all()
    assert st.latency_s == 2.5 and st.txns == 12
