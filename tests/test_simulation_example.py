"""Smoke test: the virtual-time simulation example runs end-to-end.

Reference analog: upstream ``examples/simulation.rs`` (SURVEY.md §2 #17)
— the reference's only benchmark artifact.  A tiny config keeps this
fast; the point is that the example's whole pipeline (DHB + SenderQueue
messages through the hardware model, message sizing, flush metrics)
stays runnable, since it is part of the bench workflow.
"""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_simulation_example_smoke():
    result = subprocess.run(
        [
            sys.executable,
            str(REPO / "examples" / "simulation.py"),
            "--nodes",
            "4",
            "--txns",
            "8",
            "--batch-size",
            "4",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        cwd=str(REPO),
    )
    assert result.returncode == 0, result.stderr[-2000:]
    assert "committed" in result.stdout
