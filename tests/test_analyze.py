"""Critical-path analyzer + stall diagnostician (round 16).

Pinned surfaces:

* the analyzer's EXACT output over golden sim-net traces from BOTH
  impls (tests/fixtures/golden_*.json — regenerate only deliberately,
  via tools/make_golden_trace.py);
* structural rerun identity: two same-seed sim-net runs produce
  critical paths with identical (stage, node, proposer) structure;
* live-cluster consistency on both node arms: every path is monotone
  and inside its epoch's open→commit wall;
* the Chrome-trace round trip: analyzing a dumped trace.json gives the
  same records as analyzing the live rings (post-mortem == live);
* the seeded stall drill: an honest-minority partition around a
  Byzantine proposer stalls the cluster and ``/diag`` names the stuck
  proposer/phase over HTTP.

Budget: driven phases keep the standard 45 s caps; no jax/XLA
(``make obs-smoke``); native halves skip cleanly without g++.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request

import pytest

from hbbft_tpu.obs.analyze import (
    STAGES,
    ba_rounds_to_decide,
    critical_path,
    derived_summaries,
    diagnose,
    epoch_events,
    merge_diags,
    path_structure,
    summarize_critical_paths,
    tracks_from_chrome,
)
from hbbft_tpu.obs.export import chrome_trace
from hbbft_tpu.obs.trace import TraceEvent
from hbbft_tpu.transport import LocalCluster

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 5 s
FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def _native_available() -> bool:
    from hbbft_tpu import native_engine

    return native_engine.get_lib() is not None


def _load_fixture_tracks(impl: str):
    with open(os.path.join(FIXDIR, f"golden_trace_{impl}.json")) as fh:
        doc = json.load(fh)
    return {
        t: [TraceEvent(ts, name, args) for ts, name, args in evs]
        for t, evs in doc["tracks"].items()
    }


def _roundtrip(obj):
    return json.loads(json.dumps(obj))


# ---------------------------------------------------------------------------
# Synthetic tracks: exact path semantics
# ---------------------------------------------------------------------------


def _mk(ts, name, **args):
    return TraceEvent(ts, name, args)


def _two_node_epoch():
    # Native-style explicit era/epoch args; node1 is the straggler in
    # rbc.deliver, node0 commits last.
    e = {"era": 0, "epoch": 2}
    return {
        "node0": [
            _mk(10.0, "epoch.open", **e),
            _mk(10.1, "rbc.value", proposer=0, **e),
            _mk(10.2, "rbc.ready", proposer=0, **e),
            _mk(10.3, "rbc.deliver", proposer=0, **e),
            _mk(10.31, "ba.input", proposer=0, round=0, value=1, **e),
            _mk(10.4, "ba.coin", proposer=0, round=0, value=1, **e),
            _mk(10.5, "ba.decide", proposer=0, round=0, value=1, **e),
            _mk(10.6, "decrypt.start", proposer=0, **e),
            _mk(10.7, "decrypt.done", proposer=0, **e),
            _mk(11.0, "epoch.commit", contribs=2, **e),
        ],
        "node1": [
            _mk(10.05, "epoch.open", **e),
            _mk(10.15, "rbc.value", proposer=1, **e),
            _mk(10.25, "rbc.ready", proposer=1, **e),
            _mk(10.85, "rbc.deliver", proposer=1, **e),  # straggler
            _mk(10.86, "ba.input", proposer=1, round=0, value=1, **e),
            _mk(10.87, "ba.decide", proposer=1, round=1, value=1, **e),
            _mk(10.9, "epoch.commit", contribs=2, **e),
        ],
    }


def test_critical_path_synthetic_attribution():
    (rec,) = critical_path(_two_node_epoch())
    assert (rec["era"], rec["epoch"]) == (0, 2)
    assert rec["t_open"] == 10.0 and rec["t_commit"] == 11.0
    assert abs(rec["wall_s"] - 1.0) < 1e-9
    assert abs(rec["commit_skew_s"] - 0.1) < 1e-9
    assert abs(rec["open_skew_s"] - 0.05) < 1e-9
    stages = [p["stage"] for p in rec["path"]]
    # path follows STAGES order, each stage at most once
    assert stages == [s for s in STAGES if s in stages]
    by_stage = {p["stage"]: p for p in rec["path"]}
    # the last rbc.deliver cluster-wide is node1's straggling one
    assert by_stage["rbc.deliver"]["node"] == "node1"
    assert by_stage["rbc.deliver"]["proposer"] == 1
    # the straggler is that rbc.deliver hop (0.6 s of the 1.0 s wall)
    assert rec["straggler"]["stage"] == "rbc.deliver"
    assert rec["straggler"]["node"] == "node1"
    assert abs(rec["straggler"]["dt_s"] - 0.6) < 1e-9
    # monotone, inside the wall
    ts = [p["t"] for p in rec["path"]]
    assert ts == sorted(ts)
    assert all(rec["t_open"] <= t <= rec["t_commit"] for t in ts)
    # rounds-to-decide histogram: node0 decided in round 0 (1 round),
    # node1 in round 1 (2 rounds)
    assert rec["ba_rounds"] == {1: 1, 2: 1}
    assert rec["coins"] == 1


def test_critical_path_needs_open_and_commit():
    # An in-flight epoch (no commit) yields no record; a commit whose
    # open was lost to ring overflow yields none either.
    tracks = {
        "node0": [
            _mk(1.0, "epoch.open", era=0, epoch=0),
            _mk(1.1, "rbc.value", proposer=0),
        ],
        "node1": [_mk(1.2, "epoch.commit", era=0, epoch=1, contribs=1)],
    }
    assert critical_path(tracks) == []


def test_cluster_and_cryptoplane_tracks_are_not_epoch_scoped():
    tracks = _two_node_epoch()
    tracks["cluster"] = [_mk(10.5, "chaos.kill", node=1)]
    tracks["cryptoplane"] = [
        _mk(10.35, "crypto.flush.open", requests=4, jobs=2, backend="b"),
        _mk(10.45, "crypto.flush.done", requests=4, jobs=2, backend="b", ok=True),
        _mk(12.0, "crypto.flush.open", requests=1, jobs=1, backend="b"),
    ]
    assert set(epoch_events(tracks)[(0, 2)]) == {"node0", "node1"}
    (rec,) = critical_path(tracks)
    # the in-window flush folded in; the post-commit (unpaired) one not
    assert rec["flush"] == {
        "flushes": 1,
        "total_s": pytest.approx(0.1),
        "max_s": pytest.approx(0.1),
    }


def test_python_arm_bracketing_assigns_leaf_events():
    # Python-arm leaf milestones carry no epoch args; they belong to
    # the track's currently-open epoch (the exporter's rule).
    tracks = {
        "node0": [
            _mk(1.0, "epoch.open", era=0, epoch=0),
            _mk(1.1, "rbc.deliver", proposer=1),
            _mk(1.2, "ba.decide", proposer=1, round=0, value=1),
            _mk(1.3, "epoch.commit", era=0, epoch=0, contribs=1),
            _mk(2.0, "epoch.open", era=0, epoch=1),
            _mk(2.1, "rbc.deliver", proposer=0),
        ]
    }
    by_epoch = epoch_events(tracks)
    assert [e.name for e in by_epoch[(0, 0)]["node0"]] == [
        "epoch.open",
        "rbc.deliver",
        "ba.decide",
        "epoch.commit",
    ]
    assert [e.name for e in by_epoch[(0, 1)]["node0"]] == [
        "epoch.open",
        "rbc.deliver",
    ]


def test_chrome_roundtrip_gives_identical_analysis():
    tracks = _two_node_epoch()
    doc = _roundtrip(chrome_trace(tracks, pids={"node0": 0, "node1": 1}))
    recovered = tracks_from_chrome(doc)
    assert _roundtrip(critical_path(recovered)) == _roundtrip(
        critical_path(tracks)
    )


def test_summarize_critical_paths_shape():
    s = summarize_critical_paths(critical_path(_two_node_epoch()))
    assert s["epochs"] == 1
    assert s["straggler_nodes"] == {"node1": 1}
    assert s["straggler_phases"] == {"rbc": 1}
    assert s["ba_rounds"] == {"1": 1, "2": 1}
    assert 0.0 < sum(s["phase_share"].values()) <= 1.0 + 1e-9
    assert summarize_critical_paths([]) == {"epochs": 0}
    # JSON-line safe end to end
    json.dumps(s)


def test_ba_rounds_summary_derivation():
    tracks = _two_node_epoch()
    assert sorted(ba_rounds_to_decide(tracks)) == [1, 2]
    sums = derived_summaries(tracks)
    quant, count, total = sums["ba.rounds"]
    assert count == 2 and total == 3.0
    assert "phase.epoch" in sums and "phase.rbc" in sums


# ---------------------------------------------------------------------------
# Diagnosis semantics (synthetic)
# ---------------------------------------------------------------------------


def _stalled_tracks():
    # Epoch 0 committed everywhere at t=2; epoch 1 open, proposer 1's
    # RBC incomplete on both nodes, proposer 0 decided+committed-side
    # complete; node1 lost its link to peer 1.
    common = [
        _mk(1.0, "epoch.open", era=0, epoch=0),
        _mk(2.0, "epoch.commit", era=0, epoch=0, contribs=2),
        _mk(2.1, "epoch.open", era=0, epoch=1),
        _mk(2.2, "rbc.value", proposer=0),
        _mk(2.3, "rbc.deliver", proposer=0),
        _mk(2.35, "ba.input", proposer=0, round=0, value=1),
        _mk(2.4, "ba.round", proposer=0, round=1),
    ]
    return {
        "node0": common
        + [_mk(2.5, "rbc.value", proposer=1)],  # value, no deliver
        "node1": common
        + [
            _mk(2.45, "transport.connect", peer=1),
            _mk(3.0, "transport.disconnect", peer=1),
        ],
    }


def test_diagnose_names_stuck_instances():
    d = diagnose(_stalled_tracks(), n=2, now=10.0, stall_after_s=5.0)
    assert d["stalled"] and d["since_s"] == pytest.approx(8.0)
    assert d["last_commit"] == [0, 0]
    assert d["open_epochs"] == {"node0": [0, 1], "node1": [0, 1]}
    by = {(s["node"], s["proposer"]): s for s in d["stuck"]}
    # proposer 0: BA undecided at round 1 on both nodes
    assert by[("node0", 0)]["phase"] == "ba"
    assert by[("node0", 0)]["round"] == 1
    # proposer 1: rbc incomplete — value seen on node0, nothing on node1
    assert by[("node0", 1)]["phase"] == "rbc"
    assert by[("node0", 1)]["detail"] == "echo/ready incomplete"
    assert by[("node1", 1)]["detail"] == "no value received"
    # verdict: both (0, ba) and (1, rbc) stuck on 2 nodes; tie goes to
    # the earlier phase (rbc blocks more)
    assert d["verdict"] == {"proposer": 1, "phase": "rbc", "nodes": 2}
    assert d["links"]["node1"]["disconnected"] == [1]


def test_diagnose_absent_proposer_outranks_quorum_noise():
    # Below quorum EVERY BA instance stalls on every node — naming the
    # most-counted one would blame an arbitrary healthy proposer.  A
    # proposer with "no value received" on >= 2 nodes (dead or
    # partitioned away) is the upstream cause and must win the verdict.
    base = [
        _mk(1.0, "epoch.open", era=0, epoch=0),
        _mk(2.0, "epoch.commit", era=0, epoch=0, contribs=2),
        _mk(2.1, "epoch.open", era=0, epoch=1),
        _mk(2.2, "rbc.deliver", proposer=0),
        _mk(2.3, "ba.input", proposer=0, round=0, value=1),
    ]
    tracks = {f"node{i}": list(base) for i in range(3)}
    d = diagnose(tracks, n=3, now=60.0, stall_after_s=5.0)
    # (0, ba) is stuck on all 3 nodes; proposers 1 and 2 sent nothing
    # to anyone (absent on 3 nodes each) — the verdict names an absent
    # proposer (count tie -> lower id), not the BA noise
    assert d["stalled"]
    assert d["verdict"] == {
        "proposer": 1,
        "phase": "rbc",
        "nodes": 3,
        "absent": True,
    }


def test_diagnose_link_loss_outranks_ba_noise():
    # Post-RBC quorum loss: every proposer delivered everywhere, every
    # BA instance equally stuck — counting would blame an arbitrary
    # healthy proposer.  The link plane holds the real cause: peers
    # reported down by >= 2 tracks become the verdict.
    def track(peer_events):
        return [
            _mk(1.0, "epoch.open", era=0, epoch=0),
            _mk(2.0, "epoch.commit", era=0, epoch=0, contribs=3),
            _mk(2.1, "epoch.open", era=0, epoch=1),
            _mk(2.2, "rbc.deliver", proposer=0),
            _mk(2.25, "rbc.deliver", proposer=1),
            _mk(2.3, "ba.input", proposer=0, round=0, value=1),
            _mk(2.35, "ba.input", proposer=1, round=0, value=1),
        ] + peer_events
    tracks = {
        "node0": track([
            _mk(1.5, "transport.connect", peer=2),
            _mk(3.0, "transport.disconnect", peer=2),
        ]),
        "node1": track([
            _mk(1.5, "transport.connect", peer=2),
            _mk(3.1, "transport.disconnect", peer=2),
        ]),
    }
    d = diagnose(tracks, n=2, now=60.0, stall_after_s=5.0)
    assert d["stalled"]
    assert d["verdict"] == {"phase": "link", "peers": [2], "nodes": 2}


def test_diagnose_quiet_cluster_not_stalled():
    d = diagnose(_stalled_tracks(), n=2, now=3.5, stall_after_s=5.0)
    assert not d["stalled"] and d["verdict"] is None


def test_merge_diags_cluster_verdict():
    tracks = _stalled_tracks()
    d0 = diagnose({"node0": tracks["node0"]}, n=2, now=10.0)
    d1 = diagnose({"node1": tracks["node1"]}, n=2, now=10.0)
    merged = merge_diags([d0, d1])
    assert merged["stalled"] and merged["workers"] == 2
    assert merged["verdict"] == {"proposer": 1, "phase": "rbc", "nodes": 2}
    assert merged["links"]["node1"]["disconnected"] == [1]
    # one healthy worker (commits still landing) => cluster not stalled
    healthy = dict(d1, stalled=False)
    assert not merge_diags([d0, healthy])["stalled"]
    assert merge_diags([]) == {
        "stalled": False,
        "since_s": None,
        "workers": 0,
    }


# ---------------------------------------------------------------------------
# Golden fixtures: the analyzer's exact output, both sim impls
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["python", "native"])
def test_golden_fixture_critical_path_pinned(impl):
    # The fixture traces came from deterministic sim-net runs
    # (tools/make_golden_trace.py); analyzing them must reproduce the
    # committed analyzer output EXACTLY — any drift is a semantics
    # change that needs a deliberate fixture regeneration.
    tracks = _load_fixture_tracks(impl)
    with open(os.path.join(FIXDIR, f"golden_cp_{impl}.json")) as fh:
        expected = json.load(fh)
    assert _roundtrip(critical_path(tracks)) == expected


@pytest.mark.parametrize("impl", ["python", "native"])
def test_golden_fixture_paths_are_consistent(impl):
    # Self-check of the acceptance invariants on the pinned output:
    # monotone chains inside the open→commit wall, stage order.
    for rec in critical_path(_load_fixture_tracks(impl)):
        ts = [p["t"] for p in rec["path"]]
        assert ts == sorted(ts)
        assert all(
            rec["t_open"] - 1e-9 <= t <= rec["t_commit"] + 1e-9 for t in ts
        )
        stages = [p["stage"] for p in rec["path"]]
        assert stages == [s for s in STAGES if s in stages]
        assert all(p["dt_s"] >= 0 for p in rec["path"])


def _drive_python_sim(seed: int, epochs: int = 2):
    from hbbft_tpu.net import NetBuilder
    from hbbft_tpu.protocols.queueing_honey_badger import (
        Input,
        QueueingHoneyBadger,
    )
    from hbbft_tpu.protocols.sender_queue import SenderQueue

    def factory(ni, sink, rng):
        return SenderQueue.wrap(
            lambda s: QueueingHoneyBadger(
                ni, s, batch_size=4, session_id=b"rerun"
            ),
            sink,
            peers=list(range(4)),
        )

    net = NetBuilder(4, seed=seed).num_faulty(0).protocol(factory).build()
    net.enable_trace()
    for i in range(4):
        net.send_input(i, Input.user(f"r-{i}"))
    net.crank_until(
        lambda n: all(
            len(n.node(i).outputs) >= epochs for i in range(4)
        ),
        max_cranks=200_000,
    )
    return critical_path(net.trace_events())


def test_same_seed_sim_rerun_identical_structure():
    # Two same-seed VirtualNet runs: wall-clock stamps differ, the
    # critical path STRUCTURE (stage, node, proposer per hop, epoch
    # set, straggler attribution) must not.
    a = _drive_python_sim(7)
    b = _drive_python_sim(7)
    assert [(r["era"], r["epoch"]) for r in a] == [
        (r["era"], r["epoch"]) for r in b
    ]
    assert [path_structure(r) for r in a] == [path_structure(r) for r in b]
    assert [r["ba_rounds"] for r in a] == [r["ba_rounds"] for r in b]


@pytest.mark.skipif(
    not _native_available(), reason="native engine unavailable"
)
def test_same_seed_native_sim_rerun_identical_structure():
    from hbbft_tpu.native_engine import NativeQhbNet
    from hbbft_tpu.protocols.queueing_honey_badger import Input

    def run():
        net = NativeQhbNet(4, seed=11, batch_size=4, num_faulty=0)
        net.enable_trace(65536)
        for i in range(4):
            net.send_input(i, Input.user(f"r-{i}"))
        net.run_until(
            lambda n: all(
                len(n.nodes[i].outputs) >= 2 for i in range(4)
            ),
            chunk=2_000,
        )
        tracks = {}
        for ev in net.drain_trace():
            tracks.setdefault(f"node{ev.args['node']}", []).append(ev)
        return critical_path(tracks)

    a, b = run(), run()
    assert [path_structure(r) for r in a] == [path_structure(r) for r in b]


# ---------------------------------------------------------------------------
# Live clusters: consistency on both arms, /diag over HTTP
# ---------------------------------------------------------------------------


def _get(url: str) -> bytes:
    return urllib.request.urlopen(url, timeout=10).read()


def _assert_consistent(records, min_epochs: int) -> None:
    assert len(records) >= min_epochs
    for rec in records:
        ts = [p["t"] for p in rec["path"]]
        assert ts == sorted(ts), rec
        assert all(
            rec["t_open"] - 1e-9 <= t <= rec["t_commit"] + 1e-9 for t in ts
        ), rec
        stages = [p["stage"] for p in rec["path"]]
        assert stages == [s for s in STAGES if s in stages]
        assert {"epoch.commit", "rbc.deliver", "ba.decide"} <= set(stages)


def _run_cluster_case(node_impl):
    c = LocalCluster(4, seed=0, node_impl=node_impl)
    with c:
        port = c.serve_obs().port
        c.drive_to(range(4), 2, timeout_s=EPOCH_TIMEOUT_S, tag="cp")
        # /diag and /trace.json answer mid-run (content asserted below
        # on the frozen rings — the cluster keeps committing between
        # any two live snapshots, so only schema is checked here)
        d = json.loads(_get(f"http://127.0.0.1:{port}/diag"))
        json.loads(_get(f"http://127.0.0.1:{port}/trace.json"))
        text = _get(f"http://127.0.0.1:{port}/metrics").decode()
    assert not d["stalled"] and d["verdict"] is None
    # rings are frozen now: the live analysis and the post-mortem
    # analysis of the SAME state must agree — identical structure, and
    # timestamps within the Chrome dump's 0.1 µs rounding
    live = critical_path(c.trace_events())
    _assert_consistent(live, 2)
    dumped = critical_path(tracks_from_chrome(c.chrome_trace()))
    assert [path_structure(r) for r in dumped] == [
        path_structure(r) for r in live
    ]
    for dr, lr in zip(dumped, live):
        assert (dr["era"], dr["epoch"]) == (lr["era"], lr["epoch"])
        assert dr["ba_rounds"] == lr["ba_rounds"]
        assert dr["straggler"]["node"] == lr["straggler"]["node"]
        assert dr["straggler"]["stage"] == lr["straggler"]["stage"]
        for dp, lp in zip(dr["path"], lr["path"]):
            assert dp["t"] == pytest.approx(lp["t"], abs=1e-6)
    # ba.rounds + per-node dropped gauges made it to /metrics
    assert 'hbbft_summary{name="ba.rounds"' in text
    assert 'hbbft_gauge{name="trace.0.dropped"} 0' in text


def test_cluster_critical_path_python_arm():
    _run_cluster_case("python")


@pytest.mark.skipif(
    not _native_available(), reason="native engine unavailable"
)
def test_cluster_critical_path_native_and_mixed():
    _run_cluster_case(
        {0: "python", 1: "native", 2: "python", 3: "native"}
    )


# ---------------------------------------------------------------------------
# The seeded stall drill: /diag must name the stuck proposer/phase
# ---------------------------------------------------------------------------


def test_stall_drill_diag_names_stuck_proposer():
    """Byzantine proposer (crash-stop, node 3) + a seeded chaos
    disconnect of an honest minority (node 2): with only two honest
    participants left the cluster cannot close epochs, and /diag must
    say WHY — the partitioned/silent proposers' instances, with the
    link state and a verdict naming a genuinely stuck proposer."""
    from hbbft_tpu.chaos.scheduler import ChaosEvent, ChaosRunner

    c = LocalCluster(4, seed=0, byzantine={3: "crash-stop"})
    with c:
        port = c.serve_obs().port
        base = f"http://127.0.0.1:{port}"
        c.drive_to(range(3), 2, timeout_s=EPOCH_TIMEOUT_S, tag="pre")
        # let crash-stop's 0.75 s deadline pass: the 0/1/2 trio keeps
        # committing (still n-f live), and every epoch opened from here
        # on is guaranteed to carry NO value from the dead proposer 3 —
        # that makes the absent-proposer diagnosis deterministic.
        time.sleep(1.2)
        runner = ChaosRunner(c, [ChaosEvent(0.0, "disconnect", 2)])
        runner.start()
        runner.drain()
        # feed txns so the survivors genuinely try (and fail) to commit
        try:
            c.drive_to([0, 1], 5, timeout_s=4, tag="stall")
        except TimeoutError:
            pass
        # wait out the quiescence threshold against the LAST commit
        deadline = time.monotonic() + EPOCH_TIMEOUT_S
        d = None
        while time.monotonic() < deadline:
            d = json.loads(_get(base + "/diag?stall_s=3"))
            if d["stalled"]:
                break
            time.sleep(0.5)
        assert d is not None and d["stalled"], d
        assert d["verdict"] is not None, d
        # the verdict names a proposer that is REALLY cut off: the
        # crashed Byzantine proposer (3, silent since ~0.75 s in, so
        # it never proposed the stuck epoch — "no value received" on
        # every live node) or the partitioned honest minority (2)
        assert d["verdict"]["proposer"] in (2, 3), d["verdict"]
        assert d["verdict"]["phase"] == "rbc", d["verdict"]
        assert d["verdict"].get("absent"), d["verdict"]
        # the crashed proposer's absence is visible on the survivors
        stuck3 = [
            s
            for s in d["stuck"]
            if s["proposer"] == 3 and s["node"] in ("node0", "node1")
        ]
        assert stuck3 and all(s["phase"] == "rbc" for s in stuck3), d["stuck"]
        # the link plane saw the partition: some honest node reports
        # peer 2 down (the chaos.disconnect landed on the cluster track)
        assert any(
            2 in st.get("disconnected", ())
            for t, st in d["links"].items()
            if t in ("node0", "node1")
        ), d["links"]
        # chaos event recorded on the cluster track for the post-mortem
        assert any(
            e.name == "chaos.disconnect"
            for e in c.trace_events().get("cluster", [])
        )
        c.reconnect(2)


# ---------------------------------------------------------------------------
# tools/analyze.py CLI error paths (missing / truncated / wrong-shape
# trace.json, empty tracks).  The happy path has golden-fixture coverage
# above; these pin that a bad input is a clean exit-2 diagnostic on
# stderr, never a traceback, and that an event-free dump is an honest
# empty analysis.
# ---------------------------------------------------------------------------


def _run_cli(capsys, argv):
    from tools.analyze import main

    rc = main(argv)
    cap = capsys.readouterr()
    return rc, cap.out, cap.err


def test_cli_missing_trace_file(tmp_path, capsys):
    rc, out, err = _run_cli(capsys, [str(tmp_path / "nope.json")])
    assert rc == 2
    assert "cannot read" in err
    assert "Traceback" not in err


def test_cli_truncated_trace_file(tmp_path, capsys):
    # A dump cut off mid-write (the realistic failure: a killed worker).
    p = tmp_path / "trunc.json"
    good = json.dumps({"traceEvents": [], "otherData": {"t0_unix_s": 1.0}})
    p.write_text(good[: len(good) // 2])
    rc, out, err = _run_cli(capsys, [str(p)])
    assert rc == 2
    assert "truncated" in err


def test_cli_wrong_shape_trace_file(tmp_path, capsys):
    # Valid JSON, wrong document shape (not a Chrome-trace object).
    p = tmp_path / "list.json"
    p.write_text("[1, 2, 3]")
    rc, out, err = _run_cli(capsys, [str(p)])
    assert rc == 2
    assert "not a Chrome-trace document" in err


def test_cli_empty_tracks(tmp_path, capsys):
    # A dump taken before any epoch opened: zero events is an honest
    # empty analysis (exit 0), flagged on stderr, valid --json output.
    p = tmp_path / "empty.json"
    p.write_text(json.dumps({"traceEvents": [], "otherData": {"t0_unix_s": 0}}))
    rc, out, err = _run_cli(capsys, [str(p), "--json"])
    assert rc == 0
    assert "empty tracks" in err
    doc = json.loads(out)
    assert doc["critical_path"] == []
    assert doc["summary"] == {"epochs": 0}


def test_cli_empty_tracks_diag(tmp_path, capsys):
    p = tmp_path / "empty.json"
    p.write_text(json.dumps({"traceEvents": [], "otherData": {"t0_unix_s": 0}}))
    rc, out, err = _run_cli(capsys, [str(p), "--json", "--diag"])
    assert rc == 0
    doc = json.loads(out)
    assert doc["diag"]["stalled"] is False
    assert doc["diag"]["open_epochs"] == {}
