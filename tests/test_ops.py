"""GF(256)/Reed-Solomon and Merkle unit tests."""

import random

import numpy as np
import pytest

from hbbft_tpu.ops.gf256 import (
    ReedSolomon,
    encoding_matrix,
    gf_inv,
    gf_mat_inv,
    gf_matmul,
    gf_mul,
)
from hbbft_tpu.ops.merkle import MerkleTree, Proof


def test_gf_field_laws():
    rng = random.Random(0)
    for _ in range(200):
        a, b, c = rng.randrange(256), rng.randrange(256), rng.randrange(256)
        assert gf_mul(a, b) == gf_mul(b, a)
        assert gf_mul(a, gf_mul(b, c)) == gf_mul(gf_mul(a, b), c)
        # distributivity over XOR (field addition)
        assert gf_mul(a, b ^ c) == gf_mul(a, b) ^ gf_mul(a, c)
    for a in range(1, 256):
        assert gf_mul(a, gf_inv(a)) == 1


def test_gf_matrix_inverse():
    rng = np.random.RandomState(1)
    for n in (1, 3, 8):
        while True:
            m = rng.randint(0, 256, size=(n, n)).astype(np.uint8)
            try:
                inv = gf_mat_inv(m)
                break
            except ValueError:
                continue
        assert np.array_equal(gf_matmul(m, inv), np.eye(n, dtype=np.uint8))


def test_encoding_matrix_systematic_and_mds():
    k, n = 4, 10
    m = encoding_matrix(k, n)
    assert np.array_equal(m[:k], np.eye(k, dtype=np.uint8))
    # MDS property: every k-row submatrix is invertible (spot check many).
    rng = random.Random(2)
    import itertools

    for rows in itertools.islice(itertools.combinations(range(n), k), 50):
        gf_mat_inv(m[list(rows)])  # raises if singular


@pytest.mark.parametrize("k,n", [(1, 1), (2, 4), (4, 10), (22, 64)])
def test_rs_roundtrip(k, n):
    rng = random.Random(k * 100 + n)
    data = [bytes(rng.randrange(256) for _ in range(33)) for _ in range(k)]
    rs = ReedSolomon(k, n)
    shards = rs.encode(data)
    assert shards[:k] == data  # systematic
    # Reconstruct from a random k-subset (worst case: all parity).
    idxs = rng.sample(range(n), k)
    rec = rs.reconstruct({i: shards[i] for i in idxs})
    assert rec == data
    if n - k >= 1:
        rec2 = rs.reconstruct({i: shards[i] for i in range(n - k, n)})
        assert rec2 == data


def test_merkle_proofs():
    leaves = [f"shard-{i}".encode() for i in range(10)]
    tree = MerkleTree(leaves)
    for i in range(10):
        p = tree.proof(i)
        assert p.validate(10)
        assert p.root == tree.root
    # Tampered value / index / path all fail.
    p = tree.proof(3)
    assert not Proof(b"evil", p.index, p.path, p.root).validate(10)
    assert not Proof(p.value, 4, p.path, p.root).validate(10)
    assert not Proof(p.value, p.index, p.path[:-1], p.root).validate(10)
    assert not Proof(p.value, p.index, p.path, b"\x00" * 32).validate(10)
    # Single-leaf tree edge case.
    t1 = MerkleTree([b"only"])
    assert t1.proof(0).validate(1)
