"""Epoch-state arena + batched sha3 plane (ISSUE 17).

Structure:

* **sha3 batch fuzz** — ``hbe_sha3_batch`` in BOTH dispatch arms
  (``hbe_simd_force``, the same shared cell as the field plane) against
  ``hashlib.sha3_256``; count edges straddle the 8-lane grouping
  (1/7/8/9/16/17) and msg_len edges straddle the SHA3-256 rate
  boundaries (135/136/137 and the two-block 271/272), plus empty
  messages.
* **Stats accounting** — the batch counters' exact deltas per call,
  including that ``ifma_msgs`` counts only full groups of 8 and only
  when the IFMA arm resolved.
* **Arena identity** — the same N=4 script (3 plain epochs + a voted
  era change) byte-identical across ``HBBFT_TPU_ARENA=0/1`` x forced
  SIMD arms: batch sequences, fault logs, delivered counts.  The
  ARENA=0 arm frees every epoch's blocks instead of recycling — same
  containers, same carve order, outputs identical by construction
  (docs/INVARIANTS.md "epoch-state arena"), and this pins it.
* **Telemetry sanity** — ``arena_stats()`` high-water marks / resets /
  recycle knob, and that a protocol run actually routes hashing through
  the batch plane (``batch_msgs`` grows).

On hosts without AVX-512 IFMA the force-1 arm resolves to scalar and
the cross-arm legs degenerate to scalar-vs-scalar (still valid, just
not discriminating).
"""

import ctypes
import hashlib
import os
import random

import pytest

from hbbft_tpu import native_engine
from hbbft_tpu.protocols.dynamic_honey_badger import Change
from hbbft_tpu.protocols.queueing_honey_badger import Input

pytestmark = pytest.mark.skipif(
    not native_engine.available(), reason="native engine unavailable"
)

BATCH_SIZE = 4
SESSION = b"sha3-arena-tier"


@pytest.fixture
def lib():
    lib = native_engine.get_lib()
    yield lib
    lib.hbe_simd_force(-1)  # back to HBBFT_TPU_SIMD/auto


def _arms(lib):
    for want in (0, 1):
        got = int(lib.hbe_simd_force(want))
        if want == 1 and not lib.hbe_simd_compiled():
            assert got == 0
        yield want, got


def _sha3_stats(lib):
    buf = (ctypes.c_uint64 * 4)()
    lib.hbe_sha3_stats(buf)
    return tuple(int(x) for x in buf)


def _batch(lib, msgs):
    """Drive hbe_sha3_batch over equal-length msgs; return digests."""
    count = len(msgs)
    msg_len = len(msgs[0])
    out = (ctypes.c_uint8 * (32 * count))()
    lib.hbe_sha3_batch(b"".join(msgs), msg_len, count, out)
    return [bytes(out[32 * i : 32 * i + 32]) for i in range(count)]


def test_sha3_batch_matches_hashlib_both_arms(lib):
    rng = random.Random(1701)
    # rate boundaries for SHA3-256 (rate = 136 bytes): one block with
    # and without room for padding, and the two-block analogues
    lens = [0, 1, 31, 32, 135, 136, 137, 271, 272, 300]
    counts = [1, 2, 7, 8, 9, 16, 17]
    for mode, _ in _arms(lib):
        for msg_len in lens:
            for count in counts:
                msgs = [
                    bytes(rng.getrandbits(8) for _ in range(msg_len))
                    for _ in range(count)
                ]
                want = [hashlib.sha3_256(m).digest() for m in msgs]
                assert _batch(lib, msgs) == want, (mode, msg_len, count)


def test_sha3_stats_accounting(lib):
    rng = random.Random(1702)
    for mode, got in _arms(lib):
        for count in (3, 8, 19):
            msgs = [bytes(rng.getrandbits(8) for _ in range(64))
                    for _ in range(count)]
            before = _sha3_stats(lib)
            _batch(lib, msgs)
            after = _sha3_stats(lib)
            assert after[0] - before[0] == 1, mode  # batch_calls
            assert after[1] - before[1] == count, mode  # batch_msgs
            # ifma_msgs counts whole groups of 8, only on the IFMA arm
            want_ifma = (count // 8) * 8 if got else 0
            assert after[2] - before[2] == want_ifma, (mode, count)


def _run_script(arena_env, simd_force):
    """One native run of the shared script under the given arms; env
    must be set BEFORE NativeQhbNet creation (hbe_create reads the
    knob), simd force flips the shared dispatch cell in-process."""
    lib = native_engine.get_lib()
    prev = os.environ.get("HBBFT_TPU_ARENA")
    if arena_env is None:
        os.environ.pop("HBBFT_TPU_ARENA", None)
    else:
        os.environ["HBBFT_TPU_ARENA"] = arena_env
    lib.hbe_simd_force(simd_force)
    try:
        nat = native_engine.NativeQhbNet(
            4, seed=11, batch_size=BATCH_SIZE, num_faulty=0, session_id=SESSION
        )
        # 3 plain epochs
        for k in range(3):
            for nid in range(4):
                nat.send_input(nid, Input.user(f"a{k}-{nid}"))
            nat.run_until(
                lambda e, w=k + 1: all(
                    len(e.nodes[i].outputs) >= w for i in e.correct_ids
                ),
                chunk=1,
            )
        # era change: vote node 3 out (scalar-suite DKG rides consensus)
        keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
        keep.pop(3)
        change = Change.node_change(keep)
        for nid in range(4):
            nat.send_input(nid, Input.change(change))

        def done(e):
            return all(
                any(b.change.kind == "complete" for b in e.nodes[i].outputs)
                for i in e.correct_ids
            )

        for r in range(8):
            if done(nat):
                break
            for nid in range(4):
                nat.send_input(nid, Input.user(f"e{r}-{nid}"))
            nat.run_until(
                lambda e, w=r + 4: all(
                    len(e.nodes[i].outputs) >= w for i in e.correct_ids
                ),
                chunk=1,
            )
        assert done(nat)
        batches = [
            [
                (b.era, b.epoch, b.contributions, b.change, b.join_plan)
                for b in nat.nodes[i].outputs
            ]
            for i in nat.correct_ids
        ]
        faults = [nat.faults(i) for i in nat.correct_ids]
        stats = nat.arena_stats()
        delivered = nat.delivered
        nat.close()
        return batches, faults, delivered, stats
    finally:
        lib.hbe_simd_force(-1)
        if prev is None:
            os.environ.pop("HBBFT_TPU_ARENA", None)
        else:
            os.environ["HBBFT_TPU_ARENA"] = prev


def test_arena_identity_epochs_and_era_change():
    """The whole ARENA x SIMD matrix commits byte-identical output."""
    runs = {}
    for arena_env in ("1", "0"):
        for simd in (0, 1):
            batches, faults, delivered, stats = _run_script(arena_env, simd)
            runs[(arena_env, simd)] = (batches, faults, delivered)
            assert stats["recycle"] == int(arena_env)
            assert stats["hwm_max"] > 0
            assert stats["hwm_sum"] >= stats["hwm_max"]
            # every node resets its watermark at every epoch open (incl.
            # the post-era restart): >= 4 epochs x 4 nodes
            assert stats["resets"] >= 16
    ref = runs[("1", 0)]
    for key, got in runs.items():
        assert got == ref, f"arm {key} diverged from (arena=1, scalar)"


def test_protocol_run_feeds_batch_plane():
    """A plain epoch routes Merkle/KDF hashing through the batch entry
    (the counters are library-global: compare deltas)."""
    lib = native_engine.get_lib()
    before = _sha3_stats(lib)
    nat = native_engine.NativeQhbNet(4, seed=7, batch_size=BATCH_SIZE)
    for nid in range(4):
        nat.send_input(nid, Input.user(f"p{nid}"))
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids)
    )
    st = nat.arena_stats()
    assert st["hwm_max"] > 0 and st["resets"] >= 4
    assert st["recycle"] == (os.environ.get("HBBFT_TPU_ARENA", "1") != "0")
    nat.close()
    after = _sha3_stats(lib)
    assert after[1] > before[1]  # batch_msgs grew
    assert after[3] > before[3]  # single_msgs (ct digest path) grew
