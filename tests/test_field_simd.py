"""Vectorized field-arithmetic plane (ISSUE 14): both dispatch arms of
the batched Montgomery kernels against the 4x64 oracle and the
pure-Python crypto path.

Structure:

* **Kernel fuzz** — random vectors (canonical AND non-canonical/
  congruent values at the boundaries, odd tail lengths) through
  ``hbe_field_*`` in BOTH arms (``hbe_simd_force``), checked against
  plain Python big-int arithmetic mod r — the same oracle discipline as
  the TPU crypto tests (pure-Python is the source of truth).
* **Oracle cross-check** — a scalar-suite threshold-signature combine
  and a DKG-style interpolation through ``hbe_scalar_interp_sum`` in
  both arms vs ``crypto/poly.py`` (the pure-Python path the engine
  mirrors).
* **Protocol identity** — a full NativeQhbNet epoch byte-identical
  across forced arms (the dispatch-identity contract,
  docs/INVARIANTS.md; the full equivalence suites pin the same thing
  against the Python net via the HBBFT_TPU_SIMD env arms).
* **Wide-NodeSet smoke** — an era change on a forced ``-DHBE_WORDS=8``
  build at small N, byte-identical to the default-width build (the
  post-256-node-cap path of ROADMAP item 4; scale runs past N=256 pick
  the wide build automatically).

On hosts without AVX-512 IFMA the force-1 arm resolves to scalar and
the cross-arm tests degenerate to scalar-vs-scalar (still valid, just
not discriminating) — the kernels' scalar arm stays covered everywhere.
"""

import ctypes
import random

import pytest

from hbbft_tpu import native_engine
from hbbft_tpu.crypto import poly
from hbbft_tpu.crypto.suite import ScalarSuite

pytestmark = pytest.mark.skipif(
    not native_engine.available(), reason="native engine unavailable"
)

R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001


@pytest.fixture
def lib():
    lib = native_engine.get_lib()
    yield lib
    lib.hbe_simd_force(-1)  # back to HBBFT_TPU_SIMD/auto


def _be(x: int, n: int = 32) -> bytes:
    return int(x).to_bytes(n, "big")


def _arms(lib):
    """Force each dispatch arm in turn; the forced mode must resolve
    exactly (force-1 clamps to scalar only on non-IFMA hosts)."""
    for want in (0, 1):
        got = int(lib.hbe_simd_force(want))
        if want == 1 and not lib.hbe_simd_compiled():
            assert got == 0
        yield want, got


def test_simd_mode_reporting(lib):
    assert int(lib.hbe_simd_compiled()) in (0, 1)
    assert int(lib.hbe_simd_mode()) in (0, 1)
    assert int(lib.hbe_simd_force(0)) == 0
    assert int(lib.hbe_simd_force(-1)) == int(lib.hbe_simd_mode())


def test_mul_batch_fuzz_both_arms(lib):
    rng = random.Random(1401)
    for mode, _ in _arms(lib):
        for _ in range(25):
            n = rng.choice([1, 2, 3, 7, 8, 9, 15, 16, 17, 40, 101])
            a = [rng.randrange(R) for _ in range(n)]
            b = []
            for _ in range(n):
                v = rng.randrange(R)
                # non-canonical congruent encodings on ONE side (the
                # engine's precondition: at least one side canonical)
                if rng.random() < 0.4 and v + R < 1 << 256:
                    v += R
                b.append(v)
            if n >= 2:  # boundary values
                a[0], b[0] = R - 1, R - 1
                # max 256-bit non-canonical operand against canonical 0
                # (the top-limb carry edge of load8/mont_mul8)
                a[1], b[1] = 0, (1 << 256) - 1
            if n >= 3:
                a[2], b[2] = 1, 2 * R - 2
            out = (ctypes.c_uint8 * (32 * n))()
            lib.hbe_field_mul_batch(
                b"".join(_be(x) for x in a), b"".join(_be(x) for x in b), n, out
            )
            got = [
                int.from_bytes(bytes(out[32 * i : 32 * i + 32]), "big")
                for i in range(n)
            ]
            assert got == [(x * y) % R for x, y in zip(a, b)], mode


def test_dot_and_rlc_accum_fuzz_both_arms(lib):
    rng = random.Random(1402)
    for mode, _ in _arms(lib):
        for _ in range(25):
            n = rng.choice([1, 3, 8, 9, 31, 32, 33, 64, 101])
            a = [rng.randrange(R) for _ in range(n)]
            b = [rng.randrange(R) for _ in range(n)]
            o32 = (ctypes.c_uint8 * 32)()
            lib.hbe_field_dot(
                b"".join(_be(x) for x in a), b"".join(_be(x) for x in b), n, o32
            )
            assert (
                int.from_bytes(bytes(o32), "big")
                == sum(x * y for x, y in zip(a, b)) % R
            ), mode
            # RLC accumulate is an EXACT integer (not a residue): shares
            # may be non-canonical wire values
            x = [
                v + R if rng.random() < 0.3 and v + R < 1 << 256 else v
                for v in a
            ]
            cs = [rng.randrange(1, 1 << 64) for _ in range(n)]
            o64 = (ctypes.c_uint8 * 64)()
            lib.hbe_field_rlc_accum(
                b"".join(_be(v) for v in x),
                b"".join(_be(c, 8) for c in cs),
                n,
                o64,
            )
            assert int.from_bytes(bytes(o64), "big") == sum(
                c * v for c, v in zip(cs, x)
            ), mode


def test_lagrange_coefficients_vs_python_oracle(lib):
    rng = random.Random(1403)
    for mode, _ in _arms(lib):
        for k in (1, 2, 3, 7, 8, 9, 33, 101):
            idxs = rng.sample(range(300), k)
            out = (ctypes.c_uint8 * (32 * k))()
            lib.hbe_field_lagrange((ctypes.c_int32 * k)(*idxs), k, out)
            oracle = poly.lagrange_coefficients(idxs, R)
            for i, idx in enumerate(idxs):
                got = int.from_bytes(bytes(out[32 * i : 32 * i + 32]), "big")
                assert got == oracle[idx], (mode, k, idx)


def test_interp_and_combine_vs_python_oracle(lib):
    """A scalar-suite threshold combine through hbe_scalar_interp_sum in
    both arms vs the pure-Python crypto path (poly.interpolate and a
    hand combine over real suite shares)."""
    suite = ScalarSuite()
    rng = random.Random(1404)
    from hbbft_tpu.crypto.keys import SecretKeySet

    sks = SecretKeySet.random(3, rng, suite)
    pks = sks.public_keys()
    msg = b"simd-combine-oracle"
    shares = {i: sks.secret_key_share(i).sign(msg) for i in range(7)}
    # pure-Python expected signature value: Lagrange over the share
    # scalars (ScalarSuite group elements are ints)
    idxs = [0, 2, 3, 5]
    lam = poly.lagrange_coefficients(idxs, R)
    expected = (
        sum(lam[i] * shares[i].g2.value for i in idxs) % R
    )
    r_be = _be(R)
    for mode, _ in _arms(lib):
        xs = (ctypes.c_int32 * len(idxs))(*[i + 1 for i in idxs])
        ys = b"".join(_be(shares[i].g2.value) for i in idxs)
        counts = (ctypes.c_int32 * 1)(len(idxs))
        out = (ctypes.c_uint8 * 32)()
        ok = int(lib.hbe_scalar_interp_sum(xs, ys, counts, 1, r_be, out))
        assert ok == 1
        assert int.from_bytes(bytes(out), "big") == expected, mode
        # grouped interpolation (the SyncKeyGen.generate shape): the sum
        # of per-group interpolations matches poly.interpolate
        pts = [[(x, rng.randrange(R)) for x in (1, 2, 3, 4)] for _ in range(3)]
        exp_sum = sum(poly.interpolate(g, R) for g in pts) % R
        gxs = (ctypes.c_int32 * 12)(*[x for g in pts for (x, _) in g])
        gys = b"".join(_be(y) for g in pts for (_, y) in g)
        gcounts = (ctypes.c_int32 * 3)(4, 4, 4)
        out2 = (ctypes.c_uint8 * 32)()
        ok = int(lib.hbe_scalar_interp_sum(gxs, gys, gcounts, 3, r_be, out2))
        assert ok == 1
        assert int.from_bytes(bytes(out2), "big") == exp_sum, mode
    # end-to-end: the keys.py combine (which routes through the same
    # native kernel when available) agrees with the oracle value
    sig = pks.combine_signatures({i: shares[i] for i in idxs})
    assert sig.g2.value == expected


def test_epoch_byte_identical_across_arms(lib):
    """The dispatch-identity contract at the protocol level: one
    NativeQhbNet epoch per forced arm, identical batches and faults."""
    results = []
    for mode, got in _arms(lib):
        nat = native_engine.NativeQhbNet(4, seed=9, batch_size=3,
                                         session_id=b"simd-arms")
        for i in nat.correct_ids:
            nat.send_input(i, ("tx", i))
        nat.run_until(
            lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
            chunk=1,
        )
        results.append(
            (
                got,
                [
                    [
                        (b.era, b.epoch, b.contributions)
                        for b in nat.nodes[i].outputs[:1]
                    ]
                    for i in nat.correct_ids
                ],
                sorted(
                    (i, f) for i in nat.correct_ids for f in nat.faults(i)
                ),
            )
        )
        nat.close()
    assert results[0][1:] == results[1][1:]


def test_w8_era_change_smoke():
    """The post-cap wide-NodeSet path (ROADMAP item 4): a full era
    change on a forced -DHBE_WORDS=8 build, byte-identical to the
    default-width build at the same seed.  N stays small — the width
    must be inert; N>256 scale runs pick wide builds automatically."""
    from hbbft_tpu.protocols.dynamic_honey_badger import Change
    from hbbft_tpu.protocols.queueing_honey_badger import Input

    if native_engine.get_lib(8) is None:
        pytest.skip("w8 engine build unavailable")

    def run(words):
        nat = native_engine.NativeQhbNet(
            4, seed=5, batch_size=3, session_id=b"w8-era",
            engine_words=words,
        )
        assert nat.lib.hbe_words() >= (words or 4)
        keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
        keep.pop(3)
        for i in nat.correct_ids:
            nat.send_input(i, Input.change(Change.node_change(keep)))

        def era_done(e):
            return all(
                any(b.change.kind == "complete" for b in e.nodes[i].outputs)
                for i in e.correct_ids
            )

        rounds = 1
        while not era_done(nat) and rounds < 12:
            for i in nat.correct_ids:
                nat.send_input(i, Input.user(("era-tx", rounds, i)))
            rounds += 1
            nat.run_until(
                lambda e, w=rounds: all(
                    len(e.nodes[i].outputs) >= w for i in e.correct_ids
                ),
                chunk=1,
            )
        assert era_done(nat), "era change did not complete"
        out = [
            [
                (b.era, b.epoch, b.change.kind, b.contributions)
                for b in nat.nodes[i].outputs
            ]
            for i in nat.correct_ids
        ]
        faults = sorted((i, f) for i in nat.correct_ids for f in nat.faults(i))
        nat.close()
        return out, faults

    assert run(8) == run(None)
