"""Sanitizer tier: the native engine under ASan/UBSan/TSan.

``make -C native asan|ubsan|tsan`` builds instrumented engine libraries;
``HBBFT_TPU_ENGINE_LIB`` (hbbft_tpu/native_engine.py) loads them in place
of the normal build.  Python itself is not instrumented, so the
sanitizer runtime must be LD_PRELOADed into the subprocess; each test
therefore drives a fresh interpreter rather than loading the lib here.

The driven workload is the small-N native epoch of the equivalence
suites (ASan/UBSan, default tier) and an ``engine_run_mt`` multi-thread
epoch (TSan, slow tier — the multicore worker rules in CLAUDE.md are
exactly what TSan checks mechanically).  The driver never imports jax:
the protocol plane is pure Python + the C++ engine, which keeps the
sanitized process small and the reports clean.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no C++ toolchain",
)

# One complete epoch at N=4 (one silent-faulty by default), asserting
# the correct nodes commit identical batch sequences — a miniature of
# tests/test_native_engine.py's fidelity contract, run for the
# sanitizer's benefit rather than for protocol coverage.
DRIVER = """
import sys
from hbbft_tpu import native_engine
assert native_engine.available(), "sanitized engine failed to load"
threads = int(sys.argv[1]) if len(sys.argv) > 1 else 0
kw = {"threads": threads} if threads else {}
nat = native_engine.NativeQhbNet(
    4, seed=1, batch_size=3, session_id=b"sanitizer", **kw
)
for i in range(4):
    nat.send_input(i, ("tx", i))
# chunk must batch MANY deliveries per engine call in threaded mode:
# engine_run_mt takes one generation per call of at most `chunk` queue
# items, and a generation with a single destination runs inline on the
# calling thread — chunk=1 would make the TSan run single-threaded and
# vacuous.  256 yields multi-destination generations (real worker
# threads) and the predicate still stops us within one chunk of the
# first batch (no QHB empty-epoch runaway).
nat.run_until(
    lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
    chunk=1 if threads == 0 else 256,
)
keys = [
    [(b.era, b.epoch, b.contributions) for b in nat.nodes[i].outputs[:1]]
    for i in nat.correct_ids
]
assert all(k == keys[0] for k in keys), "correct nodes diverged"
print("SANITIZED-EPOCH-OK")

# A full era change drives the round-6 batch-digest entry points under
# the sanitizer: hbe_dkg_ack_check_batch / hbe_dkg_part_check_batch
# (registry copy-out + batched KEM/Horner), hbe_scalar_interp_sum /
# hbe_scalar_combine_unmask, and the shared ct-hash cache.
from hbbft_tpu.protocols.dynamic_honey_badger import Change
from hbbft_tpu.protocols.queueing_honey_badger import Input

keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
keep.pop(3)
for i in nat.correct_ids:
    nat.send_input(i, Input.change(Change.node_change(keep)))

def era_done(e):
    return all(
        any(b.change.kind == "complete" for b in e.nodes[i].outputs)
        for i in e.correct_ids
    )

rounds = 1
while not era_done(nat) and rounds < 12:
    for i in nat.correct_ids:
        nat.send_input(i, Input.user(("era-tx", rounds, i)))
    rounds += 1
    nat.run_until(
        lambda e, w=rounds: all(
            len(e.nodes[i].outputs) >= w for i in e.correct_ids
        ),
        chunk=1 if threads == 0 else 256,
    )
assert era_done(nat), "sanitized era change did not complete"
print("SANITIZED-ERA-OK")

# Round 7: a deferred-RLC epoch with corrupt COIN/DECRYPT shares from
# node 0 — every group containing one of its shares FAILS the RLC check
# and runs the bisection (rlc_assign_range down to per-item leaves,
# the CSR group scratch, the folded group continuations): the new
# branchy code most likely to hide an OOB, exercised under the
# sanitizer with verdicts ending in real fault entries.
import ctypes
from hbbft_tpu.native_engine import _TAMPER_CB

nat2 = native_engine.NativeQhbNet(
    4, seed=1, batch_size=3, session_id=b"sanitizer-rlc",
    rlc=True, flush_every=0,
)
lib, h = nat2.lib, nat2.handle
mod = nat2._suite.scalar_modulus

def corrupt(sender, mtype, era, epoch, proposer, rnd):
    if mtype not in (8, 10):  # BA_COIN / HB_DECRYPT
        return
    buf = (ctypes.c_uint8 * 32)()
    lib.hbe_tamper_share(h, buf)
    out = (2 * int.from_bytes(bytes(buf), "big") % mod).to_bytes(32, "big")
    ob = (ctypes.c_uint8 * 32).from_buffer_copy(out)
    lib.hbe_tamper_set_share(h, ob, 32)

cb = _TAMPER_CB(corrupt)
lib.hbe_set_tamper(h, cb)
lib.hbe_set_tampered(h, 0, 1)
# node 3 is silent-faulty (default f=1); nodes 1/2 are the honest
# observers whose fault logs must pin node 0's corrupt shares.
for i in nat2.correct_ids:
    nat2.send_input(i, ("rlc-tx", i))
nat2.run_until(
    lambda e: all(len(e.nodes[i].outputs) >= 1 for i in (1, 2)),
    chunk=256,
)
kinds = {k for i in (1, 2) for (_, k) in nat2.faults(i)}
assert "threshold_sign:invalid-share" in kinds, kinds
assert int(lib.hbe_prof_count(h, 11)) > 0, "RLC verdict pass never ran"
print("SANITIZED-RLC-BISECT-OK")

# Round 9: the message-boundary wire API on hostile input.  A cluster-
# mode engine produces real egress frames; every truncation and a bit-
# flip sweep of one goes through hbe_wire_classify (decode-only), and a
# mixed good/corrupt/short batch through hbe_node_ingest_frames — the
# byte-parsing surfaces a Byzantine peer reaches first, where an OOB
# read hides most easily.  Verdicts are parity-pinned elsewhere
# (tests/test_transport_native.py); the sanitizer's job here is the
# memory safety of the reject paths.
import random as _wrng
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.native_engine import NativeNodeEngine
from hbbft_tpu.transport.cluster import build_netinfo

_suite = ScalarSuite()
node = NativeNodeEngine(
    0, build_netinfo(4, 1, 0, _suite, 0), seed=0, batch_size=3,
    session_id=b"san-wire",
)
node.handle_input(Input.user("wire-tx"))
node.run()
frames = []
node.drain_egress(lambda d, p: frames.append(p))
assert frames, "cluster-mode engine produced no egress"
payload = frames[0]
wl = node.lib
for cut in range(len(payload) + 1):
    wl.hbe_wire_classify(payload[:cut], cut)
rng9 = _wrng.Random(5)
mut = payload
for _ in range(500):
    i = rng9.randrange(len(payload))
    mut = payload[:i] + bytes([payload[i] ^ (1 << rng9.randrange(8))]) + payload[i + 1:]
    wl.hbe_wire_classify(mut, len(mut))
batch = [payload[: len(payload) // 2], b"", bytes([255]) * 9, mut, payload]
node.ingest([1, 2, 99, 0, 2], batch)  # 99 out of range, 0 = local: both bad
node.run()
assert node.stats()["bad_payload"] >= 2, node.stats()
print("SANITIZED-WIRE-OK")

# Round 20: the MSGB wire fast path on hostile input.  Real per-dest
# MSGB bodies from hbe_node_egress_drain_msgb come back through
# hbe_node_ingest_wire interleaved with structurally-corrupt records —
# claim mismatch, truncation, trailing garbage, an inflated count —
# the exact C walk where an OOB read hides; then a clamped max_body
# drain exercises the group-split path.  Verdict parity is pinned in
# tests/test_transport_native.py; the sanitizer's job here is the
# memory safety of the reject paths.
nodeb = NativeNodeEngine(
    0, build_netinfo(4, 1, 0, _suite, 0), seed=0, batch_size=3,
    session_id=b"san-msgb",
)
nodeb.handle_input(Input.user("msgb-tx"))
nodeb.run()
groups = []
nodeb.drain_egress_msgb(lambda d, nm, b: groups.append((nm, b)), 1 << 20)
assert any(nm > 1 for nm, _ in groups), "no MSGB groups drained"
gnm, gbody = next((nm, b) for nm, b in groups if nm > 1)
records = [
    (gnm, gbody),                                     # clean batch
    (gnm + 1, gbody),                                 # claim mismatch
    (gnm, gbody[: len(gbody) // 2]),                  # truncated
    (gnm, gbody + bytes([0, 7])),                     # trailing garbage
    (gnm + 9, (gnm + 9).to_bytes(4, "big") + gbody[4:]),  # inflated count
    (1, b""),                                         # empty body
    (0, gbody),                                       # MSGB bytes as MSG
]
before20 = nodeb.stats()
nodeb.ingest_wire([1, 2, 3, 1, 2, 3, 1], records)
nodeb.run()
after20 = nodeb.stats()
assert after20["handled"] - before20["handled"] >= gnm, after20
assert after20["bad_payload"] - before20["bad_payload"] >= 5, after20
nodeb.drain_egress_msgb(lambda d, nm, b: None, 1)  # clamped split drain
print("SANITIZED-MSGB-OK")

# Round 11: one mixed good/equivocating/corrupt ingest batch.  The
# chaos plane's equivocation/corrupt-share variants are VALID wire
# traffic (TamperingAdversary rewrites re-encoded over the same serde
# grammar) — the decoder must classify and ingest them interleaved with
# corrupt and truncated frames without the sanitizer noticing anything.
from hbbft_tpu.chaos.strategies import (
    EQUIVOCABLE_KINDS, SHARE_KINDS, tamper_payload,
)

rng11 = _wrng.Random(11)
node3 = NativeNodeEngine(
    0, build_netinfo(4, 1, 0, _suite, 0), seed=0, batch_size=3,
    session_id=b"san-chaos",
)
node3.handle_input(Input.user("chaos-tx"))
node3.run()
frames3 = []
node3.drain_egress(lambda d, p: frames3.append(p))
variants = []
for p in frames3:
    v = tamper_payload(p, rng11, _suite, EQUIVOCABLE_KINDS | SHARE_KINDS)
    if v is not None:
        variants.append(v)
assert variants, "no equivocable egress traffic produced"
for v in variants:
    assert int(wl.hbe_wire_classify(v, len(v))) > 0, "variant rejected"
good = frames3[0]
corrupt = bytes([good[0] ^ 0xFF]) + good[1:]
mixed = [
    good,
    variants[0],
    corrupt,
    variants[-1][: max(1, len(variants[-1]) // 2)],
    variants[0] + b"\\x00",  # trailing garbage: reject path
]
node3.ingest([1, 2, 3, 1, 2], mixed)
node3.run()
assert node3.stats()["handled"] >= 2, node3.stats()
print("SANITIZED-CHAOS-OK")

# Round 15: the vectorized field plane under the sanitizer, BOTH
# dispatch arms forced in-process (hbe_simd_force).  The kernel fuzz
# drives the AoS<->SoA conversion/normalization edges (odd tails,
# non-canonical congruent inputs, near-r values) where an OOB or
# carry bug hides; the epoch re-run pins cross-arm protocol identity
# under instrumentation.  On a non-IFMA host force(1) resolves to the
# scalar arm and this degenerates to scalar-vs-scalar (still a valid
# sanitizer pass of the batch plane).
import random as _frng

flib = nat.lib
mod_r = (0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001)
rng15 = _frng.Random(15)
# fixed index set for the cross-arm Lagrange comparison (the fuzz rng
# advances differently per arm; cross-arm identity needs equal inputs)
lag_idxs = _frng.Random(99).sample(range(200), 33)
arm_results = []
for arm in (0, 1):
    got = int(flib.hbe_simd_force(arm))
    for trial in range(6):
        n = rng15.choice([1, 3, 7, 8, 9, 17, 40])
        a = [rng15.randrange(mod_r) for _ in range(n)]
        b = [
            v + mod_r
            if rng15.random() < 0.4 and v + mod_r < (1 << 256)
            else v
            for v in (rng15.randrange(mod_r) for _ in range(n))
        ]
        ab = b"".join(x.to_bytes(32, "big") for x in a)
        bb = b"".join(x.to_bytes(32, "big") for x in b)
        out = (ctypes.c_uint8 * (32 * n))()
        flib.hbe_field_mul_batch(ab, bb, n, out)
        got_v = [
            int.from_bytes(bytes(out[32 * i : 32 * i + 32]), "big")
            for i in range(n)
        ]
        assert got_v == [(x * y) % mod_r for x, y in zip(a, b)], (arm, trial)
        o32 = (ctypes.c_uint8 * 32)()
        flib.hbe_field_dot(ab, bb, n, o32)
        assert int.from_bytes(bytes(o32), "big") == (
            sum(x * y for x, y in zip(a, b)) % mod_r
        ), (arm, trial)
    k = 33
    outl = (ctypes.c_uint8 * (32 * k))()
    flib.hbe_field_lagrange((ctypes.c_int32 * k)(*lag_idxs), k, outl)
    nat15 = native_engine.NativeQhbNet(
        4, seed=3, batch_size=3, session_id=b"sanitizer-simd", **kw
    )
    for i in nat15.correct_ids:
        nat15.send_input(i, ("simd-tx", i))
    nat15.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
        chunk=1 if threads == 0 else 256,
    )
    arm_results.append(
        (
            bytes(outl),
            [
                [
                    (b.era, b.epoch, b.contributions)
                    for b in nat15.nodes[i].outputs[:1]
                ]
                for i in nat15.correct_ids
            ],
        )
    )
    nat15.close()
flib.hbe_simd_force(-1)
assert arm_results[0] == arm_results[1], "SIMD arms diverged"
print("SANITIZED-SIMD-OK")

# Round 17: the epoch arena + batched sha3 plane.  The default arm
# (ARENA=1, every stage above) POISONS recycled blocks under ASan, so
# any use-after-reset in the epoch path already trips; here the
# free-every-epoch arm (HBBFT_TPU_ARENA=0, read at hbe_create) runs
# the opening script too — both reset models sanitized, first-batch
# output pinned identical.  The sha3 batch kernel is fuzzed at the
# SHA3-256 rate boundaries in both dispatch arms against hashlib (the
# x8 gather/scatter absorb in field_ifma.cpp is where an OOB hides).
import hashlib as _hl
import os as _os

for _arm in (0, 1):
    flib.hbe_simd_force(_arm)
    for _mlen in (0, 1, 135, 136, 137, 271, 272):
        for _cnt in (1, 7, 8, 9, 17):
            _msgs = [
                bytes((_arm * 31 + i + j) & 0xFF for j in range(_mlen))
                for i in range(_cnt)
            ]
            _out = (ctypes.c_uint8 * (32 * _cnt))()
            flib.hbe_sha3_batch(b"".join(_msgs), _mlen, _cnt, _out)
            for i in range(_cnt):
                assert (
                    bytes(_out[32 * i : 32 * i + 32])
                    == _hl.sha3_256(_msgs[i]).digest()
                ), (_arm, _mlen, _cnt, i)
flib.hbe_simd_force(-1)

_os.environ["HBBFT_TPU_ARENA"] = "0"
try:
    nat17 = native_engine.NativeQhbNet(
        4, seed=1, batch_size=3, session_id=b"sanitizer", **kw
    )
    for i in range(4):
        nat17.send_input(i, ("tx", i))
    nat17.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
        chunk=1 if threads == 0 else 256,
    )
    keys17 = [
        [(b.era, b.epoch, b.contributions) for b in nat17.nodes[i].outputs[:1]]
        for i in nat17.correct_ids
    ]
    assert keys17 == keys, "ARENA=0 arm diverged from the recycling arm"
    assert nat17.arena_stats()["recycle"] == 0
    nat17.close()
finally:
    _os.environ.pop("HBBFT_TPU_ARENA", None)
print("SANITIZED-ARENA-SHA3-OK")
"""


def _runtime(name: str) -> str:
    """Full path of the sanitizer runtime g++ links against."""
    out = subprocess.run(
        ["g++", f"-print-file-name={name}"],
        capture_output=True,
        text=True,
        check=True,
    ).stdout.strip()
    if not os.path.isabs(out) or not os.path.exists(out):
        pytest.skip(f"{name} runtime not installed")
    return out


def _build(target: str) -> str:
    res = subprocess.run(
        ["make", "-C", NATIVE, target],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert res.returncode == 0, f"make {target} failed:\n{res.stderr[-4000:]}"
    lib = os.path.join(NATIVE, "build", f"libhbbft_engine_{target}.so")
    assert os.path.exists(lib)
    return lib


def _drive(lib: str, preload: str, extra_env: dict, threads: int = 0):
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        # Drop the axon sitecustomize (CLAUDE.md env gotchas): the
        # driver has no jax dependency and the TPU relay must not be
        # touched from a sanitized process.
        "PYTHONPATH": REPO,
        "HBBFT_TPU_ENGINE_LIB": lib,
        "LD_PRELOAD": preload,
        **extra_env,
    }
    cmd = [sys.executable, "-c", DRIVER]
    if threads:
        cmd.append(str(threads))
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=600
    )


def test_asan_native_epoch():
    lib = _build("asan")
    res = _drive(
        lib,
        _runtime("libasan.so"),
        # Python's own allocations "leak" by ASan's lights; the engine
        # checks we care about are heap misuse, not the interpreter's
        # exit-time bookkeeping.
        {"ASAN_OPTIONS": "detect_leaks=0"},
    )
    assert res.returncode == 0, res.stderr[-4000:]
    assert "SANITIZED-EPOCH-OK" in res.stdout
    assert "SANITIZED-ERA-OK" in res.stdout
    assert "SANITIZED-RLC-BISECT-OK" in res.stdout
    assert "SANITIZED-MSGB-OK" in res.stdout
    assert "SANITIZED-SIMD-OK" in res.stdout
    assert "SANITIZED-CHAOS-OK" in res.stdout
    assert "SANITIZED-ARENA-SHA3-OK" in res.stdout
    assert "AddressSanitizer" not in res.stderr


def test_ubsan_native_epoch():
    lib = _build("ubsan")
    res = _drive(lib, _runtime("libubsan.so"), {})
    assert res.returncode == 0, res.stderr[-4000:]
    assert "SANITIZED-EPOCH-OK" in res.stdout
    assert "SANITIZED-ERA-OK" in res.stdout
    assert "SANITIZED-RLC-BISECT-OK" in res.stdout
    assert "SANITIZED-MSGB-OK" in res.stdout
    assert "SANITIZED-SIMD-OK" in res.stdout
    assert "SANITIZED-CHAOS-OK" in res.stdout
    assert "SANITIZED-ARENA-SHA3-OK" in res.stdout
    assert "runtime error" not in res.stderr


@pytest.mark.slow
def test_tsan_multithread_epoch():
    lib = _build("tsan")
    res = _drive(
        lib,
        _runtime("libtsan.so"),
        {"TSAN_OPTIONS": "report_thread_leaks=0"},
        threads=2,
    )
    assert res.returncode == 0, res.stderr[-4000:]
    assert "SANITIZED-EPOCH-OK" in res.stdout
    assert "SANITIZED-ERA-OK" in res.stdout
    assert "SANITIZED-RLC-BISECT-OK" in res.stdout
    assert "SANITIZED-MSGB-OK" in res.stdout
    assert "SANITIZED-SIMD-OK" in res.stdout
    assert "SANITIZED-ARENA-SHA3-OK" in res.stdout
    assert "WARNING: ThreadSanitizer" not in res.stderr
