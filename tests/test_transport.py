"""TCP transport + cluster runtime (ISSUE 4 acceptance surface).

Default-tier budget on the 1-core box: each cluster test drives an N=4
localhost cluster for a handful of epochs — single-digit seconds apiece
in practice, with generous wall caps so a loaded box does not flake
(CLAUDE.md "transport test budgets").  The subprocess-mode test is
``slow``.
"""

from __future__ import annotations

import random
import socket
import time

import pytest

from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.transport import (
    FaultInjector,
    FrameDecoder,
    FrameError,
    KIND_MSG,
    KIND_MSGB,
    LinkFaults,
    LocalCluster,
    PartitionSpec,
    decode_hello,
    decode_msgb,
    encode_frame,
    encode_hello,
    encode_msgb,
    frame_message_count,
    msgb_body,
    validate_msgb,
)
from hbbft_tpu.utils import serde

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 2 s


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def test_frame_roundtrip_incremental():
    payloads = [b"", b"x", b"hello world" * 100]
    stream = b"".join(encode_frame(KIND_MSG, p) for p in payloads)
    dec = FrameDecoder()
    got = []
    # feed byte-by-byte: the decoder must resynchronize on frame edges
    for i in range(len(stream)):
        dec.feed(stream[i : i + 1])
        got.extend(dec.frames())
    assert [p for _, p in got] == payloads
    assert dec.buffered() == 0


def test_frame_oversize_rejected_from_prefix_alone():
    dec = FrameDecoder(max_frame_len=1024)
    # the declared length alone must reject — no payload bytes needed
    dec.feed((1 << 20).to_bytes(4, "big"))
    with pytest.raises(FrameError):
        dec.next_frame()
    # poisoned decoder refuses further input
    with pytest.raises(FrameError):
        dec.feed(b"more")


def test_frame_bad_kind_and_zero_length_rejected():
    import zlib

    dec = FrameDecoder()
    # unknown kind 0x7f with a VALID crc: must die on the kind check,
    # not the crc check
    body = b"\x7f"
    dec.feed(
        (1).to_bytes(4, "big") + zlib.crc32(body).to_bytes(4, "big") + body
    )
    with pytest.raises(FrameError):
        dec.next_frame()
    dec2 = FrameDecoder()
    dec2.feed((0).to_bytes(4, "big"))
    with pytest.raises(FrameError):
        dec2.next_frame()


def test_frame_crc_rejects_payload_bit_flip():
    """Channel corruption anywhere in the frame body dies at the framing
    layer (connection-drop path), so the resume layer's clean-original
    retransmission covers it; without the CRC a payload flip could parse
    and be consumed+ACKed as the honest peer's message."""
    frame = bytearray(encode_frame(KIND_MSG, b"hello world payload"))
    frame[10] ^= 0x04  # flip a payload bit (body starts at offset 8)
    dec = FrameDecoder()
    dec.feed(bytes(frame))
    with pytest.raises(FrameError, match="CRC"):
        dec.next_frame()


def test_encode_refuses_over_limit():
    with pytest.raises(FrameError):
        encode_frame(KIND_MSG, b"x" * 100, max_frame_len=50)


def test_hello_validation():
    frame = encode_hello(3, b"cluster-a")
    dec = FrameDecoder()
    dec.feed(frame)
    kind, payload = dec.next_frame()
    assert decode_hello(payload, b"cluster-a") == 3
    with pytest.raises(FrameError):
        decode_hello(payload, b"cluster-b")  # foreign cluster
    with pytest.raises(FrameError):
        decode_hello(b"\xff garbage", b"cluster-a")
    with pytest.raises(FrameError):
        # wrong version
        decode_hello(serde.dumps((99, b"cluster-a", 3)), b"cluster-a")


def test_framing_fuzz_parity_with_serde():
    """Satellite: truncated/oversized/bit-flipped frames through the
    decoder — no crash ever, and for frames that survive framing the
    payload's accept/reject must match the pure-Python serde decoder
    (the native scan path and limits stay in lockstep, extending the
    tests/test_serde.py fuzz-equivalence pattern to the frame layer)."""
    from hbbft_tpu.protocols.sender_queue import SqMessage

    def pure_loads(data):
        r = serde._Reader(data, None)
        obj = serde._decode(r, 0)
        if r.pos != len(r.data):
            raise serde.DecodeError("trailing bytes")
        return obj

    msg = SqMessage.epoch_started((2, 7))
    enc = serde.dumps(msg)
    frame = encode_frame(KIND_MSG, enc)
    rng = random.Random(1234)

    def sweep(mutated: bytes):
        dec = FrameDecoder(max_frame_len=1 << 16)
        try:
            dec.feed(mutated)
            frames = dec.frames()
        except FrameError:
            return  # rejected at the frame layer: fine
        for kind, payload in frames:
            if kind != KIND_MSG:
                continue
            try:
                got = serde.loads(payload)
            except serde.DecodeError:
                got = "ERR"
            try:
                want = pure_loads(payload)
            except serde.DecodeError:
                want = "ERR"
            assert (got == "ERR") == (want == "ERR")
            if want != "ERR":
                assert got == want

    for cut in range(len(frame)):
        sweep(frame[:cut])
    for _ in range(400):
        i = rng.randrange(len(frame))
        mutated = (
            frame[:i]
            + bytes([frame[i] ^ (1 << rng.randrange(8))])
            + frame[i + 1 :]
        )
        sweep(mutated)
    # oversized declared lengths at every byte of the prefix
    for i in range(4):
        mutated = bytearray(frame)
        mutated[i] = 0xFF
        sweep(bytes(mutated))


# ---------------------------------------------------------------------------
# satellite: MSGB batch frames (round 20 coalescing)
# ---------------------------------------------------------------------------


def test_msgb_grammar_roundtrip_and_rejects():
    """The batch-frame body grammar: roundtrip, count extraction, and
    every structural reject (zero count, bogus count, truncated element
    header, overlong element, trailing bytes) — a batch never partially
    parses."""
    payloads = [b"", b"x", b"hello world" * 40]
    body = msgb_body(payloads)
    assert validate_msgb(body) == 3
    assert decode_msgb(body) == payloads
    frame = encode_msgb(payloads)
    dec = FrameDecoder()
    dec.feed(frame)
    kind, got = dec.next_frame()
    assert kind == KIND_MSGB and got == body
    assert frame_message_count(frame) == 3
    assert frame_message_count(encode_frame(KIND_MSG, b"p")) == 1
    with pytest.raises(FrameError):
        validate_msgb(b"")  # shorter than the count field
    with pytest.raises(FrameError):
        validate_msgb((0).to_bytes(4, "big"))  # zero messages
    with pytest.raises(FrameError):
        # bogus count: claims more elements than the body could hold
        validate_msgb((500).to_bytes(4, "big") + b"\x00" * 8)
    with pytest.raises(FrameError):
        validate_msgb(body[:-1])  # truncated final element
    with pytest.raises(FrameError):
        validate_msgb(body[: len(body) - len(payloads[-1]) - 2])
    with pytest.raises(FrameError):
        validate_msgb(body + b"\x00")  # trailing bytes
    with pytest.raises(FrameError):
        # overlong element: inner length runs past the body
        validate_msgb((1).to_bytes(4, "big") + (10).to_bytes(4, "big") + b"abc")


def test_msgb_fuzz_parity_with_serde():
    """The round-8 framing fuzz extended to KIND_MSGB: truncations,
    bit flips, and corrupted count/length prefixes through the decoder
    — no crash ever; for frames that survive framing, the body either
    validates as a whole or raises FrameError (the transport's
    drop/strike path), and each validated element's serde accept/reject
    matches the pure-Python decoder."""
    from hbbft_tpu.protocols.sender_queue import SqMessage

    def pure_loads(data):
        r = serde._Reader(data, None)
        obj = serde._decode(r, 0)
        if r.pos != len(r.data):
            raise serde.DecodeError("trailing bytes")
        return obj

    msgs = [
        serde.dumps(SqMessage.epoch_started((2, 7))),
        serde.dumps(SqMessage.epoch_started((2, 8))),
        b"not-serde-at-all",
    ]
    frame = encode_msgb(msgs)
    rng = random.Random(4321)

    def sweep(mutated: bytes):
        dec = FrameDecoder(max_frame_len=1 << 16)
        try:
            dec.feed(mutated)
            frames = dec.frames()
        except FrameError:
            return  # rejected at the frame layer: fine
        for kind, payload in frames:
            if kind != KIND_MSGB:
                continue
            try:
                elements = decode_msgb(payload)
            except FrameError:
                continue  # whole-batch reject: the ingress drop path
            for enc in elements:
                try:
                    got = serde.loads(enc)
                except serde.DecodeError:
                    got = "ERR"
                try:
                    want = pure_loads(enc)
                except serde.DecodeError:
                    want = "ERR"
                assert (got == "ERR") == (want == "ERR")
                if want != "ERR":
                    assert got == want

    for cut in range(len(frame)):
        sweep(frame[:cut])
    for _ in range(500):
        i = rng.randrange(len(frame))
        mutated = (
            frame[:i]
            + bytes([frame[i] ^ (1 << rng.randrange(8))])
            + frame[i + 1 :]
        )
        sweep(mutated)
    # corrupt every byte of the batch count and the first element header
    for i in range(9, 17):
        mutated = bytearray(frame)
        mutated[i] = 0xFF
        sweep(bytes(mutated))


def test_coalescing_arms_commit_identically_with_honest_ratio():
    """`HBBFT_TPU_COALESCE=0/1` is a wire-shape A/B, never a semantics
    change: both arms commit byte-identical batches at the same seed,
    and the metrics self-describe the arm — the coalescing arm moves
    strictly more messages than MSG/MSGB frames, the per-frame arm
    exactly as many.

    Cross-RUN epoch COMPOSITION on a live thread cluster is
    scheduling-dependent (drive_to paces ~2 rounds ahead, so which
    epoch cut a txn lands in can differ between runs — same caveat as
    the proc tier's cross-run digests), so the cross-arm identity uses
    the repo's retry-until-match convention; a real semantic
    divergence never converges.  The safety/ratio/error invariants are
    asserted on EVERY run, no retries."""

    def run_arm(coalesce: bool):
        with LocalCluster(
            4, seed=20, transport_kwargs={"coalesce": coalesce}
        ) as c:
            # identical tag on both arms: the tag is txn content, and
            # the cross-arm assert is batch BYTE identity
            drive(c, [0, 1, 2, 3], 3, tag="co")
            keys = batch_keys(c, 0, upto=3)
            for i in (1, 2, 3):
                assert batch_keys(c, i, upto=3) == keys
            msgs = frames = 0
            for node in c.nodes.values():
                for st in node.transport.stats().values():
                    msgs += st["msgs_out"]
                    frames += st["frames_out"]
            assert msgs > 0
            if coalesce:
                # frames_out also counts HELLO/ACK frames, so strictly
                # more messages than total frames is an honest ratio win
                assert msgs > frames, (msgs, frames)
            m = c.merged_metrics()
            assert m.counters.get("cluster.handler_errors", 0) == 0
            assert m.counters.get("cluster.bad_payload", 0) == 0
            return keys

    last = None
    for _ in range(4):
        last = (run_arm(True), run_arm(False))
        if last[0] == last[1]:
            break
    assert last[0] == last[1]  # cross-arm byte identity


# ---------------------------------------------------------------------------
# cluster drivers
# ---------------------------------------------------------------------------


def drive(cluster, ids, target, timeout_s=EPOCH_TIMEOUT_S, tag="d"):
    """LocalCluster.drive_to holds the pacing invariant; tests fail on
    its TimeoutError."""
    cluster.drive_to(ids, target, timeout_s=timeout_s, tag=tag)


def batch_keys(cluster, nid, upto=None):
    bs = cluster.batches(nid)
    if upto is not None:
        bs = bs[:upto]
    return [(b.era, b.epoch, serde.dumps(b.contributions)) for b in bs]


# ---------------------------------------------------------------------------
# acceptance: N=4 epochs, kill/restart, partition/heal
# ---------------------------------------------------------------------------


def test_cluster_commits_three_epochs_byte_identical():
    """N=4 localhost TCP cluster commits >= 3 HoneyBadger epochs with
    byte-identical outputs across all correct nodes, well under 60 s."""
    t0 = time.monotonic()
    with LocalCluster(4, seed=42) as c:
        drive(c, [0, 1, 2, 3], 3)
        want = batch_keys(c, 0, upto=3)
        for i in [1, 2, 3]:
            assert batch_keys(c, i, upto=3) == want
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("cluster.bad_payload", 0) == 0
        assert m.counters.get("transport.accepts", 0) >= 12  # full mesh
    assert time.monotonic() - t0 < 60


@pytest.mark.parametrize("coalesce", [True, False])
def test_cluster_kill_restart_continues_committing(coalesce):
    """f=1 over real sockets: killing one node mid-epoch does not stop
    the other three; a restarted (state-wiped) node's transport comes
    back and the cluster keeps committing byte-identically.  Runs on
    both coalescing arms (round 20): frame-unit ACK + batch-atomic
    consumption must keep the drill's losslessness with MSGB frames in
    flight."""
    with LocalCluster(
        4, seed=11, transport_kwargs={"coalesce": coalesce}
    ) as c:
        drive(c, [0, 1, 2, 3], 2)
        c.kill(3)
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 2)
        c.restart(3)
        drive(c, [0, 1, 2], len(c.batches(0)) + 2, tag="post")
        want = batch_keys(c, 0, upto=4)
        for i in [1, 2]:
            assert batch_keys(c, i, upto=4) == want
        # the reborn node is reachable again (its listener accepted
        # fresh peer connections on the old port) — allow for the
        # peers' dial-backoff cap before their next retry fires
        def reborn_accepted(cl):
            return (
                sum(
                    st["accepts"]
                    for st in cl.nodes[3].transport.stats().values()
                )
                >= 1
            )

        assert c.wait(reborn_accepted, 15)
        assert c.merged_metrics().counters.get("cluster.handler_errors", 0) == 0


@pytest.mark.parametrize("coalesce", [True, False])
def test_cluster_partition_heals_and_continues(coalesce):
    """A seeded partition isolating one node: the majority side keeps
    committing during the window; after heal the links carry frames
    again and committing continues.  Both coalescing arms (round 20)."""
    inj = FaultInjector(seed=5)
    with LocalCluster(
        4, seed=13, injector=inj, transport_kwargs={"coalesce": coalesce}
    ) as c:
        drive(c, [0, 1, 2, 3], 2)
        inj.add_partition(
            PartitionSpec(
                (frozenset([0, 1, 2]), frozenset([3])), start_s=inj.elapsed()
            )
        )
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 2, tag="part")
        assert inj.stats.partitioned > 0  # the fault is logged
        frames_to_3_before = sum(
            c.nodes[i].transport.peer_stats[3].frames_out for i in [0, 1, 2]
        )
        inj.heal_all()
        drive(c, [0, 1, 2], len(c.batches(0)) + 2, tag="heal")
        frames_to_3_after = sum(
            c.nodes[i].transport.peer_stats[3].frames_out for i in [0, 1, 2]
        )
        assert frames_to_3_after > frames_to_3_before  # links healed
        want = batch_keys(c, 0, upto=4)
        for i in [1, 2]:
            assert batch_keys(c, i, upto=4) == want


# ---------------------------------------------------------------------------
# fault injection: corruption never crashes a node
# ---------------------------------------------------------------------------


def test_corrupt_frames_drop_connection_then_reconnect():
    """Raw sockets attacking a node's listener: an unconfigured peer id
    and an oversized frame each get the connection dropped (we observe
    EOF) with the fault counted — the node stays alive, keeps accepting
    its real peers, and keeps committing."""
    with LocalCluster(4, seed=21) as c:
        drive(c, [0, 1, 2, 3], 1)
        addr = c.addr_map[0]
        cid = c.cluster_id

        def drain_to_eof(s):
            s.settimeout(5)
            while s.recv(64):
                pass
            s.close()

        # unknown peer id: rejected at HELLO
        s = socket.create_connection(addr, timeout=5)
        s.sendall(encode_hello(99, cid))
        drain_to_eof(s)

        # known peer id, then an oversized declared length: rejected
        # from the 4-byte prefix alone (no MSG frame is ever consumed,
        # so the spoofed id cannot desync the real peer's resume ACKs)
        s2 = socket.create_connection(addr, timeout=5)
        s2.sendall(encode_hello(2, cid))
        s2.sendall((1 << 30).to_bytes(4, "big") + b"\xde\xad")
        drain_to_eof(s2)

        def faults_counted(cl):
            return (
                cl.nodes[0].transport.metrics.counters.get(
                    "transport.frame_errors", 0
                )
                >= 2
            )

        assert c.wait(faults_counted, 10)

        # the node is still committing epochs with its REAL peers
        drive(c, [0, 1, 2, 3], len(c.batches(0)) + 1, tag="after")
        assert c.merged_metrics().counters.get("cluster.handler_errors", 0) == 0


def test_wrong_type_payload_is_bad_payload_not_handler_error():
    """A well-formed serde payload that is not an SqMessage is peer
    garbage: counted as cluster.bad_payload and dropped, never fed to
    the protocol (cluster.handler_errors stays the local-bug-only
    signal the other tests pin to zero)."""
    with LocalCluster(4, seed=61) as c:
        node = c.nodes[0]
        node.inbox.put(("msgs", 1, [serde.dumps(7)]))
        node.inbox.put(("msgs", 1, [serde.dumps((b"x", [1, 2]))]))

        def counted(cl):
            return cl.nodes[0].metrics.counters.get("cluster.bad_payload", 0) >= 2

        assert c.wait(counted, 10)
        assert node.metrics.counters.get("cluster.handler_errors", 0) == 0
        drive(c, [0, 1, 2, 3], 1)  # still live


def test_random_link_corruption_cluster_survives():
    """Byte corruption + duplication + delay on every link OUT of one
    node: receivers' decoders reject, connections cycle (drop ->
    reconnect), and the cluster keeps committing byte-identically —
    f=1 covers a node whose outbound traffic is flaky.  (Sustained
    corruption on ALL links is not a liveness scenario: frames lost
    between connection drops are never retransmitted, by design — see
    docs/TRANSPORT.md "loss model".)"""
    flaky = LinkFaults(corrupt_p=0.05, dup_p=0.1, delay_p=0.2)
    inj = FaultInjector(
        seed=3, links={(3, 0): flaky, (3, 1): flaky, (3, 2): flaky}
    )
    with LocalCluster(4, seed=33, injector=inj) as c:
        drive(c, [0, 1, 2], 3, timeout_s=60)
        want = batch_keys(c, 0, upto=3)
        for i in [1, 2]:
            assert batch_keys(c, i, upto=3) == want
        m = c.merged_metrics()
        # corruption actually happened, was detected, and was survived.
        # Detection surfaces at whichever layer the flipped bits land:
        # header bytes -> frame_errors (connection dropped), payload
        # bytes -> bad_payload (message dropped at the serde boundary).
        assert inj.stats.corrupted > 0
        detected = m.counters.get("transport.frame_errors", 0) + m.counters.get(
            "cluster.bad_payload", 0
        )
        assert detected > 0
        assert m.counters.get("cluster.handler_errors", 0) == 0


def test_backpressure_overflow_is_counted_not_fatal():
    """A dead destination with a tiny queue cap: the sender drops and
    counts instead of buffering without bound."""
    with LocalCluster(4, seed=55, max_queue_frames=50) as c:
        c.kill(3)
        drive(c, [0, 1, 2], len(c.batches(0)) + 3, timeout_s=60)
        m = c.merged_metrics()
        assert m.counters.get("transport.queue_overflow", 0) > 0
        assert m.counters.get("cluster.handler_errors", 0) == 0


# ---------------------------------------------------------------------------
# satellite: sender-queue churn over real sockets
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("coalesce", [True, False])
def test_sender_queue_churn_disconnect_reconnect_catches_up(coalesce):
    """A node that disconnects MID-EPOCH and reconnects catches up via
    the sender-queue window machinery plus the transport's resume layer
    (unacked frames retransmit on reconnect, docs/TRANSPORT.md): its
    committed sequence has no holes and no duplicates, byte-identical
    to the stable nodes'.  No quiescing — QHB churns empty epochs
    continuously, so there IS no quiet moment to cut at; the resume
    layer is what makes an arbitrary cut lossless for a live process.
    Runs on both coalescing arms: a disconnect mid-MSGB-burst must be
    exactly as lossless (the ACK unit is the frame, consumption is
    batch-atomic — a partially-delivered batch retransmits whole)."""
    with LocalCluster(
        4, seed=7, transport_kwargs={"coalesce": coalesce}
    ) as c:
        drive(c, [0, 1, 2, 3], 2)
        c.disconnect(3)
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 3, tag="out")
        stalled = len(c.batches(3))
        assert stalled < len(c.batches(0))  # it really was cut off
        c.reconnect(3)
        target = len(c.batches(0))

        def caught_up(cl):
            return len(cl.batches(3)) >= target

        # No new load during catch-up: the missed-epoch stream already
        # sits in the peers' outbound queues and sender-queue outboxes;
        # releasing it only needs the victim's own epoch announcements.
        assert c.wait(caught_up, EPOCH_TIMEOUT_S), (len(c.batches(3)), target)
        b0, b3 = batch_keys(c, 0), batch_keys(c, 3)
        k = min(len(b0), len(b3))
        assert b3[:k] == b0[:k]  # no lost outputs: identical prefix
        keys = [(e, ep) for e, ep, _ in b3]
        assert len(keys) == len(set(keys))  # no duplicate outputs
        st = c.nodes[3].transport.stats()
        assert sum(s["accepts"] for s in st.values()) >= 3  # peers re-dialed


# ---------------------------------------------------------------------------
# subprocess mode (flag-gated; slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_subprocess_cluster_commits_identically():
    import json
    import os
    import subprocess
    import sys

    n, epochs, seed = 4, 2, 9
    ports = []
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    peers = ",".join(f"127.0.0.1:{p}" for p in ports)
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "hbbft_tpu.transport.cluster_worker",
                "--node-id", str(i),
                "--n", str(n),
                "--seed", str(seed),
                "--port", str(ports[i]),
                "--peers", peers,
                "--epochs", str(epochs),
                "--timeout-s", "90",
            ],
            stdout=subprocess.PIPE,
            text=True,
            env=env,
        )
        for i in range(n)
    ]
    outs = [p.communicate(timeout=150)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    batch_lines = []
    for out in outs:
        lines = [json.loads(line) for line in out.splitlines() if line]
        assert lines[-1]["done"] is True
        batch_lines.append(lines[: epochs])
    for i in range(1, n):
        assert batch_lines[i] == batch_lines[0]
