"""Native-engine cluster nodes (round 9): oracle equivalence + wire parity.

``LocalCluster(node_impl="native")`` runs one C++ engine per node behind
the message-boundary wire API (``hbe_node_ingest_frames`` / egress
drain); the Python :class:`~hbbft_tpu.transport.cluster.ClusterNode` is
the cross-check oracle.  This file pins the contract from both ends:

* same-seed byte-identity of committed batches between the native and
  Python arms (and full agreement inside each arm, and in MIXED
  clusters);
* the ISSUE-4 fault drills (kill/restart, partition/heal, garbage
  payloads) re-run against native nodes;
* wire-codec fuzz parity: `hbe_wire_classify` must accept/reject
  EXACTLY what the Python codec path accepts/rejects
  (``serde.try_loads`` + the SqMessage isinstance gate) across
  truncations and bit flips of real traffic, and `hbe_wire_roundtrip`
  must reproduce Python's encodings byte-for-byte.

Cross-arm byte-identity needs a DETERMINISTIC workload: txns are
pre-submitted before ``start()`` so every arm's proposers see identical
queues (a wall-clock-paced feeder like ``drive_to`` races the faster
arm ahead into different proposal splits — measured, not hypothetical).

Default-tier budget: every driven phase is single-digit seconds on the
1-core box with a generous cap (CLAUDE.md transport budgets); the fuzz
sweep is pure CPU (~2 s).  Skips cleanly when no C++ toolchain exists.
"""

from __future__ import annotations

import ctypes
import random

import pytest

from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.protocols.sender_queue import SqMessage
from hbbft_tpu.transport import FaultInjector, LocalCluster, PartitionSpec
from hbbft_tpu.utils import serde

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 2 s


def _lib_or_skip():
    from hbbft_tpu import native_engine

    lib = native_engine.get_lib()
    if lib is None:
        pytest.skip("native engine unavailable (no compiler?)")
    return lib


def batch_keys(cluster, nid, upto=None):
    bs = cluster.batches(nid)
    if upto is not None:
        bs = bs[:upto]
    return [(b.era, b.epoch, serde.dumps(b.contributions)) for b in bs]


def drive(cluster, ids, target, timeout_s=EPOCH_TIMEOUT_S, tag="d"):
    cluster.drive_to(ids, target, timeout_s=timeout_s, tag=tag)


# ---------------------------------------------------------------------------
# oracle equivalence: native vs python arms commit identical bytes
# ---------------------------------------------------------------------------


def _run_arm(impl, seed, rounds=6, target=4):
    """One cluster run with the whole workload pre-submitted (the
    deterministic cross-arm driving described in the module docstring);
    returns per-node batch keys for the first `target` batches."""
    c = LocalCluster(4, seed=seed, node_impl=impl)
    for k in range(rounds):
        for i in range(4):
            c.submit(i, Input.user(f"tx-{k}-{i}"))
    c.start()
    try:
        ok = c.wait(
            lambda cl: all(len(cl.batches(i)) >= target for i in range(4)),
            EPOCH_TIMEOUT_S,
        )
        assert ok, {i: len(c.batches(i)) for i in range(4)}
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("cluster.bad_payload", 0) == 0
        return {i: batch_keys(c, i, upto=target) for i in range(4)}
    finally:
        c.stop()


def test_native_cluster_matches_python_oracle_byte_identical():
    """The acceptance pin: a native-node cluster at seed s commits
    byte-identical batches to the Python-node cluster at seed s."""
    _lib_or_skip()
    for seed in (42, 7):
        py = _run_arm("python", seed)
        nat = _run_arm("native", seed)
        for out in (py, nat):
            for i in range(1, 4):
                assert out[i] == out[0], f"intra-arm divergence at seed {seed}"
        assert nat[0] == py[0], f"cross-arm divergence at seed {seed}"


def test_mixed_cluster_interop_agrees():
    """Half native / half python in ONE cluster: the wire format is the
    only contract between them, and all four commit identically."""
    _lib_or_skip()
    with LocalCluster(
        4, seed=17, node_impl={0: "native", 2: "native"}
    ) as c:
        drive(c, [0, 1, 2, 3], 3)
        want = batch_keys(c, 0, upto=3)
        for i in [1, 2, 3]:
            assert batch_keys(c, i, upto=3) == want
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("cluster.bad_payload", 0) == 0


# ---------------------------------------------------------------------------
# fault drills re-run against native nodes
# ---------------------------------------------------------------------------


def test_native_kill_restart_continues_committing():
    """f=1 with native nodes: killing one node mid-epoch does not stop
    the other three; the restarted (state-wiped) engine comes back and
    the cluster keeps committing byte-identically."""
    _lib_or_skip()
    with LocalCluster(4, seed=11, node_impl="native") as c:
        drive(c, [0, 1, 2, 3], 2)
        c.kill(3)
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 2)
        c.restart(3)
        drive(c, [0, 1, 2], len(c.batches(0)) + 2, tag="post")
        want = batch_keys(c, 0, upto=4)
        for i in [1, 2]:
            assert batch_keys(c, i, upto=4) == want

        def reborn_accepted(cl):
            return (
                sum(
                    st["accepts"]
                    for st in cl.nodes[3].transport.stats().values()
                )
                >= 1
            )

        assert c.wait(reborn_accepted, 15)
        assert c.merged_metrics().counters.get("cluster.handler_errors", 0) == 0


def test_native_partition_heals_and_continues():
    """A seeded partition isolating one native node: the majority keeps
    committing; after heal the links carry frames again."""
    _lib_or_skip()
    inj = FaultInjector(seed=5)
    with LocalCluster(4, seed=13, injector=inj, node_impl="native") as c:
        drive(c, [0, 1, 2, 3], 2)
        inj.add_partition(
            PartitionSpec(
                (frozenset([0, 1, 2]), frozenset([3])), start_s=inj.elapsed()
            )
        )
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 2, tag="part")
        assert inj.stats.partitioned > 0
        inj.heal_all()
        drive(c, [0, 1, 2], len(c.batches(0)) + 2, tag="heal")
        want = batch_keys(c, 0, upto=4)
        for i in [1, 2]:
            assert batch_keys(c, i, upto=4) == want


def test_native_garbage_payload_is_bad_payload_not_handler_error():
    """Codec-rejected and wrong-type payloads through the native ingest
    are counted cluster.bad_payload and dropped in C — never a handler
    error, and the node keeps committing (the Python node's untrusted-
    input stance, preserved across the wire API)."""
    _lib_or_skip()
    with LocalCluster(4, seed=61, node_impl="native") as c:
        node = c.nodes[0]
        node.inbox.put(
            ("msgs", 1, [serde.dumps(7), b"\xff\xfe garbage",
                         serde.dumps((b"x", [1, 2]))])
        )

        def counted(cl):
            return cl.nodes[0].metrics.counters.get("cluster.bad_payload", 0) >= 3

        assert c.wait(counted, 10)
        assert node.metrics.counters.get("cluster.handler_errors", 0) == 0
        drive(c, [0, 1, 2, 3], 1)  # still live


# ---------------------------------------------------------------------------
# wire-codec fuzz parity: hbe_wire_classify / hbe_wire_roundtrip
# ---------------------------------------------------------------------------

#: struct names that identify a message flavor inside its encoding —
#: used only to pick a type-diverse corpus sample for the sweep.
_FLAVOR_TAGS = [
    b"epoch_started", b"bc_value", b"bc_echo", b"bc_ready", b"bc_echohash",
    b"bc_candecode", b"ba_bval", b"ba_aux", b"ba_conf", b"ba_term",
    b"ba_coin", b"decmsg",
]


def _capture_wire_corpus(seed=42, target=2):
    """Every distinct payload a PYTHON cluster put on the wire for a
    couple of epochs — real traffic, Python-encoded (the reference
    bytes the native codec must match)."""
    c = LocalCluster(4, seed=seed)
    corpus = set()
    for node in c.nodes.values():
        orig = node.transport.send
        orig_many = node.transport.send_many

        def send(dest, payload, _orig=orig):
            corpus.add(payload)
            return _orig(dest, payload)

        def send_many(items, _orig=orig_many):
            corpus.update(p for _, p in items)
            return _orig(items)

        node.transport.send = send
        node.transport.send_many = send_many
    c.start()
    try:
        drive(c, [0, 1, 2, 3], target)
    finally:
        c.stop()
    return sorted(corpus)


def _python_accepts(data, suite):
    m = serde.try_loads(data, suite=suite)
    return isinstance(m, SqMessage)


def test_wire_fuzz_parity_native_vs_python_codecs():
    """`hbe_wire_classify` accepts (> 0) exactly the payloads the Python
    node accepts, and rejects (-1) exactly what it rejects — over real
    traffic of every message flavor, all truncations, and random bit
    flips.  `hbe_wire_roundtrip` re-encodes every accepted engine
    message byte-for-byte (the C encoder == serde.dumps pin the egress
    path rests on)."""
    lib = _lib_or_skip()
    from hbbft_tpu.crypto.suite import ScalarSuite

    suite = ScalarSuite()
    corpus = _capture_wire_corpus()
    assert len(corpus) > 50  # a real run produced real traffic

    flavors_seen = set()
    samples = []
    for payload in corpus:
        key = tuple(t for t in _FLAVOR_TAGS if t in payload)
        # clean-corpus parity + roundtrip pin for EVERY payload
        verdict = int(lib.hbe_wire_classify(payload, len(payload)))
        assert verdict > 0, f"native rejected live python traffic: {payload!r}"
        assert _python_accepts(payload, suite)
        buf = (ctypes.c_uint8 * (len(payload) + 64))()
        rc = int(lib.hbe_wire_roundtrip(payload, len(payload), buf, len(buf)))
        assert rc == len(payload), (rc, key)
        assert bytes(buf[:rc]) == payload, f"re-encode diverged for {key}"
        if key not in flavors_seen:
            flavors_seen.add(key)
            samples.append(payload)
    # a plain-epoch run must exercise at least the always-on flavor
    # core (echo-hash/can-decode/term traffic is scheduling-dependent —
    # it rides along in the sweep whenever the run produced it)
    seen_flat = {t for k in flavors_seen for t in k}
    assert seen_flat >= {
        b"epoch_started", b"bc_value", b"bc_echo", b"bc_ready",
        b"ba_bval", b"ba_aux", b"ba_coin", b"decmsg",
    }, seen_flat

    rng = random.Random(1234)
    checked = 0

    def parity(data):
        nonlocal checked
        checked += 1
        native_ok = int(lib.hbe_wire_classify(data, len(data))) > 0
        python_ok = _python_accepts(data, suite)
        assert native_ok == python_ok, (
            f"parity break (native={native_ok}, python={python_ok}) "
            f"on {data!r}"
        )

    for payload in samples:
        stride = max(1, len(payload) // 150)
        for cut in range(0, len(payload), stride):
            parity(payload[:cut])
        for _ in range(200):
            i = rng.randrange(len(payload))
            parity(
                payload[:i]
                + bytes([payload[i] ^ (1 << rng.randrange(8))])
                + payload[i + 1:]
            )
        # appended trailing garbage must reject on both sides
        parity(payload + b"\x00")
    assert checked > 1000

    # well-formed serde that is NOT an SqMessage: reject parity on
    # shapes the bit-flip sweep is unlikely to hit
    for obj in (None, 0, b"bytes", "str", (1, 2), [1], {"k": 1}):
        parity(serde.dumps(obj))


def test_wire_classify_non_engine_sqmessages_accepted():
    """SqMessage kinds the engine cannot represent internally (a real
    JoinPlan; a bare-HbMessage algo from the static stack) are still
    CONSUMABLE wire traffic (classify kind 3): the native node counts
    them handled+ignored like the Python node handles-then-discards,
    keeping the resume-layer ACK counts aligned between impls.  A fake
    join_plan whose value is NOT a JoinPlan is rejected by the Python
    codec's shape check — and must be rejected natively too."""
    lib = _lib_or_skip()
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.protocols.dynamic_honey_badger import (
        EncryptionSchedule,
        JoinPlan,
    )
    from hbbft_tpu.transport.cluster import build_netinfo

    suite = ScalarSuite()
    ni = build_netinfo(4, 1, 0, suite, 0)
    plan = JoinPlan(
        era=1,
        public_key_set=ni.public_key_set,
        validators=tuple(sorted(ni.public_key_map.items())),
        encryption_schedule=EncryptionSchedule.always(),
    )
    non_engine = [serde.dumps(SqMessage.join_plan(plan))]

    # bare-HbMessage algo: unwrap a live DhbMessage envelope
    corpus = _capture_wire_corpus(seed=3, target=1)
    for payload in corpus:
        m = serde.try_loads(payload, suite=suite)
        if m is not None and m.kind == "algo":
            non_engine.append(serde.dumps(SqMessage.algo(m.value.inner)))
            break
    assert len(non_engine) == 2, "no live algo traffic captured"

    for enc in non_engine:
        assert _python_accepts(enc, suite)
        assert int(lib.hbe_wire_classify(enc, len(enc))) == 3, enc[:48]
        # roundtrip correctly refuses what encode cannot represent
        buf = (ctypes.c_uint8 * (len(enc) + 64))()
        assert int(lib.hbe_wire_roundtrip(enc, len(enc), buf, len(buf))) == -3

    fake = serde.dumps(SqMessage.join_plan((1, b"plan")))
    assert serde.try_loads(fake, suite=suite) is None  # codec shape check
    assert int(lib.hbe_wire_classify(fake, len(fake))) == -1


# ---------------------------------------------------------------------------
# round 20: MSGB wire fast path — grammar parity + drain identity
# ---------------------------------------------------------------------------


def _msgb_engines_or_skip():
    """A (producer, consumer) NativeNodeEngine pair in one 4-node net,
    with producer egress already drained into per-payload frames.
    Skips when the loaded engine predates the wire fast path (seed
    snapshots via HBBFT_TPU_ENGINE_LIB)."""
    _lib_or_skip()
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.native_engine import NativeNodeEngine
    from hbbft_tpu.transport.cluster import build_netinfo

    suite = ScalarSuite()
    producer = NativeNodeEngine(
        0, build_netinfo(4, 1, 0, suite, 0), seed=0, batch_size=3,
        session_id=b"msgb-parity",
    )
    if not producer.supports_wire_batch:
        pytest.skip("engine lib predates the MSGB wire fast path")
    consumer = NativeNodeEngine(
        1, build_netinfo(4, 1, 0, suite, 1), seed=0, batch_size=3,
        session_id=b"msgb-parity",
    )
    producer.handle_input(Input.user("msgb-tx"))
    producer.run()
    payloads = []
    producer.drain_egress(lambda d, p: payloads.append(p))
    assert len(payloads) >= 3, "engine produced no broadcast egress"
    return producer, consumer, payloads


def test_msgb_engine_grammar_parity_with_python_validator():
    """`hbe_node_ingest_wire`'s MSGB walk agrees with the Python
    grammar authority (framing.validate_msgb) on every hostile body:
    a Python-rejected body makes the engine count bad_payload (never
    crash, never read OOB — the sanitizer tier covers memory safety);
    a Python-accepted body of live traffic is fully consumed with
    every message accounted exactly once."""
    from hbbft_tpu.transport.framing import FrameError, msgb_body, validate_msgb

    _, consumer, payloads = _msgb_engines_or_skip()
    k = min(len(payloads), 5)
    good = msgb_body(payloads[:k])

    def py_count(body):
        try:
            return validate_msgb(body)
        except FrameError:
            return None

    def engine_deltas(nm, body):
        before = consumer.stats()
        consumer.ingest_wire([0], [(nm, body)])
        after = consumer.stats()
        return (
            after["handled"] - before["handled"],
            after["bad_payload"] - before["bad_payload"],
        )

    # the clean body: grammar-accepted on both sides, all k consumable
    assert py_count(good) == k
    handled, bad = engine_deltas(k, good)
    assert (handled, bad) == (k, 0)

    def nm_claim(body):
        # what a (hypothetically fooled) transport would claim: the
        # declared count where parseable, else 1 — never 0, which
        # would route down the plain-MSG path instead of the walk
        if len(body) >= 4:
            return max(1, int.from_bytes(body[:4], "big"))
        return 1

    hostile = [
        (k + 1).to_bytes(4, "big") + good[4:],          # inflated count
        good[: len(good) // 2],                          # truncated
        good + b"\x00\x07",                              # trailing bytes
        (0).to_bytes(4, "big"),                          # zero count
        b"",                                             # no count field
        good[:4] + (1 << 24).to_bytes(4, "big") + good[8:],  # overlong elem
    ]
    for body in hostile:
        assert py_count(body) is None, body[:16]
        handled, bad = engine_deltas(nm_claim(body), body)
        assert bad >= 1, (body[:16], handled, bad)
    # record-claim mismatch: the body is well-formed but the record
    # header lies about the count — every claimed message is bad
    handled, bad = engine_deltas(k + 1, good)
    assert (handled, bad) == (0, k + 1)

    # fuzz sweep: every truncation, plus bit flips through the count
    # field and the first element header — full accept/reject parity
    rng = random.Random(2020)
    cases = [good[:cut] for cut in range(len(good))]
    for _ in range(300):
        i = rng.randrange(min(len(good), 8))
        cases.append(
            good[:i] + bytes([good[i] ^ (1 << rng.randrange(8))]) + good[i + 1:]
        )
    checked_rejects = 0
    for body in cases:
        want = py_count(body)
        handled, bad = engine_deltas(
            want if want is not None else nm_claim(body), body
        )
        if want is None:
            checked_rejects += 1
            assert bad >= 1, body[:16]
        else:
            # grammar-accepted mutant: every message accounted exactly
            # once (handled if serde-consumable, bad_payload otherwise)
            assert handled + bad == want, (body[:16], handled, bad, want)
    assert checked_rejects > 100


def test_msgb_drain_matches_per_frame_drain():
    """`hbe_node_egress_drain_msgb` re-groups the SAME payload stream
    the per-frame drain emits: per destination, concatenating the
    decoded MSGB groups (in emission order) reproduces the per-frame
    (dest, payload) sequence byte-for-byte — at a roomy max_body and
    at a tiny one that forces every group down to a singleton."""
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.native_engine import NativeNodeEngine
    from hbbft_tpu.transport.cluster import build_netinfo
    from hbbft_tpu.transport.framing import decode_msgb

    _lib_or_skip()
    suite = ScalarSuite()

    def fresh():
        eng = NativeNodeEngine(
            0, build_netinfo(4, 1, 0, suite, 0), seed=0, batch_size=3,
            session_id=b"msgb-drain",
        )
        if not eng.supports_wire_batch:
            pytest.skip("engine lib predates the MSGB wire fast path")
        eng.handle_input(Input.user("drain-tx"))
        eng.run()
        return eng

    per_frame = {}
    nframes = fresh().drain_egress(
        lambda d, p: per_frame.setdefault(d, []).append(p)
    )
    assert nframes >= 3 and len(per_frame) >= 2  # a real broadcast

    for max_body, expect_batched in ((1 << 20, True), (1, False)):
        grouped = {}
        singles_only = True

        def emit(dest, nmsg, body):
            nonlocal singles_only
            if nmsg > 1:
                singles_only = False
            grouped.setdefault(dest, []).extend(decode_msgb(body))

        fresh().drain_egress_msgb(emit, max_body)
        assert grouped == per_frame, f"stream diverged at max_body={max_body}"
        if expect_batched:
            assert not singles_only, "roomy max_body never coalesced"
        else:
            assert singles_only, "max_body=1 (clamped 16) still batched"


@pytest.mark.parametrize("coalesce", [True, False])
def test_native_churn_disconnect_reconnect_catches_up(coalesce):
    """The round-8 disconnect-mid-epoch resume drill on NATIVE nodes,
    on both coalescing arms: cutting a live node mid-MSGB-burst is
    exactly as lossless as the per-frame arm (frame-unit ACK, batch-
    atomic consumption — a partially-delivered batch retransmits
    whole), and the native egress fast path replays through the same
    resume layer."""
    _lib_or_skip()
    with LocalCluster(
        4, seed=7, node_impl="native",
        transport_kwargs={"coalesce": coalesce},
    ) as c:
        drive(c, [0, 1, 2, 3], 2)
        c.disconnect(3)
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 3, tag="out")
        assert len(c.batches(3)) < len(c.batches(0))  # it really was cut off
        c.reconnect(3)
        target = len(c.batches(0))

        def caught_up(cl):
            return len(cl.batches(3)) >= target

        assert c.wait(caught_up, EPOCH_TIMEOUT_S), (len(c.batches(3)), target)
        b0, b3 = batch_keys(c, 0), batch_keys(c, 3)
        kk = min(len(b0), len(b3))
        assert b3[:kk] == b0[:kk]  # no lost outputs: identical prefix
        keys = [(e, ep) for e, ep, _ in b3]
        assert len(keys) == len(set(keys))  # no duplicate outputs
        if coalesce:
            st = c.nodes[0].transport.stats()
            msgs = sum(s.get("msgs_out", 0) for s in st.values())
            frames = sum(s.get("frames_out", 0) for s in st.values())
            assert msgs > frames > 0  # the fast path actually coalesced
