"""Native-engine cluster nodes (round 9): oracle equivalence + wire parity.

``LocalCluster(node_impl="native")`` runs one C++ engine per node behind
the message-boundary wire API (``hbe_node_ingest_frames`` / egress
drain); the Python :class:`~hbbft_tpu.transport.cluster.ClusterNode` is
the cross-check oracle.  This file pins the contract from both ends:

* same-seed byte-identity of committed batches between the native and
  Python arms (and full agreement inside each arm, and in MIXED
  clusters);
* the ISSUE-4 fault drills (kill/restart, partition/heal, garbage
  payloads) re-run against native nodes;
* wire-codec fuzz parity: `hbe_wire_classify` must accept/reject
  EXACTLY what the Python codec path accepts/rejects
  (``serde.try_loads`` + the SqMessage isinstance gate) across
  truncations and bit flips of real traffic, and `hbe_wire_roundtrip`
  must reproduce Python's encodings byte-for-byte.

Cross-arm byte-identity needs a DETERMINISTIC workload: txns are
pre-submitted before ``start()`` so every arm's proposers see identical
queues (a wall-clock-paced feeder like ``drive_to`` races the faster
arm ahead into different proposal splits — measured, not hypothetical).

Default-tier budget: every driven phase is single-digit seconds on the
1-core box with a generous cap (CLAUDE.md transport budgets); the fuzz
sweep is pure CPU (~2 s).  Skips cleanly when no C++ toolchain exists.
"""

from __future__ import annotations

import ctypes
import random

import pytest

from hbbft_tpu.protocols.queueing_honey_badger import Input
from hbbft_tpu.protocols.sender_queue import SqMessage
from hbbft_tpu.transport import FaultInjector, LocalCluster, PartitionSpec
from hbbft_tpu.utils import serde

EPOCH_TIMEOUT_S = 45  # wall cap per driven phase; typical is < 2 s


def _lib_or_skip():
    from hbbft_tpu import native_engine

    lib = native_engine.get_lib()
    if lib is None:
        pytest.skip("native engine unavailable (no compiler?)")
    return lib


def batch_keys(cluster, nid, upto=None):
    bs = cluster.batches(nid)
    if upto is not None:
        bs = bs[:upto]
    return [(b.era, b.epoch, serde.dumps(b.contributions)) for b in bs]


def drive(cluster, ids, target, timeout_s=EPOCH_TIMEOUT_S, tag="d"):
    cluster.drive_to(ids, target, timeout_s=timeout_s, tag=tag)


# ---------------------------------------------------------------------------
# oracle equivalence: native vs python arms commit identical bytes
# ---------------------------------------------------------------------------


def _run_arm(impl, seed, rounds=6, target=4):
    """One cluster run with the whole workload pre-submitted (the
    deterministic cross-arm driving described in the module docstring);
    returns per-node batch keys for the first `target` batches."""
    c = LocalCluster(4, seed=seed, node_impl=impl)
    for k in range(rounds):
        for i in range(4):
            c.submit(i, Input.user(f"tx-{k}-{i}"))
    c.start()
    try:
        ok = c.wait(
            lambda cl: all(len(cl.batches(i)) >= target for i in range(4)),
            EPOCH_TIMEOUT_S,
        )
        assert ok, {i: len(c.batches(i)) for i in range(4)}
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("cluster.bad_payload", 0) == 0
        return {i: batch_keys(c, i, upto=target) for i in range(4)}
    finally:
        c.stop()


def test_native_cluster_matches_python_oracle_byte_identical():
    """The acceptance pin: a native-node cluster at seed s commits
    byte-identical batches to the Python-node cluster at seed s."""
    _lib_or_skip()
    for seed in (42, 7):
        py = _run_arm("python", seed)
        nat = _run_arm("native", seed)
        for out in (py, nat):
            for i in range(1, 4):
                assert out[i] == out[0], f"intra-arm divergence at seed {seed}"
        assert nat[0] == py[0], f"cross-arm divergence at seed {seed}"


def test_mixed_cluster_interop_agrees():
    """Half native / half python in ONE cluster: the wire format is the
    only contract between them, and all four commit identically."""
    _lib_or_skip()
    with LocalCluster(
        4, seed=17, node_impl={0: "native", 2: "native"}
    ) as c:
        drive(c, [0, 1, 2, 3], 3)
        want = batch_keys(c, 0, upto=3)
        for i in [1, 2, 3]:
            assert batch_keys(c, i, upto=3) == want
        m = c.merged_metrics()
        assert m.counters.get("cluster.handler_errors", 0) == 0
        assert m.counters.get("cluster.bad_payload", 0) == 0


# ---------------------------------------------------------------------------
# fault drills re-run against native nodes
# ---------------------------------------------------------------------------


def test_native_kill_restart_continues_committing():
    """f=1 with native nodes: killing one node mid-epoch does not stop
    the other three; the restarted (state-wiped) engine comes back and
    the cluster keeps committing byte-identically."""
    _lib_or_skip()
    with LocalCluster(4, seed=11, node_impl="native") as c:
        drive(c, [0, 1, 2, 3], 2)
        c.kill(3)
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 2)
        c.restart(3)
        drive(c, [0, 1, 2], len(c.batches(0)) + 2, tag="post")
        want = batch_keys(c, 0, upto=4)
        for i in [1, 2]:
            assert batch_keys(c, i, upto=4) == want

        def reborn_accepted(cl):
            return (
                sum(
                    st["accepts"]
                    for st in cl.nodes[3].transport.stats().values()
                )
                >= 1
            )

        assert c.wait(reborn_accepted, 15)
        assert c.merged_metrics().counters.get("cluster.handler_errors", 0) == 0


def test_native_partition_heals_and_continues():
    """A seeded partition isolating one native node: the majority keeps
    committing; after heal the links carry frames again."""
    _lib_or_skip()
    inj = FaultInjector(seed=5)
    with LocalCluster(4, seed=13, injector=inj, node_impl="native") as c:
        drive(c, [0, 1, 2, 3], 2)
        inj.add_partition(
            PartitionSpec(
                (frozenset([0, 1, 2]), frozenset([3])), start_s=inj.elapsed()
            )
        )
        base = len(c.batches(0))
        drive(c, [0, 1, 2], base + 2, tag="part")
        assert inj.stats.partitioned > 0
        inj.heal_all()
        drive(c, [0, 1, 2], len(c.batches(0)) + 2, tag="heal")
        want = batch_keys(c, 0, upto=4)
        for i in [1, 2]:
            assert batch_keys(c, i, upto=4) == want


def test_native_garbage_payload_is_bad_payload_not_handler_error():
    """Codec-rejected and wrong-type payloads through the native ingest
    are counted cluster.bad_payload and dropped in C — never a handler
    error, and the node keeps committing (the Python node's untrusted-
    input stance, preserved across the wire API)."""
    _lib_or_skip()
    with LocalCluster(4, seed=61, node_impl="native") as c:
        node = c.nodes[0]
        node.inbox.put(
            ("msgs", 1, [serde.dumps(7), b"\xff\xfe garbage",
                         serde.dumps((b"x", [1, 2]))])
        )

        def counted(cl):
            return cl.nodes[0].metrics.counters.get("cluster.bad_payload", 0) >= 3

        assert c.wait(counted, 10)
        assert node.metrics.counters.get("cluster.handler_errors", 0) == 0
        drive(c, [0, 1, 2, 3], 1)  # still live


# ---------------------------------------------------------------------------
# wire-codec fuzz parity: hbe_wire_classify / hbe_wire_roundtrip
# ---------------------------------------------------------------------------

#: struct names that identify a message flavor inside its encoding —
#: used only to pick a type-diverse corpus sample for the sweep.
_FLAVOR_TAGS = [
    b"epoch_started", b"bc_value", b"bc_echo", b"bc_ready", b"bc_echohash",
    b"bc_candecode", b"ba_bval", b"ba_aux", b"ba_conf", b"ba_term",
    b"ba_coin", b"decmsg",
]


def _capture_wire_corpus(seed=42, target=2):
    """Every distinct payload a PYTHON cluster put on the wire for a
    couple of epochs — real traffic, Python-encoded (the reference
    bytes the native codec must match)."""
    c = LocalCluster(4, seed=seed)
    corpus = set()
    for node in c.nodes.values():
        orig = node.transport.send

        def send(dest, payload, _orig=orig):
            corpus.add(payload)
            return _orig(dest, payload)

        node.transport.send = send
    c.start()
    try:
        drive(c, [0, 1, 2, 3], target)
    finally:
        c.stop()
    return sorted(corpus)


def _python_accepts(data, suite):
    m = serde.try_loads(data, suite=suite)
    return isinstance(m, SqMessage)


def test_wire_fuzz_parity_native_vs_python_codecs():
    """`hbe_wire_classify` accepts (> 0) exactly the payloads the Python
    node accepts, and rejects (-1) exactly what it rejects — over real
    traffic of every message flavor, all truncations, and random bit
    flips.  `hbe_wire_roundtrip` re-encodes every accepted engine
    message byte-for-byte (the C encoder == serde.dumps pin the egress
    path rests on)."""
    lib = _lib_or_skip()
    from hbbft_tpu.crypto.suite import ScalarSuite

    suite = ScalarSuite()
    corpus = _capture_wire_corpus()
    assert len(corpus) > 50  # a real run produced real traffic

    flavors_seen = set()
    samples = []
    for payload in corpus:
        key = tuple(t for t in _FLAVOR_TAGS if t in payload)
        # clean-corpus parity + roundtrip pin for EVERY payload
        verdict = int(lib.hbe_wire_classify(payload, len(payload)))
        assert verdict > 0, f"native rejected live python traffic: {payload!r}"
        assert _python_accepts(payload, suite)
        buf = (ctypes.c_uint8 * (len(payload) + 64))()
        rc = int(lib.hbe_wire_roundtrip(payload, len(payload), buf, len(buf)))
        assert rc == len(payload), (rc, key)
        assert bytes(buf[:rc]) == payload, f"re-encode diverged for {key}"
        if key not in flavors_seen:
            flavors_seen.add(key)
            samples.append(payload)
    # a plain-epoch run must exercise at least the always-on flavor
    # core (echo-hash/can-decode/term traffic is scheduling-dependent —
    # it rides along in the sweep whenever the run produced it)
    seen_flat = {t for k in flavors_seen for t in k}
    assert seen_flat >= {
        b"epoch_started", b"bc_value", b"bc_echo", b"bc_ready",
        b"ba_bval", b"ba_aux", b"ba_coin", b"decmsg",
    }, seen_flat

    rng = random.Random(1234)
    checked = 0

    def parity(data):
        nonlocal checked
        checked += 1
        native_ok = int(lib.hbe_wire_classify(data, len(data))) > 0
        python_ok = _python_accepts(data, suite)
        assert native_ok == python_ok, (
            f"parity break (native={native_ok}, python={python_ok}) "
            f"on {data!r}"
        )

    for payload in samples:
        stride = max(1, len(payload) // 150)
        for cut in range(0, len(payload), stride):
            parity(payload[:cut])
        for _ in range(200):
            i = rng.randrange(len(payload))
            parity(
                payload[:i]
                + bytes([payload[i] ^ (1 << rng.randrange(8))])
                + payload[i + 1:]
            )
        # appended trailing garbage must reject on both sides
        parity(payload + b"\x00")
    assert checked > 1000

    # well-formed serde that is NOT an SqMessage: reject parity on
    # shapes the bit-flip sweep is unlikely to hit
    for obj in (None, 0, b"bytes", "str", (1, 2), [1], {"k": 1}):
        parity(serde.dumps(obj))


def test_wire_classify_non_engine_sqmessages_accepted():
    """SqMessage kinds the engine cannot represent internally (a real
    JoinPlan; a bare-HbMessage algo from the static stack) are still
    CONSUMABLE wire traffic (classify kind 3): the native node counts
    them handled+ignored like the Python node handles-then-discards,
    keeping the resume-layer ACK counts aligned between impls.  A fake
    join_plan whose value is NOT a JoinPlan is rejected by the Python
    codec's shape check — and must be rejected natively too."""
    lib = _lib_or_skip()
    from hbbft_tpu.crypto.suite import ScalarSuite
    from hbbft_tpu.protocols.dynamic_honey_badger import (
        EncryptionSchedule,
        JoinPlan,
    )
    from hbbft_tpu.transport.cluster import build_netinfo

    suite = ScalarSuite()
    ni = build_netinfo(4, 1, 0, suite, 0)
    plan = JoinPlan(
        era=1,
        public_key_set=ni.public_key_set,
        validators=tuple(sorted(ni.public_key_map.items())),
        encryption_schedule=EncryptionSchedule.always(),
    )
    non_engine = [serde.dumps(SqMessage.join_plan(plan))]

    # bare-HbMessage algo: unwrap a live DhbMessage envelope
    corpus = _capture_wire_corpus(seed=3, target=1)
    for payload in corpus:
        m = serde.try_loads(payload, suite=suite)
        if m is not None and m.kind == "algo":
            non_engine.append(serde.dumps(SqMessage.algo(m.value.inner)))
            break
    assert len(non_engine) == 2, "no live algo traffic captured"

    for enc in non_engine:
        assert _python_accepts(enc, suite)
        assert int(lib.hbe_wire_classify(enc, len(enc))) == 3, enc[:48]
        # roundtrip correctly refuses what encode cannot represent
        buf = (ctypes.c_uint8 * (len(enc) + 64))()
        assert int(lib.hbe_wire_roundtrip(enc, len(enc), buf, len(buf))) == -3

    fake = serde.dumps(SqMessage.join_plan((1, b"plan")))
    assert serde.try_loads(fake, suite=suite) is None  # codec shape check
    assert int(lib.hbe_wire_classify(fake, len(fake))) == -1
