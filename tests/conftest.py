"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` (see the build rules in the
repo docs).  Must run before any ``import jax`` anywhere in the suite.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

# The axon plugin's sitecustomize registers its backend and pins
# jax_platforms at interpreter start, before this file runs — env vars
# alone cannot re-select the CPU platform.  Re-select and clear the
# backend cache (no arrays exist yet, so this is safe).
import jax  # noqa: E402

if jax.default_backend() != "cpu" or len(jax.devices()) < 8:
    jax.config.update("jax_platforms", "cpu")
    from jax.extend.backend import clear_backends  # noqa: E402

    clear_backends()

from hbbft_tpu.utils.jaxcache import enable_cache  # noqa: E402

enable_cache()
