"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` (see the build rules in the
repo docs).  Must run before any ``import jax`` anywhere in the suite.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")
