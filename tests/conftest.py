"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` (see the build rules in the
repo docs).  Must run before any ``import jax`` anywhere in the suite.
"""

import os

# HBBFT_TPU_TESTS_ON_TPU=1 opts OUT of the CPU forcing so the device
# test battery can run against the real chip when the relay is up
# (multi-device sharding tests then skip on the 1-chip platform).
_ON_TPU = bool(os.environ.get("HBBFT_TPU_TESTS_ON_TPU"))

if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

# The axon plugin's sitecustomize registers its backend and pins
# jax_platforms at interpreter start, before this file runs — env vars
# alone cannot re-select the CPU platform.  Re-select and clear the
# backend cache (no arrays exist yet, so this is safe).
import jax  # noqa: E402

if not _ON_TPU and (jax.default_backend() != "cpu" or len(jax.devices()) < 8):
    jax.config.update("jax_platforms", "cpu")
    from jax.extend.backend import clear_backends  # noqa: E402

    clear_backends()

from hbbft_tpu.utils.jaxcache import enable_cache  # noqa: E402

enable_cache()
